"""metriccache + metricsadvisor + statesinformer tests against a fake kernel fs."""

import os

import pytest

from koordinator_tpu.api.qos import QoSClass
from koordinator_tpu.koordlet import metriccache as mc
from koordinator_tpu.koordlet import metricsadvisor as ma
from koordinator_tpu.koordlet.statesinformer import (
    ContainerMeta, NodeInfo, PodMeta, StatesInformer,
)
from koordinator_tpu.koordlet.system import cgroup as cg
from koordinator_tpu.koordlet.system.config import make_test_config
from tests.test_koordlet_system import write_cgroup_file


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def tick(self, dt):
        self.t += dt


@pytest.fixture
def clock():
    return FakeClock()


@pytest.fixture
def cfg(tmp_path):
    return make_test_config(tmp_path)


def make_pod(uid="pod-1", qos=QoSClass.LS, kube_qos="burstable", **kw):
    return PodMeta(
        uid=uid, name=uid, namespace="default", qos_class=qos,
        kube_qos=kube_qos, **kw,
    )


class TestMetricCache:
    def test_ring_window_and_aggregates(self, clock):
        cache = mc.MetricCache(capacity_per_series=8, clock=clock)
        for i in range(12):  # wraps: only last 8 retained
            cache.append(mc.NODE_CPU_USAGE, float(i), ts=1000.0 + i)
        result = cache.query(mc.NODE_CPU_USAGE, start=0, end=2000)
        assert result.count == 8
        assert result.latest() == 11.0
        assert result.max() == 11.0
        assert result.avg() == pytest.approx(sum(range(4, 12)) / 8)
        # windowed subset
        sub = cache.query(mc.NODE_CPU_USAGE, start=1008, end=1010)
        assert sub.count == 3

    def test_percentiles_lower_interpolation(self, clock):
        cache = mc.MetricCache(clock=clock)
        for i in range(1, 101):
            cache.append(mc.NODE_CPU_USAGE, float(i), ts=1000.0 + i)
        result = cache.query(mc.NODE_CPU_USAGE, start=0, end=2000)
        ps = result.percentiles([0.5, 0.9, 0.95, 0.99])
        assert ps[0.5] == 50.0
        assert ps[0.99] == 99.0

    def test_labels_and_gc(self, clock):
        cache = mc.MetricCache(clock=clock)
        cache.append(mc.POD_CPU_USAGE, 1.0, {"pod_uid": "a"})
        cache.append(mc.POD_CPU_USAGE, 2.0, {"pod_uid": "b"})
        assert len(cache.series_labels(mc.POD_CPU_USAGE)) == 2
        dropped = cache.gc(keep_pod_uids={"a"})
        assert dropped == 1
        assert cache.query(mc.POD_CPU_USAGE, {"pod_uid": "b"}).empty

    def test_kv(self):
        cache = mc.MetricCache()
        cache.set_kv("numa", {"nodes": 2})
        assert cache.get_kv("numa") == {"nodes": 2}


def write_proc(cfg, used_jiffies, mem_used_kb=400, mem_total_kb=1000):
    os.makedirs(cfg.proc_root, exist_ok=True)
    with open(cfg.proc_path("stat"), "w") as f:
        f.write(f"cpu  {used_jiffies} 0 0 800 0 0 0 0 0 0\n")
    with open(cfg.proc_path("meminfo"), "w") as f:
        f.write(
            f"MemTotal: {mem_total_kb} kB\n"
            f"MemAvailable: {mem_total_kb - mem_used_kb} kB\nCached: 100 kB\n"
        )


class TestCollectors:
    def test_node_cpu_rate(self, cfg, clock):
        states = StatesInformer(clock=clock)
        cache = mc.MetricCache(clock=clock)
        advisor = ma.MetricsAdvisor(states, cache, cfg, clock)
        write_proc(cfg, used_jiffies=1000)
        advisor.collect_once()
        clock.tick(10)
        write_proc(cfg, used_jiffies=1000 + 2000)  # 2000 jiffies = 2 cores * 10s
        advisor.collect_once()
        result = cache.query(mc.NODE_CPU_USAGE, start=0, end=clock.t + 1)
        assert result.latest() == pytest.approx(2.0)
        mem = cache.query(mc.NODE_MEMORY_USAGE, start=0, end=clock.t + 1)
        assert mem.latest() == 400 * 1024

    def test_pod_and_container_usage(self, cfg, clock):
        pod = make_pod(containers=(ContainerMeta("c1", "cid-1"),))
        states = StatesInformer(clock=clock)
        states.set_pods([pod])
        cache = mc.MetricCache(clock=clock)
        advisor = ma.MetricsAdvisor(states, cache, cfg, clock)
        rel = pod.cgroup_dir(cfg)
        crel = cfg.container_cgroup_dir("burstable", pod.uid, "cid-1")
        write_proc(cfg, 100)
        write_cgroup_file(cfg, cg.CPUACCT_USAGE, rel, "0")
        write_cgroup_file(cfg, cg.MEMORY_USAGE, rel, "1048576")
        write_cgroup_file(cfg, cg.CPUACCT_USAGE, crel, "0")
        write_cgroup_file(cfg, cg.MEMORY_USAGE, crel, "524288")
        advisor.collect_once()
        clock.tick(10)
        write_cgroup_file(cfg, cg.CPUACCT_USAGE, rel, str(15 * 10**9))
        write_cgroup_file(cfg, cg.CPUACCT_USAGE, crel, str(5 * 10**9))
        advisor.collect_once()
        pod_cpu = cache.query(mc.POD_CPU_USAGE, {"pod_uid": pod.uid}, 0, clock.t + 1)
        assert pod_cpu.latest() == pytest.approx(1.5)
        c_cpu = cache.query(
            mc.CONTAINER_CPU_USAGE,
            {"pod_uid": pod.uid, "container_id": "cid-1"}, 0, clock.t + 1,
        )
        assert c_cpu.latest() == pytest.approx(0.5)
        pod_mem = cache.query(mc.POD_MEMORY_USAGE, {"pod_uid": pod.uid}, 0, clock.t + 1)
        assert pod_mem.latest() == 1048576

    def test_be_usage_v2(self, tmp_path, clock):
        cfg = make_test_config(tmp_path, use_cgroup_v2=True)
        states = StatesInformer(clock=clock)
        cache = mc.MetricCache(clock=clock)
        advisor = ma.MetricsAdvisor(states, cache, cfg, clock)
        rel = cfg.kube_qos_dir("besteffort")
        write_proc(cfg, 100)
        write_cgroup_file(cfg, cg.CPU_STAT, rel, "usage_usec 0\n")
        advisor.collect_once()
        clock.tick(5)
        write_cgroup_file(cfg, cg.CPU_STAT, rel, f"usage_usec {4 * 10**6 * 5}\n")
        advisor.collect_once()
        be = cache.query(mc.BE_CPU_USAGE, start=0, end=clock.t + 1)
        assert be.latest() == pytest.approx(4.0)

    def test_throttled_ratio(self, cfg, clock):
        pod = make_pod()
        states = StatesInformer(clock=clock)
        states.set_pods([pod])
        cache = mc.MetricCache(clock=clock)
        advisor = ma.MetricsAdvisor(states, cache, cfg, clock)
        rel = pod.cgroup_dir(cfg)
        write_proc(cfg, 100)
        write_cgroup_file(cfg, cg.CPU_STAT, rel, "nr_periods 100\nnr_throttled 10\n")
        advisor.collect_once()
        clock.tick(10)
        write_cgroup_file(cfg, cg.CPU_STAT, rel, "nr_periods 200\nnr_throttled 60\n")
        advisor.collect_once()
        thr = cache.query(mc.CONTAINER_CPU_THROTTLED, {"pod_uid": pod.uid}, 0, clock.t + 1)
        assert thr.latest() == pytest.approx(0.5)

    def test_sys_resource(self, cfg, clock):
        pod = make_pod()
        states = StatesInformer(clock=clock)
        states.set_pods([pod])
        cache = mc.MetricCache(clock=clock)
        cache.append(mc.NODE_CPU_USAGE, 4.0)
        cache.append(mc.POD_CPU_USAGE, 1.5, {"pod_uid": pod.uid})
        cache.append(mc.NODE_MEMORY_USAGE, 1000.0)
        cache.append(mc.POD_MEMORY_USAGE, 400.0, {"pod_uid": pod.uid})
        advisor = ma.MetricsAdvisor(states, cache, cfg, clock)
        ma.SysResourceCollector(advisor.deps).collect()
        assert cache.query(mc.SYS_CPU_USAGE, start=0, end=clock.t + 1).latest() == 2.5
        assert cache.query(mc.SYS_MEMORY_USAGE, start=0, end=clock.t + 1).latest() == 600.0


class TestStatesInformer:
    def test_callbacks_fire(self, clock):
        states = StatesInformer(clock=clock)
        seen = []
        states.register_callback("all-pods", lambda pods: seen.append(len(pods)))
        states.set_pods([make_pod(), make_pod(uid="pod-2")])
        assert seen == [2]

    def test_node_metric_aggregation(self, clock):
        cache = mc.MetricCache(clock=clock)
        states = StatesInformer(metric_cache=cache, clock=clock)
        pod = make_pod(priority=9500)
        states.set_pods([pod])
        states.set_node(NodeInfo(name="n1"))
        for i in range(10):
            cache.append(mc.NODE_CPU_USAGE, 1.0 + i * 0.1, ts=clock.t - 100 + i)
            cache.append(mc.NODE_MEMORY_USAGE, 1e9, ts=clock.t - 100 + i)
            cache.append(mc.POD_CPU_USAGE, 0.5, {"pod_uid": pod.uid},
                         ts=clock.t - 100 + i)
        status = states.build_node_metric(window_seconds=300)
        assert status.node_usage.cpu_milli == pytest.approx(1450, abs=1)
        assert status.aggregated_node_usage is not None
        assert status.aggregated_node_usage.cpu_milli_p[0.5] == 1400
        assert len(status.pods_metrics) == 1
        assert status.pods_metrics[0].usage.cpu_milli == 500
        assert status.pods_metrics[0].qos_class == "LS"


class TestExtensionProtocol:
    def test_qos_label_roundtrip(self):
        from koordinator_tpu.api import extension as ext

        labels = {}
        ext.set_pod_qos(labels, QoSClass.BE)
        assert labels[ext.LABEL_POD_QOS] == "BE"
        assert ext.get_pod_qos(labels) == QoSClass.BE
        assert ext.get_pod_qos({}) == QoSClass.NONE

    def test_resource_status_roundtrip(self):
        from koordinator_tpu.api import extension as ext

        ann = {}
        ext.set_resource_status(ann, "0-3,8")
        assert ext.get_resource_status(ann)["cpuset"] == "0-3,8"

    def test_device_allocation_roundtrip(self):
        from koordinator_tpu.api import extension as ext

        ann = {}
        allocs = {"gpu": [{"minor": 0, "resources": {"kubernetes.io/gpu-core": 50}}]}
        ext.set_device_allocations(ann, allocs)
        assert ext.get_device_allocations(ann) == allocs

    def test_amplification_and_normalization(self):
        from koordinator_tpu.api import extension as ext

        ann = {ext.ANNOTATION_NODE_AMPLIFICATION: '{"cpu": 1.5}',
               ext.ANNOTATION_CPU_NORMALIZATION: "1.2"}
        assert ext.get_node_amplification_ratios(ann) == {"cpu": 150}
        assert ext.get_cpu_normalization_ratio_pct(ann) == 120
        assert ext.get_cpu_normalization_ratio_pct({}) == 100
