"""metriccache + metricsadvisor + statesinformer tests against a fake kernel fs."""

import os

import pytest

from koordinator_tpu.api.qos import QoSClass
from koordinator_tpu.koordlet import metriccache as mc
from koordinator_tpu.koordlet import metricsadvisor as ma
from koordinator_tpu.koordlet.statesinformer import (
    ContainerMeta, NodeInfo, PodMeta, StatesInformer,
)
from koordinator_tpu.koordlet.system import cgroup as cg
from koordinator_tpu.koordlet.system.config import make_test_config
from tests.test_koordlet_system import write_cgroup_file


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def tick(self, dt):
        self.t += dt


@pytest.fixture
def clock():
    return FakeClock()


@pytest.fixture
def cfg(tmp_path):
    return make_test_config(tmp_path)


def make_pod(uid="pod-1", qos=QoSClass.LS, kube_qos="burstable", **kw):
    return PodMeta(
        uid=uid, name=uid, namespace="default", qos_class=qos,
        kube_qos=kube_qos, **kw,
    )


class TestMetricCache:
    def test_ring_window_and_aggregates(self, clock):
        cache = mc.MetricCache(capacity_per_series=8, clock=clock)
        for i in range(12):  # wraps: only last 8 retained
            cache.append(mc.NODE_CPU_USAGE, float(i), ts=1000.0 + i)
        result = cache.query(mc.NODE_CPU_USAGE, start=0, end=2000)
        assert result.count == 8
        assert result.latest() == 11.0
        assert result.max() == 11.0
        assert result.avg() == pytest.approx(sum(range(4, 12)) / 8)
        # windowed subset
        sub = cache.query(mc.NODE_CPU_USAGE, start=1008, end=1010)
        assert sub.count == 3

    def test_percentiles_lower_interpolation(self, clock):
        cache = mc.MetricCache(clock=clock)
        for i in range(1, 101):
            cache.append(mc.NODE_CPU_USAGE, float(i), ts=1000.0 + i)
        result = cache.query(mc.NODE_CPU_USAGE, start=0, end=2000)
        ps = result.percentiles([0.5, 0.9, 0.95, 0.99])
        assert ps[0.5] == 50.0
        assert ps[0.99] == 99.0

    def test_labels_and_gc(self, clock):
        cache = mc.MetricCache(clock=clock)
        cache.append(mc.POD_CPU_USAGE, 1.0, {"pod_uid": "a"})
        cache.append(mc.POD_CPU_USAGE, 2.0, {"pod_uid": "b"})
        assert len(cache.series_labels(mc.POD_CPU_USAGE)) == 2
        dropped = cache.gc(keep_pod_uids={"a"})
        assert dropped == 1
        assert cache.query(mc.POD_CPU_USAGE, {"pod_uid": "b"}).empty

    def test_kv(self):
        cache = mc.MetricCache()
        cache.set_kv("numa", {"nodes": 2})
        assert cache.get_kv("numa") == {"nodes": 2}


class TestMetricCacheRetentionAndDownsampling:
    """Retention/downsampling boundaries (ISSUE 5 satellite): only the
    happy path was covered before."""

    def test_exact_horizon_sample_kept_one_older_evicted(self, clock):
        cache = mc.MetricCache(clock=clock, retention_sec=60.0)
        clock.t = 1060.0
        cache.append(mc.NODE_CPU_USAGE, 1.0, ts=999.9)    # one older
        cache.append(mc.NODE_CPU_USAGE, 2.0, ts=1000.0)   # exactly at horizon
        cache.append(mc.NODE_CPU_USAGE, 3.0, ts=1030.0)
        res = cache.query(mc.NODE_CPU_USAGE, start=0, end=2000)
        # the sample AT now - retention is served; the one strictly
        # older is not, even though the ring still physically holds it
        assert res.count == 2
        assert sorted(res.values.tolist()) == [2.0, 3.0]

    def test_retention_moves_with_the_clock(self, clock):
        cache = mc.MetricCache(clock=clock, retention_sec=60.0)
        clock.t = 1000.0
        cache.append(mc.NODE_CPU_USAGE, 1.0, ts=1000.0)
        assert cache.query(mc.NODE_CPU_USAGE, start=0).count == 1
        clock.tick(61.0)
        assert cache.query(mc.NODE_CPU_USAGE, start=0).count == 0

    def test_no_retention_serves_everything(self, clock):
        cache = mc.MetricCache(clock=clock)   # retention_sec=None
        cache.append(mc.NODE_CPU_USAGE, 1.0, ts=1.0)
        clock.t = 10_000.0
        assert cache.query(mc.NODE_CPU_USAGE, start=0).count == 1

    def test_empty_window_aggregates_are_sentinels_not_nan(self, clock):
        import math

        cache = mc.MetricCache(clock=clock)
        cache.append(mc.NODE_CPU_USAGE, 5.0, ts=1000.0)
        res = cache.query(mc.NODE_CPU_USAGE, start=2000, end=3000)
        assert res.empty and res.count == 0
        for value in (res.avg(), res.latest(), res.first(), res.max(),
                      res.percentile(0.99), res.duration_seconds()):
            assert value == 0.0
            assert not math.isnan(value)
        # a never-written series behaves identically
        ghost = cache.query("never_written")
        assert ghost.empty and not math.isnan(ghost.avg())


class TestMetricCacheLongHorizonTier:
    """Two-tier downsampling horizon (ISSUE 9 satellite): samples aging
    past ``downsample_after_sec`` move into a bounded cold ring at
    mean-per-bin resolution instead of being silently evicted by hot
    wraparound — hours-long soaks stay memory-bounded AND keep history.
    """

    def _cache(self, clock, **kw):
        kw.setdefault("downsample_after_sec", 60.0)
        kw.setdefault("downsample_resolution_sec", 10.0)
        return mc.MetricCache(clock=clock, **kw)

    def test_exact_horizon_kept_hot_one_older_downsampled(self, clock):
        cache = self._cache(clock)
        cache.append(mc.NODE_CPU_USAGE, 1.0, ts=999.9)    # one older
        cache.append(mc.NODE_CPU_USAGE, 2.0, ts=1000.0)   # exactly AT
        cache.append(mc.NODE_CPU_USAGE, 3.0, ts=1030.0)
        clock.t = 1060.0
        cache.compact()
        # the horizon sample and newer stay in the hot ring at full
        # resolution; the strictly-older one moved to the cold tier
        key = mc._series_key(mc.NODE_CPU_USAGE, None)
        hot_ts, hot_vals = cache._series[key].chronological()
        assert hot_vals.tolist() == [2.0, 3.0]
        # ... but the QUERY still serves all three (cold merged in)
        res = cache.query(mc.NODE_CPU_USAGE, start=0, end=2000)
        assert sorted(res.values.tolist()) == [1.0, 2.0, 3.0]

    def test_drained_samples_downsample_to_bin_means(self, clock):
        cache = self._cache(clock)
        # bin [1000, 1010): three samples -> ONE cold sample at their mean
        for ts, v in ((1001.0, 1.0), (1004.0, 2.0), (1007.0, 9.0)):
            cache.append(mc.NODE_CPU_USAGE, v, ts=ts)
        # a later bin's sample finalizes the pending one
        cache.append(mc.NODE_CPU_USAGE, 5.0, ts=1015.0)
        clock.t = 1200.0
        cache.compact()
        res = cache.query(mc.NODE_CPU_USAGE, start=0, end=2000)
        assert res.count == 2          # two bins, one sample each
        assert sorted(res.values.tolist()) == [4.0, 5.0]   # mean(1,2,9)=4
        assert res.avg() == pytest.approx(4.5)

    def test_memory_stays_bounded_over_a_long_run(self, clock):
        cache = self._cache(clock, capacity_per_series=64)
        # simulate hours: 10x the hot capacity at 1s cadence
        for i in range(640):
            clock.t = 1000.0 + i
            cache.append(mc.NODE_CPU_USAGE, float(i))
        key = mc._series_key(mc.NODE_CPU_USAGE, None)
        assert cache._series[key].count <= 64
        tier = cache._cold[key]
        assert tier.ring.count <= 64
        # history survived in downsampled form: the window covers far
        # more than the hot ring alone could (64 raw + cold bins)
        res = cache.query(mc.NODE_CPU_USAGE, start=0, end=5000)
        assert res.count > 64
        assert res.duration_seconds() > 500.0

    def test_append_triggers_compaction_lazily(self, clock):
        cache = self._cache(clock)
        cache.append(mc.NODE_CPU_USAGE, 1.0, ts=1000.0)
        # an append a full bin past the horizon compacts without an
        # explicit compact() call
        clock.t = 1075.0
        cache.append(mc.NODE_CPU_USAGE, 2.0, ts=1075.0)
        key = mc._series_key(mc.NODE_CPU_USAGE, None)
        hot_ts, hot_vals = cache._series[key].chronological()
        assert hot_vals.tolist() == [2.0]
        assert key in cache._cold

    def test_disabled_tier_keeps_old_behavior(self, clock):
        cache = mc.MetricCache(clock=clock)   # no downsample horizon
        cache.append(mc.NODE_CPU_USAGE, 1.0, ts=1.0)
        clock.t = 100_000.0
        cache.compact()                        # no-op
        assert cache.query(mc.NODE_CPU_USAGE, start=0).count == 1
        assert not cache._cold

    def test_delete_series_drops_cold_tier_too(self, clock):
        cache = self._cache(clock)
        cache.append(mc.POD_CPU_USAGE, 1.0, {"pod_uid": "a"}, ts=1000.0)
        clock.t = 1200.0
        cache.compact()
        cache.delete_series(mc.POD_CPU_USAGE, {"pod_uid": "a"})
        assert not cache._cold
        assert cache.query(mc.POD_CPU_USAGE, {"pod_uid": "a"}).empty

    def test_downsample_mean_per_bin(self, clock):
        cache = mc.MetricCache(clock=clock)
        for i in range(10):   # ts 1000..1009, values 0..9
            cache.append(mc.NODE_CPU_USAGE, float(i), ts=1000.0 + i)
        res = cache.query(mc.NODE_CPU_USAGE, start=0, end=2000)
        down = res.downsample(5.0)
        assert down.count == 2
        assert down.values.tolist() == [
            pytest.approx(2.0), pytest.approx(7.0)]
        assert down.ts.tolist() == [
            pytest.approx(1002.0), pytest.approx(1007.0)]
        # aggregates keep working on the downsampled view
        assert down.avg() == pytest.approx(4.5)

    def test_downsample_noop_cases(self, clock):
        cache = mc.MetricCache(clock=clock)
        empty = cache.query(mc.NODE_CPU_USAGE)
        assert empty.downsample(5.0) is empty
        cache.append(mc.NODE_CPU_USAGE, 1.0, ts=1000.0)
        res = cache.query(mc.NODE_CPU_USAGE, start=0, end=2000)
        assert res.downsample(0.0) is res


def write_proc(cfg, used_jiffies, mem_used_kb=400, mem_total_kb=1000):
    os.makedirs(cfg.proc_root, exist_ok=True)
    with open(cfg.proc_path("stat"), "w") as f:
        f.write(f"cpu  {used_jiffies} 0 0 800 0 0 0 0 0 0\n")
    with open(cfg.proc_path("meminfo"), "w") as f:
        f.write(
            f"MemTotal: {mem_total_kb} kB\n"
            f"MemAvailable: {mem_total_kb - mem_used_kb} kB\nCached: 100 kB\n"
        )


class TestCollectors:
    def test_node_cpu_rate(self, cfg, clock):
        states = StatesInformer(clock=clock)
        cache = mc.MetricCache(clock=clock)
        advisor = ma.MetricsAdvisor(states, cache, cfg, clock)
        write_proc(cfg, used_jiffies=1000)
        advisor.collect_once()
        clock.tick(10)
        write_proc(cfg, used_jiffies=1000 + 2000)  # 2000 jiffies = 2 cores * 10s
        advisor.collect_once()
        result = cache.query(mc.NODE_CPU_USAGE, start=0, end=clock.t + 1)
        assert result.latest() == pytest.approx(2.0)
        mem = cache.query(mc.NODE_MEMORY_USAGE, start=0, end=clock.t + 1)
        assert mem.latest() == 400 * 1024

    def test_pod_and_container_usage(self, cfg, clock):
        pod = make_pod(containers=(ContainerMeta("c1", "cid-1"),))
        states = StatesInformer(clock=clock)
        states.set_pods([pod])
        cache = mc.MetricCache(clock=clock)
        advisor = ma.MetricsAdvisor(states, cache, cfg, clock)
        rel = pod.cgroup_dir(cfg)
        crel = cfg.container_cgroup_dir("burstable", pod.uid, "cid-1")
        write_proc(cfg, 100)
        write_cgroup_file(cfg, cg.CPUACCT_USAGE, rel, "0")
        write_cgroup_file(cfg, cg.MEMORY_USAGE, rel, "1048576")
        write_cgroup_file(cfg, cg.CPUACCT_USAGE, crel, "0")
        write_cgroup_file(cfg, cg.MEMORY_USAGE, crel, "524288")
        advisor.collect_once()
        clock.tick(10)
        write_cgroup_file(cfg, cg.CPUACCT_USAGE, rel, str(15 * 10**9))
        write_cgroup_file(cfg, cg.CPUACCT_USAGE, crel, str(5 * 10**9))
        advisor.collect_once()
        pod_cpu = cache.query(mc.POD_CPU_USAGE, {"pod_uid": pod.uid}, 0, clock.t + 1)
        assert pod_cpu.latest() == pytest.approx(1.5)
        c_cpu = cache.query(
            mc.CONTAINER_CPU_USAGE,
            {"pod_uid": pod.uid, "container_id": "cid-1"}, 0, clock.t + 1,
        )
        assert c_cpu.latest() == pytest.approx(0.5)
        pod_mem = cache.query(mc.POD_MEMORY_USAGE, {"pod_uid": pod.uid}, 0, clock.t + 1)
        assert pod_mem.latest() == 1048576

    def test_be_usage_v2(self, tmp_path, clock):
        cfg = make_test_config(tmp_path, use_cgroup_v2=True)
        states = StatesInformer(clock=clock)
        cache = mc.MetricCache(clock=clock)
        advisor = ma.MetricsAdvisor(states, cache, cfg, clock)
        rel = cfg.kube_qos_dir("besteffort")
        write_proc(cfg, 100)
        write_cgroup_file(cfg, cg.CPU_STAT, rel, "usage_usec 0\n")
        advisor.collect_once()
        clock.tick(5)
        write_cgroup_file(cfg, cg.CPU_STAT, rel, f"usage_usec {4 * 10**6 * 5}\n")
        advisor.collect_once()
        be = cache.query(mc.BE_CPU_USAGE, start=0, end=clock.t + 1)
        assert be.latest() == pytest.approx(4.0)

    def test_throttled_ratio(self, cfg, clock):
        pod = make_pod()
        states = StatesInformer(clock=clock)
        states.set_pods([pod])
        cache = mc.MetricCache(clock=clock)
        advisor = ma.MetricsAdvisor(states, cache, cfg, clock)
        rel = pod.cgroup_dir(cfg)
        write_proc(cfg, 100)
        write_cgroup_file(cfg, cg.CPU_STAT, rel, "nr_periods 100\nnr_throttled 10\n")
        advisor.collect_once()
        clock.tick(10)
        write_cgroup_file(cfg, cg.CPU_STAT, rel, "nr_periods 200\nnr_throttled 60\n")
        advisor.collect_once()
        thr = cache.query(mc.CONTAINER_CPU_THROTTLED, {"pod_uid": pod.uid}, 0, clock.t + 1)
        assert thr.latest() == pytest.approx(0.5)

    def test_sys_resource(self, cfg, clock):
        pod = make_pod()
        states = StatesInformer(clock=clock)
        states.set_pods([pod])
        cache = mc.MetricCache(clock=clock)
        cache.append(mc.NODE_CPU_USAGE, 4.0)
        cache.append(mc.POD_CPU_USAGE, 1.5, {"pod_uid": pod.uid})
        cache.append(mc.NODE_MEMORY_USAGE, 1000.0)
        cache.append(mc.POD_MEMORY_USAGE, 400.0, {"pod_uid": pod.uid})
        advisor = ma.MetricsAdvisor(states, cache, cfg, clock)
        ma.SysResourceCollector(advisor.deps).collect()
        assert cache.query(mc.SYS_CPU_USAGE, start=0, end=clock.t + 1).latest() == 2.5
        assert cache.query(mc.SYS_MEMORY_USAGE, start=0, end=clock.t + 1).latest() == 600.0


class TestStatesInformer:
    def test_callbacks_fire(self, clock):
        states = StatesInformer(clock=clock)
        seen = []
        states.register_callback("all-pods", lambda pods: seen.append(len(pods)))
        states.set_pods([make_pod(), make_pod(uid="pod-2")])
        assert seen == [2]

    def test_node_metric_aggregation(self, clock):
        cache = mc.MetricCache(clock=clock)
        states = StatesInformer(metric_cache=cache, clock=clock)
        pod = make_pod(priority=9500)
        states.set_pods([pod])
        states.set_node(NodeInfo(name="n1"))
        for i in range(10):
            cache.append(mc.NODE_CPU_USAGE, 1.0 + i * 0.1, ts=clock.t - 100 + i)
            cache.append(mc.NODE_MEMORY_USAGE, 1e9, ts=clock.t - 100 + i)
            cache.append(mc.POD_CPU_USAGE, 0.5, {"pod_uid": pod.uid},
                         ts=clock.t - 100 + i)
        status = states.build_node_metric(window_seconds=300)
        assert status.node_usage.cpu_milli == pytest.approx(1450, abs=1)
        assert status.aggregated_node_usage is not None
        assert status.aggregated_node_usage.cpu_milli_p[0.5] == 1400
        assert len(status.pods_metrics) == 1
        assert status.pods_metrics[0].usage.cpu_milli == 500
        assert status.pods_metrics[0].qos_class == "LS"


class TestExtensionProtocol:
    def test_qos_label_roundtrip(self):
        from koordinator_tpu.api import extension as ext

        labels = {}
        ext.set_pod_qos(labels, QoSClass.BE)
        assert labels[ext.LABEL_POD_QOS] == "BE"
        assert ext.get_pod_qos(labels) == QoSClass.BE
        assert ext.get_pod_qos({}) == QoSClass.NONE

    def test_resource_status_roundtrip(self):
        from koordinator_tpu.api import extension as ext

        ann = {}
        ext.set_resource_status(ann, "0-3,8")
        assert ext.get_resource_status(ann)["cpuset"] == "0-3,8"

    def test_device_allocation_roundtrip(self):
        from koordinator_tpu.api import extension as ext

        ann = {}
        allocs = {"gpu": [{"minor": 0, "resources": {"kubernetes.io/gpu-core": 50}}]}
        ext.set_device_allocations(ann, allocs)
        assert ext.get_device_allocations(ann) == allocs

    def test_amplification_and_normalization(self):
        from koordinator_tpu.api import extension as ext

        ann = {ext.ANNOTATION_NODE_AMPLIFICATION: '{"cpu": 1.5}',
               ext.ANNOTATION_CPU_NORMALIZATION: "1.2"}
        assert ext.get_node_amplification_ratios(ann) == {"cpu": 150}
        assert ext.get_cpu_normalization_ratio_pct(ann) == 120
        assert ext.get_cpu_normalization_ratio_pct({}) == 100


class TestMetricCachePersistence:
    """Metric-history persistence across agent restart (reference role:
    pkg/koordlet/metriccache/tsdb_storage.go:29 — the embedded TSDB is
    persisted on the node).  Memory-only ring buffers meant a koordlet
    restart zeroed the NodeMetric aggregation windows and suppress/evict
    ran on cold data (VERDICT r4 missing #4)."""

    def test_snapshot_restore_roundtrip(self, clock, tmp_path):
        path = str(tmp_path / "mc.npz")
        cache = mc.MetricCache(capacity_per_series=32, clock=clock)
        for i in range(40):  # wraps the ring
            cache.append(mc.NODE_CPU_USAGE, float(i), ts=1000.0 + i)
        for i in range(5):
            cache.append(mc.POD_CPU_USAGE, 0.1 * i,
                         labels={"pod_uid": "p1"}, ts=1000.0 + i)
        cache.set_kv("json_ok", {"a": 1})
        cache.set_kv("opaque", object())  # not JSON-serializable: dropped
        cache.snapshot(path)

        fresh = mc.MetricCache(capacity_per_series=32, clock=clock)
        assert fresh.restore(path)
        orig = cache.query(mc.NODE_CPU_USAGE, start=0, end=2000)
        got = fresh.query(mc.NODE_CPU_USAGE, start=0, end=2000)
        assert got.count == orig.count == 32
        assert got.avg() == orig.avg()
        assert got.latest() == orig.latest() == 39.0
        pod = fresh.query(mc.POD_CPU_USAGE, labels={"pod_uid": "p1"},
                          start=0, end=2000)
        assert pod.count == 5
        assert fresh.get_kv("json_ok") == {"a": 1}
        assert fresh.get_kv("opaque") is None
        # appends continue cleanly after restore (head position correct)
        fresh.append(mc.NODE_CPU_USAGE, 99.0, ts=1100.0)
        assert fresh.query(mc.NODE_CPU_USAGE, 
                           start=0, end=2000).latest() == 99.0

    def test_restore_smaller_capacity_keeps_newest(self, clock, tmp_path):
        path = str(tmp_path / "mc.npz")
        cache = mc.MetricCache(capacity_per_series=64, clock=clock)
        for i in range(50):
            cache.append(mc.NODE_CPU_USAGE, float(i), ts=1000.0 + i)
        cache.snapshot(path)
        small = mc.MetricCache(capacity_per_series=16, clock=clock)
        assert small.restore(path)
        got = small.query(mc.NODE_CPU_USAGE, start=0, end=2000)
        assert got.count == 16
        # the NEWEST 16 samples survive, in order
        assert got.latest() == 49.0
        assert got.values.min() == 34.0

    def test_corrupt_snapshot_starts_fresh(self, clock, tmp_path):
        path = str(tmp_path / "mc.npz")
        (tmp_path / "mc.npz").write_bytes(b"not an npz file")
        cache = mc.MetricCache(clock=clock)
        assert not cache.restore(path)
        assert not cache.restore(str(tmp_path / "missing.npz"))
        cache.append(mc.NODE_CPU_USAGE, 1.0)
        assert cache.query(mc.NODE_CPU_USAGE, start=0,
                           end=2000).count == 1

    def test_daemon_restart_unbroken_p95_window(self, clock, cfg):
        """The done-criterion: kill and restart the daemon, and the
        reporter's p95-over-window is computed over the FULL window, not
        the seconds since restart."""
        from koordinator_tpu.koordlet.daemon import Daemon
        from koordinator_tpu.koordlet.statesinformer import NodeInfo

        d1 = Daemon(cfg=cfg, clock=clock)
        # five minutes of 30s node-usage samples (collector cadence)
        for i in range(11):
            d1.metric_cache.append(mc.NODE_CPU_USAGE, 2.0 + 0.1 * i,
                                   ts=clock.t)
            d1.metric_cache.append(mc.NODE_MEMORY_USAGE, 1e9 + i * 1e7,
                                   ts=clock.t)
            clock.tick(30)
        before = d1.states.build_node_metric(window_seconds=300.0)
        # interval snapshot fires on a tick (kill -9 survivability: no
        # stop() needed) — arm the proc files the collectors read
        write_proc(cfg, used_jiffies=1000)
        d1.tick()
        # ... process dies here without stop() ...

        d2 = Daemon(cfg=cfg, clock=clock)
        d2.states.set_node(NodeInfo(name="n0", allocatable={}))
        after = d2.states.build_node_metric(window_seconds=300.0)
        assert after.aggregated_node_usage.duration_seconds == pytest.approx(
            before.aggregated_node_usage.duration_seconds)
        assert after.aggregated_node_usage.duration_seconds >= 250.0
        for q in (0.5, 0.9, 0.95, 0.99):
            assert (after.aggregated_node_usage.cpu_milli_p[q]
                    == before.aggregated_node_usage.cpu_milli_p[q])
            assert (after.aggregated_node_usage.memory_bytes_p[q]
                    == before.aggregated_node_usage.memory_bytes_p[q])
        # and the daemon-level stop() snapshot also persists (SIGTERM)
        d2.metric_cache.append(mc.NODE_CPU_USAGE, 9.0, ts=clock.t)
        d2.stop()
        d3 = Daemon(cfg=cfg, clock=clock)
        assert d3.metric_cache.query(
            mc.NODE_CPU_USAGE, start=0, end=clock.t + 1).latest() == 9.0
