import math

import jax.numpy as jnp
import numpy as np

from koordinator_tpu.prediction.histogram import (
    ExponentialBuckets,
    HistogramBank,
    add_samples,
    default_cpu_buckets,
    load_bank,
    percentile,
    save_bank,
)
from koordinator_tpu.prediction.predictor import pod_reclaimable


def oracle_percentile(weights, starts, p, eps=1e-10):
    """Direct port of histogram.go:158 Percentile in plain Python."""
    total = sum(weights)
    sig = [i for i, w in enumerate(weights) if w >= eps]
    if not sig:
        return 0.0
    min_b, max_b = sig[0], sig[-1]
    partial = 0.0
    bucket = min_b
    while bucket < max_b:
        partial += weights[bucket]
        if partial >= p * total:
            break
        bucket += 1
    if bucket < len(weights) - 1:
        return starts[bucket + 1]
    return starts[bucket]


def test_bucket_layout_monotone_and_inverse():
    b = default_cpu_buckets()
    starts = b.starts()
    assert starts[0] == 0.0
    assert (np.diff(starts) > 0).all()
    # find_bucket is the inverse of starts: a value inside bucket i maps to i
    vals = (starts[:-1] + starts[1:]) / 2
    idx = np.asarray(b.find_bucket(jnp.asarray(vals)))
    assert (idx == np.arange(len(vals))).all()


def test_percentile_matches_oracle():
    rng = np.random.default_rng(5)
    b = ExponentialBuckets.for_range(1000.0, 1.0, 1.05)
    bank = HistogramBank.zeros(4, b, half_life_sec=86_400.0)
    t = jnp.float32(0.0)
    for _ in range(50):
        uids = jnp.asarray(rng.integers(0, 4, 8).astype(np.int32))
        vals = jnp.asarray((rng.random(8) * 900).astype(np.float32))
        bank = add_samples(bank, b, uids, vals, t)
    starts = b.starts()
    for p in (0.5, 0.9, 0.95, 0.99):
        got = np.asarray(percentile(bank, b, p))
        for u in range(4):
            want = oracle_percentile(np.asarray(bank.weights)[u].tolist(),
                                     starts.tolist(), p)
            assert math.isclose(got[u], want, rel_tol=1e-5), (u, p, got[u], want)


def test_percentile_empty_is_zero():
    b = ExponentialBuckets.for_range(100.0, 1.0, 1.05)
    bank = HistogramBank.zeros(2, b, half_life_sec=3600.0)
    assert np.asarray(percentile(bank, b, 0.95)).tolist() == [0.0, 0.0]


def test_decay_halves_old_samples():
    b = ExponentialBuckets.for_range(1000.0, 1.0, 1.05)
    bank = HistogramBank.zeros(1, b, half_life_sec=100.0)
    u = jnp.asarray(np.array([0], np.int32))
    # old sample at value ~10, new sample at ~500 one half-life later with
    # the same nominal weight -> new sample weighs 2x the old
    bank = add_samples(bank, b, u, jnp.asarray(np.array([10.0], np.float32)),
                       jnp.float32(0.0))
    bank = add_samples(bank, b, u, jnp.asarray(np.array([500.0], np.float32)),
                       jnp.float32(100.0))
    # p50 * total: total = 1 + 2 = 3; threshold 1.5 -> falls in the 500 bucket
    p50 = float(percentile(bank, b, 0.5)[0])
    assert p50 > 400.0


def test_decay_renormalizes_far_future():
    b = ExponentialBuckets.for_range(1000.0, 1.0, 1.05)
    bank = HistogramBank.zeros(1, b, half_life_sec=3600.0)
    u = jnp.asarray(np.array([0], np.int32))
    bank = add_samples(bank, b, u, jnp.asarray(np.array([100.0], np.float32)),
                       jnp.float32(0.0))
    # 100 half-lives later: would be 2^100 without renormalization
    bank = add_samples(bank, b, u, jnp.asarray(np.array([100.0], np.float32)),
                       jnp.float32(360_000.0))
    assert np.isfinite(np.asarray(bank.weights)).all()
    assert float(bank.total[0]) > 0


def test_pod_reclaimable():
    b = ExponentialBuckets.for_range(10_000.0, 10.0, 1.05)
    cpu_bank = HistogramBank.zeros(3, b, half_life_sec=86_400.0)
    mem_bank = HistogramBank.zeros(3, b, half_life_sec=86_400.0)
    u = jnp.asarray(np.array([0, 1, 2], np.int32))
    t = jnp.float32(0.0)
    # pods use ~1000 mcpu / ~1000 MiB steadily
    for _ in range(20):
        cpu_bank = add_samples(cpu_bank, b, u,
                               jnp.asarray(np.array([1000.0, 1000.0, 1000.0],
                                                    np.float32)), t)
        mem_bank = add_samples(mem_bank, b, u,
                               jnp.asarray(np.array([1000.0] * 3, np.float32)), t)
    req_cpu = jnp.asarray(np.array([4000.0, 4000.0, 4000.0], np.float32))
    req_mem = jnp.asarray(np.array([2000.0] * 3, np.float32))
    mask = jnp.asarray(np.array([True, True, False]))  # pod 2 in cold start
    rc, rm = pod_reclaimable(
        cpu_bank, mem_bank, b, b, req_cpu, req_mem, mask,
        node_allocatable_cpu=jnp.float32(16_000.0),
        node_allocatable_mem=jnp.float32(65_536.0),
        safety_margin_pct=10.0,
    )
    # peak ~= 1000*1.1 = ~1100 (bucket upper bound), reclaimable ~2900 x2 pods
    assert 5_000 < float(rc) < 6_200, float(rc)
    assert 1_500 < float(rm) < 2_000, float(rm)


def test_priority_reclaimable_clamped_by_allocatable():
    from koordinator_tpu.prediction.predictor import priority_reclaimable

    b = ExponentialBuckets.for_range(10_000.0, 10.0, 1.05)
    cpu_bank = HistogramBank.zeros(1, b, 86_400.0)
    mem_bank = HistogramBank.zeros(1, b, 86_400.0)
    u = jnp.asarray(np.array([0], np.int32))
    cpu_bank = add_samples(cpu_bank, b, u,
                           jnp.asarray(np.array([1000.0], np.float32)),
                           jnp.float32(0.0))
    mem_bank = add_samples(mem_bank, b, u,
                           jnp.asarray(np.array([1000.0], np.float32)),
                           jnp.float32(0.0))
    # tier requests 100k but the node only has 5k allocatable: result must be
    # min(alloc - peak, request - peak), not the inflated request-based figure
    rc, _ = priority_reclaimable(
        cpu_bank, mem_bank, b, b, u,
        jnp.float32(100_000.0), jnp.float32(100_000.0),
        jnp.float32(5_000.0), jnp.float32(5_000.0),
    )
    assert float(rc) < 5_000.0


def test_checkpoint_roundtrip(tmp_path):
    b = ExponentialBuckets.for_range(100.0, 1.0, 1.05)
    bank = HistogramBank.zeros(2, b, half_life_sec=3600.0)
    bank = add_samples(bank, b, jnp.asarray(np.array([0], np.int32)),
                       jnp.asarray(np.array([42.0], np.float32)), jnp.float32(5.0))
    path = str(tmp_path / "bank.npz")
    save_bank(bank, path)
    restored = load_bank(path)
    assert np.array_equal(np.asarray(bank.weights), np.asarray(restored.weights))
    assert float(bank.ref_time) == float(restored.ref_time)
