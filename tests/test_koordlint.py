"""koordlint self-tests: the analyzer corpus contract + the whole-tree
gate (ISSUE 7).

Pure AST — this file never imports jax (which is also the marker-audit
rule it helps enforce).  Three layers:

- **corpus**: every rule flags its seeded known-bad fixture (including
  the reconstruction of the PR-1 ``ClusterState.zeros``
  donation-aliasing bug) and stays silent on the known-good twin;
- **tree**: ``python -m tools.koordlint`` semantics over THIS repo —
  zero unsuppressed findings, every suppression carries a reason, no
  stale baseline entries;
- **machinery**: inline ignores need reasons, reasonless baseline
  entries are findings, CLI exit codes.
"""

import json
import os
import subprocess
import sys
import tempfile

import pytest

from tools import koordlint
from tools.koordlint.analyzers.donation_flow import DonationFlowAnalyzer
from tools.koordlint.analyzers.donation_safety import DonationSafetyAnalyzer
from tools.koordlint.analyzers.dtype_regime import DtypeRegimeAnalyzer
from tools.koordlint.analyzers.jit_host_sync import JitHostSyncAnalyzer
from tools.koordlint.analyzers.latency_home import LatencyHomeAnalyzer
from tools.koordlint.analyzers.lock_discipline import LockDisciplineAnalyzer
from tools.koordlint.analyzers.marker_audit import MarkerAuditAnalyzer
from tools.koordlint.analyzers.mesh_discipline import MeshDisciplineAnalyzer
from tools.koordlint.analyzers.spec_consistency import (
    SpecConsistencyAnalyzer,
)
from tools.koordlint.analyzers.surface_parity import SurfaceParityAnalyzer
from tools.koordlint.analyzers.tenant_axis import TenantAxisAnalyzer
from tools.koordlint.analyzers.wire_codec import WireCodecAnalyzer
from tools.koordlint.analyzers import dashboard_drift
from tools.koordlint.core import (
    Project,
    SourceFile,
    apply_suppressions,
    load_baseline,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tools", "koordlint", "fixtures")


def corpus(rule: str, kind: str, targets) -> Project:
    return Project(os.path.join(FIXTURES, rule, kind), targets=targets)


class TestJitHostSyncCorpus:
    def analyzer(self):
        return JitHostSyncAnalyzer(package="pkg",
                                   root_paths=["pkg/solver.py"])

    def test_bad_corpus_flags_every_seeded_sync(self):
        findings = self.analyzer().run(
            corpus("jit_host_sync", "bad", ("pkg",)))
        messages = "\n".join(f.message for f in findings)
        for needle in ("host cast float()", "host cast int()",
                       "host cast bool()", "numpy.asarray()",
                       ".item() on a traced value",
                       "data-dependent branch",
                       "host iteration over a traced value"):
            assert needle in messages, f"missing: {needle}\n{messages}"
        # the interprocedural edge: the helper's branch is flagged too
        assert any("_helper" in f.message for f in findings)

    def test_good_corpus_is_clean(self):
        assert self.analyzer().run(
            corpus("jit_host_sync", "good", ("pkg",))) == []


class TestDonationSafetyCorpus:
    def test_bad_corpus_flags_the_pr1_bug_class(self):
        findings = DonationSafetyAnalyzer(package="pkg").run(
            corpus("donation_safety", "bad", ("pkg",)))
        messages = "\n".join(f.message for f in findings)
        # the PR-1 ClusterState.zeros reconstruction: one buffer,
        # several pytree fields
        assert "aliased across pytree fields" in messages
        assert "ClusterState.zeros" in messages   # names the bug class
        assert "read after being donated" in messages
        assert "also passed at position" in messages
        # the ISSUE-11 double-buffer anti-idiom: stashing the donated
        # in-flight buffer on a handle after dispatch is a second
        # read-after-donate seed (Pipeline.dispatch in the corpus); the
        # ISSUE-17 checkpoint path seeds a third (serialising the
        # pre-donation reference in Restorer.catch_up) plus a second
        # aliased construction (RestoredState.restore)
        assert messages.count("read after being donated") == 3
        assert messages.count("aliased across pytree fields") == 2
        assert len(findings) == 6

    def test_good_corpus_is_clean(self):
        assert DonationSafetyAnalyzer(package="pkg").run(
            corpus("donation_safety", "good", ("pkg",))) == []


class TestLockDisciplineCorpus:
    def test_bad_corpus_flags_cycle_and_bare_write(self):
        findings = LockDisciplineAnalyzer(package="pkg").run(
            corpus("lock_discipline", "bad", ("pkg",)))
        messages = "\n".join(f.message for f in findings)
        assert "lock-order cycle" in messages
        assert "Informer._lock" in messages and "Store._lock" in messages
        assert "race candidate" in messages
        assert "bare in reset()" in messages
        # multi-item `with a, b:` vs nested `with b: with a:` is a
        # cycle too (the combined form acquires in sequence)
        assert any("Combined._a" in f.message and "Combined._b"
                   in f.message for f in findings), messages
        # the ISSUE-17 checkpoint seeds: writer-lock / round-lock order
        # cycle, and the restore path's bare replay-cursor write
        assert any("RoundScheduler.lock" in f.message
                   and "CheckpointWriter._lock" in f.message
                   for f in findings), messages
        assert "bare in restore()" in messages

    def test_good_corpus_is_clean(self):
        # guarded-by annotation honored, RLock reentrancy not a cycle,
        # one-directional nesting not a cycle
        assert LockDisciplineAnalyzer(package="pkg").run(
            corpus("lock_discipline", "good", ("pkg",))) == []


class TestMeshDisciplineCorpus:
    def analyzer(self):
        return MeshDisciplineAnalyzer(package="pkg",
                                      capacity_home=("pkg/ops.py",))

    def test_bad_corpus_flags_every_seeded_violation(self):
        findings = self.analyzer().run(
            corpus("mesh_discipline", "bad", ("pkg",)))
        messages = "\n".join(f.message for f in findings)
        assert "omits in_specs and out_specs" in messages
        # donated-position gaps: missing entry, explicit None, and the
        # ISSUE-11 pipelined hand-off whose donated stacked state is
        # left to inference
        assert messages.count("has no explicit in_spec") == 3
        assert "raw check_node_capacity call outside" in messages
        assert len(findings) == 5

    def test_good_corpus_is_clean(self):
        # explicit specs everywhere, donated positions covered, the
        # capacity guard only inside its owning module
        assert self.analyzer().run(
            corpus("mesh_discipline", "good", ("pkg",))) == []


class TestSurfaceParityCorpus:
    def analyzer(self):
        return SurfaceParityAnalyzer(services_path="services.py",
                                     gateway_path="gateway.py")

    def test_bad_corpus_flags_drift_and_typed_error_gap(self):
        findings = self.analyzer().run(
            corpus("surface_parity", "bad",
                   ("services.py", "gateway.py")))
        messages = "\n".join(f.message for f in findings)
        assert "no matching dispatch" in messages        # route drift
        assert "never registers it" in messages          # reverse drift
        assert "without calling the shared builder" in messages
        assert "does not map it" in messages             # DebugApiError

    def test_good_corpus_is_clean(self):
        assert self.analyzer().run(
            corpus("surface_parity", "good",
                   ("services.py", "gateway.py"))) == []


class TestDashboardDriftCorpus:
    KNOWN = {"koord_registered_fixture_total",
             "koord_registered_fixture_seconds_bucket"}

    def test_bad_dashboard_flags_unregistered_metric(self):
        errors, checked = dashboard_drift.check_file(
            os.path.join(FIXTURES, "dashboard_drift", "bad_dash.json"),
            self.KNOWN)
        assert checked == 2
        assert len(errors) == 1
        assert "koord_metric_that_does_not_exist_total" in errors[0]

    def test_good_dashboard_is_clean(self):
        errors, checked = dashboard_drift.check_file(
            os.path.join(FIXTURES, "dashboard_drift", "good_dash.json"),
            self.KNOWN)
        assert (errors, checked) == ([], 2)


class TestMarkerAuditCorpus:
    def test_bad_corpus_flags_marker_and_import(self):
        findings = MarkerAuditAnalyzer().run(
            corpus("marker_audit", "bad", ("tests",)))
        messages = "\n".join(f.message for f in findings)
        assert "marked chaos but not slow" in messages
        assert "module-scope jax import" in messages
        assert len(findings) == 2   # the properly-marked test is silent

    def test_good_corpus_is_clean(self):
        assert MarkerAuditAnalyzer().run(
            corpus("marker_audit", "good", ("tests",))) == []


class TestDtypeRegimeCorpus:
    def analyzer(self):
        return DtypeRegimeAnalyzer(package="pkg", targets=("pkg/ops.py",))

    def test_bad_corpus_flags_the_packed_regime_wall(self):
        findings = self.analyzer().run(
            corpus("dtype_regime", "bad", ("pkg",)))
        messages = "\n".join(f.message for f in findings)
        # the reconstructed 2**15 ranking-key overflow: a 2**20-wide
        # clip pushes `q << 15` past int32
        assert "packed ranking-key arithmetic overflows" in messages
        # the unguarded packed composition: no _packed_regime gate, so
        # the tie-break field has no provable 15-bit bound
        assert "no provable bound" in messages
        assert "2**15" in messages
        # unseeded shift operand + the lying retN contract
        assert "cannot be proven to fit int32" in messages
        assert "shape annotation declares" in messages
        assert len(findings) == 5

    def test_good_corpus_is_clean(self):
        # guard + clip + rotation idiom + annotation seeds all prove
        assert self.analyzer().run(
            corpus("dtype_regime", "good", ("pkg",))) == []


class TestForecastCorpus:
    """The forecast kernels' seeded corpus (ISSUE 15): jit-host-sync on
    the horizon scalar, mesh-discipline on the sharded percentile —
    the two regressions forecast/kernels.py must never grow."""

    def sync_analyzer(self):
        return JitHostSyncAnalyzer(package="pkg",
                                   root_paths=["pkg/kernels.py"])

    def mesh_analyzer(self):
        return MeshDisciplineAnalyzer(package="pkg")

    def test_bad_corpus_flags_horizon_host_syncs(self):
        findings = self.sync_analyzer().run(
            corpus("forecast", "bad", ("pkg",)))
        messages = "\n".join(f.message for f in findings)
        assert "host cast float()" in messages       # float(horizon)
        assert "host cast int()" in messages         # int(horizon // 60)
        assert "data-dependent branch" in messages   # if growth > 0
        assert len(findings) == 3

    def test_bad_corpus_flags_sharded_percentile_specs(self):
        findings = self.mesh_analyzer().run(
            corpus("forecast", "bad", ("pkg",)))
        messages = "\n".join(f.message for f in findings)
        assert "omits in_specs and out_specs" in messages
        assert "has no explicit in_spec" in messages  # donated bank
        assert len(findings) == 2

    def test_good_corpus_is_clean(self):
        project = corpus("forecast", "good", ("pkg",))
        assert self.sync_analyzer().run(project) == []
        assert self.mesh_analyzer().run(project) == []


class TestSpecConsistencyCorpus:
    def analyzer(self):
        return SpecConsistencyAnalyzer(package="pkg")

    def test_bad_corpus_flags_every_seeded_violation(self):
        findings = self.analyzer().run(
            corpus("spec_consistency", "bad", ("pkg",)))
        messages = "\n".join(f.message for f in findings)
        assert "names an axis not live" in messages       # psum("pods")
        assert "in_specs declares 3 entries" in messages  # arity drift
        assert "out_specs declares 2 entries" in messages
        assert "replicas" in messages and "diverge" in messages
        assert "propagated layout contradicts" in messages
        # the 2-D regression seed (ISSUE 14): pod batch re-gathered
        # inside the round loop
        assert "inside a device loop body" in messages
        assert len(findings) == 6

    def test_good_corpus_is_clean(self):
        # right axis, aligned arities, sharded-base scatter (with the
        # shape-annotation layout seed), matched chained layouts, and
        # the 2-D gather-once-above-the-loop twin
        assert self.analyzer().run(
            corpus("spec_consistency", "good", ("pkg",))) == []


class TestDonationFlowCorpus:
    def analyzer(self):
        return DonationFlowAnalyzer(package="pkg")

    def test_bad_corpus_flags_missing_swap_and_stash(self):
        findings = self.analyzer().run(
            corpus("donation_flow", "bad", ("pkg",)))
        messages = "\n".join(f.message for f in findings)
        # the interprocedural kill: dispatch_without_swap leaves the
        # state dead, round()'s commit() call reads it two hops later
        assert "left dead" in messages
        assert "commit" in messages
        # the stash-the-donated-buffer tenancy anti-idiom — seeded in
        # pipeline.py AND in the quality rounding loop's pre-re-solve
        # stash (quality_rounding.py, ISSUE 13)
        assert "stash" in messages
        # direct dead reads: the rebound-alias non-swap (pipeline.py),
        # the rounding loop's missing SECOND swap after the residual
        # re-solve, and the residual re-solve's donated ASSIGNMENT
        # buffer read back afterwards (quality_rounding.py)
        assert messages.count("read after its buffers were donated") == 3
        assert "self.last_assignments" in messages
        by_file = {f.path for f in findings}
        assert by_file == {"pkg/pipeline.py", "pkg/quality_rounding.py"}
        assert len(findings) == 6

    def test_good_corpus_is_clean(self):
        # blessed swap, metadata reads, swap-through-method (the
        # adopt_state idiom), the rebind idiom, and the quality
        # rounding loop's swap-between-passes / merge-before-donating
        # twins all pass
        assert self.analyzer().run(
            corpus("donation_flow", "good", ("pkg",))) == []


class TestTenantAxisCorpus:
    def analyzer(self):
        return TenantAxisAnalyzer(package="pkg",
                                  targets=("pkg/front.py",))

    def test_bad_corpus_flags_unreduced_tenant_axis(self):
        findings = self.analyzer().run(
            corpus("tenant_axis", "bad", ("pkg",)))
        messages = "\n".join(f.message for f in findings)
        assert "still carries the leading tenant axis" in messages
        # the kit-entry contract from the binding's shape annotation
        assert "per-tenant contract" in messages
        assert len(findings) == 5

    def test_good_corpus_is_clean(self):
        # every slice _unstack'd (or [i]-indexed) before the sink
        assert self.analyzer().run(
            corpus("tenant_axis", "good", ("pkg",))) == []


class TestWireCodecCorpus:
    """ISSUE 19: per-event json.dumps on a frame type that has a v2
    columnar encoding is a finding — the rule that keeps the codec
    tentpole from quietly regressing to per-event JSON."""

    def analyzer(self):
        return WireCodecAnalyzer(package="pkg",
                                 codec_home=("pkg/wire.py",))

    def test_bad_corpus_flags_each_columnar_frame(self):
        findings = self.analyzer().run(
            corpus("wire_codec", "bad", ("pkg",)))
        messages = "\n".join(f.message for f in findings)
        # one seeded regression per columnar frame type: the per-event
        # STATE_PUSH send loop, the DELTA payload built from a
        # comprehension of dumps, the while-loop SNAPSHOT chunker
        for frame in ("STATE_PUSH", "DELTA", "SNAPSHOT"):
            assert f"FrameType.{frame}" in messages, messages
        assert len(findings) == 3
        assert all("events_v2" in f.message for f in findings)
        assert all("wire_protocol" in f.hint for f in findings)

    def test_good_corpus_is_clean(self):
        # per-frame dumps on columnar frames, a dumps loop with no
        # columnar frame in scope, and the exempted codec home's v1
        # fallback all pass
        assert self.analyzer().run(
            corpus("wire_codec", "good", ("pkg",))) == []

    def test_codec_home_exemption_is_load_bearing(self):
        # the same good corpus WITHOUT the exemption flags the v1
        # fallback packer — proof the default exemption for
        # transport/wire.py + deltasync.py is what keeps the real
        # tree's legacy path legal
        findings = WireCodecAnalyzer(package="pkg", codec_home=()).run(
            corpus("wire_codec", "good", ("pkg",)))
        assert [f.path for f in findings] == ["pkg/wire.py"]
        assert "pack_events_v1" in findings[0].message

    def test_real_transport_tree_is_clean(self, real_tree):
        # the shipped tree ships no per-event JSON on columnar frames
        # (the v1 paths live inside the exempt codec home; real_tree
        # reuses the shared whole-tree parse — the parse dominates)
        assert WireCodecAnalyzer().run(real_tree) == []


class TestLatencyHomeCorpus:
    def test_bad_corpus_flags_every_seeded_site(self):
        findings = LatencyHomeAnalyzer().run(
            corpus("latency_home", "bad", ("pkg",)))
        messages = "\n".join(f"{f.line}: {f.message}" for f in findings)
        assert len(findings) == 3, messages
        # one delta inside the bind loop, one against a stashed stamp
        # in the pending loop, one stored keyed by pod name
        for needle in ("inside `for (pod, node) in binds`",
                       "inside `for name in pending`",
                       "stored per pod under [pod.name]"):
            assert needle in messages, f"missing: {needle}\n{messages}"
        assert all("journey.LEDGER" in f.hint for f in findings)

    def test_good_corpus_round_scoped_deltas_stay_silent(self):
        assert LatencyHomeAnalyzer().run(
            corpus("latency_home", "good", ("pkg",))) == []

    def test_measurement_homes_are_exempt(self, real_tree):
        # journey.py itself subtracts clocks per pod BY DESIGN; the
        # rule must skip the sanctioned homes or it flags its own cure
        assert all(f.path not in ("koordinator_tpu/journey.py",
                                  "koordinator_tpu/timeline.py")
                   for f in LatencyHomeAnalyzer().run(real_tree))

    def test_real_tree_is_clean(self, real_tree):
        assert LatencyHomeAnalyzer().run(real_tree) == []


@pytest.fixture(scope="module")
def real_tree():
    """One whole-tree parse shared by every real-code specflow test
    (the parse dominates; SourceFiles are immutable so clones are
    cheap)."""
    return Project(REPO)


def clone_project(base: Project) -> Project:
    clone = object.__new__(Project)
    clone.root = base.root
    clone.files = dict(base.files)
    return clone


class TestSpecflowOnRealCode:
    """The acceptance demos: the proofs hold on the SHIPPED solver, and
    deliberately breaking a previously-unchecked invariant fails the
    build — not just on fixtures."""

    def _mutated(self, base, path, old, new):
        project = clone_project(base)
        src = project.files[path].text
        assert old in src, f"mutation anchor missing from {path}"
        fd, tmp = tempfile.mkstemp(suffix=".py")
        with os.fdopen(fd, "w") as f:
            f.write(src.replace(old, new, 1))
        project.files[path] = SourceFile(tmp, path)
        os.unlink(tmp)
        return project

    def test_real_batch_assign_proves_clean(self):
        # through the runner so the one reasoned inline ignore (the
        # trace-time float-scale shift) applies, as in the gate
        result = koordlint.run(REPO, rules=["dtype-regime"])
        assert result.findings == [], "\n".join(
            f.render() for f in result.findings)
        assert result.suppressed, "the reasoned inline ignore is live"

    def test_widened_clip_overflows_the_packed_key(self, real_tree):
        # the 2**15-wall class of bug, planted in the REAL solver: a
        # 2**20-wide score clip pushes `q << _TB_BITS` past int32
        project = self._mutated(
            real_tree,
            "koordinator_tpu/ops/batch_assign.py",
            "_SCORE_CLIP = (1 << 30 - _TB_BITS) - 1",
            "_SCORE_CLIP = (1 << 20) - 1")
        messages = "\n".join(
            f.message for f in DtypeRegimeAnalyzer().run(project))
        assert "packed ranking-key arithmetic overflows" in messages

    def test_removed_regime_guard_fails_the_field_proof(self, real_tree):
        # delete the packed/wide split: the tie-break field can reach
        # n_total - 1 > 2**15 and the rule must refuse the proof
        project = self._mutated(
            real_tree,
            "koordinator_tpu/ops/batch_assign.py",
            "key = ((q << _TB_BITS) | tb) if _packed_regime(n_total) "
            "else q",
            "key = (q << _TB_BITS) | tb")
        messages = "\n".join(
            f.message for f in DtypeRegimeAnalyzer().run(project))
        assert "reserves only 15 bits" in messages

    def test_real_scheduler_double_buffer_proves_clean(self, real_tree):
        findings = DonationFlowAnalyzer().run(clone_project(real_tree))
        assert findings == [], "\n".join(f.render() for f in findings)

    def test_dropped_unstack_in_real_tenancy_is_rank_drift(
            self, real_tree):
        # hand one tenant the still-stacked assignments instead of its
        # _unstack'd slice: the tenant-axis taint must reach the sink
        project = self._mutated(
            real_tree,
            "koordinator_tpu/scheduler/tenancy.py",
            "                self._unstack(a, i), "
            "self._unstack(st, i),",
            "                a, self._unstack(st, i),")
        messages = "\n".join(
            f.message for f in TenantAxisAnalyzer().run(project))
        assert "still carries the leading tenant axis" in messages

    def test_removed_blessed_swap_is_caught_interprocedurally(
            self, real_tree):
        # delete the dispatch half's re-point of snapshot.state: the
        # read surfaces FUNCTIONS AWAY (schedule_round's host-half
        # introspection) — the class donation-safety cannot see
        project = self._mutated(
            real_tree,
            "koordinator_tpu/scheduler/scheduler.py",
            "                self.snapshot.state = new_state\n",
            "")
        findings = DonationFlowAnalyzer().run(project)
        assert findings, "missing-swap mutation produced no findings"
        messages = "\n".join(f.message for f in findings)
        assert "self.snapshot.state" in messages
        assert "left dead" in messages


@pytest.fixture(scope="module")
def full_tree_run():
    """ONE full-suite CLI run shared by the whole-tree gate and the
    wall-clock guard (each whole-tree pass costs ~5s of tier-1)."""
    return subprocess.run(
        [sys.executable, "-m", "tools.koordlint", "--format", "json"],
        cwd=REPO, capture_output=True, text=True, timeout=120)


class TestWholeTree:
    """The gate tier-1 actually enforces: the shipped tree is clean."""

    def test_tree_is_clean_and_baseline_is_live(self, full_tree_run):
        assert full_tree_run.returncode == 0, (
            full_tree_run.stdout[-2000:] + full_tree_run.stderr)
        doc = json.loads(full_tree_run.stdout)
        assert doc["findings"] == []
        # the baseline is doing real work (grandfathered jax imports)
        # and every suppression carries a reason by construction
        assert doc["suppressed"]
        assert all(e["reason"].strip() for e in doc["suppressed"])
        # no dead weight: every baseline entry still matches something
        assert doc["stale_baseline"] == []

    def test_every_shipped_analyzer_has_a_corpus(self):
        for cls in koordlint.ALL_ANALYZERS:
            rule_dir = cls.name.replace("-", "_")
            assert os.path.isdir(os.path.join(FIXTURES, rule_dir)), (
                f"analyzer {cls.name} ships no fixture corpus")


class TestSuppressionMachinery:
    def _tmp_repo(self, tmp_path, body: str):
        tests = tmp_path / "tests"
        tests.mkdir()
        (tests / "test_seeded.py").write_text(body)
        return Project(str(tmp_path), targets=("tests",))

    def test_inline_ignore_with_reason_suppresses(self, tmp_path):
        project = self._tmp_repo(
            tmp_path,
            "import jax  "
            "# koordlint: ignore[marker-audit] -- perf fixture needs "
            "module-scope jax\n")
        findings = MarkerAuditAnalyzer().run(project)
        assert len(findings) == 1
        result = apply_suppressions(project, findings, [])
        assert result.findings == []
        assert len(result.suppressed) == 1
        assert "perf fixture" in result.suppressed[0][1]

    def test_inline_ignore_without_reason_is_a_finding(self, tmp_path):
        project = self._tmp_repo(
            tmp_path, "import jax  # koordlint: ignore[marker-audit]\n")
        findings = MarkerAuditAnalyzer().run(project)
        result = apply_suppressions(project, findings, [])
        rules = [f.rule for f in result.findings]
        assert "marker-audit" in rules       # NOT suppressed
        assert "lint-hygiene" in rules       # and the bad ignore flagged

    def test_baseline_entry_without_reason_is_a_finding(self, tmp_path):
        path = tmp_path / "baseline.json"
        path.write_text(json.dumps({"suppressions": [
            {"rule": "marker-audit", "path": "tests/test_x.py"}]}))
        entries, problems = load_baseline(str(path))
        assert entries == []
        assert len(problems) == 1
        assert problems[0].rule == "lint-hygiene"

    def test_shipped_baseline_reasons_are_mandatory_and_present(self):
        entries, problems = load_baseline(koordlint.BASELINE_PATH)
        assert problems == []
        assert entries
        assert all(e.reason.strip() for e in entries)


class TestCli:
    def _run(self, *args):
        return subprocess.run(
            [sys.executable, "-m", "tools.koordlint", *args],
            cwd=REPO, capture_output=True, text=True, timeout=120)

    def test_clean_tree_exits_zero(self):
        # one rule keeps the subprocess cheap; the FULL suite's
        # whole-tree gate runs in-process in TestWholeTree above
        proc = self._run("--rule", "marker-audit")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "koordlint OK" in proc.stdout
        assert "suppressed-with-reason" in proc.stdout

    def test_new_finding_exits_nonzero(self, tmp_path):
        bad = tmp_path / "tests"
        bad.mkdir()
        (bad / "test_fresh.py").write_text("import jax\n")
        (tmp_path / "koordinator_tpu").mkdir()
        (tmp_path / "tools").mkdir()
        proc = self._run("--root", str(tmp_path))
        assert proc.returncode == 1
        assert "module-scope jax import" in proc.stdout

    def test_unknown_rule_exits_two(self):
        assert self._run("--rule", "no-such-rule").returncode == 2

    def test_list_rules_names_every_shipped_rule(self):
        proc = self._run("--list-rules")
        assert proc.returncode == 0
        for rule in ("jit-host-sync", "donation-safety", "lock-discipline",
                     "surface-parity", "dashboard-drift", "marker-audit",
                     "mesh-discipline", "spec-consistency", "dtype-regime",
                     "donation-flow", "tenant-axis"):
            assert rule in proc.stdout

    def test_format_json_is_machine_readable(self):
        proc = self._run("--rule", "marker-audit", "--format", "json")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        doc = json.loads(proc.stdout)
        assert doc["findings"] == []
        assert doc["suppressed"], "baseline suppressions should appear"
        entry = doc["suppressed"][0]["finding"]
        # the pre-commit contract: file/line/rule/message/fix-hint
        assert set(entry) >= {"rule", "path", "line", "message", "hint"}
        assert doc["elapsed_s"] > 0

    def test_changed_only_filters_to_touched_files(self, tmp_path):
        repo = tmp_path / "repo"
        (repo / "tests").mkdir(parents=True)
        (repo / "koordinator_tpu").mkdir()
        (repo / "tools").mkdir()
        (repo / "tests" / "test_old.py").write_text("import jax\n")

        def git(*args):
            subprocess.run(["git", *args], cwd=repo, check=True,
                           capture_output=True, timeout=30)

        git("init", "-q")
        git("config", "user.email", "t@t")
        git("config", "user.name", "t")
        git("add", "-A")
        git("commit", "-qm", "seed")
        # a NEW bad file after the ref: only it may be reported
        (repo / "tests" / "test_new.py").write_text("import jax\n")
        proc = self._run("--root", str(repo), "--no-baseline",
                         "--changed-only", "HEAD", "--format", "json")
        doc = json.loads(proc.stdout)
        paths = {f["path"] for f in doc["findings"]}
        assert paths == {"tests/test_new.py"}, doc["findings"]
        assert proc.returncode == 1
        assert doc["changed_only"] == ["tests/test_new.py"]

    def test_full_tree_stays_inside_the_tier1_budget(self, full_tree_run):
        # the wall-clock guard the issue demands: the dataflow engine
        # must not silently eat the tier-1 budget.  elapsed_s is the
        # tool's own timing (interpreter startup excluded); the run is
        # shared with TestWholeTree's gate
        assert full_tree_run.returncode == 0, (
            full_tree_run.stdout[-2000:] + full_tree_run.stderr)
        doc = json.loads(full_tree_run.stdout)
        assert doc["elapsed_s"] < 20.0, (
            f"full-tree koordlint took {doc['elapsed_s']}s — the "
            "static-analysis suite is eating the tier-1 budget")


class TestRuntimeHelpers:
    def test_find_cycle(self):
        from tools.koordlint.runtime import find_cycle

        assert find_cycle({("a", "b"), ("b", "c")}) is None
        cycle = find_cycle({("a", "b"), ("b", "c"), ("c", "a")})
        assert cycle is not None and set(cycle) >= {"a", "b", "c"}

    def test_instrumented_lock_records_edges(self):
        import threading

        from tools.koordlint.runtime import (
            LockOrderRecorder,
            instrument_locks,
        )

        class Box:
            def __init__(self):
                self._outer = threading.Lock()
                self._inner = threading.Lock()

        box = Box()
        rec = LockOrderRecorder()
        # explicit cls_name overrides the module.Class default
        assert set(instrument_locks(box, rec, cls_name="Box")) == {
            "Box._outer", "Box._inner"}
        with box._outer:
            with box._inner:
                pass
        assert ("Box._outer", "Box._inner") in rec.edge_pairs()
        assert ("Box._inner", "Box._outer") not in rec.edge_pairs()
