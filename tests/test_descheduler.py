import jax.numpy as jnp
import numpy as np

from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS, ResourceDim
from koordinator_tpu.descheduler.lownodeload import (
    LowNodeLoadArgs,
    classify_nodes,
    eviction_budget,
    effective_thresholds,
    select_victims,
    update_anomaly_counters,
    usage_percent,
)
from koordinator_tpu.descheduler.migration import (
    ArbitrationLimits,
    MigrationController,
    MigrationJob,
    MigrationJobPhase,
)

R = NUM_RESOURCE_DIMS
CPU, MEM = ResourceDim.CPU, ResourceDim.MEMORY


def mk(n, cpu_cap=10_000, mem_cap=100_000):
    cap = np.zeros((n, R), np.int32)
    cap[:, CPU], cap[:, MEM] = cpu_cap, mem_cap
    return cap


def usage_of(cap, cpu_pct, mem_pct):
    u = np.zeros_like(cap)
    u[:, CPU] = cap[:, CPU] * np.asarray(cpu_pct) // 100
    u[:, MEM] = cap[:, MEM] * np.asarray(mem_pct) // 100
    return u


def test_classify_under_over():
    cap = mk(4)
    usage = usage_of(cap, [20, 50, 80, 30], [30, 50, 50, 90])
    valid = np.ones(4, bool)
    under, over = classify_nodes(
        jnp.asarray(usage), jnp.asarray(cap), jnp.asarray(valid),
        LowNodeLoadArgs.default(),  # low 45/60, high 65/80
    )
    # node0: all below low -> under; node1: between -> neither;
    # node2: cpu 80 > 65 -> over; node3: mem 90 > 80 -> over
    assert np.asarray(under).tolist()[:4] == [True, False, False, False]
    assert np.asarray(over).tolist()[:4] == [False, False, True, True]


def test_deviation_thresholds():
    cap = mk(2)
    usage = usage_of(cap, [30, 70], [50, 50])
    args = LowNodeLoadArgs.default().replace(
        low_thresholds=jnp.full(R, -1, jnp.int32).at[CPU].set(10),
        high_thresholds=jnp.full(R, -1, jnp.int32).at[CPU].set(10),
        use_deviation=jnp.asarray(True),
    )
    pct = usage_percent(jnp.asarray(usage), jnp.asarray(cap))
    low, high = effective_thresholds(args, pct, jnp.asarray(np.ones(2, bool)))
    # mean cpu = 50 -> low 40, high 60
    assert int(low[CPU]) == 40
    assert int(high[CPU]) == 60
    under, over = classify_nodes(
        jnp.asarray(usage), jnp.asarray(cap), jnp.asarray(np.ones(2, bool)), args
    )
    assert np.asarray(under).tolist() == [True, False]
    assert np.asarray(over).tolist() == [False, True]


def test_anomaly_counter():
    c = jnp.asarray(np.zeros(3, np.int32))
    over = jnp.asarray(np.array([True, True, False]))
    c = update_anomaly_counters(c, over)
    c = update_anomaly_counters(c, jnp.asarray(np.array([True, False, False])))
    assert np.asarray(c).tolist() == [2, 0, 0]


def test_eviction_budget():
    cap = mk(2)
    usage = usage_of(cap, [20, 90], [30, 90])
    args = LowNodeLoadArgs.default()
    pct = usage_percent(jnp.asarray(usage), jnp.asarray(cap))
    _, high = effective_thresholds(args, pct, jnp.asarray(np.ones(2, bool)))
    under = jnp.asarray(np.array([True, False]))
    b = eviction_budget(jnp.asarray(usage), jnp.asarray(cap), under, high)
    # node0: cpu 65%*10000 - 2000 = 4500; mem 80%*100000 - 30000 = 50000
    assert int(b[CPU]) == 4_500
    assert int(b[MEM]) == 50_000


def select(usage, cap, pod_node, pod_usage, prio, evictable=None, counters=None,
           args=None):
    n = cap.shape[0]
    p = len(pod_node)
    return np.asarray(select_victims(
        jnp.asarray(usage), jnp.asarray(cap), jnp.asarray(np.ones(n, bool)),
        jnp.asarray(np.asarray(pod_node, np.int32)),
        jnp.asarray(pod_usage),
        jnp.asarray(np.asarray(prio, np.int32)),
        jnp.asarray(np.ones(p, bool) if evictable is None else evictable),
        jnp.asarray(np.full(n, 99, np.int32) if counters is None else counters),
        args or LowNodeLoadArgs.default(),
    ))


def test_select_victims_rebalances_hot_node():
    cap = mk(2)
    usage = usage_of(cap, [90, 20], [50, 20])  # node0 hot on cpu, node1 cold
    pod_usage = np.zeros((3, R), np.int32)
    pod_usage[:, CPU] = [3_000, 2_000, 1_000]
    victims = select(usage, cap, [0, 0, 0], pod_usage, [9_000, 5_000, 3_000])
    # evict cheapest first: pod2 (1000, prio 3000) -> node at 80% still > 65;
    # pod1 (2000) -> 60% <= 65 stop. pod0 survives.
    assert victims.tolist()[:3] == [False, True, True]


def test_select_victims_respects_budget():
    cap = mk(2)
    usage = usage_of(cap, [90, 60], [50, 20])  # node1 not under (cpu 60 >= 45)
    pod_usage = np.zeros((1, R), np.int32)
    pod_usage[0, CPU] = 1_000
    victims = select(usage, cap, [0], pod_usage, [3_000])
    # no underutilized nodes -> zero budget -> nothing evicted
    assert not victims.any()


def test_select_victims_needs_anomaly_rounds():
    cap = mk(2)
    usage = usage_of(cap, [90, 20], [50, 20])
    pod_usage = np.zeros((1, R), np.int32)
    pod_usage[0, CPU] = 1_000
    victims = select(usage, cap, [0], pod_usage, [3_000],
                     counters=np.array([1, 0], np.int32))  # < 3 rounds
    assert not victims.any()


def test_select_victims_skips_unevictable():
    cap = mk(2)
    usage = usage_of(cap, [90, 20], [50, 20])
    pod_usage = np.zeros((2, R), np.int32)
    pod_usage[:, CPU] = [2_000, 2_000]
    victims = select(usage, cap, [0, 0], pod_usage, [3_000, 3_000],
                     evictable=np.array([False, True]))
    assert victims.tolist()[:2] == [False, True]


# -- migration controller ----------------------------------------------------


def test_migration_lifecycle_with_reservation():
    evicted = []
    ctl = MigrationController(
        reserve_fn=lambda j: f"resv-{j.pod}",
        evict_fn=lambda j: evicted.append(j.pod) or True,
    )
    ctl.submit(MigrationJob(name="j1", pod="p1", node="n1"))
    ctl.reconcile()
    job = ctl.jobs["j1"]
    assert job.phase is MigrationJobPhase.SUCCEEDED
    assert job.reservation == "resv-p1"
    assert evicted == ["p1"]


def test_migration_reservation_failure():
    ctl = MigrationController(reserve_fn=lambda j: None)
    ctl.submit(MigrationJob(name="j1", pod="p1", node="n1"))
    ctl.reconcile()
    assert ctl.jobs["j1"].phase is MigrationJobPhase.FAILED
    assert ctl.jobs["j1"].reason == "ReservationFailed"


def test_migration_group_limits_per_node():
    ctl = MigrationController(
        limits=ArbitrationLimits(max_migrating_per_node=1),
        evict_fn=lambda j: False,  # stays running
    )
    ctl.submit(MigrationJob(name="j1", pod="p1", node="n1", create_time=1))
    ctl.submit(MigrationJob(name="j2", pod="p2", node="n1", create_time=2))
    ctl.submit(MigrationJob(name="j3", pod="p3", node="n2", create_time=3))
    ctl.reconcile()
    phases = {n: j.phase for n, j in ctl.jobs.items()}
    assert phases["j1"] is MigrationJobPhase.RUNNING
    assert phases["j2"] is MigrationJobPhase.PENDING  # node n1 at limit
    assert phases["j3"] is MigrationJobPhase.RUNNING


def test_migration_workload_unavailable_budget():
    ctl = MigrationController(
        limits=ArbitrationLimits(max_unavailable_per_workload=1),
        workload_unavailable_fn=lambda w: 1,  # already one unavailable
        evict_fn=lambda j: True,
    )
    ctl.submit(MigrationJob(name="j1", pod="p1", node="n1", workload="w1"))
    ctl.reconcile()
    assert ctl.jobs["j1"].phase is MigrationJobPhase.PENDING


def test_migration_sort_lower_priority_first():
    started = []
    ctl = MigrationController(
        limits=ArbitrationLimits(max_migrating_per_node=1),
        evict_fn=lambda j: started.append(j.pod) or True,
    )
    ctl.submit(MigrationJob(name="j1", pod="hi", node="n1", priority=9_500,
                            create_time=1))
    ctl.submit(MigrationJob(name="j2", pod="lo", node="n1", priority=3_000,
                            create_time=2))
    ctl.reconcile()
    # only one runs (node limit); the lower-priority pod goes first
    assert started == ["lo"]


def test_migration_timeout():
    t = [0.0]
    ctl = MigrationController(evict_fn=lambda j: False, clock=lambda: t[0])
    ctl.submit(MigrationJob(name="j1", pod="p1", node="n1", timeout_sec=10))
    ctl.reconcile()
    assert ctl.jobs["j1"].phase is MigrationJobPhase.RUNNING
    t[0] = 100.0
    ctl.reconcile()
    assert ctl.jobs["j1"].phase is MigrationJobPhase.FAILED
    assert ctl.jobs["j1"].reason == "Timeout"


# ---- controllerfinder: workload-derived budgets (migration/util/util.go:81,
# arbitrator/filter.go:409) --------------------------------------------------

def test_get_max_unavailable_defaults_and_scaling():
    from koordinator_tpu.descheduler.migration import get_max_unavailable

    # replica-count-dependent defaults when unspecified
    assert get_max_unavailable(1, None) == 1
    assert get_max_unavailable(3, None) == 1
    assert get_max_unavailable(4, None) == 2
    assert get_max_unavailable(10, None) == 2
    assert get_max_unavailable(50, None) == 5      # 10%
    # explicit int and percent specs (round-down, 0 floors to 1)
    assert get_max_unavailable(20, 3) == 3
    assert get_max_unavailable(20, "25%") == 5
    assert get_max_unavailable(5, "10%") == 1       # 0.5 -> 0 -> floor 1
    # capped at replicas
    assert get_max_unavailable(2, 10) == 2


def test_migration_workload_derived_budgets():
    from koordinator_tpu.descheduler.migration import (
        ControllerFinder, Workload)

    finder = ControllerFinder()
    # 20-replica deployment declaring maxUnavailable 10% -> budget 2
    finder.register(Workload(ref="Deployment/web", expected_replicas=20,
                             max_unavailable="10%", unavailable=1))
    ctl = MigrationController(
        controller_finder=finder,
        evict_fn=lambda j: False,  # keep jobs running to occupy budget
    )
    for i in range(3):
        ctl.submit(MigrationJob(name=f"j{i}", pod=f"p{i}", node=f"n{i}",
                                workload="Deployment/web", create_time=i))
    ctl.reconcile()
    phases = [ctl.jobs[f"j{i}"].phase for i in range(3)]
    # budget 2, one pod already unavailable -> only one migration admitted
    assert phases == [MigrationJobPhase.RUNNING, MigrationJobPhase.PENDING,
                      MigrationJobPhase.PENDING]


def test_migration_unknown_workload_uses_flat_limits():
    from koordinator_tpu.descheduler.migration import ControllerFinder

    ctl = MigrationController(
        controller_finder=ControllerFinder(),   # knows nothing
        evict_fn=lambda j: False,
    )
    for i in range(3):
        ctl.submit(MigrationJob(name=f"j{i}", pod=f"p{i}", node=f"n{i}",
                                workload="Deployment/mystery", create_time=i))
    ctl.reconcile()
    running = sum(j.phase is MigrationJobPhase.RUNNING
                  for j in ctl.jobs.values())
    assert running == 2   # flat default budget
