import jax
import jax.numpy as jnp
import numpy as np

from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS, ResourceDim
from koordinator_tpu.ops.assignment import ScoringConfig
from koordinator_tpu.ops.gang import GangInfo, gang_assign, pre_enqueue_mask
from koordinator_tpu.state.cluster_state import ClusterState, PodBatch

R = NUM_RESOURCE_DIMS
CPU, MEM = ResourceDim.CPU, ResourceDim.MEMORY


def mk_state(node_cpus, mem=65_536):
    alloc = np.zeros((len(node_cpus), R), np.int32)
    alloc[:, CPU] = node_cpus
    alloc[:, MEM] = mem
    return ClusterState.from_arrays(alloc)


def mk_pods(cpus, gang_id, state, mem=1_024, priority=None):
    req = np.zeros((len(cpus), R), np.int32)
    req[:, CPU] = cpus
    req[:, MEM] = mem
    return PodBatch.build(
        req,
        gang_id=np.asarray(gang_id, np.int32),
        priority=None if priority is None else np.asarray(priority, np.int32),
        node_capacity=state.capacity,
    )


def cfg():
    return ScoringConfig.default().replace(
        usage_thresholds=jnp.zeros(R, jnp.int32),
        estimator_defaults=jnp.zeros(R, jnp.int32),
    )


def test_gang_satisfied_schedules_all():
    state = mk_state([10_000, 10_000])
    pods = mk_pods([4_000] * 4, [0, 0, 0, 0], state)
    gangs = GangInfo.build(np.array([4]))
    a, _, _ = jax.jit(gang_assign, static_argnames="passes")(
        state, pods, cfg(), gangs
    )
    assert (np.asarray(a)[:4] >= 0).all()


def test_gang_unsatisfiable_rolls_back_all():
    # only 3 of the 4 gang pods can fit -> whole gang rolls back
    state = mk_state([10_000])
    pods = mk_pods([3_000] * 4, [0, 0, 0, 0], state)
    gangs = GangInfo.build(np.array([4]))
    a, st, _ = gang_assign(state, pods, cfg(), gangs)
    assert (np.asarray(a)[:4] == -1).all()
    # and its capacity was fully returned
    assert int(st.node_requested[0, CPU]) == 0


def test_gang_min_member_below_total():
    # 4 pods, minMember 3, capacity for exactly 3 -> gang succeeds with 3
    state = mk_state([9_000])
    pods = mk_pods([3_000] * 4, [0, 0, 0, 0], state)
    gangs = GangInfo.build(np.array([3]))
    a, _, _ = gang_assign(state, pods, cfg(), gangs)
    assert (np.asarray(a)[:4] >= 0).sum() == 3


def test_failed_gang_frees_capacity_for_others():
    # gang needs 4x3000 on one 10k node (impossible); a lone pod needs 9000.
    # pass 1: gang pods grab capacity, lone pod may not fit; after rollback,
    # pass 2 must place the lone pod.
    state = mk_state([10_000])
    pods = mk_pods(
        [3_000, 3_000, 3_000, 3_000, 9_000],
        [0, 0, 0, 0, -1],
        state,
        priority=[9_500, 9_500, 9_500, 9_500, 3_000],  # gang first
    )
    gangs = GangInfo.build(np.array([4]))
    a, st, _ = gang_assign(state, pods, cfg(), gangs, passes=2)
    a = np.asarray(a)
    assert (a[:4] == -1).all()
    assert a[4] == 0
    assert int(st.node_requested[0, CPU]) == 9_000


def test_gang_group_all_or_nothing():
    # two gangs in one group; gang B cannot fit -> gang A rolls back too
    state = mk_state([4_000, 4_000])
    pods = mk_pods(
        [2_000, 2_000, 6_000, 6_000],
        [0, 0, 1, 1],
        state,
    )
    gangs = GangInfo.build(np.array([2, 2]), group_id=np.array([0, 0]))
    a, st, _ = gang_assign(state, pods, cfg(), gangs)
    assert (np.asarray(a)[:4] == -1).all()
    assert int(np.asarray(st.node_requested)[:, CPU].sum()) == 0

    # independent groups: gang A succeeds alone
    gangs2 = GangInfo.build(np.array([2, 2]), group_id=np.array([0, 1]))
    a2, _, _ = gang_assign(state, pods, cfg(), gangs2)
    assert (np.asarray(a2)[:2] >= 0).all()
    assert (np.asarray(a2)[2:4] == -1).all()


def test_pre_enqueue_blocks_incomplete_gang():
    state = mk_state([10_000])
    # gang 0 declares minMember 3 but only 2 pods are pending
    pods = mk_pods([1_000, 1_000], [0, 0], state)
    gangs = GangInfo.build(np.array([3]))
    mask = np.asarray(pre_enqueue_mask(pods, gangs))
    assert not mask[:2].any()
    a, _, _ = gang_assign(state, pods, cfg(), gangs)
    assert (np.asarray(a)[:2] == -1).all()


def test_surplus_member_of_satisfied_gang_binds_in_later_pass():
    # Gang A (3x2000, minMember 2) and higher-priority gang B (2x6000,
    # minMember 2) on one 10k node. Pass 1: B takes 12000? no - only one B pod
    # fits (6000+2000*2=10000), B fails, A keeps 2. Pass 2: A's third pod must
    # bind into B's freed capacity — the gang is already satisfied, so the
    # recount must credit A's prior keeps (Permit: satisfied gang binds more).
    state = mk_state([10_000])
    pods = mk_pods(
        [2_000, 2_000, 2_000, 6_000, 6_000],
        [0, 0, 0, 1, 1],
        state,
        priority=[5_000, 5_000, 5_000, 9_500, 9_500],
    )
    gangs = GangInfo.build(np.array([2, 2]))
    a, st, _ = gang_assign(state, pods, cfg(), gangs, passes=2)
    a = np.asarray(a)
    assert (a[:3] >= 0).all(), a  # all three A pods placed across passes
    assert (a[3:5] == -1).all()
    assert int(st.node_requested[0, CPU]) == 6_000


def test_multi_pass_respects_usage_threshold_feedback():
    # Regression: pass 2 must see pass-1 keeps' estimated usage. One node,
    # usage 5000/10000, threshold 65% (limit 6500), two 1000m pods: single
    # pass rejects the second (7000 > 6500); multi-pass must agree.
    alloc = np.zeros((1, R), np.int32)
    alloc[0, CPU], alloc[0, MEM] = 10_000, 100_000
    usage = np.zeros((1, R), np.int32)
    usage[0, CPU] = 5_000
    state = ClusterState.from_arrays(alloc, usage=usage)
    pods = mk_pods([1_000, 1_000], [-1, -1], state, mem=16)
    gangs = GangInfo.build(np.array([], dtype=np.int64).reshape(0))
    c = cfg().replace(usage_thresholds=jnp.zeros(R, jnp.int32).at[CPU].set(65))
    a, _, _ = gang_assign(state, pods, c, gangs, passes=2)
    from koordinator_tpu.ops.assignment import greedy_assign

    a1, _, _ = greedy_assign(state, pods, c)
    assert np.asarray(a)[:2].tolist() == np.asarray(a1)[:2].tolist() == [0, -1]


def test_gang_with_quota_rollback_restores_headroom():
    from koordinator_tpu.quota import QuotaDeviceState, QuotaTree
    from koordinator_tpu.quota.tree import UNBOUNDED

    state = mk_state([10_000])

    def vec(c, m):
        v = np.zeros(R, np.int64)
        v[CPU], v[MEM] = c, m
        return v

    mx = np.full(R, UNBOUNDED, np.int64)
    mx[CPU], mx[MEM] = 20_000, 131_072
    t = QuotaTree(vec(20_000, 131_072))
    t.add("q", min=vec(0, 0), max=mx)
    t.set_request("q", vec(12_000, 4_096))
    t.refresh_runtime()
    qs, idx = QuotaDeviceState.from_tree(t)
    before = int(qs.headroom[idx["q"], CPU])

    req = np.zeros((4, R), np.int32)
    req[:, CPU] = 3_000
    req[:, MEM] = 1_024
    pods = PodBatch.build(
        req,
        gang_id=np.zeros(4, np.int32),
        quota_id=np.full(4, idx["q"], np.int32),
        node_capacity=state.capacity,
    )
    gangs = GangInfo.build(np.array([4]))
    # node fits only 3 -> gang fails -> quota must be fully restored
    a, _, qs2 = gang_assign(state, pods, cfg(), gangs, quota=qs)
    assert (np.asarray(a)[:4] == -1).all()
    assert int(qs2.headroom[idx["q"], CPU]) == before


# ---- batch-parallel solver engine (gang_assign solver="batch") -------------

def test_gang_all_or_nothing_with_batch_solver():
    # 4-member gang, capacity for only 3: the batch engine must roll the
    # whole gang back exactly like the greedy engine
    state = mk_state([4_000, 4_000, 4_000])
    pods = mk_pods([3_000] * 4, [0, 0, 0, 0], state)
    gangs = GangInfo.build(np.array([4]))
    for solver in ("greedy", "batch"):
        a, new_state, _ = gang_assign(state, pods, cfg(), gangs,
                                      solver=solver)
        assert np.asarray(a)[:4].tolist() == [-1, -1, -1, -1], solver
        np.testing.assert_array_equal(
            np.asarray(new_state.node_requested),
            np.asarray(state.node_requested), err_msg=solver)


def test_gang_satisfied_with_batch_solver():
    state = mk_state([8_000] * 4)
    pods = mk_pods([2_000] * 3, [0, 0, 0], state)
    gangs = GangInfo.build(np.array([3]))
    a, _, _ = gang_assign(state, pods, cfg(), gangs, solver="batch")
    a = np.asarray(a)
    assert (a[:3] >= 0).all()


def test_gang_assign_rejects_unknown_solver():
    import pytest

    state = mk_state([8_000])
    pods = mk_pods([100], [0], state)
    with pytest.raises(ValueError, match="solver"):
        gang_assign(state, pods, cfg(), GangInfo.build(np.array([1])),
                    solver="annealing")
