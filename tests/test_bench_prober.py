"""ProbeArmer (koordinator_tpu/bench_prober.py): probe outcomes land in
metrics, the first success publishes immediately, and a hung probe
burns the bench_probe_hang SLO into an alert WITH a flight-record dump
— all deterministic (fake clocks, fake probes, no hardware, no sleeps).
"""

import pytest

from koordinator_tpu import metrics
from koordinator_tpu.bench_prober import ProbeArmer, probe_hang_spec
from koordinator_tpu.scheduler.flight_recorder import (
    FlightRecorder,
    RoundRecord,
)
from koordinator_tpu.slo_monitor import SloMonitor


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def make_record(n=1) -> RoundRecord:
    return RoundRecord(
        round=n, trace_id=f"t{n}", start_time=0.0, duration_s=0.01,
        solver="batch", solve_path="incremental", pods=1, placed=1,
        failed=0, suspended=0, degraded=False, staleness_s=0.0,
        dirty_node_frac=0.0, dirty_pod_frac=0.0, solve_wall_s=0.01,
        solve_device_s=0.005)


class TestProbeArmer:
    def _armer(self, probe_fn, clock=None, monitor_clock=None, **kw):
        clock = clock or FakeClock()
        monitor = SloMonitor(
            specs=[probe_hang_spec(objective=0.05, fast_window_s=600.0,
                                   fire_burn=4.0)],
            clock=monitor_clock or clock)
        armer = ProbeArmer(probe_fn, clock=clock, monitor=monitor, **kw)
        monitor.on_breach = armer._breach
        return armer, clock, monitor

    def test_success_publishes_once_immediately(self):
        published = []
        armer, clock, _ = self._armer(
            lambda: (True, "", ""), publish_fn=lambda: published.append(1))
        assert armer.tick() is True
        assert published == [1]          # the FIRST success publishes
        assert armer.tick() is True
        assert published == [1]          # ... exactly once
        assert metrics.bench_probe_window_open.value() == 1.0
        assert metrics.bench_probe_attempts.value(
            labels={"outcome": "ok"}) == 2.0

    def test_outcomes_and_durations_are_recorded(self):
        outcomes = iter([
            (False, "no_devices_enumerated", "empty"),
            (False, "probe_kernel_hung", "wedged"),
            (True, "", ""),
        ])
        armer, clock, _ = self._armer(lambda: next(outcomes))
        for _ in range(3):
            armer.tick()
            clock.t += 10.0
        assert metrics.bench_probe_attempts.value(
            labels={"outcome": "no_devices_enumerated"}) == 1.0
        assert metrics.bench_probe_attempts.value(
            labels={"outcome": "probe_kernel_hung"}) == 1.0
        assert metrics.bench_probe_attempts.value(
            labels={"outcome": "ok"}) == 1.0
        assert armer.attempts == 3 and armer.successes == 1
        # the success cleared the hung gauge
        assert metrics.bench_probe_hung.value() == 0.0

    def test_crashing_probe_is_an_outcome_not_a_crash(self):
        def boom():
            raise RuntimeError("backend exploded")

        armer, _, _ = self._armer(boom)
        assert armer.tick() is False
        assert metrics.bench_probe_attempts.value(
            labels={"outcome": "probe_error"}) == 1.0

    def test_hung_probe_fires_slo_breach_with_flight_dump(self):
        """The ROADMAP item 1 acceptance: a probe hung past its deadline
        is a burn-rate breach WITH a flight record, not a silent retry
        loop."""
        recorder = FlightRecorder(capacity=8)
        recorder.record(make_record())
        clock = FakeClock()

        def hung_probe():
            clock.t += 200.0             # each probe wedges for 200s
            return (False, "probe_kernel_hung", "kernel never returned")

        armer, clock, monitor = self._armer(
            hung_probe, clock=clock, deadline_s=180.0,
            flight_recorder=recorder)
        hang_events = []
        armer.on_hang = hang_events.append
        fired = False
        for _ in range(12):              # a run of hung probes
            armer.tick()
            clock.t += 60.0
            if metrics.slo_alerts_total.value(
                    labels={"slo": "bench_probe_hang",
                            "phase": "fire"}) >= 1.0:
                fired = True
                break
        assert fired, "hung probes never fired the bench_probe_hang SLO"
        assert metrics.bench_probe_hung.value() == 1.0
        # the breach dumped the flight record with the SLO named
        assert metrics.round_flight_dumps.value(
            labels={"reason": "slo:bench_probe_hang"}) >= 1.0
        assert recorder.dumps >= 1
        assert hang_events and hang_events[0]["name"] == "bench_probe_hang"

    def test_fast_failures_do_not_count_as_hangs(self):
        armer, clock, _ = self._armer(
            lambda: (False, "no_devices_enumerated", "refused"))
        armer.tick()
        assert metrics.bench_probe_hung.value() == 0.0

    def test_background_cadence_stops_cleanly(self):
        armer = ProbeArmer(lambda: (True, "", ""), interval_s=30.0)
        armer.start()
        armer.stop()
        assert armer._thread is None


class TestProbeHangSpec:
    def test_spec_targets_the_hung_gauge(self):
        spec = probe_hang_spec()
        assert spec.metric == "koord_scheduler_bench_probe_hung"
        assert spec.kind == "gauge"
        assert spec.threshold == pytest.approx(0.5)
