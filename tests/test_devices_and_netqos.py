"""Device collectors (gpu/rdma/xpu parity) and the resctrl/tc/terwayqos
runtime hooks — the r1-VERDICT koordlet matrix tail.

Reference anchors: pkg/koordlet/metricsadvisor/devices/{gpu,rdma,xpu},
pkg/koordlet/runtimehooks/hooks/{resctrl,tc,terwayqos}.
"""

import json
import os

import pytest

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.qos import QoSClass
from koordinator_tpu.features import KOORDLET_GATES, RUNTIMEHOOK_GATES
from koordinator_tpu.koordlet import metriccache as mc
from koordinator_tpu.koordlet.devices import (
    AcceleratorCollector,
    RdmaCollector,
    XpuCollector,
)
from koordinator_tpu.koordlet.metricsadvisor import _Deps
from koordinator_tpu.koordlet.runtimehooks.plugins import (
    TC_CLASSID_HIGH,
    TC_CLASSID_LOW,
    TC_CLASSID_MID,
    ResctrlHook,
    ResctrlUpdater,
    TCNetworkQoS,
    TerwayQoS,
    tc_setup_commands,
)
from koordinator_tpu.koordlet.runtimehooks.protocol import PodContext
from koordinator_tpu.koordlet.statesinformer import PodMeta, StatesInformer
from koordinator_tpu.koordlet.system import cgroup as cg
from koordinator_tpu.koordlet.system.config import make_test_config


@pytest.fixture
def cfg(tmp_path):
    return make_test_config(tmp_path)


def make_deps(cfg):
    return _Deps(StatesInformer(), mc.MetricCache(), cfg, lambda: 100.0)


def pod(qos=QoSClass.BE, annotations=None):
    return PodMeta(
        uid="pod-1", name="pod-1", namespace="default", qos_class=qos,
        kube_qos="besteffort" if qos.is_best_effort else "burstable",
        annotations=annotations or {},
    )


def run_hook(hook, p):
    ctx = PodContext(pod=p, cgroup_dir="kubepods/pod-1")
    hook(ctx)
    return ctx.response


def fake_accel_device(cfg, name="accel0", **fields):
    root = os.path.join(cfg.sys_root, "class", "accel", name)
    os.makedirs(root, exist_ok=True)
    defaults = dict(uuid=f"GPU-{name}", minor="0", type="gpu",
                    usage_pct="37.5", mem_used="1024", mem_total="8192",
                    numa_node="1", busid="0000:3b:00.0", health="1")
    defaults.update(fields)
    for fn, val in defaults.items():
        with open(os.path.join(root, fn), "w") as f:
            f.write(str(val))


class TestAcceleratorCollector:
    def _fake_device(self, cfg, name="accel0", **fields):
        fake_accel_device(cfg, name, **fields)

    def test_samples_and_device_infos(self, cfg):
        self._fake_device(cfg, "accel0", minor="0")
        self._fake_device(cfg, "accel1", minor="1", health="0",
                          usage_pct="80")
        deps = make_deps(cfg)
        col = AcceleratorCollector(deps)
        KOORDLET_GATES.set("Accelerators", True)
        try:
            assert col.enabled()
            col.collect()
        finally:
            KOORDLET_GATES.set("Accelerators", False)
        res = deps.cache.query(mc.ACCEL_CORE_USAGE,
                               {"minor": "0", "uuid": "GPU-accel0",
                                "type": "gpu"}, end=200.0)
        assert list(res.values) == [37.5]
        infos = col.device_infos()
        assert [d.uuid for d in infos] == ["GPU-accel0", "GPU-accel1"]
        assert infos[0].health and not infos[1].health
        assert infos[0].numa_node == 1
        assert infos[0].resources["gpu-memory"] == 8192

    def test_gate_and_missing_sysfs_disable(self, cfg):
        col = AcceleratorCollector(make_deps(cfg))
        KOORDLET_GATES.set("Accelerators", True)
        try:
            assert not col.enabled()      # no sysfs dir
        finally:
            KOORDLET_GATES.set("Accelerators", False)
        self._fake_device(cfg, "accel0")
        assert not col.enabled()          # gate off


class TestRdmaCollector:
    def test_inventory_with_port_state(self, cfg):
        base = os.path.join(cfg.sys_root, "class", "infiniband", "mlx5_0")
        os.makedirs(os.path.join(base, "ports", "1"), exist_ok=True)
        with open(os.path.join(base, "node_guid"), "w") as f:
            f.write("0c42:a103:0065:2b8a")
        with open(os.path.join(base, "ports", "1", "state"), "w") as f:
            f.write("4: ACTIVE")
        down = os.path.join(cfg.sys_root, "class", "infiniband", "mlx5_1")
        os.makedirs(os.path.join(down, "ports", "1"), exist_ok=True)
        with open(os.path.join(down, "ports", "1", "state"), "w") as f:
            f.write("1: DOWN")

        infos = RdmaCollector(make_deps(cfg)).device_infos()
        by_uuid = {d.uuid: d for d in infos}
        assert by_uuid["0c42:a103:0065:2b8a"].health
        assert not by_uuid["mlx5_1"].health
        assert all(d.type == "rdma" for d in infos)


class TestXpuCollector:
    def test_vendor_json_inventory(self, cfg):
        root = os.path.join(cfg.var_run_root, "xpu-device-infos")
        os.makedirs(root, exist_ok=True)
        with open(os.path.join(root, "dev0.json"), "w") as f:
            json.dump({"uuid": "XPU-0", "minor": 0, "healthy": True,
                       "vendor": "acme", "model": "x100",
                       "numaNode": 0, "busID": "0000:17:00.0",
                       "resources": {"xpu-core": 100,
                                     "xpu-memory": 65536}}, f)
        with open(os.path.join(root, "broken.json"), "w") as f:
            f.write("{not json")

        infos = XpuCollector(make_deps(cfg)).device_infos()
        assert len(infos) == 1            # broken file skipped, not fatal
        d = infos[0]
        assert d.uuid == "XPU-0" and d.labels["vendor"] == "acme"
        assert d.resources["xpu-memory"] == 65536


class TestResctrlHook:
    @pytest.fixture(autouse=True)
    def gate(self):
        RUNTIMEHOOK_GATES.set("Resctrl", True)
        yield
        RUNTIMEHOOK_GATES.set("Resctrl", False)

    def test_annotated_pod_gets_private_group(self):
        p = pod(qos=QoSClass.LS, annotations={
            ext.ANNOTATION_RESCTRL: json.dumps({"l3": 50, "mb": 40})})
        resp = run_hook(ResctrlHook(num_ways=20), p)
        assert resp.resctrl_group == "koord-pod-pod-1"
        # 50% of 20 ways = 10 low bits set
        assert resp.resctrl_schemata == f"L3:0={(1 << 10) - 1:x}\nMB:0=40\n"

    def test_unannotated_pod_joins_qos_group(self):
        assert run_hook(ResctrlHook(), pod(QoSClass.BE)).resctrl_group == "BE"
        assert run_hook(ResctrlHook(), pod(QoSClass.LSR)).resctrl_group == "LSR"
        assert run_hook(ResctrlHook(), pod(QoSClass.LS)).resctrl_group == "LS"

    def test_updater_programs_fake_resctrl_fs(self, cfg):
        p = pod(annotations={
            ext.ANNOTATION_RESCTRL: json.dumps({"l3": 100})})
        resp = run_hook(ResctrlHook(num_ways=4), p)
        updater = ResctrlUpdater(cfg)
        updater.apply(resp, pids=[1234])
        gdir = updater.fs.group_dir("koord-pod-pod-1")
        assert open(os.path.join(gdir, "schemata")).read() == "L3:0=f\n"
        assert "1234" in open(os.path.join(gdir, "tasks")).read()
        updater.remove_group("pod-1")
        assert not os.path.isdir(gdir)


class TestTCNetworkQoS:
    @pytest.fixture(autouse=True)
    def gate(self):
        RUNTIMEHOOK_GATES.set("TCNetworkQoS", True)
        yield
        RUNTIMEHOOK_GATES.set("TCNetworkQoS", False)

    def test_classid_per_tier(self):
        hook = TCNetworkQoS()
        key = cg.NET_CLS_CLASSID.name
        assert run_hook(hook, pod(QoSClass.BE)).cgroup_values[
            key] == str(TC_CLASSID_LOW)
        assert run_hook(hook, pod(QoSClass.LSR)).cgroup_values[
            key] == str(TC_CLASSID_HIGH)
        assert run_hook(hook, pod(QoSClass.LS)).cgroup_values[
            key] == str(TC_CLASSID_HIGH)
        assert run_hook(hook, pod(QoSClass.NONE)).cgroup_values[
            key] == str(TC_CLASSID_MID)

    def test_setup_commands_htb_plan(self):
        cmds = tc_setup_commands("eth0", 10_000)
        assert cmds[0][:4] == ["tc", "qdisc", "add", "dev"]
        assert "htb" in cmds[0]
        # guaranteed rates split the line rate, ceils borrow up to it
        assert "4000mbit" in cmds[1] and "10000mbit" in cmds[1]
        assert "3000mbit" in cmds[2] and "3000mbit" in cmds[3]

    def test_gate_off_is_noop(self):
        RUNTIMEHOOK_GATES.set("TCNetworkQoS", False)
        assert cg.NET_CLS_CLASSID.name not in run_hook(
            TCNetworkQoS(), pod(QoSClass.BE)).cgroup_values


class TestTerwayQoS:
    @pytest.fixture(autouse=True)
    def gate(self):
        RUNTIMEHOOK_GATES.set("TerwayQoS", True)
        yield
        RUNTIMEHOOK_GATES.set("TerwayQoS", False)

    def test_writes_and_removes_bandwidth_file(self, cfg):
        hook = TerwayQoS(cfg)
        p = pod(qos=QoSClass.BE, annotations={
            ext.ANNOTATION_NETWORK_QOS: json.dumps(
                {"ingressBps": 1_000_000, "egressBps": 2_000_000})})
        run_hook(hook, p)
        path = os.path.join(cfg.var_run_root, "terway-qos", "pod-1.json")
        data = json.load(open(path))
        assert data == {"podUID": "pod-1", "ingressBps": 1_000_000,
                        "egressBps": 2_000_000, "prio": 2}
        hook.remove("pod-1")
        assert not os.path.exists(path)

    def test_no_annotation_no_file(self, cfg):
        hook = TerwayQoS(cfg)
        run_hook(hook, pod(QoSClass.LS))
        assert not os.path.exists(os.path.join(
            cfg.var_run_root, "terway-qos", "pod-1.json"))


class TestDeviceInventoryBridge:
    def test_device_infos_to_inventory_round_trip(self):
        from koordinator_tpu.api import crds
        from koordinator_tpu.koordlet.devices import (
            device_infos_to_inventory,
        )
        from koordinator_tpu.scheduler.device_manager import DeviceManager

        infos = [
            crds.DeviceInfo(type="gpu", minor=0, health=True, numa_node=0,
                            resources={"gpu-core": 100,
                                       "gpu-memory": 81_920}),
            crds.DeviceInfo(type="gpu", minor=2, health=True, numa_node=1,
                            resources={"gpu-core": 100,
                                       "gpu-memory": 81_920}),
            crds.DeviceInfo(type="gpu", minor=1, health=False, numa_node=0,
                            resources={"gpu-core": 100,
                                       "gpu-memory": 81_920}),
            crds.DeviceInfo(type="rdma", minor=0,
                            resources={"rdma-core": 100}),
        ]
        inv = device_infos_to_inventory(infos)
        assert len(inv["gpu"]) == 3
        assert inv["gpu"][1] == {"core": 0, "memory": 0, "group": 0}  # sick
        assert inv["gpu"][2]["group"] == 1
        assert inv["rdma"][0]["core"] == 100

        mgr = DeviceManager()
        mgr.register_node_devices("gpu", "n0", inv["gpu"])
        # only the two healthy GPUs allocate
        assert mgr.allocate("gpu", "n0", "p", core=200) is not None
        assert mgr.allocate("gpu", "n0", "q", core=100) is None


class TestResctrlReconcile:
    def test_reconciler_applies_and_removes_resctrl(self, cfg):
        """The daemon path: annotated pod gets its ctrl group programmed at
        reconcile; the group is removed when the pod leaves the node."""
        from koordinator_tpu.koordlet.resourceexecutor import (
            ResourceUpdateExecutor,
        )
        from koordinator_tpu.koordlet.runtimehooks.hooks import HookRegistry
        from koordinator_tpu.koordlet.runtimehooks.plugins import (
            ResctrlUpdater,
            register_default_hooks,
        )
        from koordinator_tpu.koordlet.runtimehooks.reconciler import (
            Reconciler,
        )
        from koordinator_tpu.api import crds

        RUNTIMEHOOK_GATES.set("Resctrl", True)
        try:
            states = StatesInformer()
            registry = HookRegistry()
            register_default_hooks(registry, node_slo=lambda: crds.NodeSLO())
            updater = ResctrlUpdater(cfg)
            rec = Reconciler(states, registry,
                             ResourceUpdateExecutor(cfg=cfg), cfg,
                             resctrl_updater=updater)
            p = PodMeta(
                uid="rp-1", name="rp-1", namespace="default",
                qos_class=QoSClass.LS, kube_qos="burstable",
                pids=(4321,),
                annotations={ext.ANNOTATION_RESCTRL: json.dumps(
                    {"l3": 50, "mb": 30})})
            states.set_pods([p])
            rec.reconcile_once()
            gdir = updater.fs.group_dir("koord-pod-rp-1")
            assert os.path.isdir(gdir)
            assert "MB:0=30" in open(os.path.join(gdir, "schemata")).read()
            assert "4321" in open(os.path.join(gdir, "tasks")).read()
            # quiet pass: unchanged state rewrites nothing
            os.unlink(os.path.join(gdir, "schemata"))
            rec.reconcile_once()
            assert not os.path.exists(os.path.join(gdir, "schemata"))
            # a group left on disk from BEFORE a restart is cleaned too
            fresh = Reconciler(states, registry,
                               ResourceUpdateExecutor(cfg=cfg), cfg,
                               resctrl_updater=ResctrlUpdater(cfg))
            os.makedirs(updater.fs.group_dir("koord-pod-ghost"),
                        exist_ok=True)
            states.set_pods([])   # pod leaves the node
            fresh.reconcile_once()
            assert not os.path.isdir(gdir)
            assert not os.path.isdir(updater.fs.group_dir("koord-pod-ghost"))
        finally:
            RUNTIMEHOOK_GATES.set("Resctrl", False)


class TestKoordletDeviceReporting:
    def test_advisor_builds_device_cr(self, cfg):
        from koordinator_tpu.koordlet import metricsadvisor as ma
        from koordinator_tpu.koordlet.metriccache import MetricCache
        from koordinator_tpu.koordlet.statesinformer import StatesInformer

        # fake one accelerator + one rdma device on the node fs
        fake_accel_device(cfg, "accel0", uuid="GPU-0", mem_total="81920",
                          mem_used="0", usage_pct="0", numa_node="0")
        ib = os.path.join(cfg.sys_root, "class", "infiniband", "mlx5_0")
        os.makedirs(ib, exist_ok=True)

        advisor = ma.MetricsAdvisor(StatesInformer(), MetricCache(), cfg)
        KOORDLET_GATES.set("Accelerators", True)
        KOORDLET_GATES.set("RDMADevices", True)
        try:
            device = advisor.build_device("n0")
        finally:
            KOORDLET_GATES.set("Accelerators", False)
            KOORDLET_GATES.set("RDMADevices", False)
        types = sorted(d.type for d in device.devices)
        assert types == ["gpu", "rdma"]
        assert device.node_name == "n0"
        # feeds the scheduler inventory bridge end to end
        from koordinator_tpu.koordlet.devices import (
            device_infos_to_inventory,
        )

        inv = device_infos_to_inventory(list(device.devices))
        assert inv["gpu"][0]["memory"] == 81920

    def test_daemon_ticks_device_report_with_dedup(self, cfg):
        from koordinator_tpu.koordlet.daemon import Daemon

        fake_accel_device(cfg, "accel0", type="xpu", uuid="XPU-0",
                          minor="0")
        # vendor JSON drop claims the SAME (type, minor): first wins
        root = os.path.join(cfg.var_run_root, "xpu-device-infos")
        os.makedirs(root, exist_ok=True)
        with open(os.path.join(root, "dev0.json"), "w") as f:
            json.dump({"uuid": "XPU-DUPE", "minor": 0}, f)

        os.makedirs(cfg.proc_root, exist_ok=True)
        with open(cfg.proc_path("stat"), "w") as f:
            f.write("cpu  0 0 0 0 0 0 0 0 0 0\n")
        with open(cfg.proc_path("meminfo"), "w") as f:
            f.write("MemTotal: 1024 kB\nMemAvailable: 512 kB\nCached: 0\n")

        from koordinator_tpu.koordlet.statesinformer import NodeInfo

        reports = []
        t = [1000.0]
        daemon = Daemon(cfg=cfg, clock=lambda: t[0],
                        device_report_fn=reports.append,
                        device_report_interval_seconds=60.0)
        KOORDLET_GATES.set("Accelerators", True)
        try:
            daemon.tick()            # node unknown yet: no anonymous report
            assert reports == []
            daemon.states.set_node(NodeInfo(name="n0", allocatable={}))
            daemon.tick()            # ...and no extra-interval penalty
            assert len(reports) == 1
            xpus = [d for d in reports[0].devices if d.type == "xpu"]
            assert [d.uuid for d in xpus] == ["XPU-0"]  # dedup: sysfs wins
            daemon.tick()                 # within the interval: no re-report
            assert len(reports) == 1
            t[0] += 61.0
            daemon.tick()
            assert len(reports) == 2
        finally:
            KOORDLET_GATES.set("Accelerators", False)


class TestDevicePluginAdapter:
    """DevicePluginAdaption gate (device_plugin_adapter.go): translate the
    repo's device-allocated payload into vendor device-plugin dialects."""

    GiB_MiB = 1024  # 1 GiB in the MiB units device tensors use

    def _alloc(self, minors=(0,), core=100, memory=None):
        memory = self.GiB_MiB if memory is None else memory
        return {"gpu": [
            {"minor": m, "resources": {"core": core, "memory": memory}}
            for m in minors
        ]}

    def test_general_adapter_bind_timestamp_and_minors(self):
        from koordinator_tpu.scheduler.device_plugin_adapter import (
            ANNOTATION_BIND_TIMESTAMP,
            ANNOTATION_GPU_MINORS,
            adapt_for_device_plugin,
        )

        res = adapt_for_device_plugin(
            self._alloc(minors=(1, 3)), clock=lambda: 12.0)
        assert res.pod_annotations[ANNOTATION_BIND_TIMESTAMP] == str(
            int(12.0 * 1e9))
        assert res.pod_annotations[ANNOTATION_GPU_MINORS] == "1,3"
        assert not res.node_annotations

    def test_huawei_npu_dialects(self):
        from koordinator_tpu.scheduler.device_plugin_adapter import (
            ANNOTATION_HUAWEI_ASCEND_310P,
            ANNOTATION_HUAWEI_NPU_CORE,
            ANNOTATION_PREDICATE_TIME,
            adapt_for_device_plugin,
        )

        res = adapt_for_device_plugin(
            self._alloc(minors=(2,)), gpu_vendor="huawei")
        assert res.pod_annotations[ANNOTATION_HUAWEI_NPU_CORE] == "2"
        assert ANNOTATION_PREDICATE_TIME in res.pod_annotations
        # vNPU template
        alloc = self._alloc(minors=(2,))
        alloc["gpu"][0]["template"] = "vir04"
        res = adapt_for_device_plugin(alloc, gpu_vendor="huawei")
        assert res.pod_annotations[ANNOTATION_HUAWEI_NPU_CORE] == "2-vir04"
        # Ascend 310P model prefixes minors
        res = adapt_for_device_plugin(
            self._alloc(minors=(0, 1)), gpu_vendor="huawei",
            gpu_model="Ascend-310P3-300I-DUO")
        assert res.pod_annotations[ANNOTATION_HUAWEI_ASCEND_310P] == \
            "Ascend310P-0,Ascend310P-1"

    def test_cambricon_profile_and_node_lock(self):
        from koordinator_tpu.scheduler.device_plugin_adapter import (
            ANNOTATION_CAMBRICON_ASSIGNED,
            ANNOTATION_CAMBRICON_LOCK,
            ANNOTATION_CAMBRICON_PROFILE,
            AdaptError,
            adapt_for_device_plugin,
        )

        res = adapt_for_device_plugin(
            self._alloc(minors=(1,), core=50, memory=2 * self.GiB_MiB),
            gpu_vendor="cambricon", clock=lambda: 100.0)
        assert res.pod_annotations[ANNOTATION_CAMBRICON_ASSIGNED] == "false"
        # 2 GiB / 256 MiB = 8 vmemory units
        assert res.pod_annotations[ANNOTATION_CAMBRICON_PROFILE] == "1_50_8"
        assert ANNOTATION_CAMBRICON_LOCK in res.node_annotations
        # multi-device share is not expressible
        with pytest.raises(AdaptError, match="multiple gpu share"):
            adapt_for_device_plugin(
                self._alloc(minors=(0, 1)), gpu_vendor="cambricon")
        # a held, fresh node lock rejects the bind
        with pytest.raises(AdaptError, match="lock"):
            adapt_for_device_plugin(
                self._alloc(minors=(1,), memory=2 * self.GiB_MiB),
                gpu_vendor="cambricon", clock=lambda: 130.0,
                node_annotations=dict(res.node_annotations))
        # ...but a stale one (> 5 min) is overwritten
        res2 = adapt_for_device_plugin(
            self._alloc(minors=(1,), memory=2 * self.GiB_MiB),
            gpu_vendor="cambricon", clock=lambda: 100.0 + 301.0,
            node_annotations=dict(res.node_annotations))
        assert ANNOTATION_CAMBRICON_LOCK in res2.node_annotations

    def test_metax_json_and_units(self):
        from koordinator_tpu.scheduler.device_plugin_adapter import (
            ANNOTATION_HAMI_LOCK,
            ANNOTATION_METAX_ALLOCATED,
            adapt_for_device_plugin,
        )

        res = adapt_for_device_plugin(
            self._alloc(minors=(0,), core=25, memory=512),
            gpu_vendor="metax")
        data = json.loads(res.pod_annotations[ANNOTATION_METAX_ALLOCATED])
        assert data == [[{"uuid": "0", "compute": 25, "vRam": 512}]]
        assert ANNOTATION_HAMI_LOCK in res.node_annotations

    def test_scheduler_bind_path_behind_gate(self):
        import numpy as np

        from koordinator_tpu.features import SCHEDULER_GATES
        from koordinator_tpu.api.resources import ResourceDim
        from koordinator_tpu.scheduler.device_manager import DeviceManager
        from koordinator_tpu.scheduler.device_plugin_adapter import (
            ANNOTATION_GPU_MINORS,
            LABEL_GPU_VENDOR,
        )
        from tests.test_scheduler import mk_scheduler, node, pod

        dm = DeviceManager()
        dm.register_node_devices("gpu", "n1", [
            {"core": 100, "memory": 4 * self.GiB_MiB, "group": 0},
        ])
        n1 = node("n1", labels={LABEL_GPU_VENDOR: "huawei"})
        n1.allocatable[ResourceDim.GPU] = 800
        n1.allocatable[ResourceDim.GPU_MEMORY] = 8 * self.GiB_MiB
        sched, binds = mk_scheduler([n1], device_manager=dm)
        p = pod("g", cpu=1_000)
        p.requests[ResourceDim.GPU] = 100
        p.requests[ResourceDim.GPU_MEMORY] = self.GiB_MiB
        old = SCHEDULER_GATES.enabled("DevicePluginAdaption")
        try:
            SCHEDULER_GATES.set("DevicePluginAdaption", True)
            sched.enqueue(p)
            res = sched.schedule_round()
            assert res.assignments == {"g": "n1"}
            dp = sched.resource_status["g"]["device-plugin"]
            assert ANNOTATION_GPU_MINORS in dp["annotations"]
            assert "huawei.com/npu-core" in dp["annotations"]
        finally:
            SCHEDULER_GATES.set("DevicePluginAdaption", old)
