"""Forecast plane (ISSUE 15): predictor edge cases, the device-resident
plane, the predictive-admission solve entries (sharded twin included),
proactive rebalance, and the reactive-vs-predictive A/B.

The predictor edge cases are the ones the closed loop now DEPENDS on:
a cold-start pod contributing nonzero would shrink BE capacity for
workloads with no history; an empty bank producing NaN would poison
the admission reserve tensor; a percentile that loses monotonicity
across decay renormalization would let a stale peak outrank a fresh
one.
"""

import time

import numpy as np
import pytest

from koordinator_tpu.api.resources import (
    NUM_RESOURCE_DIMS,
    ResourceDim,
    resource_vector,
)
from koordinator_tpu.forecast import FORECAST_MODES, kernels
from koordinator_tpu.forecast.plane import ForecastPlane
from koordinator_tpu.prediction.histogram import (
    HistogramBank,
    add_samples,
    default_cpu_buckets,
    percentile,
)
from koordinator_tpu.prediction.predictor import pod_reclaimable
from koordinator_tpu.state.cluster_state import ClusterState, MAX_QUANTITY

R = NUM_RESOURCE_DIMS
CPU = ResourceDim.CPU
MEM = ResourceDim.MEMORY


# ---------------------------------------------------------------------------
# predictor edge cases the loop depends on
# ---------------------------------------------------------------------------


class TestPredictorEdges:
    def test_cold_start_pods_contribute_zero(self):
        """A pod younger than coldStartDuration contributes 0 to both
        reclaimable and unreclaimable (peak_predictor.go:154) — via the
        reclaimable mask AND via add_samples' sample mask."""
        import jax.numpy as jnp

        buckets = default_cpu_buckets()
        bank = HistogramBank.zeros(2, buckets, 300.0)
        uids = jnp.asarray([0, 1], jnp.int32)
        values = jnp.asarray([4000.0, 9000.0], jnp.float32)
        # pod 1 is cold-starting: its samples are masked out
        bank = add_samples(bank, buckets, uids, values, jnp.float32(0.0),
                           mask=jnp.asarray([True, False]))
        assert float(bank.total[1]) == 0.0
        reclaim_cpu, _ = pod_reclaimable(
            bank, bank, buckets, buckets,
            pod_request_cpu=jnp.asarray([8000.0, 8000.0]),
            pod_request_mem=jnp.asarray([1024.0, 1024.0]),
            reclaimable_mask=jnp.asarray([True, False]),
            node_allocatable_cpu=jnp.float32(16000.0),
            node_allocatable_mem=jnp.float32(65536.0),
        )
        # only pod 0's (request - peak) survives; the cold pod adds 0
        with_cold, _ = pod_reclaimable(
            bank, bank, buckets, buckets,
            pod_request_cpu=jnp.asarray([8000.0, 0.0]),
            pod_request_mem=jnp.asarray([1024.0, 0.0]),
            reclaimable_mask=jnp.asarray([True, False]),
            node_allocatable_cpu=jnp.float32(16000.0),
            node_allocatable_mem=jnp.float32(65536.0),
        )
        assert float(reclaim_cpu) == float(with_cold)

    def test_empty_bank_sentinel_never_nan(self):
        """An empty histogram answers 0 (the sentinel), and the whole
        predicted-peak tensor stays finite — a NaN here would poison
        the admission reserve and every percent kernel after it."""
        import jax.numpy as jnp

        buckets = default_cpu_buckets()
        bank = HistogramBank.zeros(4, buckets, 300.0)
        p = np.asarray(percentile(bank, buckets, 0.95))
        assert np.all(p == 0.0) and np.all(np.isfinite(p))
        out = np.asarray(kernels.predicted_peaks(
            bank.weights, bank.total, bank.weights, bank.total,
            jnp.float32(120.0), jnp.float32(1.0),
            cpu_buckets=buckets, mem_buckets=buckets))
        assert np.all(out == 0) and out.dtype == np.int32

    def test_percentile_monotone_across_decay_steps(self):
        """p50 <= p95 <= p98 holds at EVERY decay step — including the
        >= 32-half-life renormalization shift — always finite, and a
        fully-decayed bank (every sample below epsilon) falls back to
        the 0 sentinel instead of a NaN or a stale peak."""
        import jax.numpy as jnp

        buckets = default_cpu_buckets()
        bank = HistogramBank.zeros(1, buckets, 10.0)
        rng = np.random.default_rng(7)
        t = 0.0
        for step in range(6):
            values = rng.uniform(100.0, 12_000.0, 8).astype(np.float32)
            bank = add_samples(
                bank, buckets, jnp.zeros(8, jnp.int32),
                jnp.asarray(values), jnp.float32(t))
            p50 = float(percentile(bank, buckets, 0.50)[0])
            p95 = float(percentile(bank, buckets, 0.95)[0])
            p98 = float(percentile(bank, buckets, 0.98)[0])
            assert p50 <= p95 <= p98, (step, p50, p95, p98)
            assert np.isfinite([p50, p95, p98]).all()
            # fresh samples dominate the decayed tail: the p98 answer
            # stays within the current window's value range (a stale
            # undecayed peak would exceed it)
            assert p98 <= float(values.max()) * 1.2
            t += 500.0   # 50 half-lives: every step renormalizes
        # decay-only aging far past every half-life: the whole bank
        # drops below epsilon and the sentinel takes over — never NaN
        bank = add_samples(
            bank, buckets, jnp.zeros(1, jnp.int32),
            jnp.zeros(1, jnp.float32), jnp.float32(t + 10_000.0),
            mask=jnp.asarray([False]))
        aged = float(percentile(bank, buckets, 0.95)[0])
        assert aged == 0.0 and np.isfinite(aged)


# ---------------------------------------------------------------------------
# the plane
# ---------------------------------------------------------------------------


def _fed_plane(capacity=8, hot_row=0, hot_cpu=14_000, valid_rows=4,
               **kw) -> ForecastPlane:
    plane = ForecastPlane(capacity, refresh_interval_s=3600.0, **kw)
    usage = np.zeros((capacity, R), np.int32)
    valid = np.zeros(capacity, bool)
    valid[:valid_rows] = True
    t0 = time.time()
    for t in range(12):
        usage[hot_row, CPU] = hot_cpu
        usage[hot_row, MEM] = 1000
        plane.observe(usage, valid, now=t0 + 30.0 * t)
    plane.refresh(now=t0 + 400.0)
    return plane


class TestForecastPlane:
    def test_observe_refresh_predicts_peak(self):
        plane = _fed_plane()
        assert plane.ready
        peaks = plane.predicted_host()
        # p95 of a constant 14k series, 10% safety margin, one bucket up
        assert 14_000 <= peaks[0, CPU] <= 18_000
        # rows 1-3 observed ZERO usage: their peak is the first bucket
        # bound (~25 mcores with margin), not the hot node's
        assert 0 <= peaks[1, CPU] <= 100
        assert peaks[4, CPU] == 0          # never observed -> sentinel 0
        assert np.all(peaks >= 0)

    def test_error_stats_after_second_refresh(self):
        plane = _fed_plane()
        usage = np.zeros((8, R), np.int32)
        usage[0, CPU] = 14_000
        valid = np.zeros(8, bool)
        valid[:4] = True
        plane.observe(usage, valid, now=time.time() + 500.0)
        plane.refresh(now=time.time() + 600.0)
        # realized 14k vs predicted ~15.4k: a small, finite fraction
        assert 0.0 < plane.error_fraction["cpu"] < 1.0

    def test_horizon_stretches_with_trend_slope(self):
        plane = ForecastPlane(4, base_horizon_s=100.0,
                              max_horizon_scale=4.0, horizon_gain=2.0)
        assert plane.horizon_for(None) == 100.0
        assert plane.horizon_for(-3.0) == 100.0       # falling: base
        assert plane.horizon_for(0.5) == 200.0
        assert plane.horizon_for(50.0) == 400.0       # clamped at 4x

    def test_auto_growth_stretches_horizon_without_external_wiring(self):
        """refresh() with no growth argument derives the trend slope
        from the plane's OWN realized window (trend.fit_slope), so the
        documented horizon stretch works in the production path where
        nothing wires an external signal."""
        plane = ForecastPlane(4, base_horizon_s=100.0,
                              refresh_interval_s=0.0, horizon_gain=1.0)
        usage = np.zeros((4, R), np.int32)
        valid = np.ones(4, bool)
        t0 = time.time()
        level = 1_000
        for window in range(4):
            for t in range(3):
                usage[:, CPU] = level
                plane.observe(usage, valid,
                              now=t0 + window * 60.0 + t * 20.0)
            plane.refresh(now=t0 + window * 60.0 + 40.0)
            level *= 4          # realized mean quadruples per minute
        assert plane.growth_per_hour > 1.0
        assert plane.horizon_s > 100.0

    def test_observe_pads_smaller_snapshots(self):
        """A plane sized AHEAD of its snapshot pads the sample instead
        of crashing the jitted observe (the constructor takes any
        capacity; attach only grows planes, never shrinks them)."""
        plane = ForecastPlane(16, refresh_interval_s=3600.0)
        usage = np.zeros((8, R), np.int32)
        usage[0, CPU] = 5_000
        plane.observe(usage, np.ones(8, bool), now=time.time())
        plane.refresh()
        peaks = plane.predicted_host()
        assert peaks.shape == (16, R)
        assert peaks[0, CPU] > 0 and np.all(peaks[8:] == 0)

    def test_grow_preserves_history(self):
        plane = _fed_plane(capacity=8)
        before = plane.predicted_host()[0, CPU]
        plane.grow(16)
        assert plane.capacity == 16
        plane.refresh(now=time.time() + 500.0)
        assert plane.predicted_host().shape == (16, R)
        assert plane.predicted_host()[0, CPU] >= before * 0.5

    def test_admission_reserve_masks_invalid_and_clamps(self):
        plane = _fed_plane()
        alloc = np.full((8, R), 16_000, np.int32)
        usage = np.zeros((8, R), np.int32)
        usage[0, CPU] = 6_000
        state = ClusterState.from_arrays(alloc[:4], usage=usage[:4],
                                         capacity=8)
        reserve = np.asarray(plane.admission_reserve(state))
        # forecast growth = predicted - observed, never negative
        peaks = plane.predicted_host()
        assert reserve[0, CPU] == max(int(peaks[0, CPU]) - 6_000, 0)
        assert np.all(reserve[4:] == 0)    # invalid rows reserve nothing
        assert np.all(reserve <= MAX_QUANTITY)
        # capacity mismatch -> None (wait for the next observe to grow)
        small = ClusterState.zeros(4)
        assert plane.admission_reserve(small) is None

    def test_sharded_percentile_bit_identical(self):
        """The shard_map percentile twin, pinned like the cluster
        state, answers bit-identically to the single-device kernel at
        mesh width (the per-row math has no cross-shard term)."""
        import jax
        import jax.numpy as jnp

        from koordinator_tpu.parallel import mesh as pmesh

        mesh = pmesh.solver_mesh(jax.devices())
        plane = _fed_plane(capacity=64, valid_rows=64, mesh=mesh)
        ref = np.asarray(plane._peaks_fn(
            plane.cpu_bank.weights, plane.cpu_bank.total,
            plane.mem_bank.weights, plane.mem_bank.total,
            jnp.float32(plane.horizon_s), jnp.float32(0.0)))
        sh = np.asarray(plane._peaks_fn_sh(
            plane.cpu_bank.weights, plane.cpu_bank.total,
            plane.mem_bank.weights, plane.mem_bank.total,
            jnp.float32(plane.horizon_s), jnp.float32(0.0)))
        np.testing.assert_array_equal(ref, sh)


# ---------------------------------------------------------------------------
# the solve entries
# ---------------------------------------------------------------------------


class TestForecastSolveEntries:
    def test_zero_reserve_bit_identical_to_plain_solve(self):
        """forecast_gang_assign with an all-zero reserve IS
        gang_assign: assignments, accounting and quota unchanged."""
        import jax.numpy as jnp

        from koordinator_tpu.ops.assignment import ScoringConfig
        from koordinator_tpu.ops.gang import GangInfo, gang_assign

        from tests.test_mesh import build_problem

        state, pods = build_problem(n_nodes=64, n_pods=16)
        cfg = ScoringConfig.default()
        gangs = GangInfo.build(np.asarray([], np.int32))
        a_ref, st_ref, _ = gang_assign(state, pods, cfg, gangs, None)
        zero = jnp.zeros((64, R), jnp.int32)
        a, st, _ = kernels.forecast_gang_assign(
            state, zero, pods, cfg, gangs, None)
        np.testing.assert_array_equal(np.asarray(a_ref), np.asarray(a))
        np.testing.assert_array_equal(np.asarray(st_ref.node_requested),
                                      np.asarray(st.node_requested))

    def test_reserve_blocks_forecast_hot_nodes(self):
        """A reserve that fills a node's remaining capacity excludes it
        from this round's placements, and the RETURNED state carries no
        trace of the charge (release happened inside the program)."""
        import jax.numpy as jnp

        from koordinator_tpu.ops.assignment import ScoringConfig
        from koordinator_tpu.ops.gang import GangInfo, gang_assign

        from tests.test_mesh import build_problem

        state, pods = build_problem(n_nodes=8, n_pods=4)
        cfg = ScoringConfig.default()
        gangs = GangInfo.build(np.asarray([], np.int32))
        free = np.asarray(state.free)
        reserve = np.zeros((8, R), np.int32)
        reserve[0] = free[0]                   # node 0 forecast-full
        a, st, _ = kernels.forecast_gang_assign(
            state, jnp.asarray(reserve), pods, cfg, gangs, None)
        a = np.asarray(a)
        assert not np.any(a[: 4] == 0), "forecast-full node 0 was used"
        # release proof: requested == original + placed requests only
        a_ref, st_ref, _ = gang_assign(state, pods, cfg, gangs, None)
        placed = np.asarray(pods.requests)[:4][a[:4] >= 0]
        expect = np.asarray(state.node_requested).copy()
        for row, req in zip(a[:4][a[:4] >= 0], placed):
            expect[row] += req
        np.testing.assert_array_equal(np.asarray(st.node_requested),
                                      expect)

    def test_sharded_forecast_entry_bit_identical_on_2d_mesh(self):
        """The sharded twin matches the single-device forecast entry on
        a 2-D (pods x nodes) mesh — the acceptance bar's parity clause
        for forecast rounds."""
        import jax
        import jax.numpy as jnp

        from koordinator_tpu.ops.assignment import ScoringConfig
        from koordinator_tpu.ops.gang import GangInfo
        from koordinator_tpu.parallel import mesh as pmesh
        from koordinator_tpu.parallel import sharded as ps

        from tests.test_mesh import build_problem

        state, pods = build_problem(n_nodes=64, n_pods=32)
        cfg = ScoringConfig.default()
        gangs = GangInfo.build(np.asarray([], np.int32))
        rng = np.random.default_rng(5)
        reserve = np.zeros((64, R), np.int32)
        reserve[:, CPU] = rng.integers(0, 8_000, 64)
        reserve = jnp.asarray(reserve)
        a_ref, st_ref, _ = kernels.forecast_gang_assign(
            state, reserve, pods, cfg, gangs, None, solver="batch")
        mesh = pmesh.solver_mesh(jax.devices(), pods_axis=2)
        a_sh, st_sh, _ = ps.sharded_forecast_gang_assign(
            mesh, state, reserve, pods, cfg, gangs, None, solver="batch")
        np.testing.assert_array_equal(np.asarray(a_ref), np.asarray(a_sh))
        np.testing.assert_array_equal(np.asarray(st_ref.node_requested),
                                      np.asarray(st_sh.node_requested))


# ---------------------------------------------------------------------------
# scheduler integration
# ---------------------------------------------------------------------------


def _scheduler(mode="off", quota=False):
    from koordinator_tpu.quota.tree import UNBOUNDED, QuotaTree
    from koordinator_tpu.scheduler import ClusterSnapshot, Scheduler
    from koordinator_tpu.scheduler.snapshot import NodeSpec

    tree = None
    if quota:
        total = np.zeros(R, np.int64)
        total[CPU] = 64_000
        tree = QuotaTree(total)
        mx = np.full(R, UNBOUNDED, np.int64)
        mx[CPU] = 20_000
        tree.add("q", min=np.zeros(R, np.int64), max=mx)
    snap = ClusterSnapshot(capacity=8)
    for i in range(4):
        snap.upsert_node(NodeSpec(
            name=f"n{i}",
            allocatable=resource_vector(cpu=16_000, memory=65_536)))
    return Scheduler(snap, forecast_mode=mode, mesh=None, quota_tree=tree)


def _enqueue(s, n=6, cpu=4_000):
    from koordinator_tpu.scheduler.snapshot import PodSpec

    for j in range(n):
        s.enqueue(PodSpec(name=f"p{j}",
                          requests=resource_vector(cpu=cpu, memory=8_192),
                          priority=10, quota="q" if s.quota_tree else None))


class TestSchedulerForecastMode:
    def test_modes(self):
        assert FORECAST_MODES == ("off", "admit", "full")
        with pytest.raises(ValueError, match="unknown forecast_mode"):
            _scheduler(mode="bogus")

    def test_off_and_inert_and_zero_reserve_identical(self):
        """Acceptance: forecast_mode=off is bit-identical — and so are
        an admit scheduler with no plane, and an admit scheduler whose
        plane predicts nothing (the zero reserve charges through the
        forecast ENTRY and still changes no decision or quota charge).
        """
        outcomes = {}
        for tag in ("off", "admit-noplane", "admit-zeroplane"):
            s = _scheduler(mode=("off" if tag == "off" else "admit"),
                           quota=True)
            if tag == "admit-zeroplane":
                plane = ForecastPlane(8, refresh_interval_s=3600.0)
                plane.observe(np.zeros((8, R), np.int32),
                              np.ones(8, bool))
                plane.refresh()
                s.attach_forecast_plane(plane)
            _enqueue(s)
            r = s.schedule_round()
            outcomes[tag] = (
                dict(sorted(r.assignments.items())),
                sorted(r.failures),
                np.asarray(s.quota_tree.nodes["q"].used).tolist(),
            )
        assert outcomes["off"] == outcomes["admit-noplane"]
        assert outcomes["off"] == outcomes["admit-zeroplane"]

    def test_admission_steers_off_forecast_hot_node(self):
        from koordinator_tpu import metrics

        s = _scheduler(mode="admit")
        plane = _fed_plane()
        s.attach_forecast_plane(plane)
        _enqueue(s)
        r = s.schedule_round()
        assert "n0" not in r.assignments.values()
        assert len(r.assignments) == 6     # capacity elsewhere suffices
        assert metrics.forecast_admission_reserved_fraction.value() > 0

    def test_plane_survives_the_donating_solve(self):
        """The plane must never retain the snapshot's own buffers: the
        round's solve DONATES the state the prelude observed, and a
        held reference would leave refresh()/report() reading a
        deleted array (the e2e gateway drive caught exactly this)."""
        s = _scheduler(mode="admit")
        plane = _fed_plane()
        s.attach_forecast_plane(plane)
        _enqueue(s)
        s.schedule_round()          # prelude observes, solve donates
        plane.refresh()             # reads _valid: must be a live copy
        body = plane.report(max_nodes=4)
        assert body["ready"] and body["nodes"]

    def test_full_queue_fails_with_capacity_reason_when_reserved(self):
        """When the reserve makes demand exceed remaining capacity the
        overflow pods fail with a real capacity diagnosis, not a
        crash."""
        s = _scheduler(mode="admit")
        s.attach_forecast_plane(_fed_plane())
        _enqueue(s, n=14, cpu=4_000)   # 56k asks vs 3x16k unreserved
        r = s.schedule_round()
        assert r.failures and "n0" not in r.assignments.values()

    def test_debug_forecast_surface(self):
        from koordinator_tpu.scheduler.services import DebugService

        s = _scheduler(mode="admit")
        svc = DebugService(s)
        status, body = svc.handle("/debug/forecast")
        assert status == 501 and "forecast" in body["error"]
        s.attach_forecast_plane(_fed_plane())
        status, body = svc.handle("/debug/forecast", {"nodes": "2"})
        assert status == 200
        assert body["mode"] == "admit" and body["ready"]
        assert len(body["nodes"]) <= 2
        assert body["nodes"][0]["node"] == "n0"     # hottest first
        assert "admission_reserved_fraction" in body
        status, body = svc.handle("/debug/forecast", {"nodes": "x"})
        assert status == 400

    def test_tenant_labels_stamp_the_plane(self):
        """attach stamps the scheduler's tenant onto the plane's gauge
        labels — per-tenant planes must not overwrite each other's
        forecast telemetry."""
        from koordinator_tpu import metrics
        from koordinator_tpu.scheduler import ClusterSnapshot, Scheduler

        s = Scheduler(ClusterSnapshot(capacity=8), forecast_mode="admit",
                      mesh=None, tenant="t7")
        plane = _fed_plane()
        s.attach_forecast_plane(plane)
        assert plane.metric_labels == {"tenant": "t7"}
        plane.refresh()
        assert metrics.forecast_horizon_seconds.value(
            labels={"tenant": "t7"}) > 0


# ---------------------------------------------------------------------------
# predictive colocation
# ---------------------------------------------------------------------------


class TestPredictiveColocation:
    def test_batch_allocatable_shrinks_before_the_ramp(self):
        """With the forecast seam attached, the colocation loop's very
        next node_allocatable push advertises batch capacity computed
        from the PREDICTED peak — before observed usage moves at all;
        without it the push is byte-identical to the reactive loop."""
        from koordinator_tpu.forecast.colocation import PredictiveColocation
        from koordinator_tpu.manager.colocation_loop import (
            ColocationLoop,
            ManagerSyncBinding,
        )
        from koordinator_tpu.manager.noderesource_controller import (
            NodeResourceController,
        )
        from koordinator_tpu.transport import StateSyncService

        clock = lambda: 1000.0  # noqa: E731

        def build(forecast):
            service = StateSyncService()
            binding = ManagerSyncBinding(clock=clock)
            service.attach_binding(binding)
            service.upsert_node("n0",
                                resource_vector(cpu=16_000, memory=16_384))
            service.update_node_usage(
                "n0", resource_vector(cpu=2_000, memory=2_048),
                hp_usage=resource_vector(cpu=2_000, memory=2_048))
            pushes = []
            loop = ColocationLoop(
                NodeResourceController(clock=clock), binding,
                lambda name, alloc: pushes.append(np.asarray(alloc).copy()),
                forecast=forecast)
            loop.tick()
            return pushes

        plane = _fed_plane(hot_cpu=12_000)   # predicted ~13.2k vs 2k seen
        rows = {"n0": 0}
        predictive = build(PredictiveColocation(plane, rows.get))
        reactive = build(None)
        assert len(predictive) == 1 and len(reactive) == 1
        batch_cpu = ResourceDim.BATCH_CPU
        # reactive: cap - 40% margin - 2k observed = 7.6k; predictive
        # subtracts the ~13.2k predicted peak instead
        assert reactive[0][batch_cpu] > 7_000
        assert predictive[0][batch_cpu] < reactive[0][batch_cpu] - 5_000
        # prod dims ride through untouched in both
        assert predictive[0][CPU] == reactive[0][CPU] == 16_000


# ---------------------------------------------------------------------------
# proactive rebalance
# ---------------------------------------------------------------------------


def _rebalance_fixture(hot_cpu=14_000, under_rows=True):
    import jax.numpy as jnp

    from koordinator_tpu.descheduler.lownodeload import LowNodeLoadArgs
    from koordinator_tpu.descheduler.migration import (
        ArbitrationLimits,
        MigrationController,
    )
    from koordinator_tpu.forecast.rebalance import ProactiveRebalancer

    plane = _fed_plane(hot_cpu=hot_cpu)
    pods = ["be-0", "be-1"]
    universe = (
        pods,
        np.asarray([0, 0], np.int32),
        np.asarray([[0] * R] * 2, np.int32),
        np.zeros(2, np.int32),
        np.ones(2, bool),
    )
    universe[2][:, CPU] = 1_000
    reserved, evicted = [], []
    controller = MigrationController(
        limits=ArbitrationLimits(max_migrating_per_node=4),
        reserve_fn=lambda job: reserved.append(job.pod) or f"rsv-{job.pod}",
        evict_fn=lambda job: evicted.append(job.pod) or True)
    args = LowNodeLoadArgs.default()
    args = args.replace(anomaly_rounds=jnp.int32(2))
    reb = ProactiveRebalancer(
        plane, controller, pods_fn=lambda: universe,
        node_name_fn=lambda row: f"n{row}", args=args)
    usage = np.zeros((8, R), np.int32)
    usage[0, CPU] = 2_000 + 6_000   # observed: calm — forecast: hot
    if not under_rows:
        usage[:4, CPU] = 12_000     # nowhere to move anything
    capacity = np.zeros((8, R), np.int32)
    capacity[:4, CPU] = 16_000
    capacity[:4, MEM] = 65_536
    valid = np.zeros(8, bool)
    valid[:4] = True
    return reb, controller, usage, capacity, valid, reserved, evicted


class TestProactiveRebalance:
    def test_prestages_reservation_first_moves(self):
        from koordinator_tpu import metrics
        from koordinator_tpu.descheduler.migration import MigrationJobPhase

        reb, controller, usage, capacity, valid, reserved, evicted = (
            _rebalance_fixture())
        assert reb.tick(usage, capacity, valid) == []   # anomaly round 1
        moves = reb.tick(usage, capacity, valid)        # round 2: stage
        assert moves and all(m.node == "n0" for m in moves)
        assert all(m.dest != "n0" for m in moves)
        assert sum(v for _, v in
                   metrics.forecast_evictions_prestaged.items()) == len(
                       moves)
        controller.reconcile()
        # reservation-first: capacity reserved BEFORE the eviction ran
        assert reserved and evicted
        for move in moves:
            assert move.job.phase is MigrationJobPhase.SUCCEEDED
            assert move.job.reservation == f"rsv-{move.pod}"
        # a released pod may stage again; an unreleased one must not
        reb.release(moves[0].pod)
        assert moves[0].pod not in reb._staged

    def test_cost_gate_blocks_without_destinations(self):
        reb, controller, usage, capacity, valid, reserved, _ = (
            _rebalance_fixture(under_rows=False))
        reb.tick(usage, capacity, valid)
        moves = reb.tick(usage, capacity, valid)
        assert moves == [] and not reserved

    def test_migration_cost_gate_sequential_feedback(self):
        """Two pods cannot both claim the last slot: the second
        candidate sees the first's charge."""
        import jax.numpy as jnp

        usage = np.zeros((2, R), np.int32)
        usage[0, CPU] = 9_000          # under node with ~1.4k of room
        capacity = np.full((2, R), 16_000, np.int32)
        high = np.full(R, -1, np.int32)
        high[CPU] = 65                 # high_quant = 10_400
        pods = np.zeros((2, R), np.int32)
        pods[:, CPU] = 1_000
        under = np.asarray([True, False])
        gate, dest = kernels.migration_cost_gate(
            jnp.asarray(pods), jnp.asarray(usage), jnp.asarray(capacity),
            jnp.asarray(under), jnp.asarray(high))
        gate, dest = np.asarray(gate), np.asarray(dest)
        assert gate[0] and dest[0] == 0
        assert not gate[1] and dest[1] == -1


# ---------------------------------------------------------------------------
# the A/B proof
# ---------------------------------------------------------------------------


AB_SMOKE = dict(seed=0, nodes=8, periods=2, period_s=360.0, tick_s=24.0,
                half_life_s=180.0, refresh_interval_s=24.0)


class TestForecastAB:
    def test_trace_deterministic(self):
        from koordinator_tpu.forecast.ab import ABConfig, generate_ls_trace

        cfg = ABConfig(**AB_SMOKE)
        t1, t2 = generate_ls_trace(cfg), generate_ls_trace(cfg)
        np.testing.assert_array_equal(t1, t2)
        # flat half really is flat, spiky half really swings
        spread = t1.max(axis=0) - t1.min(axis=0)
        assert spread[:4].max() < spread[4:].min()

    def test_predictive_arm_wins_the_ab(self):
        """The acceptance clause: under one seeded diurnal trace the
        predictive arm shows fewer SLO-breach minutes AND fewer
        reactive evictions, with the proactive path exercised."""
        from koordinator_tpu.forecast.ab import ABConfig, run_ab

        doc = run_ab(ABConfig(**AB_SMOKE))
        r, p = doc["reactive"], doc["predictive"]
        assert doc["predictive_no_worse"]
        assert doc["predictive_strictly_better"], (r, p)
        assert p["prestaged_migrations"] > 0
        assert p["migrations_completed"] > 0
        assert 0.0 < p["forecast_error_fraction"]["cpu"] < 1.0
        # the win is not "BE never ran": the predictive arm keeps a
        # substantial share of the reactive arm's BE occupancy
        assert p["be_pod_ticks"] > r["be_pod_ticks"] * 0.5
