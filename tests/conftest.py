"""Test env: force an 8-device virtual CPU platform before JAX initializes.

Multi-chip sharding logic is tested on this virtual mesh (the real TPU tunnel
exposes a single chip); the driver's dryrun_multichip does the same.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# Force the CPU platform (the ambient sitecustomize pins the TPU tunnel
# backend via jax.config, so the env var alone is not enough); set
# KOORD_TEST_TPU=1 to run the suite against real hardware instead.
if not os.environ.get("KOORD_TEST_TPU"):
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

# Build the native shim once up front so collector tests exercise the C path
# (lazy loading would otherwise race the background build).
from koordinator_tpu import native as _native  # noqa: E402

_native.ensure_built()
