"""Test env: force an 8-device virtual CPU platform before JAX initializes.

Multi-chip sharding logic is tested on this virtual mesh (the real TPU tunnel
exposes a single chip); the driver's dryrun_multichip does the same.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# Force the CPU platform (the ambient sitecustomize pins the TPU tunnel
# backend via jax.config, so the env var alone is not enough); set
# KOORD_TEST_TPU=1 to run the suite against real hardware instead.
if not os.environ.get("KOORD_TEST_TPU"):
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

# Build the native shim once up front so collector tests exercise the C path
# (lazy loading would otherwise race the background build).
from koordinator_tpu import native as _native  # noqa: E402

_native.ensure_built()

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _isolate_metrics():
    """Zero every metric registry after each test (values only — the
    module-level instrument handles stay registered), so counters stop
    bleeding across tests within one pytest process.  Tests that want
    deltas mid-test still see them; tests that assert absolute values
    start from a clean slate."""
    yield
    from koordinator_tpu import metrics, timeline

    metrics.reset_all_for_tests()
    # the timeline recorder is process-wide like the registries: drop
    # recorded segments/cycles so one test's rounds can't attribute
    # into another's window
    timeline.RECORDER.reset_for_tests()


def prop_seeds(default_n: int) -> list[int]:
    """Seed list for the randomized property suites.

    CI runs the fixed ``range(default_n)``; the soak harness
    (tools/soak.sh) sweeps FRESH seeds by setting
    ``KOORD_PROP_SEED_BASE`` (window start) and ``KOORD_PROP_SEED_COUNT``
    (window size, 0 = each suite's default count).  Every suite keeps its
    own default so CI cost stays where it was tuned, while one env knob
    re-aims all of them at an arbitrary seed window."""
    base = int(os.environ.get("KOORD_PROP_SEED_BASE", "0"))
    count = int(os.environ.get("KOORD_PROP_SEED_COUNT", "0") or 0)
    return list(range(base, base + (count or default_n)))
