"""Cross-process HA, end to end: two real scheduler PROCESSES sync state
over the wire protocol, contend one lease, and the survivor keeps
scheduling after the leader is SIGKILLed.

This is the deployment story the reference runs on the apiserver
(leader-elected koord-scheduler replicas, informer-fed, Lease locks): here
the state server (deltasync) plays the apiserver, lease frames carry the
lock, and rounds are leader-gated inside each Scheduler.  Binds surface
through each process's status file; the test plays the apiserver's part of
the bind wash by removing bound pods from the shared state so both
replicas converge.
"""

import textwrap
import time

from koordinator_tpu.api.resources import resource_vector
from koordinator_tpu.ha import LeaseService
from koordinator_tpu.transport.channel import RpcServer
from koordinator_tpu.transport.deltasync import StateSyncService

from tests.proc_helpers import kill_all, spawn_replicas, wait_for

#: long enough that no post-warmup pause (GC, loaded CI core) outlives the
#: lease and flips leadership mid-test; failover after SIGKILL waits this out
LEASE_SECONDS = 20.0

REPLICA = textwrap.dedent("""
    import sys, time
    import jax
    jax.config.update("jax_platforms", "cpu")
    sock, ident, status, lease_s = (
        sys.argv[1], sys.argv[2], sys.argv[3], float(sys.argv[4]))

    from koordinator_tpu.api.resources import resource_vector
    from koordinator_tpu.ha import LeaderElector, RemoteLeaseStore
    from koordinator_tpu.scheduler.scheduler import Scheduler
    from koordinator_tpu.scheduler.snapshot import (
        ClusterSnapshot,
        NodeSpec,
        PodSpec,
    )
    from koordinator_tpu.transport.channel import RpcClient
    from koordinator_tpu.transport.deltasync import (
        SchedulerBinding,
        StateSyncClient,
    )

    ready = [False]

    def bind_fn(pod, node):
        if not ready[0]:
            return               # warmup binds stay private
        with open(status, "a") as f:
            f.write(f"BIND {pod} {node}\\n")

    snap = ClusterSnapshot(capacity=16)
    sched = Scheduler(snap, bind_fn=bind_fn)
    # WARMUP before contending the lease: the first round jit-compiles the
    # solve; on a loaded single-core CI box that pause can exceed the
    # lease and flip leadership mid-test.  The wire bootstrap below resets
    # all scheduler state, washing the dummy binds away.
    for i in range(2):
        snap.upsert_node(NodeSpec(
            name=f"warm-n{i}",
            allocatable=resource_vector(cpu=16_000, memory=65_536)))
    for i in range(3):
        sched.enqueue(PodSpec(name=f"warm-p{i}",
                              requests=resource_vector(cpu=1_000,
                                                       memory=1_024)))
    sched.schedule_round()

    sync = StateSyncClient(SchedulerBinding(sched))
    client = RpcClient(sock, on_push=sync.on_push)
    client.connect()
    sync.bootstrap(client)
    # wall clock: contenders in different processes share a clock domain
    sched.elector = LeaderElector(
        RemoteLeaseStore(client), "koord-scheduler", ident,
        lease_duration=lease_s, clock=time.time)
    ready[0] = True
    with open(status, "a") as f:
        f.write("READY\\n")
    while True:
        try:
            sched.schedule_round()   # leader-gated internally
        except Exception as e:
            with open(status, "a") as f:
                f.write(f"ERROR {e!r}\\n")
        time.sleep(0.1)
""")


def _binds(path):
    out = []
    for line in path.read_text().splitlines():
        if line.startswith("BIND "):
            _, pod, node = line.split()
            out.append((pod, node))
    return out


def test_two_scheduler_processes_failover_and_keep_scheduling(tmp_path):
    sock = str(tmp_path / "state.sock")
    server = RpcServer(sock)
    service = StateSyncService()
    service.attach(server)
    LeaseService().attach(server)
    server.start()

    script = tmp_path / "replica.py"
    script.write_text(REPLICA)
    status = {i: tmp_path / f"status-{i}" for i in ("a", "b")}
    for f in status.values():
        f.write_text("")

    for i in range(2):
        service.upsert_node(
            f"n{i}", resource_vector(cpu=16_000, memory=65_536))

    procs, errs = spawn_replicas(
        script,
        {i: [sock, i, str(status[i]), str(LEASE_SECONDS)]
         for i in ("a", "b")},
        tmp_path)
    try:
        # wait for both replicas to finish warmup + bootstrap, so neither
        # contends the lease while still compiling
        wait_for(
            lambda: all("READY" in status[i].read_text()
                        for i in ("a", "b")),
            procs, errs, 240, "replica warmup")

        # phase 1: pods for the first leader
        for i in range(3):
            service.add_pod(f"p{i}", resource_vector(cpu=1_000,
                                                     memory=1_024))

        def all_binds():
            return {i: _binds(status[i]) for i in ("a", "b")}

        def phase1_done():
            bound = {p for v in all_binds().values() for (p, _) in v}
            return {"p0", "p1", "p2"} <= bound

        wait_for(phase1_done, procs, errs, 120, "phase-1 binds")
        leader = "a" if _binds(status["a"]) else "b"
        # exactly ONE replica schedules while the lease is held
        standby = "b" if leader == "a" else "a"
        assert not _binds(status[standby]), \
            "standby replica scheduled while the leader held the lease"
        # apiserver wash: bound pods leave the shared state
        for p, _ in _binds(status[leader]):
            service.remove_pod(p)

        procs[leader].kill()     # SIGKILL: no voluntary lease release
        procs[leader].wait(timeout=10)
        live = {standby: procs[standby]}

        # phase 2: new pods arrive; the standby must wait out the lease,
        # take over, and bind
        for i in range(3, 6):
            service.add_pod(f"p{i}", resource_vector(cpu=1_000,
                                                     memory=1_024))
        wait_for(
            lambda: {"p3", "p4", "p5"} <= {
                p for (p, _) in _binds(status[standby])},
            live, errs, 180, "standby takeover binds")
        got = {p for (p, _) in _binds(status[standby])}
        # no pod was ever bound by both replicas
        dup = {p for (p, _) in _binds(status[leader])} & got
        assert not dup, f"pods double-bound across replicas: {dup}"
    finally:
        kill_all(procs)
        server.stop()
