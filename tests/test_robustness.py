"""Control-plane robustness: retry/backoff/circuit-breaker pacing,
per-call deadline propagation + server-side shed, fail-fast on dead
streams, DeltaLog replay-window boundaries, the ERROR-frame
``resync: true`` path, rv-gap detection, and the stale-state degraded
mode.  All deterministic (fake clocks / seeded rngs) — the randomized
end-to-end counterpart is tests/test_chaos.py."""

import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from koordinator_tpu import metrics
from koordinator_tpu.api.qos import QoSClass
from koordinator_tpu.api.resources import (
    NUM_RESOURCE_DIMS,
    ResourceDim,
    resource_vector,
)
from koordinator_tpu.ops.assignment import ScoringConfig
from koordinator_tpu.scheduler import ClusterSnapshot, Scheduler
from koordinator_tpu.scheduler.snapshot import PodSpec
from koordinator_tpu.transport import (
    FaultConfig,
    FaultInjector,
    RpcClient,
    RpcDeadlineError,
    RpcError,
    RpcRemoteError,
    RpcServer,
    StateSyncClient,
    StateSyncService,
)
from koordinator_tpu.transport.deltasync import (
    DeltaLog,
    ResyncRequired,
    SchedulerBinding,
    _pack_events,
)
from koordinator_tpu.transport.retry import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    CircuitBreaker,
    RetryPolicy,
    RetrySchedule,
)
from koordinator_tpu.transport.services import SolveService, solve_remote
from koordinator_tpu.transport.wire import Frame, FrameType, encode_payload

R = NUM_RESOURCE_DIMS


def mk_scheduler(**kw):
    snap = ClusterSnapshot(capacity=16)
    cfg = ScoringConfig.default().replace(
        usage_thresholds=jnp.zeros(R, jnp.int32),
        estimator_defaults=jnp.zeros(R, jnp.int32))
    return Scheduler(snap, config=cfg, **kw)


def wait_until(pred, timeout=5.0, what="condition"):
    deadline = time.monotonic() + timeout
    while not pred() and time.monotonic() < deadline:
        time.sleep(0.005)
    assert pred(), f"{what} not reached in time"


# ---- RetryPolicy / CircuitBreaker ------------------------------------------


def test_retry_policy_backoff_grows_exponentially_and_caps():
    p = RetryPolicy(initial_backoff_s=0.5, max_backoff_s=4.0,
                    multiplier=2.0, jitter="none")
    assert [p.backoff(a) for a in range(5)] == [0.5, 1.0, 2.0, 4.0, 4.0]


def test_retry_policy_jitter_bounds():
    import random

    rng = random.Random(7)
    full = RetryPolicy(initial_backoff_s=1.0, jitter="full")
    equal = RetryPolicy(initial_backoff_s=1.0, jitter="equal")
    for _ in range(50):
        assert 0.0 <= full.backoff(0, rng) <= 1.0
        assert 0.5 <= equal.backoff(0, rng) <= 1.0


def test_retry_schedule_exhausts_max_elapsed_budget():
    t = [0.0]
    p = RetryPolicy(initial_backoff_s=1.0, multiplier=2.0,
                    jitter="none", max_elapsed_s=5.0)
    sched = RetrySchedule(p, clock=lambda: t[0])
    d1 = sched.next_delay()        # 1.0, elapsed 0 -> fits
    assert d1 == 1.0
    t[0] += d1
    d2 = sched.next_delay()        # 2.0, elapsed 1 -> fits (3 <= 5)
    assert d2 == 2.0
    t[0] += d2
    assert sched.next_delay() is None   # 4.0 would land at 7 > 5: stop


def test_breaker_opens_half_opens_and_recloses():
    t = [0.0]
    b = CircuitBreaker(target="t", failure_threshold=1, clock=lambda: t[0],
                       policy=RetryPolicy(initial_backoff_s=1.0,
                                          multiplier=2.0, jitter="none"))
    assert b.state == CLOSED and b.allow()
    b.record_failure()
    assert b.state == OPEN
    assert not b.allow()            # window 1.0s
    t[0] = 0.5
    assert not b.allow()
    t[0] = 1.0
    assert b.allow()                # the half-open probe
    assert b.state == HALF_OPEN
    assert not b.allow()            # only ONE probe per window
    b.record_failure()              # probe failed: reopen, window 2.0s
    assert b.state == OPEN
    t[0] = 2.9
    assert not b.allow()
    t[0] = 3.0
    assert b.allow()
    b.record_success()
    assert b.state == CLOSED and b.opens == 0
    # recovered breaker starts its backoff schedule over
    b.record_failure()
    t[0] += 1.0
    assert b.allow()


def test_breaker_paces_dials_logarithmically():
    """Over a T-second outage, dials are O(log T) until the cap: the
    acceptance criterion's replacement for one-dial-per-tick."""
    t = [0.0]
    b = CircuitBreaker(target="t2", failure_threshold=1, clock=lambda: t[0],
                       policy=RetryPolicy(initial_backoff_s=0.5,
                                          max_backoff_s=64.0,
                                          multiplier=2.0, jitter="none"))
    dials = 0
    while t[0] < 60.0:              # a 60s outage, "ticked" every 10ms
        if b.allow():
            dials += 1
            b.record_failure()
        t[0] += 0.01
    # geometric windows 0.5+1+2+...: ~8 dials in 60s, vs 6000 ticks
    assert dials <= 9


# ---- fault injector --------------------------------------------------------


def test_fault_injector_schedule_is_deterministic_per_seed():
    cfg = FaultConfig(send_sever_p=0.2, send_truncate_p=0.2,
                      push_drop_p=0.3, push_reorder_p=0.3)
    a = FaultInjector(seed=42, config=cfg)
    b = FaultInjector(seed=42, config=cfg)
    seq_a = [a.outbound_action(is_push=i % 2 == 0) for i in range(200)]
    seq_b = [b.outbound_action(is_push=i % 2 == 0) for i in range(200)]
    assert seq_a == seq_b
    assert any(x is not None for x in seq_a), "schedule never fired"
    c = FaultInjector(seed=43, config=cfg)
    seq_c = [c.outbound_action(is_push=i % 2 == 0) for i in range(200)]
    assert seq_a != seq_c


def test_fault_injector_heal_stops_injection():
    inj = FaultInjector(seed=1, config=FaultConfig(send_sever_p=1.0))
    assert inj.outbound_action(is_push=False) == "sever"
    inj.heal()
    assert inj.outbound_action(is_push=False) is None


def test_fault_injector_heal_resets_registered_breakers():
    """The heal seam (ISSUE 17): a drill's heal() must force-close every
    registered breaker so callers probe the healed peer NOW, instead of
    waiting out an open window that chaos backoff growth pushed far past
    the heal."""
    t = [0.0]
    b = CircuitBreaker(target="healed", failure_threshold=1,
                       clock=lambda: t[0],
                       policy=RetryPolicy(initial_backoff_s=600.0,
                                          multiplier=2.0, jitter="none"))
    inj = FaultInjector(seed=7, config=FaultConfig(send_sever_p=1.0))
    inj.register_breaker(b)
    b.record_failure()
    assert b.state == OPEN
    assert not b.allow()            # 600s window: dead until the heal
    inj.heal()
    assert b.state == CLOSED
    assert b.allow()                # probed immediately, no half-open
    assert b.opens == 0             # backoff history zeroed
    # the reset breaker starts its schedule over, not where chaos left it
    b.record_failure()
    t[0] += 600.0
    assert b.allow()


def test_injected_connect_refusal_surfaces_as_rpc_error(tmp_path):
    server = RpcServer(str(tmp_path / "s.sock"))
    server.start()
    try:
        inj = FaultInjector(seed=1,
                            config=FaultConfig(connect_refuse_p=1.0))
        client = RpcClient(server.path, faults=inj)
        with pytest.raises(ConnectionRefusedError):
            client.connect()
        assert inj.injected["connect_refuse"] == 1
    finally:
        server.stop()


def test_injected_truncation_severs_and_both_sides_recover(tmp_path):
    """A mid-write truncated client frame desyncs the server's framing;
    the connection dies loudly on both ends and a fresh connect works."""
    server = RpcServer(str(tmp_path / "t.sock"))
    server.register(FrameType.SOLVE_REQUEST,
                    lambda doc, arrays: ({"ok": True}, None))
    server.start()
    clients = []
    try:
        inj = FaultInjector(seed=3,
                            config=FaultConfig(send_truncate_p=1.0))
        client = RpcClient(server.path, faults=inj)
        client.connect()
        clients.append(client)
        with pytest.raises(RpcError, match="connection lost"):
            client.call(FrameType.SOLVE_REQUEST, {})
        assert inj.injected["client_truncate"] == 1
        wait_until(lambda: not client.connected, what="client severed")
        inj.heal()
        fresh = RpcClient(server.path, faults=inj)
        fresh.connect()
        clients.append(fresh)
        _, doc, _ = fresh.call(FrameType.SOLVE_REQUEST, {})
        assert doc == {"ok": True}
    finally:
        for c in clients:
            c.close()
        server.stop()


# ---- fail-fast + reader join (satellites) ----------------------------------


def test_call_fails_fast_when_reader_is_dead(tmp_path):
    server = RpcServer(str(tmp_path / "ff.sock"))
    server.start()
    client = RpcClient(server.path, timeout=10.0)
    client.connect()
    try:
        server.stop()                      # peer EOF kills the reader
        wait_until(lambda: not client.connected, what="reader death")
        t0 = time.monotonic()
        with pytest.raises(RpcError, match="not connected"):
            client.call(FrameType.PING, {})
        assert time.monotonic() - t0 < 1.0, (
            "dead-stream call burned toward the full timeout instead of "
            "failing fast")
    finally:
        client.close()


def test_client_close_joins_reader_thread(tmp_path):
    server = RpcServer(str(tmp_path / "join.sock"))
    server.start()
    try:
        baseline = threading.active_count()
        for _ in range(8):
            client = RpcClient(server.path)
            client.connect()
            client.close()
            assert client._reader is None or not client._reader.is_alive()
        wait_until(lambda: threading.active_count() <= baseline,
                   what="reader threads reaped")
    finally:
        server.stop()


# ---- deadline propagation --------------------------------------------------


@pytest.fixture
def solve_rpc(tmp_path):
    sched = mk_scheduler()
    sched.snapshot.upsert_node(__import__(
        "koordinator_tpu.scheduler.snapshot", fromlist=["NodeSpec"]
    ).NodeSpec(name="n0", allocatable=resource_vector(cpu=8000,
                                                      memory=16384)))
    server = RpcServer(str(tmp_path / "dl.sock"))
    service = SolveService(sched)
    service.attach(server)
    server.start()
    client = RpcClient(server.path)
    client.connect()
    try:
        yield sched, service, client
    finally:
        client.close()
        server.stop()


def test_expired_deadline_is_shed_at_the_channel(solve_rpc):
    sched, service, client = solve_rpc
    before = metrics.rpc_deadline_shed_total.value(
        labels={"type": "SOLVE_REQUEST"})
    # deadline already spent when the frame lands: shed pre-dispatch
    # (deadline_ms in the doc, not the kwarg, so the client still waits
    # for the ERROR instead of timing out locally first)
    with pytest.raises(RpcDeadlineError):
        client.call(FrameType.SOLVE_REQUEST, {"deadline_ms": -1.0})
    assert metrics.rpc_deadline_shed_total.value(
        labels={"type": "SOLVE_REQUEST"}) == before + 1
    assert service.sheds == 0              # never reached the handler


def test_solve_shed_after_burning_budget_on_the_round_lock(solve_rpc):
    """The issue's headline case: a SOLVE_REQUEST that spent its budget
    waiting for the scheduler lock is shed WITHOUT running the solve."""
    sched, service, client = solve_rpc
    sched.enqueue(PodSpec(name="p0",
                          requests=resource_vector(cpu=100, memory=128)))
    release = threading.Event()
    holding = threading.Event()

    def hog():
        with sched.lock:
            holding.set()
            release.wait(5)

    t = threading.Thread(target=hog, daemon=True)
    t.start()
    holding.wait(5)
    err = []

    def call():
        try:
            client.call(FrameType.SOLVE_REQUEST, {"deadline_ms": 100.0})
        except Exception as e:  # noqa: BLE001
            err.append(e)

    caller = threading.Thread(target=call, daemon=True)
    caller.start()
    time.sleep(0.4)                        # budget long gone
    release.set()
    caller.join(5)
    t.join(5)
    assert err and isinstance(err[0], RpcDeadlineError)
    assert service.sheds == 1
    assert "p0" in sched.pending, "shed request must not have solved"
    # a fresh in-budget call still solves
    out = solve_remote(client, deadline_ms=5000)
    assert out["assignments"] == {"p0": "n0"}


def test_request_queued_behind_slow_handler_burns_its_budget(tmp_path):
    """Handlers are sequential per connection; the eager read loop
    stamps TRUE arrival, so a request that waited out its budget in the
    inbox behind a slow handler is shed — not granted a fresh budget
    when the handler finally returns."""
    server = RpcServer(str(tmp_path / "q.sock"))
    runs = []

    def handler(doc, arrays):
        runs.append(doc.get("who"))
        if doc.get("sleep"):
            time.sleep(0.4)
        return {"ok": True}, None

    server.register(FrameType.SOLVE_REQUEST, handler)
    server.start()
    client = RpcClient(server.path)
    client.connect()
    results = {}

    def call(who, doc):
        try:
            results[who] = client.call(FrameType.SOLVE_REQUEST,
                                       dict(doc, who=who))
        except Exception as e:  # noqa: BLE001
            results[who] = e

    try:
        slow = threading.Thread(target=call,
                                args=("slow", {"sleep": True}))
        slow.start()
        time.sleep(0.1)                   # slow's handler is running
        # queued behind slow with a 100ms budget (doc field, so the
        # client waits for the server's answer instead of timing out)
        call("late", {"deadline_ms": 100.0})
        slow.join(5)
        assert results["slow"][1] == {"ok": True}
        assert isinstance(results["late"], RpcDeadlineError), results["late"]
        assert runs == ["slow"], (
            f"expired queued request still ran its handler: {runs}")
    finally:
        client.close()
        server.stop()


def test_deadline_wait_expiry_is_not_a_transport_error(solve_rpc):
    """A deadline-bounded wait that runs out raises RpcDeadlineError
    (the connection is healthy) — shared-connection owners must not
    tear the client down over a per-call budget."""
    sched, service, client = solve_rpc
    release = threading.Event()
    holding = threading.Event()

    def hog():
        with sched.lock:
            holding.set()
            release.wait(5)

    t = threading.Thread(target=hog, daemon=True)
    t.start()
    holding.wait(5)
    try:
        with pytest.raises(RpcDeadlineError):
            client.call(FrameType.SOLVE_REQUEST, {}, deadline_ms=150.0)
        assert client.connected
    finally:
        release.set()
        t.join(5)


def test_deadline_kwarg_bounds_the_client_wait(solve_rpc):
    sched, service, client = solve_rpc
    release = threading.Event()
    holding = threading.Event()

    def hog():
        with sched.lock:
            holding.set()
            release.wait(5)

    t = threading.Thread(target=hog, daemon=True)
    t.start()
    holding.wait(5)
    try:
        t0 = time.monotonic()
        with pytest.raises(RpcError):
            client.call(FrameType.SOLVE_REQUEST, {}, deadline_ms=150.0)
        assert time.monotonic() - t0 < 2.0
    finally:
        release.set()
        t.join(5)


# ---- DeltaLog replay-window boundary (satellite) ---------------------------


def test_delta_log_boundary_exact_oldest_gets_delta():
    log = DeltaLog(retention=4)
    for rv in range(1, 9):                 # retained: 5..8
        log.append(rv, {"n": rv}, {})
    assert log.oldest_rv() == 5
    # at the oldest retained event: replay the rest
    assert [e["n"] for _, e, _ in log.since(5)] == [6, 7, 8]
    # one BEFORE the oldest retained event: the client is missing
    # nothing the log lost (5.. are all retained) — still a DELTA
    assert [e["n"] for _, e, _ in log.since(4)] == [5, 6, 7, 8]
    # one event older: rv 4 was evicted — resync required
    with pytest.raises(ResyncRequired):
        log.since(3)


def test_hello_at_replay_window_boundary(tmp_path):
    """The same boundary through the wire: last_rv at the window edge
    gets DELTA, one event older gets the full SNAPSHOT."""
    server = RpcServer(str(tmp_path / "bnd.sock"))
    service = StateSyncService(retention=4)
    service.attach(server)
    server.start()
    clients = []

    def hello(last_rv):
        client = RpcClient(server.path)
        client.connect()
        clients.append(client)
        ftype, doc, arrays = client.call(FrameType.HELLO, {
            "last_rv": last_rv, "proto": 3,
            "instance": service.instance})
        return ftype, doc

    try:
        for i in range(8):                 # rv 1..8; retained 5..8
            service.upsert_node(f"n{i}",
                                resource_vector(cpu=1000, memory=1024))
        assert service.log.oldest_rv() == 5
        ftype, doc = hello(4)
        assert ftype is FrameType.DELTA
        assert [e["rv"] for e in doc["events"]] == [5, 6, 7, 8]
        ftype, doc = hello(3)              # rv 4 evicted: full snapshot
        assert ftype is FrameType.SNAPSHOT
        assert doc.get("snapshot") and len(doc["events"]) == 8
        ftype, doc = hello(8)              # fully caught up
        assert ftype is FrameType.ACK
    finally:
        for c in clients:
            c.close()
        server.stop()


# ---- ERROR resync: true end-to-end (satellite) -----------------------------


def test_unknown_node_error_carries_resync_flag(tmp_path):
    server = RpcServer(str(tmp_path / "rs.sock"))
    service = StateSyncService()
    service.attach(server)
    server.start()
    client = RpcClient(server.path)
    client.connect()
    try:
        with pytest.raises(RpcRemoteError) as ei:
            client.call(FrameType.STATE_PUSH,
                        {"kind": "node_usage", "name": "ghost"},
                        {"usage": resource_vector(cpu=1)})
        assert ei.value.resync is True
        # a plain schema error must NOT ask for resync
        with pytest.raises(RpcRemoteError) as ei:
            client.call(FrameType.STATE_PUSH,
                        {"kind": "node_usage", "name": "ghost"})
        assert ei.value.resync is False
    finally:
        client.close()
        server.stop()


def test_error_resync_rehellos_and_manager_binding_survives(tmp_path):
    """End-to-end: a manager pushing for a node the sidecar no longer
    knows gets ERROR resync:true; the reconnecting client re-HELLOs on
    the spot and the mid-stream (snapshot) resync preserves the
    koordlet-fed node_usage aggregates (hp_request/hp_max_used_req)
    instead of resetting them to over-advertising zeros."""
    from koordinator_tpu.cmd.binaries import ReconnectingSidecarClient
    from koordinator_tpu.manager.colocation_loop import ManagerSyncBinding

    server = RpcServer(str(tmp_path / "mgr.sock"))
    service = StateSyncService()
    service.attach(server)
    server.start()
    service.upsert_node("n0", resource_vector(cpu=16000, memory=16384))
    service.update_node_usage(
        "n0", resource_vector(cpu=2000, memory=4096),
        hp_request=resource_vector(cpu=3000, memory=2048),
        hp_max_used_req=resource_vector(cpu=3500, memory=2100),
        report_time=123.0)
    service.upsert_node("n1", resource_vector(cpu=8000, memory=8192))

    binding = ManagerSyncBinding()
    sync = StateSyncClient(binding)

    def bootstrap_watch(client):
        sync.bind_client(client)
        sync.bootstrap(client)

    sidecar = ReconnectingSidecarClient(
        server.path, on_push=sync.on_push, on_connect=bootstrap_watch)
    try:
        sidecar.ensure()
        assert set(binding.nodes) == {"n0", "n1"}

        # the sidecar loses n1 while the manager isn't looking (watch
        # push suppressed: simulate the lost-delta world by removing it
        # behind the client's back)
        with service._lock:
            service.nodes.pop("n1")
        # ...and force the re-HELLO down the SNAPSHOT path: pretend the
        # manager last synced a different service incarnation
        sync.instance = "stale-incarnation"
        before = sidecar.resyncs

        with pytest.raises(RpcRemoteError) as ei:
            sidecar.call(FrameType.STATE_PUSH,
                         {"kind": "node_allocatable", "name": "n1"},
                         {"allocatable": resource_vector(cpu=1)})
        assert ei.value.resync is True
        assert sidecar.resyncs == before + 1
        # the re-HELLO ran: instance healed, view re-snapshot
        assert sync.instance == service.instance
        wait_until(lambda: "n1" not in binding.nodes,
                   what="ghost node dropped by resync")
        view = binding.nodes["n0"]
        assert view.hp_request is not None, (
            "snapshot resync dropped the koordlet usage aggregates")
        assert int(view.hp_request[ResourceDim.CPU]) == 3000
        assert int(view.hp_max_used_req[ResourceDim.CPU]) == 3500
        assert view.usage_time == 123.0
        # pushes against the fresh view work again
        sidecar.call(FrameType.STATE_PUSH,
                     {"kind": "node_allocatable", "name": "n0"},
                     {"allocatable": resource_vector(cpu=16000,
                                                     memory=16384,
                                                     batch_cpu=1000)})
    finally:
        sidecar.close()
        server.stop()


# ---- rv-gap detection ------------------------------------------------------


class _FakeTransport:
    def __init__(self):
        self.closed = 0

    def close(self):
        self.closed += 1


def _delta_frame(rv):
    doc, arrays = _pack_events([(rv, {"kind": "pod_remove",
                                      "name": f"p{rv}"}, {})])
    return Frame(FrameType.DELTA, 0, encode_payload(doc, arrays))


class _NullBinding:
    def __getattr__(self, name):
        return lambda *a, **k: None


def test_rv_gap_flags_resync_and_severs_the_stream():
    sync = StateSyncClient(_NullBinding())
    fake = _FakeTransport()
    sync.bind_client(fake)
    sync.rv = 0
    sync.on_push(_delta_frame(1))
    sync.on_push(_delta_frame(2))
    assert sync.gaps == 0 and not sync.needs_resync
    sync.on_push(_delta_frame(4))          # rv 3 lost on the wire
    assert sync.gaps == 1 and sync.needs_resync
    assert fake.closed == 1
    # duplicates/overlaps stay idempotent, not gaps
    sync.on_push(_delta_frame(4))
    assert sync.gaps == 1 and sync.skipped == 1


def test_rv_gap_repair_rides_the_full_snapshot():
    """The gap handler APPLIES the fresher events, so self.rv has
    already advanced past the hole — a delta re-HELLO from last_rv
    would replay nothing and the lost event would stay lost forever
    with both rv counters agreeing.  The reconnect bootstrap must ask
    for the full snapshot instead."""
    sync = StateSyncClient(_NullBinding())
    sync.rv = 2
    sync.on_push(_delta_frame(4))          # rv 3 lost; rv now 4
    assert sync.needs_resync and sync.rv == 4

    hellos = []

    class _FakeClient:
        def call(self, ftype, doc, arrays=None):
            hellos.append(doc)
            return FrameType.ACK, {}, {}

    sync.bootstrap(_FakeClient())
    assert hellos[0]["last_rv"] == -1      # full snapshot, not a delta
    assert not sync.needs_resync           # repaired: flag cleared
    # healthy reconnects keep the cheap delta path
    sync.bootstrap(_FakeClient())
    assert hellos[1]["last_rv"] == sync.rv


# ---- stale-state degraded mode ---------------------------------------------


def _degraded_fixture():
    from koordinator_tpu.scheduler.snapshot import NodeSpec

    t = [0.0]
    sched = mk_scheduler(clock=lambda: t[0], staleness_threshold_sec=10.0)
    sched.snapshot.upsert_node(NodeSpec(
        name="n0",
        allocatable=resource_vector(cpu=64000, memory=65536,
                                    batch_cpu=10000, batch_memory=8192)))
    sched.note_sync_event()                # the feed spoke at t=0
    return t, sched


def test_stalled_feed_flips_degraded_and_suspends_be_admission():
    t, sched = _degraded_fixture()
    sched.enqueue(PodSpec(name="prod-1",
                          requests=resource_vector(cpu=1000, memory=1024)))
    sched.enqueue(PodSpec(name="be-1", qos=int(QoSClass.BE),
                          requests=resource_vector(cpu=500, memory=256)))
    sched.enqueue(PodSpec(name="batch-dim-1",
                          requests=resource_vector(batch_cpu=500,
                                                   batch_memory=256)))
    t[0] = 5.0                             # fresh enough: everything flows
    result = sched.schedule_round()
    assert not sched.degraded
    assert set(result.assignments) == {"prod-1", "be-1", "batch-dim-1"}

    sched.enqueue(PodSpec(name="prod-2",
                          requests=resource_vector(cpu=1000, memory=1024)))
    sched.enqueue(PodSpec(name="be-2", qos=int(QoSClass.BE),
                          requests=resource_vector(cpu=500, memory=256)))
    sched.enqueue(PodSpec(name="batch-dim-2",
                          requests=resource_vector(batch_cpu=500,
                                                   batch_memory=256)))
    t[0] = 16.0                            # feed silent past threshold
    result = sched.schedule_round()
    assert sched.degraded and sched.degraded_entries == 1
    assert metrics.degraded_mode.value() == 1.0
    assert metrics.state_staleness_seconds.value() == pytest.approx(16.0)
    # prod keeps scheduling; BE and batch-dim admission is suspended
    # (held pending, not failed — they resume on resync)
    assert set(result.assignments) == {"prod-2"}
    assert "be-2" in sched.pending and "batch-dim-2" in sched.pending
    assert sched.last_suspended == 2
    assert metrics.degraded_suspended_pods.value() == 2.0

    # feed heals (resync/delta applies) -> exit + suspended pods flow
    t[0] = 17.0
    sched.note_sync_event()
    result = sched.schedule_round()
    assert not sched.degraded
    assert metrics.degraded_mode.value() == 0.0
    assert set(result.assignments) == {"be-2", "batch-dim-2"}


def test_degraded_exit_has_hysteresis():
    t, sched = _degraded_fixture()
    t[0] = 11.0
    sched.schedule_round()
    assert sched.degraded
    # a single trickle event at age just under the threshold is NOT
    # enough: exit needs age <= threshold/2
    t[0] = 20.0
    sched.note_sync_event()
    t[0] = 26.0                            # age 6 > exit threshold 5
    sched.schedule_round()
    assert sched.degraded
    t[0] = 24.0 + 0.5                      # age fell under threshold/2
    sched.schedule_round()
    assert not sched.degraded


def test_degraded_forces_full_pass_over_incremental_cache():
    from koordinator_tpu.scheduler.snapshot import NodeSpec

    t = [0.0]
    sched = mk_scheduler(clock=lambda: t[0], staleness_threshold_sec=10.0,
                         batch_solver_threshold=1)
    # tiny fixture: the 2-pod/4-node dirty fractions would trip the
    # ordinary fallback and mask the path under test
    sched.incremental_dirty_threshold = 1.0
    # small static round count: the propose/accept passes unroll per
    # round, and this test exercises PATH SELECTION, not solve quality —
    # 12 unrolled rounds would triple the jit compile for nothing
    sched.solve_rounds = 2
    for i in range(4):
        sched.snapshot.upsert_node(NodeSpec(
            name=f"n{i}",
            allocatable=resource_vector(cpu=64000, memory=65536)))
    sched.note_sync_event()
    sched.enqueue(PodSpec(name="w0",
                          requests=resource_vector(cpu=100, memory=128)))
    sched.schedule_round()
    assert sched.last_solve_path == "full_cold"   # cache warms
    sched.enqueue(PodSpec(name="w1",
                          requests=resource_vector(cpu=100, memory=128)))
    t[0] = 2.0
    sched.schedule_round()
    assert sched.last_solve_path == "incremental"
    sched.enqueue(PodSpec(name="w2",
                          requests=resource_vector(cpu=100, memory=128)))
    t[0] = 15.0                            # stale: cache dropped
    sched.schedule_round()
    assert sched.degraded
    assert sched.last_solve_path == "degraded"
    assert sched._cand_cache is None
    # resync: incremental resumes from a cold rebuild
    sched.note_sync_event()
    t[0] = 15.5
    sched.enqueue(PodSpec(name="w3",
                          requests=resource_vector(cpu=100, memory=128)))
    sched.schedule_round()
    assert not sched.degraded
    assert sched.last_solve_path == "full_cold"


def test_degraded_watchdog_disabled_by_default():
    sched = mk_scheduler(clock=lambda: 1e9)
    sched.note_sync_event()
    sched.schedule_round()
    assert not sched.degraded


# ---- breaker-paced reconnecting client -------------------------------------


def test_reconnecting_client_backs_off_on_dead_sidecar(tmp_path):
    from koordinator_tpu.cmd.binaries import ReconnectingSidecarClient

    dials = [0]
    t = [0.0]
    breaker = CircuitBreaker(
        target="dead", failure_threshold=1, clock=lambda: t[0],
        policy=RetryPolicy(initial_backoff_s=1.0, multiplier=2.0,
                           jitter="none"))
    client = ReconnectingSidecarClient(
        str(tmp_path / "nobody-home.sock"), breaker=breaker)

    real_connect = RpcClient.connect

    def counting_connect(self):
        dials[0] += 1
        return real_connect(self)

    try:
        RpcClient.connect = counting_connect
        # 100 "ticks" over 10s of fake time: without the breaker this
        # was 100 dials; with it, the geometric windows allow ~5
        for _ in range(100):
            t[0] += 0.1
            with pytest.raises(RpcError):
                client.ensure()
        assert dials[0] <= 5
        assert breaker.state == OPEN
    finally:
        RpcClient.connect = real_connect
        client.close()


def test_reconnecting_client_recovers_after_breaker_window(tmp_path):
    from koordinator_tpu.cmd.binaries import ReconnectingSidecarClient

    t = [0.0]
    breaker = CircuitBreaker(
        target="rec", failure_threshold=1, clock=lambda: t[0],
        policy=RetryPolicy(initial_backoff_s=1.0, jitter="none"))
    sock = str(tmp_path / "late.sock")
    client = ReconnectingSidecarClient(sock, breaker=breaker)
    try:
        with pytest.raises(RpcError):
            client.ensure()
        server = RpcServer(sock)
        server.start()
        try:
            with pytest.raises(RpcError, match="circuit open"):
                client.ensure()            # window not yet elapsed
            t[0] = 1.0
            assert client.ensure().connected   # half-open probe succeeds
            assert breaker.state == CLOSED
        finally:
            server.stop()
    finally:
        client.close()
