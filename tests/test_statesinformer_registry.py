"""Informer plugin registry + NodeMetric reporter loop
(koordlet/statesinformer.py additions) vs impl/states_informer.go
(dependency-ordered startup) and impl/states_nodemetric.go:206 (sync
worker, spec-driven interval, expired handling)."""

import pytest

from koordinator_tpu.api.qos import QoSClass
from koordinator_tpu.koordlet import metriccache as mc
from koordinator_tpu.koordlet.statesinformer import (
    InformerPlugin,
    InformerRegistry,
    KubeletPodsInformer,
    NodeInfo,
    NodeMetricReporter,
    PodMeta,
    StatesInformer,
    TYPE_NODE_METRIC,
)


class Recorder(InformerPlugin):
    def __init__(self, name, depends=(), log=None, fail=False):
        self.name = name
        self.depends = depends
        self.log = log if log is not None else []
        self.fail = fail

    def sync(self, states):
        if self.fail:
            raise RuntimeError("informer broke")
        self.log.append(self.name)


def test_registry_orders_by_dependencies():
    log = []
    reg = InformerRegistry()
    reg.register(Recorder("pods", depends=("node",), log=log))
    reg.register(Recorder("nodemetric", depends=("pods",), log=log))
    reg.register(Recorder("node", log=log))
    reg.register(Recorder("device", log=log))
    assert reg.sync_all(StatesInformer()) == 4
    assert log.index("node") < log.index("pods") < log.index("nodemetric")


def test_registry_rejects_cycles_and_unknown_deps():
    reg = InformerRegistry()
    reg.register(Recorder("a", depends=("b",)))
    reg.register(Recorder("b", depends=("a",)))
    with pytest.raises(ValueError, match="cycle"):
        reg.ordered()
    reg2 = InformerRegistry()
    reg2.register(Recorder("a", depends=("ghost",)))
    with pytest.raises(ValueError, match="unknown"):
        reg2.ordered()


def test_failing_informer_isolated():
    log = []
    reg = InformerRegistry()
    reg.register(Recorder("node", log=log))
    reg.register(Recorder("pods", depends=("node",), log=log, fail=True))
    reg.register(Recorder("device", log=log))
    assert reg.sync_all(StatesInformer()) == 2
    assert "pods" in reg.sync_errors
    assert log == ["device", "node"]   # alphabetical roots, pods failed
    # recovery clears the error
    reg._plugins["pods"].fail = False
    reg.sync_all(StatesInformer())
    assert "pods" not in reg.sync_errors


def test_kubelet_pods_informer():
    class Stub:
        def get_all_pods(self):
            return [PodMeta(uid="u1", name="p", namespace="d",
                            qos_class=QoSClass.LS, kube_qos="burstable")]

    states = StatesInformer()
    states.set_node(NodeInfo(name="n1"))
    informer = KubeletPodsInformer(Stub())
    assert informer.depends == ("node",)
    informer.sync(states)
    assert states.get_pod("u1").name == "p"


def mk_states(clock):
    cache = mc.MetricCache(clock=clock)
    states = StatesInformer(metric_cache=cache, clock=clock)
    return states, cache


def test_reporter_interval_and_spec_update():
    t = [0.0]
    states, cache = mk_states(lambda: t[0])
    cache.append(mc.NODE_CPU_USAGE, 2.0, ts=0.0)
    cache.append(mc.NODE_MEMORY_USAGE, 1 << 30, ts=0.0)
    reports = []
    rep = NodeMetricReporter(states, reports.append,
                             report_interval_seconds=60, clock=lambda: t[0])
    t[0] = 1.0
    assert rep.tick() is not None        # first report
    t[0] = 30.0
    assert rep.tick() is None            # not due
    rep.update_spec(report_interval_seconds=10,
                    aggregate_window_seconds=120)
    t[0] = 31.0
    cache.append(mc.NODE_CPU_USAGE, 4.0, ts=31.0)
    assert rep.tick() is not None        # manager shortened the interval
    assert rep.reports == 2 and rep.degraded_reports == 0
    assert reports[-1].node_usage.cpu_milli > 0


def test_reporter_degrades_when_collectors_silent():
    t = [0.0]
    states, cache = mk_states(lambda: t[0])
    cache.append(mc.NODE_CPU_USAGE, 2.0, ts=0.0)
    cache.append(mc.NODE_MEMORY_USAGE, 1.0, ts=0.0)
    fired = []
    states.register_callback(TYPE_NODE_METRIC, fired.append)
    rep = NodeMetricReporter(states, lambda s: None,
                             report_interval_seconds=60,
                             expire_seconds=180, clock=lambda: t[0])
    t[0] = 10.0
    assert rep.tick().degraded is False
    t[0] = 500.0     # collectors silent for 490s > 180s budget
    status = rep.tick()
    assert status.degraded is True
    assert rep.degraded_reports == 1
    assert fired[-1] is status           # TYPE_NODE_METRIC callback fan-out
