"""Concurrency stress: the race-detector analog.

The reference runs its whole suite under ``go test -race`` (Makefile:96-98;
SURVEY.md §5). Python has no tsan for this code, so these tests do what
-race would: hammer every threaded component (metriccache, resource
executor, runtime-proxy dispatcher/failover store, audit log, explanation
store, lease store) from many writer+reader threads at once and assert the
invariants that a data race would break — no lost/duplicated counts, no
torn reads, no exceptions escaping worker threads.
"""

import tempfile
import threading

import numpy as np
import pytest


N_THREADS = 8
N_OPS = 200


def hammer(fn_per_thread):
    """Run fn(i) on N_THREADS threads; re-raise any worker exception."""
    errors = []

    def wrap(i):
        try:
            fn_per_thread(i)
        except Exception as e:  # pragma: no cover - only on race
            errors.append(e)

    threads = [threading.Thread(target=wrap, args=(i,))
               for i in range(N_THREADS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors


def test_metriccache_concurrent_append_query_gc():
    from koordinator_tpu.koordlet.metriccache import MetricCache

    cache = MetricCache(capacity_per_series=N_OPS * N_THREADS)
    stop = threading.Event()

    def writer(i):
        for k in range(N_OPS):
            cache.append("node_cpu", float(k), labels={"t": str(i)},
                         ts=float(k))
            cache.append("pod_cpu", float(k), labels={"uid": f"u{i}"},
                         ts=float(k))

    def churn():
        while not stop.is_set():
            cache.query("node_cpu", start=0, end=float(N_OPS))
            cache.gc(keep_pod_uids={f"u{i}" for i in range(N_THREADS)})

    reader = threading.Thread(target=churn)
    reader.start()
    try:
        hammer(writer)
    finally:
        stop.set()
        reader.join()
    for i in range(N_THREADS):
        res = cache.query("node_cpu", labels={"t": str(i)},
                          start=0, end=float(N_OPS) + 1)
        assert res.count == N_OPS          # no lost appends
    # gc must not have dropped live pod series
    res = cache.query("pod_cpu", labels={"uid": "u0"},
                      start=0, end=float(N_OPS) + 1)
    assert res.count == N_OPS


def test_resource_executor_concurrent_update_same_files(tmp_path):
    import os

    from koordinator_tpu.koordlet.resourceexecutor import (
        ResourceUpdate, ResourceUpdateExecutor)
    from koordinator_tpu.koordlet.system import cgroup as cg
    from koordinator_tpu.koordlet.system.config import make_test_config

    cfg = make_test_config(tmp_path)
    path = cfg.cgroup_abs_path(cg.CPU_SHARES.subsystem, "kubepods",
                               cg.CPU_SHARES.filename(cg.CgroupVersion.V1))
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        f.write("2")
    executor = ResourceUpdateExecutor(cfg)

    def writer(i):
        for k in range(N_OPS):
            executor.update(ResourceUpdate(
                cg.CPU_SHARES, "kubepods", str(2 + i * N_OPS + k)))

    hammer(writer)
    # file holds exactly one of the written values, not torn garbage
    final = int(open(path).read())
    assert 2 <= final < 2 + N_THREADS * N_OPS
    # write cache stays coherent with the file after quiescence
    executor.update(ResourceUpdate(cg.CPU_SHARES, "kubepods", "7"))
    assert open(path).read() == "7"


def test_failover_store_concurrent_save_get_delete():
    from koordinator_tpu.runtimeproxy import FailoverStore, HookRequest

    store = FailoverStore()

    def worker(i):
        for k in range(N_OPS):
            pid = f"pod-{i}-{k % 10}"
            store.save_pod(pid, HookRequest(pod_meta={"uid": pid}))
            got = store.get_pod(pid)
            # never observe another pod's request under the same key
            assert got is None or got.pod_meta["uid"] == pid
            if k % 3 == 0:
                store.delete_pod(pid)

    hammer(worker)


def test_dispatcher_concurrent_register_dispatch():
    from koordinator_tpu.runtimeproxy import (
        Dispatcher, HookRequest, HookResponse, HookType)

    dispatcher = Dispatcher()
    calls = []
    lock = threading.Lock()

    class Server:
        def __init__(self, i):
            self.i = i

        def handle(self, hook, request):
            with lock:
                calls.append(self.i)
            return HookResponse()

    def worker(i):
        dispatcher.register(Server(i), [HookType.PRE_RUN_POD_SANDBOX])
        for _ in range(N_OPS // 10):
            dispatcher.dispatch(HookType.PRE_RUN_POD_SANDBOX,
                                HookRequest(pod_meta={"uid": f"p{i}"}))

    hammer(worker)
    assert len(calls) > 0


def test_auditor_concurrent_log_rotate_query():
    from koordinator_tpu.koordlet.audit import Auditor

    with tempfile.TemporaryDirectory() as d:
        auditor = Auditor(log_dir=d, max_file_bytes=4096, max_files=4)

        def worker(i):
            for k in range(N_OPS):
                auditor.log("cgroup", "update", f"t{i}-{k}",
                            {"v": k})
                if k % 20 == 0:
                    auditor.query(limit=50)

        hammer(worker)
        rows = auditor.query(limit=10_000)
        assert rows                       # retained tail survives rotation
        for row in rows:
            assert row["group"] == "cgroup" and "target" in row


def test_explanation_store_concurrent_record_drain():
    from koordinator_tpu.scheduler.diagnosis import PodDiagnosis
    from koordinator_tpu.scheduler.explanation import ExplanationStore

    store = ExplanationStore(capacity=10_000, queue_size=10_000)
    d = PodDiagnosis(total_nodes=1, feasible_nodes=0,
                     insufficient_resources=1, usage_over_threshold=0,
                     affinity_mismatch=0, quota_rejected=False, invalid=0)
    stop = threading.Event()

    def drainer():
        while not stop.is_set():
            store.drain(max_items=17)

    th = threading.Thread(target=drainer)
    th.start()

    def worker(i):
        for k in range(N_OPS):
            store.record(f"p{i}-{k}", d)

    try:
        hammer(worker)
    finally:
        stop.set()
        th.join()
    store.drain()
    assert len(store.list()) + store.dropped == N_THREADS * N_OPS
    assert store.dropped == 0


def test_lease_store_single_winner_per_term():
    from koordinator_tpu.ha import InMemoryLeaseStore, LeaderElector

    store = InMemoryLeaseStore()
    t = [0.0]
    electors = [LeaderElector(store, "L", f"id{i}", lease_duration=1e9,
                              clock=lambda: t[0]) for i in range(N_THREADS)]
    results = [None] * N_THREADS
    barrier = threading.Barrier(N_THREADS)

    def worker(i):
        barrier.wait()
        results[i] = electors[i].tick()

    hammer(worker)
    assert sum(bool(r) for r in results) == 1   # exactly one leader


@pytest.mark.parametrize("rounds", [3])
def test_metrics_registry_concurrent_inc(rounds):
    from koordinator_tpu.metrics import Counter

    c = Counter("stress_total", "stress counter")

    def worker(i):
        for _ in range(N_OPS * rounds):
            c.inc(labels={"w": str(i % 2)})

    hammer(worker)
    total = sum(c.value(labels={"w": str(j)}) for j in (0, 1))
    assert total == N_THREADS * N_OPS * rounds   # no lost increments


class TestSidecarPushSolveStress:
    """The sidecar assembly under contention: concurrent STATE_PUSH
    writers, solve callers, and HELLO bootstrappers against one
    scheduler-binary sidecar.  Exercises the commit->binding-queue drain
    (rv order without holding the service lock) and the scheduler lock
    under real thread interleaving; the end state must be exactly the
    pushed universe."""

    def test_concurrent_push_solve_hello(self, tmp_path):
        import numpy as np

        from koordinator_tpu.api.resources import resource_vector
        from koordinator_tpu.cmd.binaries import main_koord_scheduler
        from koordinator_tpu.transport import RpcClient
        from koordinator_tpu.transport.services import solve_remote
        from koordinator_tpu.transport.wire import (
            PROTOCOL_VERSION,
            FrameType,
        )

        asm = main_koord_scheduler([
            "--node-capacity", "64",
            "--listen-socket", str(tmp_path / "stress.sock"),
            "--disable-leader-election",
        ])
        n_writers, nodes_per_writer = 4, 8
        errors: list = []
        clients: list = []

        def client():
            c = RpcClient(asm.server.path, timeout=30.0)
            c.connect()
            clients.append(c)
            return c

        def push_nodes(w):
            try:
                c = client()
                for i in range(nodes_per_writer):
                    c.call(FrameType.STATE_PUSH,
                           {"kind": "node_upsert",
                            "name": f"w{w}-n{i}"},
                           {"allocatable": np.asarray(resource_vector(
                               cpu=16_000, memory=32_768), np.int32)})
                    c.call(FrameType.STATE_PUSH,
                           {"kind": "pod_add", "name": f"w{w}-p{i}"},
                           {"requests": np.asarray(resource_vector(
                               cpu=1_000, memory=1_024), np.int32)})
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        def solver():
            try:
                c = client()
                for _ in range(6):
                    solve_remote(c)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        def hello_storm():
            try:
                for _ in range(10):
                    c = RpcClient(asm.server.path, timeout=30.0)
                    c.connect()
                    c.call(FrameType.HELLO,
                           {"last_rv": -1, "proto": PROTOCOL_VERSION})
                    c.close()
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        try:
            threads = (
                [threading.Thread(target=push_nodes, args=(w,))
                 for w in range(n_writers)]
                + [threading.Thread(target=solver) for _ in range(2)]
                + [threading.Thread(target=hello_storm)]
            )
            for t in threads:
                t.start()
            for t in threads:
                t.join(120)
                assert not t.is_alive(), "stress thread wedged"
            assert not errors, errors[:3]

            # the service holds exactly the pushed universe, rv exact
            service = asm.state_sync
            assert service.rv == n_writers * nodes_per_writer * 2
            assert len(service.nodes) == n_writers * nodes_per_writer
            # and the binding applied everything: a final solve places
            # every remaining pod (capacity is ample)
            solve_remote(client())
            sched = asm.component
            assert not sched.pending, (
                f"{len(sched.pending)} pods never applied/solved")
        finally:
            for c in clients:
                c.close()
            asm.stop()


# -- koordlint debug-mode lock instrumentation -------------------------------


@pytest.fixture
def lock_recorder():
    """Debug-mode instrumented-lock fixture (tools/koordlint/runtime):
    wraps lock attributes in recording proxies so a test can assert the
    acquisition order real threads take against the STATIC lock-order
    graph the lock-discipline analyzer builds."""
    from tools.koordlint.runtime import LockOrderRecorder

    return LockOrderRecorder()


class _CountingBinding:
    """In-process sync subscriber with its own lock — the scheduler-
    binding shape: applies block on a private lock, never the service's."""

    def __init__(self):
        self.lock = threading.Lock()
        self.applied = 0

    def _bump(self):
        with self.lock:
            self.applied += 1

    def node_upsert(self, entry, arrs):
        self._bump()

    def node_usage(self, entry, arrs):
        self._bump()

    def node_remove(self, name):
        self._bump()

    def pod_add(self, entry, arrs):
        self._bump()

    def pod_remove(self, name):
        self._bump()


def test_lock_order_runtime_validates_static_graph(lock_recorder):
    """The static lock-order graph survives contact with real threads.

    Drives a StateSyncService (two locks: the RLock service lock and the
    binding-drain lock) plus an attached binding from N writer threads,
    with every lock wrapped in a recording proxy, then asserts:

    - the commit path's documented invariant holds at runtime: the
      service lock is NEVER held while the binding queue drains
      (deltasync._store_and_commit releases before _drain_bindings);
    - every observed acquisition edge merged with the lock-discipline
      analyzer's static edges still forms an acyclic graph — a dynamic
      order the analyzer could not see must not invert a static edge.
    """
    import os

    import koordinator_tpu
    from koordinator_tpu.api.resources import resource_vector
    from koordinator_tpu.transport.deltasync import StateSyncService
    from tools.koordlint.runtime import (
        find_cycle,
        instrument_locks,
        static_lock_edges,
    )

    service = StateSyncService()
    binding = _CountingBinding()
    service.attach_binding(binding)
    names = instrument_locks(service, lock_recorder)
    names += instrument_locks(binding, lock_recorder)
    SVC = "koordinator_tpu.transport.deltasync.StateSyncService"
    assert f"{SVC}._lock" in names
    assert f"{SVC}._binding_lock" in names

    alloc = np.asarray(resource_vector(cpu=8_000, memory=16_384), np.int32)
    req = np.asarray(resource_vector(cpu=500, memory=512), np.int32)

    def writer(w):
        for i in range(40):
            service.upsert_node(f"w{w}-n{i}", alloc)
            service.add_pod(f"w{w}-p{i}", req)
            if i % 4 == 0:
                service.remove_pod(f"w{w}-p{i}")

    hammer(writer)
    events = N_THREADS * (40 * 2 + 10)
    assert binding.applied == events            # no lost drains
    assert lock_recorder.acquisitions > events  # proxies really recorded

    observed = lock_recorder.edge_pairs()
    # the drain runs OUTSIDE the service lock — the deadlock-avoidance
    # invariant deltasync documents, proven against real interleaving
    assert (f"{SVC}._lock", f"{SVC}._binding_lock") not in observed
    assert any(src == f"{SVC}._binding_lock"
               and dst.endswith("_CountingBinding.lock")
               for src, dst in observed), observed

    root = os.path.dirname(os.path.dirname(
        os.path.abspath(koordinator_tpu.__file__)))
    static = static_lock_edges(root)
    assert static, "static lock graph unexpectedly empty"
    cycle = find_cycle(static | observed)
    assert cycle is None, f"static+observed lock graph has a cycle: {cycle}"
