"""Live-vs-replay parity, randomized.

Every round of review on the device-inventory wire loop found another
way for the IN-PROCESS scheduler's fine-grained registries (device
tensors, CPU topologies) to drift from what a bootstrap-replay client
would build — omitted-devices upserts, NODE_REMOVE, annotation loss,
resync.  This pins the invariant wholesale: apply a random event
sequence live, then bootstrap a FRESH scheduler over the real wire
path, and require identical registries and node sets.
"""

import json

import numpy as np
import pytest

from tests.conftest import prop_seeds

from koordinator_tpu.api.resources import resource_vector
from koordinator_tpu.transport import RpcClient, RpcServer
from koordinator_tpu.transport.deltasync import (
    SchedulerBinding,
    StateSyncClient,
    StateSyncService,
)


def _mk_sched():
    from koordinator_tpu.ops.assignment import ScoringConfig
    from koordinator_tpu.scheduler.cpu_manager import CPUManager
    from koordinator_tpu.scheduler.device_manager import DeviceManager
    from koordinator_tpu.scheduler.scheduler import Scheduler
    from koordinator_tpu.scheduler.snapshot import ClusterSnapshot

    snap = ClusterSnapshot(capacity=16)
    return Scheduler(snap, config=ScoringConfig.default(),
                     cpu_manager=CPUManager(),
                     device_manager=DeviceManager())


def _fingerprint(sched):
    """Registry state that must be identical live vs replayed: raw
    device inventory per type, CPU topology presence/shape per node,
    and the snapshot's node set."""
    dm, cm = sched.device_manager, sched.cpu_manager
    dev = {t: dict(sorted(raw.items()))
           for t, raw in sorted(dm._raw.items())}
    topo = {n: np.asarray(st.topology.core_of).tolist()
            for n, st in sorted(cm._nodes.items())}
    rsv = sorted((s.name, s.requests.tolist(), s.allocate_once)
                 for s in sched.reservations.specs())
    rows = sorted(
        (n, np.asarray(spec.allocatable).tolist(),
         np.asarray(spec.usage).tolist())
        for n, spec in sched.snapshot.node_specs.items())
    return dev, topo, sorted(sched.snapshot.node_index), rsv, rows


def _nrt(cores: int) -> dict:
    detail = [{"core": c // 2, "node": 0, "socket": 0, "id": c}
              for c in range(cores)]
    return {"node.koordinator.sh/cpu-topology":
            json.dumps({"detail": detail})}


@pytest.mark.parametrize("seed", prop_seeds(8))
def test_random_event_sequences_replay_identically(seed):
    rng = np.random.default_rng(seed)
    live = _mk_sched()
    service = StateSyncService()
    service.attach_binding(SchedulerBinding(live))

    known: set[str] = set()
    rsv_known: set[str] = set()
    pod_seq = 0
    for _ in range(120):
        op = int(rng.integers(0, 14))
        name = f"n{int(rng.integers(0, 6))}"
        if op <= 4:
            # upsert with randomly present/absent devices + NRT
            # annotation — the doc replaces stored state wholesale, so
            # omission must CLEAR live registries
            kw = {}
            if rng.random() < 0.5:
                count = int(rng.integers(1, 4))
                kw["devices"] = {"gpu": [
                    {"core": 100, "memory": 1 << 10, "group": 0}
                ] * count}
            if rng.random() < 0.5:
                kw["annotations"] = _nrt(int(rng.integers(2, 6)) * 2)
            service.upsert_node(
                name, resource_vector(cpu=8_000, memory=8_192), **kw)
            known.add(name)
        elif op <= 6 and known:
            target = sorted(known)[int(rng.integers(0, len(known)))]
            devices = ({} if rng.random() < 0.3 else
                       {"xpu": [{"core": 50, "memory": 1 << 9, "group": 0}]
                        * int(rng.integers(1, 3))})
            service.update_node_devices(target, devices)
        elif op <= 8 and known:
            target = sorted(known)[int(rng.integers(0, len(known)))]
            service.remove_node(target)
            known.discard(target)
        elif op == 9:
            service.add_pod(f"p{pod_seq}",
                            resource_vector(cpu=100, memory=64))
            pod_seq += 1
        elif op == 10:
            rname = f"r{int(rng.integers(0, 4))}"
            service.upsert_reservation(
                rname, resource_vector(cpu=500, memory=256),
                allocate_once=bool(rng.random() < 0.5),
                owners=[{"labels": {"app": rname}}])
            rsv_known.add(rname)
        elif op == 11 and rsv_known:
            target = sorted(rsv_known)[int(rng.integers(0, len(rsv_known)))]
            service.remove_reservation(target)
            rsv_known.discard(target)
        elif op == 12 and known:
            # the manager's node_allocatable patch: merged live AND into
            # the stored doc, so replay must see the same row
            target = sorted(known)[int(rng.integers(0, len(known)))]
            service.update_node_allocatable(target, resource_vector({
                "cpu": 8_000, "memory": 8_192,
                "kubernetes.io/batch-cpu": int(rng.integers(0, 6_000)),
                "kubernetes.io/batch-memory": int(rng.integers(0, 4_096)),
            }))
        elif op == 13 and known:
            target = sorted(known)[int(rng.integers(0, len(known)))]
            service.update_node_usage(
                target,
                resource_vector(cpu=int(rng.integers(0, 8_000)),
                                memory=int(rng.integers(0, 8_192))),
                sys_usage=resource_vector(cpu=100, memory=128),
                hp_usage=resource_vector(
                    cpu=int(rng.integers(0, 2_000)), memory=256))

    replay = _mk_sched()
    server = RpcServer("tcp://127.0.0.1:0")
    service.attach(server)
    server.start()
    try:
        sync = StateSyncClient(SchedulerBinding(replay))
        client = RpcClient(server.address, on_push=sync.on_push)
        client.connect()
        try:
            sync.bootstrap(client)
            assert sync.rv == service.rv
            assert _fingerprint(replay) == _fingerprint(live), (
                f"seed {seed}: live and bootstrap-replay registries "
                f"diverged")
        finally:
            client.close()
    finally:
        server.stop()


@pytest.mark.parametrize("seed", prop_seeds(4))
def test_fallen_behind_client_resyncs_to_parity(seed):
    """The OTHER replay entry point: a client that connected early,
    disconnected, and fell behind the bounded delta-log retention gets a
    full snapshot on reconnect — SchedulerBinding.reset() + replay must
    land on the same registries as the live applier, including clearing
    everything the early events had registered (reset() wiping the
    device/CPU registries was one of this round's fixed bugs)."""
    rng = np.random.default_rng(1000 + seed)
    live = _mk_sched()
    service = StateSyncService(retention=16)
    service.attach_binding(SchedulerBinding(live))

    server = RpcServer("tcp://127.0.0.1:0")
    service.attach(server)
    server.start()
    try:
        # early client: sees the first few events, then disconnects
        replay = _mk_sched()
        sync = StateSyncClient(SchedulerBinding(replay))
        # seed some state BEFORE the client joins, including registries
        # the later walk may remove entirely
        service.upsert_node("n0", resource_vector(cpu=8_000, memory=8_192),
                            devices={"gpu": [{"core": 100,
                                              "memory": 1 << 10,
                                              "group": 0}]},
                            annotations=_nrt(4))
        client = RpcClient(server.address, on_push=sync.on_push)
        client.connect()
        sync.bootstrap(client)
        client.close()            # misses everything from here on

        known = {"n0"}
        for _ in range(60):       # >> retention=16: forces ResyncRequired
            op = int(rng.integers(0, 10))
            name = f"n{int(rng.integers(0, 4))}"
            if op <= 5:
                kw = {}
                if rng.random() < 0.5:
                    kw["devices"] = {"gpu": [
                        {"core": 100, "memory": 1 << 10, "group": 0}]}
                if rng.random() < 0.5:
                    kw["annotations"] = _nrt(4)
                service.upsert_node(
                    name, resource_vector(cpu=8_000, memory=8_192), **kw)
                known.add(name)
            elif op <= 7 and known:
                target = sorted(known)[int(rng.integers(0, len(known)))]
                service.remove_node(target)
                known.discard(target)
            elif name in known:
                service.update_node_devices(
                    name, {"xpu": [{"core": 50, "memory": 1 << 9,
                                    "group": 0}]})

        # n0 — the node the early client registered devices + topology
        # for — must end ABSENT: a final-snapshot upsert of n0 would
        # repair stale registries via the full-inventory path, masking
        # a reset() that failed to clear them (mutation-verified: with
        # the clear() calls deleted, the test only fails because of
        # this removal)
        if "n0" in known:
            service.remove_node("n0")

        client2 = RpcClient(server.address, on_push=sync.on_push)
        client2.connect()
        try:
            sync.bootstrap(client2)   # behind retention -> full snapshot
            assert sync.rv == service.rv
            assert _fingerprint(replay) == _fingerprint(live), (
                f"seed {seed}: resync replay diverged from live")
        finally:
            client2.close()
    finally:
        server.stop()
