"""JAX solver introspection (ISSUE 5): recompiles, device bytes, profiler.

The acceptance recompile test: changing a batch shape bucket increments
``solver_recompiles_total`` exactly as expected — and same-shape rounds
increment nothing; the device-bytes gauge matches ``nbytes`` of the live
``ClusterState``/``CandidateCache`` arrays.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from koordinator_tpu import metrics
from koordinator_tpu.api.resources import resource_vector
from koordinator_tpu.ops import introspection as insp
from koordinator_tpu.scheduler import ClusterSnapshot, Scheduler
from koordinator_tpu.scheduler.snapshot import NodeSpec, PodSpec


def recompile_totals() -> dict:
    """{(fn, shape): count} snapshot of solver_recompiles_total."""
    return {(labels["fn"], labels["shape"]): value
            for labels, value in metrics.solver_recompiles.items()}


class TestInstrumentedJit:
    def test_counts_misses_per_shape_bucket(self):
        fn = insp.instrument(
            jax.jit(lambda x: x + 1), "plus_one",
            shape_of=lambda a, k: f"N{a[0].shape[0]}")
        before = recompile_totals()

        out = fn(jnp.zeros(4))
        assert out.shape == (4,)
        assert metrics.solver_recompiles.value(
            {"fn": "plus_one", "shape": "N4"}) == before.get(
                ("plus_one", "N4"), 0) + 1
        fn(jnp.ones(4))    # warm: same shape, no miss
        assert metrics.solver_recompiles.value(
            {"fn": "plus_one", "shape": "N4"}) == before.get(
                ("plus_one", "N4"), 0) + 1
        fn(jnp.zeros(8))   # new shape bucket: one miss
        assert metrics.solver_recompiles.value(
            {"fn": "plus_one", "shape": "N8"}) == 1
        assert fn.misses == 2
        assert metrics.solver_jit_cache_size.value(
            {"fn": "plus_one"}) == 2.0

    def test_default_shape_label_and_shape_of_failure(self):
        label = insp.default_shape_of((jnp.zeros((4, 2)), jnp.zeros(3)), {})
        assert "4x2" in label and "3" in label

        def broken_shape_of(a, k):
            raise RuntimeError("labeling bug")

        fn = insp.instrument(jax.jit(lambda x: x * 2), "twice",
                             shape_of=broken_shape_of)
        fn(jnp.zeros(2))   # the solve must survive a labeling bug
        assert metrics.solver_recompiles.value(
            {"fn": "twice", "shape": "unknown"}) == 1

    def test_uninstrumentable_fn_degrades_to_passthrough(self):
        fn = insp.instrument(lambda x: x + 1, "plain")
        assert fn(41) == 42
        assert fn.misses == 0

    def test_device_bytes_sums_leaf_nbytes(self):
        from koordinator_tpu.state.cluster_state import ClusterState

        state = ClusterState.zeros(16)
        expect = sum(int(leaf.nbytes) for leaf in jax.tree.leaves(state))
        assert insp.device_bytes(state) == expect
        assert insp.device_bytes(None) == 0


class TestSchedulerRecompileAccounting:
    """The acceptance test: shape-bucket changes produce exactly the
    expected increments; same-shape rounds produce zero."""

    def make_sched(self, **kw):
        snap = ClusterSnapshot(capacity=64)
        snap.upsert_node(NodeSpec(
            name="n0",
            allocatable=resource_vector(cpu=10_000_000,
                                        memory=10_000_000)))
        return Scheduler(snap, batch_solver_threshold=1, **kw)

    def enqueue_n(self, sched, n, prefix):
        for i in range(n):
            sched.enqueue(PodSpec(
                name=f"{prefix}{i}",
                requests=resource_vector(cpu=100, memory=64)))

    def test_full_path_exact_increments_on_shape_change(self):
        sched = self.make_sched(incremental_solve=False)
        # 20 pods -> pod bucket 32 (power-of-two, min 16)
        self.enqueue_n(sched, 20, "a")
        sched.schedule_round()
        after_cold = recompile_totals()
        assert after_cold[("gang_assign", "P32xN64")] == 1

        # same shape bucket again: ZERO increments anywhere
        self.enqueue_n(sched, 20, "b")
        sched.schedule_round()
        assert recompile_totals() == after_cold

        # 40 pods -> bucket 64: exactly ONE increment, on gang_assign's
        # new shape label (the only jitted entry the full path runs)
        self.enqueue_n(sched, 40, "c")
        sched.schedule_round()
        after_grow = recompile_totals()
        delta = {k: v - after_cold.get(k, 0) for k, v in after_grow.items()
                 if v != after_cold.get(k, 0)}
        assert delta == {("gang_assign", "P64xN64"): 1}

    def test_incremental_path_warm_rounds_add_zero(self):
        sched = self.make_sched()
        # round 1 compiles the cold path (select + pass1); round 2 is
        # the first with a live candidate cache, compiling the align
        # kernel — the steady-state working set is warm after it
        self.enqueue_n(sched, 20, "a")
        sched.schedule_round()
        assert any(fn == "assign_round_pass" and shape.startswith("P32")
                   for fn, shape in recompile_totals())
        self.enqueue_n(sched, 20, "b")
        sched.schedule_round()
        warm = recompile_totals()
        # same-shape steady state: the whole pipeline re-runs with
        # ZERO further misses across rounds
        for batch in ("c", "d"):
            self.enqueue_n(sched, 20, batch)
            sched.schedule_round()
        assert recompile_totals() == warm

    def test_device_bytes_gauge_matches_live_arrays(self):
        sched = self.make_sched()
        self.enqueue_n(sched, 20, "a")
        sched.schedule_round()
        assert metrics.solver_device_bytes.value(
            {"kind": "cluster_state"}) == float(
                insp.device_bytes(sched.snapshot.state))
        cand = sched._cand_cache
        assert cand is not None
        assert metrics.solver_device_bytes.value(
            {"kind": "candidate_cache"}) == float(
                insp.device_bytes(cand["cache"]))
        assert metrics.solver_device_bytes.value(
            {"kind": "candidate_cache"}) > 0

    def test_padding_waste_fraction(self):
        sched = self.make_sched()
        self.enqueue_n(sched, 20, "a")   # bucket 32 -> 12/32 wasted
        sched.schedule_round()
        assert metrics.solver_batch_padding_waste.value() == pytest.approx(
            1.0 - 20 / 32)


class TestProfilerCapture:
    def test_gate_off_by_default(self):
        cap = insp.ProfilerCapture()
        with pytest.raises(insp.ProfileDisabled):
            cap.capture(0.01)

    def test_capture_with_stub_profiler(self, tmp_path):
        calls = []

        class StubProfiler:
            def start_trace(self, out_dir):
                calls.append(("start", out_dir))

            def stop_trace(self):
                calls.append(("stop", None))

        cap = insp.ProfilerCapture(
            enabled=True, out_dir=str(tmp_path), max_seconds=5.0,
            profiler=StubProfiler(), sleep=lambda s: calls.append(
                ("sleep", s)))
        out = cap.capture(2.0)
        assert out == {"dir": str(tmp_path), "seconds": 2.0}
        assert [c[0] for c in calls] == ["start", "sleep", "stop"]
        assert cap.captures == 1

    def test_seconds_clamped_to_max(self, tmp_path):
        class StubProfiler:
            def start_trace(self, out_dir):
                pass

            def stop_trace(self):
                pass

        slept = []
        cap = insp.ProfilerCapture(
            enabled=True, out_dir=str(tmp_path), max_seconds=0.5,
            profiler=StubProfiler(), sleep=slept.append)
        assert cap.capture(600.0)["seconds"] == 0.5
        assert slept == [0.5]

    def test_stop_trace_runs_even_when_sleep_dies(self, tmp_path):
        calls = []

        class StubProfiler:
            def start_trace(self, out_dir):
                calls.append("start")

            def stop_trace(self):
                calls.append("stop")

        def bad_sleep(s):
            raise KeyboardInterrupt

        cap = insp.ProfilerCapture(
            enabled=True, out_dir=str(tmp_path),
            profiler=StubProfiler(), sleep=bad_sleep)
        with pytest.raises(KeyboardInterrupt):
            cap.capture(0.1)
        assert calls == ["start", "stop"]
        # the lock released: a next capture is not spuriously busy
        cap._sleep = lambda s: None
        assert cap.capture(0.1)["seconds"] == 0.1

    def test_debug_profile_routes_when_enabled(self):
        from koordinator_tpu.scheduler.services import DebugService

        class StubProfiler:
            def start_trace(self, out_dir):
                pass

            def stop_trace(self):
                pass

        snap = ClusterSnapshot(capacity=8)
        snap.upsert_node(NodeSpec(
            name="n0", allocatable=resource_vector(cpu=1000, memory=1000)))
        sched = Scheduler(snap)
        service = DebugService(sched)
        # gate off (the default): 403
        status, body = service.handle("/debug/profile", {"seconds": 0.01})
        assert status == 403
        # armed: the capture runs and returns its artifact dir
        sched.profile_capture = insp.ProfilerCapture(
            enabled=True, out_dir="/tmp/x", profiler=StubProfiler(),
            sleep=lambda s: None)
        status, body = service.handle("/debug/profile", {"seconds": 0.25})
        assert status == 200
        assert body == {"dir": "/tmp/x", "seconds": 0.25}
        status, body = service.handle("/debug/profile",
                                      {"seconds": "nope"})
        assert status == 400
        # nan parses as a float but must not start a trace (it would
        # die inside sleep() as a blanket 500)
        status, body = service.handle("/debug/profile",
                                      {"seconds": "nan"})
        assert status == 400

class TestShardedIntrospection:
    """ISSUE 10 satellite: per-shard device bytes, collective counts,
    the solver_shard_count gauge, and the /debug/slo sharding section."""

    def test_device_bytes_by_shard_single_device(self):
        a = jnp.zeros((16, 4), jnp.int32)
        by = insp.device_bytes_by_shard(a)
        assert sum(by.values()) == a.nbytes and len(by) == 1
        assert insp.device_bytes_by_shard(None) == {}

    def test_device_bytes_by_shard_sharded_and_replicated(self):
        from koordinator_tpu.parallel import mesh as pmesh

        mesh = pmesh.solver_mesh()
        sharded = jax.device_put(jnp.zeros((64, 4), jnp.int32),
                                 pmesh.node_sharding(mesh))
        by = insp.device_bytes_by_shard(sharded)
        # node-sharded: the slices sum to the global footprint, spread
        # over every device of the mesh
        assert sum(by.values()) == sharded.nbytes
        assert len(by) == len(jax.devices())
        rep = jax.device_put(
            jnp.zeros((8,), jnp.int32),
            jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec()))
        by_rep = insp.device_bytes_by_shard(rep)
        # replicated: every device honestly pays a full copy
        assert all(v == rep.nbytes for v in by_rep.values())

    def test_collective_counts_parses_hlo(self):
        txt = """
  %ag = s32[4,8]{1,0} all-gather(s32[4,1]{1,0} %x), replica_groups={}
  %ar.1 = s32[4]{0} all-reduce(s32[4]{0} %y), to_apply=%sum
  %ars = s32[2]{0} reduce-scatter(s32[4]{0} %z), to_apply=%sum
  %not_a_match = s32[] add(s32[] %a, s32[] %b)
"""
        got = insp.collective_counts(txt)
        assert got == {"all-gather": 1, "all-reduce": 1,
                       "reduce-scatter": 1}

    def test_compiled_collectives_counts_sharded_psum(self):
        from koordinator_tpu.parallel import mesh as pmesh
        from koordinator_tpu.parallel import sharded as ps

        mesh = pmesh.solver_mesh()
        from functools import partial

        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        fn = jax.jit(shard_map(
            lambda x: jax.lax.psum(x.sum(), ps.NODES_AXIS),
            mesh=mesh, in_specs=(P("nodes"),), out_specs=P(),
            check_rep=False))
        got = insp.compiled_collectives(fn, jnp.zeros((64,), jnp.int32))
        assert got.get("all-reduce", 0) >= 1, got

    def test_sharding_report_and_debug_slo_section(self):
        from types import SimpleNamespace

        from koordinator_tpu.scheduler.services import debug_slo_body

        snap = ClusterSnapshot(capacity=64)
        sched = Scheduler(snap, shard_min_nodes=0)
        assert sched.solver_shard_count == len(jax.devices())
        report = sched.sharding_report()
        assert report["active"] and report["mesh"]["nodes"] == 8
        assert "cluster_state" in report["device_bytes_by_shard"]
        assert len(report["device_bytes_by_shard"]["cluster_state"]) == 8
        sched.slo_monitor = SimpleNamespace(report=lambda: {"slos": []})
        body = debug_slo_body(sched)
        assert body["sharding"]["solver_shard_count"] == 8
        # mesh off => the report says so and the gauge path reads 1
        single = Scheduler(ClusterSnapshot(capacity=64), mesh="off")
        rep = single.sharding_report()
        assert rep["solver_shard_count"] == 1 and rep["mesh"] is None

    def test_solver_shard_count_gauge_set_per_round(self):
        snap = ClusterSnapshot(capacity=64)
        snap.upsert_node(NodeSpec(
            name="n0", allocatable=resource_vector(cpu=10_000,
                                                   memory=10_000)))
        sched = Scheduler(snap, batch_solver_threshold=1,
                          shard_min_nodes=0)
        sched.enqueue(PodSpec(
            name="p0", requests=resource_vector(cpu=100, memory=64)))
        sched.schedule_round()
        assert metrics.solver_shard_count.value() == float(
            len(jax.devices()))
        # per-shard byte rows carry the shard label
        assert any("shard" in labels
                   for labels, _ in metrics.solver_device_bytes.items())
