"""PredictServer, RecommendationController, runtime proxy, device daemon."""

import os

import pytest

from koordinator_tpu.api import crds, extension as ext
from koordinator_tpu.api.qos import QoSClass
from koordinator_tpu.koordlet import metriccache as mc
from koordinator_tpu.koordlet.prediction_server import (
    BAND_UIDS, MIB, PredictServer, UID_NODE,
)
from koordinator_tpu.koordlet.statesinformer import PodMeta, StatesInformer
from koordinator_tpu.manager.recommendation import RecommendationController
from tests.test_koordlet_metrics import FakeClock

from koordinator_tpu.api.priority import PriorityClass


def prod_pod(uid, priority=9500):
    return PodMeta(uid=uid, name=uid, namespace="d", qos_class=QoSClass.LS,
                   kube_qos="burstable", priority=priority)


class TestPredictServer:
    def make(self, clock, tmp_path=None):
        cache = mc.MetricCache(clock=clock)
        states = StatesInformer(metric_cache=cache, clock=clock)
        server = PredictServer(
            states, cache,
            checkpoint_dir=str(tmp_path) if tmp_path else None,
            capacity=16, clock=clock,
        )
        return server, states, cache

    def feed(self, server, states, cache, clock, steps=30, cpu_cores=2.0):
        pod = prod_pod("p1")
        states.set_pods([pod])
        for _ in range(steps):
            cache.append(mc.NODE_CPU_USAGE, cpu_cores * 2)
            cache.append(mc.NODE_MEMORY_USAGE, 4096 * MIB)
            cache.append(mc.POD_CPU_USAGE, cpu_cores, {"pod_uid": "p1"})
            cache.append(mc.POD_MEMORY_USAGE, 1024 * MIB, {"pod_uid": "p1"})
            server.train_once()
            clock.tick(60)

    def test_training_and_peak(self, clock=None):
        clock = FakeClock()
        server, states, cache = self.make(clock)
        self.feed(server, states, cache, clock)
        # cold start passed (30 min simulated)
        peak = server.peak("p1")
        assert peak is not None
        cpu_peak, mem_peak = peak
        # ~2000 mcores with 10% margin, bucket granularity 5%
        assert 2000 <= cpu_peak <= 2600
        assert 1024 <= mem_peak <= 1350
        node_peak = server.peak(UID_NODE)
        assert node_peak[0] >= 4000

    def test_cold_start_returns_none(self):
        clock = FakeClock()
        server, states, cache = self.make(clock)
        pod = prod_pod("p1")
        states.set_pods([pod])
        cache.append(mc.POD_CPU_USAGE, 1.0, {"pod_uid": "p1"})
        server.train_once()
        assert server.peak("p1") is None

    def test_band_aggregation(self):
        clock = FakeClock()
        server, states, cache = self.make(clock)
        states.set_pods([prod_pod("p1"), prod_pod("p2"),
                         prod_pod("b1", priority=5500)])
        for _ in range(30):
            for uid, cores in (("p1", 1.0), ("p2", 2.0), ("b1", 4.0)):
                cache.append(mc.POD_CPU_USAGE, cores, {"pod_uid": uid})
                cache.append(mc.POD_MEMORY_USAGE, 100 * MIB, {"pod_uid": uid})
            server.train_once()
            clock.tick(60)
        prod_peak = server.peak(BAND_UIDS[PriorityClass.PROD])
        batch_peak = server.peak(BAND_UIDS[PriorityClass.BATCH])
        assert 3000 <= prod_peak[0] <= 3700   # 1+2 cores
        assert 4000 <= batch_peak[0] <= 4900  # 4 cores

    def test_gc_frees_rows(self):
        clock = FakeClock()
        server, states, cache = self.make(clock)
        states.set_pods([prod_pod(f"p{i}") for i in range(5)])
        for i in range(5):
            cache.append(mc.POD_CPU_USAGE, 1.0, {"pod_uid": f"p{i}"})
        server.train_once()
        free_before = len(server._free_rows)
        states.set_pods([prod_pod("p0")])
        assert server.gc() == 4
        assert len(server._free_rows) == free_before + 4

    def test_checkpoint_restore(self, tmp_path):
        clock = FakeClock()
        server, states, cache = self.make(clock, tmp_path)
        self.feed(server, states, cache, clock)
        server.checkpoint()
        peak_before = server.peak("p1")

        clock2 = FakeClock(t=clock.t)
        cache2 = mc.MetricCache(clock=clock2)
        states2 = StatesInformer(metric_cache=cache2, clock=clock2)
        restored = PredictServer(states2, cache2, checkpoint_dir=str(tmp_path),
                                 capacity=16, clock=clock2)
        assert restored._rows == server._rows
        assert restored.peak("p1") == peak_before

    def test_capacity_exhaustion_drops_new(self):
        clock = FakeClock()
        server, states, cache = self.make(clock)
        pods = [prod_pod(f"p{i}") for i in range(20)]  # capacity 16
        states.set_pods(pods)
        for p in pods:
            cache.append(mc.POD_CPU_USAGE, 1.0, {"pod_uid": p.uid})
        ingested = server.train_once()
        assert ingested <= 16
        assert len(server._free_rows) == 0


class TestRecommendation:
    def test_recommend_from_observations(self):
        clock = FakeClock()
        controller = RecommendationController(clock=clock, margin_pct=15)
        for _ in range(50):
            controller.observe([
                ("Deployment/web", 500.0, 256.0),
                ("Deployment/api", 2000.0, 1024.0),
            ])
            clock.tick(60)
        recs = {r.workload_ref: r for r in controller.recommend_all()}
        assert 500 <= recs["Deployment/web"].target_cpu_milli <= 650
        assert 2000 <= recs["Deployment/api"].target_cpu_milli <= 2600
        assert recs["Deployment/api"].target_memory_bytes >= 1024 * MIB


class TestRuntimeProxy:
    def make(self):
        from koordinator_tpu.runtimeproxy import (
            CRIProxy, Dispatcher, FailoverStore, HookRequest, HookResponse,
            HookType,
        )

        calls = []

        class Hook:
            def handle(self, hook, request):
                calls.append(hook)
                return HookResponse(
                    annotations={"hooked": hook.value},
                    envs={"BVT": "-1"},
                )

        dispatcher = Dispatcher()
        dispatcher.register(Hook(), list(HookType))
        forwarded = []
        backend = {
            name: (lambda req, n=name: forwarded.append(n) or "ok")
            for name in ("RunPodSandbox", "CreateContainer", "StartContainer",
                         "UpdateContainerResources", "StopPodSandbox")
        }
        proxy = CRIProxy(dispatcher, FailoverStore(), backend)
        return proxy, calls, forwarded, HookRequest, HookType

    def test_hook_then_forward(self):
        proxy, calls, forwarded, HookRequest, HookType = self.make()
        request = HookRequest(pod_meta={"name": "p1"})
        assert proxy.run_pod_sandbox("pod1", request) == "ok"
        assert request.annotations["hooked"] == "PreRunPodSandbox"
        assert forwarded == ["RunPodSandbox"]
        proxy.create_container("c1", HookRequest())
        proxy.start_container("c1")
        assert HookType.POST_START_CONTAINER in calls
        # failover store kept the container request for start
        assert forwarded == ["RunPodSandbox", "CreateContainer", "StartContainer"]

    def test_fail_open(self):
        from koordinator_tpu.runtimeproxy import (
            CRIProxy, Dispatcher, FailoverStore, HookRequest, HookType,
        )

        class Broken:
            def handle(self, hook, request):
                raise RuntimeError("hook server down")

        dispatcher = Dispatcher()
        dispatcher.register(Broken(), list(HookType))
        proxy = CRIProxy(dispatcher, FailoverStore(),
                         {"RunPodSandbox": lambda r: "ok"})
        assert proxy.run_pod_sandbox("p", HookRequest()) == "ok"

    def test_stop_cleans_store(self):
        proxy, calls, forwarded, HookRequest, HookType = self.make()
        proxy.run_pod_sandbox("pod1", HookRequest())
        assert proxy.store.get_pod("pod1") is not None
        proxy.stop_pod_sandbox("pod1")
        assert proxy.store.get_pod("pod1") is None


class TestDeviceDaemon:
    def test_sysfs_probing(self, tmp_path):
        from koordinator_tpu.device_daemon import DeviceDaemon

        gpu_dir = tmp_path / "bus" / "pci" / "drivers" / "nvidia" / "0000:3b:00.0"
        gpu_dir.mkdir(parents=True)
        (gpu_dir / "numa_node").write_text("1")
        accel = tmp_path / "class" / "accel" / "accel0"
        accel.mkdir(parents=True)
        ib = tmp_path / "class" / "infiniband" / "mlx5_0" / "device"
        ib.mkdir(parents=True)
        (ib / "numa_node").write_text("0")

        daemon = DeviceDaemon("n1", sys_root=str(tmp_path))
        device = daemon.collect()
        kinds = sorted(d.type for d in device.devices)
        assert kinds == ["gpu", "rdma", "xpu"]
        gpu = next(d for d in device.devices if d.type == "gpu")
        assert gpu.numa_node == 1 and gpu.busid == "0000:3b:00.0"
        assert "scheduling.koordinator.sh/gpu-partitions" in device.annotations

    def test_empty_host(self, tmp_path):
        from koordinator_tpu.device_daemon import DeviceDaemon

        device = DeviceDaemon("n1", sys_root=str(tmp_path)).collect()
        assert device.devices == ()
        assert device.annotations == {}
