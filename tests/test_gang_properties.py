"""Randomized invariants of gang (all-or-nothing) assignment.

test_gang.py pins the coscheduling scenarios at hand-built shapes; this
sweeps random clusters, gang structures, and both solver engines,
asserting the contract for ANY input:

  (atomic)   a valid gang places either >= min_member pods or none
  (group)    gangs sharing a gang-group live or die together: if any
             valid gang in a group missed its min, every gang in that
             group places nothing
  (enqueue)  a gang with fewer pending members than min_member never
             places anything (PreEnqueue parity)
  (capacity) node_requested never exceeds allocatable
"""

import jax.numpy as jnp
import numpy as np
import pytest

from tests.conftest import prop_seeds

from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS, ResourceDim
from koordinator_tpu.ops.assignment import ScoringConfig
from koordinator_tpu.ops.gang import GangInfo, gang_assign
from koordinator_tpu.state.cluster_state import ClusterState, PodBatch

R = NUM_RESOURCE_DIMS
CPU, MEM = ResourceDim.CPU, ResourceDim.MEMORY


def _plain_cfg():
    return ScoringConfig.default().replace(
        usage_thresholds=jnp.zeros(R, jnp.int32),
        estimator_defaults=jnp.zeros(R, jnp.int32))


def _random_problem(rng: np.random.Generator):
    n_nodes = int(rng.integers(2, 8))
    alloc = np.zeros((n_nodes, R), np.int32)
    # tight-ish capacity so some gangs genuinely fail
    alloc[:, CPU] = rng.integers(2_000, 10_000, n_nodes)
    alloc[:, MEM] = rng.integers(4_096, 32_768, n_nodes)
    state = ClusterState.from_arrays(alloc, capacity=n_nodes)

    n_gangs = int(rng.integers(1, 5))
    members = rng.integers(1, 6, n_gangs)
    # min_member sometimes above the actual member count (gang can
    # never be ready) and sometimes below (surplus members)
    min_member = np.maximum(
        1, members + rng.integers(-2, 3, n_gangs)).astype(np.int32)
    group_id = rng.integers(0, max(1, n_gangs - 1),
                            n_gangs).astype(np.int32)
    gangs = GangInfo.build(min_member, group_id=group_id)

    n_loose = int(rng.integers(0, 6))
    n_pods = int(members.sum()) + n_loose
    req = np.zeros((n_pods, R), np.int32)
    req[:, CPU] = rng.integers(200, 3_000, n_pods)
    req[:, MEM] = rng.integers(128, 4_096, n_pods)
    gang_ids = np.full(n_pods, -1, np.int32)
    i = 0
    for g, m in enumerate(members):
        gang_ids[i:i + m] = g
        i += m
    pris = rng.integers(3_000, 10_000, n_pods).astype(np.int32)
    pods = PodBatch.build(req, priority=pris, gang_id=gang_ids,
                          node_capacity=n_nodes)
    return state, pods, gangs, members


@pytest.mark.parametrize("seed", prop_seeds(12))
@pytest.mark.parametrize("solver", ["greedy", "batch"])
def test_gang_invariants(seed, solver):
    rng = np.random.default_rng(seed)
    state, pods, gangs, members = _random_problem(rng)

    asn, st, _ = gang_assign(state, pods, _plain_cfg(), gangs,
                             passes=2, solver=solver)
    asn = np.asarray(asn)
    valid = np.asarray(pods.valid)
    gang_ids = np.asarray(pods.gang_id)
    placed = (asn >= 0) & valid

    # (capacity)
    assert (np.asarray(st.node_requested)
            <= np.asarray(st.node_allocatable)).all(), f"seed {seed}"

    mm = np.asarray(gangs.min_member)
    gvalid = np.asarray(gangs.valid)
    groups = np.asarray(gangs.group_id)
    pending = np.bincount(gang_ids[valid & (gang_ids >= 0)],
                          minlength=gangs.capacity)
    counts = np.bincount(gang_ids[placed & (gang_ids >= 0)],
                         minlength=gangs.capacity)

    for g in range(gangs.capacity):
        if not gvalid[g]:
            continue
        # (atomic)
        assert counts[g] == 0 or counts[g] >= mm[g], (
            f"seed {seed} {solver}: gang {g} placed {counts[g]} "
            f"< min {mm[g]}")
        # (enqueue)
        if pending[g] < mm[g]:
            assert counts[g] == 0, (
                f"seed {seed} {solver}: unready gang {g} placed pods")

    # (group): any missed gang zeroes its whole group
    satisfied = counts >= mm
    for grp in np.unique(groups[gvalid]):
        in_group = gvalid & (groups == grp)
        if (~satisfied & in_group).any():
            assert counts[in_group].sum() == 0, (
                f"seed {seed} {solver}: group {grp} partially placed "
                f"{counts[in_group]}")
