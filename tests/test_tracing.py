"""End-to-end tracing + round flight recorder (ISSUE 3).

Covers the tracing core (spans, context, exporters), the scheduler's
pod/round instrumentation (flight recorder, wall-vs-device solve split,
debug endpoints), and the acceptance flow: one trace_id emitted at
``Scheduler.enqueue`` observable in spans from the scheduler, manager,
and koordlet services over real sockets — including across a
fault-injected reconnect/resync.
"""

import json
import time
import urllib.request

import numpy as np
import pytest

from koordinator_tpu import metrics, tracing
from koordinator_tpu.api.resources import resource_vector
from koordinator_tpu.scheduler import ClusterSnapshot, Scheduler
from koordinator_tpu.scheduler.snapshot import NodeSpec, PodSpec
from koordinator_tpu.transport import (
    RpcClient,
    RpcServer,
    StateSyncClient,
    StateSyncService,
)
from koordinator_tpu.transport.deltasync import SchedulerBinding
from koordinator_tpu.transport.services import SolveService, solve_remote
from koordinator_tpu.transport.wire import FrameType


@pytest.fixture
def collector():
    col = tracing.InMemoryExporter()
    tracing.TRACER.add_exporter(col)
    yield col
    tracing.TRACER.remove_exporter(col)


def wait_until(predicate, timeout=5.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


def make_sched(capacity=8, **kw):
    snap = ClusterSnapshot(capacity=capacity)
    snap.upsert_node(NodeSpec(
        name="n0", allocatable=resource_vector(cpu=16_000, memory=16_384)))
    return Scheduler(snap, **kw)


def pod_spec(name, cpu=1_000):
    return PodSpec(name=name,
                   requests=resource_vector(cpu=cpu, memory=1_024))


# ---- tracing core ----------------------------------------------------------

class TestTracingCore:
    def test_span_nesting_and_context(self, collector):
        with tracing.TRACER.span("outer", service="a") as outer:
            assert tracing.current_context().span_id == outer.span_id
            with tracing.TRACER.span("inner") as inner:
                assert inner.trace_id == outer.trace_id
                assert inner.parent_id == outer.span_id
        assert tracing.current_context() is None
        names = [s.name for s in collector.spans]
        assert names == ["inner", "outer"]  # inner ends first
        assert collector.spans[0].duration_s is not None

    def test_activate_remote_parent_and_noop(self, collector):
        ctx = tracing.TraceContext(trace_id="t" * 32, span_id="s" * 16)
        with tracing.activate(ctx):
            with tracing.TRACER.span("child") as sp:
                assert sp.trace_id == "t" * 32
                assert sp.parent_id == "s" * 16
            # activate(None) must NOT clobber the ambient context
            with tracing.activate(None):
                assert tracing.current_context().trace_id == "t" * 32

    def test_inject_extract_roundtrip(self):
        with tracing.TRACER.span("op") as sp:
            doc = tracing.inject({"kind": "pod_add"})
            assert doc[tracing.TRACE_DOC_KEY]["trace_id"] == sp.trace_id
            ctx = tracing.extract(doc)
            assert ctx.span_id == sp.span_id
            assert tracing.TRACE_DOC_KEY not in doc  # popped like deadline_ms
        # no active trace: inject is a no-op passthrough (same object)
        base = {"kind": "pod_add"}
        assert tracing.inject(base) is base

    def test_malformed_context_drops_silently(self):
        for bad in (None, "x", 7, {}, {"trace_id": 1, "span_id": "s"},
                    {"trace_id": "", "span_id": "s"}):
            assert tracing.TraceContext.from_doc(bad) is None
        assert tracing.TraceContext.from_annotation("{not json") is None

    def test_annotation_roundtrip(self):
        ctx = tracing.TraceContext(trace_id="ab" * 16, span_id="cd" * 8)
        assert tracing.TraceContext.from_annotation(
            ctx.to_annotation()) == ctx

    def test_span_error_status(self, collector):
        with pytest.raises(ValueError):
            with tracing.TRACER.span("boom"):
                raise ValueError("nope")
        assert collector.spans[-1].status == "error"

    def test_jsonl_exporter(self, tmp_path, collector):
        path = tmp_path / "trace.jsonl"
        exp = tracing.JsonlExporter(str(path))
        tracing.TRACER.add_exporter(exp)
        try:
            with tracing.TRACER.span("written", service="svc"):
                pass
        finally:
            tracing.TRACER.remove_exporter(exp)
        lines = path.read_text().splitlines()
        doc = json.loads(lines[-1])
        assert doc["name"] == "written" and doc["service"] == "svc"
        assert doc["duration_s"] >= 0

    def test_exporter_failure_never_breaks_the_operation(self, collector):
        class Broken:
            def export(self, span):
                raise RuntimeError("exporter bug")

        broken = Broken()
        tracing.TRACER.add_exporter(broken)
        try:
            with tracing.TRACER.span("survives"):
                pass
        finally:
            tracing.TRACER.remove_exporter(broken)
        assert collector.spans[-1].name == "survives"
        assert tracing.TRACER.export_errors >= 1


# ---- scheduler instrumentation ---------------------------------------------

class TestSchedulerTracing:
    def test_pod_trace_enqueue_to_bind(self, collector):
        sched = make_sched(trace_pods=True)
        sched.enqueue(pod_spec("p0"))
        trace_id = sched.pod_trace_id("p0")
        assert trace_id is not None
        sched.schedule_round()
        spans = tracing.TRACER.spans_for_trace(trace_id)
        names = [s.name for s in spans]
        assert names == ["scheduler.enqueue", "scheduler.bind"]
        bind = spans[-1]
        assert bind.parent_id == spans[0].span_id
        assert bind.attributes["node"] == "n0"
        # the bind annotation the shell carries onto the pod object
        ann = sched.resource_status["p0"][tracing.TRACE_ANNOTATION]
        assert tracing.TraceContext.from_annotation(
            ann).trace_id == trace_id

    def test_untraced_pods_pay_no_pod_spans(self, collector):
        sched = make_sched()  # trace_pods off, no ambient context
        sched.enqueue(pod_spec("p0"))
        assert sched.pod_trace_id("p0") is None
        sched.schedule_round()
        assert not collector.find(name="scheduler.enqueue")
        assert not collector.find(name="scheduler.bind")
        # the round span still exists
        assert collector.find(name="scheduler.round")

    def test_propagated_context_always_traces(self, collector):
        sched = make_sched()  # trace_pods off
        with tracing.TRACER.span("submit") as sp:
            sched.enqueue(pod_spec("p0"))
        assert sched.pod_trace_id("p0") == sp.trace_id

    def test_round_span_and_phase_children(self, collector):
        sched = make_sched(trace_pods=True)
        sched.enqueue(pod_spec("p0"))
        sched.schedule_round()
        rounds = collector.find(name="scheduler.round")
        assert len(rounds) == 1
        round_span = rounds[0]
        phases = [s for s in collector.spans
                  if s.name.startswith("phase.")
                  and s.trace_id == round_span.trace_id]
        assert {"phase.Solve", "phase.Bind"} <= {s.name for s in phases}
        assert all(s.parent_id == round_span.span_id for s in phases)
        # wall-vs-device split on the round span
        attrs = round_span.attributes
        assert attrs["solve_wall_s"] > 0
        assert attrs["solve_device_s"] > 0
        assert attrs["solve_wall_s"] >= attrs["solve_device_s"]

    def test_flight_recorder_record_and_slow_dump(self, collector):
        from koordinator_tpu.scheduler.monitor import SchedulerMonitor

        # a tiny timeout makes every round "slow": the dump fires
        sched = make_sched(monitor=SchedulerMonitor(timeout_sec=1e-9))
        before = metrics.round_flight_dumps.value(labels={"reason": "slow"})
        sched.enqueue(pod_spec("p0"))
        sched.schedule_round()
        rec = sched.flight_recorder.last()
        assert rec is not None
        assert rec.dump_reason == "slow"
        assert rec.placed == 1 and rec.pods == 1
        assert rec.solve_wall_s > 0 and rec.solve_device_s > 0
        assert rec.phase_s["Solve"] == rec.solve_wall_s
        assert metrics.round_flight_dumps.value(
            labels={"reason": "slow"}) == before + 1
        assert sched.flight_recorder.slowest()["round"] == rec.round

    def test_flight_ring_overwrite_is_counted(self, collector):
        """Records evicted by ring overwrite were silent before the
        counter (ISSUE 5 satellite): dump reasons were counted, drops
        were not."""
        from koordinator_tpu.scheduler.flight_recorder import (
            FlightRecorder,
            RoundRecord,
        )

        def rec(i):
            return RoundRecord(
                round=i, trace_id="t", start_time=0.0, duration_s=0.01,
                solver="greedy", solve_path="greedy", pods=0, placed=0,
                failed=0, suspended=0, degraded=False, staleness_s=None,
                dirty_node_frac=0.0, dirty_pod_frac=0.0,
                solve_wall_s=0.0, solve_device_s=0.0)

        before = metrics.round_flight_overwritten.value()
        fr = FlightRecorder(capacity=2, slow_threshold_s=1.0)
        fr.record(rec(1))
        fr.record(rec(2))
        assert metrics.round_flight_overwritten.value() == before
        assert fr.overwrites == 0
        fr.record(rec(3))          # evicts record 1 unread
        fr.record(rec(4))          # evicts record 2 unread
        assert fr.overwrites == 2
        assert metrics.round_flight_overwritten.value() == before + 2
        assert [r["round"] for r in fr.snapshot()] == [4, 3]

        # dump_now: the SLO breach trigger dumps the latest record with
        # the trigger's reason, without waiting for a slow round
        dumps_before = metrics.round_flight_dumps.value(
            labels={"reason": "slo:lat"})
        assert fr.dump_now("slo:lat") is True
        assert metrics.round_flight_dumps.value(
            labels={"reason": "slo:lat"}) == dumps_before + 1
        assert fr.last().dump_reason == "slo:lat"
        empty = FlightRecorder(capacity=2)
        assert empty.dump_now("slo:lat") is False

    def test_solve_path_and_device_split_on_batch_rounds(self, collector):
        # batch_solver_threshold=1 forces the batch engine (and, with no
        # gangs and factored masks, the incremental driver)
        sched = make_sched(batch_solver_threshold=1)
        for i in range(4):
            sched.enqueue(pod_spec(f"p{i}", cpu=100))
        sched.schedule_round()
        rec = sched.flight_recorder.last()
        assert rec.solver == "batch"
        assert rec.solve_path in ("full_cold", "incremental")
        assert rec.solve_device_s > 0
        # second round re-uses the cache: the path label updates
        sched.enqueue(pod_spec("p9", cpu=100))
        sched.schedule_round()
        rec2 = sched.flight_recorder.last()
        assert rec2.solve_path in ("incremental", "full_fallback")
        assert rec2.dirty_pod_frac >= 0.0

    def test_debug_rounds_and_trace_endpoints(self, collector):
        from koordinator_tpu.scheduler.services import DebugService

        sched = make_sched(trace_pods=True)
        service = DebugService(sched)
        sched.enqueue(pod_spec("p0"))
        sched.schedule_round()
        status, body = service.handle("/debug/rounds", {"size": 10})
        assert status == 200
        assert body["rounds"][0]["placed"] == 1
        assert body["rounds"][0]["trace_id"]
        status, body = service.handle("/debug/trace/p0")
        assert status == 200
        assert body["trace_id"] == sched.pod_trace_id("p0")
        assert [s["name"] for s in body["spans"]] == [
            "scheduler.enqueue", "scheduler.bind"]
        status, _ = service.handle("/debug/trace/ghost")
        assert status == 404

    def test_gated_rounds_claim_no_stale_solve_path(self, collector):
        class ClosedBarrier:
            def check(self):
                return False

        sched = make_sched(trace_pods=True)
        sched.enqueue(pod_spec("p0"))
        sched.schedule_round()   # a real round sets last_solver/path
        sched.barrier = ClosedBarrier()
        sched.enqueue(pod_spec("p1"))
        sched.schedule_round()   # gated: decides nothing
        gated = collector.find(name="scheduler.round")[-1]
        assert gated.attributes.get("gated") is True
        assert "solver" not in gated.attributes  # no stale solve claim
        # gated rounds stay out of the flight recorder too
        assert len(sched.flight_recorder.records) == 1

    def test_latency_exemplars_link_to_round_trace(self, collector):
        sched = make_sched(trace_pods=True)
        sched.enqueue(pod_spec("p0"))
        sched.schedule_round()
        round_span = collector.find(name="scheduler.round")[0]
        exemplars = metrics.scheduling_latency.exemplars(
            labels={"phase": "Solve"})
        assert exemplars, "Solve phase observation carried no exemplar"
        assert any(ex["labels"]["trace_id"] == round_span.trace_id
                   for ex in exemplars.values())
        # exemplars render only in the OpenMetrics exposition
        classic = metrics.SCHEDULER.expose()
        assert " # {" not in classic
        om = metrics.SCHEDULER.expose(openmetrics=True)
        assert f'# {{trace_id="{round_span.trace_id}"}}' in om


# ---- HTTP gateway surfaces -------------------------------------------------

class TestGatewaySurfaces:
    def test_metrics_rounds_and_trace_over_http(self, collector):
        from koordinator_tpu.transport.http_gateway import HttpGateway

        sched = make_sched(trace_pods=True)
        sched.enqueue(pod_spec("p0"))
        sched.schedule_round()
        gw = HttpGateway(scheduler=sched)
        gw.start()
        try:
            base = f"http://127.0.0.1:{gw.port}"

            def get(path):
                with urllib.request.urlopen(base + path, timeout=5) as r:
                    return r.status, r.read().decode(), r.headers

            status, text, headers = get("/metrics")
            assert status == 200
            assert "text/plain" in headers["Content-Type"]
            # aggregated: all five component registries in one scrape
            for prefix in ("koord_scheduler_", "koordlet_",
                           "koord_manager_", "koord_descheduler_",
                           "koord_transport_"):
                assert prefix in text, prefix
            assert " # {" not in text
            status, om, headers = get("/metrics?openmetrics=1")
            assert "openmetrics" in headers["Content-Type"]
            assert " # {" in om  # exemplars present

            status, body, _ = get("/debug/rounds?size=1")
            rounds = json.loads(body)["rounds"]
            assert len(rounds) == 1 and rounds[0]["placed"] == 1

            status, body, _ = get("/debug/trace/p0")
            doc = json.loads(body)
            assert doc["trace_id"] == sched.pod_trace_id("p0")
            assert doc["spans"]
            with pytest.raises(urllib.error.HTTPError) as ei:
                get("/debug/trace/ghost")
            assert ei.value.code == 404

            # POST /v1/solve ignored its body before tracing existed; a
            # non-JSON body must keep triggering the round, not 500
            req = urllib.request.Request(
                base + "/v1/solve", data=b"run-now", method="POST")
            with urllib.request.urlopen(req, timeout=5) as r:
                assert r.status == 200
                assert "assignments" in json.loads(r.read())
        finally:
            gw.stop()


# ---- koordlet reconcile ----------------------------------------------------

class TestKoordletReconcileTracing:
    def test_reconcile_joins_annotated_pod_trace(self, tmp_path, collector):
        from koordinator_tpu.api.qos import QoSClass
        from koordinator_tpu.koordlet.resourceexecutor import (
            ResourceUpdateExecutor,
        )
        from koordinator_tpu.koordlet.runtimehooks.hooks import HookRegistry
        from koordinator_tpu.koordlet.runtimehooks.plugins import (
            register_default_hooks,
        )
        from koordinator_tpu.koordlet.runtimehooks.reconciler import (
            Reconciler,
        )
        from koordinator_tpu.koordlet.statesinformer import (
            PodMeta,
            StatesInformer,
        )
        from koordinator_tpu.koordlet.system.config import make_test_config
        from koordinator_tpu.api import crds

        cfg = make_test_config(tmp_path)
        ctx = tracing.TraceContext(trace_id="ee" * 16, span_id="ff" * 8)
        pod = PodMeta(
            uid="u1", name="traced-pod", namespace="default",
            qos_class=QoSClass.BE, kube_qos="besteffort",
            annotations={tracing.TRACE_ANNOTATION: ctx.to_annotation()})
        states = StatesInformer()
        states.set_pods([pod])
        registry = HookRegistry()
        register_default_hooks(registry, node_slo=lambda: crds.NodeSLO())
        reconciler = Reconciler(states, registry,
                                ResourceUpdateExecutor(cfg), cfg)
        reconciler.reconcile_once()
        spans = collector.find(name="koordlet.reconcile_pod",
                               service="koordlet")
        assert len(spans) == 1
        assert spans[0].trace_id == ctx.trace_id
        assert spans[0].parent_id == ctx.span_id
        assert spans[0].attributes["pod"] == "traced-pod"
        assert "writes" in spans[0].attributes
        # periodic re-reconciles must NOT re-join the same trace every
        # tick (a pod lives for weeks; its annotation doesn't change)
        reconciler.reconcile_once()
        reconciler.reconcile_once()
        assert len(collector.find(name="koordlet.reconcile_pod")) == 1
        # ...but a NEW trace annotation (pod re-bound) joins again
        ctx2 = tracing.TraceContext(trace_id="aa" * 16, span_id="bb" * 8)
        pod2 = PodMeta(
            uid="u1", name="traced-pod", namespace="default",
            qos_class=QoSClass.BE, kube_qos="besteffort",
            annotations={tracing.TRACE_ANNOTATION: ctx2.to_annotation()})
        states.set_pods([pod2])
        reconciler.reconcile_once()
        spans = collector.find(name="koordlet.reconcile_pod")
        assert len(spans) == 2 and spans[-1].trace_id == ctx2.trace_id


# ---- the acceptance flow: scheduler -> manager -> koordlet -----------------

class TestEndToEndPropagation:
    def test_one_trace_across_three_services_and_a_faulted_resync(
            self, tmp_path, collector):
        """One trace_id emitted at Scheduler.enqueue shows up in spans
        from the scheduler, manager, and koordlet services, all hops
        over real sockets; a fault-injected write truncation then severs
        the manager's watch connection and the post-reconnect resync
        replay still attributes the missed pod event to its trace."""
        from koordinator_tpu.cmd.binaries import ReconnectingSidecarClient
        from koordinator_tpu.manager.colocation_loop import (
            ManagerSyncBinding,
        )
        from koordinator_tpu.runtimeproxy import Dispatcher
        from koordinator_tpu.transport.faults import (
            FaultConfig,
            FaultInjector,
        )
        from koordinator_tpu.transport.services import HookService

        # -- scheduler "process": sync service + solver over one socket
        server = RpcServer(str(tmp_path / "sched.sock"),
                           service="scheduler")
        sync_service = StateSyncService()
        sync_service.attach(server)
        sched = make_sched()
        sync_service.attach_binding(SchedulerBinding(sched))
        SolveService(sched).attach(server)
        server.start()

        # -- koordlet "process": runtime-hook server on its own socket
        hook_server = RpcServer(str(tmp_path / "hooks.sock"),
                                service="koordlet")
        HookService(Dispatcher()).attach(hook_server)
        hook_server.start()

        # -- manager "process": watch client over a fault-injectable
        #    socket (probabilities start at zero; flipped below)
        inj = FaultInjector(seed=7, config=FaultConfig())
        binding = ManagerSyncBinding()
        sync = StateSyncClient(binding)

        def bootstrap_watch(client):
            sync.bind_client(client)
            sync.bootstrap(client)

        manager = ReconnectingSidecarClient(
            server.path, on_push=sync.on_push,
            on_connect=bootstrap_watch, breaker=False, faults=inj)

        feeder = RpcClient(server.path)
        hook_client = RpcClient(hook_server.path)
        try:
            manager.ensure()
            feeder.connect()
            hook_client.connect()
            sync_service.upsert_node(
                "n0", resource_vector(cpu=16_000, memory=16_384))

            # 1) submit the pod under a root span; the context rides the
            #    STATE_PUSH frame doc (like deadline_ms)
            with tracing.TRACER.span("submit-pod",
                                     service="submitter") as sp:
                trace_id = sp.trace_id
                feeder.call(
                    FrameType.STATE_PUSH,
                    {"kind": "pod_add", "name": "pod-e2e", "priority": 3},
                    {"requests": resource_vector(cpu=1_000, memory=512)})

            # scheduler hop: the enqueue span joined the submitter trace
            assert sched.pod_trace_id("pod-e2e") == trace_id
            # and the server-side dispatch span carries it too
            rpc_spans = collector.find(name="rpc.STATE_PUSH",
                                       service="scheduler")
            assert any(s.trace_id == trace_id for s in rpc_spans)

            # manager hop: the DELTA applied on the watch stream under
            # the same trace
            wait_until(
                lambda: any(s.trace_id == trace_id for s in
                            collector.find(name="sync.pod_add",
                                           service="manager")),
                what="manager sync span for the pod trace")

            # 2) solve remotely — the round joins the pod's... no: the
            #    round joins the CALLER's trace; drive it under the pod
            #    trace to keep one timeline
            with tracing.activate(tracing.TraceContext(
                    trace_id=trace_id, span_id=sp.span_id)):
                out = solve_remote(feeder)
            assert out["assignments"] == {"pod-e2e": "n0"}
            round_spans = collector.find(name="scheduler.round",
                                         service="scheduler")
            assert any(s.trace_id == trace_id for s in round_spans)
            bind_spans = collector.find(name="scheduler.bind",
                                        service="scheduler")
            assert any(s.trace_id == trace_id for s in bind_spans)

            # 3) koordlet hop: the bind annotation's context rides the
            #    HOOK_REQUEST frame to the koordlet's hook server
            ann = sched.resource_status["pod-e2e"][
                tracing.TRACE_ANNOTATION]
            bind_ctx = tracing.TraceContext.from_annotation(ann)
            assert bind_ctx.trace_id == trace_id
            with tracing.activate(bind_ctx):
                hook_client.call(FrameType.HOOK_REQUEST,
                                 {"hook": "PreCreateContainer",
                                  "pod_meta": {"name": "pod-e2e"}})
            wait_until(
                lambda: any(s.trace_id == trace_id for s in
                            collector.find(name="rpc.HOOK_REQUEST",
                                           service="koordlet")),
                what="koordlet hook dispatch span")

            # acceptance: one trace_id, spans from all three services
            services = {s.service for s in collector.spans
                        if s.trace_id == trace_id}
            assert {"scheduler", "manager", "koordlet"} <= services

            # 4) fault-injected reconnect/resync: truncate the manager's
            #    next write mid-frame (the connection severs), heal, and
            #    prove a pod event missed during the outage still joins
            #    its trace after the re-HELLO replay
            inj.config.send_truncate_p = 1.0
            from koordinator_tpu.transport.channel import RpcError

            with pytest.raises(RpcError):
                manager.call(FrameType.STATE_PUSH,
                             {"kind": "node_allocatable", "name": "n0"},
                             {"allocatable": resource_vector(
                                 cpu=16_000, memory=16_384)})
            assert inj.injected["client_truncate"] >= 1
            inj.config.send_truncate_p = 0.0

            # traced pod pushed while the manager watch is down
            with tracing.TRACER.span("submit-pod-2",
                                     service="submitter") as sp2:
                feeder.call(
                    FrameType.STATE_PUSH,
                    {"kind": "pod_add", "name": "pod-after-fault"},
                    {"requests": resource_vector(cpu=500, memory=256)})
            manager.ensure()  # re-dial + re-HELLO from last_rv
            wait_until(
                lambda: any(s.trace_id == sp2.trace_id for s in
                            collector.find(name="sync.pod_add",
                                           service="manager")),
                what="post-resync manager span for the missed pod event")
        finally:
            feeder.close()
            hook_client.close()
            manager.close()
            hook_server.stop()
            server.stop()
