"""Runtime-hook dispatch ACROSS a process boundary.

The reference's hook path spans two processes and a wire protocol:
koord-runtime-proxy (or containerd's NRI) raises lifecycle hooks that
koordlet's hook server answers (nri/server.go:34, runtimeproxy/
dispatcher/dispatcher.go).  Round 3 exercised this seam in-process only;
here the koordlet-side hook server (HookRegistry plugins behind a
HookService) runs in a REAL subprocess, and the proxy side dispatches to
it over the framed TCP transport via RemoteHookServer.  Also proves the
fail-open contract the hard way: SIGKILL the hook server mid-flight and
the CRI path keeps working with requests passing through unmodified.

The redesign rationale for speaking bespoke frames here instead of CRI
gRPC / NRI ttrpc is docs/runtime_boundary.md.
"""

import textwrap
import time

import pytest

from koordinator_tpu.api import extension as ext
from koordinator_tpu.koordlet.runtimehooks.server import RemoteHookServer
from koordinator_tpu.runtimeproxy import (
    CRIProxy,
    Dispatcher,
    FailoverStore,
    HookRequest,
    HookType,
)
from koordinator_tpu.transport.channel import RpcClient

from tests.proc_helpers import kill_all, spawn_replicas, wait_for

HOOK_SERVER = textwrap.dedent("""
    import sys, time
    status = sys.argv[1]

    from koordinator_tpu.koordlet.runtimehooks.hooks import HookRegistry
    from koordinator_tpu.koordlet.runtimehooks.plugins import (
        register_default_hooks,
    )
    from koordinator_tpu.koordlet.runtimehooks.server import (
        RegistryHookServer,
    )
    from koordinator_tpu.api import crds
    from koordinator_tpu.runtimeproxy import Dispatcher, HookType
    from koordinator_tpu.transport.channel import RpcServer
    from koordinator_tpu.transport.services import HookService

    registry = HookRegistry()
    register_default_hooks(registry, node_slo=crds.NodeSLO,
                           share_pool=lambda: "0-3")
    dispatcher = Dispatcher()
    dispatcher.register(RegistryHookServer(registry), list(HookType))

    server = RpcServer("tcp://127.0.0.1:0")
    HookService(dispatcher).attach(server)
    server.start()
    with open(status, "w") as f:
        f.write(server.address + "\\n")
    while True:
        time.sleep(0.5)
""")


@pytest.fixture
def remote_hooks(tmp_path):
    script = tmp_path / "hook_server.py"
    script.write_text(HOOK_SERVER)
    status = tmp_path / "addr"
    procs, errs = spawn_replicas(script, {"hooks": [str(status)]}, tmp_path)
    try:
        wait_for(lambda: status.exists() and status.read_text().strip(),
                 procs, errs, 30.0, "hook server address")
        addr = status.read_text().strip()
        client = RpcClient(addr, timeout=10.0)
        client.connect()
        try:
            yield client, procs["hooks"]
        finally:
            client.close()
    finally:
        kill_all(procs)


def be_request(batch_cpu=0, batch_mem=0):
    return HookRequest(
        pod_meta={"uid": "u-be", "name": "be-pod", "namespace": "default"},
        labels={ext.LABEL_POD_QOS: "BE"},
        cgroup_parent="kubepods/besteffort/podu-be",
        resources=({ext.RESOURCE_BATCH_CPU: batch_cpu,
                    ext.RESOURCE_BATCH_MEMORY: batch_mem}
                   if batch_cpu or batch_mem else {}),
    )


def test_hooks_answered_from_other_process(remote_hooks):
    client, _server_proc = remote_hooks
    dispatcher = Dispatcher()
    dispatcher.register(RemoteHookServer(client), list(HookType))
    forwarded = {}
    proxy = CRIProxy(dispatcher, FailoverStore(), {
        "RunPodSandbox": lambda req: forwarded.setdefault("sandbox", req),
        "CreateContainer": lambda req: forwarded.setdefault("create", req),
    })

    # PreRunPodSandbox: GroupIdentity (default-on gate) resolves the BE
    # bvt from the default NodeSLO in the REMOTE process
    proxy.run_pod_sandbox("pod-be", be_request())
    assert forwarded["sandbox"].resources["cpu.bvt_warp_ns"] == "-1"

    # PreCreateContainer: BatchResource derives kernel limits from the
    # batch requests; CPUSetAllocator stays quiet for BE
    request = be_request(batch_cpu=2000, batch_mem=1 << 30)
    request.container_meta = {"name": "main", "id": "c1"}
    proxy.create_container("c1", request)
    merged = forwarded["create"].resources
    assert merged["cpu.cfs_quota"] == str(2000 * 100_000 // 1000)
    assert merged["cpu.shares"] == str(2000 * 1024 // 1000)
    assert merged["memory.limit"] == str(1 << 30)
    assert "cpuset.cpus" not in merged

    # LS pod: CPUSetAllocator hands out the remote's share pool
    ls = HookRequest(
        pod_meta={"uid": "u-ls", "name": "ls-pod", "namespace": "default"},
        container_meta={"name": "main", "id": "c2"},
        labels={ext.LABEL_POD_QOS: "LS"},
    )
    proxy.create_container("c2", ls)
    assert ls.resources["cpuset.cpus"] == "0-3"
    assert ls.resources["cpu.bvt_warp_ns"] == "2"


def test_fail_open_when_hook_server_dies(remote_hooks):
    client, server_proc = remote_hooks
    dispatcher = Dispatcher()
    dispatcher.register(RemoteHookServer(client), list(HookType))
    proxy = CRIProxy(dispatcher, FailoverStore(),
                     {"RunPodSandbox": lambda req: req})

    out = proxy.run_pod_sandbox("pod-1", be_request())
    assert out.resources["cpu.bvt_warp_ns"] == "-1"

    server_proc.kill()
    server_proc.wait()
    time.sleep(0.2)

    # dead hook server: the CRI call still completes, request unchanged
    fresh = be_request()
    out = proxy.run_pod_sandbox("pod-2", fresh)
    assert out is fresh or out.resources == {}
    assert "cpu.bvt_warp_ns" not in fresh.resources
