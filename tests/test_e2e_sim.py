"""End-to-end cluster simulation — the kind-e2e analog (SURVEY.md §4):

admission webhook -> quota evaluation -> batched TPU scheduling ->
node agent enforcement on a fake kernel fs -> NodeMetric reporting ->
manager colocation math -> batch capacity appears -> BE pods schedule ->
hot node -> descheduler eviction. One test class per flow stage plus a
whole-loop scenario.
"""

import os

import numpy as np
import pytest

from koordinator_tpu.api import crds, extension as ext
from koordinator_tpu.api.qos import QoSClass
from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS, ResourceDim, resource_vector
from koordinator_tpu.koordlet.daemon import Daemon
from koordinator_tpu.koordlet.statesinformer import NodeInfo, PodMeta
from koordinator_tpu.koordlet.system.config import make_test_config
from koordinator_tpu.manager import sloconfig
from koordinator_tpu.manager.nodemetric import NodeMetricController
from koordinator_tpu.manager.noderesource_controller import (
    MIB, NodeRecord, NodeResourceController,
)
from koordinator_tpu.manager.webhook import (
    PodMutatingWebhook, PodValidatingWebhook, QuotaEvaluator,
)
from koordinator_tpu.scheduler.barrier import SyncBarrier
from koordinator_tpu.scheduler.scheduler import Scheduler
from koordinator_tpu.scheduler.services import DebugService
from koordinator_tpu.scheduler.snapshot import ClusterSnapshot, NodeSpec, PodSpec
from tests.test_koordlet_metrics import FakeClock


def make_cluster(n_nodes=4, cpu=16000, mem=32768):
    snapshot = ClusterSnapshot(capacity=16)
    for i in range(n_nodes):
        snapshot.upsert_node(NodeSpec(
            name=f"n{i}",
            allocatable=resource_vector({"cpu": cpu, "memory": mem}),
        ))
    return snapshot


def be_pod_dict(name, cpu="2", memory="4Gi"):
    return {
        "metadata": {"name": name, "namespace": "default",
                     "labels": {"app": "spark"}},
        "spec": {"containers": [{"name": "m", "resources": {
            "requests": {"cpu": cpu, "memory": memory},
            "limits": {"cpu": cpu, "memory": memory}}}]},
    }


class TestFullColocationLoop:
    """The SURVEY 3.1 + 3.2 loops stitched together."""

    def test_admission_to_enforcement_to_capacity(self, tmp_path):
        clock = FakeClock()
        # --- control plane setup
        profile = crds.ClusterColocationProfile(
            name="colo", pod_selector={"app": "spark"}, qos_class="BE",
            koordinator_priority=5500, scheduler_name="koord-scheduler")
        mutating = PodMutatingWebhook([profile])
        validating = PodValidatingWebhook()
        snapshot = make_cluster()
        scheduler = Scheduler(snapshot)
        service = DebugService(scheduler)

        # --- 1. admission: BE pod arrives, gets QoS + batch translation
        pod = be_pod_dict("spark-1")
        mutating.mutate(pod)
        assert validating.validate(pod) == []
        requests = pod["spec"]["containers"][0]["resources"]["requests"]
        assert requests[ext.RESOURCE_BATCH_CPU] == 2000

        # --- 2. no batch capacity yet: pod must NOT schedule
        batch_req = resource_vector({
            ext.RESOURCE_BATCH_CPU: requests[ext.RESOURCE_BATCH_CPU],
            ext.RESOURCE_BATCH_MEMORY: requests[ext.RESOURCE_BATCH_MEMORY] // MIB,
        })
        scheduler.enqueue(PodSpec(name="spark-1", requests=batch_req,
                                  priority=5500, qos=int(QoSClass.BE)))
        result = scheduler.schedule_round()
        assert "spark-1" in result.failures
        status, diag = service.handle("/apis/v1/diagnosis")
        assert status == 200 and "spark-1" in diag

        # --- 3. node agent reports usage; manager computes batch capacity
        cfg = make_test_config(tmp_path)
        daemon = Daemon(cfg=cfg, clock=clock)
        daemon.states.set_node(NodeInfo(name="n0",
                                        allocatable={"cpu": 16000,
                                                     "memory": 32768 * MIB}))
        os.makedirs(cfg.proc_root, exist_ok=True)
        for i in range(6):
            open(cfg.proc_path("stat"), "w").write(
                f"cpu  {int(4.0 * (clock.t - 900) * 100)} 0 0 800 0 0 0 0 0 0\n")
            open(cfg.proc_path("meminfo"), "w").write(
                "MemTotal: 33554432 kB\nMemAvailable: 25165824 kB\nCached: 0\n")
            daemon.tick()
            clock.tick(30)
        status_report = daemon.states.build_node_metric()

        nm = NodeMetricController(clock=clock)
        nm.upsert_node("n0")
        nm.report_status("n0", status_report)
        nrc = NodeResourceController(
            sloconfig.ColocationConfig(enable=True), clock=clock)
        records = [NodeRecord(name=f"n{i}", cpu_capacity_milli=16000,
                              mem_capacity_mib=32768,
                              metric=nm.get("n0").status) for i in range(4)]
        patches = {p.name: p for p in nrc.reconcile(records)}
        assert patches["n0"].batch_cpu_milli > 2000

        # --- 4. patch batch capacity onto nodes -> pod schedules
        for name, patch in patches.items():
            alloc = resource_vector({
                "cpu": 16000, "memory": 32768,
                ext.RESOURCE_BATCH_CPU: patch.batch_cpu_milli,
                ext.RESOURCE_BATCH_MEMORY: patch.batch_mem_mib,
            })
            snapshot.upsert_node(NodeSpec(name=name, allocatable=alloc))
        result = scheduler.schedule_round()
        assert result.assignments.get("spark-1") in {"n0", "n1", "n2", "n3"}

        # --- 5. the agent enforces the scheduled pod's batch limits
        node = result.assignments["spark-1"]
        agent_pod = PodMeta(
            uid="spark-1", name="spark-1", namespace="default",
            qos_class=QoSClass.BE, kube_qos="besteffort", priority=5500,
            requests={ext.RESOURCE_BATCH_CPU: 2000,
                      ext.RESOURCE_BATCH_MEMORY: 4 << 30},
        )
        daemon.states.set_pods([agent_pod])
        from koordinator_tpu.koordlet.system import cgroup as cg
        from tests.test_koordlet_system import write_cgroup_file

        rel = agent_pod.cgroup_dir(cfg)
        for res in (cg.CPU_CFS_QUOTA, cg.CPU_SHARES, cg.MEMORY_LIMIT,
                    cg.CPU_BVT_WARP_NS):
            write_cgroup_file(cfg, res, rel, "0")
        daemon.tick()
        assert cg.cgroup_read(cg.CPU_CFS_QUOTA, rel, cfg) == "200000"
        assert cg.cgroup_read(cg.CPU_BVT_WARP_NS, rel, cfg) == "-1"

    def test_quota_gate_in_admission(self):
        evaluator = QuotaEvaluator()
        evaluator.set_quota(crds.ElasticQuota(
            name="spark", parent="root",
            max={ext.RESOURCE_BATCH_CPU: 3000}))
        assert evaluator.admit("spark", {ext.RESOURCE_BATCH_CPU: 2000}) is None
        assert evaluator.admit("spark", {ext.RESOURCE_BATCH_CPU: 2000}) is not None


class TestSyncBarrier:
    def test_gates_until_observed(self):
        clock = FakeClock()
        source_version = [10]
        observed = [5]
        barrier = SyncBarrier(
            mark=lambda: source_version[0],
            observed_version=lambda: observed[0],
            timeout_seconds=5.0, clock=clock, sleep=lambda s: clock.tick(s),
        )
        barrier.start()
        assert not barrier.check()
        observed[0] = 10
        assert barrier.check()

    def test_timeout_opens_anyway(self):
        clock = FakeClock()
        barrier = SyncBarrier(
            mark=lambda: 100, observed_version=lambda: 1,
            timeout_seconds=1.0, clock=clock, sleep=lambda s: clock.tick(s),
        )
        barrier.start()
        assert barrier.wait_until_synced() is False
        assert barrier.synced  # open but reported

    def test_fresh_process_not_gated(self):
        barrier = SyncBarrier(mark=lambda: 1, observed_version=lambda: 0)
        assert barrier.check()


class TestDebugService:
    def make(self):
        snapshot = make_cluster(2)
        scheduler = Scheduler(snapshot)
        return DebugService(scheduler), scheduler

    def test_nodes_and_pods_routes(self):
        service, scheduler = self.make()
        scheduler.enqueue(PodSpec(name="p1",
                                  requests=resource_vector({"cpu": 1000}),
                                  priority=9500))
        status, nodes = service.handle("/apis/v1/nodes")
        assert status == 200 and len(nodes) == 2
        status, pods = service.handle("/apis/v1/pods")
        assert pods[0]["name"] == "p1"

    def test_unknown_route_404(self):
        service, _ = self.make()
        status, body = service.handle("/nope")
        assert status == 404

    def test_plugin_mount(self):
        service, _ = self.make()
        service.register_plugin("loadaware", "status", lambda p: {"ok": True})
        status, body = service.handle("/apis/v1/plugins/loadaware/status")
        assert status == 200 and body == {"ok": True}

    def test_metrics_scrape(self):
        service, _ = self.make()
        status, body = service.handle("/metrics")
        assert status == 200 and "koord_scheduler" in body

    def test_top_n_scores_toggle(self):
        service, scheduler = self.make()
        status, body = service.handle("/apis/v1/__debug/set-top-n", {"n": 2})
        assert body["dump_top_n_scores"] == 2
        scores = np.asarray([[10.0, 20.0]])
        service.record_scores(
            [PodSpec(name="p", requests=resource_vector({}))], scores,
            ["n0", "n1"],
        )
        status, dumped = service.handle("/apis/v1/__debug/scores")
        assert dumped["p"][0]["node"] == "n1"


class TestBarrierGatesScheduler:
    def test_round_noop_until_synced(self):
        observed = [0]
        barrier = SyncBarrier(mark=lambda: 7, observed_version=lambda: observed[0])
        barrier.start()
        snapshot = make_cluster(2)
        scheduler = Scheduler(snapshot, barrier=barrier)
        scheduler.enqueue(PodSpec(name="p1",
                                  requests=resource_vector({"cpu": 1000}),
                                  priority=9500))
        result = scheduler.schedule_round()
        assert result.round_pods == 0 and "p1" in scheduler.pending
        observed[0] = 7
        result = scheduler.schedule_round()
        assert result.assignments.get("p1")


class TestScoreDumpWired:
    def test_solve_records_scores(self):
        snapshot = make_cluster(2)
        scheduler = Scheduler(snapshot)
        service = DebugService(scheduler)
        scheduler.debug_service = service
        service.dump_top_n_scores = 2
        scheduler.enqueue(PodSpec(name="p1",
                                  requests=resource_vector({"cpu": 1000}),
                                  priority=9500))
        scheduler.schedule_round()
        status, dumped = service.handle("/apis/v1/__debug/scores")
        assert "p1" in dumped and len(dumped["p1"]) == 2

    def test_diagnosis_structured(self):
        snapshot = make_cluster(1, cpu=100)
        scheduler = Scheduler(snapshot)
        service = DebugService(scheduler)
        scheduler.enqueue(PodSpec(name="big",
                                  requests=resource_vector({"cpu": 999000}),
                                  priority=9500))
        scheduler.schedule_round()
        status, diag = service.handle("/apis/v1/diagnosis")
        assert isinstance(diag["big"], dict)  # structured, not a repr string
        # stale diagnosis cleared once the queue drains
        scheduler.dequeue("big")
        scheduler.schedule_round()
        status, diag = service.handle("/apis/v1/diagnosis")
        assert diag == {}


class TestSidecarDeployment:
    """The deployment shape end to end: both binaries assembled from CLI
    flags, state flowing over the wire (snapshot + deltas), rounds driven
    by solve RPCs — the full SURVEY §7 step 4 composition."""

    def test_colocation_over_the_wire(self, tmp_path):
        from tests.test_transport import wait_until

        from koordinator_tpu.cmd.binaries import main_koord_scheduler
        from koordinator_tpu.transport import (
            RpcClient, StateSyncClient, StateSyncService)
        from koordinator_tpu.transport.deltasync import SchedulerBinding
        from koordinator_tpu.transport.services import solve_remote

        # scheduler binary: socket + solve service from flags
        out = main_koord_scheduler([
            "--node-capacity", "16",
            "--listen-socket", str(tmp_path / "sched.sock"),
            "--disable-leader-election",
        ])
        client = None
        try:
            scheduler = out.component
            # the shell side: informer state authority on the same server
            service = StateSyncService()
            service.attach(out.server)

            sync = StateSyncClient(SchedulerBinding(scheduler))
            client = RpcClient(out.server.path, on_push=sync.on_push)
            client.connect()
            sync.bootstrap(client)

            # manager computed batch capacity -> node carries batch dims
            service.upsert_node("n0", resource_vector({
                "cpu": 16_000, "memory": 32_768,
                ext.RESOURCE_BATCH_CPU: 9_000,
                ext.RESOURCE_BATCH_MEMORY: 20_000,
            }))
            # webhook-translated BE pod requests batch resources
            service.add_pod("spark-1", resource_vector({
                ext.RESOURCE_BATCH_CPU: 2_000,
                ext.RESOURCE_BATCH_MEMORY: 4_000,
            }), priority=5_500)

            wait_until(lambda: sync.rv == service.rv)
            result = solve_remote(client)
            assert result["assignments"] == {"spark-1": "n0"}

            # batch capacity revoked (load rose): next BE pod fails with a
            # structured reason served over the same wire
            service.upsert_node("n0", resource_vector({
                "cpu": 16_000, "memory": 32_768,
                ext.RESOURCE_BATCH_CPU: 0,
            }))
            service.add_pod("spark-2", resource_vector({
                ext.RESOURCE_BATCH_CPU: 2_000,
            }), priority=5_500)
            wait_until(lambda: sync.rv == service.rv)
            result = solve_remote(client)
            assert "spark-2" in result["failures"]
            assert "insufficient" in result["failures"]["spark-2"]
        finally:
            if client is not None:
                client.close()
            out.server.stop()


class TestFineGrainedLoop:
    def test_scheduler_cpuset_applies_on_node(self, tmp_path):
        """SURVEY 3.3 with the fine-grained path: scheduler allocates an
        exclusive cpuset at bind (nodenumaresource Reserve), the decision
        travels as the resource-status annotation, and the koordlet cpuset
        hook writes it to the pod's cgroup."""
        from koordinator_tpu.api.resources import resource_vector
        from koordinator_tpu.features import RUNTIMEHOOK_GATES
        from koordinator_tpu.koordlet.runtimehooks.hooks import (
            HookRegistry, Stage,
        )
        from koordinator_tpu.koordlet.runtimehooks.plugins import (
            register_default_hooks,
        )
        from koordinator_tpu.koordlet.runtimehooks.protocol import PodContext
        from koordinator_tpu.koordlet.statesinformer import PodMeta
        from koordinator_tpu.ops.numa import CPUTopology
        from koordinator_tpu.scheduler.cpu_manager import CPUManager
        from koordinator_tpu.scheduler.scheduler import Scheduler
        from koordinator_tpu.scheduler.snapshot import NodeSpec, PodSpec

        import numpy as _np

        cm = CPUManager()
        cm.register_node("n0", CPUTopology.build(
            _np.arange(8, dtype=_np.int32) // 2,
            _np.arange(8, dtype=_np.int32) // 4,
            _np.zeros(8, _np.int32)))
        snapshot = make_cluster(n_nodes=1)
        sched = Scheduler(snapshot, cpu_manager=cm)
        sched.enqueue(PodSpec(
            name="lsr-1",
            requests=resource_vector({"cpu": 4_000, "memory": 1_024}),
            qos=int(QoSClass.LSR), priority=9_000))
        res = sched.schedule_round()
        assert res.assignments["lsr-1"] == "n0"
        status = sched.resource_status["lsr-1"]["resource-status"]

        # the annotation rides the pod object to the node agent
        annotations = ext.set_resource_status(
            {}, status["cpuset"], status["numaNodeResources"])
        cfg = make_test_config(tmp_path)
        registry = HookRegistry()
        register_default_hooks(registry, node_slo=lambda: crds.NodeSLO())
        prev = RUNTIMEHOOK_GATES.enabled("CPUSetAllocator")
        RUNTIMEHOOK_GATES.set("CPUSetAllocator", True)
        try:
            agent_pod = PodMeta(
                uid="lsr-1", name="lsr-1", namespace="default",
                qos_class=QoSClass.LSR, kube_qos="guaranteed",
                annotations=annotations)
            ctx = PodContext.from_pod(agent_pod, cfg)
            registry.run(Stage.PRE_CREATE_CONTAINER, ctx)
        finally:
            RUNTIMEHOOK_GATES.set("CPUSetAllocator", prev)
        assert ctx.response.cpuset_cpus == status["cpuset"]
