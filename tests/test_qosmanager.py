"""QoS manager strategy tests: suppress math, eviction windows, burst, tier
reconcilers — all against the fake kernel fs."""

import os

import pytest

from koordinator_tpu.api import crds
from koordinator_tpu.api.qos import QoSClass
from koordinator_tpu.koordlet import metriccache as mc
from koordinator_tpu.koordlet.qosmanager import (
    Evictor, QOSManager, StrategyContext,
)
from koordinator_tpu.koordlet.qosmanager import cpusuppress as cs
from koordinator_tpu.koordlet.qosmanager.cpuburst import CPUBurst
from koordinator_tpu.koordlet.qosmanager.evict import CPUEvict, MemoryEvict
from koordinator_tpu.koordlet.qosmanager.reconcile import (
    BlkIOQOS, CgroupReconcile, ResctrlQOS, SysReconcile,
)
from koordinator_tpu.koordlet.resourceexecutor import ResourceUpdateExecutor
from koordinator_tpu.koordlet.statesinformer import NodeInfo, PodMeta, StatesInformer
from koordinator_tpu.koordlet.system import cgroup as cg
from koordinator_tpu.koordlet.system import procfs, resctrl
from koordinator_tpu.koordlet.system.config import make_test_config
from tests.test_koordlet_metrics import FakeClock
from tests.test_koordlet_system import write_cgroup_file


def make_topology(n_cpus=8, n_numa=2):
    infos = [
        procfs.CPUInfo(cpu=i, core=i // 2, socket=0, node=i % n_numa)
        for i in range(n_cpus)
    ]
    return procfs.CPUTopology(cpus=tuple(infos))


def make_ctx(tmp_path, clock, pods=(), cpu_capacity_milli=8000,
             mem_capacity=8 << 30, slo=None):
    cfg = make_test_config(tmp_path)
    states = StatesInformer(clock=clock)
    states.set_node(NodeInfo(
        name="n1",
        allocatable={"cpu": cpu_capacity_milli, "memory": mem_capacity},
    ))
    states.set_pods(list(pods))
    if slo is not None:
        states.set_node_slo(slo)
    cache = mc.MetricCache(clock=clock)
    executor = ResourceUpdateExecutor(cfg)
    return StrategyContext(states, cache, executor, cfg, clock=clock)


def be_pod(uid, cpu_req=2000, priority=5500):
    return PodMeta(
        uid=uid, name=uid, namespace="default", qos_class=QoSClass.BE,
        kube_qos="besteffort", priority=priority,
        requests={"kubernetes.io/batch-cpu": cpu_req},
    )


def enabled_slo(**threshold_kwargs):
    defaults = dict(enable=True)
    defaults.update(threshold_kwargs)
    return crds.NodeSLO(
        resource_used_threshold_with_be=crds.ResourceThresholdStrategy(**defaults)
    )


class TestSuppressMath:
    def test_formula(self):
        # 16 cores, threshold 65%, LS+sys using 6 cores => BE gets 10.4 - 6 = 4.4
        out = cs.calculate_be_suppress_milli(
            16000, node_used_milli=7000, be_used_milli=1000, threshold_pct=65
        )
        assert out == 16000 * 65 // 100 - 6000

    def test_min_floor_and_cap(self):
        assert cs.calculate_be_suppress_milli(16000, 16000, 0, 65) == cs.BE_MIN_CPUS * 1000
        assert cs.calculate_be_suppress_milli(4000, 0, 0, 200) == 4000

    def test_rate_limited_growth(self):
        out = cs.calculate_be_suppress_milli(
            100_000, 0, 0, 65, max_increase_pct=5, prev_allowable_milli=10_000
        )
        assert out == 15_000  # +5% of capacity per tick

    def test_cpuset_selection_numa_spread(self):
        topo = make_topology(8, 2)
        picked = cs.select_be_cpuset(topo, 4)
        # round-robin across numa nodes: 2 from each
        assert len(picked) == 4
        assert sum(1 for c in picked if c % 2 == 0) == 2

    def test_cpuset_avoids_exclusive(self):
        topo = make_topology(8, 2)
        picked = cs.select_be_cpuset(topo, 3, exclusive_cpus=frozenset({0, 1}))
        assert not set(picked) & {0, 1}

    def test_exclusive_fallback_when_starved(self):
        topo = make_topology(4, 1)
        picked = cs.select_be_cpuset(topo, 4, exclusive_cpus=frozenset({0, 1, 2}))
        assert len(picked) == 4


class TestCPUSuppress:
    def test_cpuset_policy_writes_tier_and_pods(self, tmp_path):
        clock = FakeClock()
        pod = be_pod("be-1")
        ctx = make_ctx(tmp_path, clock, pods=[pod], slo=enabled_slo())
        ctx.cache.append(mc.NODE_CPU_USAGE, 5.0)
        ctx.cache.append(mc.BE_CPU_USAGE, 1.0)
        be_dir = ctx.cfg.kube_qos_dir("besteffort")
        write_cgroup_file(ctx.cfg, cg.CPUSET_CPUS, be_dir, "0-7")
        write_cgroup_file(ctx.cfg, cg.CPUSET_CPUS, pod.cgroup_dir(ctx.cfg), "0-7")
        plugin = cs.CPUSuppress(ctx, topology=make_topology())
        assert plugin.enabled()
        plugin.update()
        # 8 cores * 65% - 4 LS cores = 1.2 => floor 2 cpus
        value = cg.cgroup_read(cg.CPUSET_CPUS, be_dir, ctx.cfg)
        assert len(procfs.parse_cpu_list(value)) == 2
        pod_value = cg.cgroup_read(cg.CPUSET_CPUS, pod.cgroup_dir(ctx.cfg), ctx.cfg)
        assert pod_value == value

    def test_cfs_quota_policy(self, tmp_path):
        clock = FakeClock()
        ctx = make_ctx(
            tmp_path, clock,
            slo=enabled_slo(cpu_suppress_policy="cfsQuota"),
        )
        ctx.cache.append(mc.NODE_CPU_USAGE, 2.0)
        ctx.cache.append(mc.BE_CPU_USAGE, 1.0)
        be_dir = ctx.cfg.kube_qos_dir("besteffort")
        write_cgroup_file(ctx.cfg, cg.CPU_CFS_QUOTA, be_dir, "-1")
        plugin = cs.CPUSuppress(ctx, topology=make_topology())
        plugin.update()
        # 8*0.65 - 1 = 4.2 cores => quota 420000us
        assert cg.cgroup_read(cg.CPU_CFS_QUOTA, be_dir, ctx.cfg) == "420000"
        assert plugin.be_real_limit_milli() == 4200


class TestCPUEvict:
    def make(self, tmp_path, clock, pods, real_limit):
        ctx = make_ctx(
            tmp_path, clock, pods=pods,
            slo=enabled_slo(
                cpu_evict_be_satisfaction_lower_percent=60,
                cpu_evict_be_satisfaction_upper_percent=80,
                cpu_evict_time_window_seconds=60,
            ),
        )
        evictor = Evictor(ctx)
        plugin = CPUEvict(ctx, evictor, be_real_limit_milli=lambda: real_limit)
        return ctx, evictor, plugin

    def test_no_evict_when_satisfied(self, tmp_path):
        clock = FakeClock()
        ctx, evictor, plugin = self.make(
            tmp_path, clock, [be_pod("a", 2000)], real_limit=2000
        )
        ctx.cache.append(mc.BE_CPU_USAGE, 1.9)
        plugin.update()
        clock.tick(120)
        plugin.update()
        assert evictor.evicted == []

    def test_evicts_after_window(self, tmp_path):
        clock = FakeClock()
        pods = [be_pod("a", 4000, priority=5100), be_pod("b", 4000, priority=5900)]
        ctx, evictor, plugin = self.make(tmp_path, clock, pods, real_limit=2000)
        # satisfaction = 2000/8000 = 25% < 60%; BE hungry (usage ~ limit)
        ctx.cache.append(mc.BE_CPU_USAGE, 2.0)
        plugin.update()          # starts the window
        assert evictor.evicted == []
        clock.tick(30)
        ctx.cache.append(mc.BE_CPU_USAGE, 2.0)
        plugin.update()          # within window: no evict yet
        assert evictor.evicted == []
        clock.tick(40)
        ctx.cache.append(mc.BE_CPU_USAGE, 2.0)
        plugin.update()          # window passed
        # to reach 80%: target request = 2000/0.8 = 2500 => release 5500
        # evicts lowest priority first ("a"), then "b"
        assert [uid for uid, _ in evictor.evicted] == ["a", "b"]

    def test_not_hungry_no_evict(self, tmp_path):
        clock = FakeClock()
        ctx, evictor, plugin = self.make(
            tmp_path, clock, [be_pod("a", 8000)], real_limit=2000
        )
        ctx.cache.append(mc.BE_CPU_USAGE, 0.1)  # barely using its limit
        plugin.update()
        clock.tick(120)
        ctx.cache.append(mc.BE_CPU_USAGE, 0.1)
        plugin.update()
        assert evictor.evicted == []


class TestMemoryEvict:
    def test_evicts_until_lower(self, tmp_path):
        clock = FakeClock()
        pods = [be_pod("a", priority=5100), be_pod("b", priority=5900)]
        ctx = make_ctx(
            tmp_path, clock, pods=pods, mem_capacity=100,
            slo=enabled_slo(memory_evict_threshold_percent=70),
        )
        ctx.cache.append(mc.NODE_MEMORY_USAGE, 80.0)
        ctx.cache.append(mc.POD_MEMORY_USAGE, 20.0, {"pod_uid": "a"})
        ctx.cache.append(mc.POD_MEMORY_USAGE, 20.0, {"pod_uid": "b"})
        evictor = Evictor(ctx)
        MemoryEvict(ctx, evictor).update()
        # need to release 80 - 68 = 12 bytes; first pod (20) is enough
        assert [uid for uid, _ in evictor.evicted] == ["a"]

    def test_below_threshold_noop(self, tmp_path):
        clock = FakeClock()
        ctx = make_ctx(
            tmp_path, clock, pods=[be_pod("a")], mem_capacity=100,
            slo=enabled_slo(memory_evict_threshold_percent=70),
        )
        ctx.cache.append(mc.NODE_MEMORY_USAGE, 50.0)
        evictor = Evictor(ctx)
        MemoryEvict(ctx, evictor).update()
        assert evictor.evicted == []


def ls_pod(uid, cpu_limit=2000, mem_req=0, mem_limit=0, priority=9500):
    return PodMeta(
        uid=uid, name=uid, namespace="default", qos_class=QoSClass.LS,
        kube_qos="burstable", priority=priority,
        requests={"memory": mem_req}, limits={"cpu": cpu_limit, "memory": mem_limit},
    )


class TestCPUBurst:
    def make(self, tmp_path, clock, policy="auto"):
        pod = ls_pod("ls-1")
        slo = crds.NodeSLO(cpu_burst_strategy=crds.CPUBurstStrategy(policy=policy))
        ctx = make_ctx(tmp_path, clock, pods=[pod], slo=slo)
        rel = pod.cgroup_dir(ctx.cfg)
        write_cgroup_file(ctx.cfg, cg.CPU_CFS_BURST, rel, "0")
        write_cgroup_file(ctx.cfg, cg.CPU_CFS_QUOTA, rel, "200000")
        return ctx, pod, rel

    def test_cfs_burst_written(self, tmp_path):
        clock = FakeClock()
        ctx, pod, rel = self.make(tmp_path, clock, policy="cpuBurstOnly")
        CPUBurst(ctx).update()
        # limit 2000m * 1000% => 20 cores of burst * 100ms period = 2_000_000us
        assert cg.cgroup_read(cg.CPU_CFS_BURST, rel, ctx.cfg) == "2000000"

    def test_quota_burst_up_then_down(self, tmp_path):
        clock = FakeClock()
        ctx, pod, rel = self.make(tmp_path, clock, policy="cfsQuotaBurstOnly")
        plugin = CPUBurst(ctx)
        # throttled + calm node => scale up 1.2x
        ctx.cache.append(mc.NODE_CPU_USAGE, 1.0)
        ctx.cache.append(mc.CONTAINER_CPU_THROTTLED, 0.4, {"pod_uid": pod.uid})
        plugin.update()
        assert cg.cgroup_read(cg.CPU_CFS_QUOTA, rel, ctx.cfg) == "240000"
        # node heats up => scale back toward base
        clock.tick(2)
        ctx.cache.append(mc.NODE_CPU_USAGE, 7.5)
        ctx.cache.append(mc.CONTAINER_CPU_THROTTLED, 0.4, {"pod_uid": pod.uid})
        plugin.update()
        assert cg.cgroup_read(cg.CPU_CFS_QUOTA, rel, ctx.cfg) == "200000"

    def test_quota_burst_capped(self, tmp_path):
        clock = FakeClock()
        ctx, pod, rel = self.make(tmp_path, clock, policy="cfsQuotaBurstOnly")
        plugin = CPUBurst(ctx)
        ctx.cache.append(mc.NODE_CPU_USAGE, 1.0)
        ctx.cache.append(mc.CONTAINER_CPU_THROTTLED, 0.4, {"pod_uid": pod.uid})
        for _ in range(20):
            plugin.update()
            clock.tick(1)
            ctx.cache.append(mc.NODE_CPU_USAGE, 1.0)
            ctx.cache.append(mc.CONTAINER_CPU_THROTTLED, 0.4, {"pod_uid": pod.uid})
        # cap: base 200000 * 300% = 600000
        assert cg.cgroup_read(cg.CPU_CFS_QUOTA, rel, ctx.cfg) == "600000"


class TestReconcilers:
    def test_cgroup_memory_qos(self, tmp_path):
        clock = FakeClock()
        pod = ls_pod("ls-1", mem_req=1000, mem_limit=2000)
        slo = crds.NodeSLO(
            resource_qos_ls=crds.QoSStrategy(
                memory=crds.MemoryQoS(enable=True, min_limit_percent=50,
                                      throttling_percent=80),
            )
        )
        ctx = make_ctx(tmp_path, clock, pods=[pod], slo=slo)
        rel = pod.cgroup_dir(ctx.cfg)
        for res in (cg.MEMORY_MIN, cg.MEMORY_HIGH, cg.MEMORY_WMARK_RATIO,
                    cg.MEMORY_WMARK_SCALE_FACTOR, cg.MEMORY_WMARK_MIN_ADJ):
            write_cgroup_file(ctx.cfg, res, rel, "0")
        plugin = CgroupReconcile(ctx)
        assert plugin.enabled()
        plugin.update()
        assert cg.cgroup_read(cg.MEMORY_MIN, rel, ctx.cfg) == "500"
        assert cg.cgroup_read(cg.MEMORY_HIGH, rel, ctx.cfg) == "1600"
        assert cg.cgroup_read(cg.MEMORY_WMARK_RATIO, rel, ctx.cfg) == "95"

    def test_resctrl_groups(self, tmp_path):
        clock = FakeClock()
        slo = crds.NodeSLO(
            resource_qos_be=crds.QoSStrategy(
                resctrl=crds.ResctrlQoS(cat_range_start_percent=0,
                                        cat_range_end_percent=30, mba_percent=50),
            )
        )
        ctx = make_ctx(tmp_path, clock, slo=slo)
        from tests.test_koordlet_system import TestResctrl

        fs = TestResctrl().make_fs(ctx.cfg, ways=10, domains=(0,))
        plugin = ResctrlQOS(ctx, fs=fs, tier_pids=lambda g: [42] if g == "BE" else [])
        plugin.update()
        be = fs.read_schemata(resctrl.GROUP_BE)
        assert be.l3 == {0: 0b111}  # 30% of 10 ways
        assert be.mb == {0: 50}
        assert fs.read_tasks(resctrl.GROUP_BE) == [42]

    def test_blkio_weight(self, tmp_path):
        clock = FakeClock()
        slo = crds.NodeSLO(
            resource_qos_be=crds.QoSStrategy(
                blkio=crds.BlkIOQoS(enable=True, weight=50),
            )
        )
        ctx = make_ctx(tmp_path, clock, slo=slo)
        rel = ctx.cfg.kube_qos_dir("besteffort")
        write_cgroup_file(ctx.cfg, cg.BLKIO_WEIGHT, rel, "100")
        BlkIOQOS(ctx).update()
        assert cg.cgroup_read(cg.BLKIO_WEIGHT, rel, ctx.cfg) == "50"

    def test_sysreconcile_no_compounding(self, tmp_path):
        clock = FakeClock()
        slo = crds.NodeSLO(
            system_strategy=crds.SystemStrategy(min_free_kbytes_factor=200,
                                                watermark_scale_factor=150)
        )
        ctx = make_ctx(tmp_path, clock, slo=slo)
        vm = ctx.cfg.proc_path("sys", "vm")
        os.makedirs(vm, exist_ok=True)
        with open(os.path.join(vm, "min_free_kbytes"), "w") as f:
            f.write("1000")
        with open(os.path.join(vm, "watermark_scale_factor"), "w") as f:
            f.write("10")
        plugin = SysReconcile(ctx)
        plugin.update()
        plugin.update()  # second tick must not re-scale
        assert open(os.path.join(vm, "min_free_kbytes")).read() == "2000"
        assert open(os.path.join(vm, "watermark_scale_factor")).read() == "150"


class TestQOSManagerTick:
    def test_interval_gating(self, tmp_path):
        clock = FakeClock()
        ctx = make_ctx(tmp_path, clock)

        class Fast:
            name = "fast"
            interval_seconds = 1.0
            runs = 0

            def enabled(self):
                return True

            def update(self):
                Fast.runs += 1

        class Slow(Fast):
            name = "slow"
            interval_seconds = 10.0
            runs = 0

            def update(self):
                Slow.runs += 1

        manager = QOSManager(ctx, [Fast(), Slow()])
        for _ in range(10):
            manager.tick()
            clock.tick(1.0)
        assert Fast.runs == 10
        assert Slow.runs == 1


class TestEvictorCooldown:
    def test_no_reevict_within_cooldown(self, tmp_path):
        clock = FakeClock()
        ctx = make_ctx(tmp_path, clock)
        evictor = Evictor(ctx, cooldown_seconds=300)
        p = be_pod("a")
        assert evictor.evict(p, "r")
        assert not evictor.evict(p, "r")
        clock.tick(301)
        assert evictor.evict(p, "r")


def test_suppress_formula_invariants_random():
    """Randomized invariants of the BE suppress formula: the allowable
    always lands in [BE_MIN floor, capacity], never grows faster than
    the rate limit, and is non-increasing in LS usage (more
    latency-sensitive load can only shrink the best-effort share)."""
    import numpy as np

    from koordinator_tpu.koordlet.qosmanager.cpusuppress import (
        BE_MIN_CPUS,
        calculate_be_suppress_milli,
    )

    rng = np.random.default_rng(0)
    for _ in range(300):
        # from sub-floor 1-CPU nodes up to 128 cores: the floor itself
        # must clamp to capacity on tiny machines
        cap = int(rng.integers(1, 129)) * 1000
        be_used = int(rng.integers(0, cap // 2))
        node_used = be_used + int(rng.integers(0, cap))
        thr = int(rng.integers(10, 100))
        prev = (int(rng.integers(0, cap))
                if rng.random() < 0.5 else None)
        a = calculate_be_suppress_milli(cap, node_used, be_used, thr,
                                        prev_allowable_milli=prev)
        floor = min(BE_MIN_CPUS * 1000, cap)
        assert floor <= a <= cap, (cap, node_used, thr, a)
        if prev is not None and a > prev:
            # the BE minimum floor overrides the rate limit (a sub-floor
            # prev must not hold the result under the guarantee)
            step = max(cap * 5 // 100, 1000)
            assert a <= max(prev + step, floor), (a, prev, step)
        # monotone in LS usage
        a_more_ls = calculate_be_suppress_milli(
            cap, node_used + 500, be_used, thr,
            prev_allowable_milli=prev)
        assert a_more_ls <= a, (a_more_ls, a)
