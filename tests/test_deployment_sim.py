"""All six binaries composed into one deployment, over their real CLIs.

test_e2e_sim proves the LIBRARY objects stitch into the reference's
flows; this proves the BINARIES do — every component assembled exactly
as `python -m ... <flags>` would, wired over the same sockets a real
deployment uses (SURVEY §2.1): the manager's webhook admits a colocated
pod, the scheduler binary solves it over its listen socket, the
device-daemon's Device CR feeds the scheduler's device manager, the
runtime-proxy binary dispatches container hooks to the koordlet
binary's hook server across TWO RpcServers, and the descheduler binary
runs a round over the resulting cluster view.
"""

import os

import numpy as np
import pytest

from koordinator_tpu.api import crds, extension as ext
from koordinator_tpu.api.qos import QoSClass
from koordinator_tpu.api.resources import resource_vector
from koordinator_tpu.cmd.binaries import MAINS
from koordinator_tpu.koordlet.runtimehooks.server import RemoteHookServer
from koordinator_tpu.koordlet.system.config import make_test_config
from koordinator_tpu.runtimeproxy import HookRequest, HookType
from koordinator_tpu.scheduler.snapshot import NodeSpec, PodSpec
from koordinator_tpu.transport import RpcClient
from koordinator_tpu.transport.services import solve_remote


@pytest.fixture
def deployment(tmp_path):
    cfg = make_test_config(tmp_path)
    # fake sysfs: one TPU accel device for the device daemon to probe
    os.makedirs(os.path.join(cfg.sys_root, "class", "accel", "accel0"),
                exist_ok=True)

    assembled = {}
    clients = []
    try:
        assembled["scheduler"] = MAINS["koord-scheduler"]([
            "--node-capacity", "16",
            "--listen-socket", str(tmp_path / "sched.sock"),
        ])
        assembled["manager"] = MAINS["koord-manager"]([])
        assembled["koordlet"] = MAINS["koordlet"]([
            "--cgroup-root-dir", cfg.cgroup_root,
            "--proc-root-dir", cfg.proc_root,
            "--sys-root-dir", cfg.sys_root,
            "--runtime-hook-server-addr", str(tmp_path / "hooks.sock"),
        ])
        assembled["proxy"] = MAINS["koord-runtime-proxy"]([
            "--hook-server-socket", str(tmp_path / "proxy-hooks.sock"),
        ])
        assembled["descheduler"] = MAINS["koord-descheduler"](
            ["--deschedule-plugins", "podlifetime"],
            pods_fn=lambda: [])
        assembled["device-daemon"] = MAINS["koord-device-daemon"]([
            "--node-name", "n0", "--sys-root-dir", cfg.sys_root,
        ])

        def connect(addr):
            client = RpcClient(addr)
            client.connect()
            clients.append(client)
            return client

        yield assembled, connect, cfg
    finally:
        for client in clients:
            client.close()
        for asm in assembled.values():
            if getattr(asm, "server", None) is not None:
                asm.server.stop()
            stop = getattr(asm.component, "stop", None)
            if callable(stop):
                stop()


def test_six_binaries_one_pod_flow(deployment):
    assembled, connect, cfg = deployment
    scheduler = assembled["scheduler"].component
    manager = assembled["manager"].component

    # --- 1. manager webhook: colocation profile turns a plain spark pod
    # into a BE pod with batch resources
    manager.pod_mutating.profiles.append(crds.ClusterColocationProfile(
        name="colo", pod_selector={"app": "spark"}, qos_class="BE",
        koordinator_priority=5500, scheduler_name="koord-scheduler"))
    pod = {
        "metadata": {"name": "spark-1", "namespace": "default",
                     "labels": {"app": "spark"}},
        "spec": {"containers": [{"name": "m", "resources": {
            "requests": {"cpu": "2", "memory": "4Gi"},
            "limits": {"cpu": "2", "memory": "4Gi"}}}]},
    }
    manager.pod_mutating.mutate(pod)
    assert manager.pod_validating.validate(pod) == []
    requests = pod["spec"]["containers"][0]["resources"]["requests"]
    assert requests[ext.RESOURCE_BATCH_CPU] == 2000

    # --- 2. device daemon probes the fake sysfs into a Device CR; the
    # scheduler's device manager ingests the converted inventory (the
    # same path the Device-CR sync uses: devices.py -> deltasync:507)
    from koordinator_tpu.koordlet.devices import device_infos_to_inventory

    device = assembled["device-daemon"].component.collect()
    assert [d.type for d in device.devices] == ["xpu"]

    scheduler.snapshot.upsert_node(NodeSpec(
        name="n0",
        allocatable=resource_vector({
            "cpu": 16_000, "memory": 32_768,
            ext.RESOURCE_BATCH_CPU: 12_000,
            ext.RESOURCE_BATCH_MEMORY: 24_576,
        })))
    for dev_type, inventory in device_infos_to_inventory(
            list(device.devices)).items():
        scheduler.device_manager.register_node_devices(
            dev_type, "n0", inventory)
    assert scheduler.device_manager.state("xpu") is not None

    # --- 3. the admitted pod schedules over the scheduler binary's
    # listen socket (the sidecar solve path)
    scheduler.enqueue(PodSpec(
        name="spark-1",
        requests=resource_vector({
            ext.RESOURCE_BATCH_CPU: 2000,
            ext.RESOURCE_BATCH_MEMORY: 4 << 10,
        }),
        priority=5500, qos=int(QoSClass.BE)))
    solve_client = connect(assembled["scheduler"].server.path)
    result = solve_remote(solve_client)
    assert result["assignments"] == {"spark-1": "n0"}

    # --- 4. the runtime proxy dispatches the container hooks to the
    # koordlet BINARY's hook server (proxy dispatcher -> RemoteHookServer
    # -> koordlet RpcServer -> RegistryHookServer -> plugins)
    proxy = assembled["proxy"].component
    hook_client = connect(assembled["koordlet"].component.hook_server.path)
    proxy.dispatcher.register(RemoteHookServer(hook_client), list(HookType))
    forwarded = {}
    proxy.backend["CreateContainer"] = (
        lambda req: forwarded.setdefault("create", req))
    request = HookRequest(
        pod_meta={"uid": "spark-1", "name": "spark-1"},
        container_meta={"name": "m", "id": "c1"},
        labels={ext.LABEL_POD_QOS: "BE"},
        cgroup_parent="kubepods/besteffort/podspark-1",
        resources={ext.RESOURCE_BATCH_CPU: 2000,
                   ext.RESOURCE_BATCH_MEMORY: 4 << 30},
    )
    proxy.create_container("c1", request, pod_id="spark-1")
    merged = forwarded["create"].resources
    assert merged["cpu.cfs_quota"] == "200000"   # 2000m over CFS_PERIOD
    assert merged["memory.limit"] == str(4 << 30)
    assert merged["cpu.bvt_warp_ns"] == "-1"     # BE group identity

    # --- 5. the descheduler binary runs a clean round over the cluster
    descheduler = assembled["descheduler"].component
    assert descheduler.run_once() == {"default": 0}


def test_nodemetric_loop_over_the_wire(tmp_path):
    """SURVEY §3.2's report loop in its wire form: the koordlet BINARY
    measures the node and pushes node_usage frames to the scheduler
    BINARY's sidecar, whose in-process binding refreshes the solver's
    usage rows — no Python glue between the two beyond their CLIs."""
    import os
    import time

    from koordinator_tpu.cmd.binaries import (
        main_koord_scheduler,
        main_koordlet,
    )

    sched_asm = main_koord_scheduler([
        "--node-capacity", "8",
        "--listen-socket", str(tmp_path / "sidecar.sock"),
        "--disable-leader-election",
    ])
    cfg = make_test_config(tmp_path)
    os.makedirs(cfg.proc_root, exist_ok=True)

    def write_proc(total_jiffies):
        with open(cfg.proc_path("stat"), "w") as f:
            f.write(f"cpu  {total_jiffies} 0 0 1000 0 0 0 0 0 0\n")
        with open(cfg.proc_path("meminfo"), "w") as f:
            f.write("MemTotal: 16777216 kB\nMemAvailable: 8388608 kB\n"
                    "Cached: 0 kB\nBuffers: 0 kB\nMemFree: 8388608 kB\n")

    koordlet_asm = None
    try:
        # the sidecar must know the node before usage can attach to it
        sched_asm.state_sync.upsert_node(
            "n-metric", resource_vector(cpu=16_000, memory=16_384))

        write_proc(0)
        koordlet_asm = main_koordlet([
            "--cgroup-root-dir", cfg.cgroup_root,
            "--proc-root-dir", cfg.proc_root,
            "--sys-root-dir", cfg.sys_root,
            "--scheduler-sidecar-addr", str(tmp_path / "sidecar.sock"),
            "--node-name", "n-metric",
            "--nodemetric-report-interval-seconds", "0",
        ])
        daemon = koordlet_asm.component
        daemon.tick()                      # first sample (no rate yet)
        time.sleep(0.05)
        write_proc(400)                    # ~cpu burn since last sample
        # reporter rounds run off-thread; tick until the push lands
        snapshot = sched_asm.component.snapshot
        usage_cpu = 0
        deadline = time.monotonic() + 20
        while usage_cpu == 0 and time.monotonic() < deadline:
            daemon.tick()
            time.sleep(0.05)
            snapshot.flush()
            row = snapshot.node_index["n-metric"]
            usage_cpu = int(np.asarray(
                snapshot.state.node_usage)[row][0])
        assert usage_cpu > 0, "pushed usage never reached the solver"
        # and the sync service's stored node carries it for bootstrap
        stored = sched_asm.state_sync.nodes["n-metric"]["arrays"]
        assert int(np.asarray(stored["usage"])[0]) == usage_cpu
        # the colocation-formula inputs ride the same frames
        assert "sys_usage" in stored and "hp_usage" in stored

        # pod-band usage: a running Prod pod's reported usage lands in
        # hp_usage (the colocation formula's HP term) AND prod_usage
        # (loadaware's prod-usage mode input) on the next report
        from koordinator_tpu.api.qos import QoSClass as QC
        from koordinator_tpu.koordlet import metriccache as mcache
        from koordinator_tpu.koordlet.statesinformer import PodMeta

        daemon.states.set_pods([PodMeta(
            uid="prod-1", name="prod-1", namespace="default",
            qos_class=QC.LS, kube_qos="burstable", priority=9_500)])
        now = daemon.clock()
        for dt in (0, 1):
            daemon.metric_cache.append(
                mcache.POD_CPU_USAGE, 1.5,
                labels={"pod_uid": "prod-1"}, ts=now + dt)
            daemon.metric_cache.append(
                mcache.POD_MEMORY_USAGE, 2.0 * (1 << 30),
                labels={"pod_uid": "prod-1"}, ts=now + dt)
        deadline = time.monotonic() + 20
        prod_cpu = 0
        while prod_cpu == 0 and time.monotonic() < deadline:
            daemon.tick()
            time.sleep(0.05)
            stored = sched_asm.state_sync.nodes["n-metric"]["arrays"]
            prod_cpu = int(np.asarray(
                stored.get("prod_usage", np.zeros(1)))[0])
        assert prod_cpu == 1_500, "prod-band usage never reached the wire"
        assert int(np.asarray(stored["hp_usage"])[0]) == 1_500
        assert int(np.asarray(stored["hp_usage"])[1]) == 2_048  # MiB
    finally:
        if koordlet_asm is not None:
            koordlet_asm.component.stop()
        sched_asm.stop()


def test_device_inventory_loop_over_the_wire(tmp_path):
    """The Device-CR report loop in wire form, INCLUDING disappearance:
    the koordlet binary's default sink pushes node_devices frames on
    change, and when every device vanishes it pushes the EMPTY inventory
    so the scheduler's live tensors clear (a skip-when-empty sink would
    leave the node allocatable forever — live-vs-replay divergence)."""
    import shutil
    import time

    from koordinator_tpu.cmd.binaries import (
        main_koord_scheduler,
        main_koordlet,
    )
    from koordinator_tpu.features import KOORDLET_GATES

    sched_asm = main_koord_scheduler([
        "--node-capacity", "8",
        "--listen-socket", str(tmp_path / "devloop.sock"),
        "--disable-leader-election",
    ])
    cfg = make_test_config(tmp_path)
    accel_root = os.path.join(cfg.sys_root, "class", "accel", "accel0")
    os.makedirs(accel_root, exist_ok=True)
    for fn, val in (("uuid", "GPU-0"), ("minor", "0"),
                    ("mem_total", "81920"), ("mem_used", "0"),
                    ("usage_pct", "0"), ("numa_node", "0"),
                    ("health", "1"), ("type", "gpu")):
        with open(os.path.join(accel_root, fn), "w") as f:
            f.write(val)
    os.makedirs(cfg.proc_root, exist_ok=True)
    with open(cfg.proc_path("stat"), "w") as f:
        f.write("cpu  0 0 0 0 0 0 0 0 0 0\n")
    with open(cfg.proc_path("meminfo"), "w") as f:
        f.write("MemTotal: 1024 kB\nMemAvailable: 512 kB\nCached: 0\n")

    koordlet_asm = None
    KOORDLET_GATES.set("Accelerators", True)
    try:
        sched_asm.state_sync.upsert_node(
            "n-dev", resource_vector(cpu=8_000, memory=8_192))
        koordlet_asm = main_koordlet([
            "--cgroup-root-dir", cfg.cgroup_root,
            "--proc-root-dir", cfg.proc_root,
            "--sys-root-dir", cfg.sys_root,
            "--scheduler-sidecar-addr", str(tmp_path / "devloop.sock"),
            "--node-name", "n-dev",
            "--device-report-interval-seconds", "0",
        ])
        daemon = koordlet_asm.component
        from koordinator_tpu.koordlet.statesinformer import NodeInfo

        daemon.states.set_node(NodeInfo(name="n-dev", allocatable={}))
        manager = sched_asm.component.device_manager

        def live_gpus():
            state = manager.state("gpu")
            return 0 if state is None else int(np.asarray(state.valid).sum())

        deadline = time.monotonic() + 20
        while live_gpus() == 0 and time.monotonic() < deadline:
            daemon.tick()
            time.sleep(0.05)
        assert live_gpus() == 1, "device push never reached the solver"

        # a label-only re-upsert on the server clears the node's device
        # inventory (upsert replaces the doc wholesale); the koordlet's
        # HEARTBEAT re-push must restore it — a pure push-on-change
        # cache would strand the node device-less forever
        sched_asm.state_sync.upsert_node(
            "n-dev", resource_vector(cpu=8_000, memory=8_192),
            labels={"zone": "b"})
        assert live_gpus() == 0     # cleared by the re-upsert
        deadline = time.monotonic() + 20
        while live_gpus() == 0 and time.monotonic() < deadline:
            daemon.tick()
            time.sleep(0.05)
        assert live_gpus() == 1, "heartbeat never restored the inventory"

        # the whole accel class vanishes: the sink must push {} so the
        # scheduler clears the type (and the stored doc matches replay)
        shutil.rmtree(os.path.dirname(accel_root))
        deadline = time.monotonic() + 20
        while live_gpus() > 0 and time.monotonic() < deadline:
            daemon.tick()
            time.sleep(0.05)
        assert live_gpus() == 0, "vanished inventory never cleared"
        stored = sched_asm.state_sync.nodes["n-dev"]["doc"]["devices"]
        assert stored == {}
        assert daemon.device_push_failures == 0
    finally:
        KOORDLET_GATES.set("Accelerators", False)
        if koordlet_asm is not None:
            koordlet_asm.component.stop()
        sched_asm.stop()


def test_colocation_loop_binary_to_binary(tmp_path):
    """SURVEY §3.2 closed end to end over real sockets (VERDICT r4 next
    #2): the koordlet BINARY reports node usage to the scheduler
    sidecar, the manager BINARY's noderesource reconcile computes
    batch allocatable from that usage and pushes a node_allocatable
    event back through ITS sidecar client, and the scheduler binary's
    next solve sees the new batch capacity — a BE pod with batch-cpu
    requests goes from unschedulable to scheduled with no Python glue
    between the three beyond their CLIs.  Reference shape:
    slo-controller/noderesource/noderesource_controller.go:71 ->
    plugins/batchresource/plugin.go:188 -> node status patch ->
    scheduler informer."""
    import time

    import jax.numpy as jnp

    from koordinator_tpu.api.resources import ResourceDim
    from koordinator_tpu.cmd.binaries import (
        main_koord_manager,
        main_koord_scheduler,
        main_koordlet,
    )

    sched_asm = main_koord_scheduler([
        "--node-capacity", "8",
        "--listen-socket", str(tmp_path / "colo.sock"),
        "--disable-leader-election",
    ])
    cfg = make_test_config(tmp_path)
    os.makedirs(cfg.proc_root, exist_ok=True)

    def write_proc(total_jiffies):
        with open(cfg.proc_path("stat"), "w") as f:
            f.write(f"cpu  {total_jiffies} 0 0 1000 0 0 0 0 0 0\n")
        with open(cfg.proc_path("meminfo"), "w") as f:
            f.write("MemTotal: 16777216 kB\nMemAvailable: 12582912 kB\n"
                    "Cached: 0 kB\nBuffers: 0 kB\nMemFree: 12582912 kB\n")

    koordlet_asm = manager_asm = None
    try:
        scheduler = sched_asm.component
        # the node registers with BASE capacity only — no batch dims yet
        sched_asm.state_sync.upsert_node(
            "n-colo", resource_vector(cpu=16_000, memory=16_384))

        # a BE pod requesting batch resources: unschedulable while no
        # node advertises batch capacity
        sched_asm.state_sync.add_pod(
            "be-1", resource_vector({
                ext.RESOURCE_BATCH_CPU: 2_000,
                ext.RESOURCE_BATCH_MEMORY: 1_024}),
            priority=5500, qos=int(QoSClass.BE))
        solve_client = RpcClient(sched_asm.server.path)
        solve_client.connect()
        result = solve_remote(solve_client)
        assert "be-1" in result["failures"], result

        # koordlet binary reports usage over the wire
        write_proc(0)
        koordlet_asm = main_koordlet([
            "--cgroup-root-dir", cfg.cgroup_root,
            "--proc-root-dir", cfg.proc_root,
            "--sys-root-dir", cfg.sys_root,
            "--scheduler-sidecar-addr", str(tmp_path / "colo.sock"),
            "--node-name", "n-colo",
            "--nodemetric-report-interval-seconds", "0",
        ])
        daemon = koordlet_asm.component
        daemon.tick()
        # the collector's cpu rate is jiffies-delta / wall-delta: keep
        # the burn small and the gap large so the reported usage stays
        # WELL under the loadaware threshold regardless of test-run
        # timing (40 jiffies / >=0.5s <= 0.8 cores of 16) — the BE pod
        # must be gated on BATCH CAPACITY, not on usage pressure
        time.sleep(0.5)
        write_proc(40)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            daemon.tick()
            time.sleep(0.05)
            stored = sched_asm.state_sync.nodes["n-colo"]["arrays"]
            if int(np.asarray(stored.get(
                    "usage", np.zeros(1)))[0]) > 0:
                break
        else:
            raise AssertionError("koordlet usage never reached the sidecar")

        # manager binary: watches the same sidecar, reconciles, pushes
        manager_asm = main_koord_manager([
            "--scheduler-sidecar-addr", str(tmp_path / "colo.sock"),
        ])
        manager = manager_asm.component
        # the sidecar client dials lazily: the first tick bootstraps the
        # watch and reconciles.  A transient cpu-rate spike (the jiffies
        # delta over a tiny wall gap right after startup) can make the
        # first reconcile legitimately compute batch=0 — the REAL system
        # corrects on the next report+reconcile cadence, so the test
        # keeps the whole loop ticking (fresh usage samples decay the
        # rate, the manager re-pushes past the diff threshold) until the
        # scheduler's device-resident allocatable carries the capacity.
        row = scheduler.snapshot.node_index["n-colo"]
        deadline = time.monotonic() + 30
        batch_cpu = 0
        while batch_cpu < 2_000 and time.monotonic() < deadline:
            daemon.tick()
            manager.colocation_loop.tick()
            scheduler.snapshot.flush()
            batch_cpu = int(np.asarray(
                scheduler.snapshot.state.node_allocatable
            )[row][int(ResourceDim.BATCH_CPU)])
            time.sleep(0.1)
        assert manager.colocation_loop.connect_failures == 0
        assert batch_cpu >= 2_000, (
            f"batch capacity {batch_cpu} too small for the BE pod "
            f"(pushes={manager.colocation_loop.push_failures})")

        # and the BE pod now schedules — over the same solve socket
        result = solve_remote(solve_client)
        assert result["assignments"].get("be-1") == "n-colo", result
        solve_client.close()
    finally:
        if koordlet_asm is not None:
            koordlet_asm.component.stop()
        if manager_asm is not None:
            manager_asm.component.stop()
        sched_asm.stop()
