"""Shared scaffolding for tests that spawn real subprocess replicas
(cross-process HA, multihost): env setup, stderr capture, spawn, liveness
polling, teardown — one copy instead of one per test file."""

import os
import subprocess
import sys
import time


def replica_env():
    """Subprocess env with the repo importable and no inherited XLA_FLAGS."""
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)
    return repo_root, env


def spawn_replicas(script_path, idents_args, tmp_path):
    """Start one subprocess per (ident, argv-tail); stderr goes to
    ``tmp_path/stderr-<ident>`` so failures carry the real traceback.
    Returns (procs, stderr_paths)."""
    repo_root, env = replica_env()
    procs, errs = {}, {}
    for ident, args in idents_args.items():
        errs[ident] = tmp_path / f"stderr-{ident}"
        procs[ident] = subprocess.Popen(
            [sys.executable, str(script_path), *args],
            env=env, cwd=repo_root,
            stdout=subprocess.DEVNULL,
            stderr=open(errs[ident], "w"))
    return procs, errs


def wait_for(predicate, procs, errs, deadline_s, what):
    """Poll ``predicate()`` until true; fail FAST with the dead replica's
    stderr if any process exits first, and with ``what`` on timeout."""
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        if predicate():
            return
        for ident, proc in procs.items():
            if proc.poll() is not None:
                tail = errs[ident].read_text()[-3000:]
                raise AssertionError(
                    f"replica {ident} exited rc={proc.returncode} while "
                    f"waiting for {what}:\n{tail}")
        time.sleep(0.2)
    raise AssertionError(f"timed out waiting for {what}")


def kill_all(procs):
    for p in procs.values():
        if p.poll() is None:
            p.kill()
