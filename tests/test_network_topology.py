import jax.numpy as jnp
import numpy as np

from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS, ResourceDim
from koordinator_tpu.ops.network_topology import (
    TopologyRequirements,
    TopologyTree,
    aggregate_tree,
    constrain_multiples,
    eligible_candidates,
    gang_offer_slots,
    plan_gang_placement,
)
from koordinator_tpu.state.cluster_state import ClusterState, PodBatch

R = NUM_RESOURCE_DIMS
CPU, MEM = ResourceDim.CPU, ResourceDim.MEMORY


def mk_tree(spines=2, blocks=2, nodes=2):
    """spines x blocks x nodes tree; node i path = (s{a}, b{a}.{b}, n{i})."""
    tree = TopologyTree(["spine", "block", "node"])
    idx = 0
    for s in range(spines):
        for b in range(blocks):
            for _ in range(nodes):
                tree.add_node([f"s{s}", f"b{s}.{b}", f"n{idx}"])
                idx += 1
    return tree.build(), idx


def mk_state(node_cpus, mem=65_536):
    alloc = np.zeros((len(node_cpus), R), np.int32)
    alloc[:, CPU] = node_cpus
    alloc[:, MEM] = mem
    return ClusterState.from_arrays(alloc)


def mk_gang_pods(n, cpu, state, total=None):
    total = total or n
    req = np.zeros((total, R), np.int32)
    req[:n, CPU] = cpu
    req[:n, MEM] = 1_024
    pods = PodBatch.build(req, node_capacity=state.capacity)
    mask = np.zeros(pods.capacity, bool)
    mask[:n] = True
    return pods, mask


def test_offer_slots_prefix_fit():
    state = mk_state([10_000, 5_000, 1_000])
    req = np.zeros((4, R), np.int32)
    req[:, CPU] = 3_000
    req[:, MEM] = 1_024
    slots = gang_offer_slots(state, jnp.asarray(req), state.node_valid)
    assert slots[:3].tolist() == [3, 1, 0]


def test_aggregate_and_layers():
    topo, n = mk_tree()  # 8 nodes, 2 spines, 4 blocks
    slots = jnp.ones(n, jnp.int32)
    t_slots, _, _ = aggregate_tree(topo, slots, slots * 0, slots * 0)
    layer = np.asarray(topo.topo_layer)
    s = np.asarray(t_slots)
    assert s[layer == 0].tolist() == [8]          # cluster root
    assert sorted(s[layer == 1].tolist()) == [4, 4]    # spines
    assert sorted(s[layer == 2].tolist()) == [2, 2, 2, 2]  # blocks


def test_constrain_multiples_rounds_down_bottom_up():
    topo, n = mk_tree(spines=1, blocks=2, nodes=2)  # 4 nodes
    slots = jnp.asarray([3, 3, 3, 3], jnp.int32)
    t_slots, _, _ = aggregate_tree(topo, slots, slots * 0, slots * 0)
    # node-layer multiple of 2: each node 3 -> 2; blocks 4; root 8
    mults = jnp.asarray([1, 1, 1, 2], jnp.int32)
    out = np.asarray(constrain_multiples(topo, t_slots, mults))
    layer = np.asarray(topo.topo_layer)
    assert (out[layer == 3] == 2).all()
    assert (out[layer == 2] == 4).all()
    assert out[layer == 0] == 8


def test_eligible_picks_deepest_layer():
    topo, n = mk_tree()  # 2 slots per node
    slots = jnp.full(n, 2, jnp.int32)
    t_slots, _, _ = aggregate_tree(topo, slots, slots * 0, slots * 0)
    # desired=4 fits in a block (4 slots) -> deepest layer is block (2)
    cand, deepest = eligible_candidates(topo, t_slots, jnp.int32(4), jnp.int32(-1))
    assert int(deepest) == 2
    assert int(cand.sum()) == 4  # every block qualifies
    # desired=6 needs a spine (8 slots)
    cand, deepest = eligible_candidates(topo, t_slots, jnp.int32(6), jnp.int32(-1))
    assert int(deepest) == 1
    assert int(cand.sum()) == 2


def test_plan_packs_gang_into_one_block():
    topo, n = mk_tree()
    state = mk_state([10_000] * n)
    pods, mask = mk_gang_pods(4, 4_000, state)  # 2 fit per node -> one block fits 4
    plan = plan_gang_placement(
        state, pods, mask, topo, TopologyRequirements(desired_slots=4)
    )
    chosen = plan[:4]
    assert (chosen >= 0).all()
    # all 4 pods land inside a single block (nodes 2k, 2k+1)
    blocks = set(chosen // 2)
    assert len(blocks) == 1


def test_plan_prefers_block_with_existing_peers():
    topo, n = mk_tree()
    state = mk_state([10_000] * n)
    pods, mask = mk_gang_pods(2, 4_000, state)
    existing = jnp.zeros(n, jnp.int32).at[5].set(3)  # peers on node 5 (block 2)
    plan = plan_gang_placement(
        state, pods, mask, topo, TopologyRequirements(desired_slots=2),
        node_existing=existing,
    )
    assert set(plan[:2] // 2) == {2}


def test_plan_respects_must_gather_infeasible():
    topo, n = mk_tree()
    state = mk_state([10_000] * n)
    # 6 pods cannot gather in any single block (4 slots max)
    pods, mask = mk_gang_pods(6, 4_000, state)
    plan = plan_gang_placement(
        state, pods, mask, topo,
        TopologyRequirements(desired_slots=6, must_gather_layer=2),
    )
    assert (plan == -1).all()
    # but a spine (8 slots) gathers them
    plan = plan_gang_placement(
        state, pods, mask, topo,
        TopologyRequirements(desired_slots=6, must_gather_layer=1),
    )
    assert (plan[:6] >= 0).all()
    assert len(set(plan[:6] // 4)) == 1  # one spine


def test_plan_pod_count_multiple():
    topo, n = mk_tree()
    state = mk_state([10_000] * n)
    pods, mask = mk_gang_pods(4, 4_000, state)
    # node-layer multiple 2: nodes offering 2 stay 2; plan still fills a block
    plan = plan_gang_placement(
        state, pods, mask, topo,
        TopologyRequirements(desired_slots=4, layer_multiples=(1, 1, 1, 2)),
    )
    counts = np.bincount(plan[:4][plan[:4] >= 0], minlength=n)
    assert set(counts[counts > 0]) == {2}
