"""Round-loop convergence across seeds at a mid shape, in DEFAULT CI.

The slow-marked north-star guards (test_north_star_shape.py) pin wave
convergence for one seed at the full 50k x 10,240 shape; the randomized
property suites sweep small shapes.  This is the cheap middle ground
(VERDICT r4 weak #6): three seeds at 15k pods x 3,072 nodes under ~2x
capacity surplus must each converge to full placement within 3 waves —
keeping the contention-convergence claim honest without slow-CI cost.
One jit compile serves all seeds and waves (same shapes throughout).
"""

import jax
import jax.numpy as jnp
import numpy as np

from __graft_entry__ import _build_problem
from koordinator_tpu.ops.batch_assign import batch_assign

N_NODES = 3_072
N_PODS = 15_000
MAX_WAVES = 3


def test_moderate_load_converges_across_seeds():
    solve = None
    for seed in (1, 7, 42):
        state, pods, cfg = _build_problem(N_NODES, N_PODS, seed=seed)
        if solve is None:
            solve = jax.jit(lambda s, p, c: batch_assign(
                s, p, c, k=16, method="approx")[:2])
        # ~2x surplus: the same moderate-contention scaling the
        # north-star wave guard uses (11/20 of generated allocatable)
        st = state.replace(
            node_allocatable=(state.node_allocatable * 11) // 20)
        remaining = pods
        assigned = np.zeros(pods.capacity, bool)
        counts = []
        for _ in range(MAX_WAVES):
            asn, st = solve(st, remaining, cfg)
            wave = (np.asarray(asn) >= 0) & np.asarray(remaining.valid)
            counts.append(int(wave.sum()))
            assigned |= wave
            stranded = ~assigned & np.asarray(pods.valid)
            if not stranded.any():
                break
            remaining = remaining.replace(valid=jnp.asarray(stranded))
        assert (np.asarray(st.node_requested)
                <= np.asarray(st.node_allocatable)).all(), seed
        assert int(assigned.sum()) == N_PODS, (
            f"seed {seed}: waves {counts}, "
            f"{N_PODS - int(assigned.sum())} pods never placed")
        # wave 1 carries the bulk — the retry loop is a straggler
        # mechanism, not a crutch (same 95% bar as the north-star guard)
        assert counts[0] >= 0.95 * N_PODS, (seed, counts)
