"""Native shim tests: batch reader correctness + fallback, CPI counter
degradation, collector integration with pod churn."""

import os

import pytest

from koordinator_tpu import native


@pytest.fixture
def files(tmp_path):
    paths = []
    for i in range(20):
        p = tmp_path / f"f{i}"
        p.write_text(f"content {i}\n")
        paths.append(str(p))
    return paths


class TestBatchReader:
    def test_read_and_missing(self, files, tmp_path):
        reader = native.BatchReader(files + [str(tmp_path / "nope")])
        out = reader.read()
        assert out[0] == "content 0\n"
        assert out[19] == "content 19\n"
        assert out[20] is None

    def test_reread_sees_changes(self, files):
        reader = native.BatchReader(files[:1])
        assert reader.read()[0] == "content 0\n"
        with open(files[0], "w") as f:
            f.write("changed\n")
        assert reader.read()[0] == "changed\n"

    def test_truncation(self, tmp_path):
        p = tmp_path / "big"
        p.write_text("x" * 10000)
        out = native.BatchReader([str(p)], max_bytes=128).read()
        assert out[0] is not None and len(out[0]) <= 127

    def test_empty(self):
        assert native.BatchReader([]).read() == []

    def test_python_fallback_matches(self, files, tmp_path, monkeypatch):
        native_out = native.BatchReader(files + [str(tmp_path / "no")]).read()
        reader = native.BatchReader(files + [str(tmp_path / "no")])
        reader._lib = None  # force fallback
        assert reader.read() == native_out


class TestCPICounter:
    def test_graceful_unavailable(self, tmp_path):
        counter = native.CPICounter(str(tmp_path / "nonexistent"), 4)
        # either perf works (real kernel + perms) or open() returns False;
        # a nonexistent cgroup dir must always be False
        assert counter.open() is False
        assert counter.read() is None
        counter.close()  # no-op, no crash


class TestCollectorChurnRebuild:
    def test_reader_rebuilt_on_pod_set_change(self, tmp_path):
        from koordinator_tpu.api.qos import QoSClass
        from koordinator_tpu.koordlet import metriccache as mc
        from koordinator_tpu.koordlet import metricsadvisor as ma
        from koordinator_tpu.koordlet.statesinformer import PodMeta, StatesInformer
        from koordinator_tpu.koordlet.system import cgroup as cg
        from koordinator_tpu.koordlet.system.config import make_test_config
        from tests.test_koordlet_metrics import FakeClock
        from tests.test_koordlet_system import write_cgroup_file

        cfg = make_test_config(tmp_path)
        clock = FakeClock()
        states = StatesInformer(clock=clock)
        cache = mc.MetricCache(clock=clock)
        collector = ma.PodResourceCollector(ma._Deps(states, cache, cfg, clock))

        def make(uid):
            p = PodMeta(uid=uid, name=uid, namespace="d",
                        qos_class=QoSClass.LS, kube_qos="burstable")
            write_cgroup_file(cfg, cg.CPUACCT_USAGE, p.cgroup_dir(cfg), "0")
            write_cgroup_file(cfg, cg.MEMORY_USAGE, p.cgroup_dir(cfg), "100")
            return p

        states.set_pods([make("a")])
        collector.collect()
        first_key = collector._reader_key
        assert len(first_key) == 2
        states.set_pods([make("a"), make("b")])
        collector.collect()
        assert len(collector._reader_key) == 4
        assert collector._reader_key != first_key
        # memory visible for both
        clock.tick(1)
        collector.collect()
        assert cache.query(mc.POD_MEMORY_USAGE, {"pod_uid": "b"},
                           0, clock.t + 1).latest() == 100.0
