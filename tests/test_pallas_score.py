"""Pallas fused Filter+Score+top-k (ops/pallas_score.py) vs the XLA
reference path — bit-exact value parity with
lax.top_k(_ranked_scores(*score_pods(...)), k) in interpret mode."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS, ResourceDim
from koordinator_tpu.ops.assignment import ScoringConfig, score_pods
from koordinator_tpu.ops.batch_assign import _ranked_scores
from koordinator_tpu.ops.pallas_score import fused_score_topk
from koordinator_tpu.state.cluster_state import ClusterState, PodBatch

R = NUM_RESOURCE_DIMS
CPU, MEM, GPU = ResourceDim.CPU, ResourceDim.MEMORY, ResourceDim.GPU


def reference_topk(state, pods, cfg, k):
    scores, feasible = score_pods(state, pods, cfg)
    return jax.lax.top_k(_ranked_scores(scores, feasible), k)


from tests.problem_helpers import build_problem, candidate_recall


def assert_parity(state, pods, cfg, k=16, tp=32, nc=32):
    got_val, got_idx = fused_score_topk(
        state, pods, cfg, k=k, tile_pods=tp, n_chunk=nc, interpret=True)
    want_val, want_idx = reference_topk(state, pods, cfg, k)
    np.testing.assert_array_equal(np.asarray(got_val), np.asarray(want_val))
    valid = np.asarray(want_val) >= 0
    np.testing.assert_array_equal(np.asarray(got_idx)[valid],
                                  np.asarray(want_idx)[valid])


def test_parity_default_config():
    state, pods = build_problem(seed=1)
    assert_parity(state, pods, ScoringConfig.default())


def test_parity_with_thresholds_and_invalid_nodes():
    state, pods = build_problem(seed=2, invalid_tail=8)
    cfg = ScoringConfig.default().replace(
        usage_thresholds=jnp.zeros(R, jnp.int32).at[CPU].set(65)
        .at[MEM].set(80))
    assert_parity(state, pods, cfg)


def test_parity_aggregated_thresholds_replace_instantaneous():
    state, pods = build_problem(seed=3)
    cfg = ScoringConfig.default().replace(
        usage_thresholds=jnp.zeros(R, jnp.int32).at[CPU].set(10),  # strict
        agg_usage_thresholds=jnp.zeros(R, jnp.int32).at[CPU].set(90))
    assert_parity(state, pods, cfg)


def test_parity_fitplus_most_allocated_and_scarce():
    state, pods = build_problem(seed=4)
    cfg = ScoringConfig.default().replace(
        fitplus_most_allocated=jnp.zeros(R, bool).at[CPU].set(True),
        scarce_dims=jnp.zeros(R, bool).at[GPU].set(True),
        scarce_plugin_weight=jnp.int32(2),
        loadaware_dominant_weight=jnp.int32(1),
    )
    assert_parity(state, pods, cfg)


def test_parity_uneven_tiling_and_k():
    state, pods = build_problem(n_nodes=128, n_pods=64, seed=5)
    assert_parity(state, pods, ScoringConfig.default(), k=32, tp=16, nc=64)


def test_parity_invalid_pods_padding():
    # PodBatch.build pads capacity; padded rows are invalid and must come
    # back all -1
    state, pods = build_problem(n_pods=100, seed=6)  # padded to 128
    got_val, _ = fused_score_topk(
        state, pods, ScoringConfig.default(), k=8, tile_pods=32,
        n_chunk=32, interpret=True)
    assert np.all(np.asarray(got_val)[100:] == -1)
    assert_parity(state, pods, ScoringConfig.default(), k=8, tp=32, nc=32)


def test_parity_spread_bits():
    # quantized ranking key (the batch_assign default) stays bit-exact
    state, pods = build_problem(seed=9)
    cfg = ScoringConfig.default()
    got_val, got_idx = fused_score_topk(
        state, pods, cfg, k=16, tile_pods=32, n_chunk=32, interpret=True,
        spread_bits=5)
    scores, feasible = score_pods(state, pods, cfg)
    want_val, want_idx = jax.lax.top_k(
        _ranked_scores(scores, feasible, spread_bits=5), 16)
    np.testing.assert_array_equal(np.asarray(got_val), np.asarray(want_val))
    valid = np.asarray(want_val) >= 0
    np.testing.assert_array_equal(np.asarray(got_idx)[valid],
                                  np.asarray(want_idx)[valid])


def test_parity_pod_axis_padding_to_tile():
    # capacity NOT a multiple of tile_pods: the wrapper pads the pod axis
    # and slices it back (north-star 50k % 128 != 0 regression)
    state, pods = build_problem(n_pods=128, seed=10)
    trimmed = jax.tree.map(
        lambda x: x[:96] if hasattr(x, "shape") and x.ndim >= 1
        and x.shape[0] == pods.capacity else x, pods)
    got_val, _ = fused_score_topk(
        state, trimmed, ScoringConfig.default(), k=8, tile_pods=64,
        n_chunk=32, interpret=True)
    assert got_val.shape[0] == 96
    want_val, _ = reference_topk(state, trimmed, ScoringConfig.default(), 8)
    np.testing.assert_array_equal(np.asarray(got_val), np.asarray(want_val))


def test_rejects_dense_batches():
    state, pods = build_problem(seed=7)
    dense = pods.replace(
        feasible=jnp.ones((pods.capacity, state.capacity), bool),
        selector_mask=None)
    with pytest.raises(ValueError, match="factored"):
        fused_score_topk(state, dense, ScoringConfig.default(),
                         interpret=True)


def test_assign_rounds_on_fused_candidates_matches_default():
    # end-to-end: the pallas candidates (interpret mode off-TPU) drive the
    # shared propose/accept stage to the same assignments as the XLA path
    from koordinator_tpu.ops.batch_assign import _assign_rounds, batch_assign

    state, pods = build_problem(n_nodes=64, n_pods=64, seed=8)
    cfg = ScoringConfig.default()
    a0, s0, _ = batch_assign(state, pods, cfg, k=16, spread_bits=5)
    ck, cn = fused_score_topk(state, pods, cfg, k=16, tile_pods=32,
                              n_chunk=32, interpret=True, spread_bits=5)
    a1, s1, _ = _assign_rounds(state, pods, None, ck, cn, rounds=12)
    np.testing.assert_array_equal(np.asarray(a0), np.asarray(a1))
    np.testing.assert_array_equal(np.asarray(s0.node_requested),
                                  np.asarray(s1.node_requested))


def test_sentinel_pool_survives_large_k_over_small_chunks():
    # k bigger than the chunk width with an all-infeasible first chunk:
    # the unique-sentinel fold must still emit -1 fills, never -2
    state, pods = build_problem(n_nodes=64, n_pods=32, seed=9)
    none_sel = pods.replace(
        selector_mask=jnp.zeros_like(pods.selector_mask))  # nothing feasible
    val, idx = fused_score_topk(state, none_sel, ScoringConfig.default(),
                                k=48, tile_pods=32, n_chunk=16,
                                interpret=True)
    assert np.all(np.asarray(val) == -1)
    assert np.all(np.asarray(idx) == 0)


def test_bucket_collisions_keep_high_recall():
    # L < N: nodes L apart share a bucket and recall becomes approximate.
    # The rotated tie-break ranks a pod's equal-scored candidates by
    # consecutive node index (distinct buckets), so recall stays high.
    state, pods = build_problem(n_nodes=256, n_pods=64, seed=11)
    cfg = ScoringConfig.default()
    k = 16
    got_val, got_idx = fused_score_topk(
        state, pods, cfg, k=k, tile_pods=32, n_chunk=32, n_bucket=128,
        interpret=True, spread_bits=5)
    scores, feasible = score_pods(state, pods, cfg)
    want_val, want_idx = jax.lax.top_k(
        _ranked_scores(scores, feasible, spread_bits=5), k)
    recall = candidate_recall(want_idx, want_val, got_idx)
    assert recall >= 0.9, f"bucket recall {recall:.3f} < 0.9"
    want_val, want_idx = np.asarray(want_val), np.asarray(want_idx)
    got_idx = np.asarray(got_idx)
    # returned keys are still the exact int keys of the returned nodes
    key_full = np.asarray(_ranked_scores(scores, feasible, spread_bits=5))
    gv = np.asarray(got_val)
    for p in range(got_idx.shape[0]):
        sel = gv[p] >= 0
        np.testing.assert_array_equal(gv[p][sel],
                                      key_full[p][got_idx[p][sel]])


def test_batch_assign_fused_topk_rejects_dense():
    from koordinator_tpu.ops.batch_assign import batch_assign

    state, pods = build_problem(seed=10)
    dense = pods.replace(
        feasible=jnp.ones((pods.capacity, state.capacity), bool),
        selector_mask=None)
    with pytest.raises(ValueError, match="factored"):
        batch_assign(state, dense, ScoringConfig.default(), fused_topk=True)
