"""Pallas fused Filter+Score+top-k (ops/pallas_score.py) vs the XLA
reference path — bit-exact value parity with
lax.top_k(_ranked_scores(*score_pods(...)), k) in interpret mode."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS, ResourceDim
from koordinator_tpu.ops.assignment import ScoringConfig, score_pods
from koordinator_tpu.ops.batch_assign import _ranked_scores
from koordinator_tpu.ops.pallas_score import fused_score_topk
from koordinator_tpu.state.cluster_state import ClusterState, PodBatch

R = NUM_RESOURCE_DIMS
CPU, MEM, GPU = ResourceDim.CPU, ResourceDim.MEMORY, ResourceDim.GPU


def reference_topk(state, pods, cfg, k):
    scores, feasible = score_pods(state, pods, cfg)
    return jax.lax.top_k(_ranked_scores(scores, feasible), k)


def build_problem(n_nodes=64, n_pods=128, seed=0, classes=3,
                  invalid_tail=0):
    rng = np.random.default_rng(seed)
    alloc = np.zeros((n_nodes, R), np.int32)
    alloc[:, CPU] = rng.integers(8_000, 64_000, n_nodes)
    alloc[:, MEM] = rng.integers(16_384, 262_144, n_nodes)
    alloc[:, GPU] = rng.integers(0, 2, n_nodes) * 8_000
    usage = (alloc * rng.random((n_nodes, R)) * 0.6).astype(np.int32)
    requested = (alloc * rng.random((n_nodes, R)) * 0.5).astype(np.int32)
    node_class = rng.integers(0, classes, n_nodes).astype(np.int32)
    if invalid_tail:
        alloc[-invalid_tail:] = 0
    state = ClusterState.from_arrays(
        alloc, requested=requested, usage=usage, capacity=n_nodes,
        node_class=node_class)
    if invalid_tail:
        valid = np.ones(n_nodes, bool)
        valid[-invalid_tail:] = False
        state = state.replace(node_valid=jnp.asarray(valid))

    req = np.zeros((n_pods, R), np.int32)
    req[:, CPU] = rng.integers(100, 4_000, n_pods)
    req[:, MEM] = rng.integers(128, 8_192, n_pods)
    req[rng.random(n_pods) < 0.2, GPU] = 1_000
    sel = rng.random((n_pods, 8)) < 0.7          # (P, C) selector classes
    sel[:, :classes] |= rng.random((n_pods, classes)) < 0.5
    cap = 1 << (n_pods - 1).bit_length()     # power-of-two padding
    pods = PodBatch.build(
        req, priority=rng.integers(3000, 9999, n_pods).astype(np.int32),
        node_capacity=n_nodes, capacity=cap,
        selector_mask=sel, class_capacity=8)
    return state, pods


def assert_parity(state, pods, cfg, k=16, tp=32, nc=32):
    got_val, got_idx = fused_score_topk(
        state, pods, cfg, k=k, tile_pods=tp, n_chunk=nc, interpret=True)
    want_val, want_idx = reference_topk(state, pods, cfg, k)
    np.testing.assert_array_equal(np.asarray(got_val), np.asarray(want_val))
    valid = np.asarray(want_val) >= 0
    np.testing.assert_array_equal(np.asarray(got_idx)[valid],
                                  np.asarray(want_idx)[valid])


def test_parity_default_config():
    state, pods = build_problem(seed=1)
    assert_parity(state, pods, ScoringConfig.default())


def test_parity_with_thresholds_and_invalid_nodes():
    state, pods = build_problem(seed=2, invalid_tail=8)
    cfg = ScoringConfig.default().replace(
        usage_thresholds=jnp.zeros(R, jnp.int32).at[CPU].set(65)
        .at[MEM].set(80))
    assert_parity(state, pods, cfg)


def test_parity_aggregated_thresholds_replace_instantaneous():
    state, pods = build_problem(seed=3)
    cfg = ScoringConfig.default().replace(
        usage_thresholds=jnp.zeros(R, jnp.int32).at[CPU].set(10),  # strict
        agg_usage_thresholds=jnp.zeros(R, jnp.int32).at[CPU].set(90))
    assert_parity(state, pods, cfg)


def test_parity_fitplus_most_allocated_and_scarce():
    state, pods = build_problem(seed=4)
    cfg = ScoringConfig.default().replace(
        fitplus_most_allocated=jnp.zeros(R, bool).at[CPU].set(True),
        scarce_dims=jnp.zeros(R, bool).at[GPU].set(True),
        scarce_plugin_weight=jnp.int32(2),
        loadaware_dominant_weight=jnp.int32(1),
    )
    assert_parity(state, pods, cfg)


def test_parity_uneven_tiling_and_k():
    state, pods = build_problem(n_nodes=128, n_pods=64, seed=5)
    assert_parity(state, pods, ScoringConfig.default(), k=32, tp=16, nc=64)


def test_parity_invalid_pods_padding():
    # PodBatch.build pads capacity; padded rows are invalid and must come
    # back all -1
    state, pods = build_problem(n_pods=100, seed=6)  # padded to 128
    got_val, _ = fused_score_topk(
        state, pods, ScoringConfig.default(), k=8, tile_pods=32,
        n_chunk=32, interpret=True)
    assert np.all(np.asarray(got_val)[100:] == -1)
    assert_parity(state, pods, ScoringConfig.default(), k=8, tp=32, nc=32)


def test_parity_spread_bits():
    # quantized ranking key (the batch_assign default) stays bit-exact
    state, pods = build_problem(seed=9)
    cfg = ScoringConfig.default()
    got_val, got_idx = fused_score_topk(
        state, pods, cfg, k=16, tile_pods=32, n_chunk=32, interpret=True,
        spread_bits=5)
    scores, feasible = score_pods(state, pods, cfg)
    want_val, want_idx = jax.lax.top_k(
        _ranked_scores(scores, feasible, spread_bits=5), 16)
    np.testing.assert_array_equal(np.asarray(got_val), np.asarray(want_val))
    valid = np.asarray(want_val) >= 0
    np.testing.assert_array_equal(np.asarray(got_idx)[valid],
                                  np.asarray(want_idx)[valid])


def test_parity_pod_axis_padding_to_tile():
    # capacity NOT a multiple of tile_pods: the wrapper pads the pod axis
    # and slices it back (north-star 50k % 128 != 0 regression)
    state, pods = build_problem(n_pods=128, seed=10)
    trimmed = jax.tree.map(
        lambda x: x[:96] if hasattr(x, "shape") and x.ndim >= 1
        and x.shape[0] == pods.capacity else x, pods)
    got_val, _ = fused_score_topk(
        state, trimmed, ScoringConfig.default(), k=8, tile_pods=64,
        n_chunk=32, interpret=True)
    assert got_val.shape[0] == 96
    want_val, _ = reference_topk(state, trimmed, ScoringConfig.default(), 8)
    np.testing.assert_array_equal(np.asarray(got_val), np.asarray(want_val))


def test_rejects_dense_batches():
    state, pods = build_problem(seed=7)
    dense = pods.replace(
        feasible=jnp.ones((pods.capacity, state.capacity), bool),
        selector_mask=None)
    with pytest.raises(ValueError, match="factored"):
        fused_score_topk(state, dense, ScoringConfig.default(),
                         interpret=True)


def test_assign_rounds_on_fused_candidates_matches_default():
    # end-to-end: the pallas candidates (interpret mode off-TPU) drive the
    # shared propose/accept stage to the same assignments as the XLA path
    from koordinator_tpu.ops.batch_assign import _assign_rounds, batch_assign

    state, pods = build_problem(n_nodes=64, n_pods=64, seed=8)
    cfg = ScoringConfig.default()
    a0, s0, _ = batch_assign(state, pods, cfg, k=16, spread_bits=5)
    ck, cn = fused_score_topk(state, pods, cfg, k=16, tile_pods=32,
                              n_chunk=32, interpret=True, spread_bits=5)
    a1, s1, _ = _assign_rounds(state, pods, None, ck, cn, rounds=12)
    np.testing.assert_array_equal(np.asarray(a0), np.asarray(a1))
    np.testing.assert_array_equal(np.asarray(s0.node_requested),
                                  np.asarray(s1.node_requested))


def test_sentinel_pool_survives_large_k_over_small_chunks():
    # k bigger than the chunk width with an all-infeasible first chunk:
    # the unique-sentinel fold must still emit -1 fills, never -2
    state, pods = build_problem(n_nodes=64, n_pods=32, seed=9)
    none_sel = pods.replace(
        selector_mask=jnp.zeros_like(pods.selector_mask))  # nothing feasible
    val, idx = fused_score_topk(state, none_sel, ScoringConfig.default(),
                                k=48, tile_pods=32, n_chunk=16,
                                interpret=True)
    assert np.all(np.asarray(val) == -1)
    assert np.all(np.asarray(idx) == 0)


def test_batch_assign_fused_topk_rejects_dense():
    from koordinator_tpu.ops.batch_assign import batch_assign

    state, pods = build_problem(seed=10)
    dense = pods.replace(
        feasible=jnp.ones((pods.capacity, state.capacity), bool),
        selector_mask=None)
    with pytest.raises(ValueError, match="factored"):
        batch_assign(state, dense, ScoringConfig.default(), fused_topk=True)
