"""Runtime hooks, PLEG and daemon-assembly tests."""

import os

import pytest

from koordinator_tpu.api import crds, extension as ext
from koordinator_tpu.api.qos import QoSClass
from koordinator_tpu.features import RUNTIMEHOOK_GATES
from koordinator_tpu.koordlet.pleg import (
    EVENT_CONTAINER_ADDED, EVENT_POD_ADDED, EVENT_POD_DELETED, PLEG,
)
from koordinator_tpu.koordlet.resourceexecutor import ResourceUpdateExecutor
from koordinator_tpu.koordlet.runtimehooks.hooks import HookRegistry, Stage
from koordinator_tpu.koordlet.runtimehooks.plugins import register_default_hooks
from koordinator_tpu.koordlet.runtimehooks.protocol import (
    ContainerContext, PodContext,
)
from koordinator_tpu.koordlet.runtimehooks.reconciler import Reconciler
from koordinator_tpu.koordlet.statesinformer import (
    ContainerMeta, PodMeta, StatesInformer,
)
from koordinator_tpu.koordlet.system import cgroup as cg
from koordinator_tpu.koordlet.system.config import make_test_config
from tests.test_koordlet_system import write_cgroup_file


@pytest.fixture
def cfg(tmp_path):
    return make_test_config(tmp_path)


@pytest.fixture
def gates():
    """Enable the optional hook gates for the test, restore after."""
    names = ["GPUEnvInject", "RDMADeviceInject", "CoreSched", "CPUNormalization"]
    for n in names:
        RUNTIMEHOOK_GATES.set(n, True)
    yield RUNTIMEHOOK_GATES
    for n in names:
        RUNTIMEHOOK_GATES.set(n, False)


def pod(qos=QoSClass.BE, kube_qos="besteffort", annotations=None, **kw):
    return PodMeta(
        uid="pod-1", name="pod-1", namespace="default", qos_class=qos,
        kube_qos=kube_qos, annotations=annotations or {}, **kw,
    )


def setup_registry(node_slo=None, **kwargs):
    registry = HookRegistry()
    slo = node_slo or crds.NodeSLO()
    register_default_hooks(registry, node_slo=lambda: slo, **kwargs)
    return registry


class TestHookPlugins:
    def test_group_identity_bvt(self, cfg):
        registry = setup_registry()
        be_ctx = PodContext.from_pod(pod(), cfg)
        registry.run(Stage.PRE_RUN_POD_SANDBOX, be_ctx)
        assert be_ctx.response.cgroup_values["cpu.bvt_warp_ns"] == "-1"
        ls_ctx = PodContext.from_pod(pod(qos=QoSClass.LS, kube_qos="burstable"), cfg)
        registry.run(Stage.PRE_RUN_POD_SANDBOX, ls_ctx)
        assert ls_ctx.response.cgroup_values["cpu.bvt_warp_ns"] == "2"

    def test_cpuset_from_annotation(self, cfg):
        ann = {}
        ext.set_resource_status(ann, "4-7")
        p = pod(qos=QoSClass.LSR, kube_qos="guaranteed", annotations=ann)
        registry = setup_registry()
        ctx = ContainerContext.from_container(p, ContainerMeta("c", "cid"), cfg)
        registry.run(Stage.PRE_CREATE_CONTAINER, ctx)
        assert ctx.response.cpuset_cpus == "4-7"

    def test_ls_share_pool(self, cfg):
        p = pod(qos=QoSClass.LS, kube_qos="burstable")
        registry = setup_registry(share_pool=lambda: "0-3")
        ctx = ContainerContext.from_container(p, ContainerMeta("c", "cid"), cfg)
        registry.run(Stage.PRE_CREATE_CONTAINER, ctx)
        assert ctx.response.cpuset_cpus == "0-3"

    def test_batch_resource_limits(self, cfg):
        p = pod(requests={
            ext.RESOURCE_BATCH_CPU: 1500, ext.RESOURCE_BATCH_MEMORY: 1 << 30,
        })
        registry = setup_registry()
        ctx = PodContext.from_pod(p, cfg)
        registry.run(Stage.PRE_UPDATE_CONTAINER, ctx)
        assert ctx.response.cgroup_values["cpu.cfs_quota"] == "150000"
        assert ctx.response.cgroup_values["memory.limit"] == str(1 << 30)
        assert ctx.response.cgroup_values["cpu.shares"] == str(1500 * 1024 // 1000)

    def test_batch_resource_skips_non_be(self, cfg):
        p = pod(qos=QoSClass.LS, kube_qos="burstable",
                requests={ext.RESOURCE_BATCH_CPU: 1500})
        registry = setup_registry()
        ctx = PodContext.from_pod(p, cfg)
        registry.run(Stage.PRE_UPDATE_CONTAINER, ctx)
        assert "cpu.cfs_quota" not in ctx.response.cgroup_values

    def test_gpu_env_inject(self, cfg, gates):
        ann = {}
        ext.set_device_allocations(ann, {"gpu": [
            {"minor": 1, "resources": {ext.RESOURCE_GPU_MEMORY_RATIO: 50,
                                       ext.RESOURCE_GPU_MEMORY: 8192}},
            {"minor": 3, "resources": {}},
        ]})
        p = pod(qos=QoSClass.LS, kube_qos="burstable", annotations=ann)
        registry = setup_registry()
        ctx = ContainerContext.from_container(p, ContainerMeta("c", "cid"), cfg)
        registry.run(Stage.PRE_CREATE_CONTAINER, ctx)
        assert ctx.response.env["NVIDIA_VISIBLE_DEVICES"] == "1,3"
        assert ctx.response.env["CUDA_MEM_LIMIT"] == "8192"

    def test_coresched_group(self, cfg, gates):
        slo = crds.NodeSLO(
            resource_qos_be=crds.QoSStrategy(
                cpu=crds.CPUQoS(group_identity=-1, core_sched=True))
        )
        registry = setup_registry(node_slo=slo)
        ctx = ContainerContext.from_container(pod(), ContainerMeta("c", "cid"), cfg)
        registry.run(Stage.PRE_START_CONTAINER, ctx)
        assert ctx.response.core_sched_group == "BE/pod-1"

    def test_cpu_normalization_quota(self, cfg, gates):
        p = pod(qos=QoSClass.LS, kube_qos="burstable", limits={"cpu": 2000})
        registry = setup_registry(cpu_normalization_ratio=lambda: 125)
        ctx = ContainerContext.from_container(p, ContainerMeta("c", "cid"), cfg)
        registry.run(Stage.PRE_CREATE_CONTAINER, ctx)
        # 2 cores => 200000us quota scaled down by 1.25 => 160000
        assert ctx.response.cgroup_values["cpu.cfs_quota"] == "160000"

    def test_hook_error_isolated(self, cfg):
        registry = HookRegistry()

        def broken(ctx):
            raise RuntimeError("boom")

        seen = []
        registry.register(Stage.PRE_CREATE_CONTAINER, "broken", broken)
        registry.register(Stage.PRE_CREATE_CONTAINER, "ok", lambda c: seen.append(1))
        failures = registry.run(Stage.PRE_CREATE_CONTAINER, None)
        assert len(failures) == 1 and failures[0][0] == "broken"
        assert seen == [1]


class TestApplyAndReconcile:
    def test_context_apply_writes_kernel(self, cfg):
        p = pod(requests={ext.RESOURCE_BATCH_CPU: 1000})
        rel = p.cgroup_dir(cfg)
        for res in (cg.CPU_BVT_WARP_NS, cg.CPU_CFS_QUOTA, cg.CPU_SHARES):
            write_cgroup_file(cfg, res, rel, "0")
        registry = setup_registry()
        executor = ResourceUpdateExecutor(cfg)
        ctx = PodContext.from_pod(p, cfg)
        registry.run(Stage.PRE_RUN_POD_SANDBOX, ctx)
        registry.run(Stage.PRE_UPDATE_CONTAINER, ctx)
        wrote = ctx.apply(executor)
        assert wrote >= 2
        assert cg.cgroup_read(cg.CPU_BVT_WARP_NS, rel, cfg) == "-1"
        assert cg.cgroup_read(cg.CPU_CFS_QUOTA, rel, cfg) == "100000"

    def test_reconciler_idempotent(self, cfg):
        p = pod(requests={ext.RESOURCE_BATCH_CPU: 1000})
        rel = p.cgroup_dir(cfg)
        for res in (cg.CPU_BVT_WARP_NS, cg.CPU_CFS_QUOTA, cg.CPU_SHARES):
            write_cgroup_file(cfg, res, rel, "0")
        states = StatesInformer()
        states.set_pods([p])
        registry = setup_registry()
        executor = ResourceUpdateExecutor(cfg)
        reconciler = Reconciler(states, registry, executor, cfg)
        first = reconciler.reconcile_once()
        second = reconciler.reconcile_once()
        assert first >= 2
        assert second == 0  # cache suppressed: nothing changed


class TestPLEG:
    def make_pod_dir(self, cfg, qos, uid, containers=()):
        base = cfg.cgroup_abs_path("cpu", cfg.pod_cgroup_dir(qos, uid))
        os.makedirs(base, exist_ok=True)
        for cid in containers:
            os.makedirs(os.path.join(base, cid), exist_ok=True)
        return base

    def test_add_and_delete_events(self, cfg):
        pleg = PLEG(cfg)
        assert pleg.poll() == []
        self.make_pod_dir(cfg, "besteffort", "abc-123", ["c1"])
        events = pleg.poll()
        assert [e.type for e in events] == [EVENT_POD_ADDED, EVENT_CONTAINER_ADDED]
        assert events[0].pod_uid == "abc-123"
        import shutil

        shutil.rmtree(self.make_pod_dir(cfg, "besteffort", "abc-123"))
        events = pleg.poll()
        assert [e.type for e in events] == [EVENT_POD_DELETED]

    def test_handler_fires(self, cfg):
        pleg = PLEG(cfg)
        seen = []
        pleg.add_handler(lambda e: seen.append(e.type))
        self.make_pod_dir(cfg, "burstable", "def-456")
        pleg.poll()
        assert seen == [EVENT_POD_ADDED]

    def test_inotify_gate_skips_quiet_scans(self, cfg):
        # the native watcher gates the tree walk: quiet polls do not scan,
        # churn (pod OR container inside a pod dir) triggers exactly one
        from koordinator_tpu import native

        if not native.ensure_built():
            import pytest

            pytest.skip("native lib unavailable")
        # QoS roots must exist before watches can attach
        for qos in ("guaranteed", "burstable", "besteffort"):
            os.makedirs(cfg.cgroup_abs_path("cpu", cfg.kube_qos_dir(qos)),
                        exist_ok=True)
        # a pod existing BEFORE the watch is armed must still be reported
        self.make_pod_dir(cfg, "guaranteed", "pre-existing")
        pleg = PLEG(cfg)
        assert pleg.start_watch()
        try:
            first = pleg.poll()           # first poll always scans
            assert [e.type for e in first] == [EVENT_POD_ADDED]
            assert first[0].pod_uid == "pre-existing"
            base_scans = pleg.scan_count
            assert base_scans == 1
            for _ in range(5):
                assert pleg.poll() == []  # quiet: no tree walks
            assert pleg.scan_count == base_scans
            self.make_pod_dir(cfg, "besteffort", "pod-w1", ["c1"])
            events = pleg.poll()          # churn: gate opens, scan diffs
            assert [e.type for e in events] == [
                EVENT_POD_ADDED, EVENT_CONTAINER_ADDED]
            assert pleg.scan_count == base_scans + 1
            # container churn INSIDE the (now watched) pod dir is seen too
            pod_dir = self.make_pod_dir(cfg, "besteffort", "pod-w1")
            os.makedirs(os.path.join(pod_dir, "c2"))
            events = pleg.poll()
            assert [e.type for e in events] == [EVENT_CONTAINER_ADDED]
            assert events[0].container_id == "c2"
        finally:
            pleg.stop_watch()

    def test_pod_recreate_between_polls_keeps_watch(self, cfg):
        # delete + recreate a pod dir with the same uid between two polls:
        # the kernel dropped the old watch with the dir, so the sync must
        # re-add unconditionally or container churn inside the NEW dir
        # would go dark until the rescan interval
        import shutil

        from koordinator_tpu import native

        if not native.ensure_built():
            import pytest

            pytest.skip("native lib unavailable")
        for qos in ("guaranteed", "burstable", "besteffort"):
            os.makedirs(cfg.cgroup_abs_path("cpu", cfg.kube_qos_dir(qos)),
                        exist_ok=True)
        pleg = PLEG(cfg)
        assert pleg.start_watch()
        try:
            pod_dir = self.make_pod_dir(cfg, "besteffort", "pod-r", ["c1"])
            pleg.poll()                       # pod-r known + watched
            shutil.rmtree(pod_dir)
            self.make_pod_dir(cfg, "besteffort", "pod-r", ["c1"])
            events = pleg.poll()              # same-path recreate
            # the diff sees no net change (same uid, same containers)...
            assert events == []
            # ...but container churn inside the RECREATED dir must still
            # open the gate immediately
            os.makedirs(os.path.join(
                self.make_pod_dir(cfg, "besteffort", "pod-r"), "c2"))
            events = pleg.poll()
            assert [e.type for e in events] == [EVENT_CONTAINER_ADDED]
        finally:
            pleg.stop_watch()

    def test_rescan_interval_safety_net(self, cfg):
        from koordinator_tpu import native

        if not native.ensure_built():
            import pytest

            pytest.skip("native lib unavailable")
        for qos in ("guaranteed", "burstable", "besteffort"):
            os.makedirs(cfg.cgroup_abs_path("cpu", cfg.kube_qos_dir(qos)),
                        exist_ok=True)
        pleg = PLEG(cfg)
        assert pleg.start_watch()
        try:
            pleg.rescan_every = 3
            pleg.poll()                   # first poll always scans
            base = pleg.scan_count
            pleg.poll()
            pleg.poll()
            assert pleg.scan_count == base       # still within interval
            pleg.poll()                   # third quiet poll forces a rescan
            assert pleg.scan_count == base + 1
        finally:
            pleg.stop_watch()


class TestDaemonAssembly:
    def test_daemon_tick(self, tmp_path):
        from tests.test_koordlet_metrics import FakeClock, write_proc
        from koordinator_tpu.koordlet.daemon import Daemon
        from koordinator_tpu.koordlet.statesinformer import NodeInfo

        cfg = make_test_config(tmp_path)
        clock = FakeClock()
        daemon = Daemon(cfg=cfg, audit_dir=str(tmp_path / "audit"), clock=clock)
        daemon.states.set_node(NodeInfo(name="n1", allocatable={"cpu": 8000}))
        p = pod(requests={ext.RESOURCE_BATCH_CPU: 1000})
        daemon.states.set_pods([p])
        write_proc(cfg, 100)
        rel = p.cgroup_dir(cfg)
        for res in (cg.CPU_BVT_WARP_NS, cg.CPU_CFS_QUOTA, cg.CPU_SHARES):
            write_cgroup_file(cfg, res, rel, "0")
        out = daemon.tick()
        assert "noderesource" in out["collected"]
        # pod dir exists in fake cgroupfs -> PLEG add -> hooks reconciled
        assert out["hook_writes"] >= 2
        assert cg.cgroup_read(cg.CPU_BVT_WARP_NS, rel, cfg) == "-1"
        out2 = daemon.tick()
        assert out2["hook_writes"] == 0  # no churn, no writes


class TestPLEGSystemd:
    def test_systemd_slice_layout(self, tmp_path):
        cfg = make_test_config(tmp_path)
        cfg.cgroup_driver_systemd = True
        pleg = PLEG(cfg)
        base = cfg.cgroup_abs_path("cpu", cfg.pod_cgroup_dir("besteffort", "ab-12"))
        os.makedirs(base, exist_ok=True)
        events = pleg.poll()
        assert [e.type for e in events] == [EVENT_POD_ADDED]
        assert events[0].pod_uid == "ab-12"  # systemd '_' unescaped to '-'
