"""Steady-state observatory smoke (ISSUE 9, tier-1): deterministic
churn-trace generation, the socket-driven harness completing a seeded
soak with a GREEN verdict, the same harness CATCHING planted
thread/queue leaks, /debug/steady parity across both surfaces, and the
flight-ring-size satellite.

Fast + deterministic by construction: small scale, fixed seeds,
time-compressed replay; heavy imports (the scheduler stack) stay
inside test functions per the marker-audit convention.
"""

import os
import sys
import threading

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

import loadgen  # noqa: E402  (tools/loadgen.py; no JAX at module scope)


class TestTraceGeneration:
    def test_same_seed_same_trace(self):
        cfg = loadgen.smoke_config(seed=13)
        a = [e.to_doc() for e in loadgen.generate_trace(cfg)]
        b = [e.to_doc() for e in loadgen.generate_trace(cfg)]
        assert a == b

    def test_different_seeds_differ(self):
        a = loadgen.generate_trace(loadgen.smoke_config(seed=1))
        b = loadgen.generate_trace(loadgen.smoke_config(seed=2))
        assert [e.to_doc() for e in a] != [e.to_doc() for e in b]

    def test_trace_is_sorted_and_covers_every_kind(self):
        events = loadgen.generate_trace(loadgen.smoke_config(seed=7))
        ts = [e.t for e in events]
        assert ts == sorted(ts)
        kinds = {e.kind for e in events}
        assert kinds == set(loadgen.EVENT_KINDS)

    def test_deletes_follow_adds_and_stay_inside_duration(self):
        cfg = loadgen.smoke_config(seed=3)
        events = loadgen.generate_trace(cfg)
        added_at = {e.name: e.t for e in events if e.kind == loadgen.POD_ADD}
        for e in events:
            if e.kind == loadgen.POD_DEL:
                assert e.name in added_at
                assert added_at[e.name] <= e.t <= cfg.duration_s

    def test_node_flaps_pair_down_then_up(self):
        cfg = loadgen.smoke_config(seed=5)
        events = loadgen.generate_trace(cfg)
        down: dict[str, float] = {}
        for e in events:
            if e.kind == loadgen.NODE_DOWN:
                assert e.name not in down   # no double-down
                down[e.name] = e.t
            elif e.kind == loadgen.NODE_UP:
                assert down.pop(e.name) < e.t

    def test_jsonl_roundtrip(self, tmp_path):
        events = loadgen.generate_trace(loadgen.smoke_config(seed=11))
        path = str(tmp_path / "trace.jsonl")
        loadgen.write_trace(events, path)
        back = loadgen.read_trace(path)
        assert [e.to_doc() for e in back] == [e.to_doc() for e in events]

    def test_diurnal_rate_modulates_arrivals(self):
        import dataclasses

        cfg = dataclasses.replace(
            loadgen.LoadGenConfig(seed=4), duration_s=600.0, nodes=4,
            arrival_rate=4.0, diurnal_amplitude=0.9,
            diurnal_period_s=600.0, gang_rate=0.0, node_flap_rate=0.0,
            quota_churn_rate=0.0, pod_lifetime_s=1e9)
        adds = [e.t for e in loadgen.generate_trace(cfg)
                if e.kind == loadgen.POD_ADD]
        # first half rides the sine peak, second half the trough
        first = sum(1 for t in adds if t < 300.0)
        second = len(adds) - first
        assert first > second * 1.5

    def test_stats_shape(self):
        events = loadgen.generate_trace(loadgen.smoke_config(seed=0))
        stats = loadgen.trace_stats(events)
        assert stats["events"] == len(events)
        assert stats["arrival_rate"] > 0


class TestTenantTraces:
    """--tenants N (ISSUE 11): tenant-tagged, per-tenant-seeded,
    deterministic multi-cluster traces."""

    def test_every_event_carries_its_tenant(self):
        cfg = loadgen.smoke_config(seed=4, tenants=3)
        events = loadgen.generate_trace(cfg)
        tenants = {e.payload.get("tenant") for e in events}
        assert tenants == {"t0", "t1", "t2"}

    def test_same_seed_same_multi_tenant_trace(self):
        cfg = loadgen.smoke_config(seed=13, tenants=4)
        a = [e.to_doc() for e in loadgen.generate_trace(cfg)]
        b = [e.to_doc() for e in loadgen.generate_trace(cfg)]
        assert a == b

    def test_tenant_subtrace_is_the_derived_seed_trace(self):
        """Tenant t's sub-stream must be byte-identical to a
        single-tenant trace generated directly from tenant_seed(seed,
        t) — the per-tenant-seed determinism contract."""
        import dataclasses

        cfg = loadgen.smoke_config(seed=6, tenants=3)
        merged = loadgen.generate_trace(cfg)
        for i, name in enumerate(cfg.tenant_names()):
            sub = [
                {k: v for k, v in e.to_doc().items() if k != "tenant"}
                for e in merged if e.payload.get("tenant") == name]
            direct = loadgen.generate_trace(dataclasses.replace(
                cfg, seed=loadgen.tenant_seed(cfg.seed, i), tenants=1))
            assert sub == [e.to_doc() for e in direct]

    def test_tenants_differ_from_each_other(self):
        cfg = loadgen.smoke_config(seed=8, tenants=2)
        events = loadgen.generate_trace(cfg)
        t0 = [e.to_doc() for e in events
              if e.payload.get("tenant") == "t0"]
        t1 = [e.to_doc() for e in events
              if e.payload.get("tenant") == "t1"]
        assert t0 and t1
        assert t0 != t1

    def test_stats_tally_per_tenant(self):
        cfg = loadgen.smoke_config(seed=2, tenants=2)
        stats = loadgen.trace_stats(loadgen.generate_trace(cfg))
        assert set(stats["tenants"]) == {"t0", "t1"}
        assert sum(stats["tenants"].values()) == stats["events"]

    def test_jsonl_roundtrip_keeps_tenant_field(self, tmp_path):
        cfg = loadgen.smoke_config(seed=3, tenants=2)
        events = loadgen.generate_trace(cfg)
        path = str(tmp_path / "mt.jsonl")
        loadgen.write_trace(events, path)
        back = loadgen.read_trace(path)
        assert [e.to_doc() for e in back] == [e.to_doc() for e in events]


class TestMultiTenantSoak:
    """The harness replays one trace stream per tenant against a
    TenantScheduler (one socket stack + sync binding per tenant) and
    the verdict grows a populated per-tenant section."""

    def test_multi_tenant_soak_green_with_per_tenant_section(
            self, tmp_path):
        import dataclasses

        cfg = dataclasses.replace(
            loadgen.smoke_config(seed=7, tenants=3), duration_s=50.0,
            nodes=12)
        events = loadgen.generate_trace(cfg)
        harness = loadgen.SteadyStateHarness(
            cfg, str(tmp_path), time_scale=15.0, solve_interval_s=4.0,
            slo_latency_threshold_s=5.0)
        harness.start()
        try:
            verdict = harness.run(events)
        finally:
            harness.close()
        assert verdict["green"], (verdict["trend"]["leaking"],
                                  verdict["trend"]["drifting"],
                                  verdict["slo_breached"],
                                  verdict["degraded"])
        tenants = verdict["tenants"]
        assert set(tenants) == {"t0", "t1", "t2"}
        # every tenant's cluster actually flowed: rounds ran, pods bound
        for name, doc in tenants.items():
            assert doc["rounds"] > 0, (name, doc)
            assert doc["bound"] > 0, (name, doc)
            assert not doc["degraded"]
        assert verdict["cycle"]["mode"] in ("pipelined", "batched")
        # the per-tenant SLO specs evaluated (and stayed inside budget)
        tenant_slos = [n for n in verdict["slo"]
                       if n.startswith("tenant_")]
        assert len(tenant_slos) == 3
        assert verdict["push_errors"] == 0


@pytest.fixture(scope="module")
def green_soak(tmp_path_factory):
    """ONE seeded churn soak shared by the green-verdict assertions:
    scheduler sidecar + manager + feeder over real sockets, the full
    observatory sampling it."""
    import dataclasses

    cfg = dataclasses.replace(loadgen.smoke_config(seed=7),
                              duration_s=90.0)
    events = loadgen.generate_trace(cfg)
    workdir = str(tmp_path_factory.mktemp("green-soak"))
    harness = loadgen.SteadyStateHarness(
        cfg, workdir, time_scale=15.0, solve_interval_s=4.0,
        slo_latency_threshold_s=5.0)
    harness.start()
    try:
        verdict = harness.run(events)
        yield harness, verdict
    finally:
        harness.close()


class TestGreenSoak:
    """The acceptance bar's fast deterministic half: a seeded churn soak
    completes with a green steady-state verdict."""

    def test_verdict_is_green(self, green_soak):
        harness, verdict = green_soak
        assert verdict["green"], (verdict["trend"]["leaking"],
                                  verdict["trend"]["drifting"],
                                  verdict["slo_breached"],
                                  verdict["degraded"])
        assert not verdict["trend"]["leaking"]
        assert not verdict["trend"]["drifting"]

    def test_churn_actually_flowed(self, green_soak):
        harness, verdict = green_soak
        assert verdict["push_errors"] == 0
        assert verdict["events_applied"] > 100
        # wall-clock compression note: a compile-heavy early round can
        # burn many virtual seconds, so the floor is conservative
        assert verdict["rounds"] >= 4
        assert verdict["bound"] > 0
        # every watched series had enough samples for a real verdict
        assert verdict["trend"]["verdicts"]["no_data"] == 0

    def test_backlog_and_degraded_time_bounded(self, green_soak):
        harness, verdict = green_soak
        assert verdict["backlog_peak"] <= 64
        assert not verdict["degraded"]

    def test_debug_steady_serves_the_same_verdicts(self, green_soak):
        """Both debug surfaces serve the shared builder's body."""
        from koordinator_tpu.scheduler.services import DebugService

        import time as _time

        harness, verdict = green_soak
        service = DebugService(harness.scheduler)
        # query the post-warmup steady window, the same one the verdict
        # used (the full-run window would re-fit over jit-compilation
        # growth, which is warmup, not steady state)
        window = max(1.0, _time.time() - harness.steady_started_at)
        status, body = service.handle("/debug/steady",
                                      {"window": f"{window}"})
        assert status == 200
        assert body["verdicts"]["leaking"] == 0
        assert {d["series"] for d in body["series"]} == {
            s.series for s in harness.trend.specs}
        assert "slo_breached" in body

    def test_debug_steady_window_validation(self, green_soak):
        from koordinator_tpu.scheduler.services import DebugService

        harness, _ = green_soak
        service = DebugService(harness.scheduler)
        assert service.handle("/debug/steady", {"window": "bogus"})[0] == 400
        assert service.handle("/debug/steady", {"window": "-5"})[0] == 400
        assert service.handle("/debug/steady", {"window": "nan"})[0] == 400


class TestLeakCatches:
    """The other half of the acceptance bar: the SAME harness must flag
    deliberately-injected leaks — a detector that can't catch a planted
    leak proves nothing."""

    def test_thread_leak_is_caught(self, tmp_path):
        import dataclasses

        cfg = dataclasses.replace(loadgen.smoke_config(seed=5),
                                  duration_s=60.0)
        events = loadgen.generate_trace(cfg)
        harness = loadgen.SteadyStateHarness(
            cfg, str(tmp_path), time_scale=15.0, solve_interval_s=2.0,
            slo_latency_threshold_s=5.0,
            inject_thread_leak=True)
        harness.start()
        try:
            verdict = harness.run(events)
        finally:
            harness.close()
        assert any("koord_process_threads" in s
                   for s in verdict["trend"]["leaking"]), verdict["trend"]
        assert not verdict["green"]
        # the leaked workers were released at close: no bleed into
        # other tests
        assert not harness._leaked_threads

    def test_queue_leak_is_caught(self, tmp_path):
        import dataclasses

        cfg = dataclasses.replace(loadgen.smoke_config(seed=6),
                                  duration_s=60.0, arrival_rate=3.0)
        events = loadgen.generate_trace(cfg)
        harness = loadgen.SteadyStateHarness(
            cfg, str(tmp_path), time_scale=15.0, solve_interval_s=2.0,
            slo_latency_threshold_s=5.0,
            inject_queue_leak=True)
        harness.start()
        try:
            verdict = harness.run(events)
        finally:
            harness.close()
        assert "koord_scheduler_pending_pods" in verdict["trend"]["leaking"]
        assert not verdict["green"]


class TestFlightRingSizeFlag:
    """--flight-ring-size satellite: the ring capacity is a flag, and
    round_flight_overwritten_total accounts exactly for the chosen
    size."""

    def test_flag_reaches_the_recorder(self):
        from koordinator_tpu.cmd.binaries import main_koord_scheduler

        asm = main_koord_scheduler(
            ["--disable-leader-election", "--flight-ring-size", "8"])
        try:
            assert asm.component.flight_recorder.capacity == 8
        finally:
            asm.stop()

    def test_overwrites_accounted_against_chosen_size(self):
        from koordinator_tpu import metrics
        from koordinator_tpu.scheduler.flight_recorder import FlightRecorder

        from tests.test_bench_prober import make_record

        rec = FlightRecorder(capacity=8)
        for n in range(20):
            rec.record(make_record(n))
        assert rec.overwrites == 20 - 8
        assert metrics.round_flight_overwritten.value() == 20 - 8
        assert len(rec.records) == 8

    def test_scheduler_rounds_respect_the_flag(self):
        """End to end through the binary assembly: more rounds than the
        ring holds -> the excess is counted, the ring holds exactly the
        flag's worth."""
        from koordinator_tpu import metrics
        from koordinator_tpu.cmd.binaries import main_koord_scheduler

        asm = main_koord_scheduler(
            ["--disable-leader-election", "--flight-ring-size", "4"])
        sched = asm.component
        try:
            for _ in range(10):
                sched.schedule_round()
            assert len(sched.flight_recorder.records) == 4
            assert metrics.round_flight_overwritten.value() == 10 - 4
        finally:
            asm.stop()


class TestTelemetryInBinaries:
    def test_every_binary_registers_self_telemetry(self):
        from koordinator_tpu import metrics
        from koordinator_tpu.cmd.binaries import (
            main_koord_manager,
            main_koord_scheduler,
        )

        sched = main_koord_scheduler(["--disable-leader-election"])
        mgr = main_koord_manager(
            ["--disable-leader-election",
             "--self-telemetry-interval-seconds", "0.05"])
        try:
            # the scheduler samples via the SLO sweep (pre-sample hook)
            sched.component.slo_monitor.sample_once()
            assert metrics.process_threads.value(
                labels={"binary": "koord-scheduler"}) >= 1.0
            # the manager's background thread samples on its own
            import time as _time

            deadline = _time.monotonic() + 5.0
            while (_time.monotonic() < deadline
                   and metrics.process_threads.value(
                       labels={"binary": "koord-manager"}) < 1.0):
                _time.sleep(0.02)
            assert metrics.process_threads.value(
                labels={"binary": "koord-manager"}) >= 1.0
        finally:
            mgr.stop()
            sched.stop()
        assert mgr.telemetry._thread is None   # stop() joined it

    def test_trend_engine_attached_with_window_flag(self):
        from koordinator_tpu.cmd.binaries import main_koord_scheduler

        asm = main_koord_scheduler(
            ["--disable-leader-election",
             "--trend-window-seconds", "900"])
        try:
            assert asm.component.trend_engine is not None
            assert asm.component.trend_engine.window_s == 900.0
            # shares the SLO monitor's cache: one sampling pass feeds both
            assert (asm.component.trend_engine.cache
                    is asm.component.slo_monitor.cache)
        finally:
            asm.stop()


class TestBacklogWatermark:
    def test_binding_backlog_peak_tracks_commits(self):
        import numpy as np

        from koordinator_tpu import metrics
        from koordinator_tpu.api.resources import resource_vector
        from koordinator_tpu.transport.deltasync import StateSyncService

        class SlowBinding:
            service_name = "scheduler"

            def __init__(self):
                self.applied = []

            def node_upsert(self, entry, arrs):
                self.applied.append(entry["name"])

            def note_sync_event(self):
                pass

        service = StateSyncService()
        service.attach_binding(SlowBinding())
        alloc = np.asarray(resource_vector(cpu=1000, memory=1000),
                           np.int32)
        for i in range(5):
            service.upsert_node(f"n{i}", alloc)
        assert metrics.sync_binding_backlog_peak.value() >= 1.0
        assert metrics.sync_binding_backlog.value() == 0.0  # drained
