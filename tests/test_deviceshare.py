"""Device-share semantics: shared/whole fit, scoring, joint + partition alloc.

Scenarios mirror pkg/scheduler/plugins/deviceshare tests (plugin_test.go fit
cases, device_allocator_test.go joint allocation, benchmark shape 1024 nodes
x 8 GPUs from plugin_benchmark_test.go:143-145).
"""

import jax
import jax.numpy as jnp
import numpy as np

from koordinator_tpu.ops.deviceshare import (
    DEV_BINPACK,
    DEV_CORE,
    DEV_SPREAD,
    DeviceState,
    allocate_on_node,
    commit_allocation,
    device_fit,
    device_score,
    joint_allocate,
    partition_allocate,
    split_request,
)
from koordinator_tpu.scheduler.device_manager import DeviceManager


def gpu_node(n_gpus=8, mem=81_920, group_size=4):
    return [
        {"core": 100, "memory": mem, "group": j // group_size}
        for j in range(n_gpus)
    ]


def test_split_request():
    assert split_request(50, 1000) == (0, 50, 1000)
    assert split_request(100, 1000) == (0, 100, 1000)
    assert split_request(200, 2000) == (2, 100, 1000)
    assert split_request(350, 0) == (4, 100, 0)  # rounded up to whole


def test_shared_fit_and_whole_fit():
    dev = DeviceState.build([gpu_node(2), []])
    # shared 50% fits node 0 only
    fit = device_fit(dev, jnp.int32(0), jnp.int32(50), jnp.int32(1000))
    assert bool(fit[0]) and not bool(fit[1])
    # 2 whole fits, 3 whole doesn't
    assert bool(device_fit(dev, jnp.int32(2), jnp.int32(100), jnp.int32(0))[0])
    assert not bool(device_fit(dev, jnp.int32(3), jnp.int32(100), jnp.int32(0))[0])


def test_unhealthy_device_excluded():
    devs = gpu_node(2)
    devs[1]["healthy"] = False
    dev = DeviceState.build([devs])
    assert not bool(device_fit(dev, jnp.int32(2), jnp.int32(100), jnp.int32(0))[0])
    assert bool(device_fit(dev, jnp.int32(1), jnp.int32(100), jnp.int32(0))[0])


def test_partial_device_blocks_whole_allocation():
    dev = DeviceState.build([gpu_node(2)])
    sel, ok = allocate_on_node(
        dev, jnp.int32(0), jnp.int32(0), jnp.int32(30), jnp.int32(100)
    )
    dev2 = commit_allocation(dev, jnp.int32(0), sel, jnp.int32(30), jnp.int32(100))
    # one device now partial: only 1 whole device left
    assert bool(device_fit(dev2, jnp.int32(1), jnp.int32(100), jnp.int32(0))[0])
    assert not bool(device_fit(dev2, jnp.int32(2), jnp.int32(100), jnp.int32(0))[0])


def test_binpack_picks_most_allocated_device():
    dev = DeviceState.build([gpu_node(2)])
    sel0 = jnp.zeros(dev.shape[1], bool).at[0].set(True)
    dev = commit_allocation(dev, jnp.int32(0), sel0, jnp.int32(40), jnp.int32(0))
    sel, ok = allocate_on_node(
        dev, jnp.int32(0), jnp.int32(0), jnp.int32(30), jnp.int32(0),
        strategy=DEV_BINPACK,
    )
    assert bool(ok) and bool(sel[0])  # goes to the already-busy device 0
    sel_spread, _ = allocate_on_node(
        dev, jnp.int32(0), jnp.int32(0), jnp.int32(30), jnp.int32(0),
        strategy=DEV_SPREAD,
    )
    assert bool(sel_spread[1])


def test_score_strategies_orient_correctly():
    dev = DeviceState.build([gpu_node(4), gpu_node(4)])
    sel = jnp.zeros(dev.shape[1], bool).at[0].set(True).at[1].set(True)
    dev = commit_allocation(dev, jnp.int32(0), sel, jnp.int32(100), jnp.int32(81_920))
    s_bin = device_score(dev, jnp.int32(1), jnp.int32(100), jnp.int32(0), DEV_BINPACK)
    s_spr = device_score(dev, jnp.int32(1), jnp.int32(100), jnp.int32(0), DEV_SPREAD)
    assert int(s_bin[0]) > int(s_bin[1])   # binpack prefers busier node 0
    assert int(s_spr[1]) > int(s_spr[0])   # spread prefers empty node 1


def test_whole_allocation_prefers_one_group():
    # 8 gpus in two groups of 4; ask 4 whole => all from one group.
    dev = DeviceState.build([gpu_node(8, group_size=4)])
    sel, ok = allocate_on_node(
        dev, jnp.int32(0), jnp.int32(4), jnp.int32(100), jnp.int32(0)
    )
    assert bool(ok)
    groups = np.asarray(dev.group[0])[np.asarray(sel)]
    assert len(set(groups.tolist())) == 1


def test_joint_allocate_same_group_nic():
    gpu = DeviceState.build([gpu_node(8, group_size=4)])
    nic = DeviceState.build(
        [[{"core": 100, "memory": 0, "group": 0}, {"core": 100, "memory": 0, "group": 1}]]
    )
    gsel, nsel, ok = joint_allocate(
        gpu, nic, jnp.int32(0), jnp.int32(4), jnp.int32(100), jnp.int32(0),
        jnp.int32(50), jnp.int32(0),
    )
    assert bool(ok)
    gpu_group = int(np.asarray(gpu.group[0])[np.asarray(gsel)][0])
    nic_group = int(np.asarray(nic.group[0])[np.asarray(nsel)][0])
    assert gpu_group == nic_group


def test_joint_allocate_required_fails_without_same_group_nic():
    gpu = DeviceState.build([gpu_node(4, group_size=4)])  # all group 0
    nic = DeviceState.build([[{"core": 100, "memory": 0, "group": 7}]])
    _, _, ok = joint_allocate(
        gpu, nic, jnp.int32(0), jnp.int32(2), jnp.int32(100), jnp.int32(0),
        jnp.int32(50), jnp.int32(0), nic_required=True,
    )
    assert not bool(ok)
    _, _, ok2 = joint_allocate(
        gpu, nic, jnp.int32(0), jnp.int32(2), jnp.int32(100), jnp.int32(0),
        jnp.int32(50), jnp.int32(0), nic_required=False,
    )
    assert bool(ok2)


def test_partition_templates():
    dev = DeviceState.build([gpu_node(8, group_size=4)])
    d = dev.shape[1]
    t = np.zeros((3, d), bool)
    t[0, 0:4] = True   # partition A: gpus 0-3
    t[1, 4:8] = True   # partition B: gpus 4-7
    t[2, 0:8] = True   # partition C: all 8
    templates = jnp.asarray(t)
    sel, ok = partition_allocate(dev, jnp.int32(0), templates, jnp.int32(4))
    assert bool(ok)
    np.testing.assert_array_equal(np.asarray(sel), t[0])
    # occupy gpu 1 => partition A infeasible, falls to B
    busy = jnp.zeros(d, bool).at[1].set(True)
    dev2 = commit_allocation(dev, jnp.int32(0), busy, jnp.int32(10), jnp.int32(0))
    sel2, ok2 = partition_allocate(dev2, jnp.int32(0), templates, jnp.int32(4))
    assert bool(ok2)
    np.testing.assert_array_equal(np.asarray(sel2), t[1])
    # no 3-device template exists
    _, ok3 = partition_allocate(dev, jnp.int32(0), templates, jnp.int32(3))
    assert not bool(ok3)


def test_device_manager_allocate_release_annotation():
    mgr = DeviceManager()
    mgr.register("gpu", ["n0", "n1"], [gpu_node(4), gpu_node(4)])
    minors = mgr.allocate("gpu", "n0", "pod-a", core=200, memory=16_384)
    assert minors is not None and len(minors) == 2
    ann = mgr.device_allocated_annotation("n0", "pod-a")
    assert ann["gpu"][0]["resources"]["core"] == 100
    # 2 whole left; a 3-whole ask fails until release
    assert mgr.allocate("gpu", "n0", "pod-b", core=300) is None
    mgr.release("n0", "pod-a")
    assert mgr.allocate("gpu", "n0", "pod-b", core=300) is not None


def test_joint_required_rejects_multi_group_gpu_spread():
    # 8 GPUs wanted from two groups of 4 => GPUs span groups; required-scope
    # joint allocation must fail even though a NIC exists in group 0.
    gpu = DeviceState.build([gpu_node(8, group_size=4)])
    nic = DeviceState.build([[{"core": 100, "memory": 0, "group": 0}]])
    _, _, ok = joint_allocate(
        gpu, nic, jnp.int32(0), jnp.int32(8), jnp.int32(100), jnp.int32(0),
        jnp.int32(50), jnp.int32(0), nic_required=True,
    )
    assert not bool(ok)


def test_two_device_types_with_different_node_orders():
    mgr = DeviceManager()
    mgr.register("gpu", ["n0", "n1"], [gpu_node(4), []])
    mgr.register("rdma", ["n1", "n0"],
                 [[{"core": 100}], [{"core": 100}]])
    assert mgr.allocate("gpu", "n0", "pod-a", core=100) is not None
    assert mgr.allocate("rdma", "n0", "pod-a", core=50) is not None
    assert mgr.allocate("gpu", "n1", "pod-b", core=100) is None  # no gpus on n1


def test_device_reallocate_replaces_not_double_charges():
    mgr = DeviceManager()
    mgr.register("gpu", ["n0"], [gpu_node(4)])
    mgr.allocate("gpu", "n0", "pod-a", core=200)
    mgr.allocate("gpu", "n0", "pod-a", core=200)  # retried bind cycle
    ann = mgr.device_allocated_annotation("n0", "pod-a")
    assert len(ann["gpu"]) == 2                    # not 4
    mgr.release("n0", "pod-a")
    assert mgr.allocate("gpu", "n0", "pod-b", core=400) is not None
    # failed re-allocate restores the old grant
    mgr2 = DeviceManager()
    mgr2.register("gpu", ["n0"], [gpu_node(4)])
    a = mgr2.allocate("gpu", "n0", "pod-a", core=200)
    assert mgr2.allocate("gpu", "n0", "pod-a", core=800) is None
    assert mgr2.device_allocated_annotation("n0", "pod-a")["gpu"][0]["minor"] == a[0]


def test_large_cluster_filter_shape():
    # The reference benchmark shape: 1024 nodes x 8 GPUs.
    dev = DeviceState.build([gpu_node(8)] * 1024)
    fit = jax.jit(device_fit)(dev, jnp.int32(8), jnp.int32(100), jnp.int32(0))
    assert fit.shape[0] >= 1024 and bool(fit[:1024].all())


def test_whole_fit_respects_per_device_capacity():
    # 1000-MiB devices: 2 whole devices at 5000 MiB each must NOT fit
    dev = DeviceState.build([gpu_node(2, mem=1_000)])
    fit = device_fit(dev, jnp.int32(2), jnp.int32(100), jnp.int32(5_000))
    assert not bool(fit[0])
    sel, ok = allocate_on_node(
        dev, jnp.int32(0), jnp.int32(2), jnp.int32(100), jnp.int32(5_000)
    )
    assert not bool(ok)
    # and the same ask within capacity fits
    fit = device_fit(dev, jnp.int32(2), jnp.int32(100), jnp.int32(1_000))
    assert bool(fit[0])


def test_shared_alloc_prefers_topology_group():
    # groups {0,1}; group-0 GPU busy so whole GPUs come from group 1;
    # NICs free in both groups -> joint alloc must pick the group-1 NIC
    gpu = DeviceState.build([gpu_node(8, group_size=4)])
    gpu = commit_allocation(
        gpu, jnp.int32(0),
        jnp.asarray([True] + [False] * (gpu.shape[1] - 1)),
        jnp.int32(10), jnp.int32(0),
    )
    nic = DeviceState.build(
        [[{"core": 100, "memory": 0, "group": 0},
          {"core": 100, "memory": 0, "group": 1}]]
    )
    gpu_sel, nic_sel, ok = joint_allocate(
        gpu, nic, jnp.int32(0), jnp.int32(4), jnp.int32(100), jnp.int32(81_920),
        jnp.int32(25), jnp.int32(0), nic_required=True,
    )
    assert bool(ok)
    assert np.flatnonzero(np.asarray(nic_sel)).tolist() == [1]
    assert (np.flatnonzero(np.asarray(gpu_sel)) >= 4).all()
