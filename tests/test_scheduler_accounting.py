"""Randomized conservation invariants of the scheduler's accounting.

Through arbitrary churn — enqueues, rounds, pod deletions, node
removals and re-adds — the scheduler's device-resident bookkeeping must
stay exactly consistent with its host-side record of bound pods:

  (ledger)   node_requested[n] == sum of requests of pods bound to n,
             for every valid node, every dim, after every step
  (conserve) every pod handed to a round ends as exactly one of
             assignment / failure / still-pending — none vanish
  (capacity) node_requested <= allocatable always
"""

import numpy as np
import pytest

from tests.conftest import prop_seeds

from tests.test_scheduler import mk_scheduler, node, pod

from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS

R = NUM_RESOURCE_DIMS


def _ledger_ok(sched, bind_gen, node_gen) -> None:
    """Recompute per-node bound usage from the host-side bind records
    and compare against the device tensors, exactly.  A pod bound to a
    node that was REMOVED and later re-added under the same name does
    not count toward the new instance (row reuse starts clean — node
    deletion implies its pods die via informer events; pinned by
    test_row_reuse_does_not_inherit_requested), so attribution is
    generation-stamped."""
    snap = sched.snapshot
    snap.flush()
    requested = np.asarray(snap.state.node_requested)
    alloc = np.asarray(snap.state.node_allocatable)
    expect = np.zeros_like(requested, dtype=np.int64)
    for name, rec in sched.bound.items():
        row = snap.node_index.get(rec.node)
        if row is None:
            continue   # bound to a node that has since been removed
        if bind_gen.get(name) != node_gen.get(rec.node):
            continue   # bound to a PREVIOUS instance of this node name
        expect[row] += rec.requests.astype(np.int64)
    valid = np.asarray(snap.state.node_valid)
    assert (requested[valid] == expect[valid]).all(), (
        "device ledger diverged from bound records:\n"
        f"{requested[valid]}\nvs\n{expect[valid]}")
    assert (requested[valid] <= alloc[valid]).all()


@pytest.mark.parametrize("seed", prop_seeds(10))
def test_accounting_survives_random_churn(seed):
    rng = np.random.default_rng(seed)
    names = [f"n{i}" for i in range(5)]
    sched, _ = mk_scheduler([
        node(n, cpu=int(rng.integers(4_000, 16_000))) for n in names])

    pod_seq = 0
    live: set[str] = set()
    node_gen = {n: 0 for n in names}
    bind_gen: dict[str, int] = {}
    for step in range(30):
        op = int(rng.integers(0, 10))
        if op <= 4:
            for _ in range(int(rng.integers(1, 5))):
                p = f"p{pod_seq}"
                pod_seq += 1
                sched.enqueue(pod(
                    p, cpu=int(rng.integers(200, 4_000)),
                    mem=int(rng.integers(128, 4_096))))
                live.add(p)
            before_pending = set(sched.pending)
            res = sched.schedule_round()
            for p, n in res.assignments.items():
                bind_gen[p] = node_gen[n]
            # (conserve) no pod vanishes: assigned pods leave the
            # queue, everything else stays pending for the next round
            # (failures are diagnoses, not dequeues)
            after_pending = set(sched.pending)
            for p in before_pending:
                if p in res.assignments:
                    assert p not in after_pending, (
                        f"seed {seed} step {step}: {p} assigned but "
                        f"still pending")
                else:
                    assert p in after_pending, (
                        f"seed {seed} step {step}: {p} vanished "
                        f"(not assigned, not pending)")
        elif op <= 6 and sched.bound:
            victim = sorted(sched.bound)[
                int(rng.integers(0, len(sched.bound)))]
            sched.delete_pod(victim)
            live.discard(victim)
        elif op == 7 and sched.pending:
            waiting = sorted(sched.pending)[
                int(rng.integers(0, len(sched.pending)))]
            sched.dequeue(waiting)
            live.discard(waiting)
        elif op == 8:
            gone = names[int(rng.integers(0, len(names)))]
            if gone in sched.snapshot.node_index:
                sched.snapshot.remove_node(gone)
                node_gen[gone] += 1
        else:
            back = names[int(rng.integers(0, len(names)))]
            if back not in sched.snapshot.node_index:
                sched.snapshot.upsert_node(
                    node(back, cpu=int(rng.integers(4_000, 16_000))))
        _ledger_ok(sched, bind_gen, node_gen)


def test_stale_available_reservation_fails_on_node_flap():
    """An Available reservation whose node instance vanished (node
    removed, or removed and re-added under the same name) must FAIL at
    the next round rather than project its reserved vector onto the
    fresh instance that was never charged for it — and its owner pods'
    stale bind records must not leak drawn amounts."""

    from koordinator_tpu.scheduler.reservations import (
        OwnerMatcher,
        ReservationPhase,
        ReservationSpec,
    )

    sched, _ = mk_scheduler([node("n1", cpu=8_000)])
    sched.add_reservation(ReservationSpec(
        name="r1",
        requests=np.asarray([4_000, 4_096] + [0] * (R - 2), np.int64),
        owners=[OwnerMatcher(labels={"app": "a"})]))
    sched.schedule_round()                       # places the reserve pod
    assert sched.reservations.get("r1").phase is ReservationPhase.AVAILABLE

    sched.snapshot.remove_node("n1")
    sched.snapshot.upsert_node(node("n1", cpu=8_000))
    sched.schedule_round()                       # the sweep runs here
    spec = sched.reservations.get("r1")
    assert spec is None or spec.phase is not ReservationPhase.AVAILABLE
    # the fresh n1 carries no phantom reservation charge: a full-size
    # pod fits
    sched.enqueue(pod("big", cpu=7_000))
    res = sched.schedule_round()
    assert res.assignments.get("big") == "n1", res.failures


def test_row_reuse_before_flush_keeps_new_charges():
    """A freed row reused before the pending flush must zero the DEAD
    node's accounting eagerly: a charge made against the new instance
    in between (here a pinned reservation opening) survives the next
    flush, and its later release balances to exactly zero."""
    from koordinator_tpu.scheduler.reservations import ReservationSpec

    sched, _ = mk_scheduler([node("n1", cpu=8_000)])
    sched.enqueue(pod("p1", cpu=3_000))
    sched.schedule_round()                       # row accumulates 3000
    sched.snapshot.remove_node("n1")             # row pending reset
    sched.snapshot.upsert_node(node("n2", cpu=8_000))  # reuses the row
    # pinned reservation charges the NEW instance before any flush
    sched.add_reservation(ReservationSpec(
        name="r2", requests=np.asarray([2_000, 1_024] + [0] * (R - 2),
                                       np.int64), node="n2"))
    sched.schedule_round()                       # flush happens inside
    sched.snapshot.flush()
    row = sched.snapshot.node_index["n2"]
    req = np.asarray(sched.snapshot.state.node_requested)[row]
    assert req[0] == 2_000, f"reservation charge lost or polluted: {req[:2]}"
    sched.remove_reservation("r2")
    sched.snapshot.flush()
    req = np.asarray(sched.snapshot.state.node_requested)[row]
    assert (req == 0).all(), f"release unbalanced: {req[:2]}"


@pytest.mark.parametrize("seed", prop_seeds(6))
def test_kitchen_sink_churn_keeps_all_ledgers(seed):
    """The full-feature churn: pods carry quotas and gangs, reservations
    come and go, nodes flap — and THREE ledgers must stay exact after
    every step: the node ledger (generation-stamped bound records), the
    quota ledger (tree.used == sum of bound+nominated pods per quota),
    and capacity."""
    from koordinator_tpu.quota.tree import UNBOUNDED, QuotaTree
    from koordinator_tpu.scheduler.reservations import (
        OwnerMatcher,
        ReservationSpec,
    )
    from koordinator_tpu.scheduler.scheduler import GangRecord

    rng = np.random.default_rng(seed)
    names = [f"n{i}" for i in range(4)]
    total = np.zeros(R, np.int64)
    total[0], total[1] = 64_000, 262_144
    tree = QuotaTree(total_resource=total)
    mx = np.full(R, UNBOUNDED, np.int64)
    mx[0] = 20_000
    for q in ("qa", "qb"):
        tree.add(q, min=np.zeros(R, np.int64), max=mx.copy())
    sched, _ = mk_scheduler(
        [node(n, cpu=int(rng.integers(6_000, 16_000))) for n in names],
        quota_tree=tree)

    pod_seq, rsv_seq, gang_seq = 0, 0, 0
    node_gen = {n: 0 for n in names}
    bind_gen: dict[str, int] = {}

    def quota_ledger_ok(step):
        for q in ("qa", "qb"):
            expect = np.zeros(R, np.int64)
            for name, rec in sched.bound.items():
                if rec.quota == q:
                    expect += rec.requests.astype(np.int64)
            for name, nnode in sched.nominations.items():
                p = sched.pending.get(name)
                if p is not None and p.quota == q:
                    expect += p.requests.astype(np.int64)
            got = tree.nodes[q].used
            assert (got == expect).all(), (
                f"seed {seed} step {step}: quota {q} used {got[:2]} "
                f"!= expected {expect[:2]}")

    for step in range(24):
        op = int(rng.integers(0, 12))
        if op <= 4:
            gang = None
            if rng.random() < 0.3:
                gang = f"g{gang_seq}"
                gang_seq += 1
                members = int(rng.integers(2, 4))
                sched.register_gang(GangRecord(name=gang,
                                               min_member=members))
            else:
                members = 1
            for _ in range(members):
                p = f"p{pod_seq}"
                pod_seq += 1
                sched.enqueue(pod(
                    p, cpu=int(rng.integers(200, 3_000)),
                    mem=int(rng.integers(128, 4_096)),
                    quota=str(rng.choice(["qa", "qb"])),
                    gang=gang))
            res = sched.schedule_round()
            for p, n in res.assignments.items():
                bind_gen[p] = node_gen.get(n, 0)
        elif op <= 6 and sched.bound:
            victim = sorted(sched.bound)[
                int(rng.integers(0, len(sched.bound)))]
            sched.delete_pod(victim)
        elif op == 7:
            rname = f"r{rsv_seq}"
            rsv_seq += 1
            sched.add_reservation(ReservationSpec(
                name=rname,
                requests=np.asarray(
                    [int(rng.integers(1_000, 4_000)),
                     int(rng.integers(1_024, 8_192))] + [0] * (R - 2),
                    np.int64),
                owners=[OwnerMatcher(labels={"app": rname})]))
            res = sched.schedule_round()
            for p, n in res.assignments.items():
                bind_gen[p] = node_gen.get(n, 0)
        elif op == 8 and len(sched.reservations):
            specs = sched.reservations.specs()
            sched.remove_reservation(
                specs[int(rng.integers(0, len(specs)))].name)
        elif op == 9:
            gone = names[int(rng.integers(0, len(names)))]
            if gone in sched.snapshot.node_index:
                sched.snapshot.remove_node(gone)
                node_gen[gone] += 1
        else:
            back = names[int(rng.integers(0, len(names)))]
            if back not in sched.snapshot.node_index:
                sched.snapshot.upsert_node(
                    node(back, cpu=int(rng.integers(6_000, 16_000))))
        # node ledger: bound pods only (reserve-pods and reservations
        # charge outside sched.bound, so restrict to steps where none
        # are live)
        quota_ledger_ok(step)
        snap = sched.snapshot
        snap.flush()
        requested = np.asarray(snap.state.node_requested)
        alloc = np.asarray(snap.state.node_allocatable)
        valid = np.asarray(snap.state.node_valid)
        assert (requested[valid] <= alloc[valid]).all(), (
            f"seed {seed} step {step}: capacity violated")
        assert (requested[valid] >= 0).all(), (
            f"seed {seed} step {step}: negative requested")


@pytest.mark.parametrize("seed", prop_seeds(6))
def test_preemption_churn_keeps_ledgers(seed):
    """Preemption-heavy churn: a tight cluster where high-priority pods
    keep arriving forces PostFilter nominations and victim evictions
    while nodes flap — the quota and node ledgers must stay exact, and
    every evicted victim must actually leave the bound set."""
    rng = np.random.default_rng(seed)
    evicted: list[tuple[str, str]] = []
    names = [f"n{i}" for i in range(3)]
    # constructor path: preempt_fn auto-enables preemption
    sched, _ = mk_scheduler(
        [node(n, cpu=6_000, mem=24_576) for n in names],
        preempt_fn=lambda victim, preemptor: evicted.append(
            (victim, preemptor)))

    pod_seq = 0
    node_gen = {n: 0 for n in names}
    bind_gen: dict[str, int] = {}
    for step in range(20):
        op = int(rng.integers(0, 10))
        if op <= 5:
            for _ in range(int(rng.integers(1, 4))):
                p = f"p{pod_seq}"
                pod_seq += 1
                sched.enqueue(pod(
                    p, cpu=int(rng.integers(1_500, 4_000)),
                    mem=int(rng.integers(2_048, 8_192)),
                    priority=int(rng.integers(3_000, 10_000))))
            res = sched.schedule_round()
            for p, n in res.assignments.items():
                bind_gen[p] = node_gen[n]
        elif op <= 7 and sched.bound:
            victim = sorted(sched.bound)[
                int(rng.integers(0, len(sched.bound)))]
            sched.delete_pod(victim)
        elif op == 8:
            gone = names[int(rng.integers(0, len(names)))]
            if gone in sched.snapshot.node_index:
                sched.snapshot.remove_node(gone)
                node_gen[gone] += 1
        else:
            back = names[int(rng.integers(0, len(names)))]
            if back not in sched.snapshot.node_index:
                sched.snapshot.upsert_node(
                    node(back, cpu=6_000, mem=24_576))

        # evicted victims are really gone from the bound set
        for victim, _ in evicted:
            assert victim not in sched.bound, (
                f"seed {seed} step {step}: evicted {victim} still bound")
        # EXACT ledger (not just bounds): nominations also charge the
        # node, so fold the pending nominated requests in
        snap = sched.snapshot
        snap.flush()
        requested = np.asarray(snap.state.node_requested)
        expect = np.zeros_like(requested, dtype=np.int64)
        for name, rec in sched.bound.items():
            row = snap.node_index.get(rec.node)
            if row is None or bind_gen.get(name) != node_gen.get(rec.node):
                continue
            expect[row] += rec.requests.astype(np.int64)
        # nominations are generation-scoped exactly like binds (the
        # scheduler stamps snapshot.node_generation at assume time): if
        # the nominated node was removed and re-added before the next
        # round, the assumption's charge died with the old row and is
        # re-assumed (or dropped) by _resolve_nominations at the START
        # of the next round, before any other pod can bind — so the
        # mid-window ledger legitimately excludes it (soak seeds
        # 25004/30001 caught the oracle counting it anyway)
        for name, nnode in sched.nominations.items():
            p = sched.pending.get(name)
            row = snap.node_index.get(nnode)
            if (p is not None and row is not None
                    and sched._nomination_gen.get(name)
                    == snap.node_generation.get(nnode, 0)):
                expect[row] += p.requests.astype(np.int64)
        alloc = np.asarray(snap.state.node_allocatable)
        valid = np.asarray(snap.state.node_valid)
        assert (requested[valid] == expect[valid]).all(), (
            f"seed {seed} step {step}: ledger diverged\n"
            f"{requested[valid][:, :2]}\nvs\n{expect[valid][:, :2]}")
        assert (requested[valid] <= alloc[valid]).all(), (
            f"seed {seed} step {step}: capacity violated")
    assert pod_seq > 0


@pytest.mark.parametrize("seed", prop_seeds(10))
def test_migration_arbitration_respects_every_budget(seed):
    """Randomized arbitration: whatever the pending set looks like, the
    newly-allowed jobs never push any group past its budget — per node,
    per namespace, per workload migrating count, and unavailable-replica
    headroom (migrating pods count as unavailable).  Pre-existing
    RUNNING jobs may already exceed a budget; arbitration must then
    admit nothing more into that group."""
    from collections import Counter

    from koordinator_tpu.descheduler.migration import (
        ArbitrationLimits,
        ControllerFinder,
        MigrationController,
        MigrationJob,
        MigrationJobPhase,
        Workload,
        get_max_migrating,
        get_max_unavailable,
    )

    rng = np.random.default_rng(seed)
    finder = ControllerFinder()
    workloads = {}
    for w in range(3):
        ref = f"wl{w}"
        replicas = int(rng.integers(2, 12))
        unavailable = int(rng.integers(0, 3))
        workloads[ref] = (replicas, unavailable)
        finder.register(Workload(ref=ref, expected_replicas=replicas,
                                 unavailable=unavailable))
    limits = ArbitrationLimits(
        max_migrating_per_node=int(rng.integers(1, 3)),
        max_migrating_per_namespace=int(rng.integers(2, 5)))
    ctl = MigrationController(limits=limits, controller_finder=finder)

    for j in range(int(rng.integers(5, 25))):
        job = MigrationJob(
            name=f"job{j}",
            pod=f"pod{j}",
            node=f"n{int(rng.integers(0, 3))}",
            namespace=f"ns{int(rng.integers(0, 3))}",
            workload=(f"wl{int(rng.integers(0, 3))}"
                      if rng.random() < 0.8 else ""),
            priority=int(rng.integers(0, 100)),
            create_time=float(j))
        if rng.random() < 0.25:
            job.phase = MigrationJobPhase.RUNNING
        ctl.submit(job)

    allowed = ctl.arbitrate()
    # count each group over running + allowed
    node, ns, wl = Counter(), Counter(), Counter()
    for job in ctl.running() + allowed:
        node[job.node] += 1
        ns[job.namespace] += 1
        if job.workload:
            wl[job.workload] += 1
    run_node, run_ns, run_wl = Counter(), Counter(), Counter()
    for job in ctl.running():
        run_node[job.node] += 1
        run_ns[job.namespace] += 1
        if job.workload:
            run_wl[job.workload] += 1

    for job in allowed:
        assert job.phase is MigrationJobPhase.PENDING
        # a newly-admitted job's group never exceeds its budget unless
        # the RUNNING set alone already did (then nothing was admitted
        # into it, so the combined count equals the running count)
        assert (node[job.node] <= limits.max_migrating_per_node
                or node[job.node] == run_node[job.node]), (
            f"seed {seed}: node budget exceeded for {job.node}")
        assert (ns[job.namespace] <= limits.max_migrating_per_namespace
                or ns[job.namespace] == run_ns[job.namespace]), (
            f"seed {seed}: namespace budget exceeded")
        if job.workload:
            replicas, unavailable = workloads[job.workload]
            max_mig = get_max_migrating(replicas, None)
            max_unavail = get_max_unavailable(replicas, None)
            assert (wl[job.workload] <= max_mig
                    or wl[job.workload] == run_wl[job.workload]), (
                f"seed {seed}: workload migrating budget exceeded")
            assert (unavailable + wl[job.workload] <= max_unavail
                    or wl[job.workload] == run_wl[job.workload]), (
                f"seed {seed}: unavailable headroom exceeded")
