"""L0 system layer + resourceexecutor tests, run against a fake kernel fs
rooted in a tempdir (the reference's NewFileTestUtil pattern)."""

import os

import pytest

from koordinator_tpu.koordlet import resourceexecutor as rex
from koordinator_tpu.koordlet.audit import Auditor
from koordinator_tpu.koordlet.system import cgroup as cg
from koordinator_tpu.koordlet.system import coresched, procfs, psi, resctrl
from koordinator_tpu.koordlet.system.config import make_test_config


@pytest.fixture
def cfg(tmp_path):
    return make_test_config(tmp_path)


@pytest.fixture
def cfg_v2(tmp_path):
    return make_test_config(tmp_path, use_cgroup_v2=True)


def write_cgroup_file(cfg, res, rel_dir, content):
    version = cg.CgroupVersion.V2 if cfg.use_cgroup_v2 else cg.CgroupVersion.V1
    path = cfg.cgroup_abs_path(res.subsystem, rel_dir, res.filename(version))
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        f.write(content)
    return path


class TestCgroupLayer:
    def test_v1_read_write_roundtrip(self, cfg):
        write_cgroup_file(cfg, cg.CPU_CFS_QUOTA, "kubepods", "-1")
        assert cg.cgroup_read(cg.CPU_CFS_QUOTA, "kubepods", cfg) == "-1"
        cg.cgroup_write(cg.CPU_CFS_QUOTA, "kubepods", "50000", cfg)
        assert cg.cgroup_read(cg.CPU_CFS_QUOTA, "kubepods", cfg) == "50000"

    def test_v2_quota_translation_preserves_period(self, cfg_v2):
        path = write_cgroup_file(cfg_v2, cg.CPU_CFS_QUOTA, "kubepods", "max 50000")
        cg.cgroup_write(cg.CPU_CFS_QUOTA, "kubepods", "25000", cfg_v2)
        assert open(path).read() == "25000 50000"
        # canonical read translates back; unlimited maps to -1
        assert cg.cgroup_read(cg.CPU_CFS_QUOTA, "kubepods", cfg_v2) == "25000"
        cg.cgroup_write(cg.CPU_CFS_QUOTA, "kubepods", "-1", cfg_v2)
        assert cg.cgroup_read(cg.CPU_CFS_QUOTA, "kubepods", cfg_v2) == "-1"

    def test_shares_weight_mapping(self, cfg_v2):
        write_cgroup_file(cfg_v2, cg.CPU_SHARES, "kubepods", "100")
        cg.cgroup_write(cg.CPU_SHARES, "kubepods", "1024", cfg_v2)
        weight = int(open(cfg_v2.cgroup_abs_path("cpu", "kubepods", "cpu.weight")).read())
        assert weight == cg.shares_to_weight(1024) == 39
        # kernel mapping endpoints
        assert cg.shares_to_weight(2) == 1
        assert cg.shares_to_weight(262144) == 10000

    def test_validator_rejects(self, cfg):
        write_cgroup_file(cfg, cg.MEMORY_WMARK_RATIO, "kubepods", "0")
        with pytest.raises(ValueError):
            cg.cgroup_write(cg.MEMORY_WMARK_RATIO, "kubepods", "150", cfg)

    def test_unsupported_on_version_returns_false(self, cfg):
        # memory.oom.group is v2-only
        assert not cg.cgroup_write(cg.MEMORY_OOM_GROUP, "kubepods", "1", cfg)

    def test_pod_container_paths(self, cfg):
        rel = cfg.pod_cgroup_dir("besteffort", "uid-1")
        assert rel == "kubepods/besteffort/poduid-1"
        crel = cfg.container_cgroup_dir("burstable", "uid-2", "abc")
        assert crel == "kubepods/burstable/poduid-2/abc"

    def test_systemd_driver_paths(self, tmp_path):
        c = make_test_config(tmp_path)
        c.cgroup_driver_systemd = True
        assert c.kube_qos_dir("besteffort") == os.path.join(
            "kubepods.slice", "kubepods-besteffort.slice"
        )
        assert "kubepods-besteffort-poduid_1.slice" in c.pod_cgroup_dir(
            "besteffort", "uid-1"
        )


class TestPSI:
    def test_parse(self):
        content = (
            "some avg10=1.50 avg60=0.75 avg300=0.10 total=12345\n"
            "full avg10=0.50 avg60=0.25 avg300=0.05 total=678\n"
        )
        stats = psi.parse_psi(content)
        assert stats.some.avg10 == 1.50
        assert stats.full.total_us == 678
        assert stats.full_supported

    def test_cpu_without_full(self):
        stats = psi.parse_psi("some avg10=0.00 avg60=0.00 avg300=0.00 total=0\n")
        assert not stats.full_supported


class TestResctrl:
    def make_fs(self, cfg, ways=20, domains=(0, 1)):
        root = cfg.resctrl_root
        os.makedirs(os.path.join(root, "info", "L3"), exist_ok=True)
        with open(os.path.join(root, "info", "L3", "cbm_mask"), "w") as f:
            f.write(format((1 << ways) - 1, "x"))
        sch = resctrl.Schemata(
            l3={d: (1 << ways) - 1 for d in domains}, mb={d: 100 for d in domains}
        )
        with open(os.path.join(root, "schemata"), "w") as f:
            f.write(sch.render())
        return resctrl.ResctrlFS(cfg)

    def test_schemata_roundtrip(self, cfg):
        fs = self.make_fs(cfg)
        assert fs.available()
        assert fs.num_cache_ways() == 20
        assert fs.cache_domains() == [0, 1]

    def test_percent_to_mask(self):
        assert resctrl.percent_to_way_mask(100, 20) == (1 << 20) - 1
        assert resctrl.percent_to_way_mask(50, 20) == (1 << 10) - 1
        assert resctrl.percent_to_way_mask(0, 20) == 1  # at least one way
        assert resctrl.percent_to_way_mask(30, 10) == 0b111

    def test_apply_qos_policy(self, cfg):
        fs = self.make_fs(cfg)
        fs.apply_qos_policy(resctrl.GROUP_BE, l3_percent=30, mb_percent=40)
        sch = fs.read_schemata(resctrl.GROUP_BE)
        assert sch.l3 == {0: 0b111111, 1: 0b111111}  # ceil(20*0.3)=6 ways
        assert sch.mb == {0: 40, 1: 40}

    def test_tasks(self, cfg):
        fs = self.make_fs(cfg)
        assert fs.add_tasks(resctrl.GROUP_LS, [101, 102]) == []
        assert fs.read_tasks(resctrl.GROUP_LS) == [101, 102]


class TestCoreSched:
    def test_fake_group_assignment(self):
        cs = coresched.FakeCoreSched()
        assert cs.supported()
        failed = cs.assign_group(100, [101, 102])
        assert failed == []
        assert cs.get(101) == cs.get(100) != 0
        assert cs.get(102) == cs.get(100)


class TestProcfs:
    def test_cpu_list_roundtrip(self):
        assert procfs.parse_cpu_list("0-3,8,10-11") == [0, 1, 2, 3, 8, 10, 11]
        assert procfs.format_cpu_list([0, 1, 2, 3, 8, 10, 11]) == "0-3,8,10-11"
        assert procfs.parse_cpu_list("") == []
        assert procfs.format_cpu_list([5]) == "5"

    def test_proc_stat(self, cfg):
        os.makedirs(cfg.proc_root, exist_ok=True)
        with open(cfg.proc_path("stat"), "w") as f:
            f.write("cpu  100 0 50 800 20 5 5 0 0 0\ncpu0 50 0 25 400 10 2 2 0 0 0\n")
        stat = procfs.read_cpu_stat(cfg)
        assert stat.used_jiffies == 100 + 50 + 5 + 5
        assert stat.total_jiffies == 160 + 800 + 20

    def test_meminfo(self, cfg):
        os.makedirs(cfg.proc_root, exist_ok=True)
        with open(cfg.proc_path("meminfo"), "w") as f:
            f.write("MemTotal: 1000 kB\nMemFree: 300 kB\nMemAvailable: 600 kB\n"
                    "Cached: 200 kB\n")
        mem = procfs.read_meminfo(cfg)
        assert mem.total == 1000 * 1024
        assert mem.used_no_cache == 400 * 1024

    def test_idle_page_stats(self):
        content = (
            "# version: 1.0\n"
            "csei 0 0 4096 8192\n"
            "dsei 0 0 0 1024\n"
            "scan_period_in_seconds 120\n"
        )
        stats = procfs.parse_idle_page_stats(content)
        assert stats["csei"] == 4096 + 8192
        assert stats["cold"] == 8192 + 1024


class TestResourceExecutor:
    def test_cache_suppresses_redundant_writes(self, cfg, tmp_path):
        write_cgroup_file(cfg, cg.CPU_CFS_QUOTA, "kubepods/pod1", "-1")
        auditor = Auditor(str(tmp_path / "audit"))
        ex = rex.ResourceUpdateExecutor(cfg, auditor)
        up = rex.ResourceUpdate(cg.CPU_CFS_QUOTA, "kubepods/pod1", "20000")
        assert ex.update(up).updated
        assert not ex.update(up).updated  # suppressed
        events = auditor.query(group="cgroup")
        assert len(events) == 1
        assert events[0]["value"] == "20000"

    def test_cache_miss_reads_kernel_value(self, cfg):
        write_cgroup_file(cfg, cg.CPU_SHARES, "kubepods", "1024")
        ex = rex.ResourceUpdateExecutor(cfg)
        up = rex.ResourceUpdate(cg.CPU_SHARES, "kubepods", "1024")
        assert not ex.update(up).updated  # kernel already has it

    def test_leveled_ordering(self, cfg):
        for rel in ("kubepods", "kubepods/pod1"):
            write_cgroup_file(cfg, cg.MEMORY_LIMIT, rel, "1000")
        ex = rex.ResourceUpdateExecutor(cfg)
        order: list[str] = []
        orig = ex.update

        def tracking_update(u):
            order.append(u.rel_dir)
            return orig(u)

        ex.update = tracking_update
        # increase: parent first even though child listed first
        ex.leveled_update_batch([
            rex.ResourceUpdate(cg.MEMORY_LIMIT, "kubepods/pod1", "2000"),
            rex.ResourceUpdate(cg.MEMORY_LIMIT, "kubepods", "3000"),
        ])
        assert order == ["kubepods", "kubepods/pod1"]
        order.clear()
        # decrease: child first
        ex.leveled_update_batch([
            rex.ResourceUpdate(cg.MEMORY_LIMIT, "kubepods", "500"),
            rex.ResourceUpdate(cg.MEMORY_LIMIT, "kubepods/pod1", "400"),
        ])
        assert order == ["kubepods/pod1", "kubepods"]

    def test_invalid_value_audited_not_raised(self, cfg, tmp_path):
        write_cgroup_file(cfg, cg.MEMORY_WMARK_RATIO, "kubepods", "0")
        auditor = Auditor(str(tmp_path / "audit"))
        ex = rex.ResourceUpdateExecutor(cfg, auditor)
        res = ex.update(rex.ResourceUpdate(cg.MEMORY_WMARK_RATIO, "kubepods", "400"))
        assert not res.updated and res.error
        assert auditor.query()[0]["operation"] == "update-failed"


class TestAuditor:
    def test_rotation_and_query(self, tmp_path):
        auditor = Auditor(str(tmp_path), max_file_bytes=2048, max_files=3)
        for i in range(50):
            auditor.log("cgroup", "update", f"dir{i}", {"value": str(i)})
        events = auditor.query(limit=10)
        assert len(events) == 10
        assert events[0]["target"] == "dir49"  # newest first
        files = os.listdir(tmp_path)
        assert len(files) <= 3


class TestLeveledCpusetOrdering:
    def test_growing_cpuset_parent_first(self, cfg):
        for rel in ("kubepods", "kubepods/pod1"):
            write_cgroup_file(cfg, cg.CPUSET_CPUS, rel, "0-1")
        ex = rex.ResourceUpdateExecutor(cfg)
        order = []
        orig = ex.update
        ex.update = lambda u: (order.append(u.rel_dir), orig(u))[1]
        ex.leveled_update_batch([
            rex.ResourceUpdate(cg.CPUSET_CPUS, "kubepods/pod1", "0-3"),
            rex.ResourceUpdate(cg.CPUSET_CPUS, "kubepods", "0-3"),
        ])
        assert order == ["kubepods", "kubepods/pod1"]

    def test_shrinking_cpuset_child_first(self, cfg):
        for rel in ("kubepods", "kubepods/pod1"):
            write_cgroup_file(cfg, cg.CPUSET_CPUS, rel, "0-3")
        ex = rex.ResourceUpdateExecutor(cfg)
        order = []
        orig = ex.update
        ex.update = lambda u: (order.append(u.rel_dir), orig(u))[1]
        ex.leveled_update_batch([
            rex.ResourceUpdate(cg.CPUSET_CPUS, "kubepods", "0-1"),
            rex.ResourceUpdate(cg.CPUSET_CPUS, "kubepods/pod1", "0-1"),
        ])
        assert order == ["kubepods/pod1", "kubepods"]

    def test_unlimited_is_increase(self, cfg):
        write_cgroup_file(cfg, cg.MEMORY_LIMIT, "kubepods", "1000")
        ex = rex.ResourceUpdateExecutor(cfg)
        order = []
        orig = ex.update
        ex.update = lambda u: (order.append(u.rel_dir), orig(u))[1]
        ex.leveled_update_batch([
            rex.ResourceUpdate(cg.MEMORY_LIMIT, "kubepods", "-1"),
        ])
        assert order == ["kubepods"]
        assert cg.cgroup_read(cg.MEMORY_LIMIT, "kubepods", cfg) == "-1"


class TestResctrlRangeMask:
    def test_disjoint_ranges_disjoint_masks(self):
        be = resctrl.range_to_way_mask(0, 30, 20)
        ls = resctrl.range_to_way_mask(30, 100, 20)
        assert be & ls == 0
        assert be | ls == (1 << 20) - 1

    def test_minimum_one_way(self):
        assert resctrl.range_to_way_mask(50, 50, 10).bit_count() == 1

    def test_full_range(self):
        assert resctrl.range_to_way_mask(0, 100, 12) == (1 << 12) - 1

    def test_sideways_cpuset_merge_then_shrink(self, cfg):
        # '0-3' -> '4-7': union written parent-first, final child-first
        for rel in ("kubepods", "kubepods/pod1"):
            write_cgroup_file(cfg, cg.CPUSET_CPUS, rel, "0-3")
        ex = rex.ResourceUpdateExecutor(cfg)
        writes = []
        orig = ex.update
        ex.update = lambda u: (writes.append((u.rel_dir, u.value)), orig(u))[1]
        ex.leveled_update_batch([
            rex.ResourceUpdate(cg.CPUSET_CPUS, "kubepods/pod1", "4-7"),
            rex.ResourceUpdate(cg.CPUSET_CPUS, "kubepods", "4-7"),
        ])
        assert writes[0] == ("kubepods", "0-7")          # merge parent first
        assert writes[1] == ("kubepods/pod1", "0-7")
        assert writes[2] == ("kubepods/pod1", "4-7")     # shrink child first
        assert writes[3] == ("kubepods", "4-7")
        assert cg.cgroup_read(cg.CPUSET_CPUS, "kubepods", cfg) == "4-7"

    def test_adjacent_ranges_no_overlap_8_ways(self):
        be = resctrl.range_to_way_mask(0, 30, 8)
        ls = resctrl.range_to_way_mask(30, 100, 8)
        assert be & ls == 0
        assert (be | ls).bit_count() == 8
