"""Solve-quality subsystem acceptance suite (ISSUE 13).

Contracts under test:

- **oracle exactness**: the jitted LP-relaxation solve
  (quality/lp_pack) is bit-identical to a plain-Python/NumPy
  reimplementation of the dual-price ascent + masked rounding loop at
  small shapes — every price, choice and acceptance is integer
  arithmetic, so exactness is equality, not tolerance;
- **never-overcommit**: on randomized fixtures (quota-charged
  included), the quality solve never exceeds node capacity and its
  accounting equals old + exactly-one-charge-per-placed-pod — the
  acceptance runs through the greedy path's own oracles, so this is a
  property of construction, verified anyway;
- **packing quality**: on seeded tight-packing fixtures the LP solve
  achieves strictly higher assigned fraction than the greedy batch
  solve at every fixture shape (the fragmentation trap greedy cannot
  see);
- **mesh invariance**: bit-identical assignments/accounting/quota at
  1/2/4/8-way CPU meshes (sharded_lp_pack_assign);
- **bounded iterations**: the rounding loop executes at most its
  static bound and reports the count;
- **scheduler wiring**: quality_mode="off" rounds are bit-identical to
  a default scheduler's; "lp" rounds pack the trap; "auto" escalates
  on slack; the tenant-batched cycle with quality tenants falls back
  to the pipelined dispatch and matches standalone execution.

Compile budget: tiny shapes, one shared problem per class where
possible, the 1/2/4/8 sweep on one small program.
"""

import numpy as np
import pytest

from koordinator_tpu.api.resources import (
    NUM_RESOURCE_DIMS,
    ResourceDim,
    resource_vector,
)
from koordinator_tpu.ops.assignment import ScoringConfig, score_pods
from koordinator_tpu.ops.batch_assign import _SCORE_CLIP, batch_assign
from koordinator_tpu.quality import lp_pack
from koordinator_tpu.quality.lp_pack import lp_pack_assign
from koordinator_tpu.quality.topo_gang import (
    gang_topo_diameter,
    plan_diameter,
    plan_gang_placement_quality,
    rank_candidates_quality,
)
from koordinator_tpu.state.cluster_state import ClusterState, PodBatch

from tests.conftest import prop_seeds

R = NUM_RESOURCE_DIMS
CPU, MEM = ResourceDim.CPU, ResourceDim.MEMORY


def plain_cfg():
    """Thresholds/estimator defaults off: fixtures reason about raw
    capacity fit, not load-aware estimation."""
    import jax.numpy as jnp

    return ScoringConfig.default().replace(
        usage_thresholds=jnp.zeros(R, jnp.int32),
        estimator_defaults=jnp.zeros(R, jnp.int32),
    )


def tight_fixture(m: int, node_capacity: int | None = None,
                  pod_capacity: int | None = None):
    """m interleaved copies of the fragmentation trap.

    Per copy: a big node (16k CPU) and a small node (10k).  Pod A (req
    10k, HIGH priority) scores the big node higher (more headroom
    after placement); pod B (req 16k, low priority) fits ONLY the big
    node.  Greedy fixes A onto the big node first and strands B —
    50% assigned.  The LP price ascent makes the contended big node
    expensive until A (who has an alternative) drains to the small
    node, then fixes both — 100% assigned.

    ``node_capacity``/``pod_capacity`` pad the tensors (invalid
    rows) so a fixture can reuse another test's jit cache entry —
    compile count is this suite's tier-1 budget.
    """
    alloc = np.zeros((2 * m, R), np.int32)
    alloc[0::2, CPU] = 16_000
    alloc[1::2, CPU] = 10_000
    alloc[:, MEM] = 65_536
    n_cap = node_capacity if node_capacity is not None else 2 * m
    state = ClusterState.from_arrays(alloc, capacity=n_cap)
    req = np.zeros((2 * m, R), np.int32)
    req[0::2, CPU] = 10_000
    req[1::2, CPU] = 16_000
    req[:, MEM] = 1_024
    prio = np.zeros(2 * m, np.int32)
    prio[0::2] = 9_000
    prio[1::2] = 3_000
    pods = PodBatch.build(
        req, priority=prio, node_capacity=n_cap,
        capacity=(pod_capacity if pod_capacity is not None
                  else max(2 * m, 2)))
    return state, pods


def rand_problem(n_nodes=32, n_pods=24, seed=0):
    from tests.problem_helpers import build_problem

    state, pods = build_problem(n_nodes=n_nodes, n_pods=n_pods,
                                seed=seed, factored=False)
    return state, pods


def assigned_count(a) -> int:
    return int((np.asarray(a) >= 0).sum())


def check_accounting(state, new_state, pods, a):
    """Overcommit + exact-charge invariants."""
    a = np.asarray(a)
    used = np.asarray(new_state.node_requested)
    alloc = np.asarray(new_state.node_allocatable)
    valid = np.asarray(new_state.node_valid)
    assert (used[valid] <= alloc[valid]).all(), "node overcommitted"
    add = np.zeros_like(np.asarray(state.node_requested))
    req = np.asarray(pods.requests)
    for p in np.flatnonzero(a >= 0):
        add[a[p]] += req[p]
    assert (np.asarray(state.node_requested) + add == used).all(), \
        "accounting is not exactly one charge per placed pod"


# ---------------------------------------------------------------------------
# NumPy oracle: the whole price/round loop in plain integer Python
# ---------------------------------------------------------------------------


def lp_oracle(state, pods, cfg, ascent_iters, rounding_iters):
    """Plain-NumPy mirror of quality/lp_pack._lp_core (quota=None).

    Every step is integer arithmetic on host ints, deliberately
    re-derived from the documented algorithm (not the JAX code), so a
    drift in either implementation breaks equality.
    """
    import jax

    scores, feasible = jax.jit(score_pods)(state, pods, cfg)
    scores = np.asarray(scores).astype(np.int64)
    feasible = np.asarray(feasible)
    n = state.capacity
    p = pods.capacity
    alloc = np.asarray(state.node_allocatable).astype(np.int64)
    node_valid = np.asarray(state.node_valid)
    requested = np.asarray(state.node_requested).astype(np.int64).copy()
    req = np.asarray(pods.requests).astype(np.int64)
    prio = np.asarray(pods.priority)
    valid = np.asarray(pods.valid)
    rot = np.asarray(pods.rot_id).astype(np.int64)

    base = np.clip(scores, 0, _SCORE_CLIP)
    # priority-descending stable order (the solver queue order)
    order = np.lexsort((np.arange(p), -prio))
    # tie-break rotation over COMPACTED valid-node positions (padded
    # rows don't dilute the fan — see lp_pack._priced_keys)
    pos = np.cumsum(node_valid) - node_valid
    n_valid = max(int(node_valid.sum()), 1)
    tb = (n - 1) - ((pos[None, :] - rot[:, None] * 7919) % n_valid)
    alloc_den = np.maximum(alloc, 1)

    prices = np.zeros(n, np.int64)
    assignments = np.full(p, -1, np.int64)
    active = valid & feasible.any(axis=1)
    iters = 0
    for i in range(rounding_iters):
        if not active.any():
            break
        iters += 1
        free = np.where(node_valid[:, None], alloc - requested, 0)
        fits = feasible & ((req[:, None, :] <= free[None, :, :])
                           | (req[:, None, :] == 0)).all(axis=-1)
        active = active & fits.any(axis=1)

        def choose(prices_now):
            u = np.clip(base - prices_now[None, :], -_SCORE_CLIP,
                        _SCORE_CLIP) + _SCORE_CLIP
            key = ((u >> 1) << 15) | tb       # packed regime (n <= 2^15)
            key = np.where(fits, key, -1)
            choice = key.argmax(axis=1)
            has = key[np.arange(p), choice] >= 0
            return choice, has

        def demand_of(choice, mask):
            d = np.zeros((n, R), np.int64)
            for j in np.flatnonzero(mask):
                d[choice[j]] += req[j]
            return d

        for _ in range(ascent_iters):
            choice, has = choose(prices)
            act = active & has
            demand = demand_of(choice, act)
            over = np.clip(demand - free, 0, lp_pack._OVERLOAD_CLIP)
            bump = ((over * lp_pack.PRICE_GAIN + alloc_den - 1)
                    // alloc_den).max(axis=-1)
            bump = np.where((over > 0).any(axis=-1),
                            np.maximum(bump, lp_pack.PRICE_MIN_STEP), 0)
            prices = np.clip(prices + bump, 0, lp_pack.PRICE_CAP)

        choice, has = choose(prices)
        act = active & has
        demand = demand_of(choice, act)
        confident = ~(demand[choice] > free[choice]).any(axis=-1)
        last = (i + 1) >= rounding_iters
        act_round = act & (confident | last)

        # sequential prefix acceptance in priority order: inclusive
        # cumulative demand per chosen node must fit its start-of-round
        # headroom (rejected pods still count toward later prefixes)
        cum = np.zeros((n, R), np.int64)
        accept = np.zeros(p, bool)
        for j in order:
            if not act_round[j]:
                continue
            cum[choice[j]] += req[j]
            ok = ((cum[choice[j]] <= free[choice[j]])
                  | (req[j] == 0)).all()
            accept[j] = ok
        for j in np.flatnonzero(accept):
            requested[choice[j]] += req[j]
            assignments[j] = choice[j]
        active = active & ~accept
    return assignments, requested, iters


class TestOracleExactness:
    @pytest.mark.parametrize("seed", prop_seeds(4))
    def test_lp_solve_matches_numpy_oracle(self, seed):
        import jax

        state, pods = rand_problem(n_nodes=16, n_pods=12, seed=seed)
        cfg = plain_cfg()
        a, st, _, iters = jax.jit(
            lp_pack_assign,
            static_argnames=("ascent_iters", "rounding_iters"))(
                state, pods, cfg, ascent_iters=4, rounding_iters=3)
        oa, oreq, oiters = lp_oracle(state, pods, cfg,
                                     ascent_iters=4, rounding_iters=3)
        assert np.asarray(a).tolist() == oa.tolist()
        assert np.asarray(st.node_requested).tolist() == oreq.tolist()
        assert int(iters) == oiters

    def test_tight_fixture_matches_oracle(self):
        import jax

        state, pods = tight_fixture(2)
        cfg = plain_cfg()
        a, st, _, iters = jax.jit(lp_pack_assign)(state, pods, cfg)
        oa, oreq, oiters = lp_oracle(
            state, pods, cfg, ascent_iters=lp_pack.ASCENT_ITERS,
            rounding_iters=lp_pack.ROUNDING_ITERS)
        assert np.asarray(a).tolist() == oa.tolist()
        assert int(iters) == oiters


# ---------------------------------------------------------------------------
# feasibility properties
# ---------------------------------------------------------------------------


class TestNeverOvercommit:
    @pytest.mark.parametrize("seed", prop_seeds(6))
    def test_randomized_fixtures_never_overcommit(self, seed):
        import jax

        state, pods = rand_problem(n_nodes=32, n_pods=40, seed=seed)
        cfg = plain_cfg()
        a, st, _, _ = jax.jit(lp_pack_assign)(state, pods, cfg)
        check_accounting(state, st, pods, a)
        # placements only on scored-feasible nodes
        _, feasible = jax.jit(score_pods)(state, pods, cfg)
        feasible = np.asarray(feasible)
        a = np.asarray(a)
        for p in np.flatnonzero(a >= 0):
            assert feasible[p, a[p]], "placed on an infeasible node"

    def test_quota_charges_are_exact(self):
        import jax
        import jax.numpy as jnp

        from koordinator_tpu.quota.admission import (
            QuotaDeviceState,
            charge_quota_batch,
        )
        from koordinator_tpu.quota.tree import UNBOUNDED, QuotaTree

        state, pods = rand_problem(n_nodes=32, n_pods=24, seed=7)
        total = np.zeros(R, np.int64)
        total[CPU] = 500_000
        tree = QuotaTree(total)
        mx = np.full(R, UNBOUNDED, np.int64)
        mx[CPU] = 18_000
        tree.add("q", min=np.zeros(R, np.int64), max=mx)
        tree.set_request("q", total)
        tree.refresh_runtime()
        quota, index = QuotaDeviceState.from_tree(tree, max_depth=3)
        qid = np.full(pods.capacity, -1, np.int32)
        qid[:16] = index["q"]
        pods = pods.replace(quota_id=jnp.asarray(qid))
        cfg = plain_cfg()
        a, st, new_quota, _ = jax.jit(lp_pack_assign)(
            state, pods, cfg, quota)
        check_accounting(state, st, pods, a)
        # the returned quota equals one whole-batch recharge of the
        # placed pods against the ORIGINAL quota — the same contract
        # the greedy passes keep
        keep = jnp.asarray(np.asarray(a) >= 0) & pods.valid
        expect = charge_quota_batch(quota, pods.requests, pods.quota_id,
                                    keep, pods.non_preemptible)
        for got, want in zip(jax.tree.leaves(new_quota),
                             jax.tree.leaves(expect)):
            assert np.asarray(got).tolist() == np.asarray(want).tolist()
        # quota max respected: charged CPU within the 18k ceiling
        a_np = np.asarray(a)
        charged = sum(int(np.asarray(pods.requests)[p][CPU])
                      for p in np.flatnonzero(a_np >= 0)
                      if qid[p] >= 0)
        assert charged <= 18_000

    def test_bounded_iterations(self):
        import jax

        state, pods = rand_problem(n_nodes=16, n_pods=32, seed=3)
        cfg = plain_cfg()
        for bound in (1, 2, 4):
            a, st, _, iters = jax.jit(
                lp_pack_assign,
                static_argnames=("ascent_iters", "rounding_iters"))(
                    state, pods, cfg, ascent_iters=2,
                    rounding_iters=bound)
            assert int(iters) <= bound
            check_accounting(state, st, pods, a)


# ---------------------------------------------------------------------------
# packing quality vs greedy
# ---------------------------------------------------------------------------


class TestBeatsGreedyOnTightPacking:
    @pytest.mark.parametrize("m", (1, 2, 8))
    def test_assigned_fraction_beats_greedy(self, m):
        import jax

        state, pods = tight_fixture(m)
        cfg = plain_cfg()
        ga, gst, _ = jax.jit(batch_assign)(state, pods, cfg)
        la, lst, _, _ = jax.jit(lp_pack_assign)(state, pods, cfg)
        greedy_n, lp_n = assigned_count(ga), assigned_count(la)
        assert lp_n == 2 * m, "LP must pack the whole fixture"
        assert greedy_n < lp_n, \
            "greedy must strand the trap or the fixture proves nothing"
        check_accounting(state, lst, pods, la)
        # the slack side of the acceptance criterion: strictly more
        # capacity put to work
        g_free = np.asarray(gst.node_allocatable
                            - gst.node_requested)[:, CPU].sum()
        l_free = np.asarray(lst.node_allocatable
                            - lst.node_requested)[:, CPU].sum()
        assert l_free < g_free


# ---------------------------------------------------------------------------
# mesh invariance
# ---------------------------------------------------------------------------


class TestMeshInvariance:
    def _sweep(self, widths):
        """Bit-identity of the sharded LP solve vs single-device at the
        given mesh widths, plus the PADDED tight fixture at the widest
        mesh (same (64-node, 32-pod) shapes, so it's a jit-cache hit on
        the memoized shard_map program — zero extra compiles)."""
        import jax

        from koordinator_tpu.parallel import mesh as pmesh
        from koordinator_tpu.parallel import sharded as ps

        state, pods = rand_problem(n_nodes=64, n_pods=24, seed=5)
        cfg = plain_cfg()
        a0, st0, _, it0 = jax.jit(lp_pack_assign)(state, pods, cfg)
        a0 = np.asarray(a0)
        r0 = np.asarray(st0.node_requested)
        for d in widths:
            mesh = pmesh.solver_mesh(jax.devices()[:d])
            a, st, _, it = ps.sharded_lp_pack_assign(
                mesh, state, pods, cfg)
            assert np.asarray(a).tolist() == a0.tolist(), \
                f"{d}-way assignments diverged"
            assert (np.asarray(st.node_requested) == r0).all(), \
                f"{d}-way accounting diverged"
            assert int(it) == int(it0)
        tstate, tpods = tight_fixture(8, node_capacity=64,
                                      pod_capacity=32)
        ta0, _, _, _ = jax.jit(lp_pack_assign)(tstate, tpods, cfg)
        mesh = pmesh.solver_mesh(jax.devices()[:max(widths)])
        ta, tst, _, _ = ps.sharded_lp_pack_assign(mesh, tstate, tpods,
                                                  cfg)
        assert np.asarray(ta).tolist() == np.asarray(ta0).tolist()
        assert assigned_count(ta) == 16
        check_accounting(tstate, tst, tpods, ta)

    def test_bit_identical_across_2_8_shards(self):
        # the tier-1 (compile-budget) slice of the sweep: the narrowest
        # REAL shard split and the acceptance criterion's 8-way mesh
        self._sweep((2, 8))

    @pytest.mark.slow
    def test_bit_identical_across_1_2_4_8_shards(self):
        # the full ISSUE 13 sweep incl. the degenerate 1-way mesh —
        # two more one-off shard_map compiles, so it rides the slow
        # lane with the other exhaustive sweeps
        self._sweep((1, 2, 4, 8))


# ---------------------------------------------------------------------------
# scheduler wiring
# ---------------------------------------------------------------------------


def _mk_sched(nodes, quality_mode="off", **kw):
    from koordinator_tpu.scheduler import (
        ClusterSnapshot,
        NodeSpec,
        Scheduler,
    )

    snap = ClusterSnapshot(capacity=16)
    for name, cpu in nodes:
        snap.upsert_node(NodeSpec(
            name=name,
            allocatable=resource_vector(cpu=cpu, memory=65_536)))
    return Scheduler(snap, config=plain_cfg(),
                     quality_mode=quality_mode, **kw)


def _trap_pods():
    from koordinator_tpu.scheduler import PodSpec

    return [
        PodSpec(name="a",
                requests=resource_vector(cpu=10_000, memory=1_024),
                priority=9_000),
        PodSpec(name="b",
                requests=resource_vector(cpu=16_000, memory=1_024),
                priority=3_000),
    ]


TRAP_NODES = [("big", 16_000), ("small", 10_000)]


class TestSchedulerWiring:
    def test_quality_off_is_bit_identical_to_default(self):
        results = []
        for kwargs in ({}, {"quality_mode": "off"}):
            sched = _mk_sched(TRAP_NODES, **kwargs)
            for p in _trap_pods():
                sched.enqueue(p)
            results.append(sched.schedule_round())
        assert dict(results[0].assignments) == dict(results[1].assignments)
        assert set(results[0].failures) == set(results[1].failures)
        assert results[0].assignments == {"a": "big"}

    def test_lp_mode_packs_the_trap(self):
        from koordinator_tpu import metrics

        sched = _mk_sched(TRAP_NODES, quality_mode="lp")
        for p in _trap_pods():
            sched.enqueue(p)
        res = sched.schedule_round()
        assert res.assignments == {"a": "small", "b": "big"}
        assert not res.failures
        assert sched.last_solve_path == "quality_lp"
        assert metrics.quality_rounds.value(
            {"mode": "lp", "outcome": "complete"}) == 1.0
        rec = sched.flight_recorder.last()
        assert rec.quality_mode == "lp"
        assert rec.quality_iterations >= 1

    def test_auto_mode_escalates_on_slack(self):
        from koordinator_tpu import metrics
        from koordinator_tpu.scheduler import PodSpec

        from koordinator_tpu.scheduler import NodeSpec

        sched = _mk_sched(TRAP_NODES, quality_mode="auto",
                          quality_slack_threshold=0.2)
        # an aux node keeps the warm-up round off the trap capacity
        sched.snapshot.upsert_node(NodeSpec(
            name="aux",
            allocatable=resource_vector(cpu=2_000, memory=65_536),
            labels={"pool": "aux"}))
        # round 1: greedy (no prior slack measurement), leaves slack
        sched.enqueue(PodSpec(
            name="warm", requests=resource_vector(cpu=500, memory=256),
            node_selector={"pool": "aux"}))
        sched.schedule_round()
        assert sched._quality_escalate
        for p in _trap_pods():
            sched.enqueue(p)
        res = sched.schedule_round()
        assert sched.last_solve_path == "quality_lp"
        assert res.assignments["b"] == "big"
        assert metrics.quality_rounds.value(
            {"mode": "auto", "outcome": "complete"}) >= 1.0

    def test_auto_mode_stays_greedy_below_threshold(self):
        from koordinator_tpu.scheduler import PodSpec

        sched = _mk_sched([("n0", 4_000)], quality_mode="auto",
                          quality_slack_threshold=0.9)
        sched.enqueue(PodSpec(
            name="fill", requests=resource_vector(cpu=3_800, memory=256)))
        sched.schedule_round()
        assert not sched._quality_escalate
        sched.enqueue(PodSpec(
            name="next", requests=resource_vector(cpu=100, memory=64)))
        sched.schedule_round()
        assert sched.last_solve_path != "quality_lp"


class TestTenantBatchedCycle:
    def test_quality_tenants_fall_back_and_match_standalone(self):
        """A quality-mode tenant cycle must (a) never take the
        tenant-axis batched program, (b) produce the SAME binds as the
        standalone scheduler fed identically."""
        from koordinator_tpu.scheduler.tenancy import (
            TenantScheduler,
            TenantSpec,
        )

        def feed(sched, salt):
            from koordinator_tpu.scheduler import NodeSpec, PodSpec

            sched.snapshot.upsert_node(NodeSpec(
                name="big",
                allocatable=resource_vector(cpu=16_000, memory=65_536)))
            sched.snapshot.upsert_node(NodeSpec(
                name="small",
                allocatable=resource_vector(cpu=10_000, memory=65_536)))
            sched.enqueue(PodSpec(
                name=f"a{salt}",
                requests=resource_vector(cpu=10_000, memory=1_024),
                priority=9_000))
            sched.enqueue(PodSpec(
                name=f"b{salt}",
                requests=resource_vector(cpu=16_000, memory=1_024),
                priority=3_000))

        front = TenantScheduler(cycle_pod_budget=1 << 16)
        for name in ("t0", "t1"):
            front.add_tenant(
                TenantSpec(name=name, node_capacity=16),
                config=plain_cfg(), quality_mode="lp",
                batch_solver_threshold=1)
        for i, tenant in enumerate(front.tenants()):
            feed(tenant.scheduler, i)
        results = front.schedule_cycle()
        assert front.last_mode != "batched", \
            "quality tenants must not enter the tenant-axis program"
        solo = {}
        for i, name in enumerate(("t0", "t1")):
            sched = _mk_sched([], quality_mode="lp",
                              batch_solver_threshold=1)
            feed(sched, i)
            solo[name] = sched.schedule_round()
        for name in ("t0", "t1"):
            assert dict(results[name].assignments) == \
                dict(solo[name].assignments), f"tenant {name} diverged"
            assert results[name].assignments[f"b{name[-1]}"] == "big"


# ---------------------------------------------------------------------------
# topology-aware gang quality
# ---------------------------------------------------------------------------


def _mk_tree(spines=2, blocks=2, nodes=2):
    from koordinator_tpu.ops.network_topology import TopologyTree

    tree = TopologyTree(["spine", "block", "node"])
    idx = 0
    for s in range(spines):
        for b in range(blocks):
            for _ in range(nodes):
                tree.add_node([f"s{s}", f"b{s}.{b}", f"n{idx}"])
                idx += 1
    return tree.build(), idx


class TestTopoGang:
    def test_diameter_matches_numpy_oracle(self):
        import jax
        import jax.numpy as jnp

        topo, n = _mk_tree(spines=2, blocks=2, nodes=2)
        rng = np.random.default_rng(11)
        paths = np.asarray(topo.node_path)
        leaf = topo.num_layers - 1
        for _ in range(8):
            rows = rng.integers(0, n, size=5).astype(np.int32)
            valid = rng.random(5) < 0.8
            got = int(jax.jit(gang_topo_diameter)(
                jnp.asarray(rows), jnp.asarray(valid), topo))
            want = 0
            live = rows[valid]
            for i in range(len(live)):
                for j in range(len(live)):
                    shared = int((np.cumprod(
                        paths[live[i]] == paths[live[j]])).sum())
                    want = max(want, 2 * (leaf - (shared - 1)))
            assert got == want

    def test_same_rack_is_diameter_two_cross_spine_six(self):
        import jax
        import jax.numpy as jnp

        topo, _ = _mk_tree()
        d = jax.jit(gang_topo_diameter)
        same_rack = int(d(jnp.asarray([0, 1]), jnp.asarray([True, True]),
                          topo))
        cross_spine = int(d(jnp.asarray([0, 7]),
                            jnp.asarray([True, True]), topo))
        single = int(d(jnp.asarray([3]), jnp.asarray([True]), topo))
        assert (same_rack, cross_spine, single) == (2, 6, 0)

    def test_quality_rank_prefers_tight_fit_over_peers(self):
        import jax.numpy as jnp

        """Baseline order puts the existing-peer subtree first; the
        quality order puts the tighter-fitting one first at equal
        depth."""
        from koordinator_tpu.ops.network_topology import rank_candidates

        topo, n = _mk_tree(spines=1, blocks=2, nodes=2)
        t = topo.num_topo
        cand = np.zeros(t, bool)
        slots = np.zeros(t, np.int32)
        existing = np.zeros(t, np.int32)
        scores = np.zeros(t, np.int32)
        block_ids = np.flatnonzero(
            np.asarray(topo.topo_layer) == 2)    # block layer
        loose, tight = int(block_ids[0]), int(block_ids[1])
        cand[[loose, tight]] = True
        slots[loose], slots[tight] = 8, 2        # desired 2: tight fits
        existing[loose] = 3                      # peers on the loose one
        base = np.asarray(rank_candidates(
            topo, jnp.asarray(cand), jnp.asarray(slots),
            jnp.asarray(scores), jnp.asarray(existing)))
        qual = np.asarray(rank_candidates_quality(
            topo, jnp.asarray(cand), jnp.asarray(slots),
            jnp.asarray(scores), jnp.asarray(existing)))
        assert base[0] == loose, "baseline should chase existing peers"
        assert qual[0] == tight, "quality should take the tight subtree"

    def test_quality_plan_diameter_never_worse(self):
        import jax.numpy as jnp

        """Property over random topologies/occupancies: the quality
        planner's realized diameter is <= the baseline planner's."""
        from koordinator_tpu.ops.network_topology import (
            TopologyRequirements,
            plan_gang_placement,
        )

        for seed in prop_seeds(4):
            rng = np.random.default_rng(seed)
            topo, n = _mk_tree(spines=2, blocks=2, nodes=2)
            alloc = np.zeros((n, R), np.int32)
            alloc[:, CPU] = rng.integers(2_000, 9_000, n)
            alloc[:, MEM] = 65_536
            state = ClusterState.from_arrays(alloc)
            members = 3
            req = np.zeros((members, R), np.int32)
            req[:, CPU] = 2_000
            req[:, MEM] = 1_024
            pods = PodBatch.build(req, node_capacity=n)
            mask = np.zeros(pods.capacity, bool)
            mask[:members] = True
            existing = jnp.asarray(
                rng.integers(0, 2, n).astype(np.int32))
            treq = TopologyRequirements(desired_slots=members)
            base = plan_gang_placement(
                state, pods, mask, topo, treq, node_existing=existing)
            qual = plan_gang_placement_quality(
                state, pods, mask, topo, treq, node_existing=existing)
            placed_b = int((base >= 0).sum())
            placed_q = int((qual >= 0).sum())
            assert placed_q >= placed_b, \
                "quality planner lost feasibility"
            if placed_b and placed_q:
                assert plan_diameter(qual, topo) <= \
                    plan_diameter(base, topo), f"seed {seed}"

    def test_scheduler_gang_uses_quality_planner(self):
        """An end-to-end gang round in quality mode plans through the
        minimal-diameter planner and still binds the whole gang."""
        from koordinator_tpu.ops.network_topology import (
            TopologyRequirements,
            TopologyTree,
        )
        from koordinator_tpu.scheduler import (
            ClusterSnapshot,
            NodeSpec,
            PodSpec,
            Scheduler,
        )

        tree = TopologyTree(["block", "node"])
        snap = ClusterSnapshot(capacity=8)
        names = []
        for b in range(2):
            for i in range(2):
                name = f"b{b}-n{i}"
                tree.add_node([f"b{b}", name])
                names.append(name)
        topo = tree.build(capacity=8)
        for name in names:
            snap.upsert_node(NodeSpec(
                name=name,
                allocatable=resource_vector(cpu=8_000, memory=65_536)))
        from koordinator_tpu.scheduler.scheduler import GangRecord

        sched = Scheduler(snap, config=plain_cfg(),
                          topology_tree=topo, quality_mode="lp")
        sched.register_gang(GangRecord(
            name="g", min_member=2,
            topology=TopologyRequirements(desired_slots=2)))
        for i in range(2):
            sched.enqueue(PodSpec(
                name=f"g{i}",
                requests=resource_vector(cpu=2_000, memory=1_024),
                gang="g"))
        res = sched.schedule_round()
        assert len(res.assignments) == 2
        placed = {res.assignments[f"g{i}"] for i in range(2)}
        # minimal diameter: both members inside ONE block
        blocks = {name.split("-")[0] for name in placed}
        assert len(blocks) == 1
