"""Descheduler framework: profiles, evictor filter (PDB), evictor modes,
LowNodeLoad bridge, migration-backed eviction."""

import numpy as np
import pytest

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS, ResourceDim
from koordinator_tpu.descheduler.framework import (
    Descheduler, Evictor, EvictorFilter, MODE_DELETE, MODE_SOFT, PDB, PodInfo,
    Profile,
)
from koordinator_tpu.descheduler.migration import MigrationController
from koordinator_tpu.descheduler.plugins import (
    CustomPriorityPlugin, LowNodeLoadPlugin, migration_evict_fn,
)


def pod(uid, node="n0", priority=5500, **kw):
    return PodInfo(uid=uid, name=uid, namespace="default", node=node,
                   priority=priority, **kw)


class TestEvictorFilter:
    def test_daemonset_and_storage_guards(self):
        f = EvictorFilter()
        assert not f.filter(pod("a", is_daemonset=True))[0]
        assert not f.filter(pod("b", has_local_storage=True))[0]
        assert f.filter(pod("c"))[0]

    def test_priority_threshold(self):
        f = EvictorFilter(priority_threshold=9000)
        assert f.filter(pod("a", priority=5000))[0]
        assert not f.filter(pod("b", priority=9500))[0]

    def test_pdb_budget(self):
        f = EvictorFilter(pdbs=[PDB(selector={"app": "web"},
                                    disruptions_allowed=1)])
        p1 = pod("a", labels={"app": "web"})
        p2 = pod("b", labels={"app": "web"})
        assert f.filter(p1)[0]
        f.consume_budget(p1)
        ok, reason = f.filter(p2)
        assert not ok and "PDB" in reason

    def test_eviction_cost_annotation(self):
        f = EvictorFilter()
        p = pod("a", annotations={ext.ANNOTATION_EVICTION_COST: "-2147483648"})
        assert not f.filter(p)[0]


class TestEvictorModes:
    def test_delete_mode(self):
        deleted = []
        ev = Evictor(mode=MODE_DELETE, delete_fn=lambda p: deleted.append(p.uid) or True)
        assert ev.evict(pod("a"), "r")
        assert deleted == ["a"]

    def test_soft_mode_labels(self):
        labeled = {}
        ev = Evictor(mode=MODE_SOFT,
                     label_fn=lambda p, ls: labeled.update({p.uid: ls}) or True)
        ev.evict(pod("a"), "LowNodeLoad")
        assert labeled["a"][ext.LABEL_SOFT_EVICTION] == "LowNodeLoad"


class TestProfileRound:
    def test_round_limit_and_filters(self):
        pods = [pod(f"p{i}", priority=3500) for i in range(5)]
        plugin = CustomPriorityPlugin(priority_floor=5000)
        profile = Profile(
            name="default",
            deschedule_plugins=[plugin],
            max_evictions_per_round=2,
        )
        d = Descheduler([profile], pods_fn=lambda: pods)
        out = d.run_once()
        assert out["default"] == 2
        assert len(profile.evictor.evicted) == 2

    def test_tick_interval(self):
        from tests.test_koordlet_metrics import FakeClock

        clock = FakeClock()
        profile = Profile(name="p")
        d = Descheduler([profile], pods_fn=list, interval_seconds=120,
                        clock=clock)
        assert d.tick() is not None
        assert d.tick() is None
        clock.tick(121)
        assert d.tick() is not None


def make_state(n=4, hot_node=0):
    r = NUM_RESOURCE_DIMS
    capacity = np.zeros((n, r), np.int32)
    capacity[:, ResourceDim.CPU] = 10_000
    capacity[:, ResourceDim.MEMORY] = 10_000
    usage = np.zeros((n, r), np.int32)
    usage[:, ResourceDim.CPU] = 2_000          # cold nodes: 20%
    usage[hot_node, ResourceDim.CPU] = 9_000   # hot: 90% > high 65%
    valid = np.ones(n, bool)
    names = [f"n{i}" for i in range(n)]
    return usage, capacity, valid, names


class TestLowNodeLoadPlugin:
    def run_rounds(self, rounds=3):
        pods = [pod("victim", node="n0", priority=3500),
                pod("keeper", node="n1", priority=9500)]

        def pod_usage(p):
            u = np.zeros(NUM_RESOURCE_DIMS, np.int32)
            u[ResourceDim.CPU] = 3000 if p.uid == "victim" else 500
            return u

        plugin = LowNodeLoadPlugin(state_fn=make_state, pod_usage_fn=pod_usage)
        profile = Profile(name="ln", balance_plugins=[plugin])
        d = Descheduler([profile], pods_fn=lambda: pods)
        results = [d.run_once() for _ in range(rounds)]
        return results, profile

    def test_anomaly_gating_then_evict(self):
        results, profile = self.run_rounds(3)
        # rounds 1-2: anomaly counter below threshold (3) -> no eviction
        assert results[0]["ln"] == 0
        assert results[1]["ln"] == 0
        assert results[2]["ln"] == 1
        assert profile.evictor.evicted == [("victim", "LowNodeLoad")]


class TestMigrationSink:
    def test_eviction_creates_jobs(self):
        controller = MigrationController()
        ev = Evictor(evict_fn=migration_evict_fn(controller))
        profile = Profile(
            name="p",
            deschedule_plugins=[CustomPriorityPlugin(priority_floor=5000)],
            evictor=ev,
        )
        pods = [pod("a", priority=3500, owner="Deployment/web")]
        Descheduler([profile], pods_fn=lambda: pods).run_once()
        assert len(controller.jobs) == 1
        job = next(iter(controller.jobs.values()))
        assert job.pod == "a" and job.workload == "Deployment/web"
