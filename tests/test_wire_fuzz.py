"""Byte-level robustness of the framed transport.

A sidecar's listen socket is reachable by anything on the node —
kubelet restarts mid-write, a confused peer, a port scanner.  Feed a
live RpcServer raw garbage at every protocol layer and require the one
acceptable outcome: that CONNECTION dies or errors, the server thread
survives, and a fresh well-formed client still completes a call.
"""

import socket
import struct

import pytest

from koordinator_tpu.transport import RpcClient, RpcServer
from koordinator_tpu.transport.wire import MAGIC, VERSION, FrameType


@pytest.fixture
def server():
    srv = RpcServer("tcp://127.0.0.1:0")
    srv.start()
    yield srv
    srv.stop()


def _raw_conn(server) -> socket.socket:
    host, port = server.address[len("tcp://"):].rsplit(":", 1)
    s = socket.create_connection((host, int(port)), timeout=5)
    s.settimeout(5)
    return s


def _server_still_works(server) -> None:
    client = RpcClient(server.address, timeout=10.0)
    client.connect()
    try:
        ftype, doc, _ = client.call(FrameType.PING, {})
        assert ftype is FrameType.ACK   # the built-in ping answered
    finally:
        client.close()


def _header(magic=MAGIC, version=VERSION, ftype=10, req_id=1, length=0):
    return struct.pack("<HBBII", magic, version, ftype, req_id, length)


def _drain(sock) -> None:
    """Read until the peer closes or times out — we only care that the
    server's answer to garbage is an error/close, not what it says."""
    try:
        while sock.recv(4096):
            pass
    except OSError:
        pass


GARBAGE = [
    b"",                                          # immediate close
    b"\x00" * 64,                                 # zero noise
    b"GET / HTTP/1.1\r\n\r\n",                    # wrong protocol entirely
    _header(magic=0xDEAD),                        # bad magic
    _header(version=99),                          # unknown framing version
    _header(ftype=250, length=4) + b"\x00" * 4,   # unknown frame type
    _header(length=2 ** 31 - 1),                  # absurd length word
    _header(ftype=10, length=8) + b"\xff" * 8,    # payload json_len lies
    # valid header, json_len exceeds payload
    _header(ftype=10, length=6) + struct.pack("<I", 400) + b"xx",
    # valid json, arrays manifest points past the raw section
    (lambda body: _header(ftype=1, length=len(body)) + body)(
        struct.pack("<I", 76)
        + b'{"last_rv":-1,"proto":3,"__arrays__":[{"key":"a","dtype":"<i4",'
          b'"shape":[64],"offset":9999,"nbytes":256}]}'),
    # truncated frame: header promises more than is sent, then close
    _header(ftype=10, length=100) + b"short",
]


@pytest.mark.parametrize("blob", range(len(GARBAGE)))
def test_garbage_never_kills_the_server(server, blob):
    s = _raw_conn(server)
    try:
        if GARBAGE[blob]:
            s.sendall(GARBAGE[blob])
        _drain(s)
    finally:
        s.close()
    _server_still_works(server)


def test_garbage_on_one_connection_leaves_others_untouched(server):
    """A garbage peer must only cost ITS connection: a healthy client
    connected at the same time keeps calling through the abuse."""
    client = RpcClient(server.address, timeout=10.0)
    client.connect()
    try:
        for blob in GARBAGE:
            raw = _raw_conn(server)
            try:
                if blob:
                    raw.sendall(blob)
                _drain(raw)
            finally:
                raw.close()
            ftype, doc, _ = client.call(FrameType.PING, {})
            assert ftype is FrameType.ACK
    finally:
        client.close()
