import jax.numpy as jnp
import numpy as np

from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS, ResourceDim, resource_vector
from koordinator_tpu.ops.assignment import ScoringConfig
from koordinator_tpu.quota.tree import UNBOUNDED, QuotaTree
from koordinator_tpu.scheduler import ClusterSnapshot, NodeSpec, PodSpec, Scheduler
from koordinator_tpu.scheduler.scheduler import GangRecord

R = NUM_RESOURCE_DIMS
CPU, MEM = ResourceDim.CPU, ResourceDim.MEMORY


def plain_cfg():
    return ScoringConfig.default().replace(
        usage_thresholds=jnp.zeros(R, jnp.int32),
        estimator_defaults=jnp.zeros(R, jnp.int32),
    )


def node(name, cpu=16_000, mem=65_536, usage_cpu=0, labels=None):
    usage = np.zeros(R, np.int32)
    usage[CPU] = usage_cpu
    return NodeSpec(
        name=name,
        allocatable=resource_vector(cpu=cpu, memory=mem),
        usage=usage,
        labels=labels or {},
    )


def pod(name, cpu=1_000, mem=1_024, **kw):
    return PodSpec(name=name, requests=resource_vector(cpu=cpu, memory=mem), **kw)


def mk_scheduler(nodes, **kw):
    snap = ClusterSnapshot(capacity=16)
    for n in nodes:
        snap.upsert_node(n)
    binds = []
    sched = Scheduler(
        snap, config=kw.pop("config", plain_cfg()),
        bind_fn=lambda p, n: binds.append((p, n)), **kw,
    )
    return sched, binds


def test_basic_round_binds_pods():
    sched, binds = mk_scheduler([node("n1"), node("n2")])
    sched.enqueue(pod("p1", cpu=4_000))
    sched.enqueue(pod("p2", cpu=4_000))
    res = sched.schedule_round()
    assert set(res.assignments) == {"p1", "p2"}
    assert not res.failures
    assert len(binds) == 2
    assert not sched.pending
    # accounting persists: a third round sees the reserved capacity
    sched.enqueue(pod("p3", cpu=14_000))
    res2 = sched.schedule_round()
    assert "p3" in res2.failures  # 12k free per node at most
    msg = res2.failures["p3"].message()
    assert "insufficient resources" in msg


def test_node_selector_routes_pod():
    sched, _ = mk_scheduler([
        node("gpu-node", labels={"pool": "gpu"}),
        node("cpu-node", labels={"pool": "cpu"}),
    ])
    sched.enqueue(pod("p1", node_selector={"pool": "gpu"}))
    res = sched.schedule_round()
    assert res.assignments["p1"] == "gpu-node"


def test_node_remove_and_delta_flush():
    sched, _ = mk_scheduler([node("n1"), node("n2")])
    sched.snapshot.remove_node("n2")
    sched.enqueue(pod("p1", node_selector={}))
    res = sched.schedule_round()
    assert res.assignments["p1"] == "n1"
    # re-add with new capacity; delta flush picks it up
    sched.snapshot.upsert_node(node("n2", cpu=32_000))
    sched.enqueue(pod("p2", cpu=20_000))
    res2 = sched.schedule_round()
    assert res2.assignments["p2"] == "n2"


def test_snapshot_grows_past_capacity():
    snap = ClusterSnapshot(capacity=4)
    for i in range(10):
        snap.upsert_node(node(f"n{i}"))
    snap.flush()
    assert snap.capacity >= 10
    assert int(np.asarray(snap.state.node_valid).sum()) == 10


def test_gang_wait_time_rejection():
    t = [0.0]
    sched, _ = mk_scheduler([node("n1", cpu=4_000)], clock=lambda: t[0])
    sched.register_gang(GangRecord(name="g", min_member=2, wait_time_sec=100))
    sched.enqueue(pod("g1", cpu=3_000, gang="g"))
    sched.enqueue(pod("g2", cpu=3_000, gang="g"))
    res = sched.schedule_round()
    assert not res.assignments  # gang can't fit together
    t[0] = 50.0
    sched.schedule_round()
    assert not sched.gangs["g"].rejected
    t[0] = 200.0
    sched.schedule_round()  # past wait time -> rejected
    assert sched.gangs["g"].rejected
    # rejected gang pods no longer enter rounds
    res4 = sched.schedule_round()
    assert res4.round_pods == 0


def test_gang_schedules_when_feasible():
    sched, binds = mk_scheduler([node("n1"), node("n2")])
    sched.register_gang(GangRecord(name="g", min_member=3))
    for i in range(3):
        sched.enqueue(pod(f"g{i}", cpu=6_000, gang="g"))
    res = sched.schedule_round()
    assert len(res.assignments) == 3


def test_quota_accounting_across_rounds():
    mx = np.full(R, UNBOUNDED, np.int64)
    mx[CPU], mx[MEM] = 5_000, 131_072
    tree = QuotaTree(resource_vector(cpu=32_000, memory=131_072).astype(np.int64))
    tree.add("team", min=np.zeros(R, np.int64), max=mx)
    sched, _ = mk_scheduler([node("n1"), node("n2")], quota_tree=tree)

    sched.enqueue(pod("p1", cpu=3_000, quota="team"))
    res1 = sched.schedule_round()
    assert "p1" in res1.assignments
    # round 2: only 2000m quota left
    sched.enqueue(pod("p2", cpu=3_000, quota="team"))
    res2 = sched.schedule_round()
    assert "p2" in res2.failures
    assert res2.failures["p2"].quota_rejected or res2.failures["p2"].feasible_nodes == 0
    sched.enqueue(pod("p3", cpu=1_500, quota="team"))
    res3 = sched.schedule_round()
    assert "p3" in res3.assignments


def test_row_reuse_does_not_inherit_requested():
    # bind onto n2, remove it, add n3 (reuses the row): n3 must start clean
    sched, _ = mk_scheduler([node("n1", cpu=1_000), node("n2")])
    sched.enqueue(pod("p1", cpu=15_000))
    res = sched.schedule_round()
    assert res.assignments["p1"] == "n2"
    sched.snapshot.remove_node("n2")
    sched.snapshot.upsert_node(node("n3"))
    sched.enqueue(pod("p2", cpu=15_000))  # only fits a clean 16k node
    res2 = sched.schedule_round()
    assert res2.assignments.get("p2") == "n3"


def test_unknown_quota_name_does_not_crash_bind():
    tree = QuotaTree(resource_vector(cpu=32_000, memory=131_072).astype(np.int64))
    mx = np.full(R, UNBOUNDED, np.int64)
    mx[CPU] = 32_000
    tree.add("real", min=np.zeros(R, np.int64), max=mx)
    sched, binds = mk_scheduler([node("n1")], quota_tree=tree)
    sched.enqueue(pod("p1", quota="typo-not-a-quota"))
    res = sched.schedule_round()
    assert "p1" in res.assignments  # quota_id -1: schedules unconstrained
    assert binds


def test_monitor_collects_phase_stats():
    sched, _ = mk_scheduler([node("n1")])
    sched.enqueue(pod("p1"))
    sched.schedule_round()
    stats = sched.monitor.stats()
    for phase in ("PreEnqueue", "BatchBuild", "Solve", "Bind"):
        assert phase in stats
        assert stats[phase]["count"] >= 1


def test_diagnosis_message_shape():
    sched, _ = mk_scheduler([node("n1", cpu=1_000)])
    sched.enqueue(pod("big", cpu=50_000))
    res = sched.schedule_round()
    d = res.failures["big"]
    assert d.total_nodes == 1
    assert d.insufficient_resources == 1
    assert "1 insufficient resources" in d.message()


def test_topology_gang_gathers_in_one_block():
    from koordinator_tpu.ops.network_topology import (
        TopologyRequirements,
        TopologyTree,
    )

    # 2 blocks x 2 nodes; rows in the snapshot match tree add order
    tree = TopologyTree(["block", "node"])
    nodes = []
    for i in range(4):
        name = f"n{i}"
        tree.add_node([f"b{i // 2}", name])
        nodes.append(node(name, cpu=8_000))
    sched, _ = mk_scheduler(nodes, topology_tree=tree.build(capacity=16))
    # 2 pods of 8000 must gather at the block layer (one per node of a block)
    sched.register_gang(GangRecord(
        name="g", min_member=2,
        topology=TopologyRequirements(desired_slots=2, must_gather_layer=1),
    ))
    for i in range(2):
        sched.enqueue(pod(f"g{i}", cpu=8_000, gang="g"))
    res = sched.schedule_round()
    assert len(res.assignments) == 2
    placed = sorted(res.assignments.values())
    assert placed in (["n0", "n1"], ["n2", "n3"])  # same block


def test_topology_gang_infeasible_backs_off():
    from koordinator_tpu.ops.network_topology import (
        TopologyRequirements,
        TopologyTree,
    )

    tree = TopologyTree(["block", "node"])
    nodes = []
    for i in range(4):
        tree.add_node([f"b{i // 2}", f"n{i}"])
        nodes.append(node(f"n{i}", cpu=8_000))
    sched, _ = mk_scheduler(nodes, topology_tree=tree.build(capacity=16))
    # 3 full-node pods cannot gather within any 2-node block
    sched.register_gang(GangRecord(
        name="g", min_member=3,
        topology=TopologyRequirements(desired_slots=3, must_gather_layer=1),
    ))
    for i in range(3):
        sched.enqueue(pod(f"g{i}", cpu=8_000, gang="g"))
    res = sched.schedule_round()
    assert not res.assignments


def test_topology_gang_surplus_members_not_invalidated():
    from koordinator_tpu.ops.network_topology import (
        TopologyRequirements,
        TopologyTree,
    )

    tree = TopologyTree(["block", "node"])
    nodes = []
    for i in range(4):
        tree.add_node([f"b{i // 2}", f"n{i}"])
        nodes.append(node(f"n{i}", cpu=8_000))
    sched, _ = mk_scheduler(nodes, topology_tree=tree.build(capacity=16))
    # 3 members pending, plan covers desired_slots=2 -> the third member
    # schedules freely instead of killing the gang
    sched.register_gang(GangRecord(
        name="g", min_member=2,
        topology=TopologyRequirements(desired_slots=2, must_gather_layer=1),
    ))
    for i in range(3):
        sched.enqueue(pod(f"g{i}", cpu=4_000, gang="g"))
    res = sched.schedule_round()
    assert len(res.assignments) == 3


# ---- hot-path caching (VERDICT weak #5: no per-round host rework) ----------

def test_quota_runtime_cached_between_unchanged_rounds():
    t = QuotaTree(total_resource=resource_vector(cpu=10_000).astype(np.int64))
    t.add("a", min=resource_vector(cpu=2_000).astype(np.int64),
          max=resource_vector(cpu=8_000).astype(np.int64))
    t.set_request("a", resource_vector(cpu=4_000).astype(np.int64))
    assert t.refresh_runtime() is True
    n = t.runtime_refreshes
    assert t.refresh_runtime() is False          # nothing changed: skipped
    assert t.runtime_refreshes == n
    t.set_request("a", resource_vector(cpu=5_000).astype(np.int64))
    assert t.refresh_runtime() is True           # request moved: recompute
    assert t.refresh_runtime(force=True) is True # force always recomputes


def test_batch_reused_across_unchanged_rounds():
    sched, _ = mk_scheduler([node("n1")])
    sched.enqueue(pod("big", cpu=99_000))        # never schedulable
    sched.schedule_round()
    assert sched.batch_rebuilds == 1
    sched.schedule_round()                       # same pending queue
    assert sched.batch_rebuilds == 1             # cache hit
    sched.enqueue(pod("tiny", cpu=100))
    res = sched.schedule_round()                 # queue changed: rebuild
    assert sched.batch_rebuilds == 2
    assert res.assignments == {"tiny": "n1"}
    sched.schedule_round()                       # tiny bound: queue changed
    assert sched.batch_rebuilds == 3


def test_batch_cache_invalidated_by_node_change():
    sched, _ = mk_scheduler([node("n1", cpu=1_000)])
    sched.enqueue(pod("p", cpu=4_000))
    res = sched.schedule_round()
    assert "p" in res.failures
    # capacity arrives: same pending queue, but snapshot grew a class/row
    for i in range(20):                          # force capacity growth
        sched.snapshot.upsert_node(node(f"x{i}", cpu=16_000))
    res = sched.schedule_round()
    assert "p" in res.assignments


def test_batch_cache_invalidated_by_new_class_within_bucket():
    # a new label equivalence class must invalidate even when neither the
    # row capacity nor the class padding bucket grows
    sched, _ = mk_scheduler([node("n1")])
    sched.enqueue(PodSpec(name="gpu-pod",
                          requests=resource_vector(cpu=1_000, memory=1_024),
                          node_selector={"gpu": "true"}))
    res = sched.schedule_round()
    assert "gpu-pod" in res.failures
    sched.snapshot.upsert_node(node("g1", labels={"gpu": "true"}))
    res = sched.schedule_round()
    assert res.assignments == {"gpu-pod": "g1"}


def test_scheduler_switches_to_batch_solver_at_scale():
    # below the threshold: exact greedy; at/above: the data-parallel engine.
    # last_solver records which engine actually ran.
    sched, binds = mk_scheduler(
        [node(f"n{i}", cpu=64_000) for i in range(8)],
        batch_solver_threshold=4)
    for i in range(3):
        sched.enqueue(pod(f"small-{i}", cpu=1_000))
    res = sched.schedule_round()           # 3 pods < 4: greedy
    assert sched.last_solver == "greedy"
    assert len(res.assignments) == 3
    for i in range(6):
        sched.enqueue(pod(f"big-{i}", cpu=1_000))
    res = sched.schedule_round()           # 6 pods >= 4: batch engine
    assert sched.last_solver == "batch"
    assert len(res.assignments) == 6
    assert len(binds) == 9


def test_batch_solver_failures_get_exact_rescue():
    # a genuinely unschedulable pod must fail with REAL diagnosis even
    # through the batch engine (the rescue pass re-solves leftovers
    # exactly, so approximation failures never masquerade as capacity
    # failures); schedulable leftovers get placed by the rescue
    sched, _ = mk_scheduler(
        [node("n1", cpu=4_000)], batch_solver_threshold=2)
    sched.enqueue(pod("fits", cpu=1_000))
    sched.enqueue(pod("too-big", cpu=50_000))
    res = sched.schedule_round()
    assert sched.last_solver == "batch"
    assert res.assignments == {"fits": "n1"}
    assert "too-big" in res.failures
    assert res.failures["too-big"].insufficient_resources == 1


def test_batch_engine_with_gangs_and_quota_contention():
    # the full stack through the batch engine: gang all-or-nothing + quota
    # caps + rescue, at a queue size over the threshold
    mx = np.full(R, UNBOUNDED, np.int64)
    mx[CPU] = 8_000
    tree = QuotaTree(resource_vector(cpu=64_000, memory=262_144).astype(np.int64))
    tree.add("team", min=np.zeros(R, np.int64), max=mx)
    sched, _ = mk_scheduler(
        [node(f"n{i}", cpu=16_000) for i in range(4)],
        quota_tree=tree, batch_solver_threshold=4)
    sched.register_gang(GangRecord(name="g", min_member=3))
    for i in range(3):
        sched.enqueue(pod(f"g{i}", cpu=4_000, gang="g"))       # gang fits
    for i in range(4):
        sched.enqueue(pod(f"q{i}", cpu=3_000, quota="team"))   # cap 8000: 2 fit
    res = sched.schedule_round()
    assert sched.last_solver == "batch"
    assert all(f"g{i}" in res.assignments for i in range(3))
    placed_q = [f"q{i}" for i in range(4) if f"q{i}" in res.assignments]
    assert len(placed_q) == 2              # quota admits floor(8000/3000)
    failed_q = [f"q{i}" for i in range(4) if f"q{i}" in res.failures]
    assert len(failed_q) == 2
    for name in failed_q:
        assert res.failures[name].quota_rejected   # real reason, not approx


def test_rescue_places_surplus_members_of_satisfied_gang():
    # 5 members, min_member=3: even if the batch engine strands surplus
    # members, the rescue must bind them individually (min is already met)
    sched, _ = mk_scheduler(
        [node(f"n{i}", cpu=16_000) for i in range(8)],
        batch_solver_threshold=2)
    sched.register_gang(GangRecord(name="g", min_member=3))
    for i in range(5):
        sched.enqueue(pod(f"g{i}", cpu=2_000, gang="g"))
    res = sched.schedule_round()
    assert sched.last_solver == "batch"
    assert len(res.assignments) == 5 and not res.failures


class TestReservationRounds:
    """Reservation lifecycle through the round loop (plugins/reservation:
    reserve-pod placement, owner allocation, expiration)."""

    def _spec(self, name="rsv-a", cpu=8_000, node=None, ttl=None,
              labels=None, allocate_once=False):
        from koordinator_tpu.scheduler.reservations import (
            OwnerMatcher, ReservationSpec,
        )

        return ReservationSpec(
            name=name, requests=resource_vector(cpu=cpu, memory=8_192),
            owners=[OwnerMatcher(labels=labels or {"app": "web"})],
            node=node, ttl_sec=ttl, allocate_once=allocate_once,
        )

    def test_reserve_pod_places_and_hides_capacity(self):
        sched, _ = mk_scheduler([node("n1", cpu=10_000)])
        sched.add_reservation(self._spec(cpu=8_000))
        res = sched.schedule_round()
        assert res.assignments.get("rsv::rsv-a") == "n1"
        avail = sched.reservations.available()
        assert [s.name for s in avail] == ["rsv-a"]
        # the reserved capacity is invisible to non-owner pods
        sched.enqueue(pod("other", cpu=4_000))
        res = sched.schedule_round()
        assert "other" in res.failures

    def test_owner_pod_allocates_from_reservation(self):
        sched, binds = mk_scheduler([node("n1", cpu=10_000),
                                     node("n2", cpu=10_000)])
        sched.add_reservation(self._spec(cpu=8_000))
        sched.schedule_round()
        rnode = sched.reservations.get("rsv-a").node
        owner = pod("web-1", cpu=6_000, labels={"app": "web"})
        sched.enqueue(owner)
        res = sched.schedule_round()
        # owner lands on the reserved node and charges the reservation
        assert res.assignments["web-1"] == rnode
        spec = sched.reservations.get("rsv-a")
        assert spec.allocated[CPU] == 6_000
        assert spec.owner_pods == ["web-1"]
        # non-owner still can't use the remaining reserved 2k on that node

    def test_pinned_reservation_available_without_solve(self):
        sched, _ = mk_scheduler([node("n1", cpu=10_000)])
        sched.add_reservation(self._spec(node="n1", cpu=8_000))
        sched.enqueue(pod("other", cpu=4_000))
        res = sched.schedule_round()
        assert "other" in res.failures      # capacity charged by pin
        assert sched.reservations.get("rsv-a").node == "n1"

    def test_allocate_once_consumes_reservation(self):
        sched, _ = mk_scheduler([node("n1", cpu=10_000)])
        sched.add_reservation(self._spec(cpu=8_000, allocate_once=True))
        sched.schedule_round()
        sched.enqueue(pod("web-1", cpu=2_000, labels={"app": "web"}))
        res = sched.schedule_round()
        from koordinator_tpu.scheduler.reservations import ReservationPhase

        assert res.assignments["web-1"] == "n1"
        spec = sched.reservations.get("rsv-a")
        assert spec.phase is ReservationPhase.SUCCEEDED
        # consumed: next owner pod schedules on free capacity only
        assert not sched.reservations.available()

    def test_expiration_returns_remainder(self):
        t = [0.0]
        sched, _ = mk_scheduler([node("n1", cpu=10_000)])
        sched.clock = lambda: t[0]
        sched.add_reservation(self._spec(cpu=8_000, ttl=60.0))
        sched.schedule_round()
        assert sched.reservations.available()
        t[0] = 120.0
        sched.enqueue(pod("other", cpu=6_000))
        res = sched.schedule_round()
        # expired: remainder returned, non-owner fits again
        assert res.assignments.get("other") == "n1"

    def test_remove_reservation_frees_capacity(self):
        sched, _ = mk_scheduler([node("n1", cpu=10_000)])
        sched.add_reservation(self._spec(cpu=8_000))
        sched.schedule_round()
        sched.remove_reservation("rsv-a")
        sched.enqueue(pod("other", cpu=6_000))
        res = sched.schedule_round()
        assert res.assignments.get("other") == "n1"

    def test_owner_pod_delete_returns_allocation_not_node_capacity(self):
        # regression: freeing an owner pod must return its drawn vector to
        # the reservation remainder, NOT uncover reserved capacity
        sched, _ = mk_scheduler([node("n1", cpu=10_000)])
        sched.add_reservation(self._spec(cpu=8_000))
        sched.schedule_round()
        sched.enqueue(pod("web-1", cpu=6_000, labels={"app": "web"}))
        sched.schedule_round()
        sched.delete_pod("web-1")
        spec = sched.reservations.get("rsv-a")
        assert spec.allocated[CPU] == 0          # drawn part returned
        # reserved capacity still hidden from non-owners
        sched.enqueue(pod("other", cpu=4_000))
        res = sched.schedule_round()
        assert "other" in res.failures
        # ...but a new owner can draw the full 8k again
        sched.enqueue(pod("web-2", cpu=8_000, labels={"app": "web"}))
        res = sched.schedule_round()
        assert res.assignments.get("web-2") == "n1"

    def test_reapply_available_reservation_is_idempotent(self):
        # regression: upsert over an Available reservation must not
        # double-charge the node via a second reserve-pod
        sched, _ = mk_scheduler([node("n1", cpu=10_000)])
        sched.add_reservation(self._spec(cpu=6_000))
        sched.schedule_round()
        sched.add_reservation(self._spec(cpu=6_000))   # controller resync
        sched.schedule_round()
        avail = sched.reservations.available()
        assert len(avail) == 1 and avail[0].node == "n1"
        # 4k remains genuinely free: exactly one 6k charge on the node
        sched.enqueue(pod("other", cpu=4_000))
        res = sched.schedule_round()
        assert res.assignments.get("other") == "n1"

    def test_pending_reservation_expires_by_ttl(self):
        t = [0.0]
        sched, _ = mk_scheduler([node("n1", cpu=2_000)])
        sched.clock = lambda: t[0]
        sched.add_reservation(self._spec(cpu=50_000, ttl=60.0))  # never fits
        sched.schedule_round()
        t[0] = 120.0
        sched.schedule_round()
        # expired AND purged by the terminal-phase gc
        assert sched.reservations.get("rsv-a") is None
        assert "rsv::rsv-a" not in sched.pending

    def test_pinned_reservation_waits_for_fit(self):
        # a pinned reservation larger than the node's free capacity must
        # stay Pending instead of over-committing the node
        sched, _ = mk_scheduler([node("n1", cpu=2_000)])
        sched.add_reservation(self._spec(node="n1", cpu=8_000))
        sched.enqueue(pod("other", cpu=1_000))
        res = sched.schedule_round()
        assert res.assignments.get("other") == "n1"  # node NOT blocked
        assert not sched.reservations.available()

    def test_allocate_once_frees_fully_with_owner_pod(self):
        # allocate-once consumed by a 2k pod holds the full 8k; the whole
        # charge must free when that pod dies
        sched, _ = mk_scheduler([node("n1", cpu=10_000)])
        sched.add_reservation(self._spec(cpu=8_000, allocate_once=True))
        sched.schedule_round()
        sched.enqueue(pod("web-1", cpu=2_000, labels={"app": "web"}))
        sched.schedule_round()
        sched.delete_pod("web-1")
        sched.enqueue(pod("other", cpu=9_000))
        res = sched.schedule_round()
        assert res.assignments.get("other") == "n1"


class TestMigrationWithReservations:
    _spec = TestReservationRounds._spec

    def test_reservation_first_migration_end_to_end(self):
        """SURVEY 3.4 flow against real scheduler reservations: the
        migration controller secures replacement capacity on another node
        BEFORE evicting, and the replacement pod lands on it."""
        from koordinator_tpu.descheduler.migration import (
            MigrationController, MigrationJob,
        )
        from koordinator_tpu.descheduler.plugins import (
            scheduler_migration_evict_fn, scheduler_reserve_fn,
        )

        # the pod binds while only the (soon-to-be-)hot node exists; the
        # cool node joins afterwards — the classic rebalance setup
        sched, _ = mk_scheduler([node("hot", cpu=10_000, usage_cpu=9_000)])
        sched.enqueue(pod("web-1", cpu=4_000, labels={"app": "web"}))
        res = sched.schedule_round()
        src = res.assignments["web-1"]
        assert src == "hot"
        sched.snapshot.upsert_node(node("cool", cpu=10_000))

        ctl = MigrationController(
            reserve_fn=scheduler_reserve_fn(sched),
            evict_fn=scheduler_migration_evict_fn(sched),
        )
        ctl.submit(MigrationJob(name="j1", pod="web-1", node=src))
        ctl.reconcile()   # arbitrate: reserve on the other node
        job = ctl.jobs["j1"]
        assert job.reservation == "migrate-j1"
        spec = sched.reservations.get("migrate-j1")
        assert spec.node is not None and spec.node != src
        ctl.reconcile()   # running: evict
        assert "web-1" not in sched.bound

        # the replacement pod allocates from the secured reservation
        sched.enqueue(pod("web-1", cpu=4_000, labels={"app": "web"}))
        res = sched.schedule_round()
        assert res.assignments["web-1"] == spec.node
        assert sched.reservations.get("migrate-j1").allocated[CPU] == 4_000

    def test_recreated_reservation_not_credited_by_old_pods(self):
        # generation check: a pod bound through a deleted reservation must
        # not corrupt a later same-named instance's accounting
        sched, _ = mk_scheduler([node("n1", cpu=20_000)])
        sched.add_reservation(self._spec(cpu=8_000))
        sched.schedule_round()
        sched.enqueue(pod("web-1", cpu=4_000, labels={"app": "web"}))
        sched.schedule_round()
        sched.remove_reservation("rsv-a")           # old instance gone
        sched.add_reservation(self._spec(cpu=6_000))  # new instance
        sched.schedule_round()
        new_spec = sched.reservations.get("rsv-a")
        assert new_spec.allocated[CPU] == 0
        sched.delete_pod("web-1")                   # old-instance owner dies
        # the NEW instance's remainder is untouched
        assert sched.reservations.get("rsv-a").allocated[CPU] == 0
        # node accounting consistent: 6k (new rsv) charged, rest free
        sched.enqueue(pod("other", cpu=14_000))
        res = sched.schedule_round()
        assert res.assignments.get("other") == "n1"

    def test_pending_update_refreshes_reserve_pod_requests(self):
        # updating a still-Pending reservation must re-enqueue the reserve
        # pod with the NEW vector, not open a 4k claim backed by a 1k charge
        sched, _ = mk_scheduler([node("n1", cpu=10_000)])
        sched.add_reservation(self._spec(cpu=1_000))
        # don't run a round yet: the reserve-pod sits queued at 1k
        sched.add_reservation(self._spec(cpu=4_000))
        sched.schedule_round()
        spec = sched.reservations.get("rsv-a")
        assert spec.node == "n1"
        # exactly 4k charged: a 7k pod must NOT fit (10k - 4k = 6k free)
        sched.enqueue(pod("big", cpu=7_000))
        res = sched.schedule_round()
        assert "big" in res.failures
        sched.enqueue(pod("ok", cpu=6_000))
        res = sched.schedule_round()
        assert res.assignments.get("ok") == "n1"

    def test_debug_service_reservations_route(self):
        from koordinator_tpu.scheduler.services import DebugService

        sched, _ = mk_scheduler([node("n1", cpu=10_000)])
        svc = DebugService(sched)
        sched.add_reservation(self._spec(cpu=6_000))
        sched.schedule_round()
        status, body = svc.handle("/apis/v1/reservations")
        assert status == 200
        assert body[0]["name"] == "rsv-a"
        assert body[0]["phase"] == "Available"
        assert body[0]["node"] == "n1"

    def test_owner_update_reaches_prepass_cache(self):
        from koordinator_tpu.scheduler.reservations import OwnerMatcher

        sched, _ = mk_scheduler([node("n1", cpu=10_000)])
        sched.add_reservation(self._spec(cpu=8_000, labels={"app": "web"}))
        sched.schedule_round()
        # a db pod isn't an owner: reserved capacity hidden
        sched.enqueue(pod("db-1", cpu=6_000, labels={"app": "db"}))
        res = sched.schedule_round()
        assert "db-1" in res.failures
        # owners widened in place (same requests): db now matches
        spec = self._spec(cpu=8_000)
        spec.owners = [OwnerMatcher(labels={"app": "db"})]
        sched.add_reservation(spec)
        res = sched.schedule_round()
        assert res.assignments.get("db-1") == "n1"
        assert sched.reservations.get("rsv-a").allocated[CPU] == 6_000

    def test_reserve_pod_honors_template_node_selector(self):
        sched, _ = mk_scheduler([
            node("cpu-1", cpu=20_000, labels={"pool": "cpu"}),
            node("gpu-1", cpu=10_000, labels={"pool": "gpu"}),
        ])
        spec = self._spec(cpu=8_000)
        spec.node_selector = {"pool": "gpu"}
        sched.add_reservation(spec)
        sched.schedule_round()
        assert sched.reservations.get("rsv-a").node == "gpu-1"


class TestFineGrainedBind:
    """CPU/device manager integration at bind (nodenumaresource Reserve
    resource_manager.go:357 + deviceshare PreBind device-allocated)."""

    def _managers(self):
        from tests.test_deviceshare import gpu_node
        from tests.test_numa import topo_2numa

        from koordinator_tpu.scheduler.cpu_manager import CPUManager
        from koordinator_tpu.scheduler.device_manager import DeviceManager

        cm = CPUManager()
        cm.register_node("n1", topo_2numa())
        dm = DeviceManager()
        dm.register("gpu", ["n1"], [gpu_node(4)])
        return cm, dm

    def test_lsr_pod_gets_exclusive_cpuset_at_bind(self):
        from koordinator_tpu.api.qos import QoSClass

        cm, dm = self._managers()
        sched, _ = mk_scheduler([node("n1")], cpu_manager=cm,
                                device_manager=dm)
        sched.enqueue(pod("lsr-1", cpu=4_000, qos=int(QoSClass.LSR)))
        sched.enqueue(pod("ls-1", cpu=4_000, qos=int(QoSClass.LS)))
        res = sched.schedule_round()
        assert set(res.assignments) == {"lsr-1", "ls-1"}
        status = sched.resource_status["lsr-1"]["resource-status"]
        assert len(status["cpuset"].split(",")) == 4
        assert "ls-1" not in sched.resource_status   # shared-pool pod
        # release on delete
        sched.delete_pod("lsr-1")
        assert "lsr-1" not in sched.resource_status
        assert cm.node("n1").ref_count.sum() == 0

    def test_gpu_pod_gets_device_allocation_at_bind(self):
        from koordinator_tpu.api.resources import resource_vector

        cm, dm = self._managers()
        from koordinator_tpu.scheduler.snapshot import NodeSpec, PodSpec

        gpu_node_spec = NodeSpec(name="n1", allocatable=resource_vector(
            {"cpu": 16_000, "memory": 65_536, "kubernetes.io/gpu": 400,
             "kubernetes.io/gpu-memory": 81_920 * 4}))
        sched, _ = mk_scheduler([gpu_node_spec], cpu_manager=cm,
                                device_manager=dm)
        sched.enqueue(PodSpec(name="gpu-1", requests=resource_vector(
            {"cpu": 1_000, "memory": 1_024, "kubernetes.io/gpu": 200,
             "kubernetes.io/gpu-memory": 16_384})))
        res = sched.schedule_round()
        assert res.assignments["gpu-1"] == "n1"
        ann = sched.resource_status["gpu-1"]["device-allocated"]
        assert len(ann["gpu"]) == 2    # 200 milli-gpu = 2 whole devices
        sched.delete_pod("gpu-1")
        assert dm.allocate("gpu", "n1", "x", core=400) is not None

    def test_debug_route_exposes_resource_status(self):
        from koordinator_tpu.api.qos import QoSClass
        from koordinator_tpu.scheduler.services import DebugService

        cm, dm = self._managers()
        sched, _ = mk_scheduler([node("n1")], cpu_manager=cm,
                                device_manager=dm)
        svc = DebugService(sched)
        sched.enqueue(pod("lsr-1", cpu=2_000, qos=int(QoSClass.LSR)))
        sched.schedule_round()
        status, body = svc.handle("/apis/v1/resource-status")
        assert status == 200 and "lsr-1" in body

    def test_preemption_releases_victim_fine_grained_allocs(self):
        from koordinator_tpu.api.qos import QoSClass

        cm, dm = self._managers()
        sched, _ = mk_scheduler(
            [node("n1", cpu=8_000)], cpu_manager=cm, device_manager=dm,
            enable_preemption=True, preempt_fn=lambda pod, node: True)
        sched.enqueue(pod("lsr-low", cpu=6_000, qos=int(QoSClass.LSR),
                          priority=3_000))
        sched.schedule_round()
        assert cm.node("n1").ref_count.sum() == 6
        sched.enqueue(pod("prod-high", cpu=6_000, priority=9_500))
        sched.schedule_round()   # PostFilter: evict lsr-low, nominate
        assert "lsr-low" not in sched.bound
        # victim's exclusive cpuset released with the eviction
        assert cm.node("n1").ref_count.sum() == 0
        assert "lsr-low" not in sched.resource_status

    def test_restart_replay_restores_pinned_cpus_and_minors(self):
        from koordinator_tpu.scheduler.scheduler import BoundPod

        cm, dm = self._managers()
        sched, _ = mk_scheduler([node("n1")], cpu_manager=cm,
                                device_manager=dm)
        # informer replay: an LSR pod pinned to cpus 0-3 and a GPU pod
        # holding minors 0-1 were running before the restart
        sched.add_bound_pod(
            BoundPod(name="old-lsr", node="n1",
                     requests=resource_vector(cpu=4_000, memory=1_024),
                     priority=9_000),
            resource_status={"resource-status": {"cpuset": "0,1,2,3"}})
        sched.add_bound_pod(
            BoundPod(name="old-gpu", node="n1",
                     requests=resource_vector(cpu=1_000, memory=1_024),
                     priority=9_000),
            resource_status={"device-allocated": {"gpu": [
                {"minor": 0, "resources": {"core": 100, "memory": 81_920}},
                {"minor": 1, "resources": {"core": 100, "memory": 81_920}},
            ]}})
        assert cm.node("n1").ref_count[:4].sum() == 4
        # a new exclusive allocation avoids the replayed cores
        cpus = cm.allocate("n1", "new-lsr", 4)
        assert cpus is not None and not set(cpus) & {0, 1, 2, 3}
        # a 3-whole GPU ask fails while minors 0-1 are replayed as held
        assert dm.allocate("gpu", "n1", "new-gpu", core=300) is None
        sched.remove_bound_pod("old-gpu")
        assert dm.allocate("gpu", "n1", "new-gpu", core=300) is not None

    def test_koordlet_nrt_annotation_registers_topology(self):
        """koordlet NodeTopologyReporter annotations -> scheduler CPUManager
        (the NRT CRD loop: nodetopo report to topology_options consume)."""
        from koordinator_tpu.api.qos import QoSClass
        from koordinator_tpu.koordlet.nodetopo import NodeTopology, NUMAZone
        from koordinator_tpu.koordlet.system import procfs
        from koordinator_tpu.scheduler.cpu_manager import (
            CPUManager, register_node_from_annotations,
        )

        cpus = tuple(
            procfs.CPUInfo(cpu=i, core=i // 2, socket=0, node=i // 4)
            for i in range(8))
        topo = NodeTopology(
            zones=(NUMAZone("node0", 4_000, 1 << 30, (0, 1, 2, 3)),
                   NUMAZone("node1", 4_000, 1 << 30, (4, 5, 6, 7))),
            cpu_topology=cpus)
        cm = CPUManager()
        assert register_node_from_annotations(
            cm, "n1", topo.to_annotations())
        sched, _ = mk_scheduler([node("n1")], cpu_manager=cm)
        sched.enqueue(pod("lsr-1", cpu=2_000, qos=int(QoSClass.LSR)))
        sched.schedule_round()
        status = sched.resource_status["lsr-1"]["resource-status"]
        assert len(status["cpuset"].split(",")) == 2
        assert not register_node_from_annotations(cm, "nx", {})

    def test_restore_rejects_malformed_and_stale_annotations(self):
        from koordinator_tpu.scheduler.scheduler import BoundPod

        cm, dm = self._managers()
        sched, _ = mk_scheduler([node("n1")], cpu_manager=cm,
                                device_manager=dm)
        # range-form cpuset parses; stale cpu ids / bad minors are skipped
        sched.add_bound_pod(
            BoundPod(name="ranged", node="n1",
                     requests=resource_vector(cpu=2_000, memory=512)),
            resource_status={"resource-status": {"cpuset": "0-1"}})
        assert cm.node("n1").ref_count[:2].sum() == 2
        sched.add_bound_pod(
            BoundPod(name="stale", node="n1",
                     requests=resource_vector(cpu=2_000, memory=512)),
            resource_status={
                "resource-status": {"cpuset": "500-501"},       # beyond topo
                "device-allocated": {"gpu": [{"minor": 99}],    # beyond devs
                                     "fpga": [{"minor": 0}]}})  # unknown type
        assert "stale" not in sched.resource_status
        # replaying the same GPU pod twice must not double-charge
        grant = {"device-allocated": {"gpu": [
            {"minor": 0, "resources": {"core": 100, "memory": 81_920}}]}}
        for _ in range(2):
            sched.add_bound_pod(
                BoundPod(name="gpu-replay", node="n1",
                         requests=resource_vector(cpu=1_000, memory=512)),
                resource_status=grant)
        sched.remove_bound_pod("gpu-replay")
        assert dm.allocate("gpu", "n1", "x", core=400) is not None

    def test_node_resync_preserves_exclusive_cpuset(self):
        # heartbeat re-registration of the same topology must not wipe
        # live allocations (double-grant of exclusive cores)
        from koordinator_tpu.ops.numa import CPUTopology

        import numpy as _np

        cm, dm = self._managers()
        topo = cm.node("n1").topology
        cpus = cm.allocate("n1", "lsr-a", 4)
        assert cpus is not None
        cm.register_node("n1", topo)             # identical re-sync
        assert cm.node("n1").ref_count.sum() == 4
        # a changed topology carries valid allocations over
        cm.register_node("n1", CPUTopology.build(
            _np.asarray(topo.core_of), _np.asarray(topo.numa_of),
            _np.asarray(topo.socket_of)), max_ref=2)
        assert cm.node("n1").allocations["lsr-a"].cpus == cpus
        assert cm.node("n1").ref_count.sum() == 4

    def test_device_inventory_shrink_keeps_records_filters_views(self):
        """An inventory shrink must not destroy allocation records (a
        transient clear + heartbeat restore would otherwise free devices
        still held by bound pods); instead the VIEWS filter to live
        minors — annotations report only existing devices, release
        doesn't crash, and a restored inventory re-commits the grant."""
        from koordinator_tpu.scheduler.device_manager import DeviceManager

        dm = DeviceManager()
        full = [{"core": 100, "memory": 0, "group": 0} for _ in range(5)]
        dm.register_node_devices("gpu", "n0", full)
        assert dm.allocate("gpu", "n0", "p", core=500) is not None
        dm.register_node_devices("gpu", "n0", full[:2])
        # the RECORD keeps all five minors; the annotation view filters
        allocs = dm._allocs[("p", "n0")]
        assert sorted(m for a in allocs for m in a.minors) == [0, 1, 2, 3, 4]
        ann = dm.device_allocated_annotation("n0", "p")
        assert sorted(g["minor"] for g in ann["gpu"]) == [0, 1]
        # inventory returns: the held minors re-commit, so a new pod
        # cannot be granted devices p still uses
        dm.register_node_devices("gpu", "n0", full)
        state = dm.state("gpu")
        # every device's core capacity is committed again — a new pod
        # cannot be granted what p holds
        assert int(np.asarray(state.free)[..., 0].sum()) == 0
        # release frees only live minors and doesn't crash
        dm.release("n0", "p")
        assert dm.allocate("gpu", "n0", "q", core=200) is not None


def test_overuse_revoke_in_round_loop():
    """quota_overuse_revoke.go through the rounds: runtime shrinks after
    admission, the over-used quota's least-important pod is revoked past
    the delay, and the freed headroom admits the other quota's pod."""
    t = [0.0]
    total = resource_vector(cpu=16_000, memory=131_072).astype(np.int64)
    tree = QuotaTree(total)
    mx = np.full(R, UNBOUNDED, np.int64)
    mx[CPU] = 16_000
    for q in ("a", "b"):
        tree.add(q, min=np.zeros(R, np.int64), max=mx)
    sched, _ = mk_scheduler([node("n1", cpu=16_000)], quota_tree=tree,
                            clock=lambda: t[0])
    revoked = []
    sched.enable_overuse_revoke(
        revoke_fn=lambda p, q: revoked.append((p, q)), delay_evict_sec=5.0)

    # quota a takes nearly everything while b is idle
    sched.enqueue(pod("a-low", cpu=10_000, quota="a", priority=3_000))
    sched.enqueue(pod("a-high", cpu=4_000, quota="a", priority=9_000))
    res = sched.schedule_round()
    assert {"a-low", "a-high"} <= set(res.assignments)

    # b starts demanding: its pod can't fit (2k node free), stays pending,
    # and its request shrinks a's runtime share below a's used
    sched.enqueue(pod("b-1", cpu=8_000, quota="b", priority=9_000))
    res = sched.schedule_round()    # monitor arms (fresh runtime computed)
    assert "b-1" in res.failures
    assert np.any(tree.nodes["a"].used > tree.nodes["a"].runtime)

    t[0] = 10.0                     # past delay_evict_sec
    res = sched.schedule_round()
    # least-important overshoot pod revoked; b's pod admitted
    assert ("a-low", "a") in revoked
    assert "a-low" not in sched.bound
    assert res.assignments.get("b-1") == "n1"
    assert "a-high" in sched.bound  # the important pod survives


def test_overuse_revoke_honors_pdb_budget():
    from koordinator_tpu.scheduler.scheduler import PdbRecord

    t = [0.0]
    total = resource_vector(cpu=16_000, memory=131_072).astype(np.int64)
    tree = QuotaTree(total)
    mx = np.full(R, UNBOUNDED, np.int64)
    mx[CPU] = 16_000
    for q in ("a", "b"):
        tree.add(q, min=np.zeros(R, np.int64), max=mx)
    sched, _ = mk_scheduler([node("n1", cpu=16_000)], quota_tree=tree,
                            clock=lambda: t[0])
    revoked = []
    sched.enable_overuse_revoke(
        revoke_fn=lambda p, q: revoked.append(p), delay_evict_sec=5.0)
    sched.register_pdb(PdbRecord(name="protect-a",
                                 selector={"app": "a"}, allowed=0))
    sched.enqueue(pod("a-low", cpu=14_000, quota="a", priority=3_000,
                      labels={"app": "a"}))
    sched.schedule_round()
    sched.enqueue(pod("b-1", cpu=8_000, quota="b", priority=9_000))
    sched.schedule_round()
    t[0] = 10.0
    sched.schedule_round()
    # PDB exhausted: the overshoot pod survives the revoke
    assert revoked == []
    assert "a-low" in sched.bound


def test_overuse_revoke_selects_around_pdb_protected_pod():
    """A PDB-protected lowest-priority pod must not permanently block
    revocation: the kernel selects the evictable alternative instead."""
    from koordinator_tpu.scheduler.scheduler import PdbRecord

    t = [0.0]
    total = resource_vector(cpu=16_000, memory=131_072).astype(np.int64)
    tree = QuotaTree(total)
    mx = np.full(R, UNBOUNDED, np.int64)
    mx[CPU] = 16_000
    for q in ("a", "b"):
        tree.add(q, min=np.zeros(R, np.int64), max=mx)
    sched, _ = mk_scheduler([node("n1", cpu=16_000)], quota_tree=tree,
                            clock=lambda: t[0])
    revoked = []
    sched.enable_overuse_revoke(
        revoke_fn=lambda p, q: revoked.append(p), delay_evict_sec=5.0)
    sched.register_pdb(PdbRecord(name="protect-low",
                                 selector={"tier": "low"}, allowed=0))
    sched.enqueue(pod("a-low", cpu=7_000, quota="a", priority=3_000,
                      labels={"tier": "low"}))
    sched.enqueue(pod("a-mid", cpu=7_000, quota="a", priority=6_000))
    sched.schedule_round()
    sched.enqueue(pod("b-1", cpu=8_000, quota="b", priority=9_000))
    sched.schedule_round()
    t[0] = 10.0
    res = sched.schedule_round()
    # the unprotected pod was chosen even though a-low is less important
    assert revoked == ["a-mid"]
    assert "a-low" in sched.bound
    assert res.assignments.get("b-1") == "n1"


def test_overuse_revoke_skips_uncurable_quota_with_blocked_pod():
    """When the overshoot is pinned by a PDB-blocked pod (eviction cannot
    cure the quota), no collateral eviction happens; the quota retries
    once budgets recover."""
    from koordinator_tpu.scheduler.scheduler import PdbRecord

    t = [0.0]
    total = resource_vector(cpu=16_000, memory=131_072).astype(np.int64)
    tree = QuotaTree(total)
    mx = np.full(R, UNBOUNDED, np.int64)
    mx[CPU] = 16_000
    for q in ("a", "b"):
        tree.add(q, min=np.zeros(R, np.int64), max=mx)
    sched, _ = mk_scheduler([node("n1", cpu=16_000)], quota_tree=tree,
                            clock=lambda: t[0])
    revoked = []
    sched.enable_overuse_revoke(
        revoke_fn=lambda p, q: revoked.append(p), delay_evict_sec=5.0)
    sched.register_pdb(PdbRecord(name="protect-big",
                                 selector={"tier": "big"}, allowed=0))
    # the protected pod ALONE overshoots whatever runtime a will get;
    # evicting the small pods cannot cure the quota
    sched.enqueue(pod("a-big", cpu=12_000, quota="a", priority=3_000,
                      labels={"tier": "big"}))
    sched.enqueue(pod("a-small", cpu=2_000, quota="a", priority=6_000))
    sched.schedule_round()
    sched.enqueue(pod("b-1", cpu=8_000, quota="b", priority=9_000))
    sched.schedule_round()
    t[0] = 10.0
    sched.schedule_round()
    assert revoked == []                  # no pointless collateral eviction
    assert {"a-big", "a-small"} <= set(sched.bound)


def test_node_flap_preserves_device_grants():
    """A node flap (NODE_REMOVE then re-upsert with the same inventory,
    e.g. a kubelet restart while pods keep running) must not free
    devices a bound pod still holds: records survive the removal and
    re-commit on the rebuild, so a second pod cannot be granted them."""
    from koordinator_tpu.scheduler.device_manager import DeviceManager

    dm = DeviceManager()
    inv = [{"core": 100, "memory": 0, "group": 0} for _ in range(2)]
    dm.register_node_devices("gpu", "n0", inv)
    assert dm.allocate("gpu", "n0", "p", core=200) == [0, 1]
    dm.remove_node("n0")
    assert dm.state("gpu") is None          # inventory rows gone
    dm.register_node_devices("gpu", "n0", inv)
    # held devices re-committed: the flap cannot double-grant
    assert dm.allocate("gpu", "n0", "q", core=200) is None
    ann = dm.device_allocated_annotation("n0", "p")
    assert sorted(g["minor"] for g in ann["gpu"]) == [0, 1]
    # pod release purges the record even while the node is absent
    dm.remove_node("n0")
    dm.release("n0", "p")
    dm.register_node_devices("gpu", "n0", inv)
    assert dm.allocate("gpu", "n0", "q", core=200) == [0, 1]
