"""Fast deterministic units for the drill substrate (ISSUE 17):

- fault domains / storm windows / seeded schedules replay EXACT storm
  membership and timing from one seed under a fake clock (no wall-clock
  reads anywhere in the schedule path);
- the warm-restart checkpoint save→restore roundtrip is bit-identical
  on the scheduler's host state and the snapshot arrays;
- the churn trace and the drill catalog are well-formed data.

The multi-second socket drills themselves live in test_drills_e2e.py
(``chaos`` + ``slow``); everything here is tier-1 fast.
"""

import os

import numpy as np
import pytest

from koordinator_tpu.drills import checkpoint as ckpt
from koordinator_tpu.drills.scenarios import (
    GANG_BURST,
    POD_ADD,
    POD_DEL,
    SCENARIOS,
    churn_trace,
)
from koordinator_tpu.transport.faults import (
    PARTITION,
    REFUSE,
    FaultConfig,
    FaultInjector,
    FaultSchedule,
    StormWindow,
    domains_from_labels,
)

# ---- fault domains and schedules -------------------------------------------


def test_domains_from_labels_groups_and_skips_unlabeled():
    doms = domains_from_labels({
        "n1": {"rack": "r1"}, "n0": {"rack": "r1"},
        "n2": {"rack": "r2"}, "n3": {}}, key="rack")
    assert doms == {"rack:r1": ["n0", "n1"], "rack:r2": ["n2"]}


def test_storm_window_validates_and_is_half_open():
    with pytest.raises(ValueError):
        StormWindow(0.0, 0.0, {"d"})        # empty window
    with pytest.raises(ValueError):
        StormWindow(0.0, 1.0, {"d"}, "bogus-mode")
    w = StormWindow(1.0, 2.0, {"d"})
    assert w.active_at(1.0) and w.active_at(1.999)
    assert not w.active_at(0.999) and not w.active_at(2.0)


def test_flap_train_boundaries_are_exact():
    wins = FaultSchedule.flap_train(("rack:r0",), start=0.0,
                                    up_s=0.5, down_s=0.5, flaps=3)
    sched = FaultSchedule(wins)
    assert sched.boundaries() == [0.0, 0.5, 1.0, 1.5, 2.0, 2.5]
    assert sched.horizon() == 2.5
    assert sched.blocked(0.25) == {"rack:r0": PARTITION}
    assert sched.blocked(0.5) == {}      # end exclusive: the down gap
    assert sched.blocked(2.0) == {"rack:r0": PARTITION}  # start inclusive
    assert sched.blocked(2.5) == {}


def test_overlapping_windows_keep_the_severest_mode():
    sched = FaultSchedule([
        StormWindow(0.0, 2.0, {"rack:r0"}, REFUSE),
        StormWindow(1.0, 3.0, {"rack:r0"}, PARTITION),
    ])
    assert sched.blocked(0.5) == {"rack:r0": REFUSE}
    assert sched.blocked(1.5) == {"rack:r0": PARTITION}
    assert sched.blocked(2.5) == {"rack:r0": PARTITION}


def test_generate_replays_exact_membership_and_timing_from_seed():
    doms = ["rack:r0", "rack:r1", "zone:z0"]
    kw = dict(horizon_s=30.0, storms=4, max_width=2,
              modes=(PARTITION, REFUSE))
    a = FaultSchedule.generate(5, doms, **kw)
    b = FaultSchedule.generate(5, doms, **kw)
    assert a.windows == b.windows
    assert a.windows, "seeded schedule never fired"
    for w in a.windows:
        assert 0.0 <= w.start < w.end <= 30.0
        assert w.domains <= set(doms)
        assert 1 <= len(w.domains) <= 2
    c = FaultSchedule.generate(6, doms, **kw)
    assert a.windows != c.windows


def test_injector_advances_through_exact_boundaries():
    """Fake-clock drive of the schedule seam: domain modes toggle at
    window boundaries and PARTITION starts sever live connections."""
    inj = FaultInjector(seed=3, config=FaultConfig())
    severed = []
    inj.register_conn("rack:r0", lambda: severed.append(1))
    inj.schedule = FaultSchedule(FaultSchedule.flap_train(
        ("rack:r0",), start=1.0, up_s=0.5, down_s=0.5, flaps=2))
    assert inj.domain_mode("rack:r0") is None
    inj.advance_to(1.0)
    assert inj.domain_mode("rack:r0") == PARTITION
    assert len(severed) == 1
    inj.advance_to(1.5)
    assert inj.domain_mode("rack:r0") is None
    inj.advance_to(2.0)
    assert inj.domain_mode("rack:r0") == PARTITION
    assert len(severed) == 2
    inj.advance_to(2.5)
    assert inj.domain_mode("rack:r0") is None
    assert inj.injected["storm_partition"] == 2
    inj.heal()
    assert inj.schedule is None


# ---- churn trace ------------------------------------------------------------


def test_churn_trace_replays_from_seed():
    a = churn_trace(7, 30.0, tenants=("t-a", "t-b"))
    b = churn_trace(7, 30.0, tenants=("t-a", "t-b"))
    assert a == b
    c = churn_trace(8, 30.0, tenants=("t-a", "t-b"))
    assert a != c
    assert all(x.t <= y.t for x, y in zip(a, a[1:]))
    adds = {e.name for e in a if e.kind == POD_ADD}
    dels = {e.name for e in a if e.kind == POD_DEL}
    assert dels <= adds, "every delete references an added pod"
    assert any(e.kind == GANG_BURST for e in a)


def test_scenario_catalog_is_well_formed():
    assert set(SCENARIOS) == {
        "leader_failover", "manager_restart", "rack_storm",
        "quota_reorg", "tenant_sever", "warm_restart"}
    for s in SCENARIOS.values():
        assert [p.name for p in s.phases] == [
            "warmup", "inject", "hold", "heal", "verify"]
        assert all(p.duration_s > 0 for p in s.phases)
        assert s.phase("inject").actions, s.name
        assert s.phase("hold").chaos, s.name
        assert s.replicas >= 1 and s.rto_budget_s > 0


# ---- warm-restart checkpoint -----------------------------------------------


def _plain_cfg():
    import jax.numpy as jnp

    from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS
    from koordinator_tpu.ops.assignment import ScoringConfig

    return ScoringConfig.default().replace(
        usage_thresholds=jnp.zeros(NUM_RESOURCE_DIMS, jnp.int32),
        estimator_defaults=jnp.zeros(NUM_RESOURCE_DIMS, jnp.int32))


def _mk_scheduler(nodes=3, with_quota=True):
    from koordinator_tpu.api.resources import resource_vector
    from koordinator_tpu.quota.tree import QuotaTree
    from koordinator_tpu.scheduler import ClusterSnapshot, NodeSpec, Scheduler

    snap = ClusterSnapshot(capacity=8)
    for i in range(nodes):
        snap.upsert_node(NodeSpec(
            name=f"ck{i}",
            allocatable=resource_vector(cpu=16_000, memory=16_384),
            labels={"rack": f"r{i % 2}"}))
    tree = None
    if with_quota:
        total = resource_vector(cpu=16_000 * 3, memory=16_384 * 3)
        tree = QuotaTree(total)
        tree.add("t-a", min=resource_vector(cpu=8_000, memory=8_192),
                 max=total)
    return Scheduler(snap, config=_plain_cfg(),
                     bind_fn=lambda p, n: None, quota_tree=tree)


def test_checkpoint_roundtrip_is_bit_identical(tmp_path):
    from koordinator_tpu.api.resources import resource_vector
    from koordinator_tpu.scheduler import PodSpec
    from koordinator_tpu.scheduler.scheduler import GangRecord

    a = _mk_scheduler()
    a.register_gang(GangRecord(name="g1", min_member=2))
    for i in range(4):
        a.enqueue(PodSpec(
            name=f"p{i}", requests=resource_vector(cpu=2_000, memory=1_024),
            priority=1000, quota="t-a", gang="g1" if i < 2 else None))
    res = a.schedule_round()
    assert len(res.assignments) == 4
    a.enqueue(PodSpec(name="pend",
                      requests=resource_vector(cpu=2_000, memory=1_024),
                      quota="t-a"))

    path = str(tmp_path / "ckpt.bin")
    stats = ckpt.save(path, a)
    assert stats["bound"] == 4 and stats["pending"] == 1

    # restore onto a FRESH, EMPTY scheduler (the caller owns its
    # construction): nodes, quota tree, gangs, queues all come back
    b = _mk_scheduler(nodes=0, with_quota=False)
    rstats = ckpt.restore(path, b)
    assert rstats["nodes"] == 3 and rstats["bound"] == 4
    assert rstats["pending"] == 1 and rstats["gangs"] == 1
    assert rstats["cursor_rv"] == -1    # no sync attached

    doc_a, arrays_a = ckpt.capture(a)
    doc_b, arrays_b = ckpt.capture(b)
    assert doc_a == doc_b
    assert sorted(arrays_a) == sorted(arrays_b)
    for key in arrays_a:
        assert arrays_a[key].dtype == arrays_b[key].dtype, key
        assert np.array_equal(arrays_a[key], arrays_b[key]), key
    # and the device accounting itself is bit-identical: the batched
    # restore reserve commutes with the sequential bind-time reserves
    a.snapshot.flush()
    b.snapshot.flush()
    assert np.array_equal(np.asarray(a.snapshot.state.node_requested),
                          np.asarray(b.snapshot.state.node_requested))
    assert set(b.bound) == set(a.bound)
    assert set(b.gangs) == {"g1"}
    a.stop()
    b.stop()


def test_checkpoint_primes_the_replay_cursor(tmp_path):
    class _Cursor:
        rv = 41
        instance = "epoch-1"

    a = _mk_scheduler()
    path = str(tmp_path / "cur.bin")
    ckpt.save(path, a, sync=_Cursor())
    b = _mk_scheduler(nodes=0, with_quota=False)
    fresh = _Cursor()
    fresh.rv, fresh.instance = -1, None
    stats = ckpt.restore(path, b, sync=fresh)
    assert stats["cursor_rv"] == 41
    assert fresh.rv == 41 and fresh.instance == "epoch-1"
    a.stop()
    b.stop()


def test_checkpoint_writer_stop_writes_a_final_cut(tmp_path):
    a = _mk_scheduler()
    path = str(tmp_path / "w.bin")
    w = ckpt.CheckpointWriter(path, a, interval_s=600.0).start()
    w.stop()                       # planned restart: freshest cut
    assert w.saves == 1 and w.errors == 0
    assert os.path.exists(path)
    doc, _ = ckpt.load(path)
    assert doc["version"] == ckpt.CHECKPOINT_VERSION
    # a failed save never raises — checkpointing is an optimization
    bad = ckpt.CheckpointWriter(
        str(tmp_path / "no-such-dir" / "w.bin"), a, interval_s=600.0)
    assert bad.save_now() is None
    assert bad.errors == 1
    a.stop()
