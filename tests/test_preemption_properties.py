"""Randomized invariants of preemption victim selection.

test_preemption.py pins the reference scenarios at hand-built shapes;
this sweeps random clusters and asserts the structural contract of
``select_victims``/``pick_node`` for ANY input:

  (candidates) victims are always valid, strictly lower priority,
               preemptible, scheduled — and same-quota when required
  (soundness)  an eligible node really fits the preemptor once its
               victims leave
  (complete)   an ineligible node with candidates could not have been
               rescued even by evicting every candidate on it
  (minimal)    no single victim on an eligible node could be reprieved
               without breaking the preemptor's fit (the reprieve
               loop's guarantee)
  (pick)       pick_node matches the documented lexicographic rule
               (violations, max victim pri, pri sum, victim count,
               lowest row), recomputed independently in numpy
"""

import jax.numpy as jnp
import numpy as np
import pytest

from tests.conftest import prop_seeds

from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS, ResourceDim
from koordinator_tpu.ops.preemption import (
    ScheduledPods,
    pick_node,
    select_victims,
)
from koordinator_tpu.state.cluster_state import ClusterState

R = NUM_RESOURCE_DIMS
CPU, MEM = ResourceDim.CPU, ResourceDim.MEMORY


def _random_problem(rng: np.random.Generator):
    n_nodes = int(rng.integers(2, 9))
    n_pods = int(rng.integers(4, 40))
    alloc = np.zeros((n_nodes, R), np.int32)
    alloc[:, CPU] = rng.integers(4_000, 16_000, n_nodes)
    alloc[:, MEM] = rng.integers(8_192, 65_536, n_nodes)

    req = np.zeros((n_pods, R), np.int32)
    req[:, CPU] = rng.integers(100, 4_000, n_pods)
    req[:, MEM] = rng.integers(128, 8_192, n_pods)
    nodes = rng.integers(0, n_nodes, n_pods).astype(np.int32)
    pris = rng.integers(3_000, 10_000, n_pods).astype(np.int32)
    nonpre = rng.random(n_pods) < 0.2
    quotas = rng.integers(-1, 3, n_pods).astype(np.int32)

    requested = np.zeros((n_nodes, R), np.int32)
    np.add.at(requested, nodes, req)
    # leave some ambient headroom variance
    requested = np.minimum(requested, alloc)
    state = ClusterState.from_arrays(alloc, requested=requested,
                                    capacity=n_nodes)
    sched = ScheduledPods.build(
        req, np.asarray(nodes), priority=pris,
        non_preemptible=nonpre, quota_id=quotas)

    p_req = np.zeros(R, np.int32)
    p_req[CPU] = rng.integers(2_000, 12_000)
    p_req[MEM] = rng.integers(1_024, 32_768)
    p_pri = int(rng.integers(4_000, 11_000))
    p_quota = int(rng.integers(-1, 3))
    same_quota = bool(rng.random() < 0.5)
    return state, sched, p_req, p_pri, p_quota, same_quota


def _fits_np(req, free):
    return (free >= req).all(axis=-1)


@pytest.mark.parametrize("seed", prop_seeds(20))
def test_select_victims_invariants(seed):
    rng = np.random.default_rng(seed)
    state, sched, p_req, p_pri, p_quota, same_quota = _random_problem(rng)
    n_nodes = state.capacity
    feasible = jnp.ones(n_nodes, bool)
    pdb_allowed = jnp.full(1, 10_000, jnp.int32)   # PDBs never bind here

    solve = select_victims(
        state, sched, jnp.asarray(p_req), jnp.int32(p_pri),
        jnp.int32(p_quota), feasible, pdb_allowed,
        same_quota_only=same_quota)

    victim = np.asarray(solve.victim)
    eligible = np.asarray(solve.eligible)
    valid = np.asarray(sched.valid)
    pris = np.asarray(sched.priority)
    nonpre = np.asarray(sched.non_preemptible)
    nodes = np.asarray(sched.node)
    quotas = np.asarray(sched.quota_id)
    reqs = np.asarray(sched.requests)
    free = np.asarray(state.node_allocatable) - np.asarray(
        state.node_requested)

    cand = valid & (pris < p_pri) & ~nonpre & (nodes >= 0)
    if same_quota:
        cand &= quotas == p_quota

    # (candidates) victims only come from the candidate set
    assert not (victim & ~cand).any(), f"seed {seed}: non-candidate victim"

    freed = np.zeros((n_nodes, R), np.int64)
    np.add.at(freed, nodes[victim], reqs[victim])
    all_cand_freed = np.zeros((n_nodes, R), np.int64)
    np.add.at(all_cand_freed, nodes[cand], reqs[cand])
    has_cand = np.zeros(n_nodes, bool)
    has_cand[nodes[cand]] = True

    for n in range(n_nodes):
        free_after = free[n] + freed[n]
        if eligible[n]:
            # (soundness) preemptor fits once the victims leave
            assert _fits_np(p_req, free_after), (
                f"seed {seed}: eligible node {n} does not fit")
            # (minimal) reprieving any single victim breaks the fit
            for v in np.flatnonzero(victim & (nodes == n)):
                assert not _fits_np(p_req, free_after - reqs[v]), (
                    f"seed {seed}: victim {v} on node {n} was reprievable")
        elif has_cand[n]:
            # (complete) even evicting every candidate would not help
            assert not _fits_np(p_req, free[n] + all_cand_freed[n]), (
                f"seed {seed}: node {n} ineligible but rescuable")

    # (pick) lexicographic oracle over eligible nodes
    chosen = int(pick_node(solve))
    if not eligible.any():
        assert chosen == -1
    else:
        keys = list(zip(
            np.asarray(solve.num_violating).tolist(),
            np.asarray(solve.max_victim_pri).tolist(),
            np.asarray(solve.sum_victim_pri).tolist(),
            np.asarray(solve.num_victims).tolist(),
            range(n_nodes),
        ))
        best = min(k for n, k in zip(range(n_nodes), keys) if eligible[n])
        assert chosen == best[4], (
            f"seed {seed}: pick_node chose {chosen}, oracle {best[4]}")
