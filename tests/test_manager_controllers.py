"""Manager-layer tests: sloconfig parsing/validation, NodeSLO rendering,
NodeMetric lifecycle, the batched noderesource controller, webhooks, quota
profiles."""

import json

import pytest

from koordinator_tpu.api import crds, extension as ext
from koordinator_tpu.manager import sloconfig
from koordinator_tpu.manager.nodemetric import NodeMetricController
from koordinator_tpu.manager.nodeslo import NodeSLOController, render_node_slo
from koordinator_tpu.manager.noderesource_controller import (
    MIB, NodePatch, NodeRecord, NodeResourceController,
)
from koordinator_tpu.manager.quota_profile import QuotaProfileController
from koordinator_tpu.manager.webhook import (
    PodMutatingWebhook, PodValidatingWebhook, QuotaEvaluator,
)
from tests.test_koordlet_metrics import FakeClock


class TestSloConfig:
    def test_colocation_defaults_and_override(self):
        data = {
            sloconfig.KEY_COLOCATION: json.dumps({
                "enable": True,
                "cpuReclaimThresholdPercent": 70,
                "nodeStrategies": [
                    {"nodeSelector": {"matchLabels": {"pool": "batch"}},
                     "cpuReclaimThresholdPercent": 80},
                ],
            })
        }
        base = sloconfig.parse_colocation_config(data, {})
        assert base.enable and base.cpu_reclaim_threshold_percent == 70
        override = sloconfig.parse_colocation_config(data, {"pool": "batch"})
        assert override.cpu_reclaim_threshold_percent == 80
        # untouched field keeps default
        assert override.memory_reclaim_threshold_percent == 65

    def test_threshold_strategy(self):
        data = {
            sloconfig.KEY_RESOURCE_THRESHOLD: json.dumps({
                "enable": True, "cpuSuppressThresholdPercent": 55,
            })
        }
        s = sloconfig.parse_threshold_strategy(data)
        assert s.enable and s.cpu_suppress_threshold_percent == 55

    def test_validation(self):
        bad = {sloconfig.KEY_COLOCATION: "{not json"}
        assert sloconfig.validate_config_data(bad)
        out_of_range = {
            sloconfig.KEY_RESOURCE_THRESHOLD: json.dumps(
                {"cpuSuppressThresholdPercent": 150}
            )
        }
        assert sloconfig.validate_config_data(out_of_range)
        ok = {sloconfig.KEY_RESOURCE_THRESHOLD: json.dumps(
            {"cpuSuppressThresholdPercent": 65})}
        assert sloconfig.validate_config_data(ok) == []


class TestNodeSLO:
    def test_render_and_reconcile(self):
        controller = NodeSLOController()
        controller.upsert_node("n1", {"pool": "batch"})
        controller.upsert_node("n2", {})
        changed = controller.update_config({
            sloconfig.KEY_RESOURCE_THRESHOLD: json.dumps({
                "enable": True,
                "nodeStrategies": [
                    {"nodeSelector": {"matchLabels": {"pool": "batch"}},
                     "cpuSuppressThresholdPercent": 50},
                ],
            })
        })
        assert set(changed) == {"n1", "n2"}
        assert controller.get("n1").resource_used_threshold_with_be \
            .cpu_suppress_threshold_percent == 50
        assert controller.get("n2").resource_used_threshold_with_be \
            .cpu_suppress_threshold_percent == 65

    def test_invalid_config_keeps_last_good(self):
        controller = NodeSLOController()
        controller.upsert_node("n1", {})
        controller.update_config({
            sloconfig.KEY_RESOURCE_THRESHOLD: json.dumps({"enable": True})
        })
        assert controller.get("n1").resource_used_threshold_with_be.enable
        controller.update_config({sloconfig.KEY_RESOURCE_THRESHOLD: "broken{"})
        assert controller.get("n1").resource_used_threshold_with_be.enable


class TestNodeMetricController:
    def test_spec_push_and_expiry(self):
        clock = FakeClock()
        config = sloconfig.ColocationConfig(update_time_threshold_seconds=300)
        controller = NodeMetricController(config, clock=clock)
        controller.upsert_node("n1")
        assert controller.get("n1").spec.aggregate_duration_seconds == 300
        assert controller.is_expired("n1")  # never reported
        controller.report_status("n1", crds.NodeMetricStatus(update_time=clock.t))
        assert not controller.is_expired("n1")
        clock.tick(301)
        assert controller.is_expired("n1")


def make_record(name="n1", metric_age=0.0, now=1000.0, **kw):
    defaults = dict(
        cpu_capacity_milli=16000, mem_capacity_mib=32768,
        metric=crds.NodeMetricStatus(
            update_time=now - metric_age,
            node_usage=crds.ResourceUsage(cpu_milli=7000,
                                          memory_bytes=8192 * MIB),
            system_usage=crds.ResourceUsage(cpu_milli=1000,
                                            memory_bytes=2048 * MIB),
        ),
    )
    defaults.update(kw)
    return NodeRecord(name=name, **defaults)


class TestNodeResourceController:
    def test_batch_formula_by_usage(self):
        clock = FakeClock()
        controller = NodeResourceController(
            sloconfig.ColocationConfig(enable=True), clock=clock
        )
        record = make_record(now=clock.t, hp_request_cpu_milli=4000)
        # hp usage 0 (no pods_metrics) => batch = 16000*0.6 - max(1000,0) - 0
        patches = controller.reconcile([record])
        assert len(patches) == 1
        assert patches[0].batch_cpu_milli == 16000 * 60 // 100 - 1000
        assert not patches[0].degraded

    def test_degrade_on_stale_metric(self):
        clock = FakeClock()
        config = sloconfig.ColocationConfig(enable=True, degrade_time_minutes=15)
        controller = NodeResourceController(config, clock=clock)
        record = make_record(now=clock.t, metric_age=16 * 60)
        patches = controller.reconcile([record])
        assert patches[0].degraded and patches[0].batch_cpu_milli == 0

    def test_diff_threshold_suppression(self):
        clock = FakeClock()
        controller = NodeResourceController(
            sloconfig.ColocationConfig(enable=True, resource_diff_threshold=0.1),
            clock=clock,
        )
        record = make_record(now=clock.t)
        assert len(controller.reconcile([record])) == 1
        # tiny usage change -> relative diff below 10% -> suppressed
        record.metric = crds.NodeMetricStatus(
            update_time=clock.t,
            node_usage=crds.ResourceUsage(cpu_milli=7100, memory_bytes=8192 * MIB),
            system_usage=crds.ResourceUsage(cpu_milli=1100, memory_bytes=2048 * MIB),
        )
        assert controller.reconcile([record]) == []

    def test_cpu_normalization_and_amplification(self):
        clock = FakeClock()
        controller = NodeResourceController(
            sloconfig.ColocationConfig(enable=True), clock=clock
        )
        record = make_record(
            now=clock.t,
            annotations={
                ext.ANNOTATION_CPU_NORMALIZATION: "1.5",
                ext.ANNOTATION_NODE_AMPLIFICATION: '{"cpu": 2.0}',
            },
        )
        patches = controller.reconcile([record])
        # capacity 16000 * 1.5 * 2.0 = 48000 => batch = 48000*0.6 - 1000
        assert patches[0].batch_cpu_milli == 48000 * 60 // 100 - 1000

    def test_device_resources_synced(self):
        clock = FakeClock()
        controller = NodeResourceController(
            sloconfig.ColocationConfig(enable=True), clock=clock
        )
        record = make_record(
            now=clock.t,
            device=crds.Device(node_name="n1", devices=(
                crds.DeviceInfo(type="gpu", minor=0,
                                resources={ext.RESOURCE_GPU_MEMORY: 16384}),
                crds.DeviceInfo(type="gpu", minor=1, health=False,
                                resources={ext.RESOURCE_GPU_MEMORY: 16384}),
                crds.DeviceInfo(type="rdma", minor=0),
            )),
        )
        patches = controller.reconcile([record])
        devres = patches[0].device_resources
        assert devres[ext.RESOURCE_GPU] == 100          # unhealthy gpu excluded
        assert devres[ext.RESOURCE_GPU_MEMORY] == 16384
        assert devres[ext.RESOURCE_RDMA] == 100

    def test_batched_many_nodes(self):
        clock = FakeClock()
        controller = NodeResourceController(
            sloconfig.ColocationConfig(enable=True), clock=clock
        )
        records = [make_record(name=f"n{i}", now=clock.t) for i in range(64)]
        patches = controller.reconcile(records)
        assert len(patches) == 64
        assert len({p.batch_cpu_milli for p in patches}) == 1


def be_pod_dict(cpu="2", memory="4Gi"):
    return {
        "metadata": {"name": "p1", "namespace": "default",
                     "labels": {ext.LABEL_POD_QOS: "BE"}},
        "spec": {
            "priority": 5500,
            "containers": [
                {"name": "main", "resources": {
                    "requests": {"cpu": cpu, "memory": memory},
                    "limits": {"cpu": cpu, "memory": memory},
                }},
            ],
        },
    }


class TestMutatingWebhook:
    def test_profile_injection(self):
        profile = crds.ClusterColocationProfile(
            name="colo", pod_selector={"app": "batch"},
            qos_class="BE", koordinator_priority=5500,
            scheduler_name="koord-scheduler",
            labels={"injected": "yes"},
        )
        hook = PodMutatingWebhook([profile])
        pod = {"metadata": {"labels": {"app": "batch"}},
               "spec": {"containers": []}}
        hook.mutate(pod)
        assert pod["metadata"]["labels"][ext.LABEL_POD_QOS] == "BE"
        assert pod["spec"]["priority"] == 5500
        assert pod["spec"]["schedulerName"] == "koord-scheduler"
        assert pod["metadata"]["labels"]["injected"] == "yes"

    def test_no_match_no_change(self):
        profile = crds.ClusterColocationProfile(
            name="colo", pod_selector={"app": "batch"}, qos_class="BE",
        )
        hook = PodMutatingWebhook([profile])
        pod = {"metadata": {"labels": {"app": "web"}}, "spec": {"containers": []}}
        hook.mutate(pod)
        assert ext.LABEL_POD_QOS not in pod["metadata"]["labels"]

    def test_batch_resource_translation(self):
        hook = PodMutatingWebhook()
        pod = be_pod_dict(cpu="500m", memory="1Gi")
        hook.mutate(pod)
        resources = pod["spec"]["containers"][0]["resources"]
        assert resources["requests"][ext.RESOURCE_BATCH_CPU] == 500
        assert resources["requests"][ext.RESOURCE_BATCH_MEMORY] == 1 << 30
        assert "cpu" not in resources["requests"]

    def test_non_be_untranslated(self):
        hook = PodMutatingWebhook()
        pod = be_pod_dict()
        pod["metadata"]["labels"][ext.LABEL_POD_QOS] = "LS"
        pod["spec"]["priority"] = 9500
        hook.mutate(pod)
        assert "cpu" in pod["spec"]["containers"][0]["resources"]["requests"]


class TestValidatingWebhook:
    def test_qos_priority_compat(self):
        hook = PodValidatingWebhook()
        bad = {"metadata": {"labels": {ext.LABEL_POD_QOS: "LSR"}},
               "spec": {"priority": 5500, "containers": []}}
        assert hook.validate(bad)
        good = {"metadata": {"labels": {ext.LABEL_POD_QOS: "LSR"}},
                "spec": {"priority": 9500, "containers": []}}
        assert hook.validate(good) == []

    def test_mixed_batch_native_rejected(self):
        hook = PodValidatingWebhook()
        pod = {
            "metadata": {"labels": {ext.LABEL_POD_QOS: "BE"}},
            "spec": {"priority": 5500, "containers": [
                {"name": "c", "resources": {"requests": {
                    "cpu": "1", ext.RESOURCE_BATCH_CPU: 1000,
                }}},
            ]},
        }
        assert any("mixed" in e for e in hook.validate(pod))

    def test_batch_request_limit_mismatch(self):
        hook = PodValidatingWebhook()
        pod = {
            "metadata": {"labels": {ext.LABEL_POD_QOS: "BE"}},
            "spec": {"priority": 5500, "containers": [
                {"name": "c", "resources": {
                    "requests": {ext.RESOURCE_BATCH_CPU: 1000},
                    "limits": {ext.RESOURCE_BATCH_CPU: 2000},
                }},
            ]},
        }
        assert any("request must equal limit" in e for e in hook.validate(pod))


class TestQuotaEvaluator:
    def make(self):
        ev = QuotaEvaluator()
        ev.set_quota(crds.ElasticQuota(name="org", parent="root",
                                       max={"cpu": 10000}))
        ev.set_quota(crds.ElasticQuota(name="team", parent="org",
                                       max={"cpu": 4000}))
        return ev

    def test_admit_and_reject(self):
        ev = self.make()
        assert ev.admit("team", {"cpu": 3000}) is None
        reason = ev.admit("team", {"cpu": 2000})
        assert reason is not None and "team" in reason
        assert ev.admit("team", {"cpu": 1000}) is None

    def test_parent_limit_enforced(self):
        ev = self.make()
        ev.set_quota(crds.ElasticQuota(name="team2", parent="org",
                                       max={"cpu": 8000}))
        assert ev.admit("team", {"cpu": 4000}) is None
        assert ev.admit("team2", {"cpu": 7000}) is not None  # org cap 10000

    def test_release(self):
        ev = self.make()
        assert ev.admit("team", {"cpu": 4000}) is None
        ev.release("team", {"cpu": 4000})
        assert ev.admit("team", {"cpu": 4000}) is None


class TestQuotaProfile:
    def test_tree_generation(self):
        controller = QuotaProfileController()
        controller.upsert_profile(crds.ElasticQuotaProfile(
            name="batch-pool", quota_name="batch-root",
            node_selector={"pool": "batch"}, resource_ratio_percent=90,
        ))
        controller.upsert_node("n1", {"pool": "batch"}, {"cpu": 16000})
        controller.upsert_node("n2", {"pool": "batch"}, {"cpu": 16000})
        controller.upsert_node("n3", {"pool": "web"}, {"cpu": 16000})
        quotas = controller.reconcile()
        assert len(quotas) == 1
        assert quotas[0].name == "batch-root"
        assert quotas[0].min == {"cpu": 32000 * 90 // 100}
        assert quotas[0].tree_id


class TestSyncSuppressionExtended:
    def test_device_change_triggers_sync(self):
        clock = FakeClock()
        controller = NodeResourceController(
            sloconfig.ColocationConfig(enable=True), clock=clock
        )
        record = make_record(now=clock.t)
        assert len(controller.reconcile([record])) == 1
        assert controller.reconcile([record]) == []  # stable
        record.device = crds.Device(node_name="n1", devices=(
            crds.DeviceInfo(type="gpu", minor=0),
        ))
        patches = controller.reconcile([record])
        assert len(patches) == 1 and patches[0].device_resources

    def test_degraded_patched_once(self):
        clock = FakeClock()
        config = sloconfig.ColocationConfig(enable=True, degrade_time_minutes=15)
        controller = NodeResourceController(config, clock=clock)
        record = make_record(now=clock.t, metric_age=16 * 60)
        assert len(controller.reconcile([record])) == 1
        assert controller.reconcile([record]) == []  # no re-patch churn
        # recovery: fresh metric -> syncs again
        record.metric = crds.NodeMetricStatus(
            update_time=clock.t,
            node_usage=crds.ResourceUsage(cpu_milli=7000, memory_bytes=8192 * MIB),
            system_usage=crds.ResourceUsage(cpu_milli=1000, memory_bytes=2048 * MIB),
        )
        patches = controller.reconcile([record])
        assert len(patches) == 1 and not patches[0].degraded

    def test_device_change_synced_while_degraded(self):
        clock = FakeClock()
        config = sloconfig.ColocationConfig(enable=True, degrade_time_minutes=15)
        controller = NodeResourceController(config, clock=clock)
        record = make_record(now=clock.t, metric_age=16 * 60)
        assert len(controller.reconcile([record])) == 1  # zeroing patch
        assert controller.reconcile([record]) == []
        record.device = crds.Device(node_name="n1", devices=(
            crds.DeviceInfo(type="gpu", minor=0),
        ))
        patches = controller.reconcile([record])
        assert len(patches) == 1 and patches[0].degraded
        assert patches[0].device_resources[ext.RESOURCE_GPU] == 100


def test_mutate_then_validate_consistency_random():
    """Cross-component invariant: a pod whose cpu/memory request equals
    its limit, mutated by a well-formed profile (QoS and priority drawn
    from the COMPATIBLE matrix), always passes the validating webhook —
    including the BE batch translation, whose output must satisfy the
    extended-resource request==limit rule it feeds.  Pods with
    MISMATCHED request/limit that get batch-translated are rejected
    with exactly the equality errors (the reference translates
    faithfully and lets core admission reject the mismatch —
    cluster_colocation_profile.go mutatePodResourceSpec)."""
    import numpy as np

    from koordinator_tpu.api.priority import (
        PRIORITY_BATCH_MIN,
        PRIORITY_FREE_MIN,
        PRIORITY_MID_MIN,
        PRIORITY_PROD_MIN,
        PriorityClass,
    )
    from koordinator_tpu.manager.webhook import QOS_PRIORITY_COMPAT

    band_value = {
        PriorityClass.PROD: PRIORITY_PROD_MIN + 50,
        PriorityClass.MID: PRIORITY_MID_MIN + 50,
        PriorityClass.BATCH: PRIORITY_BATCH_MIN + 50,
        PriorityClass.FREE: PRIORITY_FREE_MIN + 50,
        PriorityClass.NONE: None,
    }
    rng = np.random.default_rng(0)
    validator = PodValidatingWebhook()
    for trial in range(200):
        qos = list(QOS_PRIORITY_COMPAT)[int(rng.integers(
            0, len(QOS_PRIORITY_COMPAT)))]
        allowed = QOS_PRIORITY_COMPAT[qos]
        band = allowed[int(rng.integers(0, len(allowed)))]
        profile = crds.ClusterColocationProfile(
            name="p", qos_class=qos.name if qos.name != "NONE" else "",
            koordinator_priority=band_value[band])
        mutator = PodMutatingWebhook([profile])
        cpu = f"{int(rng.integers(1, 4000))}m"
        mem = f"{int(rng.integers(1, 8))}Gi"
        matched = bool(rng.random() < 0.7)
        limits = ({"cpu": cpu, "memory": mem} if matched else
                  {"cpu": f"{int(rng.integers(4000, 8000))}m",
                   "memory": f"{int(rng.integers(8, 16))}Gi"})
        pod = {
            "metadata": {"name": f"pod{trial}", "labels": {}},
            "spec": {"containers": [{
                "name": "main",
                "resources": {
                    "requests": {"cpu": cpu, "memory": mem},
                    "limits": limits,
                }}]},
        }
        mutated = mutator.mutate(pod)
        errors = validator.validate(mutated)
        translated = any(
            "batch" in k
            for c in mutated["spec"]["containers"]
            for k in c.get("resources", {}).get("requests", {}))
        if matched or not translated:
            assert not errors, (
                f"trial {trial}: qos={qos.name} band={band.name}: {errors}")
        else:
            # faithful translation of a mismatched pod: rejected with
            # exactly the extended-resource equality errors
            assert errors and all("must equal limit" in e for e in errors), (
                f"trial {trial}: {errors}")
