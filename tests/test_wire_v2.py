"""Wire protocol v2: typed request schemas + lease frames + cross-process
leader election.

- REQUEST_SCHEMAS / validate_doc make peer skew fail loud at the server
  boundary (VERDICT r2 item 10 — the api.proto versioned-contract role);
- LEASE_GET/LEASE_UPDATE + RemoteLeaseStore let two scheduler PROCESSES
  contend one lease over the transport (VERDICT r2 item 6); the failover
  test kill -9s the leading process and the standby must take over.
"""

import textwrap
import time

import pytest

from koordinator_tpu.ha import (
    InMemoryLeaseStore,
    LeaderElector,
    LeaseRecord,
    LeaseService,
    RemoteLeaseStore,
)
from koordinator_tpu.transport.channel import RpcClient, RpcError, RpcServer
from koordinator_tpu.transport.wire import (
    PROTOCOL_VERSION,
    FrameType,
    WireSchemaError,
    validate_doc,
)


class TestSchemas:
    def test_missing_required_field_raises(self):
        with pytest.raises(WireSchemaError, match="last_rv"):
            validate_doc(FrameType.HELLO, {"proto": PROTOCOL_VERSION})

    def test_wrong_type_raises(self):
        with pytest.raises(WireSchemaError, match="name"):
            validate_doc(FrameType.LEASE_GET, {"name": 7})

    def test_bool_not_accepted_as_int(self):
        with pytest.raises(WireSchemaError, match="bool"):
            validate_doc(
                FrameType.HELLO, {"last_rv": True,
                                  "proto": PROTOCOL_VERSION})

    def test_extra_fields_allowed(self):
        validate_doc(FrameType.HELLO, {
            "last_rv": 3, "proto": PROTOCOL_VERSION, "future": "field"})

    def test_unschemad_types_pass(self):
        validate_doc(FrameType.DELTA, {"anything": object()})


def _server(tmp_path, name="lease.sock"):
    path = str(tmp_path / name)
    server = RpcServer(path)
    svc = LeaseService()
    svc.attach(server)
    server.start()
    return path, server, svc


class TestLeaseFrames:
    def test_remote_get_update_roundtrip(self, tmp_path):
        path, server, svc = _server(tmp_path)
        try:
            client = RpcClient(path)
            client.connect()
            store = RemoteLeaseStore(client)
            assert store.get("sched").holder == ""
            rec = LeaseRecord(holder="a", duration_seconds=2.0,
                              acquire_time=1.0, renew_time=1.0,
                              transitions=1)
            assert store.update("sched", "", rec)
            got = store.get("sched")
            assert got.holder == "a" and got.transitions == 1
            # CAS: stale expect_holder fails
            assert not store.update(
                "sched", "b", LeaseRecord(holder="b"))
            client.close()
        finally:
            server.stop()

    def test_schema_violation_surfaces_as_rpc_error(self, tmp_path):
        path, server, svc = _server(tmp_path)
        try:
            client = RpcClient(path)
            client.connect()
            with pytest.raises(RpcError, match="missing required field"):
                client.call(FrameType.LEASE_GET, {})
            # connection survives a schema error: next call works
            assert RemoteLeaseStore(client).get("x").holder == ""
            client.close()
        finally:
            server.stop()

    def test_old_protocol_hello_rejected(self, tmp_path):
        from koordinator_tpu.transport.deltasync import StateSyncService

        path = str(tmp_path / "sync.sock")
        server = RpcServer(path)
        sync = StateSyncService()
        sync.attach(server)
        server.start()
        try:
            client = RpcClient(path)
            client.connect()
            # a v1 peer omits "proto": the schema rejects it loudly
            with pytest.raises(RpcError, match="proto"):
                client.call(FrameType.HELLO, {"last_rv": -1})
            # a mismatched advertised protocol is also rejected
            with pytest.raises(RpcError, match="incompatible"):
                client.call(FrameType.HELLO,
                            {"last_rv": -1, "proto": 99})
            client.close()
        finally:
            server.stop()

    def test_two_electors_one_leader_in_process(self, tmp_path):
        path, server, svc = _server(tmp_path)
        try:
            clients = [RpcClient(path), RpcClient(path)]
            for c in clients:
                c.connect()
            now = [100.0]
            electors = [
                LeaderElector(RemoteLeaseStore(c), "sched", ident,
                              lease_duration=5.0,
                              clock=lambda: now[0])
                for c, ident in zip(clients, ("a", "b"))
            ]
            leads = [e.tick() for e in electors]
            assert leads.count(True) == 1
            # holder crashes (no release); follower waits out the lease
            now[0] += 6.0
            standby = electors[leads.index(False)]
            assert standby.tick()
            for c in clients:
                c.close()
        finally:
            server.stop()


CONTENDER = textwrap.dedent("""
    import sys, time
    sock, ident, status = sys.argv[1], sys.argv[2], sys.argv[3]
    from koordinator_tpu.ha import LeaderElector, RemoteLeaseStore
    from koordinator_tpu.transport.channel import RpcClient

    client = RpcClient(sock)
    client.connect()
    # wall clock: cross-process contenders must share a clock domain
    elector = LeaderElector(RemoteLeaseStore(client), "sched", ident,
                            lease_duration=1.0, clock=time.time)
    rounds = 0
    while True:
        if elector.tick():
            rounds += 1
            with open(status, "a") as f:
                f.write(f"ROUND {ident} {rounds}\\n")
        time.sleep(0.05)
""")


def test_cross_process_failover_kill9(tmp_path):
    """kill -9 the leading scheduler process; the standby must acquire the
    lease and run rounds (cmd/koord-manager/main.go Leases semantics)."""
    from tests.proc_helpers import kill_all, spawn_replicas, wait_for

    path, server, svc = _server(tmp_path, "failover.sock")
    script = tmp_path / "contender.py"
    script.write_text(CONTENDER)
    status = {i: tmp_path / f"status-{i}" for i in ("a", "b")}
    for f in status.values():
        f.write_text("")
    procs, errs = spawn_replicas(
        script, {i: [path, i, str(status[i])] for i in ("a", "b")},
        tmp_path)
    try:
        def leader_now():
            return svc.store.get("sched").holder

        wait_for(lambda: bool(leader_now()), procs, errs, 60,
                 "first lease acquisition")
        first = leader_now()
        assert first in ("a", "b"), "no process acquired the lease"
        # the leader actually runs rounds
        wait_for(lambda: f"ROUND {first}" in status[first].read_text(),
                 procs, errs, 30, "leader rounds")

        procs[first].kill()          # SIGKILL: no voluntary release
        procs[first].wait(timeout=10)
        other = "b" if first == "a" else "a"
        live = {other: procs[other]}
        # standby must wait out the 1s lease, then take over and schedule
        wait_for(lambda: leader_now() == other, live, errs, 60,
                 "standby lease takeover")
        wait_for(lambda: f"ROUND {other}" in status[other].read_text(),
                 live, errs, 30, "standby rounds")
    finally:
        kill_all(procs)
        server.stop()


class TestStatePushValidation:
    """A malformed client-encoded array must fail the PUSHING call and
    never enter the replay log (where it would poison every sync
    client, including future bootstrappers)."""

    def _server(self, tmp_path):
        from koordinator_tpu.transport.channel import RpcServer
        from koordinator_tpu.transport.deltasync import StateSyncService

        server = RpcServer(str(tmp_path / "push.sock"))
        service = StateSyncService()
        service.attach(server)
        server.start()
        return server, service

    def test_wrong_shape_and_dtype_rejected(self, tmp_path):
        import numpy as np
        import pytest

        from koordinator_tpu.transport.channel import RpcClient, RpcError
        from koordinator_tpu.transport.wire import FrameType

        server, service = self._server(tmp_path)
        client = RpcClient(server.path)
        client.connect()
        try:
            for bad in (np.zeros(3, np.int32),            # wrong length
                        np.zeros((2, 10), np.int32),      # wrong rank
                        np.zeros(10, np.float32)):        # wrong dtype
                with pytest.raises(RpcError):
                    client.call(FrameType.STATE_PUSH,
                                {"kind": "node_upsert", "name": "bad"},
                                {"allocatable": bad})
            assert service.rv == 0 and not service.nodes  # nothing logged

            # nested element poisoning: a string where the reservation
            # owner matcher expects a mapping must fail the call
            with pytest.raises(RpcError, match="labels"):
                client.call(FrameType.STATE_PUSH,
                            {"kind": "rsv_upsert", "name": "r1",
                             "owners": [{"labels": "xyz"}]},
                            {"requests": np.zeros(10, np.int32)})
            with pytest.raises(RpcError, match="core"):
                client.call(FrameType.STATE_PUSH,
                            {"kind": "node_upsert", "name": "n1",
                             "devices": {"gpu": [{"core": "many"}]}},
                            {"allocatable": np.zeros(10, np.int32)})
            assert service.rv == 0 and not service.nodes

            _, doc, _ = client.call(
                FrameType.STATE_PUSH,
                {"kind": "node_upsert", "name": "good"},
                {"allocatable": np.zeros(10, np.int32)})
            assert doc["rv"] == 1 and "good" in service.nodes
        finally:
            client.close()
            server.stop()


class TestStatePushNoPartialCommit:
    """Property: ANY state push either commits atomically (rv advances
    by one, the event replays to fresh clients) or raises WireSchemaError
    with the service byte-identical to before — never a partial write.
    Random adversarial documents/arrays via hypothesis."""

    def test_random_pushes_atomic(self):
        import numpy as np
        pytest.importorskip("hypothesis")
        from hypothesis import given, settings, strategies as st

        from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS
        from koordinator_tpu.transport.deltasync import StateSyncService
        from koordinator_tpu.transport.wire import WireSchemaError

        r = NUM_RESOURCE_DIMS

        from koordinator_tpu.scheduler import ClusterSnapshot, Scheduler
        from koordinator_tpu.transport.deltasync import (
            SchedulerBinding,
            _dispatch_event,
            _unpack_event_arrays,
        )

        # ONE real scheduler binding replays every committed event: the
        # atomicity property includes "the committed event cannot crash a
        # real consumer on replay" (reservation owners, device entries)
        replay = SchedulerBinding(Scheduler(ClusterSnapshot(capacity=16)))

        json_scalars = st.one_of(
            st.none(), st.booleans(), st.integers(-2**40, 2**40),
            st.text(max_size=8))
        docs = st.fixed_dictionaries(
            {"kind": st.sampled_from(
                ["node_upsert", "node_usage", "pod_add", "pod_remove",
                 "rsv_upsert", "rsv_remove", "bogus"]),
             "name": st.text(min_size=1, max_size=8)},
            optional={
                "labels": json_scalars | st.dictionaries(
                    st.text(max_size=4), st.text(max_size=4), max_size=2),
                "owners": json_scalars | st.lists(
                    json_scalars | st.fixed_dictionaries(
                        {},
                        optional={
                            "labels": json_scalars | st.dictionaries(
                                st.text(max_size=4), st.text(max_size=4),
                                max_size=2),
                            "controller": json_scalars,
                        }),
                    max_size=2),
                "devices": json_scalars | st.dictionaries(
                    st.text(max_size=4),
                    st.lists(json_scalars | st.fixed_dictionaries(
                        {}, optional={"core": json_scalars,
                                      "memory": json_scalars}),
                             max_size=2),
                    max_size=2),
                "priority": json_scalars,
                "ttl_sec": json_scalars,
            })
        arrays = st.dictionaries(
            st.sampled_from(["allocatable", "usage", "requests"]),
            st.one_of(
                st.just(np.zeros(r, np.int32)),
                st.just(np.zeros(r - 1, np.int32)),
                st.just(np.zeros((2, r), np.int32)),
                st.just(np.zeros(r, np.float32)),
                st.just(np.full(r, 2**40, np.int64)),
            ),
            max_size=2)

        @settings(max_examples=200, deadline=None)
        @given(doc=docs, arrs=arrays)
        def check(doc, arrs):
            service = StateSyncService()
            before = (service.rv, dict(service.nodes), dict(service.pods),
                      dict(service.reservations))
            try:
                out, _ = service._handle_state_push(dict(doc), dict(arrs))
            except WireSchemaError:
                after = (service.rv, dict(service.nodes),
                         dict(service.pods), dict(service.reservations))
                assert after == before, (
                    f"rejected push mutated the service: {doc} {list(arrs)}")
            else:
                assert out["rv"] == before[0] + 1
                snapshot_doc, arrays = service._snapshot()
                assert snapshot_doc["rv"] == out["rv"]
                # the committed event must replay cleanly into a REAL
                # consumer — a commit that crashes SchedulerBinding on
                # replay poisons every client and future bootstrapper
                for entry in snapshot_doc["events"]:
                    _dispatch_event(
                        replay, entry, _unpack_event_arrays(entry, arrays))

        check()
