"""Placement explainability (ISSUE 6): device-side reject-reason
accounting, /debug/explain on both surfaces, unschedulability rollups.

Covers the acceptance criteria:
- reason-count EXACTNESS: a hand-built fixture where every reason code
  fires, device counts vs a NumPy oracle (both the hand-computed
  expectations and ``diagnosis.explain_pod``);
- jit-cache flatness: toggling explain on/off adds no per-round
  recompiles after warmup (``ops/introspection`` counters);
- end-to-end: a pod infeasible for a known mix of reasons stays pending
  and ``/debug/explain/<pod>`` on BOTH surfaces reports exact per-reason
  node counts carrying the pod's trace_id, with
  ``unschedulable_pods{reason}`` matching;
- typed 404s for unknown pods, reserve-pods, and the trace route, on
  both surfaces; degraded-mode suspension explanations.
"""

import json
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np

from koordinator_tpu import metrics
from koordinator_tpu.api.qos import QoSClass
from koordinator_tpu.api.resources import (
    NUM_RESOURCE_DIMS,
    ResourceDim,
    resource_vector,
)
from koordinator_tpu.ops import explain as ex
from koordinator_tpu.ops.assignment import ScoringConfig, score_pods
from koordinator_tpu.quota.tree import QuotaTree
from koordinator_tpu.scheduler import NodeSpec, PodSpec
from koordinator_tpu.scheduler.diagnosis import (
    diagnosis_from_counts,
    explain_pod,
)
from koordinator_tpu.scheduler.scheduler import GangRecord, RSV_POD_PREFIX
from koordinator_tpu.scheduler.services import DebugService
from koordinator_tpu.state.cluster_state import ClusterState, PodBatch
from koordinator_tpu.transport.http_gateway import HttpGateway

from tests.test_scheduler import mk_scheduler, node, pod

R = NUM_RESOURCE_DIMS
CPU, MEM = ResourceDim.CPU, ResourceDim.MEMORY


def _get(port, path):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10) as resp:
            return resp.status, json.loads(resp.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


class TestReasonCountExactness:
    """The 3-pod x 4-node fixture where every node-level reason fires,
    asserted against hand-built expectations AND the explain_pod
    NumPy oracle."""

    def _fixture(self):
        alloc = np.zeros((4, R), np.int32)
        alloc[:, CPU] = [10_000, 100, 10_000, 10_000]
        alloc[:, MEM] = [10_000, 10_000, 100, 10_000]
        usage = np.zeros((4, R), np.int32)
        usage[3, CPU] = 9_900        # node 3: over the 65% cpu threshold
        state = ClusterState.from_arrays(alloc, usage=usage, capacity=4)
        cfg = ScoringConfig.default()

        reqs = np.zeros((3, R), np.int32)
        reqs[:, CPU] = 1_000
        reqs[:, MEM] = 1_000
        reqs[2, MEM] = 0             # pod 2 requests no memory
        feasible = np.ones((3, 4), bool)
        feasible[1, 0] = False       # pod 1: affinity excludes node 0
        batch = PodBatch.build(reqs, feasible=feasible, node_capacity=4,
                               capacity=4)
        return state, batch, cfg

    def test_device_counts_equal_numpy_oracle(self):
        state, batch, cfg = self._fixture()
        counts, feas = jax.jit(ex.explain_counts)(state, batch, cfg)
        counts, feas = np.asarray(counts), np.asarray(feas)

        expected = {
            # pod 0: n0 feasible; n1 fit_cpu; n2 fit_memory; n3 threshold
            0: ({"fit_cpu": 1, "fit_memory": 1, "usage_threshold": 1}, 1),
            # pod 1: same but n0 lost to affinity -> 0 feasible
            1: ({"fit_cpu": 1, "fit_memory": 1, "usage_threshold": 1,
                 "affinity": 1}, 0),
            # pod 2: no memory request -> n2's tiny memory never excludes
            # it from FIT, but the estimator's default memory estimate
            # (200 MiB vs 100 allocatable) pushes n2 over the memory
            # usage threshold; n3 is over on cpu as before
            2: ({"fit_cpu": 1, "usage_threshold": 2}, 1),
        }
        for i, (reasons, n_feasible) in expected.items():
            got = {name: int(counts[i, j])
                   for j, name in enumerate(ex.REASON_NAMES)
                   if counts[i, j]}
            assert got == reasons, (i, got)
            assert int(feas[i]) == n_feasible
            # partition invariant: every valid node counted exactly once
            assert int(feas[i]) + int(counts[i].sum()) == 4
            # the host oracle agrees bit for bit
            oracle = explain_pod(state, batch, cfg, i)
            derived = diagnosis_from_counts(counts[i], feas[i],
                                            oracle.total_nodes)
            assert oracle.reason_counts == derived.reason_counts
            assert oracle.feasible_nodes == derived.feasible_nodes
            assert oracle.insufficient_resources == \
                derived.insufficient_resources
            assert oracle.usage_over_threshold == \
                derived.usage_over_threshold
            assert oracle.affinity_mismatch == derived.affinity_mismatch

    def test_invalid_pod_rows_count_nothing(self):
        state, batch, cfg = self._fixture()
        counts, feas = ex.explain_counts(state, batch, cfg)
        # row 3 is padding (valid=False): all zero
        assert int(np.asarray(counts)[3].sum()) == 0
        assert int(np.asarray(feas)[3]) == 0

    def test_decomposition_sums_to_composite_score(self):
        state, batch, cfg = self._fixture()
        scores, _ = score_pods(state, batch, cfg)
        cand = jnp.asarray(
            np.tile(np.arange(4, dtype=np.int32), (batch.capacity, 1)))
        terms = ex.decompose_scores(state, batch, cfg, cand)
        la, fp, sc = (np.asarray(terms[t])
                      for t in ("loadaware", "fitplus", "scarce"))
        weighted = (
            la * int(cfg.loadaware_plugin_weight)
            + fp * int(cfg.fitplus_plugin_weight)
            + sc * int(cfg.scarce_plugin_weight))
        assert (np.asarray(terms["total"]) == weighted).all()
        assert (np.asarray(terms["total"])[:3] ==
                np.asarray(scores)[:3, :4]).all()


class TestSchedulerExplainEndToEnd:
    def _mixed_reason_scheduler(self):
        """A pod infeasible for a known mix: fit_cpu on two nodes,
        fit_memory on one, usage_threshold on one, affinity on one, and
        elastic quota blocking the single otherwise-feasible node."""
        nodes = [
            node("n-ok", cpu=64_000, mem=65_536),
            node("n-cpu1", cpu=500, mem=65_536),
            node("n-cpu2", cpu=500, mem=65_536),
            NodeSpec(name="n-mem",
                     allocatable=resource_vector(cpu=64_000, memory=100)),
            node("n-hot", cpu=10_000, mem=65_536, usage_cpu=9_500),
            NodeSpec(name="n-taint",
                     allocatable=resource_vector(cpu=64_000, memory=65_536),
                     taints={"reserved": "special"}),
        ]
        total = np.asarray(resource_vector(cpu=1, memory=1), np.int64)
        tree = QuotaTree(total_resource=total)
        tree.add("starved", min=np.zeros_like(total),
                 max=np.asarray(resource_vector(cpu=1, memory=1), np.int64))
        tree.refresh_runtime()
        sched, _ = mk_scheduler(
            nodes, config=ScoringConfig.default(), quota_tree=tree,
            trace_pods=True)
        sched.enqueue(pod("stuck", cpu=1_000, mem=500, quota="starved"))
        return sched

    EXPECTED = {"fit_cpu": 2, "fit_memory": 1, "usage_threshold": 1,
                "affinity": 1, "quota": 1}

    def test_exact_counts_on_both_surfaces_with_trace_id(self):
        sched = self._mixed_reason_scheduler()
        res = sched.schedule_round()
        assert "stuck" in res.failures
        assert res.failures["stuck"].quota_rejected

        svc = DebugService(sched)
        status, body = svc.handle("/debug/explain/stuck")
        assert status == 200
        exp = body["explanation"]
        assert exp["reasons"] == self.EXPECTED
        assert exp["feasible_nodes"] == 0
        assert exp["total_nodes"] == 6
        assert exp["top_reason"] == "quota"
        assert exp["quota"] == "starved"
        assert body["trace_id"] == sched.pod_trace_id("stuck")
        assert exp["trace_id"] == sched.pod_trace_id("stuck")
        assert exp["round"] == sched.round_seq

        gw = HttpGateway(scheduler=sched)
        gw.start()
        try:
            status, doc = _get(gw.port, "/debug/explain/stuck")
            assert status == 200
            assert doc == body   # shared builder: surfaces cannot drift
        finally:
            gw.stop()

        # cluster rollup: the gauge matches, every other reason reads 0
        assert metrics.unschedulable_pods.value(
            labels={"reason": "quota"}) == 1.0
        for reason in ex.REASON_NAMES:
            if reason != "quota":
                assert metrics.unschedulable_pods.value(
                    labels={"reason": reason}) == 0.0
        # flight record carries the round's rollup
        assert sched.flight_recorder.last().top_unschedulable == \
            {"quota": 1}
        # rejection-fraction histogram observed each firing reason
        observed = {labels.get("reason"): total for labels, _, total, _
                    in metrics.filter_reject_fraction.state()}
        for reason in self.EXPECTED:
            assert observed.get(reason, 0) >= 1, (reason, observed)
        # capacity slack published per dim
        assert 0.0 <= metrics.capacity_slack.value(
            labels={"dim": "cpu"}) <= 1.0

    def test_counts_match_host_oracle_after_round(self):
        """The served counts equal explain_pod recomputed against the
        post-round state (nothing placed, so state is unchanged)."""
        sched = self._mixed_reason_scheduler()
        sched.schedule_round()
        spec = sched.pending["stuck"]
        batch = PodBatch.build(
            spec.requests[None].astype(np.int32),
            feasible=sched.snapshot.feasibility_row(spec)[None],
            node_capacity=sched.snapshot.capacity, capacity=16)
        oracle = explain_pod(sched.snapshot.state, batch, sched.config, 0)
        exp = sched.pod_explanation("stuck")
        oracle_reasons = {k: v for k, v in oracle.reason_counts.items()
                          if v > 0 and k != "node_invalid"}
        served = dict(exp.reasons)
        served.pop("quota")          # host-attributed gate
        assert served == oracle_reasons
        assert oracle.feasible_nodes == self.EXPECTED["quota"]

    def test_bound_pod_explanation_has_winner_decomposition(self):
        sched, _ = mk_scheduler([node("n1"), node("n2")])
        sched.enqueue(pod("p1", cpu=4_000))
        res = sched.schedule_round()
        assert "p1" in res.assignments
        svc = DebugService(sched)
        status, body = svc.handle("/debug/explain/p1")
        assert status == 200
        assert body["status"] == "bound"
        assert body["node"] == res.assignments["p1"]
        assert body["candidates"][0]["winner"]
        assert set(body["candidates"][0]["terms"]) == \
            {"loadaware", "fitplus", "scarce"}

    def test_pending_pod_candidates_decompose(self):
        sched, _ = mk_scheduler([node("n1")])      # one 16k-cpu node
        sched.enqueue(pod("first", cpu=9_000))
        sched.enqueue(pod("second", cpu=9_000))
        sched.schedule_round()   # one placed, one stuck on capacity
        stuck = [n for n in ("first", "second") if n in sched.pending]
        assert len(stuck) == 1
        svc = DebugService(sched)
        status, body = svc.handle(f"/debug/explain/{stuck[0]}")
        assert status == 200
        assert body["status"] == "pending"
        # no node fits right now -> no candidates, but the explanation
        # names why
        assert body["candidates"] == []
        assert body["explanation"]["reasons"] == {"fit_cpu": 1}

    def test_candidates_opt_out_param_on_both_surfaces(self):
        """?candidates=0 skips the (1, N) decomposition pass — the
        polling-loop mode tools/explain_summary.py uses."""
        sched, _ = mk_scheduler([node("n1", cpu=1_000)])
        sched.enqueue(pod("big", cpu=50_000))
        sched.schedule_round()
        svc = DebugService(sched)
        status, body = svc.handle("/debug/explain/big",
                                  {"candidates": "0"})
        assert status == 200
        assert "candidates" not in body
        assert body["explanation"]["reasons"] == {"fit_cpu": 1}
        gw = HttpGateway(scheduler=sched)
        gw.start()
        try:
            status, doc = _get(gw.port,
                               "/debug/explain/big?candidates=0")
            assert status == 200
            assert "candidates" not in doc
        finally:
            gw.stop()

    def test_degraded_suspension_explained(self):
        sched, _ = mk_scheduler([node("n1")])
        sched.degraded = True    # watchdog disabled; state set directly
        sched.enqueue(pod("be-pod", qos=int(QoSClass.BE)))
        res = sched.schedule_round()
        assert res.round_pods == 0 or "be-pod" not in res.assignments
        exp = sched.pod_explanation("be-pod")
        assert exp.reasons == {"degraded_suspended": 1}
        assert exp.top_reason() == "degraded_suspended"
        assert metrics.unschedulable_pods.value(
            labels={"reason": "degraded_suspended"}) == 1.0
        svc = DebugService(sched)
        status, body = svc.handle("/debug/explain/be-pod")
        assert status == 200
        assert body["explanation"]["top_reason"] == "degraded_suspended"

    def test_rejected_gang_parkees_explained(self):
        sched, _ = mk_scheduler([node("n1")])
        sched.register_gang(GangRecord(name="g", min_member=2))
        sched.gangs["g"].rejected = True
        sched.enqueue(pod("g-member", gang="g"))
        sched.schedule_round()
        exp = sched.pod_explanation("g-member")
        assert exp.reasons == {"gang_barrier": 1}
        assert exp.gang == "g"

    def test_kill_switch_disables_accounting(self):
        sched, _ = mk_scheduler([node("n1", cpu=1_000)], explain=False)
        sched.enqueue(pod("big", cpu=50_000))
        res = sched.schedule_round()
        # diagnosis still works (host fallback path)...
        assert res.failures["big"].insufficient_resources == 1
        assert res.failures["big"].reason_counts is not None
        # ...but nothing is retained or rolled up
        assert sched.pod_explanation("big") is None
        assert metrics.unschedulable_pods.value(
            labels={"reason": "fit_cpu"}) == 0.0
        svc = DebugService(sched)
        status, body = svc.handle("/debug/explain/big")
        assert status == 200            # pod known (pending)
        assert body["explanation"] is None
        assert body["explain_enabled"] is False


class TestTypedDebugErrors:
    def test_unknown_pod_404_on_both_surfaces(self):
        sched, _ = mk_scheduler([node("n1")])
        svc = DebugService(sched)
        for path in ("/debug/explain/ghost", "/debug/trace/ghost"):
            status, body = svc.handle(path)
            assert status == 404, path
            assert "ghost" in body["error"]
        gw = HttpGateway(scheduler=sched)
        gw.start()
        try:
            for path in ("/debug/explain/ghost", "/debug/trace/ghost"):
                status, body = _get(gw.port, path)
                assert status == 404, path
                assert "ghost" in body["error"]
        finally:
            gw.stop()

    def test_reserve_pod_404_names_the_reservation_surface(self):
        sched, _ = mk_scheduler([node("n1")])
        name = RSV_POD_PREFIX + "cache-warm"
        svc = DebugService(sched)
        status, body = svc.handle(f"/debug/explain/{name}")
        assert status == 404
        assert "reservations" in body["error"]
        gw = HttpGateway(scheduler=sched)
        gw.start()
        try:
            status, body = _get(
                gw.port, "/debug/explain/rsv%3A%3Acache-warm")
            assert status == 404
            assert "reservations" in body["error"]
        finally:
            gw.stop()

    def test_degraded_mode_explain_still_serves(self):
        """Degraded mode must not break the debug surface: a suspended
        pod's explanation serves 200 on both surfaces while degraded."""
        sched, _ = mk_scheduler([node("n1")])
        sched.degraded = True
        sched.enqueue(pod("be-held", qos=int(QoSClass.BE)))
        sched.schedule_round()
        gw = HttpGateway(scheduler=sched)
        gw.start()
        try:
            status, body = _get(gw.port, "/debug/explain/be-held")
            assert status == 200
            assert body["explanation"]["reasons"] == \
                {"degraded_suspended": 1}
        finally:
            gw.stop()


class TestJitCacheFlatAcrossToggles:
    def test_explain_toggle_adds_no_per_round_recompiles(self):
        """After warmup, toggling explain on/off/on adds no recompiles:
        the explain kernel keeps its own shape-bucketed cache entry and
        the solve's shapes are untouched by the flag."""
        from koordinator_tpu.ops.assignment import ScoringConfig
        from koordinator_tpu.scheduler import ClusterSnapshot, Scheduler

        # a UNIQUE node capacity (N64) so the explain kernel's compile
        # demonstrably happens in THIS test: jax shares compiled
        # executables for the same function+shape across Scheduler
        # instances, and another test's N16 warmup would otherwise
        # satisfy this scheduler's first call cache-hot
        snap = ClusterSnapshot(capacity=64)
        snap.upsert_node(node("n1", cpu=2_000))
        sched = Scheduler(snap, config=ScoringConfig.default())
        sched.enqueue(pod("fits", cpu=500))
        sched.enqueue(pod("stuck", cpu=50_000))

        def total_recompiles():
            return sum(v for _, v in metrics.solver_recompiles.items())

        sched.schedule_round()             # warmup: compiles everything
        sched.schedule_round()             # second round: caches warm
        warm = total_recompiles()
        explain_misses = sched._explain_counts.misses
        for flag in (False, True, False, True):
            sched.explain = flag
            sched.schedule_round()
        assert total_recompiles() == warm
        assert sched._explain_counts.misses == explain_misses
        # the kernel is instrumented like every solver entry point
        assert metrics.solver_recompiles.value(
            labels={"fn": "explain_counts",
                    "shape": "P32xN64"}) >= 1.0


class TestBenchStageSmoke:
    def test_explain_overhead_stage_runs_on_cpu(self, tmp_path):
        """The bench_stages explain stages are smoke-runnable on CPU and
        emit the pct_of_solve verdict (acceptance: the overhead guard is
        a measured stage)."""
        import subprocess
        import sys

        env = dict(__import__("os").environ, JAX_PLATFORMS="cpu",
                   KOORD_STAGES_NODES="64", KOORD_STAGES_PODS="128",
                   KOORD_STAGES_METHODS="exact")
        proc = subprocess.run(
            [sys.executable, "bench_stages.py", "--smoke"],
            capture_output=True, text=True, timeout=600, env=env,
            cwd=__import__("os").path.join(
                __import__("os").path.dirname(
                    __import__("os").path.abspath(__file__)), ".."))
        assert proc.returncode == 0, proc.stderr[-2000:]
        stages = {}
        for line in proc.stdout.strip().splitlines():
            doc = json.loads(line)
            stages[doc["stage"]] = doc
        assert "provenance" in stages          # stage-promotion stamp
        assert "explain_compact_1pct" in stages
        assert "explain_full_batch" in stages
        assert "pct_of_solve" in stages["explain_compact_1pct"]
        assert "within_5pct" in stages["explain_compact_1pct"]
