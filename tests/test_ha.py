"""Leader election / HA (koordinator_tpu/ha.py) vs the reference's
Lease-based election (cmd/koord-manager/main.go --enable-leader-election;
same mechanism for scheduler and descheduler), plus the failover-restart
story: new leader rebuilds state through the startup sync barrier
(cmd/koord-scheduler/app/sync_barrier.go)."""

import threading
import time

import numpy as np

from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS, resource_vector
from koordinator_tpu.descheduler.framework import Descheduler, Profile
from koordinator_tpu.ha import (
    InMemoryLeaseStore,
    LeaderElector,
    LeaseRecord,
    leader_gated,
)
from koordinator_tpu.ops.assignment import ScoringConfig
from koordinator_tpu.scheduler import ClusterSnapshot, NodeSpec, PodSpec, Scheduler
from koordinator_tpu.scheduler.barrier import SyncBarrier

R = NUM_RESOURCE_DIMS


def electors(n, store=None, clock=None, **kw):
    store = store or InMemoryLeaseStore()
    return store, [
        LeaderElector(store, "koord-manager", f"replica-{i}",
                      clock=clock or (lambda: 0.0), **kw)
        for i in range(n)
    ]


def test_first_candidate_acquires_and_renews():
    t = [0.0]
    _, (a, b) = electors(2, clock=lambda: t[0], lease_duration=15)
    assert a.tick() is True
    assert b.tick() is False
    t[0] = 10.0            # inside the lease
    assert a.tick() is True
    assert b.tick() is False


def test_failover_after_lease_expiry():
    t = [0.0]
    events = []
    store = InMemoryLeaseStore()
    a = LeaderElector(store, "L", "a", lease_duration=15,
                      clock=lambda: t[0],
                      on_stopped_leading=lambda: events.append("a-stop"))
    b = LeaderElector(store, "L", "b", lease_duration=15,
                      clock=lambda: t[0],
                      on_started_leading=lambda: events.append("b-start"),
                      on_new_leader=lambda who: events.append(f"new:{who}"))
    assert a.tick() and not b.tick()
    # leader a stops renewing (crash); b takes over only after expiry
    t[0] = 10.0
    assert not b.tick()
    t[0] = 20.0
    assert b.tick()
    assert "b-start" in events and "new:b" in events
    # stale ex-leader comes back: sees b's live lease, demotes itself
    assert not a.tick()
    assert "a-stop" in events
    lease = store.get("L")
    assert lease.holder == "b" and lease.transitions == 2


def test_release_hands_off_immediately():
    t = [0.0]
    _, (a, b) = electors(2, clock=lambda: t[0], lease_duration=1000)
    assert a.tick()
    a.release()
    assert b.tick()          # no need to wait out the 1000s lease
    assert not a.tick()      # released elector stays stopped


def test_cas_update_rejects_stale_holder():
    store = InMemoryLeaseStore()
    store.update("L", "", LeaseRecord(holder="x", renew_time=0))
    assert not store.update("L", "y", LeaseRecord(holder="y"))
    assert store.get("L").holder == "x"


def test_leader_gated_controller_step():
    t = [0.0]
    _, (a, b) = electors(2, clock=lambda: t[0])
    runs = []
    assert leader_gated(a, lambda: runs.append("a") or 1) == 1
    assert leader_gated(b, lambda: runs.append("b") or 1) is None
    assert runs == ["a"]
    assert leader_gated(None, lambda: 2) == 2  # election disabled


def test_descheduler_replica_only_evicts_as_leader():
    t = [0.0]
    store, (a, b) = electors(2, clock=lambda: t[0], lease_duration=15)
    mk = lambda el: Descheduler([Profile(name="p")], pods_fn=lambda: [],
                                interval_seconds=0, clock=lambda: t[0],
                                elector=el)
    d_a, d_b = mk(a), mk(b)
    assert d_a.tick() == {"p": 0}
    assert d_b.tick() is None          # follower never runs plugins
    t[0] = 30.0                         # a's lease expires silently
    assert d_b.tick() == {"p": 0}      # b took over


def test_run_loop_thread_releases_on_stop():
    store = InMemoryLeaseStore()
    a = LeaderElector(store, "L", "a", retry_period=0.001)
    stop = threading.Event()
    th = threading.Thread(target=a.run, args=(stop,))
    th.start()
    deadline = time.monotonic() + 5.0
    while not a.is_leader() and time.monotonic() < deadline:
        time.sleep(0.001)
    assert a.is_leader()
    stop.set()
    th.join(timeout=5)
    assert not th.is_alive()
    assert store.get("L").holder == ""   # released


def test_failover_scheduler_restart_through_sync_barrier():
    """The HA restart story end to end: the standby wins the lease, builds a
    FRESH scheduler, and its first rounds no-op until the informer stream
    replays past the barrier mark — then it schedules correctly from the
    rebuilt snapshot."""
    t = [0.0]
    store = InMemoryLeaseStore()
    old = LeaderElector(store, "sched", "sched-0", lease_duration=15,
                        clock=lambda: t[0])
    assert old.tick()
    t[0] = 60.0   # sched-0 crashed; lease expired
    new = LeaderElector(store, "sched", "sched-1", lease_duration=15,
                        clock=lambda: t[0])
    assert new.tick()

    # the "apiserver": barrier marks bump its version; the informer lags
    apiserver = {"version": 7}
    informer = {"version": 5}

    def mark():
        apiserver["version"] += 1
        return apiserver["version"]

    snap = ClusterSnapshot(capacity=16)
    snap.upsert_node(NodeSpec(
        name="n1", allocatable=resource_vector(cpu=16_000, memory=65_536),
        usage=np.zeros(R, np.int32)))
    binds = []
    cfg = ScoringConfig.default().replace(
        usage_thresholds=np.zeros(R, np.int32),
        estimator_defaults=np.zeros(R, np.int32))
    barrier = SyncBarrier(mark=mark,
                          observed_version=lambda: informer["version"])
    barrier.start()
    sched = Scheduler(snap, config=cfg,
                      bind_fn=lambda p, n: binds.append((p, n)),
                      barrier=barrier)
    sched.enqueue(PodSpec(name="p1",
                          requests=resource_vector(cpu=1_000, memory=1_024)))
    res = sched.schedule_round()
    assert not res.assignments and not binds     # gated: cache still stale
    informer["version"] = apiserver["version"]   # replay caught up
    res = sched.schedule_round()
    assert res.assignments == {"p1": "n1"}
    assert binds == [("p1", "n1")]


def test_scheduler_rounds_gate_on_leadership():
    """server.go semantics: a standby scheduler replica syncs state but
    decides nothing until it acquires the lease; the old leader's loss
    demotes it mid-stream."""
    from koordinator_tpu.api.resources import resource_vector
    from koordinator_tpu.ha import InMemoryLeaseStore, LeaderElector
    from koordinator_tpu.scheduler import (
        ClusterSnapshot, NodeSpec, PodSpec, Scheduler,
    )

    t = [0.0]
    store = InMemoryLeaseStore()
    lead = LeaderElector(store, "sched", identity="a",
                         lease_duration=10.0, clock=lambda: t[0])
    standby = LeaderElector(store, "sched", identity="b",
                            lease_duration=10.0, clock=lambda: t[0])
    assert lead.tick() and not standby.tick()

    def mk(elector):
        snap = ClusterSnapshot(capacity=8)
        snap.upsert_node(NodeSpec(
            name="n1", allocatable=resource_vector(cpu=16_000,
                                                   memory=65_536)))
        return Scheduler(snap, elector=elector)

    leader_sched, standby_sched = mk(lead), mk(standby)
    for s in (leader_sched, standby_sched):
        s.enqueue(PodSpec(name="p1",
                          requests=resource_vector(cpu=1_000, memory=512)))
    assert leader_sched.schedule_round().assignments == {"p1": "n1"}
    assert standby_sched.schedule_round().assignments == {}
    assert "p1" in standby_sched.pending          # queue intact on standby
    # the standby's debug surface reflects standby, not stale state
    assert standby_sched.last_result.assignments == {}

    # leader dies; lease expires; the standby takes over and decides
    t[0] = 30.0
    assert standby.tick()
    assert standby_sched.schedule_round().assignments == {"p1": "n1"}
