"""bench_stages.py (the stage-split profiler) must keep working: its
predecessor lived in /tmp as scratch_timing.py and rotted away between
sessions, losing the round-3 stage-split capture recipe.  Run it as a
subprocess at a tiny shape and assert every stage emits a record —
exactly how the prober (tools/tpu_probe.sh) invokes it on hardware."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_stage_profiler_smoke():
    env = dict(os.environ, KOORD_STAGES_NODES="64", KOORD_STAGES_PODS="256",
               KOORD_STAGES_METHODS="approx,chunked")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench_stages.py"), "--smoke"],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    records = [json.loads(line) for line in proc.stdout.splitlines()]
    stages = {r["stage"] for r in records}
    assert stages == {"rtt_floor", "score", "select_approx",
                      "select_chunked", "rounds"}, stages
    by_stage = {r["stage"]: r for r in records}
    # every timed stage produced a positive per-iteration time
    for name in ("score", "select_approx", "select_chunked", "rounds"):
        assert by_stage[name]["ms_per_iter"] > 0, by_stage[name]
    # the rounds stage really assigned pods (256 pods, ample capacity)
    assert by_stage["rounds"]["assigned_per_iter"] > 0


def test_latest_probe_capture_selection(tmp_path):
    """The zero-record path promotes the prober's newest nonzero capture
    for the CURRENT metric only — zero records, wrong shapes, and
    garbage files are skipped."""
    sys.path.insert(0, REPO)
    from bench import _latest_probe_capture

    d = tmp_path / "probe_results"
    d.mkdir()
    assert _latest_probe_capture(str(d)) is None
    (d / "bench_1.json").write_text(
        '{"metric": "solve_pods_per_sec_50000p_10240n", "value": 0.0}')
    (d / "bench_2.json").write_text("not json at all")
    (d / "bench_3.json").write_text(
        '{"metric": "solve_pods_per_sec_10p_10n", "value": 99.0}')
    assert _latest_probe_capture(str(d)) is None
    (d / "bench_4.json").write_text(
        '{"metric": "solve_pods_per_sec_50000p_10240n", "value": 250001.5,'
        ' "unit": "pods/s", "vs_baseline": 1.0}')
    (d / "bench_5.json").write_text(
        '{"metric": "solve_pods_per_sec_50000p_10240n", "value": 260000.0,'
        ' "unit": "pods/s", "vs_baseline": 1.04}')
    doc, source = _latest_probe_capture(str(d))
    assert source == "bench_5.json" and doc["value"] == 260000.0
    # captures older than ~a round (12h by mtime) are from a PREVIOUS
    # round and must not be re-reported as this round's measurement
    import time as _time

    old = _time.time() - 13 * 3600
    os.utime(d / "bench_5.json", (old, old))
    doc, source = _latest_probe_capture(str(d))
    assert source == "bench_4.json"
    os.utime(d / "bench_4.json", (old, old))
    assert _latest_probe_capture(str(d)) is None
    # a record that is itself a promotion must never count as a fresh
    # capture — accepting it would launder one stale measurement into
    # every future round via its refreshed mtime
    (d / "bench_6.json").write_text(
        '{"metric": "solve_pods_per_sec_50000p_10240n", "value": 270000.0,'
        ' "unit": "pods/s", "vs_baseline": 1.08,'
        ' "extra": {"probe_capture": {"source": "bench_4.json"}}}')
    assert _latest_probe_capture(str(d)) is None
