"""bench_stages.py (the stage-split profiler) must keep working: its
predecessor lived in /tmp as scratch_timing.py and rotted away between
sessions, losing the round-3 stage-split capture recipe.  Run it as a
subprocess at a tiny shape and assert every stage emits a record —
exactly how the prober (tools/tpu_probe.sh) invokes it on hardware."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_stage_profiler_smoke():
    env = dict(os.environ, KOORD_STAGES_NODES="64", KOORD_STAGES_PODS="256",
               KOORD_STAGES_METHODS="approx,chunked")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench_stages.py"), "--smoke"],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    records = [json.loads(line) for line in proc.stdout.splitlines()]
    stages = {r["stage"] for r in records}
    assert stages == {"provenance", "rtt_floor", "score", "select_approx",
                      "select_chunked", "rounds",
                      "refresh_incremental_1pct",
                      "lp_pack_smoke", "topo_gang_rank",
                      "score_sharded", "rounds_sharded", "merge_topk",
                      "score_sharded_1d", "rounds_sharded_1d",
                      "score_sharded_2d", "rounds_sharded_2d",
                      "sharded_2d_footprint",
                      "explain_compact_1pct", "explain_full_batch",
                      "wire_codec_v1_vs_v2", "deltasync_apply_batched",
                      "bind_commit_batched",
                      "tenancy_serial", "tenancy_pipelined",
                      "tenancy_batched", "timeline_overhead",
                      "journey_ledger_overhead"}, stages
    by_stage = {r["stage"]: r for r in records}
    # every timed stage produced a positive per-iteration time
    for name in ("score", "select_approx", "select_chunked", "rounds",
                 "refresh_incremental_1pct", "lp_pack_smoke",
                 "topo_gang_rank", "score_sharded",
                 "rounds_sharded", "merge_topk",
                 "score_sharded_1d", "rounds_sharded_1d",
                 "score_sharded_2d", "rounds_sharded_2d",
                 "explain_compact_1pct",
                 "explain_full_batch", "wire_codec_v1_vs_v2",
                 "deltasync_apply_batched", "bind_commit_batched",
                 "tenancy_serial",
                 "tenancy_pipelined", "tenancy_batched"):
        assert by_stage[name]["ms_per_iter"] > 0, by_stage[name]
    # the host-plane turbo stages (ISSUE 19) record the legacy path
    # beside the batched one so bench_diff guards both inputs of the
    # speedup ratio
    assert by_stage["wire_codec_v1_vs_v2"]["v1_ms"] > 0
    assert by_stage["wire_codec_v1_vs_v2"]["speedup_vs_v1"] > 0
    assert by_stage["deltasync_apply_batched"]["per_event_ms"] > 0
    assert by_stage["deltasync_apply_batched"]["speedup_vs_per_event"] > 0
    assert by_stage["bind_commit_batched"]["per_pod_ms"] > 0
    assert by_stage["bind_commit_batched"]["speedup_vs_per_pod"] > 0
    # the quality stage reports its cost relative to the greedy rounds
    # it replaces on escalated rounds
    assert by_stage["lp_pack_smoke"]["vs_rounds_x"] > 0
    # the multi-tenant stage reports the acceptance observables: the
    # aggregate-rate ratio vs the serial baseline and the device-idle
    # fraction before/after pipelining (ISSUE 11)
    assert by_stage["tenancy_serial"]["device_idle_fraction"] is not None
    assert by_stage["tenancy_pipelined"]["speedup_vs_serial"] is not None
    assert by_stage["tenancy_pipelined"]["device_idle_fraction"] is not None
    # the stage capture stamps code provenance for later promotion
    assert "commit" in by_stage["provenance"]
    # ... and FULL 2-D mesh provenance (ISSUE 14): device count, per-axis
    # split, axis names and the PxN shape string, on the provenance line
    # and on every sharded stage record
    assert by_stage["provenance"]["n_devices"] >= 1
    assert by_stage["provenance"]["mesh_axes"]["nodes"] >= 1
    assert by_stage["provenance"]["mesh_axes"]["pods"] >= 1
    assert by_stage["provenance"]["mesh_axis_names"] == ["pods", "nodes"]
    assert "x" in by_stage["provenance"]["mesh_shape"]
    assert by_stage["score_sharded"]["n_devices"] >= 1
    assert by_stage["score_sharded"]["mesh_axes"]["nodes"] >= 1
    # the 2-D comparison stages (ISSUE 14 acceptance observables): the
    # pods-split mesh reports its throughput ratio vs the all-nodes
    # mesh, and the per-device candidate-tensor footprint scales
    # ~1/pods_axis (exactly 1/2 at pods_axis=2)
    assert by_stage["score_sharded_2d"]["mesh_axes"]["pods"] == 2
    assert by_stage["score_sharded_2d"]["speedup_vs_1d"] > 0
    assert by_stage["rounds_sharded_2d"]["speedup_vs_1d"] > 0
    fp = by_stage["sharded_2d_footprint"]
    assert fp["ratio"] <= 0.51, fp
    # the explain overhead stages price themselves against the solve
    assert "pct_of_solve" in by_stage["explain_compact_1pct"]
    assert "within_5pct" in by_stage["explain_compact_1pct"]
    # the rounds stage really assigned pods (256 pods, ample capacity)
    assert by_stage["rounds"]["assigned_per_iter"] > 0
    # the timeline self-overhead stage (ISSUE 18) reports the on/off
    # wall comparison the perf sentinel gates; the fraction can dip
    # negative on timing noise but must exist and the timed wall must
    # be real
    assert by_stage["timeline_overhead"]["ms_per_iter"] > 0
    assert by_stage["timeline_overhead"]["overhead_fraction"] is not None
    # the journey-ledger self-overhead stage (ISSUE 20) measures the
    # ledger's hot-path seconds directly (shim accounting), so unlike
    # the wall-differenced delta its fraction is a real upper bound
    assert by_stage["journey_ledger_overhead"]["ms_per_iter"] > 0
    assert by_stage["journey_ledger_overhead"]["ledger_ms_per_iter"] >= 0
    assert by_stage["journey_ledger_overhead"]["overhead_fraction"] is not None


def test_latest_probe_capture_selection(tmp_path):
    """The zero-record path promotes the prober's newest nonzero capture
    for the CURRENT metric only — zero records, wrong shapes, garbage
    files, and captures without verifiable code provenance are skipped."""
    sys.path.insert(0, REPO)
    from bench import _git_head, _latest_probe_capture

    head = _git_head()["commit"]
    assert head, "test must run inside the git repo"
    stamp = f', "extra": {{"provenance": {{"commit": "{head}"}}}}'

    d = tmp_path / "probe_results"
    d.mkdir()
    assert _latest_probe_capture(str(d)) is None
    (d / "bench_1.json").write_text(
        '{"metric": "solve_pods_per_sec_50000p_10240n", "value": 0.0'
        + stamp + '}')
    (d / "bench_2.json").write_text("not json at all")
    (d / "bench_3.json").write_text(
        '{"metric": "solve_pods_per_sec_10p_10n", "value": 99.0'
        + stamp + '}')
    assert _latest_probe_capture(str(d)) is None
    (d / "bench_4.json").write_text(
        '{"metric": "solve_pods_per_sec_50000p_10240n", "value": 250001.5,'
        ' "unit": "pods/s", "vs_baseline": 1.0' + stamp + '}')
    (d / "bench_5.json").write_text(
        '{"metric": "solve_pods_per_sec_50000p_10240n", "value": 260000.0,'
        ' "unit": "pods/s", "vs_baseline": 1.04' + stamp + '}')
    doc, source = _latest_probe_capture(str(d))
    assert source == "bench_5.json" and doc["value"] == 260000.0
    # captures older than ~a round (12h by mtime) are from a PREVIOUS
    # round and must not be re-reported as this round's measurement
    import time as _time

    old = _time.time() - 13 * 3600
    os.utime(d / "bench_5.json", (old, old))
    doc, source = _latest_probe_capture(str(d))
    assert source == "bench_4.json"
    os.utime(d / "bench_4.json", (old, old))
    assert _latest_probe_capture(str(d)) is None
    # a record that is itself a promotion must never count as a fresh
    # capture — accepting it would launder one stale measurement into
    # every future round via its refreshed mtime
    (d / "bench_6.json").write_text(
        '{"metric": "solve_pods_per_sec_50000p_10240n", "value": 270000.0,'
        ' "unit": "pods/s", "vs_baseline": 1.08,'
        ' "extra": {"probe_capture": {"source": "bench_4.json"},'
        f' "provenance": {{"commit": "{head}"}}}}')
    assert _latest_probe_capture(str(d)) is None


def test_probe_capture_commit_provenance(tmp_path):
    """VERDICT r4 weak #2: a capture measured on a DIFFERENT commit with
    solver changes in between must not become the official number — and
    an unstamped capture ties to no code at all, so it is refused with a
    recorded reason."""
    sys.path.insert(0, REPO)
    import subprocess

    from bench import _git_head, _latest_probe_capture, _solver_diff

    head = _git_head()["commit"]
    rec = ('{"metric": "solve_pods_per_sec_50000p_10240n",'
           ' "value": 250001.5, "unit": "pods/s", "vs_baseline": 1.0%s}')

    d = tmp_path / "probe_results"
    d.mkdir()
    # unstamped: refused, with a note
    (d / "bench_1.json").write_text(rec % "")
    notes = []
    assert _latest_probe_capture(str(d), notes=notes) is None
    assert notes and "unverifiable" in notes[0]
    # stamped with a commit git does not know: refused
    (d / "bench_1.json").write_text(
        rec % ', "extra": {"provenance": {"commit": "f00dfeed"}}')
    notes = []
    assert _latest_probe_capture(str(d), notes=notes) is None
    assert notes and "unverifiable" in notes[0]
    # stamped with an OLD commit that differs from HEAD by solver files:
    # refused, naming the files (koordinator_tpu/ churn is guaranteed
    # between any two round commits; pick one where the diff is nonempty)
    log = subprocess.run(
        ["git", "log", "--format=%H", "-n", "200"], capture_output=True,
        text=True, cwd=REPO).stdout.split()
    old_commit = next(
        (c for c in log[1:] if _solver_diff(c, head)), None)
    if old_commit is not None:
        (d / "bench_1.json").write_text(
            rec % f', "extra": {{"provenance": {{"commit": "{old_commit}"}}}}')
        notes = []
        assert _latest_probe_capture(str(d), notes=notes) is None
        assert notes and "solver files changed" in notes[0]
    # HEAD-stamped but captured on a DIRTY tree: the uncommitted solver
    # edits the capture measured are invisible to any commit diff, so it
    # is refused even at the same commit
    (d / "bench_1.json").write_text(
        rec % f', "extra": {{"provenance": '
              f'{{"commit": "{head}", "dirty": true}}}}')
    notes = []
    assert _latest_probe_capture(str(d), notes=notes) is None
    assert notes and "dirty tree" in notes[0]
    # HEAD-stamped and clean: promoted
    (d / "bench_1.json").write_text(
        rec % f', "extra": {{"provenance": {{"commit": "{head}"}}}}')
    doc, source = _latest_probe_capture(str(d))
    assert source == "bench_1.json" and doc["value"] == 250001.5


def test_bench_recall_smoke():
    """bench_recall.py (the prober's approx-recall capture) must keep
    producing a parseable record: tiny shape, at-shape leg off.  On CPU
    approx_max_k lowers exactly, so only the float-key quantization can
    cost recall — the mean should stay high."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", KOORD_RECALL_NODES="128",
               KOORD_RECALL_PODS="256", KOORD_RECALL_SHAPE_PODS="0")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench_recall.py")],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["backend"] == "cpu"
    assert rec["provenance"]["commit"]
    assert rec["candidate_recall_mean_256p_128n"] >= 0.8
    assert rec["assigned_frac_exact_256p_128n"] >= 0.9
    assert rec["assigned_frac_approx_256p_128n"] >= 0.9


def test_latest_probe_stages_promotion(tmp_path):
    """A recent bench_stages capture promotes into a zero record's extra
    (staged capture with provenance instead of all-or-nothing); captures
    whose commit cannot be tied to HEAD promote WITH a caveat — they are
    marked partial evidence, never refused like the headline."""
    sys.path.insert(0, REPO)
    from bench import _git_head, _latest_probe_stages

    head = _git_head()["commit"]
    d = tmp_path / "probe_results"
    d.mkdir()
    assert _latest_probe_stages(str(d)) is None
    (d / "stages_1.jsonl").write_text("\n".join([
        json.dumps({"stage": "provenance", "commit": head, "dirty": False,
                    "n_devices": 8,
                    "mesh_axes": {"pods": 1, "nodes": 8}}),
        json.dumps({"stage": "score", "ms_per_iter": 12.5}),
        json.dumps({"stage": "rounds", "ms_per_iter": 3.2}),
    ]))
    rec = _latest_probe_stages(str(d))
    assert rec["source"] == "stages_1.jsonl"
    assert rec["stages"]["score"]["ms_per_iter"] == 12.5
    assert rec["capture_commit"] == head
    # mesh-shape provenance rides the promotion (ISSUE 10)
    assert rec["n_devices"] == 8 and rec["mesh_axes"]["nodes"] == 8
    assert "caveat" not in rec
    # a NEWER unstamped capture wins but carries a caveat
    (d / "stages_2.jsonl").write_text(
        json.dumps({"stage": "score", "ms_per_iter": 1.0}))
    rec = _latest_probe_stages(str(d))
    assert rec["source"] == "stages_2.jsonl"
    assert "caveat" in rec


def test_device_alive_kinds():
    """_device_alive classifies failures into structured error kinds
    (ROADMAP item 1's diagnosis split); on the CPU test backend the
    probe must come back clean."""
    sys.path.insert(0, REPO)
    from bench import DEVICE_ERROR_KINDS, _device_alive

    assert set(DEVICE_ERROR_KINDS) == {
        "no_devices_enumerated", "probe_kernel_hung", "transfer_stall",
        "probe_error"}
    ok, kind, err = _device_alive(120.0)
    assert ok and kind == "" and err == ""
