"""bench_stages.py (the stage-split profiler) must keep working: its
predecessor lived in /tmp as scratch_timing.py and rotted away between
sessions, losing the round-3 stage-split capture recipe.  Run it as a
subprocess at a tiny shape and assert every stage emits a record —
exactly how the prober (tools/tpu_probe.sh) invokes it on hardware."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_stage_profiler_smoke():
    env = dict(os.environ, KOORD_STAGES_NODES="64", KOORD_STAGES_PODS="256",
               KOORD_STAGES_METHODS="approx,chunked")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench_stages.py"), "--smoke"],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    records = [json.loads(line) for line in proc.stdout.splitlines()]
    stages = {r["stage"] for r in records}
    assert stages == {"rtt_floor", "score", "select_approx",
                      "select_chunked", "rounds"}, stages
    by_stage = {r["stage"]: r for r in records}
    # every timed stage produced a positive per-iteration time
    for name in ("score", "select_approx", "select_chunked", "rounds"):
        assert by_stage[name]["ms_per_iter"] > 0, by_stage[name]
    # the rounds stage really assigned pods (256 pods, ample capacity)
    assert by_stage["rounds"]["assigned_per_iter"] > 0
