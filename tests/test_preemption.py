"""Preemption (PostFilter) tests.

Scenarios mirror the reference's preemption test surfaces:
- elasticquota/preempt_test.go — same-quota victim selection, canPreempt
  (non-preemptible / quota match), PDB grouping, quota-limit-driven eviction;
- coscheduling/core/preemption_test.go — job-level all-or-nothing preemption,
  lower-priority eligibility, nomination;
- upstream pickOneNodeForPreemption — lexicographic node choice.
"""

import jax.numpy as jnp
import numpy as np

from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS, ResourceDim, resource_vector
from koordinator_tpu.ops.preemption import (
    ScheduledPods,
    pick_node,
    preempt_one,
    select_victims,
)
from koordinator_tpu.state.cluster_state import ClusterState

from tests.test_scheduler import mk_scheduler, node, plain_cfg, pod

R = NUM_RESOURCE_DIMS
CPU = ResourceDim.CPU


def cluster(*alloc_cpu, requested_cpu=None):
    n = len(alloc_cpu)
    alloc = np.zeros((n, R), np.int32)
    alloc[:, CPU] = alloc_cpu
    req = np.zeros((n, R), np.int32)
    if requested_cpu is not None:
        req[:, CPU] = requested_cpu
    return ClusterState.from_arrays(alloc, requested=req)


def sched_pods(nodes, cpus, pris, **kw):
    v = len(nodes)
    req = np.zeros((v, R), np.int32)
    req[:, CPU] = cpus
    return ScheduledPods.build(
        req, np.array(nodes, np.int32), priority=np.array(pris, np.int32), **kw
    )


def req(cpu):
    return jnp.asarray(resource_vector(cpu=cpu).astype(np.int32))


NO_PDB = jnp.zeros(1, jnp.int32)


def run_select(state, sp, cpu, pri, quota=-1, feasible=None, pdb=NO_PDB, **kw):
    if feasible is None:
        feasible = jnp.ones(state.capacity, bool)
    return select_victims(
        state, sp, req(cpu), jnp.int32(pri), jnp.int32(quota), feasible, pdb, **kw
    )


class TestSelectVictims:
    def test_minimal_victim_set_keeps_most_important(self):
        # node 0: 4 cpu, full with 4x1cpu pods of priorities 40,30,20,10.
        # A 2-cpu preemptor at pri 100 needs 2 victims; reprieve
        # most-important-first keeps 40 and 30, evicts 20 and 10.
        state = cluster(4_000, requested_cpu=[4_000])
        sp = sched_pods([0, 0, 0, 0], [1_000] * 4, [40, 30, 20, 10])
        out = run_select(state, sp, 2_000, 100)
        assert bool(out.eligible[0])
        assert np.asarray(out.victim)[:4].tolist() == [False, False, True, True]
        assert int(out.num_victims[0]) == 2

    def test_higher_priority_pods_never_victims(self):
        state = cluster(4_000, requested_cpu=[4_000])
        sp = sched_pods([0, 0], [2_000, 2_000], [200, 300])
        out = run_select(state, sp, 2_000, 100)
        assert not bool(out.eligible[0])
        assert not np.asarray(out.victim).any()

    def test_non_preemptible_excluded(self):
        # canPreempt: extension.IsPodNonPreemptible victims are skipped
        state = cluster(4_000, requested_cpu=[4_000])
        sp = sched_pods(
            [0, 0], [2_000, 2_000], [10, 10],
            non_preemptible=np.array([True, False]),
        )
        out = run_select(state, sp, 4_000, 100)
        # only one candidate (1x2cpu) but preemptor needs 4 -> not eligible
        assert not bool(out.eligible[0])
        out2 = run_select(state, sp, 2_000, 100)
        assert bool(out2.eligible[0])
        assert np.asarray(out2.victim)[:2].tolist() == [False, True]

    def test_same_quota_only(self):
        # canPreempt: podQuotaName == vicQuotaName (preempt.go:309)
        state = cluster(4_000, requested_cpu=[4_000])
        sp = sched_pods(
            [0, 0], [2_000, 2_000], [10, 10],
            quota_id=np.array([0, 1], np.int32),
        )
        out = run_select(
            state, sp, 2_000, 100, quota=0,
            quota_headroom=jnp.full(R, 2**30 - 1, jnp.int32),
            same_quota_only=True,
        )
        assert bool(out.eligible[0])
        assert np.asarray(out.victim)[:2].tolist() == [True, False]

    def test_quota_limit_forces_extra_victims(self):
        # reprievePod's usedLimit check: the node has room, but the quota is
        # at its runtime limit, so same-quota victims must free quota too.
        state = cluster(10_000, requested_cpu=[2_000])
        sp = sched_pods(
            [0, 0], [1_000, 1_000], [10, 20],
            quota_id=np.array([0, 0], np.int32),
        )
        headroom = jnp.zeros(R, jnp.int32)  # used == runtime
        out = run_select(
            state, sp, 2_000, 100, quota=0, quota_headroom=headroom,
            same_quota_only=True,
        )
        # both pods evicted despite 8 cpu free on the node
        assert bool(out.eligible[0])
        assert np.asarray(out.victim)[:2].tolist() == [True, True]

    def test_node_without_candidates_ineligible(self):
        # "No victims found" -> UnschedulableAndUnresolvable (preempt.go:152)
        state = cluster(4_000, 4_000, requested_cpu=[4_000, 0])
        sp = sched_pods([0], [4_000], [10])
        out = run_select(state, sp, 2_000, 100)
        assert bool(out.eligible[0])
        assert not bool(out.eligible[1])  # empty node: nothing to preempt
        # (the pod would have scheduled there in the main solve if it fit)

    def test_affinity_failure_not_fixed_by_preemption(self):
        state = cluster(4_000, requested_cpu=[4_000])
        sp = sched_pods([0], [4_000], [10])
        feasible = jnp.zeros(state.capacity, bool)
        out = run_select(state, sp, 2_000, 100, feasible=feasible)
        assert not bool(out.eligible[0])


class TestPdb:
    def test_pdb_budget_marks_violating(self):
        # one PDB covering both candidates with 1 disruption allowed: the
        # second (less important) match is violating; chosen node pays 1
        # violation only if both must go.
        state = cluster(4_000, requested_cpu=[4_000])
        sp = sched_pods(
            [0, 0, 0, 0], [1_000] * 4, [40, 30, 20, 10],
            pdb_id=np.array([0, 0, 0, 0], np.int32),
        )
        pdb = jnp.array([1], jnp.int32)
        out = run_select(state, sp, 2_000, 100, pdb=pdb)
        viol = np.asarray(out.violating)[:4]
        # importance order 40,30,20,10 -> first match ok, rest violating
        assert viol.tolist() == [False, True, True, True]
        assert bool(out.eligible[0])
        # violating candidates are reprieved first: 30 and 20 come back
        # before non-violating 40; victims minimize violations
        assert int(out.num_violating[0]) <= 2

    def test_pick_node_prefers_fewer_violations(self):
        # node 0 victims violate a PDB, node 1 victims do not -> node 1 wins
        # even though both fit.
        state = cluster(4_000, 4_000, requested_cpu=[4_000, 4_000])
        sp = sched_pods(
            [0, 1], [2_000, 2_000], [10, 10],
            pdb_id=np.array([0, -1], np.int32),
        )
        pdb = jnp.array([0], jnp.int32)  # no disruptions allowed
        out = run_select(state, sp, 2_000, 100, pdb=pdb)
        assert bool(out.eligible[0]) and bool(out.eligible[1])
        assert int(pick_node(out)) == 1

    def test_pick_node_prefers_lower_victim_priority(self):
        # equal violations: lowest highest-victim-priority wins
        state = cluster(4_000, 4_000, requested_cpu=[4_000, 4_000])
        sp = sched_pods([0, 1], [2_000, 2_000], [50, 10])
        out = run_select(state, sp, 2_000, 100)
        assert int(pick_node(out)) == 1


class TestPreemptOne:
    def test_commit_updates_state_and_pdb(self):
        state = cluster(4_000, requested_cpu=[4_000])
        sp = sched_pods(
            [0, 0], [2_000, 2_000], [10, 20],
            pdb_id=np.array([0, -1], np.int32),
        )
        pdb = jnp.array([5], jnp.int32)
        out = preempt_one(
            state, sp, req(2_000), jnp.int32(100), jnp.int32(-1),
            jnp.ones(state.capacity, bool), pdb,
        )
        assert int(out.node) == 0
        victims = np.asarray(out.victims)[:2]
        assert victims.tolist() == [True, False]  # keep the more important
        # victim's 2 cpu freed, preemptor's 2 cpu nominated
        assert int(out.state.node_requested[0, CPU]) == 4_000
        assert not bool(out.sched.valid[0])
        assert bool(out.sched.valid[1])
        assert int(out.pdb_allowed[0]) == 4

    def test_no_help_returns_minus_one(self):
        state = cluster(4_000, requested_cpu=[4_000])
        sp = sched_pods([0], [1_000], [500])
        out = preempt_one(
            state, sp, req(2_000), jnp.int32(100), jnp.int32(-1),
            jnp.ones(state.capacity, bool), NO_PDB,
        )
        assert int(out.node) == -1
        assert not np.asarray(out.victims).any()


class TestSchedulerPostFilter:
    # enable_preemption defaults to off unless a preempt_fn is wired (the
    # scheduler must not free accounting for pods nothing evicts); tests
    # opt in explicitly.
    def bind_all(self, sched, pods):
        for p in pods:
            sched.enqueue(p)
        res = sched.schedule_round()
        assert not res.failures, res.failures
        return res

    def test_preempt_then_bind_next_round(self):
        sched, _ = mk_scheduler([node("n1", cpu=4_000)], enable_preemption=True)
        self.bind_all(sched, [
            pod("low-a", cpu=2_000, priority=10),
            pod("low-b", cpu=2_000, priority=20),
        ])
        evictions = []
        sched.preempt_fn = lambda v, by: evictions.append((v, by))
        sched.enqueue(pod("high", cpu=2_000, priority=9_500))
        res = sched.schedule_round()
        assert "high" in res.failures
        node_name, victims = res.nominations["high"]
        assert node_name == "n1"
        assert victims == ["low-a"]  # least important evicted
        assert evictions == [("low-a", "high")]
        assert "fits on n1 after preempting [low-a]" in \
            res.failures["high"].message()
        assert "low-a" not in sched.bound
        # next round: the nominated pod lands on the freed node
        res2 = sched.schedule_round()
        assert res2.assignments == {"high": "n1"}
        assert not sched.nominations

    def test_preemption_policy_never(self):
        sched, _ = mk_scheduler([node("n1", cpu=4_000)], enable_preemption=True)
        self.bind_all(sched, [pod("low", cpu=4_000, priority=10)])
        sched.enqueue(pod("high", cpu=2_000, priority=9_500,
                          preemption_policy="Never"))
        res = sched.schedule_round()
        assert "high" in res.failures
        assert not res.nominations
        assert "low" in sched.bound

    def test_pdb_respected_in_eviction_accounting(self):
        from koordinator_tpu.scheduler.scheduler import PdbRecord

        sched, _ = mk_scheduler([node("n1", cpu=4_000)], enable_preemption=True)
        sched.register_pdb(PdbRecord("pdb1", {"app": "web"}, allowed=1))
        self.bind_all(sched, [
            pod("web-a", cpu=2_000, priority=10, labels={"app": "web"}),
            pod("web-b", cpu=2_000, priority=20, labels={"app": "web"}),
        ])
        sched.enqueue(pod("high", cpu=2_000, priority=9_500))
        res = sched.schedule_round()
        # budget allows 1 disruption: web-a (2nd match in importance order)
        # would be the violating eviction, so it is reprieved FIRST and the
        # in-budget web-b is evicted instead — PDB safety beats priority in
        # the reprieve order (filterPodsWithPDBViolation + reprieve loop).
        assert res.nominations["high"][1] == ["web-b"]
        assert sched.pdbs["pdb1"].allowed == 0

    def test_gang_preemption_all_or_nothing(self):
        from koordinator_tpu.scheduler.scheduler import GangRecord

        sched, _ = mk_scheduler(
            [node("n1", cpu=4_000), node("n2", cpu=4_000)],
            enable_preemption=True,
        )
        self.bind_all(sched, [
            pod("low-1", cpu=4_000, priority=10),
            pod("low-2", cpu=4_000, priority=10),
        ])
        sched.register_gang(GangRecord("job", min_member=2))
        sched.enqueue(pod("g1", cpu=4_000, priority=9_000, gang="job"))
        sched.enqueue(pod("g2", cpu=4_000, priority=9_000, gang="job"))
        res = sched.schedule_round()
        # both members preempt: one victim per node
        assert set(res.nominations) == {"g1", "g2"}
        all_victims = sorted(
            v for _, vs in res.nominations.values() for v in vs
        )
        assert all_victims == ["low-1", "low-2"]
        res2 = sched.schedule_round()
        assert set(res2.assignments) == {"g1", "g2"}

    def test_gang_preemption_fails_atomically(self):
        # only one node's victims can be preempted (the other node's pod is
        # non-preemptible): the gang needs both -> nothing is evicted
        sched, _ = mk_scheduler(
            [node("n1", cpu=4_000), node("n2", cpu=4_000)],
            enable_preemption=True,
        )
        self.bind_all(sched, [
            pod("low-1", cpu=4_000, priority=10),
            pod("hard", cpu=4_000, priority=10, non_preemptible=True),
        ])
        from koordinator_tpu.scheduler.scheduler import GangRecord

        sched.register_gang(GangRecord("job", min_member=2))
        sched.enqueue(pod("g1", cpu=4_000, priority=9_000, gang="job"))
        sched.enqueue(pod("g2", cpu=4_000, priority=9_000, gang="job"))
        res = sched.schedule_round()
        assert not res.nominations
        assert set(sched.bound) == {"low-1", "hard"}

    def test_unchecked_dim_deficit_does_not_block_preemption(self):
        # a quota declaring only cpu in max must not have preemption blocked
        # by a memory "deficit" (runtime < used on the undeclared dim)
        total = np.zeros(R, np.int64)
        total[CPU] = 4_000
        from koordinator_tpu.quota.tree import UNBOUNDED, QuotaTree

        tree = QuotaTree(total)
        mx = resource_vector(cpu=4_000).astype(np.int64)
        mx[1] = UNBOUNDED  # memory undeclared in max -> unchecked dim
        tree.add("q", min=resource_vector(cpu=4_000).astype(np.int64), max=mx)
        sched, _ = mk_scheduler(
            [node("n1", cpu=4_000)], quota_tree=tree, enable_preemption=True,
        )
        # the bound pod uses memory (undeclared dim) freely
        self.bind_all(sched, [pod("low", cpu=4_000, mem=2_048,
                                  priority=10, quota="q")])
        sched.enqueue(pod("high", cpu=4_000, mem=2_048,
                          priority=9_500, quota="q"))
        res = sched.schedule_round()
        assert res.nominations["high"][1] == ["low"]

    def test_gang_quota_headroom_not_double_spent(self):
        # two gang members of the same quota: the second member's dry run
        # must see the first member's nominated request charged
        total = np.zeros(R, np.int64)
        total[CPU] = 4_000
        from koordinator_tpu.quota.tree import QuotaTree

        tree = QuotaTree(total)
        tree.add("q", min=resource_vector(cpu=4_000).astype(np.int64),
                 max=resource_vector(cpu=4_000).astype(np.int64))
        sched, _ = mk_scheduler(
            [node("n1", cpu=8_000), node("n2", cpu=8_000)],
            quota_tree=tree, enable_preemption=True,
        )
        self.bind_all(sched, [
            pod("low-1", cpu=2_000, mem=0, priority=10, quota="q"),
            pod("low-2", cpu=2_000, mem=0, priority=10, quota="q"),
        ])
        from koordinator_tpu.scheduler.scheduler import GangRecord

        sched.register_gang(GangRecord("job", min_member=2))
        # each member needs 4k cpu quota; quota runtime is 4k total, victims
        # free 2k each -> only ONE member can ever fit the quota; the gang
        # must fail atomically with no evictions
        sched.enqueue(pod("g1", cpu=4_000, mem=0, priority=9_000,
                          gang="job", quota="q"))
        sched.enqueue(pod("g2", cpu=4_000, mem=0, priority=9_000,
                          gang="job", quota="q"))
        res = sched.schedule_round()
        assert not res.nominations
        assert set(sched.bound) == {"low-1", "low-2"}

    def test_nominated_gang_resolves_all_or_nothing(self):
        # both members nominated; one nominated node vanishes before the next
        # round -> NEITHER member binds (no partial gang below minMember)
        from koordinator_tpu.scheduler.scheduler import GangRecord

        sched, _ = mk_scheduler(
            [node("n1", cpu=4_000), node("n2", cpu=4_000)],
            enable_preemption=True,
        )
        self.bind_all(sched, [
            pod("low-1", cpu=4_000, priority=10),
            pod("low-2", cpu=4_000, priority=10),
        ])
        sched.register_gang(GangRecord("job", min_member=2))
        sched.enqueue(pod("g1", cpu=4_000, priority=9_000, gang="job"))
        sched.enqueue(pod("g2", cpu=4_000, priority=9_000, gang="job"))
        res = sched.schedule_round()
        assert set(res.nominations) == {"g1", "g2"}
        victim_node = res.nominations["g1"][0]
        other_node = res.nominations["g2"][0]
        assert {victim_node, other_node} == {"n1", "n2"}
        sched.snapshot.remove_node(other_node)  # g2's node vanishes
        res2 = sched.schedule_round()
        assert "g1" not in res2.assignments
        assert "g2" not in res2.assignments
        assert not sched.nominations  # released, will retry from scratch

    def test_multiple_pdbs_all_decremented(self):
        from koordinator_tpu.scheduler.scheduler import PdbRecord

        sched, _ = mk_scheduler([node("n1", cpu=4_000)], enable_preemption=True)
        sched.register_pdb(PdbRecord("pdb-a", {"app": "web"}, allowed=3))
        sched.register_pdb(PdbRecord("pdb-b", {"app": "web"}, allowed=2))
        self.bind_all(sched, [
            pod("low", cpu=4_000, priority=10, labels={"app": "web"}),
        ])
        sched.enqueue(pod("high", cpu=4_000, priority=9_500))
        res = sched.schedule_round()
        assert res.nominations["high"][1] == ["low"]
        assert sched.pdbs["pdb-a"].allowed == 2
        assert sched.pdbs["pdb-b"].allowed == 1

    def test_nominated_capacity_protected_from_other_pods(self):
        # the preemptor's resources are assumed on the nominated node: an
        # equal-priority pod enqueued later must NOT steal the freed capacity
        sched, _ = mk_scheduler([node("n1", cpu=4_000)], enable_preemption=True)
        self.bind_all(sched, [pod("low", cpu=4_000, priority=10)])
        sched.enqueue(pod("high", cpu=4_000, priority=9_500))
        res = sched.schedule_round()
        assert res.nominations["high"][1] == ["low"]
        # a rival created "earlier" (creation=0 vs default) at same priority
        sched.enqueue(pod("rival", cpu=4_000, priority=9_500, creation=-1.0))
        res2 = sched.schedule_round()
        assert res2.assignments.get("high") == "n1"
        assert "rival" in res2.failures

    def test_dequeue_clears_nomination_and_reservation(self):
        sched, _ = mk_scheduler([node("n1", cpu=4_000)], enable_preemption=True)
        self.bind_all(sched, [pod("low", cpu=4_000, priority=10)])
        sched.enqueue(pod("high", cpu=4_000, priority=9_500))
        sched.schedule_round()
        assert "high" in sched.nominations
        sched.dequeue("high")  # user deletes the preemptor
        assert not sched.nominations
        # the assumed reservation is released: another pod can use the node
        sched.enqueue(pod("other", cpu=4_000, priority=100))
        res = sched.schedule_round()
        assert res.assignments == {"other": "n1"}

    def test_quota_preemption_same_quota_victims(self):
        import numpy as np

        from koordinator_tpu.quota.tree import QuotaTree

        total = np.zeros(R, np.int64)
        total[CPU] = 8_000
        tree = QuotaTree(total)
        tree.add("team-a", min=resource_vector(cpu=4_000).astype(np.int64),
                 max=resource_vector(cpu=4_000).astype(np.int64))
        tree.add("team-b", min=resource_vector(cpu=4_000).astype(np.int64),
                 max=resource_vector(cpu=4_000).astype(np.int64))
        sched, _ = mk_scheduler(
            [node("n1", cpu=16_000)], quota_tree=tree, enable_preemption=True,
        )
        self.bind_all(sched, [
            pod("a-low", cpu=4_000, mem=0, priority=10, quota="team-a"),
            pod("b-low", cpu=4_000, mem=0, priority=10, quota="team-b"),
        ])
        # team-a is at its limit; a higher-pri team-a pod preempts ONLY the
        # team-a victim even though the node has free cpu
        sched.enqueue(pod("a-high", cpu=4_000, mem=0, priority=9_500, quota="team-a"))
        res = sched.schedule_round()
        assert res.nominations["a-high"][1] == ["a-low"]
        assert "b-low" in sched.bound
        res2 = sched.schedule_round()
        assert res2.assignments == {"a-high": "n1"}


class TestPreemptChain:
    """preempt_chain == sequential preempt_one + host commit (VERDICT r2
    item 4: batched PostFilter), plus the scheduler-level round budget."""

    def _chain_problem(self, seed=0, n_nodes=6, n_bound=24, n_fail=8):
        rng = np.random.default_rng(seed)
        alloc = rng.integers(4_000, 12_000, n_nodes).astype(np.int32)
        bound_nodes = rng.integers(0, n_nodes, n_bound)
        bound_cpu = rng.integers(500, 3_000, n_bound).astype(np.int32)
        requested = np.zeros(n_nodes, np.int32)
        for nd, c in zip(bound_nodes, bound_cpu):
            requested[nd] += c
        requested = np.minimum(requested, alloc)
        state = cluster(*alloc.tolist(), requested_cpu=requested.tolist())
        sp = sched_pods(
            bound_nodes.tolist(), bound_cpu.tolist(),
            rng.integers(10, 90, n_bound).tolist(),
            quota_id=rng.integers(-1, 3, n_bound).astype(np.int32),
        )
        reqs = np.zeros((n_fail, R), np.int32)
        reqs[:, CPU] = rng.integers(2_000, 6_000, n_fail)
        pris = rng.integers(5_000, 9_000, n_fail).astype(np.int32)
        qids = rng.integers(-1, 3, n_fail).astype(np.int32)
        same_q = qids >= 0
        feas = rng.random((n_fail, state.capacity)) < 0.9
        base_hr = rng.integers(-2_000, 20_000,
                               (3, R)).astype(np.int32)
        pdb = jnp.zeros(1, jnp.int32)
        return state, sp, reqs, pris, qids, feas, same_q, base_hr, pdb

    def test_chain_matches_sequential(self):
        from koordinator_tpu.ops.preemption import (
            HEADROOM_OPEN,
            preempt_chain,
        )

        for seed in range(4):
            (state, sp, reqs, pris, qids, feas, same_q, base_hr,
             pdb) = self._chain_problem(seed=seed)
            n_fail = reqs.shape[0]
            out = preempt_chain(
                state, sp, jnp.asarray(reqs), jnp.asarray(pris),
                jnp.asarray(qids), jnp.asarray(feas),
                jnp.asarray(same_q), jnp.ones(n_fail, bool), pdb,
                jnp.asarray(base_hr),
            )
            # sequential reference: preempt_one per pod, with the same
            # commit-mirror quota accounting the chain carries
            cur_state, cur_sched, cur_pdb = state, sp, pdb
            assumed = np.zeros_like(base_hr)
            want_nodes = []
            want_victims = []
            for j in range(n_fail):
                qid = int(qids[j])
                if same_q[j]:
                    hr = np.clip(base_hr[qid] - assumed[qid],
                                 -HEADROOM_OPEN, HEADROOM_OPEN)
                else:
                    hr = np.full(R, HEADROOM_OPEN, np.int32)
                o = preempt_one(
                    cur_state, cur_sched, jnp.asarray(reqs[j]),
                    jnp.int32(pris[j]), jnp.int32(qid),
                    jnp.asarray(feas[j]), cur_pdb,
                    quota_headroom=jnp.asarray(hr.astype(np.int32)),
                    same_quota_only=bool(same_q[j]),
                )
                nd = int(o.node)
                want_nodes.append(nd)
                if nd < 0:
                    want_victims.append(np.zeros(sp.capacity, bool))
                    continue
                chosen = np.asarray(o.victims)
                want_victims.append(chosen)
                vq = np.asarray(cur_sched.quota_id)
                for v in np.flatnonzero(chosen):
                    if vq[v] >= 0:
                        assumed[vq[v]] -= np.asarray(sp.requests)[v]
                if qid >= 0:
                    assumed[qid] += reqs[j]
                cur_state, cur_sched, cur_pdb = o.state, o.sched, o.pdb_allowed
            assert np.asarray(out.node).tolist() == want_nodes, seed
            np.testing.assert_array_equal(
                np.asarray(out.victims), np.stack(want_victims))
            np.testing.assert_array_equal(
                np.asarray(out.state.node_requested),
                np.asarray(cur_state.node_requested))
            np.testing.assert_array_equal(
                np.asarray(out.sched.valid), np.asarray(cur_sched.valid))
            np.testing.assert_array_equal(
                np.asarray(out.pdb_allowed), np.asarray(cur_pdb))

    def test_inactive_rows_leave_carry_untouched(self):
        from koordinator_tpu.ops.preemption import preempt_chain

        (state, sp, reqs, pris, qids, feas, same_q, base_hr,
         pdb) = self._chain_problem(seed=5)
        n_fail = reqs.shape[0]
        active = np.zeros(n_fail, bool)
        active[0] = True
        out = preempt_chain(
            state, sp, jnp.asarray(reqs), jnp.asarray(pris),
            jnp.asarray(qids), jnp.asarray(feas), jnp.asarray(same_q),
            jnp.asarray(active), pdb, jnp.asarray(base_hr),
        )
        assert np.all(np.asarray(out.node)[1:] == -1)
        assert not np.asarray(out.victims)[1:].any()


class TestPreemptionBudget:
    def test_round_cap_bounds_preemptors(self):
        # 6 failed singles, cap 2: only the 2 highest-priority pods get
        # nominations this round; the rest stay failed and retry later
        sched, _ = mk_scheduler(
            [node(f"n{i}", cpu=4_000) for i in range(6)],
            enable_preemption=True,
        )
        sched.preempt_cap = 2
        for i in range(6):
            sched.enqueue(pod(f"low-{i}", cpu=4_000, priority=10))
        res = sched.schedule_round()
        assert not res.failures
        for i in range(6):
            sched.enqueue(pod(f"high-{i}", cpu=4_000,
                              priority=9_000 + 100 * i))
        res = sched.schedule_round()
        assert len(res.nominations) == 2
        # highest-priority failed pods won the budget
        assert set(res.nominations) == {"high-5", "high-4"}
        # next round the remaining pods get their turn
        res2 = sched.schedule_round()
        assert len(res2.nominations) == 2

    def test_chunked_singles_one_dispatch(self, monkeypatch):
        # consecutive single-pod preemptors ride ONE chain dispatch
        sched, _ = mk_scheduler(
            [node(f"n{i}", cpu=4_000) for i in range(4)],
            enable_preemption=True,
        )
        for i in range(4):
            sched.enqueue(pod(f"low-{i}", cpu=4_000, priority=10))
        assert not sched.schedule_round().failures
        calls = {"chain": 0, "one": 0}
        real_chain = sched._preempt_chain
        real_one = sched._preempt
        sched._preempt_chain = (
            lambda *a, **k: (calls.__setitem__("chain", calls["chain"] + 1)
                             or real_chain(*a, **k)))
        sched._preempt = (
            lambda *a, **k: (calls.__setitem__("one", calls["one"] + 1)
                             or real_one(*a, **k)))
        for i in range(4):
            sched.enqueue(pod(f"high-{i}", cpu=4_000, priority=9_000))
        res = sched.schedule_round()
        assert len(res.nominations) == 4
        assert calls == {"chain": 1, "one": 0}
