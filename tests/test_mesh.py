"""Sharded solve == unsharded solve on the virtual 8-device mesh."""

import jax
import numpy as np

from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS, ResourceDim
from koordinator_tpu.ops.assignment import ScoringConfig, greedy_assign, score_pods
from koordinator_tpu.parallel import mesh as pmesh
from koordinator_tpu.state.cluster_state import ClusterState, PodBatch

R = NUM_RESOURCE_DIMS
CPU, MEM = ResourceDim.CPU, ResourceDim.MEMORY


def build_problem(n_nodes=64, n_pods=32, seed=3):
    rng = np.random.default_rng(seed)
    alloc = np.zeros((n_nodes, R), np.int32)
    alloc[:, CPU] = rng.integers(8_000, 64_000, n_nodes)
    alloc[:, MEM] = rng.integers(16_384, 262_144, n_nodes)
    usage = (alloc * rng.random((n_nodes, R)) * 0.5).astype(np.int32)
    state = ClusterState.from_arrays(alloc, usage=usage, capacity=n_nodes)
    req = np.zeros((n_pods, R), np.int32)
    req[:, CPU] = rng.integers(100, 4_000, n_pods)
    req[:, MEM] = rng.integers(128, 8_192, n_pods)
    prio = rng.integers(3000, 9999, n_pods).astype(np.int32)
    pods = PodBatch.build(req, priority=prio, node_capacity=n_nodes, capacity=n_pods)
    return state, pods


def test_eight_virtual_devices():
    assert len(jax.devices()) == 8


def test_sharded_score_matches_unsharded():
    state, pods = build_problem()
    cfg = ScoringConfig.default()
    scores_ref, feas_ref = jax.jit(score_pods)(state, pods, cfg)

    mesh = pmesh.solver_mesh(pods_axis=2)
    sstate = pmesh.shard_cluster_state(state, mesh)
    spods = pmesh.shard_pod_batch(pods, mesh)
    scores_sh, feas_sh = jax.jit(score_pods)(sstate, spods, cfg)

    assert np.array_equal(np.asarray(scores_ref), np.asarray(scores_sh))
    assert np.array_equal(np.asarray(feas_ref), np.asarray(feas_sh))


def test_sharded_greedy_assign_matches_unsharded():
    state, pods = build_problem()
    cfg = ScoringConfig.default()
    a_ref, st_ref, _ = jax.jit(greedy_assign)(state, pods, cfg)

    mesh = pmesh.solver_mesh()  # all devices on the nodes axis
    sstate = pmesh.shard_cluster_state(state, mesh)
    a_sh, st_sh, _ = jax.jit(greedy_assign)(sstate, pods, cfg)

    assert np.array_equal(np.asarray(a_ref), np.asarray(a_sh))
    assert np.array_equal(
        np.asarray(st_ref.node_requested), np.asarray(st_sh.node_requested)
    )
