"""Sharded solve == unsharded solve on the virtual 8-device mesh."""

import jax
import numpy as np

from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS, ResourceDim
from koordinator_tpu.ops.assignment import ScoringConfig, greedy_assign, score_pods
from koordinator_tpu.parallel import mesh as pmesh
from koordinator_tpu.state.cluster_state import ClusterState, PodBatch

R = NUM_RESOURCE_DIMS
CPU, MEM = ResourceDim.CPU, ResourceDim.MEMORY


def build_problem(n_nodes=64, n_pods=32, seed=3):
    rng = np.random.default_rng(seed)
    alloc = np.zeros((n_nodes, R), np.int32)
    alloc[:, CPU] = rng.integers(8_000, 64_000, n_nodes)
    alloc[:, MEM] = rng.integers(16_384, 262_144, n_nodes)
    usage = (alloc * rng.random((n_nodes, R)) * 0.5).astype(np.int32)
    state = ClusterState.from_arrays(alloc, usage=usage, capacity=n_nodes)
    req = np.zeros((n_pods, R), np.int32)
    req[:, CPU] = rng.integers(100, 4_000, n_pods)
    req[:, MEM] = rng.integers(128, 8_192, n_pods)
    prio = rng.integers(3000, 9999, n_pods).astype(np.int32)
    pods = PodBatch.build(req, priority=prio, node_capacity=n_nodes, capacity=n_pods)
    return state, pods


def test_eight_virtual_devices():
    assert len(jax.devices()) == 8


def test_resolve_solver_mesh_2d_env_overrides(monkeypatch):
    """ISSUE 14: KOORD_SOLVER_MESH=PxN builds the explicit 2-D mesh;
    KOORD_SOLVER_MESH_PODS splits the pods axis off "auto"; the default
    (pods_axis=1) reproduces today's all-nodes layout exactly."""
    monkeypatch.delenv("KOORD_SOLVER_MESH", raising=False)
    monkeypatch.delenv("KOORD_SOLVER_MESH_PODS", raising=False)
    default = pmesh.resolve_solver_mesh("auto")
    assert default == pmesh.solver_mesh(pods_axis=1)
    assert pmesh.mesh_axes(default) == {"pods": 1, "nodes": 8}

    monkeypatch.setenv("KOORD_SOLVER_MESH", "2x4")
    m = pmesh.resolve_solver_mesh("auto")
    assert pmesh.mesh_axes(m) == {"pods": 2, "nodes": 4}
    assert pmesh.pods_shard_count(m) == 2
    assert pmesh.nodes_shard_count(m) == 4

    monkeypatch.setenv("KOORD_SOLVER_MESH", "4x4")
    import pytest

    with pytest.raises(ValueError, match="needs 16 devices"):
        pmesh.resolve_solver_mesh("auto")

    monkeypatch.delenv("KOORD_SOLVER_MESH")
    monkeypatch.setenv("KOORD_SOLVER_MESH_PODS", "4")
    m = pmesh.resolve_solver_mesh("auto")
    assert pmesh.mesh_axes(m) == {"pods": 4, "nodes": 2}

    assert pmesh.mesh_axes(None) is None
    assert pmesh.pods_shard_count(None) == 1


def test_sharded_score_matches_unsharded():
    state, pods = build_problem()
    cfg = ScoringConfig.default()
    scores_ref, feas_ref = jax.jit(score_pods)(state, pods, cfg)

    mesh = pmesh.solver_mesh(pods_axis=2)
    sstate = pmesh.shard_cluster_state(state, mesh)
    spods = pmesh.shard_pod_batch(pods, mesh)
    scores_sh, feas_sh = jax.jit(score_pods)(sstate, spods, cfg)

    assert np.array_equal(np.asarray(scores_ref), np.asarray(scores_sh))
    assert np.array_equal(np.asarray(feas_ref), np.asarray(feas_sh))


def test_sharded_greedy_assign_matches_unsharded():
    state, pods = build_problem()
    cfg = ScoringConfig.default()
    a_ref, st_ref, _ = jax.jit(greedy_assign)(state, pods, cfg)

    mesh = pmesh.solver_mesh()  # all devices on the nodes axis
    sstate = pmesh.shard_cluster_state(state, mesh)
    a_sh, st_sh, _ = jax.jit(greedy_assign)(sstate, pods, cfg)

    assert np.array_equal(np.asarray(a_ref), np.asarray(a_sh))
    assert np.array_equal(
        np.asarray(st_ref.node_requested), np.asarray(st_sh.node_requested)
    )


def test_sharded_batch_assign_matches_unsharded():
    state, pods = build_problem()
    cfg = ScoringConfig.default()
    from koordinator_tpu.ops.batch_assign import batch_assign

    f = jax.jit(batch_assign, static_argnames=("k", "rounds"))
    a_ref, st_ref, _ = f(state, pods, cfg, k=8, rounds=4)

    mesh = pmesh.solver_mesh(pods_axis=2)
    sstate = pmesh.shard_cluster_state(state, mesh)
    spods = pmesh.shard_pod_batch(pods, mesh)
    a_sh, st_sh, _ = f(sstate, spods, cfg, k=8, rounds=4)

    assert np.array_equal(np.asarray(a_ref), np.asarray(a_sh))
    assert np.array_equal(
        np.asarray(st_ref.node_requested), np.asarray(st_sh.node_requested)
    )


def test_sharded_batch_assign_matches_across_shard_counts():
    """Node-axis GSPMD placement at 2/4/8-way widths: the whole batch
    solve is width-invariant, not just 8-way (the shard_map path has its
    own 1/2/4/8 sweep in tests/test_sharded_solve.py)."""
    state, pods = build_problem()
    cfg = ScoringConfig.default()
    from koordinator_tpu.ops.batch_assign import batch_assign

    f = jax.jit(batch_assign, static_argnames=("k", "rounds"))
    a_ref, st_ref, _ = f(state, pods, cfg, k=8, rounds=4)
    for d in (2, 8):
        mesh = pmesh.solver_mesh(jax.devices()[:d])
        sstate = pmesh.shard_cluster_state(state, mesh)
        a_sh, st_sh, _ = f(sstate, pods, cfg, k=8, rounds=4)
        assert np.array_equal(np.asarray(a_ref), np.asarray(a_sh)), d
        assert np.array_equal(
            np.asarray(st_ref.node_requested),
            np.asarray(st_sh.node_requested)), d


def test_sharded_reservation_assign_matches_unsharded():
    """Reservation-first exact solve on the mesh == single-device
    (ISSUE 10 satellite: reservation solves join the parity suite)."""
    from koordinator_tpu.ops.reservation import (
        ReservationSet,
        reservation_greedy_assign,
    )

    state, pods = build_problem(n_pods=24)
    cfg = ScoringConfig.default()
    n_rsv = 4
    rsv_req = np.zeros((n_rsv, R), np.int32)
    rsv_req[:, CPU] = 4_000
    rsv_req[:, MEM] = 8_192
    rsv = ReservationSet.build(rsv_req, np.arange(n_rsv, dtype=np.int32))
    match = np.zeros((pods.capacity, rsv.capacity), bool)
    match[:8, :n_rsv] = True
    f = jax.jit(reservation_greedy_assign)
    ref = f(state, pods, cfg, rsv, match)
    mesh = pmesh.solver_mesh()
    sstate = pmesh.shard_cluster_state(state, mesh)
    got = f(sstate, pods, cfg, pmesh.shard_reservation_set(rsv, mesh),
            match)
    for i, name in enumerate(("assignments", "rsv_choice")):
        assert np.array_equal(np.asarray(got[i]), np.asarray(ref[i])), name
    assert int((np.asarray(got[1]) >= 0).sum()) > 0


def test_sharded_gang_quota_assign_matches_unsharded():
    """Gang all-or-nothing + elastic-quota admission on the mesh equals the
    single-device solve (VERDICT r1 item 7: multi-device gang+quota parity)."""
    from koordinator_tpu.ops.gang import GangInfo, gang_assign
    from koordinator_tpu.quota.admission import QuotaDeviceState
    from koordinator_tpu.quota.tree import UNBOUNDED, QuotaTree

    state, pods = build_problem(n_pods=32)
    gang_id = np.full(pods.capacity, -1, np.int32)
    gang_id[:8] = 0
    gang_id[8:12] = 1
    quota_id = np.full(pods.capacity, -1, np.int32)

    total = np.zeros(R, np.int64)
    total[CPU] = 60_000
    tree = QuotaTree(total)
    mx = np.full(R, UNBOUNDED, np.int64)
    mx[CPU] = 24_000
    mn = np.zeros(R, np.int64)
    tree.add("q", min=mn, max=mx)
    tree.set_request("q", total)
    tree.refresh_runtime()
    quota, index = QuotaDeviceState.from_tree(tree)
    quota_id[12:24] = index["q"]

    pods = pods.replace(
        gang_id=np.asarray(gang_id), quota_id=np.asarray(quota_id)
    )
    gangs = GangInfo.build(np.array([6, 4], np.int32))
    cfg = ScoringConfig.default()

    f = jax.jit(gang_assign, static_argnames=("passes",))
    a_ref, st_ref, q_ref = f(state, pods, cfg, gangs, quota, passes=2)

    mesh = pmesh.solver_mesh(pods_axis=2)
    sstate = pmesh.shard_cluster_state(state, mesh)
    spods = pmesh.shard_pod_batch(pods, mesh)
    a_sh, st_sh, q_sh = f(sstate, spods, cfg, gangs, quota, passes=2)

    assert np.array_equal(np.asarray(a_ref), np.asarray(a_sh))
    assert np.array_equal(
        np.asarray(st_ref.node_requested), np.asarray(st_sh.node_requested)
    )
    assert np.array_equal(
        np.asarray(q_ref.headroom), np.asarray(q_sh.headroom)
    )
