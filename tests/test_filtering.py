import jax.numpy as jnp
import numpy as np

from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS
from koordinator_tpu.ops import filtering
from tests import oracle

R = NUM_RESOURCE_DIMS
RNG = np.random.default_rng(1)


def test_fit_mask_basic():
    free = jnp.asarray(np.array([[1000, 2048] + [0] * (R - 2),
                                 [500, 4096] + [0] * (R - 2)], np.int32))
    req = jnp.asarray(np.array([[600, 1024] + [0] * (R - 2),
                                [600, 3000] + [0] * (R - 2),
                                [0, 0] + [0] * (R - 2)], np.int32))
    m = np.asarray(filtering.fit_mask(free, req))
    assert m.tolist() == [
        [True, False],   # cpu fits node0 only
        [False, False],  # cpu too big for node1, mem too big for node0
        [True, True],    # zero request fits everywhere
    ]


def test_fit_mask_ignores_unrequested_negative_free():
    # batch allocatable can shrink below already-scheduled requests -> negative
    # free on a dim the pod doesn't request must NOT exclude the node.
    free = np.zeros((1, R), np.int32)
    free[0, 0] = 1000
    free[0, 6] = -500  # batch-cpu overdrawn
    req = np.zeros((1, R), np.int32)
    req[0, 0] = 500
    m = np.asarray(filtering.fit_mask(jnp.asarray(free), jnp.asarray(req)))
    assert m[0, 0]


def test_usage_threshold_rounding_parity():
    # The reference compares round(est*100/total) > threshold; check the exact
    # rounding boundary: 655/1000 -> 66 > 65 rejected, 654/1000 -> 65 passes.
    total = jnp.asarray(np.array([[1000] + [0] * (R - 1)], np.int32))
    thresholds = jnp.asarray(np.array([65] + [0] * (R - 1), np.int32))
    for est, want in ((640, True), (654, True), (655, False), (651, True), (700, False)):
        usage = jnp.asarray(np.array([[est] + [0] * (R - 1)], np.int32))
        got = bool(np.asarray(filtering.usage_threshold_mask(usage, total, thresholds))[0])
        assert got == want, (est, got)


def test_usage_threshold_random_parity():
    n = 300
    total = RNG.integers(0, 100_000, size=(n, R)).astype(np.int32)
    total[RNG.random((n, R)) < 0.15] = 0
    usage = (total * RNG.random((n, R)) * 1.2).astype(np.int32)
    thresholds = np.array([65, 95, 0, 80, 0, 0, 50, 0, 0, 0], np.int32)[:R]
    got = np.asarray(
        filtering.usage_threshold_mask(
            jnp.asarray(usage), jnp.asarray(total), jnp.asarray(thresholds)
        )
    )
    for i in range(n):
        assert got[i] == oracle.usage_threshold_ok(
            usage[i].tolist(), total[i].tolist(), thresholds.tolist()
        ), i


def test_usage_threshold_with_pod_estimates():
    total = jnp.asarray(np.array([[1000] + [0] * (R - 1)], np.int32))
    usage = jnp.asarray(np.array([[500] + [0] * (R - 1)], np.int32))
    thresholds = jnp.asarray(np.array([65] + [0] * (R - 1), np.int32))
    pod_est = jnp.asarray(np.array([[100] + [0] * (R - 1),
                                    [200] + [0] * (R - 1)], np.int32))
    got = np.asarray(
        filtering.usage_threshold_mask(usage, total, thresholds, pod_est)
    )
    # 600/1000 = 60 <= 65 ok; 700/1000 = 70 > 65 reject
    assert got[:, 0].tolist() == [True, False]
