"""Perf regression sentinel (ISSUE 18 satellite): tools/bench_diff.py.

The acceptance criterion is the NEGATIVE test: a candidate capture with
a planted 2x slowdown must flip the exit code to 1 — the soak gate
(``SOAK_BENCH_DIFF=1`` in tools/soak.sh) is only worth wiring if the
sentinel actually fires.  Around it, the comparison rules: the
two-sided regression bar (relative slowdown AND absolute floor),
missing/errored candidate stages fatal, baseline-errored stages
skipped, metadata lines ignored, candidate-only stages pass as new.
"""

import json
import os
import subprocess
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

import bench_diff  # noqa: E402


def _write(path, records):
    with open(path, "w") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")
    return str(path)


BASE = [
    {"stage": "provenance", "commit": "abc123", "dirty": False},
    {"stage": "rtt_floor", "ms_per_iter": 9.9},
    {"stage": "score", "ms_per_iter": 2.0},
    {"stage": "rounds", "ms_per_iter": 10.0},
    {"stage": "tiny", "ms_per_iter": 0.02},
    {"stage": "broken", "error": "RuntimeError('no mesh')"},
]


class TestLoadStages:
    def test_skips_metadata_malformed_and_blank_lines(self, tmp_path):
        p = tmp_path / "cap.jsonl"
        with open(p, "w") as f:
            f.write(json.dumps(BASE[0]) + "\n")
            f.write("\n")
            f.write('{"stage": "score", "ms_per_iter": 2.0}\n')
            f.write("[1, 2, 3]\n")
            f.write('{"no_stage_key": true}\n')
            f.write('{"stage": "trunca')      # timeout-truncated tail
        stages = bench_diff.load_stages(str(p))
        assert set(stages) == {"score"}

    def test_rtt_floor_is_machine_state_not_code_speed(self, tmp_path):
        stages = bench_diff.load_stages(_write(tmp_path / "b.jsonl", BASE))
        assert "rtt_floor" not in stages
        assert "provenance" not in stages


class TestDiffRules:
    def test_identical_captures_pass(self, tmp_path):
        base = bench_diff.load_stages(_write(tmp_path / "b.jsonl", BASE))
        regressions, rows = bench_diff.diff_stages(base, dict(base),
                                                   0.25, 0.05)
        assert regressions == []
        verdicts = {r["stage"]: r["verdict"] for r in rows}
        assert verdicts == {"score": "ok", "rounds": "ok", "tiny": "ok",
                            "broken": "skipped"}

    def test_two_sided_bar_needs_both_relative_and_absolute(self):
        base = {"s": {"stage": "s", "ms_per_iter": 10.0}}
        # relative breach without the absolute floor: 10 -> 13 at 25%
        # tolerance breaches relative, passes a 5ms floor
        regs, _ = bench_diff.diff_stages(
            base, {"s": {"stage": "s", "ms_per_iter": 13.0}}, 0.25, 5.0)
        assert regs == []
        # absolute breach without the relative one: +6ms on 100ms base
        base100 = {"s": {"stage": "s", "ms_per_iter": 100.0}}
        regs, _ = bench_diff.diff_stages(
            base100, {"s": {"stage": "s", "ms_per_iter": 106.0}}, 0.25, 5.0)
        assert regs == []
        # both breached -> regression
        regs, rows = bench_diff.diff_stages(
            base, {"s": {"stage": "s", "ms_per_iter": 20.0}}, 0.25, 5.0)
        assert [r["stage"] for r in regs] == ["s"]
        assert rows[0]["verdict"] == "regressed"
        assert rows[0]["ratio"] == 2.0

    def test_min_delta_floor_suppresses_microsecond_flaps(self):
        # a 0.02ms stage doubling is 100% relative but 0.02ms absolute:
        # scheduler jitter, not a regression
        base = {"tiny": {"stage": "tiny", "ms_per_iter": 0.02}}
        regs, rows = bench_diff.diff_stages(
            base, {"tiny": {"stage": "tiny", "ms_per_iter": 0.04}},
            0.25, 0.05)
        assert regs == []
        assert rows[0]["verdict"] == "ok"

    def test_missing_candidate_stage_is_fatal(self):
        base = {"s": {"stage": "s", "ms_per_iter": 1.0}}
        regs, rows = bench_diff.diff_stages(base, {}, 0.25, 0.05)
        assert rows[0]["verdict"] == "missing"
        assert regs == rows

    def test_errored_candidate_stage_is_fatal(self):
        base = {"s": {"stage": "s", "ms_per_iter": 1.0}}
        cand = {"s": {"stage": "s", "error": "Exception('boom')"}}
        regs, rows = bench_diff.diff_stages(base, cand, 0.25, 0.05)
        assert rows[0]["verdict"] == "errored"
        assert len(regs) == 1

    def test_baseline_errored_stage_skipped_even_if_candidate_times(self):
        base = {"s": {"stage": "s", "error": "never compiled"}}
        cand = {"s": {"stage": "s", "ms_per_iter": 5.0}}
        regs, rows = bench_diff.diff_stages(base, cand, 0.25, 0.05)
        assert regs == []
        assert rows[0]["verdict"] == "skipped"

    def test_candidate_only_stage_is_new_and_passes(self):
        base = {"s": {"stage": "s", "ms_per_iter": 1.0}}
        cand = {"s": {"stage": "s", "ms_per_iter": 1.0},
                "grown": {"stage": "grown", "ms_per_iter": 99.0}}
        regs, rows = bench_diff.diff_stages(base, cand, 0.25, 0.05)
        assert regs == []
        assert {r["stage"]: r["verdict"] for r in rows} == {
            "s": "ok", "grown": "new"}

    def test_improvement_is_named(self):
        base = {"s": {"stage": "s", "ms_per_iter": 10.0}}
        cand = {"s": {"stage": "s", "ms_per_iter": 4.0}}
        _, rows = bench_diff.diff_stages(base, cand, 0.25, 0.05)
        assert rows[0]["verdict"] == "improved"

    def test_rows_sorted_for_deterministic_reports(self):
        base = {n: {"stage": n, "ms_per_iter": 1.0}
                for n in ("zeta", "alpha", "mid")}
        _, rows = bench_diff.diff_stages(base, dict(base), 0.25, 0.05)
        assert [r["stage"] for r in rows] == ["alpha", "mid", "zeta"]


class TestExitCodes:
    """main() through its argv surface — what tools/soak.sh calls."""

    def test_identical_captures_exit_0(self, tmp_path, capsys):
        b = _write(tmp_path / "b.jsonl", BASE)
        assert bench_diff.main([b, b]) == 0

    def test_planted_2x_slowdown_exits_1(self, tmp_path, capsys):
        """THE acceptance criterion: the sentinel gates a planted
        regression non-zero."""
        b = _write(tmp_path / "b.jsonl", BASE)
        slowed = [dict(rec) for rec in BASE]
        for rec in slowed:
            if rec["stage"] == "rounds":
                rec["ms_per_iter"] = rec["ms_per_iter"] * 2.0
        c = _write(tmp_path / "c.jsonl", slowed)
        assert bench_diff.main([b, c, "--tolerance", "0.25",
                                "--min-delta-ms", "0.05"]) == 1
        err = capsys.readouterr().err
        assert "rounds" in err and "FAIL" in err

    def test_generous_tolerance_forgives_the_same_capture(
            self, tmp_path, capsys):
        b = _write(tmp_path / "b.jsonl", BASE)
        slowed = [dict(rec) for rec in BASE]
        for rec in slowed:
            if rec["stage"] == "rounds":
                rec["ms_per_iter"] = rec["ms_per_iter"] * 1.5
        c = _write(tmp_path / "c.jsonl", slowed)
        assert bench_diff.main([b, c, "--tolerance", "1.0"]) == 0

    def test_empty_or_unreadable_inputs_exit_2(self, tmp_path, capsys):
        b = _write(tmp_path / "b.jsonl", BASE)
        empty = _write(tmp_path / "empty.jsonl",
                       [{"stage": "provenance"}])
        assert bench_diff.main([empty, b]) == 2
        assert bench_diff.main([b, empty]) == 2
        assert bench_diff.main([str(tmp_path / "absent.jsonl"), b]) == 2

    def test_report_rows_are_json_lines(self, tmp_path, capsys):
        b = _write(tmp_path / "b.jsonl", BASE)
        assert bench_diff.main([b, b]) == 0
        out = capsys.readouterr().out
        rows = [json.loads(line) for line in out.splitlines() if line]
        assert {r["stage"] for r in rows} == {"score", "rounds", "tiny",
                                              "broken"}

    def test_cli_entrypoint_runs_standalone(self, tmp_path):
        """The soak gate shells out to the script — prove the file is
        executable as a program, not only importable."""
        b = _write(tmp_path / "b.jsonl", BASE)
        proc = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(__file__), "..", "tools",
                          "bench_diff.py"), b, b],
            capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, proc.stderr
        assert "ok" in proc.stderr


class TestCommittedBaseline:
    """The repo's committed smoke baseline must stay usable — the soak
    gate diffs fresh captures against it."""

    BASELINE = os.path.join(os.path.dirname(__file__), "..", "tools",
                            "baselines", "bench_stages_smoke.jsonl")

    def test_baseline_exists_and_parses(self):
        stages = bench_diff.load_stages(self.BASELINE)
        assert stages, "committed baseline has no timed stages"
        for name, rec in stages.items():
            if "error" not in rec:
                assert rec["ms_per_iter"] > 0, name

    def test_baseline_self_diff_passes(self):
        assert bench_diff.main([self.BASELINE, self.BASELINE]) == 0

    def test_baseline_covers_the_host_turbo_stages(self):
        """The ISSUE 19 host-plane stages are part of the gated set."""
        stages = bench_diff.load_stages(self.BASELINE)
        for name in ("wire_codec_v1_vs_v2", "deltasync_apply_batched",
                     "bind_commit_batched"):
            rec = stages.get(name)
            assert rec is not None and "error" not in rec, name
            assert rec["ms_per_iter"] > 0, rec

    def test_planted_codec_regression_flagged(self, tmp_path, capsys):
        """THE ISSUE 19 acceptance: a candidate where the wire codec
        stage got 10x slower against the COMMITTED baseline must exit
        1 naming the stage — the sentinel really guards the codec."""
        slowed = []
        with open(self.BASELINE) as fh:
            for line in fh:
                rec = json.loads(line)
                if rec.get("stage") == "wire_codec_v1_vs_v2":
                    rec["ms_per_iter"] = round(
                        rec["ms_per_iter"] * 10 + 1.0, 2)
                slowed.append(rec)
        c = _write(tmp_path / "cand.jsonl", slowed)
        assert bench_diff.main([self.BASELINE, c]) == 1
        err = capsys.readouterr().err
        assert "wire_codec_v1_vs_v2" in err and "FAIL" in err

    def test_baseline_covers_the_timeline_overhead_stage(self):
        """The ISSUE's self-overhead stage must be part of the gated
        set, with its measured fraction under the 3% bar."""
        stages = bench_diff.load_stages(self.BASELINE)
        rec = stages.get("timeline_overhead")
        assert rec is not None and "error" not in rec
        assert rec["overhead_fraction"] < 0.03

    def test_baseline_covers_the_journey_ledger_overhead_stage(self):
        """ISSUE 20 acceptance: the always-on pod-journey ledger costs
        under 1% of the pipelined cycle (its scheduling-path work is
        stamps + staged appends; sketch digestion amortizes onto the
        telemetry sampler)."""
        stages = bench_diff.load_stages(self.BASELINE)
        rec = stages.get("journey_ledger_overhead")
        assert rec is not None and "error" not in rec, rec
        assert rec["ms_per_iter"] > 0
        assert rec["overhead_fraction"] < 0.01, rec

    def test_planted_journey_regression_flagged(self, tmp_path, capsys):
        """A candidate where the journey-ledger stage got 10x slower
        against the COMMITTED baseline must exit 1 naming the stage —
        the sentinel really guards the ledger's hot path."""
        slowed = []
        with open(self.BASELINE) as fh:
            for line in fh:
                rec = json.loads(line)
                if rec.get("stage") == "journey_ledger_overhead":
                    rec["ms_per_iter"] = round(
                        rec["ms_per_iter"] * 10 + 1.0, 2)
                slowed.append(rec)
        c = _write(tmp_path / "cand.jsonl", slowed)
        assert bench_diff.main([self.BASELINE, c]) == 1
        err = capsys.readouterr().err
        assert "journey_ledger_overhead" in err and "FAIL" in err
