"""The HTTP/JSON gateway driven by curl — a stock off-the-shelf client.

The C wire client (test_c_conformance.py) proves the framed protocol is
language-neutral; this proves the OTHER boundary — the HTTP gateway that
plays the role of gRPC JSON transcoding for the reference's api.proto
surface — is consumable by a client nobody on this project wrote: plain
curl, as a Go plugin using net/http would.  Covers solve, lease CAS
(incl. the 409 conflict path), hook dispatch, version discovery, and
diagnosis.
"""

import json
import shutil
import subprocess

import pytest

from koordinator_tpu.ha import LeaseService
from koordinator_tpu.runtimeproxy import Dispatcher, HookResponse, HookType
from koordinator_tpu.transport.http_gateway import HttpGateway
from koordinator_tpu.transport.wire import PROTOCOL_VERSION

from tests.test_scheduler import mk_scheduler, node, pod

pytestmark = pytest.mark.skipif(
    shutil.which("curl") is None, reason="curl not available")


def curl(method, url, body=None, timeout=15):
    cmd = ["curl", "-s", "-S", "-X", method,
           "-w", "\n%{http_code}", "--max-time", str(timeout), url]
    if body is not None:
        cmd += ["-H", "Content-Type: application/json",
                "-d", json.dumps(body)]
    proc = subprocess.run(cmd, capture_output=True, text=True,
                          timeout=timeout + 5)
    assert proc.returncode == 0, proc.stderr
    payload, _, code = proc.stdout.rpartition("\n")
    return int(code), json.loads(payload)


@pytest.fixture
def gateway():
    scheduler, _ = mk_scheduler([node("n1")])
    scheduler.enqueue(pod("curl-pod"))

    dispatcher = Dispatcher()

    class Hooker:
        def handle(self, hook, request):
            return HookResponse(envs={"SEEN_BY": "hook"})

    dispatcher.register(Hooker(), [HookType.PRE_CREATE_CONTAINER])

    gw = HttpGateway(scheduler=scheduler, dispatcher=dispatcher,
                     lease_store=LeaseService().store)
    gw.start()
    try:
        yield gw
    finally:
        gw.stop()


def test_curl_drives_the_full_surface(gateway):
    base = f"http://127.0.0.1:{gateway.port}"

    code, doc = curl("GET", f"{base}/healthz")
    assert (code, doc) == (200, {"ok": True})

    code, doc = curl("GET", f"{base}/version")
    assert code == 200 and doc["protocol"] == PROTOCOL_VERSION

    code, doc = curl("POST", f"{base}/v1/solve", body={})
    assert code == 200 and doc["assignments"] == {"curl-pod": "n1"}

    code, doc = curl("GET", f"{base}/v1/diagnosis")
    assert code == 200 and doc["failures"] == {}

    code, doc = curl("POST", f"{base}/v1/hooks/PreCreateContainer",
                     body={"pod_meta": {"uid": "u1"}})
    assert code == 200 and doc["envs"] == {"SEEN_BY": "hook"}

    # lease acquire via CAS from empty, then a stale CAS answers 409
    record = {"expect_holder": "", "holder": "curl-client",
              "duration_seconds": 15.0, "acquire_time": 1.0,
              "renew_time": 1.0, "transitions": 0}
    code, doc = curl("PUT", f"{base}/v1/leases/curl-lease", body=record)
    assert (code, doc["ok"]) == (200, True)

    code, doc = curl("GET", f"{base}/v1/leases/curl-lease")
    assert code == 200 and doc["holder"] == "curl-client"

    stale = dict(record, expect_holder="someone-else", holder="thief")
    code, doc = curl("PUT", f"{base}/v1/leases/curl-lease", body=stale)
    assert (code, doc["ok"]) == (409, False)
