"""The HTTP/JSON gateway driven by curl — a stock off-the-shelf client.

The C wire client (test_c_conformance.py) proves the framed protocol is
language-neutral; this proves the OTHER boundary — the HTTP gateway that
plays the role of gRPC JSON transcoding for the reference's api.proto
surface — is consumable by a client nobody on this project wrote: plain
curl, as a Go plugin using net/http would.  Covers solve, lease CAS
(incl. the 409 conflict path), hook dispatch, version discovery, and
diagnosis.
"""

import json
import shutil
import subprocess

import pytest

from koordinator_tpu.ha import LeaseService
from koordinator_tpu.runtimeproxy import Dispatcher, HookResponse, HookType
from koordinator_tpu.transport.http_gateway import HttpGateway
from koordinator_tpu.transport.wire import PROTOCOL_VERSION

from tests.test_scheduler import mk_scheduler, node, pod

pytestmark = pytest.mark.skipif(
    shutil.which("curl") is None, reason="curl not available")


def curl(method, url, body=None, timeout=15):
    cmd = ["curl", "-s", "-S", "-X", method,
           "-w", "\n%{http_code}", "--max-time", str(timeout), url]
    if body is not None:
        cmd += ["-H", "Content-Type: application/json",
                "-d", json.dumps(body)]
    proc = subprocess.run(cmd, capture_output=True, text=True,
                          timeout=timeout + 5)
    assert proc.returncode == 0, proc.stderr
    payload, _, code = proc.stdout.rpartition("\n")
    return int(code), json.loads(payload)


@pytest.fixture
def gateway():
    scheduler, _ = mk_scheduler([node("n1")])
    scheduler.enqueue(pod("curl-pod"))

    dispatcher = Dispatcher()

    class Hooker:
        def handle(self, hook, request):
            return HookResponse(envs={"SEEN_BY": "hook"})

    dispatcher.register(Hooker(), [HookType.PRE_CREATE_CONTAINER])

    gw = HttpGateway(scheduler=scheduler, dispatcher=dispatcher,
                     lease_store=LeaseService().store)
    gw.start()
    try:
        yield gw
    finally:
        gw.stop()


def test_curl_drives_the_full_surface(gateway):
    base = f"http://127.0.0.1:{gateway.port}"

    code, doc = curl("GET", f"{base}/healthz")
    assert (code, doc) == (200, {"ok": True})

    code, doc = curl("GET", f"{base}/version")
    assert code == 200 and doc["protocol"] == PROTOCOL_VERSION

    code, doc = curl("POST", f"{base}/v1/solve", body={})
    assert code == 200 and doc["assignments"] == {"curl-pod": "n1"}

    code, doc = curl("GET", f"{base}/v1/diagnosis")
    assert code == 200 and doc["failures"] == {}

    code, doc = curl("POST", f"{base}/v1/hooks/PreCreateContainer",
                     body={"pod_meta": {"uid": "u1"}})
    assert code == 200 and doc["envs"] == {"SEEN_BY": "hook"}

    # lease acquire via CAS from empty, then a stale CAS answers 409
    record = {"expect_holder": "", "holder": "curl-client",
              "duration_seconds": 15.0, "acquire_time": 1.0,
              "renew_time": 1.0, "transitions": 0}
    code, doc = curl("PUT", f"{base}/v1/leases/curl-lease", body=record)
    assert (code, doc["ok"]) == (200, True)

    code, doc = curl("GET", f"{base}/v1/leases/curl-lease")
    assert code == 200 and doc["holder"] == "curl-client"

    stale = dict(record, expect_holder="someone-else", holder="thief")
    code, doc = curl("PUT", f"{base}/v1/leases/curl-lease", body=stale)
    assert (code, doc["ok"]) == (409, False)


def test_curl_pushes_state_and_solves(tmp_path):
    """State enters over plain HTTP (the /v1/state route, STATE_PUSH's
    JSON form), reaches the scheduler through the production
    commit->broadcast->binding path, and the pushed pod schedules onto
    the pushed node — the full plugin->sidecar feed direction with zero
    custom client code."""
    from koordinator_tpu.transport import (
        RpcClient,
        RpcServer,
        StateSyncClient,
        StateSyncService,
    )
    from koordinator_tpu.transport.deltasync import SchedulerBinding

    scheduler, _ = mk_scheduler([])
    server = RpcServer(str(tmp_path / "sync.sock"))
    service = StateSyncService()
    service.attach(server)
    server.start()
    sync = StateSyncClient(SchedulerBinding(scheduler))
    feed = RpcClient(server.path, on_push=sync.on_push)
    feed.connect()
    sync.bootstrap(feed)

    from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS as r

    gw = HttpGateway(scheduler=scheduler, state_sync=service)
    gw.start()
    base = f"http://127.0.0.1:{gw.port}"
    try:
        alloc = [16_000, 32_768] + [0] * (r - 2)
        code, doc = curl("POST", f"{base}/v1/state", body={
            "kind": "node_upsert", "name": "curl-node",
            "allocatable": alloc})
        assert code == 200 and doc["rv"] == 1

        code, doc = curl("POST", f"{base}/v1/state", body={
            "kind": "pod_add", "name": "curl-pod-2",
            "requests": [1_000, 1_024] + [0] * (r - 2)})
        assert code == 200 and doc["rv"] == 2

        # usage refresh with the colocation-formula arrays, and the
        # manager's allocatable patch — both over plain HTTP
        usage = [2_000, 4_096] + [0] * (r - 2)
        code, doc = curl("POST", f"{base}/v1/state", body={
            "kind": "node_usage", "name": "curl-node", "usage": usage,
            "sys_usage": [500, 512] + [0] * (r - 2),
            "hp_usage": [1_000, 256] + [0] * (r - 2)})
        assert code == 200 and doc["rv"] == 3
        stored = service.nodes["curl-node"]["arrays"]
        assert int(stored["sys_usage"][0]) == 500
        assert int(stored["hp_usage"][0]) == 1_000
        code, doc = curl("POST", f"{base}/v1/state", body={
            "kind": "node_allocatable", "name": "curl-node",
            "allocatable": alloc})
        assert code == 200 and doc["rv"] == 4

        # malformed pushes answer 400 and never reach the replay log
        code, doc = curl("POST", f"{base}/v1/state", body={
            "kind": "node_upsert", "name": "bad",
            "allocatable": [1, 2, 3]})
        assert code == 400 and "shape" in doc["error"]
        code, doc = curl("POST", f"{base}/v1/state", body={
            "kind": "pod_add", "name": "bad",
            "requests": "not-an-array"})
        assert code == 400
        assert service.rv == 4

        # the solve sees the HTTP-pushed state once the feed applies it
        deadline = 50
        for _ in range(deadline):
            code, doc = curl("POST", f"{base}/v1/solve", body={})
            assert code == 200
            if doc["assignments"].get("curl-pod-2") == "curl-node":
                break
            import time
            time.sleep(0.1)
        assert doc["assignments"]["curl-pod-2"] == "curl-node"
    finally:
        gw.stop()
        feed.close()
        server.stop()
