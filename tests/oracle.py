"""Pure-Python integer oracles mirroring the reference's Go scorer semantics.

Each function is a direct reimplementation (from observed semantics, not code)
of the cited Go function using plain Python ints, used to check the JAX kernels
for exact integer parity on random fixtures.
"""

from __future__ import annotations

MAX_NODE_SCORE = 100


def least_used_score(used: int, capacity: int) -> int:
    # loadaware/load_aware.go:368
    if capacity == 0 or used > capacity:
        return 0
    return (capacity - used) * MAX_NODE_SCORE // capacity


def most_requested_score(requested: int, capacity: int) -> int:
    # noderesourcefitplus utils mostRequestedScore: clamps over-capacity to 100
    if capacity == 0:
        return 0
    if requested > capacity:
        requested = capacity
    return requested * MAX_NODE_SCORE // capacity


def loadaware_score(used, allocatable, weights, dominant_weight) -> int:
    # loadaware/load_aware.go:347 loadAwareSchedulingScorer
    node_score = 0
    weight_sum = 0
    dominant = MAX_NODE_SCORE if dominant_weight != 0 else 0
    if dominant_weight != 0:
        weight_sum += dominant_weight
    for i, w in enumerate(weights):
        if w <= 0:
            continue
        s = least_used_score(used[i], allocatable[i])
        node_score += s * w
        weight_sum += w
        if dominant > s:
            dominant = s
    node_score += dominant * dominant_weight
    if weight_sum <= 0:
        return 0
    return node_score // weight_sum


def fitplus_score(node_requested, allocatable, pod_request, weights, most_allocated) -> int:
    # noderesourcefitplus resourceScorer: only resources the pod requests count
    num = 0
    den = 0
    for i, w in enumerate(weights):
        if pod_request[i] <= 0 or w <= 0:
            continue
        combined = node_requested[i] + pod_request[i]
        if most_allocated[i]:
            s = most_requested_score(combined, allocatable[i])
        else:
            s = least_used_score(combined, allocatable[i])
        num += s * w
        den += w
    if den <= 0:
        return MAX_NODE_SCORE  # weightSum==0 branch returns MaxNodeScore
    return num // den


def scarce_resource_score(pod_request, node_allocatable, scarce) -> int:
    # scarceresourceavoidance scarce_resource_avoidance.go:89,158
    diff = [
        i
        for i in range(len(pod_request))
        if node_allocatable[i] > 0 and pod_request[i] <= 0
    ]
    inter = [i for i in diff if scarce[i]]
    if not diff or not inter:
        return MAX_NODE_SCORE
    return (len(diff) - len(inter)) * MAX_NODE_SCORE // len(diff)


def _round_half_up(x: float) -> int:
    # Go math.Round = half away from zero; operands here are non-negative.
    import math

    return math.floor(x + 0.5)


def usage_threshold_ok(est_used, total, thresholds) -> bool:
    # loadaware/load_aware.go:150,320-345: round(est*100/total) > threshold -> reject
    for i, value in enumerate(thresholds):
        if value <= 0 or total[i] == 0:
            continue
        usage = _round_half_up(est_used[i] / total[i] * 100)
        if usage > value:
            return False
    return True


def estimate_pod_usage(request, factors, defaults) -> list[int]:
    # loadaware/estimator/default_estimator.go:74-121
    out = []
    for i, r in enumerate(request):
        if r == 0 and defaults[i] > 0:
            out.append(defaults[i])
        else:
            out.append(_round_half_up(r * factors[i] / 100))
    return out
