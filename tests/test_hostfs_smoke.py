"""Read-only smoke of the L0 host layer against the LIVE kernel.

The fake-fs tests (make_test_config temp trees, the FileTestUtil
equivalent of util_test_tool.go:93) prove the parsers; they cannot catch
path-format drift between our path builders and a real /proc //sys —
that is what this opt-in suite does (VERDICT r4 next #9).  Strictly
read-only: no cgroup writes, no resctrl group creation.

Run with:  pytest -m hostfs tests/test_hostfs_smoke.py
(deselected by default via pytest.ini addopts).
"""

import os

import pytest

from koordinator_tpu import native
from koordinator_tpu.koordlet.system import cgroup as cg
from koordinator_tpu.koordlet.system import procfs, psi
from koordinator_tpu.koordlet.system.config import SystemConfig

pytestmark = [
    pytest.mark.hostfs,
    pytest.mark.skipif(not os.path.exists("/proc/stat"),
                       reason="needs a live Linux procfs"),
]

#: defaults point at the real roots (/proc, /sys/fs/cgroup, /sys)
LIVE = SystemConfig(use_cgroup_v2=os.path.exists(
    "/sys/fs/cgroup/cgroup.controllers"))


def test_native_batch_read_live_proc():
    """ks_batch_read (native/koordsys.cpp) against real /proc: content
    parity with the pure-Python fallback, None for a missing path."""
    assert native.ensure_built() and native.available(), \
        "native shim must build on this box"
    reader = native.BatchReader(
        ["/proc/stat", "/proc/meminfo", "/proc/koord_definitely_missing"],
        max_bytes=65536)
    got = reader.read()
    assert got[0] is not None and got[0].startswith("cpu")
    assert got[1] is not None and "MemTotal" in got[1]
    assert got[2] is None
    py = reader._read_python()
    # /proc/stat jiffies advance between reads; compare structure only
    assert py[0].splitlines()[0].split()[0] == "cpu"
    assert ("MemTotal" in py[1]) and py[2] is None


def test_procfs_parsers_live():
    st = procfs.read_cpu_stat(LIVE)
    assert st.total_jiffies > 0
    assert 0 < st.used_jiffies <= st.total_jiffies
    mi = procfs.read_meminfo(LIVE)
    assert mi.total > (1 << 28)           # >256 MiB of RAM
    assert 0 < mi.used_no_cache <= mi.total
    disks = procfs.read_diskstats(LIVE)
    assert isinstance(disks, dict)        # may be empty in a container


def test_cgroup_path_resolution_live():
    """The v1/v2 filename tables must resolve to files that actually
    exist on the live hierarchy (path-format drift is exactly what the
    temp-tree tests cannot see)."""
    probes = [(cg.CPU_STAT, ""), (cg.CPU_CFS_PERIOD, ""),
              (cg.CPUSET_CPUS, "")]
    resolved = 0
    for res, rel in probes:
        if not res.supported(cg.CgroupVersion.V2 if LIVE.use_cgroup_v2
                             else cg.CgroupVersion.V1):
            continue
        path = cg.resource_path(res, rel, LIVE)
        if os.path.exists(path):
            resolved += 1
            content = cg.cgroup_read(res, rel, LIVE)
            assert content.strip(), path
    assert resolved >= 2, (
        "fewer than 2 of the probe cgroup files resolved — path drift "
        f"against {LIVE.cgroup_root}")
    stat = cg.parse_stat(cg.cgroup_read(cg.CPU_STAT, "", LIVE))
    assert stat, "root cpu.stat parsed to nothing"


def test_psi_live():
    if not os.path.exists("/proc/pressure/cpu"):
        pytest.skip("kernel without PSI")
    with open("/proc/pressure/cpu") as f:
        stats = psi.parse_psi(f.read())
    assert stats.some.total_us >= 0
    assert 0.0 <= stats.some.avg10 <= 100.0
    # cgroup-level PSI: must not raise either way (v1 roots have no
    # pressure files -> empty stats; v2 -> parsed stats)
    by_res = psi.read_psi("", LIVE)
    assert by_res.cpu.some.avg10 >= 0.0
