"""Integer-parity tests: JAX kernels vs pure-Python oracles on random fixtures."""

import jax.numpy as jnp
import numpy as np

from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS
from koordinator_tpu.ops import scoring
from tests import oracle

R = NUM_RESOURCE_DIMS
RNG = np.random.default_rng(0)


def rand_alloc(n):
    a = RNG.integers(0, 100_000, size=(n, R)).astype(np.int32)
    a[RNG.random((n, R)) < 0.2] = 0  # some zero-capacity dims
    return a


def test_least_used_score_parity():
    cap = rand_alloc(200)
    used = (cap * RNG.random((200, R))).astype(np.int32)
    used[RNG.random((200, R)) < 0.1] += 1_000_000  # some over-capacity
    got = np.asarray(scoring.least_used_score(jnp.asarray(used), jnp.asarray(cap)))
    for i in range(200):
        for j in range(R):
            assert got[i, j] == oracle.least_used_score(int(used[i, j]), int(cap[i, j]))


def test_loadaware_score_parity():
    n = 100
    cap = rand_alloc(n)
    used = (cap * RNG.random((n, R))).astype(np.int32)
    weights = np.zeros(R, np.int32)
    weights[0], weights[1], weights[3] = 1, 2, 3
    for dw in (0, 1, 4):
        got = np.asarray(
            scoring.loadaware_score(
                jnp.asarray(used), jnp.asarray(cap), jnp.asarray(weights), dw
            )
        )
        for i in range(n):
            assert got[i] == oracle.loadaware_score(
                used[i].tolist(), cap[i].tolist(), weights.tolist(), dw
            ), (i, dw)


def test_fitplus_score_parity():
    n, p = 50, 20
    cap = rand_alloc(n)
    # some over-requested nodes exercise mostRequestedScore's clamp branch
    req_node = (cap * RNG.random((n, R)) * 1.4).astype(np.int32)
    pod_req = RNG.integers(0, 30_000, size=(p, R)).astype(np.int32)
    pod_req[RNG.random((p, R)) < 0.5] = 0
    pod_req[0] = 0          # all-zero request -> weightSum==0 -> MaxNodeScore
    pod_req[1, :2] = 0
    pod_req[1, 4] = 5_000   # only a zero-weight dim requested -> MaxNodeScore
    weights = np.array([1, 1, 2, 3, 0, 1, 0, 0, 0, 0], np.int32)[:R]
    most = np.zeros(R, bool)
    most[3] = True
    got = np.asarray(
        scoring.fitplus_score(
            jnp.asarray(req_node), jnp.asarray(cap), jnp.asarray(pod_req),
            jnp.asarray(weights), jnp.asarray(most),
        )
    )
    for i in range(p):
        for j in range(n):
            assert got[i, j] == oracle.fitplus_score(
                req_node[j].tolist(), cap[j].tolist(), pod_req[i].tolist(),
                weights.tolist(), most.tolist(),
            ), (i, j)


def test_scarce_resource_score_parity():
    n, p = 40, 15
    cap = rand_alloc(n)
    pod_req = RNG.integers(0, 10_000, size=(p, R)).astype(np.int32)
    pod_req[RNG.random((p, R)) < 0.6] = 0
    scarce = np.zeros(R, bool)
    scarce[3], scarce[5] = True, True
    got = np.asarray(
        scoring.scarce_resource_score(
            jnp.asarray(pod_req), jnp.asarray(cap), jnp.asarray(scarce)
        )
    )
    for i in range(p):
        for j in range(n):
            assert got[i, j] == oracle.scarce_resource_score(
                pod_req[i].tolist(), cap[j].tolist(), scarce.tolist()
            ), (i, j)


def test_most_requested_score_clamps_overcommit():
    got = scoring.most_requested_score(
        jnp.asarray(np.array([1500, 500, 0], np.int32)),
        jnp.asarray(np.array([1000, 1000, 0], np.int32)),
    )
    assert np.asarray(got).tolist() == [100, 50, 0]


def test_estimate_by_band_translates_batch_requests():
    # A batch pod requesting batch-cpu/batch-memory must estimate PHYSICAL
    # cpu/memory usage (TranslateResourceNameByPriorityClass semantics).
    from koordinator_tpu.api.resources import ResourceDim

    req = np.zeros((2, R), np.int32)
    req[0, ResourceDim.BATCH_CPU] = 1000
    req[0, ResourceDim.BATCH_MEMORY] = 2048
    # pod 1 requests nothing -> defaults apply to physical dims only
    factors = np.full(R, 100, np.int32)
    factors[ResourceDim.CPU] = 85
    factors[ResourceDim.MEMORY] = 70
    defaults = np.zeros(R, np.int32)
    defaults[ResourceDim.CPU] = 250
    defaults[ResourceDim.MEMORY] = 200
    got = np.asarray(
        scoring.estimate_pod_usage_by_band(
            jnp.asarray(req), jnp.asarray(factors), jnp.asarray(defaults)
        )
    )
    assert got[0, ResourceDim.CPU] == 850        # round(1000*85/100)
    assert got[0, ResourceDim.MEMORY] == 1434    # round(2048*70/100) = 1433.6
    assert got[0, ResourceDim.BATCH_CPU] == 0    # no double count in batch dims
    assert got[1, ResourceDim.CPU] == 250        # defaults
    assert got[1, ResourceDim.MEMORY] == 200


def test_estimate_pod_usage_parity():
    p = 100
    req = RNG.integers(0, 50_000, size=(p, R)).astype(np.int32)
    req[RNG.random((p, R)) < 0.4] = 0
    factors = np.full(R, 100, np.int32)
    factors[0], factors[1] = 85, 70
    defaults = np.zeros(R, np.int32)
    defaults[0], defaults[1] = 250, 200
    got = np.asarray(
        scoring.estimate_pod_usage(
            jnp.asarray(req), jnp.asarray(factors), jnp.asarray(defaults)
        )
    )
    for i in range(p):
        assert got[i].tolist() == oracle.estimate_pod_usage(
            req[i].tolist(), factors.tolist(), defaults.tolist()
        ), i
