"""Reservation semantics: restore, policies, allocate-once, lifecycle.

Covers the reference behaviors in pkg/scheduler/plugins/reservation/
(transformer restore, Aligned/Restricted fit, nominator best-fit, Reserve
accounting) and the Pending->Available->Expired phase machine.
"""

import jax
import jax.numpy as jnp
import numpy as np

from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS, ResourceDim
from koordinator_tpu.ops.assignment import ScoringConfig
from koordinator_tpu.ops.reservation import (
    ReservationSet,
    allocate_from_reservation,
    nominate_reservation,
    reservation_fit,
    reservation_greedy_assign,
    score_pods_with_reservations,
)
from koordinator_tpu.scheduler.reservations import (
    OwnerMatcher,
    ReservationCache,
    ReservationPhase,
    ReservationSpec,
)
from koordinator_tpu.scheduler.snapshot import ClusterSnapshot, NodeSpec, PodSpec
from koordinator_tpu.state.cluster_state import ClusterState, PodBatch

R = NUM_RESOURCE_DIMS
CPU, MEM = ResourceDim.CPU, ResourceDim.MEMORY


def vec(cpu=0, mem=0):
    v = np.zeros(R, np.int32)
    v[CPU], v[MEM] = cpu, mem
    return v


def mk_state(node_cpus, requested_cpus=None, mem=65_536):
    alloc = np.zeros((len(node_cpus), R), np.int32)
    alloc[:, CPU] = node_cpus
    alloc[:, MEM] = mem
    req = None
    if requested_cpus is not None:
        req = np.zeros_like(alloc)
        req[:, CPU] = requested_cpus
    return ClusterState.from_arrays(alloc, requested=req)


def mk_pods(cpus, state, mem=1_024):
    req = np.zeros((len(cpus), R), np.int32)
    req[:, CPU] = cpus
    req[:, MEM] = mem
    return PodBatch.build(req, node_capacity=state.capacity)


def quiet_cfg():
    return ScoringConfig.default().replace(
        usage_thresholds=jnp.zeros(R, jnp.int32),
        estimator_defaults=jnp.zeros(R, jnp.int32),
    )


def one_reservation(node=0, cpu=4_000, mem=8_192, **kw):
    return ReservationSet.build(
        np.stack([vec(cpu, mem)]), np.array([node]), **kw
    )


def test_non_owner_cannot_use_reserved_capacity():
    # Node 0: 10 cores, 8 of which are reserved (charged to node_requested).
    state = mk_state([10_000], requested_cpus=[8_000])
    pods = mk_pods([4_000], state)
    rsv = one_reservation(node=0, cpu=8_000)
    match = jnp.zeros((pods.capacity, rsv.capacity), bool)  # not an owner
    _, feasible, _ = jax.jit(score_pods_with_reservations)(
        state, pods, quiet_cfg(), rsv, match
    )
    assert not bool(feasible[0, 0])


def test_owner_fits_via_reservation_restore():
    state = mk_state([10_000], requested_cpus=[8_000])
    pods = mk_pods([4_000], state)
    rsv = one_reservation(node=0, cpu=8_000)
    match = jnp.zeros((pods.capacity, rsv.capacity), bool).at[0, 0].set(True)
    scores, feasible, fits = jax.jit(score_pods_with_reservations)(
        state, pods, quiet_cfg(), rsv, match
    )
    assert bool(feasible[0, 0]) and bool(fits[0, 0])


def test_aligned_spill_uses_node_free():
    # 2 cores free on the node + 3 reserved => a 4-core owner pod fits (Aligned).
    state = mk_state([10_000], requested_cpus=[8_000])  # free = 2000
    pods = mk_pods([4_000], state)
    rsv = one_reservation(node=0, cpu=3_000)
    match = jnp.ones((pods.capacity, rsv.capacity), bool)
    fits = reservation_fit(rsv, state.free, pods.requests, match)
    assert bool(fits[0, 0])


def test_restricted_blocks_spill_on_reserved_dims():
    state = mk_state([10_000], requested_cpus=[8_000])  # free = 2000
    pods = mk_pods([4_000], state)
    rsv = one_reservation(node=0, cpu=3_000, restricted=np.array([True]))
    match = jnp.ones((pods.capacity, rsv.capacity), bool)
    fits = reservation_fit(rsv, state.free, pods.requests, match)
    assert not bool(fits[0, 0])  # 4000 > 3000 remaining, spill not allowed
    small = mk_pods([3_000], state)
    fits2 = reservation_fit(rsv, state.free, small.requests, match)
    assert bool(fits2[0, 0])


def test_nominate_prefers_best_fit():
    # Two reservations on node 0: 8-core and 3-core. A 2-core pod should take
    # the 3-core one (smallest sufficient remainder).
    state = mk_state([20_000], requested_cpus=[11_000])
    rsv = ReservationSet.build(
        np.stack([vec(8_000, 8_192), vec(3_000, 8_192)]), np.array([0, 0])
    )
    pods = mk_pods([2_000], state)
    match = jnp.ones((pods.capacity, rsv.capacity), bool)
    fits = reservation_fit(rsv, state.free, pods.requests, match)
    choice = nominate_reservation(fits, rsv, jnp.zeros(pods.capacity, jnp.int32))
    assert int(choice[0]) == 1


def test_allocate_once_consumes_everything():
    rsv = one_reservation(node=0, cpu=8_000, allocate_once=np.array([True]))
    new_rsv, spill = allocate_from_reservation(
        rsv, jnp.int32(0), jnp.asarray(vec(2_000, 512))
    )
    np.testing.assert_array_equal(
        np.asarray(new_rsv.allocated[0]), np.asarray(rsv.reserved[0])
    )
    assert int(spill[CPU]) == 0
    assert float(jnp.sum(new_rsv.remaining)) == 0


def test_greedy_assign_charges_reservation_then_node():
    # Node: 10 cores, 6 reserved. Owner pod of 8 cores: 6 from reservation,
    # 2 spill to node_requested.
    state = mk_state([10_000], requested_cpus=[6_000])
    pods = mk_pods([8_000], state, mem=1_024)
    rsv = one_reservation(node=0, cpu=6_000, mem=2_048)
    match = jnp.ones((pods.capacity, rsv.capacity), bool)
    a, rc, new_state, new_rsv, _ = jax.jit(reservation_greedy_assign)(
        state, pods, quiet_cfg(), rsv, match
    )
    assert int(a[0]) == 0 and int(rc[0]) == 0
    assert int(new_state.node_requested[0, CPU]) == 6_000 + 2_000
    assert int(new_rsv.allocated[0, CPU]) == 6_000


def test_greedy_assign_prefers_reserved_node():
    # Two identical nodes; reservation on node 1 => owner pod goes to node 1
    # even though node 0 is emptier by plain scoring.
    state = mk_state([10_000, 10_000], requested_cpus=[0, 4_000])
    pods = mk_pods([2_000], state)
    rsv = one_reservation(node=1, cpu=4_000)
    match = jnp.ones((pods.capacity, rsv.capacity), bool)
    a, rc, _, _, _ = jax.jit(reservation_greedy_assign)(
        state, pods, quiet_cfg(), rsv, match
    )
    assert int(a[0]) == 1 and int(rc[0]) == 0


def test_overloaded_node_stays_infeasible_even_for_owners():
    # Usage threshold CPU=65%; node at 90% usage. Reservation restore must not
    # bypass the LoadAware Filter.
    state = mk_state([10_000], requested_cpus=[8_000])
    state = state.replace(
        node_usage=state.node_usage.at[0, CPU].set(9_000),
        node_agg_usage=state.node_agg_usage.at[0, CPU].set(9_000),
    )
    pods = mk_pods([1_000], state)
    rsv = one_reservation(node=0, cpu=8_000)
    match = jnp.ones((pods.capacity, rsv.capacity), bool)
    cfg = ScoringConfig.default().replace(estimator_defaults=jnp.zeros(R, jnp.int32))
    _, feasible, _ = score_pods_with_reservations(state, pods, cfg, rsv, match)
    assert not bool(feasible[0, 0])


def test_unrequested_dim_negative_free_does_not_block():
    # Node shrank: allocatable < requested in MEM, pod requests only CPU.
    state = mk_state([10_000], requested_cpus=[8_000], mem=1_024)
    state = state.replace(
        node_requested=state.node_requested.at[0, MEM].set(2_048)
    )
    req = np.zeros((1, R), np.int32)
    req[0, CPU] = 3_000
    pods = PodBatch.build(req, node_capacity=state.capacity)
    rsv = one_reservation(node=0, cpu=8_000, mem=0)
    match = jnp.ones((pods.capacity, rsv.capacity), bool)
    fits = reservation_fit(rsv, state.free, pods.requests, match)
    assert bool(fits[0, 0])


def test_expire_after_node_deleted_does_not_crash():
    snap = ClusterSnapshot()
    snap.upsert_node(NodeSpec("n0", vec(10_000, 65_536)))
    snap.flush()
    cache = ReservationCache()
    cache.upsert(ReservationSpec("rsv-x", vec(4_000, 4_096), ttl_sec=10.0))
    cache.make_available("rsv-x", "n0", snap, now=0.0)
    snap.remove_node("n0")
    snap.flush()
    assert cache.expire_tick(now=11.0, snapshot=snap) == ["rsv-x"]
    assert cache.get("rsv-x").phase is ReservationPhase.EXPIRED


def test_exhausted_reservation_gets_no_boost():
    # Node 0 empty; node 1 carries a consumed allocate-once reservation.
    # Two owner pods: the first consumes it; the second must NOT be steered
    # to node 1 by a stale boost.
    state = mk_state([10_000, 10_000], requested_cpus=[0, 4_000])
    pods = mk_pods([2_000, 2_000], state)
    rsv = one_reservation(node=1, cpu=4_000, allocate_once=np.array([True]))
    match = jnp.ones((pods.capacity, rsv.capacity), bool)
    a, rc, _, _, _ = jax.jit(reservation_greedy_assign)(
        state, pods, quiet_cfg(), rsv, match
    )
    a, rc = np.asarray(a), np.asarray(rc)
    assert int(a[0]) == 1 and int(rc[0]) == 0      # first pod consumes it
    assert int(a[1]) == 0 and int(rc[1]) == -1     # second goes elsewhere


def test_greedy_assign_accepts_numpy_match():
    state = mk_state([10_000])
    pods = mk_pods([2_000], state)
    rsv = one_reservation(node=0, cpu=4_000)
    match = np.ones((pods.capacity, rsv.capacity), bool)  # numpy, not jnp
    a, rc, _, _, _ = reservation_greedy_assign(state, pods, quiet_cfg(), rsv, match)
    assert int(a[0]) == 0


def test_cache_lifecycle_and_expiration():
    snap = ClusterSnapshot()
    snap.upsert_node(NodeSpec("n0", vec(10_000, 65_536)))
    snap.flush()
    cache = ReservationCache()
    cache.upsert(
        ReservationSpec(
            "rsv-a", vec(6_000, 8_192),
            owners=[OwnerMatcher(labels={"app": "web"})],
            ttl_sec=60.0,
        )
    )
    cache.make_available("rsv-a", "n0", snap, now=100.0)
    assert cache.get("rsv-a").phase is ReservationPhase.AVAILABLE
    assert int(snap.state.node_requested[0, CPU]) == 6_000

    # Owner allocates 2 cores; on expiry only the remainder (4) returns.
    pod = PodSpec("p0", vec(2_000, 512), labels={"app": "web"})
    dev, names = cache.build_set(snap)
    match = cache.match_matrix([pod], 1, dev.capacity)
    assert match[0, 0]
    stranger = PodSpec("p1", vec(2_000, 512), labels={"app": "db"})
    assert not cache.match_matrix([stranger], 1, dev.capacity)[0, 0]

    cache.commit_allocations(names, [pod], np.array([0]), np.array([0]))
    assert cache.get("rsv-a").allocated[CPU] == 2_000

    expired = cache.expire_tick(now=161.0, snapshot=snap)
    assert expired == ["rsv-a"]
    assert cache.get("rsv-a").phase is ReservationPhase.EXPIRED
    assert int(snap.state.node_requested[0, CPU]) == 2_000  # allocated part stays


def test_allocate_once_commit_marks_succeeded():
    snap = ClusterSnapshot()
    snap.upsert_node(NodeSpec("n0", vec(10_000, 65_536)))
    snap.flush()
    cache = ReservationCache()
    cache.upsert(
        ReservationSpec(
            "rsv-b", vec(4_000, 4_096),
            owners=[OwnerMatcher(labels={"job": "x"})],
            allocate_once=True,
        )
    )
    cache.make_available("rsv-b", "n0", snap, now=0.0)
    dev, names = cache.build_set(snap)
    pod = PodSpec("p0", vec(1_000, 256), labels={"job": "x"})
    cache.commit_allocations(names, [pod], np.array([0]), np.array([0]))
    spec = cache.get("rsv-b")
    assert spec.phase is ReservationPhase.SUCCEEDED
    np.testing.assert_array_equal(spec.allocated, spec.requests)
