"""Randomized invariants of LowNodeLoad victim selection.

test_descheduler.py pins the reference scenarios; this sweeps random
cluster load shapes asserting the balance contract for ANY input:

  (source)   victims run only on abnormal nodes — overutilized for at
             least anomaly_rounds consecutive rounds — and are
             evictable
  (stop)     eviction stops at the high threshold: replaying the
             selection in order, every victim's node was still above
             high on a configured dim at its turn
  (headroom) the underutilized pool's budget never goes negative —
             victims must have somewhere to land
  (quiet)    with no overutilized or no underutilized nodes, nothing
             is evicted
"""

import jax.numpy as jnp
import numpy as np
import pytest

from tests.conftest import prop_seeds

from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS, ResourceDim
from koordinator_tpu.descheduler.lownodeload import (
    LowNodeLoadArgs,
    classify_nodes,
    eviction_budget,
    select_victims,
)

R = NUM_RESOURCE_DIMS
CPU, MEM = ResourceDim.CPU, ResourceDim.MEMORY


def _random_problem(rng: np.random.Generator):
    n_nodes = int(rng.integers(2, 10))
    n_pods = int(rng.integers(4, 50))
    cap = np.zeros((n_nodes, R), np.int32)
    cap[:, CPU] = rng.integers(8_000, 32_000, n_nodes)
    cap[:, MEM] = rng.integers(16_384, 131_072, n_nodes)
    usage = (cap * rng.uniform(0.05, 1.0, (n_nodes, R))).astype(np.int32)
    pod_node = rng.integers(0, n_nodes, n_pods).astype(np.int32)
    pod_usage = np.zeros((n_pods, R), np.int32)
    pod_usage[:, CPU] = rng.integers(50, 3_000, n_pods)
    pod_usage[:, MEM] = rng.integers(64, 8_192, n_pods)
    prio = rng.integers(3_000, 10_000, n_pods).astype(np.int32)
    evictable = rng.random(n_pods) < 0.8
    counters = rng.integers(0, 6, n_nodes).astype(np.int32)
    return cap, usage, pod_node, pod_usage, prio, evictable, counters


@pytest.mark.parametrize("seed", prop_seeds(24))
def test_select_victims_invariants(seed):
    rng = np.random.default_rng(seed)
    (cap, usage, pod_node, pod_usage, prio, evictable,
     counters) = _random_problem(rng)
    n_nodes = cap.shape[0]
    valid = jnp.ones(n_nodes, bool)
    args = LowNodeLoadArgs.default()

    victims = np.asarray(select_victims(
        jnp.asarray(usage), jnp.asarray(cap), valid,
        jnp.asarray(pod_node), jnp.asarray(pod_usage), jnp.asarray(prio),
        jnp.asarray(evictable), jnp.asarray(counters), args))

    under, over = (np.asarray(m) for m in classify_nodes(
        jnp.asarray(usage), jnp.asarray(cap), valid, args))
    abnormal = over & (counters >= int(args.anomaly_rounds))
    high = np.asarray(args.high_thresholds)
    high_quant = np.where(high >= 0, cap.astype(np.int64)
                          * np.maximum(high, 0) // 100, 2**30)
    budget0 = np.asarray(eviction_budget(
        jnp.asarray(usage), jnp.asarray(cap), jnp.asarray(under),
        jnp.asarray(high)))

    # (source)
    for v in np.flatnonzero(victims):
        assert evictable[v], f"seed {seed}: unevictable victim {v}"
        assert abnormal[pod_node[v]], (
            f"seed {seed}: victim {v} on non-abnormal node {pod_node[v]}")

    # (quiet)
    if not abnormal.any() or not under.any():
        if not under.any():
            # budget is zero without an underutilized pool: headroom
            # gating must have blocked everything
            assert (budget0 <= 0).any() or not victims.any()
        if not abnormal.any():
            assert not victims.any(), f"seed {seed}: evicted while calm"
        return

    # (stop) + (headroom): replay in the same cheapest-first order
    order = np.lexsort((pod_usage[:, 0], prio))
    node_usage = usage.astype(np.int64).copy()
    budget = budget0.astype(np.int64).copy()
    for idx in order:
        if not victims[idx]:
            continue
        n = pod_node[idx]
        still_hot = ((high >= 0) & (node_usage[n] > high_quant[n])).any()
        assert still_hot, (
            f"seed {seed}: victim {idx} evicted from node {n} already "
            f"at/below its high threshold")
        node_usage[n] -= pod_usage[idx]
        budget -= pod_usage[idx]
        assert (budget[high >= 0] >= 0).all(), (
            f"seed {seed}: pool headroom overdrawn after victim {idx}")
