"""Trend engine (koordinator_tpu/trend.py): slope math on known shapes,
verdict classification, and the leak classifier catching a deliberately
leaked fixture — ISSUE 9's "the instrument must be proven against a
planted leak before any soak verdict means anything".

Pure host math: no JAX anywhere near these tests.
"""

import math
import threading
import time

import numpy as np
import pytest

from koordinator_tpu import metrics, trend
from koordinator_tpu.koordlet.metriccache import MetricCache
from koordinator_tpu.selftelemetry import SelfTelemetry


def _fit(ts, values):
    return trend.fit_slope(np.asarray(ts, float), np.asarray(values, float))


class TestFitSlope:
    def test_constant_series(self):
        fit = _fit(range(100), [7.0] * 100)
        assert fit.slope == pytest.approx(0.0)
        assert fit.r2 == 1.0              # a flat line fits perfectly
        assert fit.growth == pytest.approx(0.0)

    def test_linear_series_exact(self):
        ts = np.arange(60.0)
        fit = _fit(ts, 5.0 + 2.5 * ts)
        assert fit.slope == pytest.approx(2.5)
        assert fit.r2 == pytest.approx(1.0)
        assert fit.growth == pytest.approx(2.5 * 59.0)
        assert fit.first == pytest.approx(5.0)
        assert fit.last == pytest.approx(5.0 + 2.5 * 59.0)

    def test_noisy_linear_series(self):
        rng = np.random.default_rng(42)
        ts = np.arange(200.0)
        values = 10.0 + 0.8 * ts + rng.normal(0, 3.0, 200)
        fit = _fit(ts, values)
        assert fit.slope == pytest.approx(0.8, rel=0.1)
        assert fit.r2 > 0.9               # trend dominates the noise

    def test_step_series(self):
        # flat, one step up, flat again: positive full-window slope
        # but each half is (near) flat
        ts = np.arange(100.0)
        values = np.where(ts < 50, 1.0, 101.0)
        fit = _fit(ts, values)
        assert fit.slope > 0
        lo = ts < 50
        first = _fit(ts[lo], values[lo])
        second = _fit(ts[~lo], values[~lo])
        assert first.slope == pytest.approx(0.0)
        assert second.slope == pytest.approx(0.0)

    def test_sawtooth_series_has_no_net_slope(self):
        ts = np.arange(400.0)
        values = ts % 40                   # ramps that always reset
        fit = _fit(ts, values)
        assert abs(fit.slope) < 0.02       # no net trend
        assert not math.isnan(fit.r2)

    def test_empty_and_single_sample_return_sentinel_not_nan(self):
        assert trend.fit_slope(np.empty(0), np.empty(0)) is None
        assert _fit([5.0], [1.0]) is None

    def test_zero_time_span_returns_sentinel(self):
        assert _fit([7.0, 7.0, 7.0], [1.0, 2.0, 3.0]) is None

    def test_unsorted_input_is_sorted_before_fitting(self):
        fit = _fit([3.0, 1.0, 2.0], [6.0, 2.0, 4.0])
        assert fit.slope == pytest.approx(2.0)
        assert fit.first == 2.0 and fit.last == 6.0

    def test_no_nan_ever(self):
        for ts, values in (
                ([0, 1], [0.0, 0.0]),
                ([0, 1, 2], [1e300, -1e300, 1e300]),
                (np.arange(5), np.zeros(5))):
            fit = _fit(ts, values)
            if fit is not None:
                for v in (fit.slope, fit.intercept, fit.r2, fit.growth):
                    assert not math.isnan(v)


class TestClassify:
    SPEC = trend.TrendSpec("s", abs_floor=10.0, max_rate_per_hour=100.0,
                           min_samples=4)

    def _verdict(self, ts, values, spec=None):
        ts = np.asarray(ts, float)
        values = np.asarray(values, float)
        fit = trend.fit_slope(ts, values)
        mid = ts.min() + (ts.max() - ts.min()) / 2
        lo = ts <= mid
        halves = (trend.fit_slope(ts[lo], values[lo]),
                  trend.fit_slope(ts[~lo], values[~lo]))
        return trend.classify(spec or self.SPEC, fit, halves)

    def test_constant_is_steady(self):
        assert self._verdict(range(100), [5.0] * 100)["verdict"] == "steady"

    def test_small_growth_under_floor_is_steady(self):
        # fast rate but total growth below abs_floor: noise immunity
        ts = np.arange(0, 10.0, 0.1)
        doc = self._verdict(ts, 0.05 * ts)     # grows 0.5 << floor 10
        assert doc["verdict"] == "steady"

    def test_slow_rate_under_threshold_is_steady(self):
        # large absolute growth but a rate under max_rate_per_hour
        ts = np.arange(0, 36000.0, 600.0)      # 10 hours
        doc = self._verdict(ts, ts * (50.0 / 3600.0))   # 50/h < 100/h
        assert doc["verdict"] == "steady"

    def test_sustained_growth_is_leaking(self):
        ts = np.arange(0, 600.0, 10.0)
        doc = self._verdict(ts, ts * 1.0)      # 3600/h, growth 590
        assert doc["verdict"] == "leaking"

    def test_step_is_drifting_not_leaking(self):
        ts = np.arange(0, 600.0, 10.0)
        values = np.where(ts < 300, 0.0, 500.0)
        doc = self._verdict(ts, values)
        assert doc["verdict"] == "drifting"    # one-shot, not persistent

    def test_downward_trend_is_drifting_when_leaks_grow_up(self):
        ts = np.arange(0, 600.0, 10.0)
        doc = self._verdict(ts, 1000.0 - ts)
        assert doc["verdict"] == "drifting"

    def test_sawtooth_is_steady(self):
        ts = np.arange(0, 600.0, 1.0)
        doc = self._verdict(ts, ts % 60)
        assert doc["verdict"] == "steady"      # churn, no net growth

    def test_big_sawtooth_never_classifies_as_leak(self):
        # 10x the amplitude: the phase remainder's fitted slope DOES
        # cross the thresholds, but a ramp-and-reset shape must fail
        # the persistence/r2 gate — drifting at worst, never leaking
        ts = np.arange(0, 600.0, 1.0)
        doc = self._verdict(ts, (ts % 60) * 10)
        assert doc["verdict"] in ("steady", "drifting")

    def test_too_few_samples_is_no_data(self):
        doc = self._verdict([0.0, 10.0, 20.0], [0.0, 5.0, 10.0])
        assert doc["verdict"] == "no_data"
        assert "reason" in doc

    def test_none_fit_is_no_data_never_nan(self):
        doc = trend.classify(self.SPEC, None)
        assert doc["verdict"] == "no_data"
        assert not any(isinstance(v, float) and math.isnan(v)
                       for v in doc.values())

    def test_uncorrelated_noise_never_leaks(self):
        # a slope through pure noise that happens to cross thresholds
        # must fail the r2 gate and downgrade to drifting at worst
        rng = np.random.default_rng(7)
        ts = np.arange(0, 60.0, 1.0)
        values = rng.normal(0, 500.0, len(ts))
        doc = self._verdict(ts, values)
        assert doc["verdict"] in ("steady", "drifting")


class TestTrendEngine:
    def _engine(self, spec, t0=1000.0):
        clock = lambda: self.now  # noqa: E731
        self.now = t0
        cache = MetricCache(clock=clock)
        return trend.TrendEngine(cache, specs=[spec], window_s=600.0,
                                 clock=clock), cache

    def test_leaky_series_is_flagged_and_gauged(self):
        spec = trend.TrendSpec("q_depth", abs_floor=10.0,
                               max_rate_per_hour=100.0, min_samples=4)
        engine, cache = self._engine(spec)
        for i in range(60):
            cache.append("q_depth", float(i * 5), ts=1000.0 + i * 10)
        self.now = 1000.0 + 59 * 10
        report = engine.evaluate()
        assert report["leaking"] == ["q_depth"]
        assert report["verdicts"]["leaking"] == 1
        assert metrics.trend_verdict.value(
            labels={"series": "q_depth"}) == trend.VERDICT_CODES["leaking"]
        assert metrics.trend_slope_per_hour.value(
            labels={"series": "q_depth"}) == pytest.approx(0.5 * 3600)

    def test_per_label_set_verdicts(self):
        spec = trend.TrendSpec("rss", abs_floor=10.0,
                               max_rate_per_hour=100.0, min_samples=4)
        engine, cache = self._engine(spec)
        for i in range(30):
            ts = 1000.0 + i * 10
            cache.append("rss", 5.0, labels={"binary": "a"}, ts=ts)
            cache.append("rss", float(i * 10), labels={"binary": "b"},
                         ts=ts)
        self.now = 1000.0 + 29 * 10
        report = engine.evaluate()
        by_labels = {tuple(sorted(d["labels"].items())): d["verdict"]
                     for d in report["series"]}
        assert by_labels[(("binary", "a"),)] == "steady"
        assert by_labels[(("binary", "b"),)] == "leaking"

    def test_report_caches_last_evaluation(self):
        spec = trend.TrendSpec("x", abs_floor=1.0, max_rate_per_hour=1.0)
        engine, cache = self._engine(spec)
        first = engine.report()          # evaluates on demand
        assert engine.report() is first  # retained

    def test_thread_leak_fixture_is_caught(self):
        """The deliberately-leaked fixture: a toy service that spawns a
        parked worker per 'request' and never reaps them.  The leak
        classifier over the sampled self-telemetry must flag
        koord_process_threads as leaking."""
        release = threading.Event()
        leaked = []
        try:
            telemetry = SelfTelemetry("toy-service")
            spec = trend.TrendSpec("koord_process_threads",
                                   abs_floor=8.0, max_rate_per_hour=32.0,
                                   min_samples=8)
            cache = MetricCache()
            engine = trend.TrendEngine(cache, specs=[spec], window_s=600.0)
            for i in range(24):
                # one "request" = one forgotten worker
                t = threading.Thread(target=release.wait, daemon=True)
                t.start()
                leaked.append(t)
                telemetry.sample()
                cache.append(
                    "koord_process_threads",
                    metrics.process_threads.value(
                        labels={"binary": "toy-service"}),
                    labels={"binary": "toy-service"},
                    ts=1000.0 + i * 30.0)
            report = engine.evaluate(now=1000.0 + 23 * 30.0)
            assert report["leaking"], report["series"]
            (doc,) = [d for d in report["series"]
                      if d["verdict"] == "leaking"]
            assert doc["series"] == "koord_process_threads"
            assert doc["rate_per_hour"] > 32.0
        finally:
            release.set()
            for t in leaked:
                t.join(timeout=5.0)

    def test_steady_service_stays_green(self):
        """Same toy service, workers reaped: threads stay flat."""
        telemetry = SelfTelemetry("tidy-service")
        spec = trend.TrendSpec("koord_process_threads",
                               abs_floor=8.0, max_rate_per_hour=32.0,
                               min_samples=8)
        cache = MetricCache()
        engine = trend.TrendEngine(cache, specs=[spec], window_s=600.0)
        for i in range(24):
            t = threading.Thread(target=lambda: None)
            t.start()
            t.join()                     # the worker is reaped
            telemetry.sample()
            cache.append(
                "koord_process_threads",
                metrics.process_threads.value(
                    labels={"binary": "tidy-service"}),
                labels={"binary": "tidy-service"},
                ts=1000.0 + i * 30.0)
        report = engine.evaluate(now=1000.0 + 23 * 30.0)
        assert not report["leaking"]


class TestSelfTelemetry:
    def test_sample_publishes_all_gauges(self):
        telemetry = SelfTelemetry("test-bin")
        telemetry.sample()
        labels = {"binary": "test-bin"}
        assert metrics.process_threads.value(labels=labels) >= 1.0
        assert metrics.process_alloc_blocks.value(labels=labels) > 0
        assert metrics.process_rss_bytes.value(labels=labels) > 0
        assert metrics.process_open_fds.value(labels=labels) > 0
        assert telemetry.samples == 1

    def test_background_sampler_stops_cleanly(self):
        telemetry = SelfTelemetry("bg-bin")
        telemetry.start(interval_s=0.05)
        time.sleep(0.15)
        telemetry.stop()
        assert telemetry.samples >= 1
        assert telemetry._thread is None

    def test_default_specs_cover_the_telemetry_series(self):
        series = {s.series for s in trend.default_trend_specs()}
        for name in ("koord_process_rss_bytes", "koord_process_open_fds",
                     "koord_process_threads",
                     "koord_scheduler_pending_pods",
                     "koord_transport_sync_binding_backlog_peak"):
            assert name in series
