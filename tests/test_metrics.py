"""Metrics registry: instruments, exposition format, wiring."""

import pytest

from koordinator_tpu.metrics import (
    Counter, Gauge, Histogram, Registry,
)


class TestInstruments:
    def test_counter_labels(self):
        c = Counter("requests_total")
        c.inc()
        c.inc(2, {"code": "200"})
        assert c.value() == 1
        assert c.value({"code": "200"}) == 2

    def test_gauge_set(self):
        g = Gauge("temp")
        g.set(3.5)
        g.set(1.0, {"node": "n1"})
        assert g.value() == 3.5
        assert g.value({"node": "n1"}) == 1.0

    def test_histogram_quantile(self):
        h = Histogram("lat", buckets=(0.1, 0.5, 1.0))
        for v in (0.05, 0.05, 0.2, 0.8):
            h.observe(v)
        # interpolated within the containing bucket (Prometheus
        # histogram_quantile): rank 2 tops out bucket [0, 0.1]
        assert h.quantile(0.5) == pytest.approx(0.1)
        # rank 3.96 -> bucket (0.5, 1.0]: 0.5 + 0.5 * 0.96
        assert h.quantile(0.99) == pytest.approx(0.98)

    def test_exposition_format(self):
        r = Registry("test")
        c = r.counter("hits", "hit count")
        c.inc(3, {"path": "/x"})
        h = r.histogram("lat", buckets=(0.1, 1.0))
        h.observe(0.05)
        text = r.expose()
        assert '# TYPE test_hits counter' in text
        assert 'test_hits{path="/x"} 3' in text
        assert 'test_lat_bucket{le="0.1"} 1' in text
        assert 'test_lat_bucket{le="+Inf"} 1' in text
        assert 'test_lat_count 1' in text

    def test_type_conflict_raises(self):
        r = Registry()
        r.counter("x")
        with pytest.raises(ValueError):
            r.gauge("x")


class TestHistogramQuantileInterpolation:
    """Bucket-interpolated quantiles from exposition state (ISSUE 5
    satellite): the SLO engine and tests compute p99 from the same
    math, including the +Inf-bucket edge cases."""

    def test_uniform_within_one_bucket(self):
        h = Histogram("lat", buckets=(1.0, 2.0, 4.0))
        for v in (1.2, 1.4, 1.6, 1.8):   # all in (1.0, 2.0]
            h.observe(v)
        # rank q*4 interpolates linearly across the (1.0, 2.0] bucket
        assert h.quantile(0.5) == pytest.approx(1.5)
        assert h.quantile(0.25) == pytest.approx(1.25)
        assert h.quantile(1.0) == pytest.approx(2.0)

    def test_first_bucket_interpolates_from_zero(self):
        h = Histogram("lat", buckets=(0.4, 1.0))
        h.observe(0.1)
        h.observe(0.3)
        assert h.quantile(0.5) == pytest.approx(0.2)   # 0 + 0.4 * (1/2)

    def test_inf_bucket_clamps_to_highest_finite_bound(self):
        h = Histogram("lat", buckets=(0.1, 0.5))
        for v in (7.0, 9.0, 11.0):   # every observation beyond 0.5
            h.observe(v)
        # the quantile of data the buckets cannot resolve is the best
        # bound they CAN name (Prometheus behavior), never inf/NaN
        assert h.quantile(0.5) == 0.5
        assert h.quantile(0.99) == 0.5

    def test_mixed_finite_and_inf_observations(self):
        h = Histogram("lat", buckets=(0.1, 0.5))
        for v in (0.05, 0.05, 0.05, 9.0):
            h.observe(v)
        assert h.quantile(0.5) <= 0.1
        assert h.quantile(0.99) == 0.5   # rank lands in +Inf -> clamp

    def test_empty_histogram_sentinel(self):
        h = Histogram("lat", buckets=(0.1,))
        assert h.quantile(0.99) == 0.0

    def test_exact_bucket_boundary_counts(self):
        from koordinator_tpu.metrics import count_at_or_below

        bounds, cum = [0.1, 0.5, 1.0], [2.0, 6.0, 8.0]
        assert count_at_or_below(bounds, cum, 8, 0.5) == pytest.approx(6.0)
        # halfway through the (0.1, 0.5] bucket: 2 + 4 * 0.5
        assert count_at_or_below(bounds, cum, 8, 0.3) == pytest.approx(4.0)
        # at/above the last finite bound: only what the buckets PROVE
        # is below — the 2 +Inf residents stay bad (a threshold >= the
        # last bound must not bless observations the buckets can't see)
        assert count_at_or_below(bounds, cum, 10, 1.0) == 8.0
        assert count_at_or_below(bounds, cum, 10, 2.0) == 8.0
        assert count_at_or_below(bounds, cum, 0, 0.5) == 0.0


class TestExpositionConformance:
    """Text-format spec conformance: label values escape backslash,
    double-quote, and line feed; HELP escapes backslash and line feed
    (the _render_labels bug ISSUE 3 names: raw specials corrupt the
    scrape body — one newline-carrying label breaks every later line)."""

    def test_label_value_escaping(self):
        r = Registry("esc")
        c = r.counter("hits", "hit count")
        c.inc(1, {"path": 'a\\b"c\nd'})
        text = r.expose()
        assert 'esc_hits{path="a\\\\b\\"c\\nd"} 1' in text
        # no raw newline may survive inside a sample line
        for line in text.splitlines():
            if line.startswith("esc_hits{"):
                assert line.endswith(" 1")

    def test_help_text_escaping(self):
        r = Registry("esc2")
        r.counter("c", 'backslash \\ and\nnewline and "quotes"')
        text = r.expose()
        assert ('# HELP esc2_c backslash \\\\ and\\nnewline and "quotes"'
                in text)

    def test_histogram_label_escaping_and_le_ordering(self):
        r = Registry("esc3")
        h = r.histogram("lat", buckets=(0.1, 1.0))
        h.observe(0.05, labels={"phase": 'So"lve\n'})
        text = r.expose()
        assert 'phase="So\\"lve\\n",le="0.1"' in text

    def test_reset_for_tests_keeps_registrations(self):
        r = Registry("rst")
        c = r.counter("hits")
        g = r.gauge("level")
        h = r.histogram("lat", buckets=(1.0,))
        c.inc(3, {"a": "b"})
        g.set(7.0)
        h.observe(0.5, exemplar={"trace_id": "t1"})
        r.reset_for_tests()
        assert c.value({"a": "b"}) == 0
        assert g.value() == 0
        assert h.quantile(0.5) == 0.0
        assert h.exemplars() == {}
        # same objects, still registered (no duplicate-registration error)
        assert r.counter("hits") is c
        assert r.gauge("level") is g

    def test_expose_all_covers_every_component_registry(self):
        from koordinator_tpu import metrics as m

        text = m.expose_all()
        for reg in m.ALL_REGISTRIES:
            assert f"{reg.prefix}_" in text
        # classic format has no OpenMetrics terminator...
        assert not text.endswith("# EOF\n")
        # ...but the OpenMetrics body MUST end with one, or a scraper
        # that negotiated openmetrics rejects the whole exposition
        assert m.expose_all(openmetrics=True).endswith("# EOF\n")

    def test_openmetrics_flag_parsing(self):
        from koordinator_tpu.metrics import parse_openmetrics_flag

        for truthy in ("1", "true", "TRUE", "yes", "on", True):
            assert parse_openmetrics_flag(truthy) is True
        for falsy in ("0", "", "false", "False", "no", "off", False,
                      None, "2"):
            assert parse_openmetrics_flag(falsy) is False


class TestWiring:
    def test_qos_eviction_counts(self, tmp_path):
        from koordinator_tpu.koordlet.qosmanager.framework import Evictor
        from koordinator_tpu.metrics import pod_eviction_total
        from tests.test_koordlet_metrics import FakeClock
        from tests.test_qosmanager import be_pod, make_ctx

        before = pod_eviction_total.value({"reason": "test-reason"})
        ctx = make_ctx(tmp_path, FakeClock())
        Evictor(ctx).evict(be_pod("a"), "test-reason")
        assert pod_eviction_total.value({"reason": "test-reason"}) == before + 1


def _load_check_dashboards():
    """Import tools/check_dashboards.py (tools/ is not a package)."""
    import importlib.util
    import os

    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "check_dashboards.py")
    spec = importlib.util.spec_from_file_location("check_dashboards", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestDashboards:
    """Shipped Grafana dashboards (dashboards/*.json) must reference only
    metric series that the registries actually register — enforced by
    the standalone drift tool (tools/check_dashboards.py), which
    tools/soak.sh also runs at the head of every soak."""

    def test_shipped_dashboards_pass_the_drift_check(self):
        tool = _load_check_dashboards()
        errors, checked = tool.check_dashboards()
        assert errors == []
        # the extractor actually extracted something — a regex/schema
        # rot must not degrade the check into a rubber stamp
        assert checked > 10

    def test_bogus_metric_fails_the_drift_check(self, tmp_path):
        import json

        tool = _load_check_dashboards()
        dash = tmp_path / "bogus.json"
        dash.write_text(json.dumps({"panels": [{
            "title": "drifted",
            "targets": [
                {"expr": "sum(rate(koord_scheduler_totally_bogus_total"
                         "[5m]))"},
                {"expr": "max(koord_scheduler_pending_pods)"},
            ]}]}))
        errors, checked = tool.check_dashboards([str(dash)])
        assert checked == 2
        assert len(errors) == 1
        assert "koord_scheduler_totally_bogus_total" in errors[0]
        assert "drifted" in errors[0]

    def test_tool_exits_nonzero_on_drift(self, tmp_path):
        import json

        tool = _load_check_dashboards()
        dash = tmp_path / "bogus.json"
        dash.write_text(json.dumps({"panels": [{
            "title": "p", "targets": [
                {"expr": "koordlet_metric_nobody_registered"}]}]}))
        assert tool.main([str(dash)]) == 1
        assert tool.main([]) == 0   # the CLI path over the shipped set

    def test_unreadable_dashboard_is_an_error_not_a_crash(self, tmp_path):
        tool = _load_check_dashboards()
        bad = tmp_path / "broken.json"
        bad.write_text("{not json")
        errors, _ = tool.check_dashboards([str(bad)])
        assert len(errors) == 1 and "unreadable" in errors[0]

    def test_monitor_feeds_prometheus_histograms(self):
        from koordinator_tpu import metrics as m
        from koordinator_tpu.scheduler.monitor import SchedulerMonitor

        before = m.scheduling_latency._totals.get((("phase", "Solve"),), 0)
        solve_before = m.solver_batch_latency._totals.get((), 0)
        mon = SchedulerMonitor()
        with mon.phase("Solve"):
            pass
        assert m.scheduling_latency._totals[(("phase", "Solve"),)] == before + 1
        assert m.solver_batch_latency._totals[()] == solve_before + 1

    def test_manager_and_descheduler_gauges_emit(self):
        """The dashboard's manager/descheduler series are fed by their
        controllers (registration alone isn't enough — panels need data)."""
        from koordinator_tpu import metrics as m
        from koordinator_tpu.descheduler.framework import (
            MODE_DELETE, Evictor,
        )
        from koordinator_tpu.descheduler.migration import (
            MigrationController, MigrationJob,
        )
        from koordinator_tpu.manager import sloconfig
        from koordinator_tpu.manager.noderesource_controller import (
            NodeRecord, NodeResourceController,
        )

        nrc = NodeResourceController(
            sloconfig.ColocationConfig(enable=True), clock=lambda: 1000.0)
        nrc.reconcile([NodeRecord(name="m1", cpu_capacity_milli=16_000,
                                  mem_capacity_mib=32_768)])
        assert m.batch_resource_allocatable.value(
            labels={"node": "m1", "resource": "batch-cpu"}) == 0.0
        # no metric report ever -> degraded -> expired gauge raised
        assert m.node_metric_expired.value(labels={"node": "m1"}) == 1.0

        ctl = MigrationController(clock=lambda: 0.0)
        ctl.submit(MigrationJob(name="j1", pod="p1", node="n0"))
        ctl.reconcile()
        assert m.migration_jobs.value(labels={"phase": "Running"}) >= 1.0

        ev = Evictor(mode=MODE_DELETE, delete_fn=lambda p: True)
        ev.profile = "lownodeload"
        before = m.descheduler_evictions_total.value(
            labels={"profile": "lownodeload", "reason": "hot"})

        class P:
            uid = "p1"
        ev.evict(P(), "hot")
        assert m.descheduler_evictions_total.value(
            labels={"profile": "lownodeload", "reason": "hot"}) == before + 1
