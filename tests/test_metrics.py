"""Metrics registry: instruments, exposition format, wiring."""

import pytest

from koordinator_tpu.metrics import (
    Counter, Gauge, Histogram, Registry,
)


class TestInstruments:
    def test_counter_labels(self):
        c = Counter("requests_total")
        c.inc()
        c.inc(2, {"code": "200"})
        assert c.value() == 1
        assert c.value({"code": "200"}) == 2

    def test_gauge_set(self):
        g = Gauge("temp")
        g.set(3.5)
        g.set(1.0, {"node": "n1"})
        assert g.value() == 3.5
        assert g.value({"node": "n1"}) == 1.0

    def test_histogram_quantile(self):
        h = Histogram("lat", buckets=(0.1, 0.5, 1.0))
        for v in (0.05, 0.05, 0.2, 0.8):
            h.observe(v)
        assert h.quantile(0.5) == 0.1
        assert h.quantile(0.99) == 1.0

    def test_exposition_format(self):
        r = Registry("test")
        c = r.counter("hits", "hit count")
        c.inc(3, {"path": "/x"})
        h = r.histogram("lat", buckets=(0.1, 1.0))
        h.observe(0.05)
        text = r.expose()
        assert '# TYPE test_hits counter' in text
        assert 'test_hits{path="/x"} 3' in text
        assert 'test_lat_bucket{le="0.1"} 1' in text
        assert 'test_lat_bucket{le="+Inf"} 1' in text
        assert 'test_lat_count 1' in text

    def test_type_conflict_raises(self):
        r = Registry()
        r.counter("x")
        with pytest.raises(ValueError):
            r.gauge("x")


class TestExpositionConformance:
    """Text-format spec conformance: label values escape backslash,
    double-quote, and line feed; HELP escapes backslash and line feed
    (the _render_labels bug ISSUE 3 names: raw specials corrupt the
    scrape body — one newline-carrying label breaks every later line)."""

    def test_label_value_escaping(self):
        r = Registry("esc")
        c = r.counter("hits", "hit count")
        c.inc(1, {"path": 'a\\b"c\nd'})
        text = r.expose()
        assert 'esc_hits{path="a\\\\b\\"c\\nd"} 1' in text
        # no raw newline may survive inside a sample line
        for line in text.splitlines():
            if line.startswith("esc_hits{"):
                assert line.endswith(" 1")

    def test_help_text_escaping(self):
        r = Registry("esc2")
        r.counter("c", 'backslash \\ and\nnewline and "quotes"')
        text = r.expose()
        assert ('# HELP esc2_c backslash \\\\ and\\nnewline and "quotes"'
                in text)

    def test_histogram_label_escaping_and_le_ordering(self):
        r = Registry("esc3")
        h = r.histogram("lat", buckets=(0.1, 1.0))
        h.observe(0.05, labels={"phase": 'So"lve\n'})
        text = r.expose()
        assert 'phase="So\\"lve\\n",le="0.1"' in text

    def test_reset_for_tests_keeps_registrations(self):
        r = Registry("rst")
        c = r.counter("hits")
        g = r.gauge("level")
        h = r.histogram("lat", buckets=(1.0,))
        c.inc(3, {"a": "b"})
        g.set(7.0)
        h.observe(0.5, exemplar={"trace_id": "t1"})
        r.reset_for_tests()
        assert c.value({"a": "b"}) == 0
        assert g.value() == 0
        assert h.quantile(0.5) == 0.0
        assert h.exemplars() == {}
        # same objects, still registered (no duplicate-registration error)
        assert r.counter("hits") is c
        assert r.gauge("level") is g

    def test_expose_all_covers_every_component_registry(self):
        from koordinator_tpu import metrics as m

        text = m.expose_all()
        for reg in m.ALL_REGISTRIES:
            assert f"{reg.prefix}_" in text
        # classic format has no OpenMetrics terminator...
        assert not text.endswith("# EOF\n")
        # ...but the OpenMetrics body MUST end with one, or a scraper
        # that negotiated openmetrics rejects the whole exposition
        assert m.expose_all(openmetrics=True).endswith("# EOF\n")

    def test_openmetrics_flag_parsing(self):
        from koordinator_tpu.metrics import parse_openmetrics_flag

        for truthy in ("1", "true", "TRUE", "yes", "on", True):
            assert parse_openmetrics_flag(truthy) is True
        for falsy in ("0", "", "false", "False", "no", "off", False,
                      None, "2"):
            assert parse_openmetrics_flag(falsy) is False


class TestWiring:
    def test_qos_eviction_counts(self, tmp_path):
        from koordinator_tpu.koordlet.qosmanager.framework import Evictor
        from koordinator_tpu.metrics import pod_eviction_total
        from tests.test_koordlet_metrics import FakeClock
        from tests.test_qosmanager import be_pod, make_ctx

        before = pod_eviction_total.value({"reason": "test-reason"})
        ctx = make_ctx(tmp_path, FakeClock())
        Evictor(ctx).evict(be_pod("a"), "test-reason")
        assert pod_eviction_total.value({"reason": "test-reason"}) == before + 1


class TestDashboards:
    """Shipped Grafana dashboards (dashboards/*.json) must reference only
    metric series that the registries actually register (reference ships
    dashboards/scheduling.json + descheduling.json)."""

    def _series_names(self):
        from koordinator_tpu import metrics as m

        names = set()
        for reg in (m.SCHEDULER, m.KOORDLET, m.MANAGER, m.DESCHEDULER,
                    m.TRANSPORT):
            for full, metric in reg._metrics.items():
                names.add(full)
                if isinstance(metric, m.Histogram):
                    names.update({f"{full}_bucket", f"{full}_sum",
                                  f"{full}_count"})
        return names

    def test_dashboard_exprs_use_registered_metrics(self):
        import glob
        import json
        import os
        import re

        root = os.path.join(os.path.dirname(__file__), "..", "dashboards")
        files = sorted(glob.glob(os.path.join(root, "*.json")))
        assert len(files) >= 2, "scheduling + descheduling dashboards"
        known = self._series_names()
        checked = 0
        for path in files:
            doc = json.load(open(path))
            for panel in doc.get("panels", []):
                for target in panel.get("targets", []):
                    for name in re.findall(
                            r"(koord_[a-z0-9_]+|koordlet_[a-z0-9_]+)",
                            target["expr"]):
                        assert name in known, (path, name)
                        checked += 1
        assert checked > 10

    def test_monitor_feeds_prometheus_histograms(self):
        from koordinator_tpu import metrics as m
        from koordinator_tpu.scheduler.monitor import SchedulerMonitor

        before = m.scheduling_latency._totals.get((("phase", "Solve"),), 0)
        solve_before = m.solver_batch_latency._totals.get((), 0)
        mon = SchedulerMonitor()
        with mon.phase("Solve"):
            pass
        assert m.scheduling_latency._totals[(("phase", "Solve"),)] == before + 1
        assert m.solver_batch_latency._totals[()] == solve_before + 1

    def test_manager_and_descheduler_gauges_emit(self):
        """The dashboard's manager/descheduler series are fed by their
        controllers (registration alone isn't enough — panels need data)."""
        from koordinator_tpu import metrics as m
        from koordinator_tpu.descheduler.framework import (
            MODE_DELETE, Evictor,
        )
        from koordinator_tpu.descheduler.migration import (
            MigrationController, MigrationJob,
        )
        from koordinator_tpu.manager import sloconfig
        from koordinator_tpu.manager.noderesource_controller import (
            NodeRecord, NodeResourceController,
        )

        nrc = NodeResourceController(
            sloconfig.ColocationConfig(enable=True), clock=lambda: 1000.0)
        nrc.reconcile([NodeRecord(name="m1", cpu_capacity_milli=16_000,
                                  mem_capacity_mib=32_768)])
        assert m.batch_resource_allocatable.value(
            labels={"node": "m1", "resource": "batch-cpu"}) == 0.0
        # no metric report ever -> degraded -> expired gauge raised
        assert m.node_metric_expired.value(labels={"node": "m1"}) == 1.0

        ctl = MigrationController(clock=lambda: 0.0)
        ctl.submit(MigrationJob(name="j1", pod="p1", node="n0"))
        ctl.reconcile()
        assert m.migration_jobs.value(labels={"phase": "Running"}) >= 1.0

        ev = Evictor(mode=MODE_DELETE, delete_fn=lambda p: True)
        ev.profile = "lownodeload"
        before = m.descheduler_evictions_total.value(
            labels={"profile": "lownodeload", "reason": "hot"})

        class P:
            uid = "p1"
        ev.evict(P(), "hot")
        assert m.descheduler_evictions_total.value(
            labels={"profile": "lownodeload", "reason": "hot"}) == before + 1
