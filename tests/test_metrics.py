"""Metrics registry: instruments, exposition format, wiring."""

import pytest

from koordinator_tpu.metrics import (
    Counter, Gauge, Histogram, Registry,
)


class TestInstruments:
    def test_counter_labels(self):
        c = Counter("requests_total")
        c.inc()
        c.inc(2, {"code": "200"})
        assert c.value() == 1
        assert c.value({"code": "200"}) == 2

    def test_gauge_set(self):
        g = Gauge("temp")
        g.set(3.5)
        g.set(1.0, {"node": "n1"})
        assert g.value() == 3.5
        assert g.value({"node": "n1"}) == 1.0

    def test_histogram_quantile(self):
        h = Histogram("lat", buckets=(0.1, 0.5, 1.0))
        for v in (0.05, 0.05, 0.2, 0.8):
            h.observe(v)
        assert h.quantile(0.5) == 0.1
        assert h.quantile(0.99) == 1.0

    def test_exposition_format(self):
        r = Registry("test")
        c = r.counter("hits", "hit count")
        c.inc(3, {"path": "/x"})
        h = r.histogram("lat", buckets=(0.1, 1.0))
        h.observe(0.05)
        text = r.expose()
        assert '# TYPE test_hits counter' in text
        assert 'test_hits{path="/x"} 3' in text
        assert 'test_lat_bucket{le="0.1"} 1' in text
        assert 'test_lat_bucket{le="+Inf"} 1' in text
        assert 'test_lat_count 1' in text

    def test_type_conflict_raises(self):
        r = Registry()
        r.counter("x")
        with pytest.raises(ValueError):
            r.gauge("x")


class TestWiring:
    def test_qos_eviction_counts(self, tmp_path):
        from koordinator_tpu.koordlet.qosmanager.framework import Evictor
        from koordinator_tpu.metrics import pod_eviction_total
        from tests.test_koordlet_metrics import FakeClock
        from tests.test_qosmanager import be_pod, make_ctx

        before = pod_eviction_total.value({"reason": "test-reason"})
        ctx = make_ctx(tmp_path, FakeClock())
        Evictor(ctx).evict(be_pod("a"), "test-reason")
        assert pod_eviction_total.value({"reason": "test-reason"}) == before + 1
