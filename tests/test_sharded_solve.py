"""Shard-count invariance of the node-axis shard_map solve (ISSUE 10).

The contract under test (parallel/sharded.py module docstring): for any
1/2/4/8-way nodes-axis mesh on the virtual 8-device CPU platform, the
sharded selection / propose-accept rounds / incremental dirty-node
refresh produce BIT-IDENTICAL assignments, node accounting and quota
charges to the single-device solver — and the >32,768-node wide
ranking-key regime composes with sharding (the old ceiling is gone).

Compile cost dominates on CPU, so the suite reuses ONE small problem and
sweeps shard counts inside each test (the jit caches persist across the
sweep's reference solves).
"""

import numpy as np
import pytest

from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS, ResourceDim
from koordinator_tpu.ops import batch_assign as ba
from koordinator_tpu.ops.assignment import ScoringConfig
from koordinator_tpu.parallel import mesh as pmesh
from koordinator_tpu.parallel import sharded as ps
from koordinator_tpu.quota.admission import QuotaDeviceState
from koordinator_tpu.quota.tree import UNBOUNDED, QuotaTree
from koordinator_tpu.state.cluster_state import _bucket

from tests.test_mesh import build_problem

R = NUM_RESOURCE_DIMS
CPU = ResourceDim.CPU

SHARD_COUNTS = (1, 2, 4, 8)
#: the 1/2/4/8 sweeps keep programs small (single stratum, tiny k) —
#: compile count x4 dominates tier-1 cost; the stratified default is
#: covered once at mesh width in test_pass_pipeline_invariant
K, ROUNDS, SB = 4, 2, 5


def _mesh(d):
    import jax

    return pmesh.solver_mesh(jax.devices()[:d])


def _quota_fixture(pods):
    import jax.numpy as jnp

    total = np.zeros(R, np.int64)
    total[CPU] = 60_000
    tree = QuotaTree(total)
    mx = np.full(R, UNBOUNDED, np.int64)
    mx[CPU] = 24_000
    tree.add("q", min=np.zeros(R, np.int64), max=mx)
    tree.set_request("q", total)
    tree.refresh_runtime()
    # depth 3 (not the default 8): every unused ancestor level unrolls
    # another device-wide prefix-accept sort into the rounds program,
    # and compile time is this suite's tier-1 budget
    quota, index = QuotaDeviceState.from_tree(tree, max_depth=3)
    qid = np.full(pods.capacity, -1, np.int32)
    qid[4:20] = index["q"]
    return quota, pods.replace(quota_id=jnp.asarray(qid))


def test_selection_and_rounds_invariant_across_shard_counts():
    """select + quota-charged rounds: assignments, node accounting and
    quota headroom bit-identical at 1/2/4/8 shards."""
    state, pods = build_problem(n_nodes=64, n_pods=32)
    cfg = ScoringConfig.default()
    quota, pods = _quota_fixture(pods)
    ck, cn, cs = ba.select_candidates(state, pods, cfg, k=K,
                                      spread_bits=SB, method="exact",
                                      with_scores=True)
    a_ref, st_ref, q_ref = ba._assign_rounds(state, pods, quota, ck, cn,
                                             ROUNDS)
    valid = np.asarray(ck) >= 0
    for d in SHARD_COUNTS:
        mesh = _mesh(d)
        sck, scn, scs = ps.sharded_select_candidates(
            mesh, state, pods, cfg, k=K, spread_bits=SB,
            with_scores=True)
        np.testing.assert_array_equal(np.asarray(sck), np.asarray(ck),
                                      err_msg=f"keys d={d}")
        np.testing.assert_array_equal(
            np.asarray(scn)[valid], np.asarray(cn)[valid],
            err_msg=f"nodes d={d}")
        np.testing.assert_array_equal(
            np.asarray(scs)[valid], np.asarray(cs)[valid],
            err_msg=f"scores d={d}")
        if d == 1:
            # the single-device reference above IS the 1-device solve;
            # compiling a 1-way rounds program re-proves it at real
            # tier-1 cost (selection still exercises the 1-way
            # shard_map path)
            continue
        a, st, q = ps.sharded_assign_rounds(mesh, state, pods, quota,
                                            sck, scn, ROUNDS)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(a_ref),
                                      err_msg=f"assignments d={d}")
        np.testing.assert_array_equal(
            np.asarray(st.node_requested),
            np.asarray(st_ref.node_requested), err_msg=f"state d={d}")
        np.testing.assert_array_equal(
            np.asarray(q.headroom), np.asarray(q_ref.headroom),
            err_msg=f"quota d={d}")


def test_incremental_refresh_invariant_across_shard_counts():
    """The dirty-node refresh: a dirty node rescores only on its owning
    shard, yet the merged cache equals the single-device refresh and the
    post-refresh solve is bit-identical at every shard count."""
    import jax.numpy as jnp

    state, pods = build_problem(n_nodes=64, n_pods=32, seed=11)
    cfg = ScoringConfig.default()
    ck, cn, cs = ba.select_candidates(state, pods, cfg, k=K,
                                      spread_bits=SB, method="exact",
                                      with_scores=True)
    cache = ba.CandidateCache(ck, cn, cs)
    # ~1% of a real cluster collapses to one node here; dirty a couple of
    # rows spread across different shards of the 8-way split
    dirty = [3, 40]
    dpad = _bucket(len(dirty), minimum=64)
    drows = np.zeros(dpad, np.int32)
    drows[: len(dirty)] = dirty
    dvalid = np.zeros(dpad, bool)
    dvalid[: len(dirty)] = True
    st2 = state.replace(
        node_usage=state.node_usage.at[jnp.asarray(dirty)].set(0))
    rk_ref, rc_ref = ba.refresh_candidates(
        st2, pods, cfg, cache, jnp.asarray(drows), jnp.asarray(dvalid),
        k=K, spread_bits=SB)
    a_ref, st_ref, _ = ba._assign_rounds(st2, pods, None, rk_ref,
                                         rc_ref.cand_node, ROUNDS)
    valid = np.asarray(rk_ref) >= 0
    for d in SHARD_COUNTS:
        mesh = _mesh(d)
        rk, rc = ps.sharded_refresh_candidates(
            mesh, st2, pods, cfg, cache, jnp.asarray(drows),
            jnp.asarray(dvalid), k=K, spread_bits=SB)
        np.testing.assert_array_equal(np.asarray(rk), np.asarray(rk_ref),
                                      err_msg=f"refresh keys d={d}")
        np.testing.assert_array_equal(
            np.asarray(rc.cand_node)[valid],
            np.asarray(rc_ref.cand_node)[valid],
            err_msg=f"refresh nodes d={d}")
        np.testing.assert_array_equal(
            np.asarray(rc.cand_score)[valid],
            np.asarray(rc_ref.cand_score)[valid],
            err_msg=f"refresh scores d={d}")
        # assignments from the dirty path, per shard count: the merged
        # cache is bit-identical, so solving each d's refreshed
        # candidates through the (already compiled) single-device
        # rounds must land on the reference assignments — the
        # non-vacuous cross-check without a new rounds program per d
        a, _, _ = ba._assign_rounds(st2, pods, None, rk, rc.cand_node,
                                    ROUNDS)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(a_ref),
                                      err_msg=f"post-refresh d={d}")


def test_pass_pipeline_invariant_at_mesh_width():
    """assign_round_pass + assign_followup_pass (the scheduler's
    incremental pass loop) at the full 8-way mesh: est accumulation,
    followup re-selection and the commit accounting bit-identical."""
    state, pods = build_problem(n_nodes=64, n_pods=32, seed=7)
    cfg = ScoringConfig.default()
    # quota=None here: the quota-on-mesh parity (admission + prefix +
    # charges) is already pinned across shard counts by the rounds
    # sweep above, and the quota chain doubles these two programs'
    # compile cost — the pass loop's OWN semantics (est accumulation,
    # followup re-select against the augmented state, commit into the
    # un-augmented accounting) are what this test adds
    k, rounds = 8, 4            # the stratified (5, 15) default path
    ck, cn, _ = ba.select_candidates(state, pods, cfg, k=k,
                                     method="exact", with_scores=True)
    ref1 = ba.assign_round_pass(state, pods, None, ck, cn, cfg,
                                rounds=rounds)
    ref2 = ba.assign_followup_pass(state, ref1[3], pods, None, cfg,
                                   k=k, rounds=rounds, method="exact")
    mesh = _mesh(8)
    a1, st1, _, est1 = ps.sharded_assign_round_pass(
        mesh, state, pods, None, ck, cn, cfg, rounds=rounds)
    np.testing.assert_array_equal(np.asarray(a1), np.asarray(ref1[0]))
    np.testing.assert_array_equal(np.asarray(st1.node_requested),
                                  np.asarray(ref1[1].node_requested))
    np.testing.assert_array_equal(np.asarray(est1), np.asarray(ref1[3]))
    a2, st2, _, est2 = ps.sharded_assign_followup_pass(
        mesh, state, est1, pods, None, cfg, k=k, rounds=rounds)
    np.testing.assert_array_equal(np.asarray(a2), np.asarray(ref2[0]))
    np.testing.assert_array_equal(np.asarray(st2.node_requested),
                                  np.asarray(ref2[1].node_requested))
    np.testing.assert_array_equal(np.asarray(est2), np.asarray(ref2[3]))


def test_wide_regime_breaks_the_old_ceiling():
    """A 65,536-node problem — double the old 32,768 wall — selects and
    solves, and the 2-way sharded solve matches bit-for-bit."""
    state, pods = build_problem(n_nodes=65_536, n_pods=8, seed=5)
    cfg = ScoringConfig.default()
    assert not ba._packed_regime(state.capacity)
    ck, cn = ba.select_candidates(state, pods, cfg, k=K, spread_bits=SB,
                                  method="exact")
    a_ref, st_ref, _ = ba._assign_rounds(state, pods, None, ck, cn,
                                         ROUNDS)
    assert int((np.asarray(a_ref) >= 0).sum()) == 8
    mesh = _mesh(2)
    sck, scn = ps.sharded_select_candidates(mesh, state, pods, cfg, k=K,
                                            spread_bits=SB)
    valid = np.asarray(ck) >= 0
    np.testing.assert_array_equal(np.asarray(sck), np.asarray(ck))
    np.testing.assert_array_equal(np.asarray(scn)[valid],
                                  np.asarray(cn)[valid])
    # identical candidates => identical rounds (the rounds are a pure
    # function of (state, pods, candidates); their 1/2/4/8 invariance is
    # proven at small shapes above — recompiling them at 65k columns
    # buys no new evidence and real tier-1 seconds)
    # no overcommit at the new scale
    assert (np.asarray(st_ref.node_requested)
            <= np.asarray(st_ref.node_allocatable)).all()


def test_wide_regime_rank_matches_lexicographic_oracle():
    """Wide-regime top-k == a NumPy (quantized score, tie-break)
    lexicographic sort oracle — the exactness anchor the packed-key
    regime has had since PR 1, restated past the 2**15 wall."""
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    p, n_total = 2, 40_000
    scores = rng.integers(0, 3_000, (p, n_total)).astype(np.int32)
    feasible = rng.random((p, n_total)) < 0.5
    sb = 5
    key, tb = ba._rank_parts(jnp.asarray(scores), jnp.asarray(feasible),
                             sb, jnp.arange(p, dtype=jnp.int32))
    kv, idx = ba._topk_by_rank(key, tb, 16, n_total)
    key_np, tb_np = np.asarray(key), np.asarray(tb)
    for i in range(p):
        order = np.lexsort((-tb_np[i], -key_np[i]))
        np.testing.assert_array_equal(np.asarray(idx)[i], order[:16],
                                      err_msg=f"row {i}")
        np.testing.assert_array_equal(np.asarray(kv)[i],
                                      key_np[i][order[:16]])


def test_check_node_capacity_new_ceiling():
    """The 32,768 wall is deleted; the loud guard moved to 2**30."""
    ba.check_node_capacity(40_960)            # the old failure shape
    ba.check_node_capacity(ba.MAX_NODE_CAPACITY)
    with pytest.raises(ValueError, match="ranking-key ceiling"):
        ba.check_node_capacity(ba.MAX_NODE_CAPACITY + 1)


def test_capacity_must_divide_over_the_mesh():
    state, pods = build_problem(n_nodes=60, n_pods=8)
    cfg = ScoringConfig.default()
    with pytest.raises(ValueError, match="does not divide"):
        ps.sharded_select_candidates(_mesh(8), state, pods, cfg, k=4)


def test_scheduler_sharded_rounds_equal_single_device():
    """End-to-end Scheduler parity: the same feed solved by a
    sharded-by-default scheduler (8-way mesh engaged via
    shard_min_nodes=0) and a single-device one binds identical pods to
    identical nodes and charges identical quota, across steady-state
    rounds that exercise the incremental dirty path."""
    from tests.test_incremental_solve import (
        _assert_no_overcommit,
        _feed_nodes,
        _mk_sched,
        _pod,
    )

    rng = np.random.default_rng(3)
    sharded = _mk_sched(True, mesh="auto", shard_min_nodes=0)
    single = _mk_sched(True, mesh="off")
    assert sharded.mesh is not None and sharded.solver_shard_count == 8
    assert single.mesh is None
    for sched in (sharded, single):
        sched.incremental_dirty_threshold = 1.0
    rng2 = np.random.default_rng(3)
    _feed_nodes(sharded, rng, n=12)
    _feed_nodes(single, rng2, n=12)
    took_incremental = False
    for rnd in range(4):
        for j in range(3):
            name = f"p{rnd}-{j}"
            pa, pb = _pod(rng, name), _pod(rng2, name)
            sharded.enqueue(pa)
            single.enqueue(pb)
        ra = sharded.schedule_round()
        rb = single.schedule_round()
        assert ra.assignments == rb.assignments, f"round {rnd}"
        assert set(ra.failures) == set(rb.failures), f"round {rnd}"
        if sharded.last_solve_path == "incremental":
            took_incremental = True
    assert sharded.snapshot.solver_sharding_active
    assert took_incremental, "incremental path never engaged while sharded"
    _assert_no_overcommit(sharded)
    np.testing.assert_array_equal(
        np.asarray(sharded.snapshot.state.node_requested),
        np.asarray(single.snapshot.state.node_requested))
