"""Shard-count invariance of the node-axis shard_map solve (ISSUE 10).

The contract under test (parallel/sharded.py module docstring): for any
1/2/4/8-way nodes-axis mesh on the virtual 8-device CPU platform, the
sharded selection / propose-accept rounds / incremental dirty-node
refresh produce BIT-IDENTICAL assignments, node accounting and quota
charges to the single-device solver — and the >32,768-node wide
ranking-key regime composes with sharding (the old ceiling is gone).

Compile cost dominates on CPU, so the suite reuses ONE small problem and
sweeps shard counts inside each test (the jit caches persist across the
sweep's reference solves).
"""

import numpy as np
import pytest

from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS, ResourceDim
from koordinator_tpu.ops import batch_assign as ba
from koordinator_tpu.ops.assignment import ScoringConfig
from koordinator_tpu.parallel import mesh as pmesh
from koordinator_tpu.parallel import sharded as ps
from koordinator_tpu.quota.admission import QuotaDeviceState
from koordinator_tpu.quota.tree import UNBOUNDED, QuotaTree
from koordinator_tpu.state.cluster_state import _bucket

from tests.test_mesh import build_problem

R = NUM_RESOURCE_DIMS
CPU = ResourceDim.CPU

SHARD_COUNTS = (1, 2, 4, 8)
#: the 1/2/4/8 sweeps keep programs small (single stratum, tiny k) —
#: compile count x4 dominates tier-1 cost; the stratified default is
#: covered once at mesh width in test_pass_pipeline_invariant
K, ROUNDS, SB = 4, 2, 5


def _mesh(d):
    import jax

    return pmesh.solver_mesh(jax.devices()[:d])


def _quota_fixture(pods):
    import jax.numpy as jnp

    total = np.zeros(R, np.int64)
    total[CPU] = 60_000
    tree = QuotaTree(total)
    mx = np.full(R, UNBOUNDED, np.int64)
    mx[CPU] = 24_000
    tree.add("q", min=np.zeros(R, np.int64), max=mx)
    tree.set_request("q", total)
    tree.refresh_runtime()
    # depth 3 (not the default 8): every unused ancestor level unrolls
    # another device-wide prefix-accept sort into the rounds program,
    # and compile time is this suite's tier-1 budget
    quota, index = QuotaDeviceState.from_tree(tree, max_depth=3)
    qid = np.full(pods.capacity, -1, np.int32)
    qid[4:20] = index["q"]
    return quota, pods.replace(quota_id=jnp.asarray(qid))


def test_selection_and_rounds_invariant_across_shard_counts():
    """select + quota-charged rounds: assignments, node accounting and
    quota headroom bit-identical at 1/2/4/8 shards."""
    state, pods = build_problem(n_nodes=64, n_pods=32)
    cfg = ScoringConfig.default()
    quota, pods = _quota_fixture(pods)
    ck, cn, cs = ba.select_candidates(state, pods, cfg, k=K,
                                      spread_bits=SB, method="exact",
                                      with_scores=True)
    a_ref, st_ref, q_ref = ba._assign_rounds(state, pods, quota, ck, cn,
                                             ROUNDS)
    valid = np.asarray(ck) >= 0
    for d in SHARD_COUNTS:
        mesh = _mesh(d)
        sck, scn, scs = ps.sharded_select_candidates(
            mesh, state, pods, cfg, k=K, spread_bits=SB,
            with_scores=True)
        np.testing.assert_array_equal(np.asarray(sck), np.asarray(ck),
                                      err_msg=f"keys d={d}")
        np.testing.assert_array_equal(
            np.asarray(scn)[valid], np.asarray(cn)[valid],
            err_msg=f"nodes d={d}")
        np.testing.assert_array_equal(
            np.asarray(scs)[valid], np.asarray(cs)[valid],
            err_msg=f"scores d={d}")
        if d == 1:
            # the single-device reference above IS the 1-device solve;
            # compiling a 1-way rounds program re-proves it at real
            # tier-1 cost (selection still exercises the 1-way
            # shard_map path)
            continue
        a, st, q = ps.sharded_assign_rounds(mesh, state, pods, quota,
                                            sck, scn, ROUNDS)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(a_ref),
                                      err_msg=f"assignments d={d}")
        np.testing.assert_array_equal(
            np.asarray(st.node_requested),
            np.asarray(st_ref.node_requested), err_msg=f"state d={d}")
        np.testing.assert_array_equal(
            np.asarray(q.headroom), np.asarray(q_ref.headroom),
            err_msg=f"quota d={d}")


def test_incremental_refresh_invariant_across_shard_counts():
    """The dirty-node refresh: a dirty node rescores only on its owning
    shard, yet the merged cache equals the single-device refresh and the
    post-refresh solve is bit-identical at every shard count."""
    import jax.numpy as jnp

    state, pods = build_problem(n_nodes=64, n_pods=32, seed=11)
    cfg = ScoringConfig.default()
    ck, cn, cs = ba.select_candidates(state, pods, cfg, k=K,
                                      spread_bits=SB, method="exact",
                                      with_scores=True)
    cache = ba.CandidateCache(ck, cn, cs)
    # ~1% of a real cluster collapses to one node here; dirty a couple of
    # rows spread across different shards of the 8-way split
    dirty = [3, 40]
    dpad = _bucket(len(dirty), minimum=64)
    drows = np.zeros(dpad, np.int32)
    drows[: len(dirty)] = dirty
    dvalid = np.zeros(dpad, bool)
    dvalid[: len(dirty)] = True
    st2 = state.replace(
        node_usage=state.node_usage.at[jnp.asarray(dirty)].set(0))
    rk_ref, rc_ref = ba.refresh_candidates(
        st2, pods, cfg, cache, jnp.asarray(drows), jnp.asarray(dvalid),
        k=K, spread_bits=SB)
    a_ref, st_ref, _ = ba._assign_rounds(st2, pods, None, rk_ref,
                                         rc_ref.cand_node, ROUNDS)
    valid = np.asarray(rk_ref) >= 0
    for d in SHARD_COUNTS:
        mesh = _mesh(d)
        rk, rc = ps.sharded_refresh_candidates(
            mesh, st2, pods, cfg, cache, jnp.asarray(drows),
            jnp.asarray(dvalid), k=K, spread_bits=SB)
        np.testing.assert_array_equal(np.asarray(rk), np.asarray(rk_ref),
                                      err_msg=f"refresh keys d={d}")
        np.testing.assert_array_equal(
            np.asarray(rc.cand_node)[valid],
            np.asarray(rc_ref.cand_node)[valid],
            err_msg=f"refresh nodes d={d}")
        np.testing.assert_array_equal(
            np.asarray(rc.cand_score)[valid],
            np.asarray(rc_ref.cand_score)[valid],
            err_msg=f"refresh scores d={d}")
        # assignments from the dirty path, per shard count: the merged
        # cache is bit-identical, so solving each d's refreshed
        # candidates through the (already compiled) single-device
        # rounds must land on the reference assignments — the
        # non-vacuous cross-check without a new rounds program per d
        a, _, _ = ba._assign_rounds(st2, pods, None, rk, rc.cand_node,
                                    ROUNDS)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(a_ref),
                                      err_msg=f"post-refresh d={d}")


def test_pass_pipeline_invariant_at_mesh_width():
    """assign_round_pass + assign_followup_pass (the scheduler's
    incremental pass loop) at the full 8-way mesh: est accumulation,
    followup re-selection and the commit accounting bit-identical."""
    state, pods = build_problem(n_nodes=64, n_pods=32, seed=7)
    cfg = ScoringConfig.default()
    # quota=None here: the quota-on-mesh parity (admission + prefix +
    # charges) is already pinned across shard counts by the rounds
    # sweep above, and the quota chain doubles these two programs'
    # compile cost — the pass loop's OWN semantics (est accumulation,
    # followup re-select against the augmented state, commit into the
    # un-augmented accounting) are what this test adds
    k, rounds = 8, 4            # the stratified (5, 15) default path
    ck, cn, _ = ba.select_candidates(state, pods, cfg, k=k,
                                     method="exact", with_scores=True)
    ref1 = ba.assign_round_pass(state, pods, None, ck, cn, cfg,
                                rounds=rounds)
    ref2 = ba.assign_followup_pass(state, ref1[3], pods, None, cfg,
                                   k=k, rounds=rounds, method="exact")
    mesh = _mesh(8)
    a1, st1, _, est1 = ps.sharded_assign_round_pass(
        mesh, state, pods, None, ck, cn, cfg, rounds=rounds)
    np.testing.assert_array_equal(np.asarray(a1), np.asarray(ref1[0]))
    np.testing.assert_array_equal(np.asarray(st1.node_requested),
                                  np.asarray(ref1[1].node_requested))
    np.testing.assert_array_equal(np.asarray(est1), np.asarray(ref1[3]))
    a2, st2, _, est2 = ps.sharded_assign_followup_pass(
        mesh, state, est1, pods, None, cfg, k=k, rounds=rounds)
    np.testing.assert_array_equal(np.asarray(a2), np.asarray(ref2[0]))
    np.testing.assert_array_equal(np.asarray(st2.node_requested),
                                  np.asarray(ref2[1].node_requested))
    np.testing.assert_array_equal(np.asarray(est2), np.asarray(ref2[3]))


def test_wide_regime_breaks_the_old_ceiling():
    """A 65,536-node problem — double the old 32,768 wall — selects and
    solves, and the 2-way sharded solve matches bit-for-bit."""
    state, pods = build_problem(n_nodes=65_536, n_pods=8, seed=5)
    cfg = ScoringConfig.default()
    assert not ba._packed_regime(state.capacity)
    ck, cn = ba.select_candidates(state, pods, cfg, k=K, spread_bits=SB,
                                  method="exact")
    a_ref, st_ref, _ = ba._assign_rounds(state, pods, None, ck, cn,
                                         ROUNDS)
    assert int((np.asarray(a_ref) >= 0).sum()) == 8
    mesh = _mesh(2)
    sck, scn = ps.sharded_select_candidates(mesh, state, pods, cfg, k=K,
                                            spread_bits=SB)
    valid = np.asarray(ck) >= 0
    np.testing.assert_array_equal(np.asarray(sck), np.asarray(ck))
    np.testing.assert_array_equal(np.asarray(scn)[valid],
                                  np.asarray(cn)[valid])
    # identical candidates => identical rounds (the rounds are a pure
    # function of (state, pods, candidates); their 1/2/4/8 invariance is
    # proven at small shapes above — recompiling them at 65k columns
    # buys no new evidence and real tier-1 seconds)
    # no overcommit at the new scale
    assert (np.asarray(st_ref.node_requested)
            <= np.asarray(st_ref.node_allocatable)).all()


def test_wide_regime_rank_matches_lexicographic_oracle():
    """Wide-regime top-k == a NumPy (quantized score, tie-break)
    lexicographic sort oracle — the exactness anchor the packed-key
    regime has had since PR 1, restated past the 2**15 wall."""
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    p, n_total = 2, 40_000
    scores = rng.integers(0, 3_000, (p, n_total)).astype(np.int32)
    feasible = rng.random((p, n_total)) < 0.5
    sb = 5
    key, tb = ba._rank_parts(jnp.asarray(scores), jnp.asarray(feasible),
                             sb, jnp.arange(p, dtype=jnp.int32))
    kv, idx = ba._topk_by_rank(key, tb, 16, n_total)
    key_np, tb_np = np.asarray(key), np.asarray(tb)
    for i in range(p):
        order = np.lexsort((-tb_np[i], -key_np[i]))
        np.testing.assert_array_equal(np.asarray(idx)[i], order[:16],
                                      err_msg=f"row {i}")
        np.testing.assert_array_equal(np.asarray(kv)[i],
                                      key_np[i][order[:16]])


def test_check_node_capacity_new_ceiling():
    """The 32,768 wall is deleted; the loud guard moved to 2**30."""
    ba.check_node_capacity(40_960)            # the old failure shape
    ba.check_node_capacity(ba.MAX_NODE_CAPACITY)
    with pytest.raises(ValueError, match="ranking-key ceiling"):
        ba.check_node_capacity(ba.MAX_NODE_CAPACITY + 1)


def test_capacity_must_divide_over_the_mesh():
    state, pods = build_problem(n_nodes=60, n_pods=8)
    cfg = ScoringConfig.default()
    with pytest.raises(ValueError, match="does not divide"):
        ps.sharded_select_candidates(_mesh(8), state, pods, cfg, k=4)


# ---------------------------------------------------------------------------
# 2-D pods x nodes mesh (ISSUE 14)
# ---------------------------------------------------------------------------

#: tier-1 keeps a compile-lean slice — (1, 2) reuses the SAME memoized
#: shard_map programs as the d=2 leg of the 1-D sweep above (equal Mesh
#: ⇒ equal lru entry ⇒ zero extra compiles), so only the 2x2 leg pays a
#: fresh trace.  The full five-shape acceptance sweep lives on the slow
#: lane (test_full_2d_mesh_shape_sweep).
TIER1_2D = ((1, 2), (2, 2))
FULL_2D = ((1, 1), (1, 8), (2, 4), (4, 2), (8, 1))


def _mesh2d(p, n):
    import jax

    return pmesh.solver_mesh(jax.devices()[:p * n], pods_axis=p)


def _numpy_rounds_oracle(state, pods, cand_key, cand_node, rounds):
    """Pure-NumPy propose/accept rounds (quota-free, packed regime):
    the acceptance-decision oracle.  Mirrors _assign_rounds semantics —
    per-round best fitting candidate by the packed key, priority-prefix
    acceptance per contended node counting EVERY active proposer in
    order — with plain Python loops, so a tensor-kernel bug cannot hide
    in both implementations."""
    alloc = np.asarray(state.node_allocatable)
    valid_n = np.asarray(state.node_valid)
    requested = np.asarray(state.node_requested).copy()
    req = np.asarray(pods.requests)
    prio = np.asarray(pods.priority)
    pvalid = np.asarray(pods.valid)
    ck, cn = np.asarray(cand_key), np.asarray(cand_node)
    p = req.shape[0]
    order = np.lexsort((np.arange(p), -prio))
    assignments = np.full(p, -1, np.int32)
    active = pvalid & (ck >= 0).any(axis=1)
    for _ in range(rounds):
        if not active.any():
            break
        free = np.where(valid_n[:, None], alloc - requested, 0)
        cand_free = free[cn]
        fits = (((req[:, None, :] <= cand_free)
                 | (req[:, None, :] == 0)).all(-1)) & (ck >= 0)
        masked = np.where(fits, ck, -1)
        best = masked.argmax(axis=1)
        has = fits[np.arange(p), best]
        choice = cn[np.arange(p), best]
        act = active & has
        accept = np.zeros(p, bool)
        used: dict[int, np.ndarray] = {}
        for i in order:
            if not act[i]:
                continue
            c = int(choice[i])
            cum = used.get(c, 0) + req[i]
            if ((cum <= free[c]) | (req[i] == 0)).all():
                accept[i] = True
            used[c] = cum
        for i in np.where(accept)[0]:
            requested[choice[i]] += req[i]
            assignments[i] = choice[i]
        active = act & ~accept
    return assignments, requested


def test_program_cache_shared_across_equal_meshes():
    """The tier-1 budget guard: equal meshes (same devices, same axis
    split) built by different solver_mesh calls share ONE memoized
    shard_map program entry, so the 2-D sweep re-traces nothing the 1-D
    sweep already compiled."""
    import jax

    m1 = pmesh.solver_mesh(jax.devices()[:2])
    m2 = _mesh2d(1, 2)
    assert m1 == m2
    p1 = ps._select_program(m1, 64, K, (SB,))
    p2 = ps._select_program(m2, 64, K, (SB,))
    assert p1 is p2
    assert ps._select_program(_mesh2d(2, 1), 64, K, (SB,)) is not p1


def test_two_axis_selection_and_rounds_tier1():
    """The compile-lean 2-D slice: pod-sharded selection + quota-charged
    rounds bit-identical to single-device at 1x2 and 2x2."""
    state, pods = build_problem(n_nodes=64, n_pods=32)
    cfg = ScoringConfig.default()
    quota, pods = _quota_fixture(pods)
    ck, cn, cs = ba.select_candidates(state, pods, cfg, k=K,
                                      spread_bits=SB, method="exact",
                                      with_scores=True)
    a_ref, st_ref, q_ref = ba._assign_rounds(state, pods, quota, ck, cn,
                                             ROUNDS)
    valid = np.asarray(ck) >= 0
    for shape in TIER1_2D:
        mesh = _mesh2d(*shape)
        sck, scn, scs = ps.sharded_select_candidates(
            mesh, state, pods, cfg, k=K, spread_bits=SB,
            with_scores=True)
        np.testing.assert_array_equal(np.asarray(sck), np.asarray(ck),
                                      err_msg=f"keys {shape}")
        np.testing.assert_array_equal(
            np.asarray(scn)[valid], np.asarray(cn)[valid],
            err_msg=f"nodes {shape}")
        a, st, q = ps.sharded_assign_rounds(mesh, state, pods, quota,
                                            sck, scn, ROUNDS)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(a_ref),
                                      err_msg=f"assignments {shape}")
        np.testing.assert_array_equal(
            np.asarray(st.node_requested),
            np.asarray(st_ref.node_requested), err_msg=f"state {shape}")
        np.testing.assert_array_equal(
            np.asarray(q.headroom), np.asarray(q_ref.headroom),
            err_msg=f"quota {shape}")


def test_two_axis_rounds_match_numpy_oracle():
    """Acceptance decisions cross-checked against the pure-NumPy
    propose/accept oracle (not just the JAX single-device twin): device
    rounds at 2x2 == _assign_rounds == the Python loop."""
    state, pods = build_problem(n_nodes=64, n_pods=32, seed=23)
    cfg = ScoringConfig.default()
    ck, cn = ba.select_candidates(state, pods, cfg, k=K, spread_bits=SB,
                                  method="exact")
    a_ref, st_ref, _ = ba._assign_rounds(state, pods, None, ck, cn,
                                         ROUNDS)
    a_np, req_np = _numpy_rounds_oracle(state, pods, ck, cn, ROUNDS)
    np.testing.assert_array_equal(np.asarray(a_ref), a_np)
    np.testing.assert_array_equal(np.asarray(st_ref.node_requested),
                                  req_np)
    a_sh, st_sh, _ = ps.sharded_assign_rounds(
        _mesh2d(2, 2), state, pods, None, ck, cn, ROUNDS)
    np.testing.assert_array_equal(np.asarray(a_sh), a_np)
    np.testing.assert_array_equal(np.asarray(st_sh.node_requested),
                                  req_np)


def test_two_axis_gang_and_greedy_tier1():
    """The explicit shard_map gang twin (both per-pass engines) at 2x2
    == the GSPMD-placed gang_assign, quota-free (the quota-charged gang
    legs ride the slow-lane sweep)."""
    import jax

    from koordinator_tpu.ops.gang import GangInfo, gang_assign

    state, pods = build_problem(n_pods=32, seed=9)
    gang_id = np.full(pods.capacity, -1, np.int32)
    gang_id[:6] = 0
    pods = pods.replace(gang_id=np.asarray(gang_id))
    gangs = GangInfo.build(np.array([4], np.int32))
    cfg = ScoringConfig.default()
    mesh = _mesh2d(2, 2)
    f = jax.jit(gang_assign, static_argnames=("passes", "solver"))
    for solver in ("batch", "greedy"):
        a_ref, st_ref, _ = f(state, pods, cfg, gangs, None, passes=2,
                             solver=solver)
        a, st, _ = ps.sharded_gang_assign(mesh, state, pods, cfg, gangs,
                                          None, passes=2, solver=solver)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(a_ref),
                                      err_msg=solver)
        np.testing.assert_array_equal(
            np.asarray(st.node_requested),
            np.asarray(st_ref.node_requested), err_msg=solver)


def test_pod_capacity_must_divide_over_the_mesh():
    from koordinator_tpu.state.cluster_state import PodBatch

    state, _ = build_problem(n_nodes=64, n_pods=8)
    rng = np.random.default_rng(0)
    req = np.zeros((20, R), np.int32)
    req[:, CPU] = rng.integers(100, 1_000, 20)
    pods = PodBatch.build(req, node_capacity=64, capacity=20)
    cfg = ScoringConfig.default()
    with pytest.raises(ValueError, match="pods axis"):
        ps.sharded_select_candidates(_mesh2d(8, 1), state, pods, cfg,
                                     k=K)


def test_scheduler_two_axis_end_to_end():
    """Scheduler parity on a 2x2 pods x nodes mesh: same feed, same
    binds, same accounting as single-device, across rounds that cover
    the full-cold, incremental and sharded gang paths — the wiring
    (solve_sh routing, pod-axis batch pinning) on top of the kernel
    parity above."""
    from tests.test_incremental_solve import (
        _assert_no_overcommit,
        _feed_nodes,
        _mk_sched,
        _pod,
    )

    rng = np.random.default_rng(5)
    rng2 = np.random.default_rng(5)
    sharded = _mk_sched(True, mesh=_mesh2d(2, 2), shard_min_nodes=0)
    single = _mk_sched(True, mesh="off")
    assert sharded.kit.pod_shards == 2
    assert sharded.solver_shard_count == 2
    assert sharded._solve_sh is not None
    for sched in (sharded, single):
        sched.incremental_dirty_threshold = 1.0
    _feed_nodes(sharded, rng, n=12)
    _feed_nodes(single, rng2, n=12)
    for rnd in range(3):
        for j in range(3):
            name = f"p{rnd}-{j}"
            sharded.enqueue(_pod(rng, name))
            single.enqueue(_pod(rng2, name))
        ra = sharded.schedule_round()
        rb = single.schedule_round()
        assert ra.assignments == rb.assignments, f"round {rnd}"
        assert set(ra.failures) == set(rb.failures), f"round {rnd}"
    _assert_no_overcommit(sharded)
    np.testing.assert_array_equal(
        np.asarray(sharded.snapshot.state.node_requested),
        np.asarray(single.snapshot.state.node_requested))
    rep = sharded.sharding_report()
    assert rep["mesh"] == {"pods": 2, "nodes": 2}
    assert rep["pod_shard_count"] == 2
    # per-(pod_shard, node_shard) byte keys (ISSUE 14 introspection)
    assert "p0n0" in rep["device_bytes_by_shard"]["cluster_state"]
    # the batch pins under the pod-axis NamedSharding
    assert sharded._batch_cache is not None
    batch = sharded._batch_cache[1]
    assert len(batch.requests.sharding.device_set) == 4


@pytest.mark.slow
def test_full_2d_mesh_shape_sweep():
    """The ISSUE 14 acceptance sweep: selection + quota-charged rounds,
    the 1%-dirty incremental refresh, gang placements (both engines,
    quota-charged) and the LP quality mode bit-identical to
    single-device across 1x1 / 1x8 / 2x4 / 4x2 / 8x1."""
    import jax
    import jax.numpy as jnp

    from koordinator_tpu.ops.gang import GangInfo, gang_assign
    from koordinator_tpu.quality.lp_pack import lp_pack_assign

    state, pods = build_problem(n_nodes=64, n_pods=32)
    cfg = ScoringConfig.default()
    quota, pods = _quota_fixture(pods)
    ck, cn, cs = ba.select_candidates(state, pods, cfg, k=K,
                                      spread_bits=SB, method="exact",
                                      with_scores=True)
    a_ref, st_ref, q_ref = ba._assign_rounds(state, pods, quota, ck, cn,
                                             ROUNDS)
    valid = np.asarray(ck) >= 0

    # gang reference (quota-charged, both engines)
    gang_id = np.full(pods.capacity, -1, np.int32)
    gang_id[:8] = 0
    gang_id[8:12] = 1
    gpods = pods.replace(gang_id=jnp.asarray(gang_id))
    gangs = GangInfo.build(np.array([6, 4], np.int32))
    gf = jax.jit(gang_assign, static_argnames=("passes", "solver"))
    gang_refs = {
        solver: gf(state, gpods, cfg, gangs, quota, passes=2,
                   solver=solver)
        for solver in ("batch", "greedy")}

    # dirty-refresh reference (~1% of a real cluster collapses here)
    cache = ba.CandidateCache(ck, cn, cs)
    dirty = [3, 40]
    dpad = _bucket(len(dirty), minimum=64)
    drows = np.zeros(dpad, np.int32)
    drows[: len(dirty)] = dirty
    dvalid = np.zeros(dpad, bool)
    dvalid[: len(dirty)] = True
    st_d = state.replace(
        node_usage=state.node_usage.at[jnp.asarray(dirty)].set(0))
    rk_ref, rc_ref = ba.refresh_candidates(
        st_d, pods, cfg, cache, jnp.asarray(drows), jnp.asarray(dvalid),
        k=K, spread_bits=SB)
    rvalid = np.asarray(rk_ref) >= 0

    # LP quality-mode reference (trimmed iteration bounds: the sweep's
    # evidence is mesh-shape invariance, not LP convergence depth)
    lp_ref = jax.jit(lp_pack_assign,
                     static_argnames=("ascent_iters", "rounding_iters"))(
        state, pods, cfg, ascent_iters=2, rounding_iters=2)

    for shape in FULL_2D:
        mesh = _mesh2d(*shape)
        sck, scn, _ = ps.sharded_select_candidates(
            mesh, state, pods, cfg, k=K, spread_bits=SB,
            with_scores=True)
        np.testing.assert_array_equal(np.asarray(sck), np.asarray(ck),
                                      err_msg=f"keys {shape}")
        np.testing.assert_array_equal(
            np.asarray(scn)[valid], np.asarray(cn)[valid],
            err_msg=f"nodes {shape}")
        a, st, q = ps.sharded_assign_rounds(mesh, state, pods, quota,
                                            sck, scn, ROUNDS)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(a_ref),
                                      err_msg=f"assignments {shape}")
        np.testing.assert_array_equal(
            np.asarray(q.headroom), np.asarray(q_ref.headroom),
            err_msg=f"quota {shape}")

        rk, rc = ps.sharded_refresh_candidates(
            mesh, st_d, pods, cfg, cache, jnp.asarray(drows),
            jnp.asarray(dvalid), k=K, spread_bits=SB)
        np.testing.assert_array_equal(np.asarray(rk), np.asarray(rk_ref),
                                      err_msg=f"refresh {shape}")
        np.testing.assert_array_equal(
            np.asarray(rc.cand_node)[rvalid],
            np.asarray(rc_ref.cand_node)[rvalid],
            err_msg=f"refresh nodes {shape}")

        for solver in ("batch", "greedy"):
            ga_ref, gst_ref, gq_ref = gang_refs[solver]
            ga, gst, gq = ps.sharded_gang_assign(
                mesh, state, gpods, cfg, gangs, quota, passes=2,
                solver=solver)
            np.testing.assert_array_equal(
                np.asarray(ga), np.asarray(ga_ref),
                err_msg=f"gang {solver} {shape}")
            np.testing.assert_array_equal(
                np.asarray(gst.node_requested),
                np.asarray(gst_ref.node_requested),
                err_msg=f"gang state {solver} {shape}")
            np.testing.assert_array_equal(
                np.asarray(gq.headroom), np.asarray(gq_ref.headroom),
                err_msg=f"gang quota {solver} {shape}")

        la, lst, _, _ = ps.sharded_lp_pack_assign(
            mesh, state, pods, cfg, ascent_iters=2, rounding_iters=2)
        np.testing.assert_array_equal(np.asarray(la),
                                      np.asarray(lp_ref[0]),
                                      err_msg=f"lp {shape}")
        np.testing.assert_array_equal(
            np.asarray(lst.node_requested),
            np.asarray(lp_ref[1].node_requested),
            err_msg=f"lp state {shape}")


def test_scheduler_sharded_rounds_equal_single_device():
    """End-to-end Scheduler parity: the same feed solved by a
    sharded-by-default scheduler (8-way mesh engaged via
    shard_min_nodes=0) and a single-device one binds identical pods to
    identical nodes and charges identical quota, across steady-state
    rounds that exercise the incremental dirty path."""
    from tests.test_incremental_solve import (
        _assert_no_overcommit,
        _feed_nodes,
        _mk_sched,
        _pod,
    )

    rng = np.random.default_rng(3)
    sharded = _mk_sched(True, mesh="auto", shard_min_nodes=0)
    single = _mk_sched(True, mesh="off")
    assert sharded.mesh is not None and sharded.solver_shard_count == 8
    assert single.mesh is None
    for sched in (sharded, single):
        sched.incremental_dirty_threshold = 1.0
    rng2 = np.random.default_rng(3)
    _feed_nodes(sharded, rng, n=12)
    _feed_nodes(single, rng2, n=12)
    took_incremental = False
    for rnd in range(4):
        for j in range(3):
            name = f"p{rnd}-{j}"
            pa, pb = _pod(rng, name), _pod(rng2, name)
            sharded.enqueue(pa)
            single.enqueue(pb)
        ra = sharded.schedule_round()
        rb = single.schedule_round()
        assert ra.assignments == rb.assignments, f"round {rnd}"
        assert set(ra.failures) == set(rb.failures), f"round {rnd}"
        if sharded.last_solve_path == "incremental":
            took_incremental = True
    assert sharded.snapshot.solver_sharding_active
    assert took_incremental, "incremental path never engaged while sharded"
    _assert_no_overcommit(sharded)
    np.testing.assert_array_equal(
        np.asarray(sharded.snapshot.state.node_requested),
        np.asarray(single.snapshot.state.node_requested))
