"""Randomized invariants of the cpuset accumulator (take_cpus).

test_numa.py pins the reference scenarios (cpu_accumulator.go policies)
at hand-built topologies; this sweeps random topologies, ref-counts,
and bans across every bind policy and strategy:

  (count)    ok => exactly n_cpus selected (FullPCPUs: rounded up to
             whole cores); !ok => nothing selected
  (legal)    selected CPUs are valid, under max_ref, and never banned
  (cores)    FullPCPUs selects only whole, fully-free physical cores
  (honest)   !ok only when the policy really cannot be satisfied —
             checked against an independent count of eligible CPUs
"""

import jax.numpy as jnp
import numpy as np
import pytest

from tests.conftest import prop_seeds

from koordinator_tpu.ops.numa import (
    BIND_DEFAULT,
    BIND_FULL_PCPUS,
    BIND_SPREAD_BY_PCPUS,
    CPUTopology,
    STRATEGY_LEAST_ALLOCATED,
    STRATEGY_MOST_ALLOCATED,
    take_cpus,
)


def _random_topo(rng: np.random.Generator):
    sockets = int(rng.integers(1, 3))
    numa_per = int(rng.integers(1, 3))
    cores_per = int(rng.integers(2, 5))
    threads = 2
    n = sockets * numa_per * cores_per * threads
    core_of = np.repeat(np.arange(sockets * numa_per * cores_per), threads)
    numa_of = core_of // cores_per
    socket_of = numa_of // numa_per
    return CPUTopology.build(core_of.astype(np.int32),
                             numa_of.astype(np.int32),
                             socket_of.astype(np.int32)), n


@pytest.mark.parametrize("seed", prop_seeds(20))
@pytest.mark.parametrize("bind", [BIND_DEFAULT, BIND_FULL_PCPUS,
                                  BIND_SPREAD_BY_PCPUS])
def test_take_cpus_invariants(seed, bind):
    rng = np.random.default_rng(seed)
    topo, n = _random_topo(rng)
    cap = topo.capacity
    max_ref = int(rng.integers(1, 3))
    ref = np.zeros(cap, np.int32)
    ref[:n] = rng.integers(0, max_ref + 1, n)
    banned = np.zeros(cap, bool)
    banned[:n] = rng.random(n) < 0.2
    want = int(rng.integers(1, n + 2))
    strategy = (STRATEGY_MOST_ALLOCATED if rng.random() < 0.5
                else STRATEGY_LEAST_ALLOCATED)

    sel, ok = take_cpus(topo, jnp.asarray(ref), jnp.int32(max_ref),
                        jnp.int32(want), bind_policy=bind,
                        strategy=strategy, banned=jnp.asarray(banned))
    sel, ok = np.asarray(sel), bool(ok)
    valid = np.asarray(topo.valid)
    core_of = np.asarray(topo.core_of)

    free = valid & (ref < max_ref) & ~banned
    if bind == BIND_FULL_PCPUS:
        # whole cores only: a core is takeable iff every sibling is free
        core_free_count = np.bincount(core_of[free],
                                      minlength=core_of.max() + 1)
        core_size = np.bincount(core_of[valid],
                                minlength=core_of.max() + 1)
        takeable = np.isin(core_of, np.flatnonzero(
            (core_size > 0) & (core_free_count == core_size))) & free
        threads = int(core_size[core_size > 0].max())
        need = -(-want // threads) * threads   # rounded to whole cores
        can = takeable.sum() >= need
    else:
        takeable = free
        need = want
        can = free.sum() >= want

    if ok:
        # (count)
        assert sel.sum() == need, (seed, bind, sel.sum(), need)
        # (legal)
        assert not (sel & ~free).any(), f"seed {seed}: illegal cpu taken"
        if bind == BIND_FULL_PCPUS:
            # (cores) selected cores are complete
            sel_cores = np.bincount(core_of[sel],
                                    minlength=core_of.max() + 1)
            partial = (sel_cores > 0) & (sel_cores != core_size)
            assert not partial.any(), f"seed {seed}: partial core taken"
            assert not (sel & ~takeable).any()
    else:
        assert sel.sum() == 0, f"seed {seed}: !ok but cpus selected"
        # (honest) failure only when genuinely unsatisfiable
        assert not can, (
            f"seed {seed} bind={bind}: refused a satisfiable request "
            f"(want {want}, takeable {int(takeable.sum())})")
