"""Elastic-quota hardening: overuse revoke, scale-min, multi-tree affinity.

Scenarios mirror the reference tests:
- quota_overuse_revoke_test.go — victim walk least-important-first with
  assign-back, non-preemptible skip, delay timer;
- scale_minquota_when_over_root_res_test.go — proportional min shrink with
  disable-scale children served first;
- multi_quota_tree_affinity_test.go — tree node selector injected at CREATE.
"""

import jax.numpy as jnp
import numpy as np

from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS, ResourceDim, resource_vector
from koordinator_tpu.ops.preemption import ScheduledPods
from koordinator_tpu.quota.overuse_revoke import (
    QuotaOveruseRevokeController,
    select_overuse_victims,
)
from koordinator_tpu.quota.tree import UNBOUNDED, QuotaTree

from tests.test_scheduler import mk_scheduler, node, pod

R = NUM_RESOURCE_DIMS
CPU = ResourceDim.CPU


def vec64(cpu):
    v = np.zeros(R, np.int64)
    v[CPU] = cpu
    return v


def unbounded_cpu(cpu):
    v = np.full(R, UNBOUNDED, np.int64)
    v[CPU] = cpu
    return v


# -- select_overuse_victims kernel ------------------------------------------


def mk_sched(cpus, pris, quota_ids, nonp=None):
    v = len(cpus)
    req = np.zeros((v, R), np.int32)
    req[:, CPU] = cpus
    return ScheduledPods.build(
        req, np.zeros(v, np.int32), priority=np.array(pris, np.int32),
        quota_id=np.array(quota_ids, np.int32),
        non_preemptible=np.asarray(nonp, bool) if nonp is not None else None,
    )


def qarrs(used_cpu, runtime_cpu):
    q = len(used_cpu)
    used = np.zeros((q, R), np.int32)
    used[:, CPU] = used_cpu
    runtime = np.zeros((q, R), np.int32)
    runtime[:, CPU] = runtime_cpu
    checked = np.zeros((q, R), bool)
    checked[:, CPU] = True
    return jnp.asarray(used), jnp.asarray(runtime), jnp.asarray(checked)


class TestSelectOveruseVictims:
    def test_revokes_least_important_until_under(self):
        # quota 0: used 8, runtime 5 -> must shed 3; pods 2+2+2+2 cpu at
        # priorities 40..10: remove 10 and 20 (least important), assign-back
        # reprieves 20? deficit 3 -> removing 10 (2cpu) leaves used 6 > 5,
        # removing 20 leaves 4 <= 5; assign-back most-important-first: 20
        # back -> 6 > 5 no. So victims = {10, 20}.
        sched = mk_sched([2_000]*4, [40, 30, 20, 10], [0]*4)
        used, runtime, checked = qarrs([8_000], [5_000])
        out = np.asarray(select_overuse_victims(sched, used, runtime, checked))
        assert out[:4].tolist() == [False, False, True, True]

    def test_assign_back_reprieves(self):
        # deficit 1, pods of 3cpu and 1cpu (pri 20, 10): walk removes the
        # 1cpu pod first (least important) -> still over? used 6, runtime 5:
        # removing 1cpu -> 5 <= 5 done. Victim = the small pod only.
        sched = mk_sched([3_000, 1_000], [20, 10], [0, 0])
        used, runtime, checked = qarrs([6_000], [5_000])
        out = np.asarray(select_overuse_victims(sched, used, runtime, checked))
        assert out[:2].tolist() == [False, True]

    def test_non_preemptible_skipped(self):
        sched = mk_sched([2_000, 2_000], [10, 20], [0, 0],
                         nonp=[True, False])
        used, runtime, checked = qarrs([4_000], [1_000])
        out = np.asarray(select_overuse_victims(sched, used, runtime, checked))
        # only the preemptible pod can go, even though quota stays over
        assert out[:2].tolist() == [False, True]

    def test_multiple_quotas_solved_together(self):
        sched = mk_sched(
            [2_000, 2_000, 2_000, 2_000], [10, 20, 10, 20], [0, 0, 1, 1]
        )
        used, runtime, checked = qarrs([4_000, 4_000], [2_000, 10_000])
        out = np.asarray(select_overuse_victims(sched, used, runtime, checked))
        # quota 0 sheds its least-important pod; quota 1 is under -> untouched
        assert out[:4].tolist() == [True, False, False, False]

    def test_under_quota_untouched(self):
        sched = mk_sched([1_000], [10], [0])
        used, runtime, checked = qarrs([1_000], [5_000])
        out = np.asarray(select_overuse_victims(sched, used, runtime, checked))
        assert not out.any()


class TestRevokeController:
    def build(self, clock):
        total = vec64(8_000)
        tree = QuotaTree(total)
        tree.add("q", min=vec64(0), max=unbounded_cpu(8_000))
        sched, _ = mk_scheduler([node("n1", cpu=16_000)], quota_tree=tree)
        revoked = []
        ctl = QuotaOveruseRevokeController(
            sched, revoke_fn=lambda p, q: revoked.append(p),
            delay_evict_sec=5.0, clock=clock,
        )
        return sched, tree, ctl, revoked

    def test_revoke_after_delay(self):
        t = [0.0]
        sched, tree, ctl, revoked = self.build(lambda: t[0])
        for name, pri in [("a", 10), ("b", 20)]:
            sched.enqueue(pod(name, cpu=3_000, mem=0, priority=pri, quota="q"))
        res = sched.schedule_round()
        assert not res.failures
        # runtime collapses (another tree consumer): force via shrink
        tree.set_request("q", vec64(6_000))
        tree.total_resource = vec64(4_000)
        tree.refresh_runtime()
        assert ctl.revoke_once() == []       # within delay: no evictions
        t[0] = 6.0
        out = ctl.revoke_once()              # past delay: shed to runtime
        assert out == ["a"]                  # least important goes
        assert revoked == ["a"]
        assert "a" not in sched.bound
        assert int(tree.nodes["q"].used[CPU]) == 3_000

    def test_under_used_resets_timer(self):
        t = [0.0]
        sched, tree, ctl, revoked = self.build(lambda: t[0])
        sched.enqueue(pod("a", cpu=3_000, mem=0, priority=10, quota="q"))
        assert not sched.schedule_round().failures
        assert ctl.monitor() == []
        t[0] = 100.0
        assert ctl.monitor() == []  # never over -> never triggers
        assert ctl.revoke_once() == []


# -- scale-min-when-over-root ------------------------------------------------


class TestScaleMin:
    def test_min_scaled_proportionally(self):
        # total 100; children: d (disable, min 40), a/b (enable, min 40/20):
        # sum 100 > total? 100 == 100 -> no scale. Shrink to 70: avail for
        # scaling = 70-40 = 30, a gets 30*40//60=20, b gets 30*20//60=10.
        tree = QuotaTree(vec64(70), scale_min_enabled=True)
        tree.add("d", min=vec64(40), max=unbounded_cpu(1_000))
        tree.add("a", min=vec64(40), max=unbounded_cpu(1_000),
                 enable_scale_min=True)
        tree.add("b", min=vec64(20), max=unbounded_cpu(1_000),
                 enable_scale_min=True)
        for n in ("d", "a", "b"):
            tree.set_request(n, vec64(1_000))
        tree.refresh_runtime()
        # runtimes start at scaled min and water-fill the rest; with requests
        # saturating, min floor is visible via runtime >= scaled min and the
        # total conserving 70
        rt = {n: int(tree.runtime_of(n)[CPU]) for n in ("d", "a", "b")}
        assert sum(rt.values()) == 70
        assert rt["d"] >= 40   # disable-scale child keeps its full min
        assert rt["a"] >= 20 and rt["b"] >= 10

    def test_no_scale_when_total_sufficient(self):
        tree = QuotaTree(vec64(100), scale_min_enabled=True)
        tree.add("a", min=vec64(30), max=unbounded_cpu(1_000),
                 enable_scale_min=True)
        tree.add("b", min=vec64(30), max=unbounded_cpu(1_000))
        tree.set_request("a", vec64(30))
        tree.set_request("b", vec64(30))
        tree.refresh_runtime()
        assert int(tree.runtime_of("a")[CPU]) == 30
        assert int(tree.runtime_of("b")[CPU]) == 30

    def test_disabled_gate_keeps_min(self):
        tree = QuotaTree(vec64(50))  # gate off
        tree.add("a", min=vec64(40), max=unbounded_cpu(1_000),
                 enable_scale_min=True)
        tree.add("b", min=vec64(40), max=unbounded_cpu(1_000))
        tree.set_request("a", vec64(40))
        tree.set_request("b", vec64(40))
        tree.refresh_runtime()
        # no scaling: both keep min even though the sum over-commits total
        assert int(tree.runtime_of("a")[CPU]) == 40
        assert int(tree.runtime_of("b")[CPU]) == 40


# -- multi-quota-tree affinity webhook ---------------------------------------


class TestMultiQuotaTreeAffinity:
    def build(self):
        from koordinator_tpu.api import crds, extension as ext
        from koordinator_tpu.manager.webhook import MultiQuotaTreeAffinity

        m = MultiQuotaTreeAffinity()
        m.set_quota(crds.ElasticQuota(name="team-a", tree_id="tree1"))
        m.set_profile_selector("tree1", {"pool": "dedicated"})
        return m, ext

    def test_injects_tree_selector(self):
        m, ext = self.build()
        p = {"metadata": {"labels": {ext.LABEL_QUOTA_NAME: "team-a"}}}
        assert m.mutate(p)
        assert p["spec"]["nodeSelector"] == {"pool": "dedicated"}

    def test_namespace_fallback(self):
        m, ext = self.build()
        m.set_quota(
            __import__("koordinator_tpu.api.crds", fromlist=["crds"])
            .ElasticQuota(name="ns1", tree_id="tree1")
        )
        p = {"metadata": {"namespace": "ns1"}}
        assert m.mutate(p)
        assert p["spec"]["nodeSelector"] == {"pool": "dedicated"}

    def test_no_tree_no_mutation(self):
        m, ext = self.build()
        p = {"metadata": {"labels": {ext.LABEL_QUOTA_NAME: "other"}}}
        assert not m.mutate(p)
        assert "spec" not in p or "nodeSelector" not in p.get("spec", {})

    def test_update_operation_skipped(self):
        m, ext = self.build()
        p = {"metadata": {"labels": {ext.LABEL_QUOTA_NAME: "team-a"}}}
        assert not m.mutate(p, operation="UPDATE")

    def test_existing_key_not_overwritten(self):
        m, ext = self.build()
        p = {
            "metadata": {"labels": {ext.LABEL_QUOTA_NAME: "team-a"}},
            "spec": {"nodeSelector": {"pool": "user-pinned"}},
        }
        assert not m.mutate(p)
        assert p["spec"]["nodeSelector"] == {"pool": "user-pinned"}
