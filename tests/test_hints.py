"""Scheduling hints, cross-scheduler nomination, in-place resize."""

import numpy as np

from koordinator_tpu.api.resources import resource_vector
from koordinator_tpu.scheduler.hints import (
    CrossSchedulerNominator, PodHint, SchedulingHints, resize_pod,
)
from koordinator_tpu.scheduler.scheduler import Scheduler
from koordinator_tpu.scheduler.snapshot import PodSpec
from tests.test_e2e_sim import make_cluster


class TestSchedulingHints:
    def test_excluded_node_skipped(self):
        snapshot = make_cluster(3)
        hints = SchedulingHints(snapshot)
        scheduler = Scheduler(snapshot, hints=hints)
        hints.set_hint("p1", PodHint(excluded_nodes={"n0", "n1"}))
        scheduler.enqueue(PodSpec(name="p1",
                                  requests=resource_vector({"cpu": 1000}),
                                  priority=9500))
        result = scheduler.schedule_round()
        assert result.assignments["p1"] == "n2"

    def test_preferred_restricts(self):
        snapshot = make_cluster(3)
        hints = SchedulingHints(snapshot)
        scheduler = Scheduler(snapshot, hints=hints)
        hints.set_hint("p1", PodHint(preferred_nodes={"n1"}))
        scheduler.enqueue(PodSpec(name="p1",
                                  requests=resource_vector({"cpu": 1000}),
                                  priority=9500))
        result = scheduler.schedule_round()
        assert result.assignments["p1"] == "n1"

    def test_infeasible_preference_ignored(self):
        snapshot = make_cluster(2)
        hints = SchedulingHints(snapshot)
        hints.set_hint("p1", PodHint(preferred_nodes={"ghost"}))
        mask = hints.apply_to_mask("p1", np.array([True, True]))
        assert mask.all()  # no feasible preferred node -> unrestricted

    def test_record_failure_excludes(self):
        snapshot = make_cluster(2)
        hints = SchedulingHints(snapshot)
        hints.record_failure("p1", "n0")
        mask = hints.apply_to_mask("p1", np.array([True, True]))
        assert not mask[0] and mask[1]


class TestCrossSchedulerNominator:
    def test_nomination_charges_capacity(self):
        snapshot = make_cluster(1, cpu=4000)
        nominator = CrossSchedulerNominator(snapshot)
        assert nominator.nominate("other-pod", "n0",
                                  resource_vector({"cpu": 3000}))
        scheduler = Scheduler(snapshot)
        scheduler.enqueue(PodSpec(name="mine",
                                  requests=resource_vector({"cpu": 2000}),
                                  priority=9500))
        result = scheduler.schedule_round()
        assert "mine" in result.failures  # 3000 claimed, only 1000 free
        nominator.release("other-pod")
        scheduler.enqueue(PodSpec(name="mine",
                                  requests=resource_vector({"cpu": 2000}),
                                  priority=9500))
        result = scheduler.schedule_round()
        assert result.assignments.get("mine") == "n0"

    def test_double_nomination_rejected(self):
        snapshot = make_cluster(1)
        nominator = CrossSchedulerNominator(snapshot)
        assert nominator.nominate("p", "n0", resource_vector({"cpu": 100}))
        assert not nominator.nominate("p", "n0", resource_vector({"cpu": 100}))
        assert nominator.nominated_node("p") == "n0"


class TestResizePod:
    def test_grow_within_free(self):
        snapshot = make_cluster(1, cpu=4000)
        snapshot.reserve("n0", resource_vector({"cpu": 1000}))
        ok, reason = resize_pod(
            snapshot, "n0",
            resource_vector({"cpu": 1000}), resource_vector({"cpu": 2000}))
        assert ok, reason
        snapshot.flush()
        free = np.asarray(snapshot.state.free)[snapshot.node_index["n0"]]
        assert free[0] == 4000 - 2000

    def test_grow_beyond_free_rejected(self):
        snapshot = make_cluster(1, cpu=4000)
        snapshot.reserve("n0", resource_vector({"cpu": 3500}))
        ok, reason = resize_pod(
            snapshot, "n0",
            resource_vector({"cpu": 3500}), resource_vector({"cpu": 4500}))
        assert not ok and "insufficient" in reason

    def test_shrink_releases(self):
        snapshot = make_cluster(1, cpu=4000)
        snapshot.reserve("n0", resource_vector({"cpu": 3000}))
        ok, _ = resize_pod(
            snapshot, "n0",
            resource_vector({"cpu": 3000}), resource_vector({"cpu": 1000}))
        assert ok
        snapshot.flush()
        free = np.asarray(snapshot.state.free)[snapshot.node_index["n0"]]
        assert free[0] == 3000
