"""Per-binary CLI layer (koordinator_tpu/cmd/) vs the reference's cmd/
flag surface: feature gates, leader-election flags, component wiring."""

import numpy as np
import pytest

from koordinator_tpu.cmd.binaries import (
    MAINS,
    main_koord_descheduler,
    main_koord_manager,
    main_koord_runtime_proxy,
    main_koord_scheduler,
    main_koordlet,
)
from koordinator_tpu.features import KOORDLET_GATES, SCHEDULER_GATES
from koordinator_tpu.ha import InMemoryLeaseStore


def test_all_six_binaries_registered():
    assert sorted(MAINS) == [
        "koord-descheduler", "koord-device-daemon", "koord-manager",
        "koord-runtime-proxy", "koord-scheduler", "koordlet",
    ]


def test_koordlet_flags_and_gates(tmp_path):
    before = KOORDLET_GATES.enabled("CPICollector")
    before_audit = KOORDLET_GATES.enabled("AuditEvents")
    try:
        out = main_koordlet([
            "--cgroup-root-dir", str(tmp_path / "cg"),
            "--proc-root-dir", str(tmp_path / "proc"),
            # AuditEvents defaults FALSE (koordlet_features.go:215):
            # --audit-log-dir alone must not construct an auditor
            "--feature-gates", "CPICollector=true,AuditEvents=true",
            "--audit-log-dir", str(tmp_path / "audit"),
        ])
        assert out.name == "koordlet"
        assert out.component.cfg.cgroup_root == str(tmp_path / "cg")
        assert out.component.auditor is not None
        assert KOORDLET_GATES.enabled("CPICollector") is True
    finally:
        KOORDLET_GATES.set("CPICollector", before)
        KOORDLET_GATES.set("AuditEvents", before_audit)


def test_koordlet_serves_runtime_hooks(tmp_path):
    from koordinator_tpu.api import extension as ext
    from koordinator_tpu.runtimeproxy import HookRequest, HookType
    from koordinator_tpu.transport import RpcClient
    from koordinator_tpu.transport.services import hook_remote

    asm = main_koordlet([
        "--cgroup-root-dir", str(tmp_path / "cg"),
        "--proc-root-dir", str(tmp_path / "proc"),
        "--runtime-hook-server-addr", str(tmp_path / "hooks.sock"),
    ])
    try:
        client = RpcClient(asm.component.hook_server.path)
        client.connect()
        try:
            res = hook_remote(client, HookType.PRE_RUN_POD_SANDBOX,
                              HookRequest(
                                  pod_meta={"uid": "u1", "name": "p1"},
                                  labels={ext.LABEL_POD_QOS: "BE"}))
            # GroupIdentity (default-on) answered from the daemon's
            # registry: BE bvt from the default NodeSLO
            assert res["resources"]["cpu.bvt_warp_ns"] == "-1"
        finally:
            client.close()
    finally:
        asm.component.stop()   # daemon lifecycle stops the hook server too


def test_scheduler_assembly_with_lease_and_socket(tmp_path):
    store = InMemoryLeaseStore()
    out = main_koord_scheduler([
        "--node-capacity", "32",
        "--gang-passes", "3",
        "--identity", "sched-a",
        "--listen-socket", str(tmp_path / "sched.sock"),
    ], lease_store=store)
    try:
        sched = out.component
        assert sched.snapshot.capacity == 32
        assert sched.gang_passes == 3
        assert sched.explanations is not None and sched.auditor is not None
        assert out.elector is not None
        assert out.elector.identity == "sched-a"
        assert out.elector.lease_name == "koordinator-system/koord-scheduler"
        assert out.elector.tick() is True
        # the solve service answers over the socket
        from koordinator_tpu.transport import RpcClient
        from koordinator_tpu.transport.services import solve_remote

        client = RpcClient(out.server.path)
        client.connect()
        try:
            result = solve_remote(client)
            assert result["assignments"] == {} and result["round_pods"] == 0
        finally:
            client.close()
    finally:
        if out.server is not None:
            out.server.stop()


def test_scheduler_leader_election_disable():
    out = main_koord_scheduler(["--disable-leader-election"])
    assert out.elector is None


def test_manager_assembly_and_gates():
    before = SCHEDULER_GATES.enabled("MultiQuotaTree")
    try:
        out = main_koord_manager(
            ["--feature-gates", "MultiQuotaTree=true", "--identity", "m0"])
        assert SCHEDULER_GATES.enabled("MultiQuotaTree") is True
        assert out.component.nodemetric is not None
        assert out.component.noderesource is not None
        assert out.component.pod_mutating is not None
        assert out.elector.lease_name == "koordinator-system/koord-manager"
        # the full controller set assembles (quota profiles + VPA-ish
        # recommendation ride along with the SLO controllers)
        assert out.component.quota_profile is not None
        assert out.component.recommendation is not None
        # multi-tree affinity is gated (reference gates this webhook)
        assert out.component.multi_tree_affinity is not None
    finally:
        SCHEDULER_GATES.set("MultiQuotaTree", before)


def test_descheduler_assembly_gated_on_leadership():
    store = InMemoryLeaseStore()
    out_a = main_koord_descheduler(
        ["--descheduling-interval-seconds", "0", "--identity", "a"],
        lease_store=store)
    out_b = main_koord_descheduler(
        ["--descheduling-interval-seconds", "0", "--identity", "b"],
        lease_store=store)
    assert out_a.component.tick() == {"default": 0}
    assert out_b.component.tick() is None       # follower replica


def test_descheduler_evictor_flags():
    out = main_koord_descheduler([
        "--priority-threshold", "8000",
        "--evict-local-storage-pods",
        "--max-evictions-per-round", "5",
    ])
    profile = out.component.profiles[0]
    assert profile.evictor_filter.priority_threshold == 8000
    assert profile.evictor_filter.evict_local_storage is True
    assert profile.max_evictions_per_round == 5


def test_runtime_proxy_with_hook_socket(tmp_path):
    from koordinator_tpu.runtimeproxy import HookRequest, HookResponse, HookType
    from koordinator_tpu.transport import RpcClient
    from koordinator_tpu.transport.services import hook_remote

    out = main_koord_runtime_proxy(
        ["--hook-server-socket", str(tmp_path / "hooks.sock")])
    try:
        class Hooker:
            def handle(self, hook, request):
                return HookResponse(annotations={"seen": "1"})

        out.component.dispatcher.register(
            Hooker(), [HookType.PRE_CREATE_CONTAINER])
        client = RpcClient(out.server.path)
        client.connect()
        try:
            res = hook_remote(client, HookType.PRE_CREATE_CONTAINER,
                              HookRequest())
            assert res["annotations"] == {"seen": "1"}
        finally:
            client.close()
    finally:
        out.server.stop()


def test_device_daemon_requires_node_name():
    with pytest.raises(SystemExit):
        MAINS["koord-device-daemon"]([])
    out = MAINS["koord-device-daemon"](["--node-name", "n1"])
    assert out.component.node_name == "n1"


def test_descheduler_assembles_upstream_plugins():
    from koordinator_tpu.cmd.binaries import main_koord_descheduler
    from koordinator_tpu.descheduler.framework import PodInfo

    pods = [PodInfo(uid="old", name="old", namespace="d",
                node="n1", phase="Failed")]
    out = main_koord_descheduler([
        "--deschedule-plugins", "removefailedpods, podlifetime ,removeduplicates",
        "--disable-leader-election",
    ], pods_fn=lambda: pods)
    profile = out.component.profiles[0]
    assert len(profile.deschedule_plugins) == 2
    assert len(profile.balance_plugins) == 1
    counts = out.component.run_once()
    assert counts["default"] >= 1        # the failed pod was descheduled

    import pytest

    with pytest.raises(SystemExit):
        main_koord_descheduler(
            ["--deschedule-plugins", "nope", "--disable-leader-election"])


def test_koordlet_http_gateway_serves_podresources(tmp_path):
    import json as _json
    import urllib.request

    old = KOORDLET_GATES.enabled("PodResourcesProxy")
    KOORDLET_GATES.set("PodResourcesProxy", True)
    try:
        asm = main_koordlet([
            "--cgroup-root-dir", str(tmp_path / "cg"),
            "--proc-root-dir", str(tmp_path / "proc"),
            "--sys-root-dir", str(tmp_path / "sys"),
            "--http-port", "0",
        ])
        gw = asm.component.gateway
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{gw.port}/v1/podresources",
                    timeout=10) as resp:
                doc = _json.loads(resp.read().decode())
            assert doc == {"pod_resources": []}
        finally:
            # daemon lifecycle owns the gateway
            asm.component.stop()
        assert asm.component.gateway is None
    finally:
        KOORDLET_GATES.set("PodResourcesProxy", old)


def test_koordlet_pod_resources_upstream_seam(tmp_path):
    import json as _json
    import urllib.request

    old = KOORDLET_GATES.enabled("PodResourcesProxy")
    KOORDLET_GATES.set("PodResourcesProxy", True)
    try:
        upstream = {"pod_resources": [{
            "name": "k", "namespace": "d",
            "containers": [{"name": "c", "devices": [
                {"resource_name": "cpu", "device_ids": ["0-3"]}]}]}]}
        asm = main_koordlet([
            "--cgroup-root-dir", str(tmp_path / "cg"),
            "--proc-root-dir", str(tmp_path / "proc"),
            "--sys-root-dir", str(tmp_path / "sys"),
            "--http-port", "0",
        ], pod_resources_upstream_fn=lambda: upstream)
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{asm.component.gateway.port}"
                    f"/v1/podresources", timeout=10) as resp:
                doc = _json.loads(resp.read().decode())
            # kubelet's own listing flows through the assembled binary
            assert doc["pod_resources"][0]["containers"][0]["devices"] == [
                {"resource_name": "cpu", "device_ids": ["0-3"]}]
        finally:
            asm.component.stop()
    finally:
        KOORDLET_GATES.set("PodResourcesProxy", old)


def test_scheduler_binary_is_a_full_sidecar(tmp_path):
    """koord-scheduler --listen-socket + --http-port: state enters over
    STATE_PUSH frames or POST /v1/state, applies to the scheduler
    SYNCHRONOUSLY through the in-process binding, and the very next
    solve sees it — no eventual-consistency window."""
    import json
    import urllib.request

    import numpy as np

    from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS
    from koordinator_tpu.transport import RpcClient
    from koordinator_tpu.transport.services import solve_remote
    from koordinator_tpu.transport.wire import FrameType

    asm = main_koord_scheduler([
        "--node-capacity", "16",
        "--listen-socket", str(tmp_path / "sidecar.sock"),
        "--http-port", "0",
    ])
    r = NUM_RESOURCE_DIMS
    try:
        # framed path: push a node, then solve over the same socket
        client = RpcClient(asm.server.path)
        client.connect()
        try:
            _, doc, _ = client.call(
                FrameType.STATE_PUSH,
                {"kind": "node_upsert", "name": "wire-node"},
                {"allocatable": np.asarray(
                    [8_000, 16_384] + [0] * (r - 2), np.int32)})
            assert doc["rv"] == 1

            # HTTP path: push a pod with curl-equivalent plumbing
            body = json.dumps({
                "kind": "pod_add", "name": "http-pod",
                "requests": [1_000, 1_024] + [0] * (r - 2),
            }).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{asm.gateway.port}/v1/state",
                data=body, headers={"Content-Type": "application/json"},
                method="POST")
            with urllib.request.urlopen(req, timeout=10) as resp:
                assert json.loads(resp.read())["rv"] == 2

            # the binding applied both synchronously: first solve wins
            result = solve_remote(client)
            assert result["assignments"] == {"http-pod": "wire-node"}
        finally:
            client.close()
    finally:
        asm.stop()


def test_stop_releases_leadership_for_fast_failover():
    store = InMemoryLeaseStore()
    a = main_koord_scheduler(["--identity", "a"], lease_store=store)
    b = main_koord_scheduler(["--identity", "b"], lease_store=store)
    assert a.elector.tick() is True
    assert b.elector.tick() is False
    a.stop()   # clean shutdown releases the lease (ReleaseOnCancel)
    assert b.elector.tick() is True, "follower should acquire immediately"


def test_manager_sloconfig_bootstrap_file(tmp_path):
    import textwrap

    path = tmp_path / "slo.yaml"
    path.write_text(textwrap.dedent("""
        colocation-config:
          enable: true
          cpuReclaimThresholdPercent: 55
        resource-threshold-config:
          enable: true
          cpuSuppressThresholdPercent: 60
    """))
    out = main_koord_manager(["--sloconfig-file", str(path),
                              "--disable-leader-election"])
    assert out.component.noderesource.config.enable is True
    assert out.component.noderesource.config \
              .cpu_reclaim_threshold_percent == 55
    # the NodeSLO controller renders the bootstrapped strategy
    out.component.nodeslo.upsert_node("n1", {})
    slo = out.component.nodeslo.get("n1")
    assert slo.resource_used_threshold_with_be \
              .cpu_suppress_threshold_percent == 60


def test_manager_sloconfig_bootstrap_rejects_invalid(tmp_path):
    path = tmp_path / "slo.yaml"
    path.write_text("colocation-config:\n  cpuReclaimThresholdPercent: 300\n")
    with pytest.raises(SystemExit, match="invalid slo config"):
        main_koord_manager(["--sloconfig-file", str(path),
                            "--disable-leader-election"])


def test_manager_watched_cm_supersedes_bootstrap(tmp_path):
    import json
    import textwrap

    path = tmp_path / "slo.yaml"
    path.write_text(textwrap.dedent("""
        colocation-config:
          enable: true
          cpuReclaimThresholdPercent: 55
    """))
    out = main_koord_manager(["--sloconfig-file", str(path),
                              "--disable-leader-election"])
    assert out.component.noderesource.config \
              .cpu_reclaim_threshold_percent == 55
    # live CM update: colocation math follows, bad updates keep last good
    out.component.update_sloconfig({"colocation-config": json.dumps(
        {"enable": True, "cpuReclaimThresholdPercent": 70})})
    assert out.component.noderesource.config \
              .cpu_reclaim_threshold_percent == 70
    out.component.update_sloconfig({"colocation-config": json.dumps(
        {"cpuReclaimThresholdPercent": 300})})
    assert out.component.noderesource.config \
              .cpu_reclaim_threshold_percent == 70


def test_manager_bootstrap_without_colocation_keeps_enable_default(tmp_path):
    path = tmp_path / "slo.yaml"
    path.write_text("resource-threshold-config:\n  enable: true\n")
    out = main_koord_manager(["--sloconfig-file", str(path),
                              "--disable-leader-election"])
    assert out.component.noderesource.config.enable is True


def test_koordlet_polls_a_kubelet(tmp_path):
    """--kubelet-addr: the agent's pod informer pulls from a live kubelet
    endpoint on the daemon tick cadence (states_pods.go), with informer
    errors isolated rather than failing the tick."""
    import http.server
    import json
    import threading

    pod_list = {"items": [{
        "metadata": {"uid": "kub-1", "name": "from-kubelet",
                     "namespace": "default",
                     "labels": {"koordinator.sh/qosClass": "BE"}},
        "spec": {"containers": [{"resources": {
            "requests": {"cpu": "250m", "memory": "256Mi"}}}]},
        "status": {"phase": "Running", "qosClass": "BestEffort"},
    }]}

    class Handler(http.server.BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            pass

        def do_GET(self):
            body = json.dumps(pod_list).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    server = http.server.HTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        asm = main_koordlet([
            "--cgroup-root-dir", str(tmp_path / "cg"),
            "--proc-root-dir", str(tmp_path / "proc"),
            "--kubelet-addr", "127.0.0.1",
            "--kubelet-port", str(server.server_address[1]),
            "--kubelet-scheme", "http",
        ])
        import time as _time

        def tick_and_settle():
            # informer rounds run off the enforcement loop on their own
            # thread; wait for the in-flight round to land
            asm.component.tick()
            deadline = _time.monotonic() + 15
            while (asm.component._informer_inflight.is_set()
                   and _time.monotonic() < deadline):
                _time.sleep(0.02)
            assert not asm.component._informer_inflight.is_set()

        try:
            tick_and_settle()
            pods = asm.component.states.get_all_pods()
            assert [p.uid for p in pods] == ["kub-1"]
            assert pods[0].requests == {"cpu": 250, "memory": 256 << 20}
            assert not asm.component.informers.sync_errors

            # kubelet goes away: the tick keeps working, the error is
            # recorded, the last-good pods stay, and a fully-failed
            # round does not stamp the cadence (it will retry)
            server.shutdown()
            server.server_close()
            asm.component._last_informer_sync = float("-inf")
            tick_and_settle()
            assert "pods" in asm.component.informers.sync_errors
            assert [p.uid for p in asm.component.states.get_all_pods()] \
                == ["kub-1"]
            assert asm.component._last_informer_sync == float("-inf")
        finally:
            asm.component.stop()
    finally:
        try:
            server.shutdown()
            server.server_close()
        except Exception:
            pass
