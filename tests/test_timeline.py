"""Critical-path observatory (ISSUE 18): the acceptance suite.

The contracts under test:

- **sweepline attribution**: over any window the per-cause seconds sum
  to the window exactly, the highest-priority covering segment wins at
  every instant (nesting puts a block wait above its containing
  phase), uncovered wall lands in the explicit ``unattributed``
  residual, and the covering chain merges same-cause neighbours;
- **device idle**: idle intervals are the window minus the union of
  the dispatch->block ``device_busy`` spans;
- **phase accounting** on a REAL pipelined multi-tenant cycle: the
  attribution fractions sum to 1.0, ``unattributed`` stays under 5%,
  and the ``device_block`` bucket matches
  ``pipeline_host_wait_fraction`` (same block_until_ready intervals —
  compared with approx, never ``==``: the gauge sums per-tenant
  accumulators, the sweep sums elementary intervals);
- **/debug/timeline** parity across DebugService and the HTTP gateway
  (shared ``debug_timeline_body``) with a typed 400 on a bad bound;
- **kill switch**: ``--no-timeline`` / ``set_enabled(False)`` records
  nothing and leaves scheduling decisions bit-identical, at under 3%
  measured wall overhead;
- **perfetto export**: ``tools/trace_dump.py --perfetto`` round-trips
  recorded segments and device-idle intervals to microsecond
  precision;
- **training export**: ``soak_report.export_training_records`` joins
  rounds to cycles by ``cycle_seq``, stamps the schema version, and is
  byte-deterministic.

Compile budget: every scheduler in this module shares ONE
``SolverKit(mesh="off")`` module fixture and tiny shapes.
"""

import json
import os
import sys

import numpy as np
import pytest

from koordinator_tpu import timeline

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _seg(start, end, cause, name="", tenant=""):
    return {"start": start, "end": end, "cause": cause, "name": name,
            "tenant": tenant}


@pytest.fixture(scope="module")
def kit_off():
    from koordinator_tpu.scheduler.solver_kit import SolverKit

    return SolverKit(mesh="off")


def _feed_nodes(scheduler, n=8, seed=3):
    from koordinator_tpu.api.resources import resource_vector
    from koordinator_tpu.scheduler.snapshot import NodeSpec

    rng = np.random.default_rng(seed)
    for i in range(n):
        scheduler.snapshot.upsert_node(NodeSpec(
            name=f"n{i}",
            allocatable=resource_vector(
                cpu=int(rng.integers(8_000, 32_000)),
                memory=int(rng.integers(16_384, 65_536))),
            usage=resource_vector(cpu=int(rng.integers(0, 2_000)),
                                  memory=int(rng.integers(0, 4_096)))))


def _enqueue_pods(scheduler, n, seed=0):
    from koordinator_tpu.api.resources import resource_vector
    from koordinator_tpu.scheduler.snapshot import PodSpec

    rng = np.random.default_rng(seed)
    for j in range(n):
        scheduler.enqueue(PodSpec(
            name=f"p{seed}-{j}",
            requests=resource_vector(cpu=int(rng.integers(200, 2_000)),
                                     memory=int(rng.integers(256, 4_096))),
            priority=int(rng.integers(3_000, 9_999))))


def _lone_scheduler(kit, capacity=32, seed=3):
    from koordinator_tpu.scheduler import ClusterSnapshot, Scheduler

    sched = Scheduler(ClusterSnapshot(capacity=capacity), mesh="off",
                      solver_kit=kit)
    _feed_nodes(sched, seed=seed)
    return sched


def _make_front(kit, tenants=("a", "b")):
    from koordinator_tpu.scheduler.tenancy import (
        TenantScheduler,
        TenantSpec,
    )

    front = TenantScheduler(solver_kit=kit, cycle_pod_budget=1 << 20)
    for name in tenants:
        front.add_tenant(TenantSpec(name=name, node_capacity=16),
                         batch_solver_threshold=1)
    for ti, tenant in enumerate(front.tenants()):
        _feed_nodes(tenant.scheduler, seed=11 + ti)
    return front


# ---------------------------------------------------------------------------
# sweepline attribution (pure host math, no JAX)
# ---------------------------------------------------------------------------


class TestSweepAttribution:
    def test_totals_sum_to_window_exactly(self):
        segs = [_seg(1.0, 3.0, "host_other"),
                _seg(2.0, 4.0, "device_block"),
                _seg(6.0, 7.5, "bind_commit")]
        totals, chain = timeline.sweep_attribution(segs, 0.0, 10.0)
        assert sum(totals.values()) == pytest.approx(10.0)
        # the chain covers the window end to end, in order
        assert chain[0]["start"] == 0.0 and chain[-1]["end"] == 10.0
        for a, b in zip(chain, chain[1:]):
            assert a["end"] == b["start"]

    def test_highest_priority_covering_segment_wins(self):
        # a block wait nested inside a phase attributes as device_block
        segs = [_seg(0.0, 10.0, "host_other", "phase.Solve"),
                _seg(2.0, 4.0, "device_block", "block_until_ready")]
        totals, _ = timeline.sweep_attribution(segs, 0.0, 10.0)
        assert totals["device_block"] == pytest.approx(2.0)
        assert totals["host_other"] == pytest.approx(8.0)
        assert totals[timeline.UNATTRIBUTED] == 0.0

    def test_gaps_land_in_unattributed(self):
        segs = [_seg(0.0, 2.0, "build_batch"), _seg(5.0, 8.0, "bind_commit")]
        totals, chain = timeline.sweep_attribution(segs, 0.0, 10.0)
        assert totals[timeline.UNATTRIBUTED] == pytest.approx(5.0)
        causes = [c["cause"] for c in chain]
        assert causes == ["build_batch", timeline.UNATTRIBUTED,
                          "bind_commit", timeline.UNATTRIBUTED]

    def test_chain_merges_adjacent_same_cause(self):
        segs = [_seg(0.0, 2.0, "deltasync_apply"),
                _seg(2.0, 5.0, "deltasync_apply")]
        totals, chain = timeline.sweep_attribution(segs, 0.0, 5.0)
        assert totals["deltasync_apply"] == pytest.approx(5.0)
        assert len(chain) == 1
        assert chain[0] == {"start": 0.0, "end": 5.0,
                            "cause": "deltasync_apply", "name": ""}

    def test_segments_clip_to_the_window(self):
        segs = [_seg(-5.0, 2.0, "json_codec"), _seg(8.0, 20.0, "lock_wait")]
        totals, _ = timeline.sweep_attribution(segs, 0.0, 10.0)
        assert totals["json_codec"] == pytest.approx(2.0)
        assert totals["lock_wait"] == pytest.approx(2.0)
        assert totals[timeline.UNATTRIBUTED] == pytest.approx(6.0)

    def test_degenerate_window(self):
        totals, chain = timeline.sweep_attribution(
            [_seg(0.0, 1.0, "dispatch")], 5.0, 5.0)
        assert sum(totals.values()) == 0.0
        assert chain == []

    def test_device_busy_never_attributes(self):
        segs = [_seg(0.0, 10.0, timeline.DEVICE_BUSY, "solve")]
        totals, chain = timeline.sweep_attribution(segs, 0.0, 10.0)
        assert totals[timeline.UNATTRIBUTED] == pytest.approx(10.0)
        assert [c["cause"] for c in chain] == [timeline.UNATTRIBUTED]


class TestDeviceIdle:
    def test_idle_is_the_complement_of_merged_busy(self):
        segs = [_seg(1.0, 3.0, timeline.DEVICE_BUSY),
                _seg(2.0, 5.0, timeline.DEVICE_BUSY),   # overlaps -> merge
                _seg(7.0, 8.0, timeline.DEVICE_BUSY),
                _seg(0.0, 10.0, "host_other")]          # ignored
        idle, busy_s = timeline.device_idle(segs, 0.0, 10.0)
        assert busy_s == pytest.approx(5.0)
        assert idle == [(0.0, 1.0), (5.0, 7.0), (8.0, 10.0)]

    def test_no_busy_means_fully_idle(self):
        idle, busy_s = timeline.device_idle([], 2.0, 6.0)
        assert busy_s == 0.0
        assert idle == [(2.0, 6.0)]


# ---------------------------------------------------------------------------
# the recorder
# ---------------------------------------------------------------------------


class TestRecorder:
    def test_cycle_doc_shape_and_critical_path(self):
        rec = timeline.TimelineRecorder()
        rec.add(100.0, 103.0, "build_batch", "phase.BatchBuild", "a")
        rec.add(103.0, 104.0, "device_block", "block_until_ready", "a")
        rec.add(100.5, 104.0, timeline.DEVICE_BUSY, "solve", "a")
        doc = rec.finish_cycle(7, 100.0, 110.0, mode="pipelined",
                               publish=False)
        assert doc["cycle"] == 7 and doc["mode"] == "pipelined"
        assert doc["wall_s"] == pytest.approx(10.0)
        # fractions sum to 1.0 with the residual included
        assert sum(doc["attribution"].values()) == pytest.approx(1.0)
        assert doc["unattributed_fraction"] == pytest.approx(0.6)
        # segments re-based to the window start
        assert doc["segments"][0]["start"] == pytest.approx(0.0)
        # busy spans 100.5..104.0 -> idle 0..0.5 and 4..10
        assert doc["device_busy_s"] == pytest.approx(3.5)
        assert doc["device_idle_fraction"] == pytest.approx(0.65)
        assert doc["device_idle"] == [
            pytest.approx((0.0, 0.5)), pytest.approx((4.0, 10.0))]
        # build_batch holds 3 of the 4 attributed seconds
        assert doc["critical_cause"] == "build_batch"
        assert doc["critical_seconds"] == pytest.approx(3.0)
        assert doc["attribution_s"]["device_block"] == pytest.approx(1.0)

    def test_cycles_are_newest_first_and_bounded(self):
        rec = timeline.TimelineRecorder(max_cycles=4)
        for i in range(6):
            rec.add(float(i), i + 0.5, "host_other")
            rec.finish_cycle(i, float(i), i + 1.0, publish=False)
        got = [d["cycle"] for d in rec.cycles(limit=16)]
        assert got == [5, 4, 3, 2]
        assert [d["cycle"] for d in rec.cycles(limit=2)] == [5, 4]

    def test_consumed_segments_never_reattribute(self):
        rec = timeline.TimelineRecorder()
        rec.add(0.0, 1.0, "bind_commit")
        first = rec.finish_cycle(1, 0.0, 2.0, publish=False)
        assert first["attribution_s"]["bind_commit"] == pytest.approx(1.0)
        again = rec.finish_cycle(2, 0.0, 2.0, publish=False)
        assert again["attribution_s"]["bind_commit"] == 0.0

    def test_disabled_recorder_is_inert(self):
        rec = timeline.TimelineRecorder(enabled=False)
        rec.add(0.0, 1.0, "host_other")
        with rec.section("json_codec"):
            pass
        assert rec.finish_cycle(1, 0.0, 2.0, publish=False) is None
        assert rec.cycles() == []

    def test_kill_switch_drops_pending_segments(self):
        rec = timeline.TimelineRecorder()
        rec.add(0.0, 1.0, "host_other")
        rec.set_enabled(False)
        rec.set_enabled(True)
        doc = rec.finish_cycle(1, 0.0, 2.0, publish=False)
        assert doc["attribution_s"]["host_other"] == 0.0

    def test_backwards_and_empty_segments_ignored(self):
        rec = timeline.TimelineRecorder()
        rec.add(5.0, 5.0, "host_other")
        rec.add(5.0, 4.0, "host_other")
        doc = rec.finish_cycle(1, 0.0, 10.0, publish=False)
        assert doc["segments"] == []


# ---------------------------------------------------------------------------
# real rounds / cycles
# ---------------------------------------------------------------------------


class TestRoundReconstruction:
    """An untenanted scheduler's round is its own one-round cycle."""

    def test_schedule_round_reconstructs_and_annotates(self, kit_off):
        timeline.RECORDER.reset_for_tests()
        sched = _lone_scheduler(kit_off)
        _enqueue_pods(sched, 6, seed=1)
        result = sched.schedule_round()
        assert result.assignments
        docs = timeline.RECORDER.cycles(1)
        assert len(docs) == 1
        doc = docs[0]
        assert doc["mode"] == "round"
        assert doc["cycle"] == sched.round_seq
        assert sum(doc["attribution"].values()) == pytest.approx(1.0)
        # the round recorded real segments: phases + the block wait
        causes = {s["cause"] for s in doc["segments"]}
        assert "device_block" in causes
        assert "host_other" in causes
        assert 0.0 <= doc["device_idle_fraction"] <= 1.0
        # the flight record carries the critical-path join
        rec = list(sched.flight_recorder.records)[-1]
        assert rec.cycle_seq == doc["cycle"]
        assert rec.cycle_critical_cause == doc["critical_cause"]
        assert rec.cycle_critical_seconds == pytest.approx(
            doc["critical_seconds"])

    def test_published_gauges_cover_every_cause(self, kit_off):
        from koordinator_tpu import metrics

        timeline.RECORDER.reset_for_tests()
        sched = _lone_scheduler(kit_off, seed=5)
        _enqueue_pods(sched, 4, seed=2)
        sched.schedule_round()
        doc = timeline.RECORDER.cycles(1)[0]
        got = {}
        for (labels, value) in metrics.host_wait_attribution.items():
            got[dict(labels)["cause"]] = value
        assert set(got) == set(timeline.ATTRIBUTION_CAUSES)
        assert sum(got.values()) == pytest.approx(1.0)
        assert got["device_block"] == pytest.approx(
            doc["attribution"]["device_block"])
        assert metrics.device_idle_fraction.value() == pytest.approx(
            doc["device_idle_fraction"])


class TestPhaseAccountingInvariant:
    """The named segments + attributed gaps must sum to the cycle wall
    with the unattributed residual under 5% — silently untimed host
    work can never reappear (ISSUE 18 satellite)."""

    @pytest.fixture(scope="class")
    def cycled_front(self, kit_off):
        timeline.RECORDER.reset_for_tests()
        front = _make_front(kit_off)
        # cycle 1 pays the jit compiles (still attributed: compile wall
        # lands inside the dispatch/Solve segments); measure after
        docs = []
        for i in range(4):
            for ti, tenant in enumerate(front.tenants()):
                _enqueue_pods(tenant.scheduler, 6, seed=100 + 10 * i + ti)
            front.schedule_cycle()
            docs.append((front.last_timeline,
                         front.last_host_wait_fraction))
        return front, docs

    def test_attribution_sums_to_the_wall(self, cycled_front):
        _, docs = cycled_front
        for doc, _ in docs:
            assert doc is not None
            assert sum(doc["attribution"].values()) == pytest.approx(1.0)
            assert sum(doc["attribution_s"].values()) == pytest.approx(
                doc["wall_s"])

    def test_unattributed_residual_under_5pct(self, cycled_front):
        _, docs = cycled_front
        # min over warm cycles: one descheduled hiccup must not flake
        # the invariant, but SOME cycle has to meet the bar squarely
        best = min(doc["unattributed_fraction"] for doc, _ in docs[1:])
        assert best < 0.05, [d["unattributed_fraction"] for d, _ in docs]

    def test_device_block_matches_pipeline_host_wait_fraction(
            self, cycled_front):
        _, docs = cycled_front
        for doc, gauge in docs[1:]:
            # same intervals, different summation order -> approx
            assert doc["attribution"]["device_block"] == pytest.approx(
                gauge, abs=0.02)

    def test_cycle_mode_and_tenant_tags(self, cycled_front):
        front, docs = cycled_front
        doc, _ = docs[-1]
        assert doc["mode"] == front.last_mode
        tenants = {s["tenant"] for s in doc["segments"]} - {""}
        assert tenants == {"a", "b"}


# ---------------------------------------------------------------------------
# debug surfaces
# ---------------------------------------------------------------------------


class TestDebugTimelineSurfaces:
    def test_parity_across_both_surfaces(self, kit_off):
        import urllib.request

        from koordinator_tpu.scheduler.services import DebugService
        from koordinator_tpu.transport.http_gateway import HttpGateway

        timeline.RECORDER.reset_for_tests()
        sched = _lone_scheduler(kit_off, seed=7)
        _enqueue_pods(sched, 4, seed=3)
        sched.schedule_round()
        service = DebugService(sched)
        status, body = service.handle("/debug/timeline", {"cycles": "4"})
        assert status == 200
        assert body["enabled"] is True
        assert body["causes"] == list(timeline.ATTRIBUTION_CAUSES)
        assert len(body["cycles"]) == 1
        assert body["cycles"][0]["critical_cause"]

        gateway = HttpGateway(scheduler=sched)
        gateway.start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{gateway.port}"
                    f"/debug/timeline?cycles=4") as resp:
                gw_body = json.loads(resp.read())
        finally:
            gateway.stop()
        # the gateway body is the same builder's output json-roundtripped
        assert gw_body == json.loads(json.dumps(body))

    def test_bad_bound_is_a_typed_400_on_both_surfaces(self, kit_off):
        import urllib.error
        import urllib.request

        from koordinator_tpu.scheduler.services import DebugService
        from koordinator_tpu.transport.http_gateway import HttpGateway

        sched = _lone_scheduler(kit_off, seed=9)
        service = DebugService(sched)
        assert service.handle("/debug/timeline", {"cycles": "bogus"})[0] == 400
        assert service.handle("/debug/timeline", {"cycles": "0"})[0] == 400
        assert service.handle("/debug/timeline", {"cycles": "-3"})[0] == 400

        gateway = HttpGateway(scheduler=sched)
        gateway.start()
        try:
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{gateway.port}"
                    f"/debug/timeline?cycles=bogus")
            assert err.value.code == 400
        finally:
            gateway.stop()


# ---------------------------------------------------------------------------
# kill switch: bit-identity + overhead
# ---------------------------------------------------------------------------


class TestKillSwitch:
    def test_no_timeline_flag_parses(self):
        from koordinator_tpu.cmd.binaries import build_scheduler_parser

        args = build_scheduler_parser().parse_args(["--no-timeline"])
        assert args.no_timeline is True
        assert build_scheduler_parser().parse_args([]).no_timeline is False

    def test_decisions_bit_identical_with_recorder_off(self, kit_off):
        def run(enabled):
            timeline.RECORDER.reset_for_tests()
            was = timeline.RECORDER.enabled
            timeline.RECORDER.set_enabled(enabled)
            try:
                sched = _lone_scheduler(kit_off, seed=13)
                _enqueue_pods(sched, 8, seed=4)
                result = sched.schedule_round()
                return (dict(result.assignments),
                        sorted(result.failures),
                        len(timeline.RECORDER.cycles()))
            finally:
                timeline.RECORDER.set_enabled(was)

        on_assign, on_fail, on_cycles = run(True)
        off_assign, off_fail, off_cycles = run(False)
        assert on_assign == off_assign
        assert on_fail == off_fail
        assert on_cycles == 1 and off_cycles == 0

    def test_recording_overhead_under_3pct(self, kit_off):
        """The recorder's whole per-cycle cost — every segment add plus
        the finish_cycle sweep/publish — must stay under 3% of a real
        cycle's wall.  Measured by REPLAYING an actual recorded cycle's
        segments through a fresh recorder: an end-to-end on/off wall
        diff at unit-test scale drowns in scheduler jitter (the
        bench_stages ``timeline_overhead`` stage measures that form at
        soak scale, ~1%), while the replay bounds the same cost
        deterministically against the same cycle's measured wall."""
        import itertools
        import time as _time

        front = _make_front(kit_off)
        seeds = itertools.count(3000)
        walls = []
        for _ in range(5):
            for tenant in front.tenants():
                _enqueue_pods(tenant.scheduler, 8, seed=next(seeds))
            t0 = _time.perf_counter()
            front.schedule_cycle()
            walls.append(_time.perf_counter() - t0)
        wall = min(walls[1:])       # post-compile cycle-wall floor
        doc = front.last_timeline
        segs = doc["segments"]
        assert len(segs) >= 10      # a genuinely instrumented cycle

        rec = timeline.TimelineRecorder()
        reps, costs = 50, []
        for _ in range(5):
            t0 = _time.perf_counter()
            for i in range(reps):
                for s in segs:
                    rec.add(s["start"], s["end"], s["cause"],
                            s["name"], s["tenant"])
                rec.finish_cycle(i, 0.0, doc["wall_s"], mode="replay")
            costs.append((_time.perf_counter() - t0) / reps)
        cost = min(costs)           # the defensible cost floor
        overhead = cost / wall
        assert overhead < 0.03, (
            f"recorder cost {cost*1e6:.0f}us on a {wall*1e3:.2f}ms "
            f"cycle = {overhead:.1%}")


# ---------------------------------------------------------------------------
# perfetto export round-trip
# ---------------------------------------------------------------------------


class TestPerfettoExport:
    def _recorded_cycle(self, kit_off):
        """A REAL recorded cycle doc + the round's spans, like a soak
        trace capture would hold."""
        from koordinator_tpu import tracing

        timeline.RECORDER.reset_for_tests()
        exporter = tracing.InMemoryExporter()
        tracing.TRACER.add_exporter(exporter)
        try:
            sched = _lone_scheduler(kit_off, seed=21)
            _enqueue_pods(sched, 4, seed=6)
            sched.schedule_round()
        finally:
            tracing.TRACER.remove_exporter(exporter)
        cycle = timeline.RECORDER.cycles(1)[0]
        spans = [s.to_doc() for s in exporter.spans]
        assert spans, "round must have produced spans"
        return cycle, spans

    def test_round_trip_on_a_recorded_trace(self, kit_off, tmp_path):
        import trace_dump

        cycle, spans = self._recorded_cycle(kit_off)
        src = tmp_path / "soak_trace.jsonl"
        with open(src, "w") as f:
            for doc in spans + [cycle]:
                f.write(json.dumps(doc, default=str) + "\n")
        out = tmp_path / "perfetto.json"
        assert trace_dump.main([str(src), "--perfetto", str(out)]) == 0
        body = json.loads(out.read_text())
        events = body["traceEvents"]

        # track metadata: every service + the timeline process named
        names = {e["args"]["name"] for e in events
                 if e["ph"] == "M" and e["name"] == "process_name"}
        assert "timeline" in names
        assert "scheduler" in names

        # every recorded segment round-trips to its X event (us clock)
        t0 = cycle["start"]
        xs = [e for e in events
              if e["ph"] == "X" and e.get("cat") in timeline.CAUSES
              + (timeline.DEVICE_BUSY,)]
        assert len(xs) == len(cycle["segments"])
        got = sorted((e["ts"], e["args"]["cause"]) for e in xs)
        want = sorted(((t0 + s["start"]) * 1e6, s["cause"])
                      for s in cycle["segments"])
        for (gts, gcause), (wts, wcause) in zip(got, want):
            assert gts == pytest.approx(wts, abs=1.0)   # 1 us
            assert gcause == wcause

        # device-idle intervals become balanced async begin/end pairs
        begins = [e for e in events if e["ph"] == "b"]
        ends = [e for e in events if e["ph"] == "e"]
        assert len(begins) == len(ends) == len(cycle["device_idle"])
        for b, (i0, _) in zip(sorted(begins, key=lambda e: e["ts"]),
                              cycle["device_idle"]):
            assert b["ts"] == pytest.approx((t0 + i0) * 1e6, abs=1.0)

        # span docs kept their ids for the cross-reference
        span_events = [e for e in events
                       if e["ph"] == "X" and "trace_id" in e["args"]]
        assert {e["args"]["trace_id"] for e in span_events} == {
            s["trace_id"] for s in spans}

    def test_export_without_input_fails(self, tmp_path):
        import trace_dump

        src = tmp_path / "empty.jsonl"
        src.write_text("not json\n")
        assert trace_dump.main(
            [str(src), "--perfetto", str(tmp_path / "o.json")]) == 1


# ---------------------------------------------------------------------------
# training-record export
# ---------------------------------------------------------------------------


class TestTrainingExport:
    def _inputs(self):
        rounds = [
            {"round": 3, "tenant": "a", "cycle_seq": 9, "placed": 4,
             "solve_path": "incremental"},
            {"round": 3, "tenant": "b", "cycle_seq": 9, "placed": 2,
             "solve_path": "full_cold"},
            {"round": 2, "tenant": "a", "cycle_seq": -1, "placed": 1,
             "solve_path": "full_cold"},
        ]
        cycles = [{"cycle": 9, "mode": "pipelined", "wall_s": 0.25,
                   "attribution": {"device_block": 0.5,
                                   "unattributed": 0.5},
                   "unattributed_fraction": 0.5,
                   "device_idle_fraction": 0.4,
                   "critical_cause": "device_block",
                   "critical_seconds": 0.125}]
        slo = {"scheduling_latency_p99": {
            "breaches_total": 1,
            "peak_burn": {"fast": 20.0, "slow": 2.0}}}
        return rounds, cycles, slo

    def test_join_schema_and_determinism(self, tmp_path):
        from soak_report import (
            TRAINING_SCHEMA_VERSION,
            export_training_records,
        )

        rounds, cycles, slo = self._inputs()
        p1, p2 = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
        assert export_training_records(rounds, cycles, slo, str(p1)) == 3
        assert export_training_records(rounds, cycles, slo, str(p2)) == 3
        # byte determinism: same inputs, byte-identical output
        assert p1.read_bytes() == p2.read_bytes()

        lines = [json.loads(l) for l in p1.read_text().splitlines()]
        for line in lines:
            assert line["schema_version"] == TRAINING_SCHEMA_VERSION
            assert line["slo"]["scheduling_latency_p99"][
                "peak_burn_fast"] == 20.0
        # rounds of cycle 9 joined their timeline features; the
        # unannotated round carries the null sentinel
        assert lines[0]["timeline"]["critical_cause"] == "device_block"
        assert lines[1]["timeline"]["device_idle_fraction"] == 0.4
        assert lines[2]["timeline"] is None

    def test_gather_from_a_live_scheduler(self, kit_off, tmp_path):
        from types import SimpleNamespace

        from soak_report import (
            export_training_records,
            gather_training_inputs,
        )

        timeline.RECORDER.reset_for_tests()
        sched = _lone_scheduler(kit_off, seed=23)
        _enqueue_pods(sched, 5, seed=8)
        sched.schedule_round()
        harness = SimpleNamespace(front=None, scheduler=sched)
        rounds, cycles = gather_training_inputs(harness)
        assert rounds and cycles
        out = tmp_path / "train.jsonl"
        n = export_training_records(rounds, cycles, {}, str(out))
        assert n == len(rounds)
        lines = [json.loads(l) for l in out.read_text().splitlines()]
        # the live round joined its reconstructed cycle
        joined = [l for l in lines if l["timeline"] is not None]
        assert joined
        assert joined[-1]["round"]["cycle_seq"] == cycles[0]["cycle"]
        assert joined[-1]["timeline"]["critical_cause"] == (
            cycles[0]["critical_cause"])


# ---------------------------------------------------------------------------
# soak_report host-wait attribution verdict (ISSUE 19 satellite)
# ---------------------------------------------------------------------------

class TestHostWaitVerdict:
    """soak_report folds the /debug/timeline attribution into the soak
    verdict: per-tenant top causes, and a RED flip when the mean
    unattributed residual exceeds the 5% bar."""

    @staticmethod
    def _cycles(residual):
        return [{
            "cycle": 9, "mode": "pipelined", "wall_s": 1.0,
            "unattributed_fraction": residual,
            "segments": [
                {"start": 0.0, "end": 0.30, "cause": "json_codec",
                 "name": "encode", "tenant": "a"},
                {"start": 0.30, "end": 0.35, "cause": "bind_commit",
                 "name": "bind", "tenant": "a"},
                {"start": 0.35, "end": 0.55, "cause": "deltasync_apply",
                 "name": "sync.run", "tenant": "b"},
                {"start": 0.55, "end": 0.60, "cause": "dispatch",
                 "name": "solve", "tenant": ""},
            ],
        }]

    def test_table_ranks_causes_per_tenant(self):
        from soak_report import host_wait_attribution

        hw = host_wait_attribution(self._cycles(0.01))
        assert hw["cycles"] == 1
        # tenant a: json_codec (0.30s) ahead of bind_commit (0.05s)
        assert [c for c, _ in hw["tenants"]["a"]] == [
            "json_codec", "bind_commit"]
        assert hw["tenants"]["b"][0][0] == "deltasync_apply"
        # untenanted segments land under "-"
        assert hw["tenants"]["-"][0][0] == "dispatch"
        assert hw["unattributed_ok"]

    def test_residual_over_bar_flips_red(self):
        from soak_report import UNATTRIBUTED_RED_FRACTION, attach_host_wait

        verdict = {"green": True}
        hw = attach_host_wait(
            verdict, {"enabled": True, "cycles": self._cycles(0.20)})
        assert verdict["green"] is False
        assert str(UNATTRIBUTED_RED_FRACTION) in hw["red_reason"] or \
            "0.05" in hw["red_reason"]
        # ... and the bar itself: residual AT the bar stays green
        verdict = {"green": True}
        attach_host_wait(
            verdict, {"enabled": True, "cycles": self._cycles(0.05)})
        assert verdict["green"] is True

    def test_disarmed_recorder_or_no_cycles_never_judges(self):
        from soak_report import attach_host_wait

        # kill switch thrown: cycles exist in the body but enabled is
        # False — attach the table, do not flip
        verdict = {"green": True}
        attach_host_wait(
            verdict, {"enabled": False, "cycles": self._cycles(0.9)})
        assert verdict["green"] is True
        # armed but nothing reconstructed: nothing to judge
        verdict = {"green": True}
        hw = attach_host_wait(verdict, {"enabled": True, "cycles": []})
        assert verdict["green"] is True and hw["cycles"] == 0

    def test_live_cycle_attribution_is_accountable(self, kit_off):
        """The real pipeline keeps itself under the bar: a live
        multi-tenant cycle's reconstruction attaches green, with the
        turbo causes present in the cause vocabulary."""
        from koordinator_tpu.scheduler import services
        from soak_report import attach_host_wait

        timeline.RECORDER.reset_for_tests()
        front = _make_front(kit_off)
        for t in front.tenants():
            _enqueue_pods(t.scheduler, 6, seed=17)
        front.schedule_cycle()
        body = services.debug_timeline_body(
            front.tenants()[0].scheduler, {"cycles": 8})
        for cause in ("json_codec", "deltasync_apply", "bind_commit"):
            assert cause in body["causes"]
        verdict = {"green": True}
        hw = attach_host_wait(verdict, body)
        assert hw["cycles"] >= 1
        assert verdict["green"] is True, hw
