"""NUMA/cpuset semantics: take-by-topology, hints, topology-manager merge.

Behavior mirrors pkg/scheduler/plugins/nodenumaresource/cpu_accumulator_test.go
scenarios and frameworkext/topologymanager policy tests.
"""

import jax.numpy as jnp
import numpy as np

from koordinator_tpu.ops.numa import (
    BIND_FULL_PCPUS,
    BIND_SPREAD_BY_PCPUS,
    EXCLUSIVE_PCPU_LEVEL,
    MAX_NUMA,
    POLICY_BEST_EFFORT,
    POLICY_NONE,
    POLICY_RESTRICTED,
    POLICY_SINGLE_NUMA_NODE,
    STRATEGY_LEAST_ALLOCATED,
    STRATEGY_MOST_ALLOCATED,
    CPUTopology,
    cpuset_fit,
    cpuset_fit_batched,
    merge_hints,
    numa_hints,
    numa_score,
    preferred_mask,
    take_cpus,
)
from koordinator_tpu.scheduler.cpu_manager import CPUManager

import jax


def topo_2numa():
    # 1 socket, 2 NUMA nodes, 4 cores each, 2 threads/core = 16 cpus.
    return CPUTopology.uniform(sockets=1, numa_per_socket=2, cores_per_numa=4)


def free_all(topo):
    return jnp.zeros(topo.capacity, jnp.int32)


def taken(topo, rc, n, **kw):
    sel, ok = take_cpus(topo, rc, jnp.int32(1), jnp.int32(n), **kw)
    assert bool(ok)
    return sorted(np.flatnonzero(np.asarray(sel)).tolist())


def test_full_pcpus_takes_whole_cores():
    topo = topo_2numa()
    cpus = taken(topo, free_all(topo), 4, bind_policy=BIND_FULL_PCPUS)
    cores = np.asarray(topo.core_of)[cpus]
    # 4 cpus = exactly 2 whole cores, each core fully taken.
    assert len(set(cores)) == 2
    for c in set(cores):
        assert (cores == c).sum() == 2


def test_spread_takes_one_sibling_per_core():
    topo = topo_2numa()
    cpus = taken(topo, free_all(topo), 4, bind_policy=BIND_SPREAD_BY_PCPUS)
    cores = np.asarray(topo.core_of)[cpus]
    assert len(set(cores)) == 4  # one cpu from four different cores


def test_single_numa_preferred():
    topo = topo_2numa()
    # 8 cpus fit exactly in one NUMA node (4 cores x 2).
    cpus = taken(topo, free_all(topo), 8)
    numas = set(np.asarray(topo.numa_of)[cpus].tolist())
    assert len(numas) == 1


def test_most_allocated_packs_fullest_numa():
    topo = topo_2numa()
    rc = np.zeros(topo.capacity, np.int32)
    rc[0:2] = 1  # one core of NUMA 0 busy => NUMA0 has 6 free, NUMA1 has 8
    cpus = taken(topo, jnp.asarray(rc), 4, strategy=STRATEGY_MOST_ALLOCATED)
    assert set(np.asarray(topo.numa_of)[cpus].tolist()) == {0}


def test_least_allocated_prefers_emptiest_numa():
    topo = topo_2numa()
    rc = np.zeros(topo.capacity, np.int32)
    rc[0:2] = 1
    cpus = taken(topo, jnp.asarray(rc), 4, strategy=STRATEGY_LEAST_ALLOCATED)
    assert set(np.asarray(topo.numa_of)[cpus].tolist()) == {1}


def test_fit_and_batched_fit():
    topo = topo_2numa()
    assert bool(cpuset_fit(topo, free_all(topo), jnp.int32(1), jnp.int32(16)))
    assert not bool(cpuset_fit(topo, free_all(topo), jnp.int32(1), jnp.int32(17)))
    # Full-pcpus counts only fully-free cores.
    rc = np.zeros(topo.capacity, np.int32)
    rc[::2] = 1  # one sibling of every core busy
    assert not bool(
        cpuset_fit(topo, jnp.asarray(rc), jnp.int32(1), jnp.int32(2), full_pcpus=True)
    )

    topos = jax.tree.map(lambda a: jnp.stack([a, a]), topo)
    rcs = jnp.stack([jnp.asarray(rc), free_all(topo)])
    fits = cpuset_fit_batched(topos, rcs, jnp.ones(2, jnp.int32), jnp.int32(10))
    assert not bool(fits[0]) and bool(fits[1])


def test_hints_and_preferred_mask():
    free = jnp.zeros(MAX_NUMA, jnp.int32).at[0].set(4).at[1].set(4)
    feasible = numa_hints(free, jnp.int32(6))
    # mask {0} infeasible (4 < 6), {0,1} feasible (8 >= 6)
    assert not bool(feasible[0b01])
    assert bool(feasible[0b11])
    assert int(preferred_mask(feasible)) == 0b11
    feasible1 = numa_hints(free, jnp.int32(3))
    assert int(preferred_mask(feasible1)) == 0b01  # single node, lowest index


def test_merge_policies():
    free = jnp.zeros(MAX_NUMA, jnp.int32).at[0].set(4).at[1].set(4)
    cpu_hints = numa_hints(free, jnp.int32(6))       # needs both nodes
    dev_hints = numa_hints(free, jnp.int32(2))       # any single node
    stack = jnp.stack([cpu_hints, dev_hints])

    admit, mask = merge_hints(stack, policy=POLICY_RESTRICTED)
    assert bool(admit) and int(mask) == 0b11

    admit, mask = merge_hints(stack, policy=POLICY_SINGLE_NUMA_NODE)
    assert not bool(admit)  # no single-node mask satisfies the cpu request

    admit, _ = merge_hints(stack, policy=POLICY_NONE)
    assert bool(admit)

    # Disjoint providers: restricted rejects, best-effort still admits.
    none = jnp.zeros_like(cpu_hints)
    admit, mask = merge_hints(jnp.stack([cpu_hints, none]), policy=POLICY_RESTRICTED)
    assert not bool(admit)
    admit, mask = merge_hints(jnp.stack([cpu_hints, none]), policy=POLICY_BEST_EFFORT)
    assert bool(admit) and int(mask) == -1


def test_numa_score_strategies():
    total = jnp.full(MAX_NUMA, 8, jnp.int32)
    emptyish = jnp.zeros(MAX_NUMA, jnp.int32).at[0].set(8)
    fullish = jnp.zeros(MAX_NUMA, jnp.int32).at[0].set(2)
    s_pack_full = int(numa_score(fullish, total, jnp.int32(2), STRATEGY_MOST_ALLOCATED))
    s_pack_empty = int(numa_score(emptyish, total, jnp.int32(2), STRATEGY_MOST_ALLOCATED))
    assert s_pack_full > s_pack_empty
    s_spread_empty = int(numa_score(emptyish, total, jnp.int32(2), STRATEGY_LEAST_ALLOCATED))
    assert s_spread_empty > int(numa_score(fullish, total, jnp.int32(2), STRATEGY_LEAST_ALLOCATED))


def test_cpu_manager_allocate_release_and_exclusive():
    mgr = CPUManager()
    mgr.register_node("n0", topo_2numa())

    a = mgr.allocate("n0", "pod-a", 4, bind_policy=BIND_FULL_PCPUS,
                     exclusive_policy=EXCLUSIVE_PCPU_LEVEL)
    assert a is not None and len(a) == 4
    status = mgr.resource_status("n0", "pod-a")
    assert status["cpuset"] == ",".join(str(c) for c in a)

    # A second exclusive pod must avoid pod-a's cores.
    b = mgr.allocate("n0", "pod-b", 4, bind_policy=BIND_FULL_PCPUS,
                     exclusive_policy=EXCLUSIVE_PCPU_LEVEL)
    assert b is not None and not (set(a) & set(b))

    # Node is 16 cpus; 8 are exclusively held; a 10-cpu ask fails.
    assert mgr.allocate("n0", "pod-c", 10) is None
    mgr.release("n0", "pod-a")
    c = mgr.allocate("n0", "pod-c", 10)
    assert c is not None and len(c) == 10


def test_numa_exclusive_pod_avoids_shared_numa():
    mgr = CPUManager()
    mgr.register_node("n0", topo_2numa())
    from koordinator_tpu.ops.numa import EXCLUSIVE_NUMA_LEVEL
    a = mgr.allocate("n0", "pod-a", 2)  # lands somewhere (NUMA 0 packing)
    b = mgr.allocate("n0", "pod-b", 4, exclusive_policy=EXCLUSIVE_NUMA_LEVEL)
    topo = mgr.node("n0").topology
    numa_of = np.asarray(topo.numa_of)
    assert b is not None
    assert not set(numa_of[a].tolist()) & set(numa_of[b].tolist())


def test_reallocate_same_pod_does_not_leak_refs():
    mgr = CPUManager()
    mgr.register_node("n0", topo_2numa())
    mgr.allocate("n0", "pod-a", 2)
    mgr.allocate("n0", "pod-a", 2)   # re-allocate, must drop old refs
    mgr.release("n0", "pod-a")
    assert (mgr.node("n0").ref_count == 0).all()


def test_numa_exclusive_vs_pcpu_exclusive_pod():
    # pod-a holds cores with PCPU exclusivity; a NUMA-exclusive pod-b must
    # avoid pod-a's whole NUMA node (independent of pod-a's own policy).
    mgr = CPUManager()
    mgr.register_node("n0", topo_2numa())
    from koordinator_tpu.ops.numa import EXCLUSIVE_NUMA_LEVEL
    a = mgr.allocate("n0", "pod-a", 2, exclusive_policy=EXCLUSIVE_PCPU_LEVEL)
    b = mgr.allocate("n0", "pod-b", 4, exclusive_policy=EXCLUSIVE_NUMA_LEVEL)
    numa_of = np.asarray(mgr.node("n0").topology.numa_of)
    assert b is not None
    assert not set(numa_of[a].tolist()) & set(numa_of[b].tolist())


def test_failed_reallocate_keeps_old_cpuset():
    mgr = CPUManager()
    mgr.register_node("n0", topo_2numa())
    a = mgr.allocate("n0", "pod-a", 4)
    assert mgr.allocate("n0", "pod-a", 100) is None  # impossible ask
    st = mgr.node("n0")
    assert st.allocations["pod-a"].cpus == a          # old grant intact
    assert st.ref_count[a].sum() == 4


def test_full_pcpus_odd_request_rounds_up_to_whole_cores():
    topo = topo_2numa()
    sel, ok = take_cpus(topo, free_all(topo), jnp.int32(1), jnp.int32(3),
                        bind_policy=BIND_FULL_PCPUS)
    assert bool(ok)
    cpus = np.flatnonzero(np.asarray(sel))
    cores = np.asarray(topo.core_of)[cpus]
    assert len(cpus) == 4  # rounded up: no half-taken core
    for c in set(cores):
        assert (cores == c).sum() == 2
    # fit agrees: 15 full-core cpus don't exist once one sibling is busy
    rc = np.zeros(topo.capacity, np.int32)
    rc[0] = 1
    assert not bool(cpuset_fit(topo, jnp.asarray(rc), jnp.int32(1),
                               jnp.int32(15), full_pcpus=True))


def test_max_ref_count_sharing():
    mgr = CPUManager()
    mgr.register_node("n0", topo_2numa(), max_ref=2)
    a = mgr.allocate("n0", "pod-a", 16)
    b = mgr.allocate("n0", "pod-b", 16)
    assert a is not None and b is not None
    assert mgr.allocate("n0", "pod-c", 1) is None


def test_topology_disappearance_preserves_allocations():
    """A transient NRT-annotation loss (annotation-less node re-upsert)
    removes the topology but must NOT wipe live CPU allocations: when
    the topology re-registers, exclusive cores held by still-bound pods
    re-commit — wiping ref counts would let them be granted twice."""
    from koordinator_tpu.ops.numa import EXCLUSIVE_PCPU_LEVEL
    from koordinator_tpu.scheduler.cpu_manager import CPUManager

    topo = CPUTopology.uniform(sockets=1, numa_per_socket=2,
                               cores_per_numa=4)
    cm = CPUManager()
    cm.register_node("n0", topo)
    cpus = cm.allocate("n0", "p", 2, exclusive_policy=EXCLUSIVE_PCPU_LEVEL)
    assert cpus
    cm.remove_node("n0")
    assert cm.node("n0") is None
    cm.register_node("n0", topo)
    st = cm.node("n0")
    assert st.allocations["p"].cpus == cpus
    assert int(st.ref_count[cpus].sum()) == len(cpus)
    assert st.allocations["p"].exclusive_policy == EXCLUSIVE_PCPU_LEVEL
    # a pod deleted while the topology was absent must not resurrect
    cm.remove_node("n0")
    cm.release("n0", "p")
    cm.register_node("n0", topo)
    assert "p" not in cm.node("n0").allocations
    assert int(cm.node("n0").ref_count.sum()) == 0
