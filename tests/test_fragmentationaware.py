"""FragmentationAware kernels vs the reference's scoring_test.go scenarios
(pkg/descheduler/framework/plugins/fragmentationaware/scoring_test.go)."""

import jax.numpy as jnp
import numpy as np

from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS, ResourceDim
from koordinator_tpu.descheduler.fragmentationaware import (
    default_resource_mask,
    node_imbalance,
    removal_gains,
    select_victims,
)
from koordinator_tpu.descheduler.framework import (
    Descheduler,
    EvictorFilter,
    Evictor,
    PodInfo,
    Profile,
)
from koordinator_tpu.descheduler.plugins import FragmentationAwarePlugin

R = NUM_RESOURCE_DIMS
CPU, MEM, GPU = ResourceDim.CPU, ResourceDim.MEMORY, ResourceDim.GPU


def node(cpu, mem, gpu=0):
    a = np.zeros((1, R), np.int32)
    a[0, CPU], a[0, MEM], a[0, GPU] = cpu, mem, gpu
    return a


def req(cpu, mem, gpu=0):
    r = np.zeros((1, R), np.int32)
    r[0, CPU], r[0, MEM], r[0, GPU] = cpu, mem, gpu
    return r


def imb(requested, allocatable, mask=None):
    mask = default_resource_mask() if mask is None else mask
    return float(node_imbalance(
        jnp.asarray(requested), jnp.asarray(allocatable), mask)[0])


def test_no_scored_resources_returns_zero():
    # scoring_test.go "no scored resources returns zero"
    mask = jnp.zeros(R, bool)
    assert imb(req(500, 512), node(1000, 1024), mask) == 0.0


def test_balanced_node_low_stddev():
    # "balanced CPU/memory node gives low stddev": 500/1000 vs 512/1024
    assert imb(req(500, 512), node(1000, 1024)) < 0.01


def test_cpu_heavy_node_high_stddev():
    # "CPU-heavy node gives high stddev": 900/1000 vs 100/1024
    assert imb(req(900, 100), node(1000, 1024)) > 0.1


def test_zero_allocatable_dim_skipped():
    # "zero allocatable resource is skipped": only CPU counts, 1-elem var = 0
    assert imb(req(500, 512), node(1000, 0)) == 0.0


def test_custom_resource_exact():
    # "custom resource works if configured": GPU 1/2, CPU 0/1000
    # mean 0.25, population std = 0.25
    mask = jnp.zeros(R, bool).at[CPU].set(True).at[GPU].set(True)
    assert abs(imb(req(0, 0, gpu=1), node(1000, 1024, gpu=2), mask) - 0.25) < 1e-6


def test_removal_gain_positive_for_imbalanced_pod():
    # TestScorePodRemovalGain "removing CPU-heavy pod improves stddev"
    alloc = node(1000, 1024)
    requested = req(900, 200)  # cpu-heavy(800,100) + balanced(100,100)
    pod_requests = np.concatenate([req(800, 100), req(100, 100)])
    gains = np.asarray(removal_gains(
        jnp.asarray(requested), jnp.asarray(alloc),
        jnp.asarray([0, 0], np.int32), jnp.asarray(pod_requests),
        default_resource_mask()))
    assert gains[0] > 0


def test_removal_gain_negative_for_balancing_pod():
    # "removing wrong pod gives low/negative gain": podA(200,800)+podB(600,100)
    alloc = node(1000, 1024)
    requested = req(800, 900)
    pod_requests = np.concatenate([req(200, 800), req(600, 100)])
    gains = np.asarray(removal_gains(
        jnp.asarray(requested), jnp.asarray(alloc),
        jnp.asarray([0, 0], np.int32), jnp.asarray(pod_requests),
        default_resource_mask()))
    assert gains[1] < 0


def test_unbound_pod_gain_zero():
    gains = np.asarray(removal_gains(
        jnp.asarray(req(500, 500)), jnp.asarray(node(1000, 1000)),
        jnp.asarray([-1], np.int32), jnp.asarray(req(100, 100)),
        default_resource_mask()))
    assert gains[0] == 0.0


def test_select_victims_greedy_updates_node_state():
    # Node skewed by two cpu-heavy pods; after evicting one the node is
    # balanced enough that the second is NOT taken.
    alloc = node(1000, 1000)
    requested = req(900, 300)
    pod_requests = np.concatenate([req(350, 50), req(350, 50), req(200, 200)])
    victims = np.asarray(select_victims(
        jnp.asarray(requested), jnp.asarray(alloc),
        jnp.ones(1, bool), jnp.asarray([0, 0, 0], np.int32),
        jnp.asarray(pod_requests), jnp.ones(3, bool),
        default_resource_mask(),
        imbalance_threshold=0.2, min_gain=0.05))
    # first cpu-heavy pod taken (imbalance 0.3 -> ~0.1); after that the
    # node imbalance falls below the 0.2 threshold so nothing else goes
    assert victims.tolist() == [True, False, False]


def test_select_victims_respects_evictable_and_cap():
    alloc = node(1000, 1000)
    requested = req(950, 100)
    pod_requests = np.concatenate([req(450, 50), req(450, 50)])
    victims = np.asarray(select_victims(
        jnp.asarray(requested), jnp.asarray(alloc),
        jnp.ones(1, bool), jnp.asarray([0, 0], np.int32),
        jnp.asarray(pod_requests), jnp.asarray([False, True]),
        default_resource_mask(), max_victims=1))
    assert victims.tolist() == [False, True]


def test_plugin_end_to_end():
    names = ["n0", "n1"]
    allocatable = np.concatenate([node(1000, 1000), node(1000, 1000)])
    requested = np.concatenate([req(900, 100), req(400, 400)])
    pods = [
        PodInfo(uid="skew", name="skew", namespace="d", node="n0"),
        PodInfo(uid="ok", name="ok", namespace="d", node="n1"),
    ]
    reqs = {"skew": req(800, 50)[0], "ok": req(100, 100)[0]}
    plugin = FragmentationAwarePlugin(
        state_fn=lambda: (requested, allocatable, np.ones(2, bool), names),
        pod_requests_fn=lambda p: reqs[p.uid],
    )
    profile = Profile(name="frag", balance_plugins=[plugin],
                      evictor_filter=EvictorFilter(), evictor=Evictor())
    d = Descheduler([profile], pods_fn=lambda: pods, interval_seconds=0)
    out = d.run_once()
    assert out["frag"] == 1
    assert profile.evictor.evicted == [("skew", "FragmentationAware")]
