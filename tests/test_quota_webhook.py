"""ElasticQuota-CR admission webhook (manager/quota_webhook.py).

Scenario coverage mirrors the reference's quota_topology_test.go: add with
min>max / negative values, parent missing / not-a-parent, sibling min sums
vs parent min, key-set consistency, tree-id consistency, forbidden
modifications (root/system, tree id change, isParent flips), delete with
children or bound pods, and default filling (parent -> root, tree id
inherited, shared weight <- max)."""

import pytest

from koordinator_tpu.api.crds import ElasticQuota
from koordinator_tpu.manager.quota_webhook import (
    DEFAULT_QUOTA,
    ROOT_QUOTA,
    SYSTEM_QUOTA,
    QuotaTopologyValidator,
)


def eq(name, parent=ROOT_QUOTA, min=None, max=None, is_parent=False,
       tree_id="", **kw):
    return ElasticQuota(
        name=name, parent=parent, min=min or {}, max=max or {},
        is_parent=is_parent, tree_id=tree_id, **kw)


def admitted(v, quota, **kw):
    errs = v.validate_add(quota, **kw)
    assert errs == [], errs


class TestSelfItem:
    def test_min_greater_than_max_rejected(self):
        v = QuotaTopologyValidator()
        errs = v.validate_add(eq("a", min={"cpu": 10}, max={"cpu": 5}))
        assert any("min 10 > max 5" in e for e in errs)

    def test_min_key_not_in_max_rejected(self):
        v = QuotaTopologyValidator()
        errs = v.validate_add(eq("a", min={"cpu": 1}, max={"memory": 5}))
        assert any("in min but not in max" in e for e in errs)

    def test_negative_values_rejected(self):
        v = QuotaTopologyValidator()
        errs = v.validate_add(eq("a", min={"cpu": -1}, max={"cpu": -2}))
        assert len([e for e in errs if "< 0" in e]) == 2

    def test_max_below_used_rejected_on_update(self):
        v = QuotaTopologyValidator()
        admitted(v, eq("a", min={"cpu": 1}, max={"cpu": 10}))
        v.set_used("a", {"cpu": 8})
        errs = v.validate_update(eq("a", min={"cpu": 1}, max={"cpu": 5}))
        assert any("max 5 < used 8" in e for e in errs)


class TestTopology:
    def test_parent_must_exist(self):
        v = QuotaTopologyValidator()
        errs = v.validate_add(
            eq("child", parent="nope", max={"cpu": 1}))
        assert any("does not exist" in e for e in errs)

    def test_parent_must_be_parent_quota(self):
        v = QuotaTopologyValidator()
        admitted(v, eq("leafy", max={"cpu": 10}))  # is_parent=False
        errs = v.validate_add(eq("child", parent="leafy", max={"cpu": 1}))
        assert any("isParent is false" in e for e in errs)

    def test_sibling_min_sum_capped_by_parent_min(self):
        v = QuotaTopologyValidator()
        admitted(v, eq("p", is_parent=True,
                       min={"cpu": 10}, max={"cpu": 20}))
        admitted(v, eq("c1", parent="p", min={"cpu": 6}, max={"cpu": 20}))
        errs = v.validate_add(
            eq("c2", parent="p", min={"cpu": 6}, max={"cpu": 20}))
        assert any("siblings' min > parent min" in e for e in errs)
        # a fitting sibling is admitted
        admitted(v, eq("c3", parent="p", min={"cpu": 4}, max={"cpu": 20}))

    def test_children_min_sum_caps_parent_shrink(self):
        v = QuotaTopologyValidator()
        admitted(v, eq("p", is_parent=True,
                       min={"cpu": 10}, max={"cpu": 20}))
        admitted(v, eq("c1", parent="p", min={"cpu": 8}, max={"cpu": 20}))
        errs = v.validate_update(
            eq("p", is_parent=True, min={"cpu": 4}, max={"cpu": 20}))
        assert any("children's min > quota min" in e for e in errs)

    def test_max_keys_must_match_parent(self):
        v = QuotaTopologyValidator()
        admitted(v, eq("p", is_parent=True,
                       min={"cpu": 5}, max={"cpu": 10, "memory": 10}))
        errs = v.validate_add(eq("c", parent="p", max={"cpu": 5}))
        assert any("max keys are not the same" in e for e in errs)
        # with the update-resource-key gate, included keys are enough
        v2 = QuotaTopologyValidator(enable_update_resource_key=True)
        admitted(v2, eq("p", is_parent=True,
                        min={"cpu": 5}, max={"cpu": 10, "memory": 10}))
        admitted(v2, eq("c", parent="p", max={"cpu": 5}))

    def test_tree_id_must_match_parent(self):
        v = QuotaTopologyValidator()
        admitted(v, eq("p", is_parent=True, max={"cpu": 10},
                       tree_id="t1"))
        errs = v.validate_add(
            eq("c", parent="p", max={"cpu": 10}, tree_id="t2"))
        assert any("tree id differs from parent" in e for e in errs)

    def test_leaf_under_root_skips_structural_checks(self):
        v = QuotaTopologyValidator()
        admitted(v, eq("solo", min={"cpu": 1}, max={"cpu": 2}))


class TestForbiddenUpdates:
    def test_system_and_root_immutable(self):
        v = QuotaTopologyValidator()
        assert v.validate_update(eq(SYSTEM_QUOTA, max={"cpu": 1}))
        assert v.validate_update(eq(ROOT_QUOTA))

    def test_tree_id_change_rejected(self):
        v = QuotaTopologyValidator()
        admitted(v, eq("a", max={"cpu": 1}, tree_id="t1"))
        errs = v.validate_update(eq("a", max={"cpu": 1}, tree_id="t2"))
        assert any("tree id changed" in e for e in errs)

    def test_is_parent_false_with_children_rejected(self):
        v = QuotaTopologyValidator()
        admitted(v, eq("p", is_parent=True, max={"cpu": 10}))
        admitted(v, eq("c", parent="p", max={"cpu": 10}))
        errs = v.validate_update(eq("p", is_parent=False, max={"cpu": 10}))
        assert any("isParent cannot become false" in e for e in errs)

    def test_is_parent_true_with_pods_rejected(self):
        v = QuotaTopologyValidator(has_pods_fn=lambda name: name == "a")
        admitted(v, eq("a", max={"cpu": 10}))
        errs = v.validate_update(eq("a", is_parent=True, max={"cpu": 10}))
        assert any("isParent cannot become true" in e for e in errs)

    def test_noop_update_admitted(self):
        v = QuotaTopologyValidator()
        q = eq("a", max={"cpu": 1})
        admitted(v, q)
        assert v.validate_update(q) == []


class TestDelete:
    def test_reserved_names_not_deletable(self):
        v = QuotaTopologyValidator()
        for name in (ROOT_QUOTA, SYSTEM_QUOTA, DEFAULT_QUOTA):
            assert v.validate_delete(name)

    def test_delete_with_children_rejected(self):
        v = QuotaTopologyValidator()
        admitted(v, eq("p", is_parent=True, max={"cpu": 10}))
        admitted(v, eq("c", parent="p", max={"cpu": 10}))
        errs = v.validate_delete("p")
        assert any("child quotas" in e for e in errs)
        assert v.validate_delete("c") == []
        assert v.validate_delete("p") == []  # children gone now

    def test_delete_with_pods_rejected(self):
        pods = {"a"}
        v = QuotaTopologyValidator(has_pods_fn=lambda n: n in pods)
        admitted(v, eq("a", max={"cpu": 10}))
        errs = v.validate_delete("a")
        assert any("bound pods" in e for e in errs)
        pods.clear()
        assert v.validate_delete("a") == []


class TestNamespaceBinding:
    def test_namespace_conflict_rejected(self):
        v = QuotaTopologyValidator()
        admitted(v, eq("a", max={"cpu": 1}), namespaces=["team-a"])
        errs = v.validate_add(
            eq("b", max={"cpu": 1}), namespaces=["team-a"])
        assert any("already bound to quota a" in e for e in errs)
        # the owning quota may keep its own namespace on update
        assert v.validate_update(
            eq("a", max={"cpu": 2}), namespaces=["team-a"]) == []


class TestFillDefaults:
    def test_fills_parent_shared_weight_and_tree_id(self):
        v = QuotaTopologyValidator()
        admitted(v, eq("p", is_parent=True, max={"cpu": 10},
                       tree_id="t9"))
        raw = ElasticQuota(name="c", parent="p", max={"cpu": 5})
        filled = v.fill_defaults(raw)
        assert filled.tree_id == "t9"
        assert dict(filled.shared_weight) == {"cpu": 5}
        orphan = ElasticQuota(name="x", parent="ghost", max={})
        with pytest.raises(ValueError, match="parent not exist"):
            v.fill_defaults(orphan)

    def test_empty_parent_defaults_to_root(self):
        v = QuotaTopologyValidator()
        filled = v.fill_defaults(ElasticQuota(name="c", parent="",
                                              max={"cpu": 5}))
        assert filled.parent == ROOT_QUOTA


class TestGuarantee:
    def test_min_below_guaranteed_used_rejected(self):
        # a leaf directly under root skips structural checks (reference
        # quota_topology_check.go:107), so guarantee only binds on nested
        # quotas
        v = QuotaTopologyValidator(guarantee_usage=True)
        admitted(v, eq("p", is_parent=True,
                       min={"cpu": 20}, max={"cpu": 40}))
        admitted(v, eq("a", parent="p", min={"cpu": 10}, max={"cpu": 40},
                       guarantee_usage=True))
        v.set_used("a", {"cpu": 8})
        errs = v.validate_update(
            eq("a", parent="p", min={"cpu": 5}, max={"cpu": 40},
               guarantee_usage=True))
        assert any("guaranteed used" in e for e in errs)
        # shrinking while staying above used is fine
        assert v.validate_update(
            eq("a", parent="p", min={"cpu": 9}, max={"cpu": 40},
               guarantee_usage=True)) == []
