"""Forced candidate-selection methods (ops/batch_assign.select_candidates).

The TPU-serving branches — the approx_max_k float-key path and the
chunked reductions — are force-selectable via ``method=`` so CPU CI
executes them (VERDICT r2 item 3: no code path may run only when a human
watches a TPU tunnel).  Invariants asserted here:

- "approx": candidate recall vs the exact path >= 0.9 on seeded problems
  (on CPU the recall loss comes only from the 24-bit float-key
  quantization; on TPU approx_max_k adds its ~0.95 recall target), and the
  downstream acceptance stays EXACT — no node over capacity, no quota
  overshoot — because fit/quota checks never depend on the method;
- "chunked"/"chunked_exact": bit-exact with "approx"/"exact" respectively
  (chunking is an execution-schedule change only);
- "auto" resolves to "exact" on CPU; unknown methods raise.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from koordinator_tpu.ops.assignment import ScoringConfig
from koordinator_tpu.ops.batch_assign import (
    CANDIDATE_METHODS,
    batch_assign,
    select_candidates,
)
from tests.problem_helpers import build_problem as _build
from tests.problem_helpers import candidate_recall


def build_problem(n_nodes=256, n_pods=128, seed=0, factored=True):
    state, pods = _build(n_nodes=n_nodes, n_pods=n_pods, seed=seed,
                         classes=4, factored=factored)
    return state, pods, ScoringConfig.default()


def test_approx_method_recall_and_exact_acceptance():
    state, pods, cfg = build_problem(seed=1)
    ek, en = select_candidates(state, pods, cfg, k=16, method="exact")
    ak, an = select_candidates(state, pods, cfg, k=16, method="approx")
    rec = candidate_recall(np.asarray(en), np.asarray(ek), np.asarray(an))
    assert rec >= 0.9, f"approx candidate recall {rec:.3f} < 0.9"
    # gathered keys must be the exact int keys for the chosen nodes
    ek_map = {(p, int(n)): int(v)
              for p in range(en.shape[0])
              for n, v in zip(np.asarray(en)[p], np.asarray(ek)[p])}
    got = np.asarray(ak)
    for p in range(an.shape[0]):
        for n, v in zip(np.asarray(an)[p], got[p]):
            if (p, int(n)) in ek_map and v >= 0:
                assert v == ek_map[(p, int(n))]

    # acceptance is exact regardless of candidate method: replay the
    # assignment and check no node exceeds allocatable
    a, st, _ = batch_assign(state, pods, cfg, k=16, method="approx")
    a = np.asarray(a)
    req = np.asarray(pods.requests)
    used = np.asarray(state.node_requested).copy()
    for p in np.nonzero(a >= 0)[0]:
        used[a[p]] += req[p]
    assert (used <= np.asarray(state.node_allocatable)).all(), \
        "approx method let a node exceed capacity"
    np.testing.assert_array_equal(used, np.asarray(st.node_requested))


def test_auto_resolves_exact_on_cpu():
    state, pods, cfg = build_problem(n_nodes=64, n_pods=32, seed=4)
    ek, en = select_candidates(state, pods, cfg, k=8, method="exact")
    au_k, au_n = select_candidates(state, pods, cfg, k=8, method="auto")
    assert jax.default_backend() != "tpu"
    np.testing.assert_array_equal(np.asarray(ek), np.asarray(au_k))
    np.testing.assert_array_equal(np.asarray(en), np.asarray(au_n))


def test_unknown_method_raises():
    state, pods, cfg = build_problem(n_nodes=64, n_pods=32, seed=5)
    with pytest.raises(ValueError, match="unknown candidate method"):
        select_candidates(state, pods, cfg, method="fancy")
    assert "exact" in CANDIDATE_METHODS


class TestStratifiedCandidates:
    """spread_bits=(5, 15): score-faithful + coverage strata (the round-3
    fix for candidate exhaustion at the north-star shape)."""

    def test_split_math(self):
        from koordinator_tpu.ops.batch_assign import _stratum_splits

        assert _stratum_splits(32, 2) == [16, 16]
        assert _stratum_splits(15, 2) == [8, 7]
        assert _stratum_splits(8, 1) == [8]

    def test_stratified_exact_candidate_structure(self):
        state, pods, cfg = build_problem(n_nodes=128, n_pods=32, seed=7)
        ck, cn = select_candidates(
            state, pods, cfg, k=16, spread_bits=(5, 15), method="exact")
        assert cn.shape == (pods.capacity, 16)
        # first half = top-8 of the sb=5 key; second half = top-8 of the
        # pure-rotation key; ALL keys reported on the sb=5 scale
        k5, n5 = select_candidates(
            state, pods, cfg, k=8, spread_bits=5, method="exact")
        np.testing.assert_array_equal(np.asarray(cn)[:, :8],
                                      np.asarray(n5))
        np.testing.assert_array_equal(np.asarray(ck)[:, :8],
                                      np.asarray(k5))
        _, n15 = select_candidates(
            state, pods, cfg, k=8, spread_bits=15, method="exact")
        np.testing.assert_array_equal(np.asarray(cn)[:, 8:],
                                      np.asarray(n15))

    def test_coverage_stratum_rescues_exhausted_tail(self):
        # the north-star stranding phenomenon at CI scale (3,072 nodes x
        # 15k pods reproduces it in ~10s): diverse scores make the sb=5
        # tie groups narrow, the whole queue's candidate sets concentrate
        # on the top score band, and once it fills the tail's candidates
        # are all full even though the cluster has 3.6x headroom.  The
        # coverage stratum must assign the ENTIRE schedulable queue; the
        # single-key run must visibly strand (the test discriminates).
        from __graft_entry__ import _build_problem

        n_nodes, n_pods = 3_072, 15_000
        state, pods, cfg = _build_problem(n_nodes, n_pods, seed=42)
        a_strat, _, _ = jax.jit(
            lambda s: batch_assign(s, pods, cfg, k=16, method="approx"))(
            state)[:3]
        n_strat = int((np.asarray(a_strat) >= 0).sum())
        assert n_strat == n_pods, f"stratified stranded {n_pods - n_strat}"
        a_sb5, _, _ = jax.jit(
            lambda s: batch_assign(s, pods, cfg, k=16, spread_bits=5,
                                   method="approx"))(state)[:3]
        n_sb5 = int((np.asarray(a_sb5) >= 0).sum())
        assert n_sb5 < n_pods, "single-key run no longer strands; " \
            "update this scenario so the coverage property stays tested"


class TestChunkedCandidates:
    """method="chunked": the approx reduction over pod chunks via lax.map.
    Chunking is an execution-schedule change ONLY — scoring, global-offset
    rotation, and the per-row reduction are row-independent, so every row
    must be bit-identical to method="approx"."""

    @pytest.mark.parametrize("n_pods,chunk_note", [
        (100, "single partial chunk (P < chunk)"),
        (5000, "multiple chunks + padded tail"),
    ])
    def test_bit_identical_to_approx(self, n_pods, chunk_note):
        state, pods, cfg = build_problem(n_nodes=512, n_pods=n_pods, seed=3)
        run = jax.jit(select_candidates, static_argnames=("k", "method"))
        ck_a, cn_a = run(state, pods, cfg, k=16, method="approx")
        ck_c, cn_c = run(state, pods, cfg, k=16, method="chunked")
        assert np.array_equal(np.asarray(ck_a), np.asarray(ck_c)), chunk_note
        assert np.array_equal(np.asarray(cn_a), np.asarray(cn_c)), chunk_note

    def test_end_to_end_assignments_match(self):
        state, pods, cfg = build_problem(n_nodes=512, n_pods=5000, seed=4)
        run = jax.jit(batch_assign, static_argnames=("k", "rounds", "method"))
        a_approx, st_a, _ = run(state, pods, cfg, k=16, rounds=6,
                                method="approx")
        a_chunked, st_c, _ = run(state, pods, cfg, k=16, rounds=6,
                                 method="chunked")
        assert np.array_equal(np.asarray(a_approx), np.asarray(a_chunked))
        assert np.array_equal(np.asarray(st_a.node_requested),
                              np.asarray(st_c.node_requested))

    @pytest.mark.parametrize("n_pods,chunk_note", [
        (100, "single partial chunk (P < chunk)"),
        (5000, "multiple chunks + padded tail"),
    ])
    def test_chunked_exact_bit_identical_to_exact(self, n_pods, chunk_note):
        """method="chunked_exact": the TPU fallback when measured
        approx_max_k recall strands pods (bench_recall.py decision rule)
        — exact top_k rows at chunked peak memory.  Every row must be
        bit-identical to method="exact"."""
        state, pods, cfg = build_problem(n_nodes=512, n_pods=n_pods, seed=3)
        run = jax.jit(select_candidates, static_argnames=("k", "method"))
        ck_e, cn_e = run(state, pods, cfg, k=16, method="exact")
        ck_c, cn_c = run(state, pods, cfg, k=16, method="chunked_exact")
        assert np.array_equal(np.asarray(ck_e), np.asarray(ck_c)), chunk_note
        assert np.array_equal(np.asarray(cn_e), np.asarray(cn_c)), chunk_note

    def test_chunked_exact_end_to_end_assignments_match_exact(self):
        state, pods, cfg = build_problem(n_nodes=512, n_pods=5000, seed=4)
        run = jax.jit(batch_assign, static_argnames=("k", "rounds", "method"))
        a_e, st_e, _ = run(state, pods, cfg, k=16, rounds=6, method="exact")
        a_c, st_c, _ = run(state, pods, cfg, k=16, rounds=6,
                           method="chunked_exact")
        assert np.array_equal(np.asarray(a_e), np.asarray(a_c))
        assert np.array_equal(np.asarray(st_e.node_requested),
                              np.asarray(st_c.node_requested))

    def test_dense_feasible_batch_supported(self):
        # dense (P, N) masks chunk over the pod axis like everything else
        state, pods, cfg = build_problem(n_nodes=256, n_pods=300, seed=5,
                                         factored=False)
        run = jax.jit(select_candidates, static_argnames=("k", "method"))
        ck_a, cn_a = run(state, pods, cfg, k=8, method="approx")
        ck_c, cn_c = run(state, pods, cfg, k=8, method="chunked")
        assert np.array_equal(np.asarray(ck_a), np.asarray(ck_c))
        assert np.array_equal(np.asarray(cn_a), np.asarray(cn_c))


def test_gang_batch_solver_method_passthrough():
    """gang_assign(solver="batch", method=...) reaches the candidate
    stage: chunked and approx passes produce identical gang outcomes."""
    from koordinator_tpu.ops.gang import GangInfo, gang_assign

    state, pods, cfg = build_problem(n_nodes=256, n_pods=600, seed=6)
    gang_id = np.full(pods.capacity, -1, np.int32)
    gang_id[:32] = 0
    gpods = pods.replace(gang_id=jnp.asarray(gang_id))
    gangs = GangInfo.build(np.array([16], np.int32))
    run = jax.jit(gang_assign,
                  static_argnames=("passes", "solver", "method"))
    a_approx, _, _ = run(state, gpods, cfg, gangs, passes=2,
                         solver="batch", method="approx")
    a_chunked, _, _ = run(state, gpods, cfg, gangs, passes=2,
                          solver="batch", method="chunked")
    assert np.array_equal(np.asarray(a_approx), np.asarray(a_chunked))
    assert int((np.asarray(a_chunked) >= 0).sum()) > 0
