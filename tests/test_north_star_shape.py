"""Solve quality AT THE NORTH-STAR SHAPE, in CI.

Round 2's spread_bits=5 fix held at a 2k-pod validation shape and
silently stranded 14% of pods at the real 50k x 10,240 shape; round 3's
stratified candidate selection fixed it but the at-shape check lived in
a manual scratch script.  This test pins the real shape in CI (slow-
marked: `pytest -m slow`) so that class of regression can never ship
silently again (VERDICT r3 item 9).

The approx float-key candidate path is FORCED — the TPU-serving branch;
on CPU `approx_max_k`'s lowering is exact, so this isolates the
stratified-selection + float-key quantization behavior from TPU recall.
"""

import numpy as np
import pytest

from __graft_entry__ import _build_problem

pytestmark = pytest.mark.slow

NORTH_STAR_NODES = 10_240
NORTH_STAR_PODS = 50_000


@pytest.fixture(scope="module")
def problem():
    # seed 42 = the scratch_quality.py shape the round-2 regression hit
    return _build_problem(NORTH_STAR_NODES, NORTH_STAR_PODS, seed=42)


@pytest.mark.parametrize("k", [8, 16, 32])
def test_stratified_candidates_assign_everything_at_shape(problem, k):
    import jax

    from koordinator_tpu.ops.batch_assign import batch_assign

    state, pods, cfg = problem
    valid = int(np.asarray(pods.valid).sum())
    assert valid == NORTH_STAR_PODS

    # pods traced, not closed over: closure capture would embed them as
    # HLO constants and constant-fold pod-dependent work at compile time
    asn, st = jax.jit(
        lambda s, p: batch_assign(s, p, cfg, k=k, method="approx")[:2]
    )(state, pods)
    asn = np.asarray(asn)

    assigned = int((asn >= 0).sum())
    # capacity must hold exactly...
    assert (np.asarray(st.node_requested)
            <= np.asarray(st.node_allocatable)).all()
    # ...and the stratified default must place every valid pod (the
    # round-2 bug left this at 0.86)
    assert assigned == valid, (
        f"k={k}: stranded {valid - assigned}/{valid} pods at the "
        f"north-star shape")


def _solve_waves(state, pods, cfg, max_waves: int):
    """Iterate batch_assign the way the scheduler's round loop does:
    unassigned pods retry against the updated state (fresh candidates).
    Returns (per-wave assigned counts, final state, assigned mask)."""
    import jax
    import jax.numpy as jnp

    from koordinator_tpu.ops.batch_assign import batch_assign

    solve = jax.jit(
        lambda s, p: batch_assign(s, p, cfg, k=16, method="approx")[:2])
    remaining, st = pods, state
    assigned_total = np.zeros(pods.capacity, bool)
    counts = []
    for _ in range(max_waves):
        asn, st = solve(st, remaining)
        wave = (np.asarray(asn) >= 0) & np.asarray(remaining.valid)
        counts.append(int(wave.sum()))
        assigned_total |= wave
        stranded = ~assigned_total & np.asarray(pods.valid)
        if not stranded.any() or counts[-1] == 0:
            break
        remaining = remaining.replace(valid=jnp.asarray(stranded))
    return counts, st, assigned_total


def test_moderate_load_converges_in_waves(problem):
    """At ~2x capacity surplus, a single solve strands ~3% of pods whose
    k=16 candidate windows all filled (candidates are chosen BEFORE the
    rounds).  The system-level behavior — the scheduler's round loop
    retries unassigned pods with fresh candidates — must converge to
    full placement within 3 waves (measured: 48,520 -> 1,470 -> 10 -> 0
    at this exact shape).  A candidate-coverage regression shows up as
    non-convergence."""
    state, pods, cfg = problem
    moderate = state.replace(
        node_allocatable=(state.node_allocatable * 11) // 20)
    counts, st, assigned = _solve_waves(moderate, pods, cfg, max_waves=3)
    assert (np.asarray(st.node_requested)
            <= np.asarray(st.node_allocatable)).all()
    assert int(assigned.sum()) == NORTH_STAR_PODS, (
        f"waves {counts}: {NORTH_STAR_PODS - int(assigned.sum())} pods "
        f"never placed despite available capacity")
    # the first wave alone must carry the overwhelming bulk — the retry
    # loop is a straggler mechanism, not a crutch.  95%: measured 97.0%
    # (48,520) at this seed; the margin absorbs tie-break perturbations
    # across jax/XLA versions without admitting a real coverage
    # regression (the round-2 bug was at 86%)
    assert counts[0] >= 0.95 * NORTH_STAR_PODS, counts


def test_contended_queue_respects_capacity_and_priority(problem):
    """TRUE contention (capacity < demand, ~15% of the original
    allocatable): after the retry waves settle, (a) capacity holds
    exactly, (b) no stranded pod has a feasible node left by the
    solver's own fit rule (no missed opportunity at the fixed point),
    and (c) assigned pods skew clearly above stranded ones in priority
    (the in-round rule is priority wins conflicts, not a strict global
    cut, so the assertion is distributional)."""
    import jax

    from koordinator_tpu.ops.assignment import score_pods

    state, pods, cfg = problem
    contended = state.replace(
        node_allocatable=(state.node_allocatable * 3) // 20)
    counts, st, assigned = _solve_waves(contended, pods, cfg, max_waves=4)
    alloc = np.asarray(st.node_allocatable)
    used = np.asarray(st.node_requested)
    valid = np.asarray(pods.valid)

    # (a) capacity holds exactly on every dim of every node
    assert (used <= alloc).all()
    n_assigned = int(assigned.sum())
    assert 0 < n_assigned < NORTH_STAR_PODS, counts   # genuinely short

    # (b) no missed opportunity once the waves settle
    has_feasible = np.asarray(jax.jit(
        lambda s, p: score_pods(s, p, cfg)[1].any(axis=1))(st, pods))
    missed = ~assigned & valid & has_feasible
    assert int(missed.sum()) == 0, (
        f"{int(missed.sum())} stranded pods still had a feasible node "
        f"after waves {counts}")

    # (c) priority skew: assigned pods outrank stranded ones clearly
    prio = np.asarray(pods.priority)
    mean_assigned = prio[assigned & valid].mean()
    mean_stranded = prio[~assigned & valid].mean()
    assert mean_assigned - mean_stranded > 500, (
        f"assigned {mean_assigned:.0f} vs stranded {mean_stranded:.0f}")


def test_double_shape_headroom():
    """2x the north star (100k pods x 20,480 nodes) on the chunked
    path: full assignment, exact capacity — the shape ceiling is not
    near the target (measured 97s wall on CPU, compile-dominated)."""
    import jax

    from koordinator_tpu.ops.batch_assign import batch_assign

    state, pods, cfg = _build_problem(20_480, 100_000, seed=7)
    asn, st = jax.jit(
        lambda s, p: batch_assign(s, p, cfg, k=16, method="chunked")[:2]
    )(state, pods)
    asn = np.asarray(asn)
    valid = int(np.asarray(pods.valid).sum())
    assert int((asn >= 0).sum()) == valid
    assert (np.asarray(st.node_requested)
            <= np.asarray(st.node_allocatable)).all()


def test_chunked_exact_assigns_everything_at_shape(problem):
    """The recall-exact TPU fallback (method="chunked_exact" — exact
    top_k rows at chunked peak memory) must hold the same
    100%-assignment bar as the default at the real shape: it is what
    method="auto"'s TPU arm flips to if bench_recall.py measures
    approx_max_k stranding pods."""
    import jax

    from koordinator_tpu.ops.batch_assign import batch_assign

    state, pods, cfg = problem
    valid = int(np.asarray(pods.valid).sum())
    asn, st = jax.jit(
        lambda s, p: batch_assign(s, p, cfg, k=16,
                                  method="chunked_exact")[:2]
    )(state, pods)
    asn = np.asarray(asn)
    assert (np.asarray(st.node_requested)
            <= np.asarray(st.node_allocatable)).all()
    assert int((asn >= 0).sum()) == valid
