"""Solve quality AT THE NORTH-STAR SHAPE, in CI.

Round 2's spread_bits=5 fix held at a 2k-pod validation shape and
silently stranded 14% of pods at the real 50k x 10,240 shape; round 3's
stratified candidate selection fixed it but the at-shape check lived in
a manual scratch script.  This test pins the real shape in CI (slow-
marked: `pytest -m slow`) so that class of regression can never ship
silently again (VERDICT r3 item 9).

The approx float-key candidate path is FORCED — the TPU-serving branch;
on CPU `approx_max_k`'s lowering is exact, so this isolates the
stratified-selection + float-key quantization behavior from TPU recall.
"""

import numpy as np
import pytest

from __graft_entry__ import _build_problem

pytestmark = pytest.mark.slow

NORTH_STAR_NODES = 10_240
NORTH_STAR_PODS = 50_000


@pytest.fixture(scope="module")
def problem():
    # seed 42 = the scratch_quality.py shape the round-2 regression hit
    return _build_problem(NORTH_STAR_NODES, NORTH_STAR_PODS, seed=42)


@pytest.mark.parametrize("k", [16, 32])
def test_stratified_candidates_assign_everything_at_shape(problem, k):
    import jax

    from koordinator_tpu.ops.batch_assign import batch_assign

    state, pods, cfg = problem
    valid = int(np.asarray(pods.valid).sum())
    assert valid == NORTH_STAR_PODS

    # pods traced, not closed over: closure capture would embed them as
    # HLO constants and constant-fold pod-dependent work at compile time
    asn, st = jax.jit(
        lambda s, p: batch_assign(s, p, cfg, k=k, method="approx")[:2]
    )(state, pods)
    asn = np.asarray(asn)

    assigned = int((asn >= 0).sum())
    # capacity must hold exactly...
    assert (np.asarray(st.node_requested)
            <= np.asarray(st.node_allocatable)).all()
    # ...and the stratified default must place every valid pod (the
    # round-2 bug left this at 0.86)
    assert assigned == valid, (
        f"k={k}: stranded {valid - assigned}/{valid} pods at the "
        f"north-star shape")
