"""Randomized invariants of device allocation (allocate_on_node).

test_deviceshare.py pins the reference scenarios (device_allocator.go)
at hand-built inventories; this sweeps random device pools across both
strategies and shared/whole requests:

  (legal)    selected devices are valid AND healthy
  (count)    whole requests take exactly n_whole devices, all fully
             free with enough total capacity; shared requests take one
             device with enough free core+memory
  (fit)      allocate_on_node succeeds exactly when device_fit says the
             node fits (the Filter and the allocator agree)
  (ledger)   commit then release round-trips the free tensor exactly
"""

import jax.numpy as jnp
import numpy as np
import pytest

from tests.conftest import prop_seeds

from koordinator_tpu.ops.deviceshare import (
    DEV_BINPACK,
    DEV_CORE,
    DEV_MEM,
    DEV_SPREAD,
    DeviceState,
    allocate_on_node,
    commit_allocation,
    device_fit,
    release_allocation,
    split_request,
)


def _random_pool(rng: np.random.Generator):
    n_nodes = int(rng.integers(1, 4))
    per_node = []
    for _ in range(n_nodes):
        n_dev = int(rng.integers(1, 6))
        per_node.append([
            # core varies (partitioned 50-core devices exist) so the
            # per-device core-capacity leg of device_fit is NON-vacuous:
            # a whole/100-core ask must reject 50-core devices
            {"core": int(rng.choice([50, 100])),
             "memory": int(rng.integers(4, 33) * 1024),
             "group": int(rng.integers(0, 2)),
             "healthy": bool(rng.random() > 0.15)}
            for _ in range(n_dev)])
    dev = DeviceState.build(per_node)
    # randomly pre-allocate some share of some devices
    free = np.asarray(dev.free).copy()
    valid = np.asarray(dev.valid)
    for (n, d) in zip(*np.nonzero(valid)):
        if rng.random() < 0.4:
            frac = rng.choice([0.25, 0.5, 1.0])
            free[n, d, DEV_CORE] = int(free[n, d, DEV_CORE] * (1 - frac))
            free[n, d, DEV_MEM] = int(free[n, d, DEV_MEM] * (1 - frac))
    return dev.replace(free=jnp.asarray(free)), n_nodes


@pytest.mark.parametrize("seed", prop_seeds(24))
@pytest.mark.parametrize("strategy", [DEV_BINPACK, DEV_SPREAD])
def test_allocate_on_node_invariants(seed, strategy):
    rng = np.random.default_rng(seed)
    dev, n_nodes = _random_pool(rng)

    core = int(rng.integers(1, 5)) * 50       # 50..200: shared or whole
    memory = int(rng.integers(0, 16)) * 1024
    n_whole, per_core, per_mem = split_request(core, memory)

    fit = np.asarray(device_fit(
        dev, jnp.int32(n_whole), jnp.int32(per_core), jnp.int32(per_mem)))

    for node in range(n_nodes):
        sel, ok = allocate_on_node(
            dev, jnp.int32(node), jnp.int32(n_whole),
            jnp.int32(per_core), jnp.int32(per_mem), strategy=strategy)
        sel, ok = np.asarray(sel), bool(ok)

        # (fit) allocator and Filter agree
        assert ok == bool(fit[node]), (
            f"seed {seed} node {node}: allocate ok={ok} but "
            f"device_fit={bool(fit[node])}")
        if not ok:
            assert sel.sum() == 0
            continue

        usable = np.asarray(dev.valid)[node] & np.asarray(dev.healthy)[node]
        free = np.asarray(dev.free)[node]
        total = np.asarray(dev.total)[node]
        # (legal)
        assert not (sel & ~usable).any(), (
            f"seed {seed}: unusable device selected")
        if n_whole > 0:
            # (count) whole: exactly n fully-free, capable devices
            assert sel.sum() == n_whole
            assert (free[sel] == total[sel]).all(), "non-free whole device"
            assert (total[sel, DEV_CORE] >= per_core).all()
            assert (total[sel, DEV_MEM] >= per_mem).all()
        else:
            assert sel.sum() == 1
            assert free[sel, DEV_CORE][0] >= per_core
            assert free[sel, DEV_MEM][0] >= per_mem

        # (ledger) commit + release round-trips exactly
        committed = commit_allocation(
            dev, jnp.int32(node), jnp.asarray(sel),
            jnp.int32(per_core), jnp.int32(per_mem))
        released = release_allocation(
            committed, jnp.int32(node), jnp.asarray(sel),
            jnp.int32(per_core), jnp.int32(per_mem))
        assert (np.asarray(released.free) == np.asarray(dev.free)).all()
        # committed free never negative
        assert (np.asarray(committed.free) >= 0).all()
