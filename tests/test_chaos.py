"""Chaos soak: scheduler sidecar + manager + koordlet-style feeder over
real unix sockets under a SEEDED fault schedule (transport/faults.py —
connection severs, mid-write truncation, push drop/delay/duplication/
reordering, slow-drip reads, connect refusals), asserting the three
acceptance invariants:

1. **No overcommit, ever** — an oracle re-checks every acceptance at
   bind time: the host-side sum of bound pods on the node (including
   the new one) must fit the node's allocatable on every dimension.
2. **Reconvergence after heal** — once the injector heals, every pod
   (prod AND BE/batch-dim) reaches a binding within bounded rounds, the
   manager's watch view catches back up to the service rv, and the
   scheduler leaves degraded mode.
3. **No thread/fd growth** — reconnect storms must not accumulate
   reader/sender threads or leak sockets (satellite: RpcClient.close
   joins its reader).

Marked ``chaos`` AND ``slow``: tier-1's ``-m "not slow"`` keeps it out
of CI; run it with ``pytest -m chaos`` or sweep seed windows with
``SOAK_CHAOS=1 tools/soak.sh`` (the failing seed base is printed for
exact replay via ``KOORD_CHAOS_SEED_BASE``).
"""

import os
import threading
import time

import numpy as np
import pytest

from koordinator_tpu.api.qos import QoSClass
from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS, resource_vector
from koordinator_tpu.cmd.binaries import ReconnectingSidecarClient
from koordinator_tpu.manager.colocation_loop import (
    ColocationLoop,
    ManagerSyncBinding,
)
from koordinator_tpu.manager.noderesource_controller import (
    NodeResourceController,
)
from koordinator_tpu.ops.assignment import ScoringConfig
from koordinator_tpu.scheduler import ClusterSnapshot, Scheduler
from koordinator_tpu.transport import (
    FaultConfig,
    FaultInjector,
    RpcError,
    RpcRemoteError,
    RpcServer,
    StateSyncClient,
    StateSyncService,
)
from koordinator_tpu.transport.deltasync import SchedulerBinding
from koordinator_tpu.transport.retry import RetryPolicy
from koordinator_tpu.transport.services import SolveService
from koordinator_tpu.transport.wire import FrameType

pytestmark = [pytest.mark.chaos, pytest.mark.slow]

R = NUM_RESOURCE_DIMS
NODES = 4
PROD_PODS = 8
BE_PODS = 4


def chaos_seeds():
    """Seed window, env-steerable exactly like conftest.prop_seeds — the
    soak harness sweeps fresh windows and prints the base on failure."""
    base = int(os.environ.get("KOORD_CHAOS_SEED_BASE", "0"))
    count = int(os.environ.get("KOORD_CHAOS_SEED_COUNT", "0") or 0) or 5
    return list(range(base, base + count))


#: fast-probing retry policy so a ~15s soak sees many breaker cycles
FAST_RETRY = RetryPolicy(initial_backoff_s=0.02, max_backoff_s=0.3,
                         multiplier=2.0, jitter="equal")

CHAOS = FaultConfig(
    connect_refuse_p=0.10,
    send_sever_p=0.01,
    send_truncate_p=0.005,
    push_drop_p=0.05,
    push_delay_p=0.05,
    push_delay_ms=5.0,
    push_duplicate_p=0.05,
    push_reorder_p=0.05,
    read_drip_p=0.02,
    read_drip_ms=2.0,
)


def _counts():
    return threading.active_count(), len(os.listdir("/proc/self/fd"))


def wait_until(pred, timeout, what):
    deadline = time.monotonic() + timeout
    while not pred() and time.monotonic() < deadline:
        time.sleep(0.02)
    assert pred(), f"timed out waiting for {what}"


class Oracle:
    """Re-checks every acceptance the moment it is made (bind_fn runs
    under the round lock, so the host sums and the snapshot agree)."""

    def __init__(self):
        self.sched = None
        self.violations = []
        self.accepted = 0

    def __call__(self, pod_name, node_name):
        self.accepted += 1
        sched = self.sched
        spec = sched.snapshot.node_specs.get(node_name)
        if spec is None:
            self.violations.append(f"{pod_name} bound to unknown node "
                                   f"{node_name}")
            return
        total = np.zeros(R, np.int64)
        for bp in sched.bound.values():
            if bp.node == node_name:
                total += bp.requests.astype(np.int64)
        alloc = spec.allocatable.astype(np.int64)
        if not np.all(total <= alloc):
            self.violations.append(
                f"overcommit on {node_name} accepting {pod_name}: "
                f"bound={total.tolist()} allocatable={alloc.tolist()}")


def node_usage_arrays():
    return {
        "usage": np.asarray(resource_vector(cpu=2_000, memory=4_096),
                            np.int32),
        "sys_usage": np.asarray(resource_vector(cpu=500, memory=512),
                                np.int32),
        "hp_usage": np.asarray(resource_vector(cpu=3_000, memory=2_048),
                               np.int32),
        "hp_request": np.asarray(resource_vector(cpu=3_000, memory=2_048),
                                 np.int32),
        "hp_max_used_req": np.asarray(
            resource_vector(cpu=3_000, memory=2_048), np.int32),
    }


@pytest.mark.parametrize("seed", chaos_seeds())
def test_chaos_soak(seed, tmp_path):
    inj = FaultInjector(seed=seed, config=CHAOS)
    inj.enabled = False                      # clean warmup first
    sock = str(tmp_path / f"chaos-{seed}.sock")

    # -- sidecar: server + sync service + in-process scheduler binding
    import jax.numpy as jnp  # deferred per the marker-audit convention

    oracle = Oracle()
    cfg = ScoringConfig.default().replace(
        usage_thresholds=jnp.zeros(R, jnp.int32),
        estimator_defaults=jnp.zeros(R, jnp.int32))
    sched = Scheduler(ClusterSnapshot(capacity=16), config=cfg,
                      bind_fn=oracle, staleness_threshold_sec=2.0)
    oracle.sched = sched
    server = RpcServer(sock, faults=inj)
    service = StateSyncService(retention=64)
    service.attach(server)
    service.attach_binding(SchedulerBinding(sched))
    solve_service = SolveService(sched)
    solve_service.attach(server)
    server.start()

    # -- koordlet-style feeder (node heartbeats) + workload pusher
    feeder = ReconnectingSidecarClient(sock, retry_policy=FAST_RETRY,
                                       faults=inj, timeout=3.0)

    # -- manager: watch view + colocation loop pushing batch allocatable
    binding = ManagerSyncBinding()
    sync = StateSyncClient(binding)

    def bootstrap_watch(client):
        sync.bind_client(client)
        sync.bootstrap(client)

    mgr_client = ReconnectingSidecarClient(
        sock, on_push=sync.on_push, on_connect=bootstrap_watch,
        retry_policy=FAST_RETRY, faults=inj, timeout=3.0)

    def push_allocatable(name, allocatable):
        mgr_client.call(FrameType.STATE_PUSH,
                        {"kind": "node_allocatable", "name": name},
                        {"allocatable": np.asarray(allocatable, np.int32)})

    loop = ColocationLoop(NodeResourceController(), binding,
                          push_allocatable, ensure_fn=mgr_client.ensure)

    # -- solver driver: long transport timeout, per-call deadline_ms
    # bounds the steady-state waits (and lets the warmup ride out jit
    # compilation)
    solver = ReconnectingSidecarClient(sock, retry_policy=FAST_RETRY,
                                       faults=inj, timeout=120.0)

    #: warm-0 schedules during the (fault-free) warmup so the solve is
    #: compiled and the solver connection live before the baseline
    #: thread/fd counts are taken; everything else arrives UNDER chaos
    pods = (
        [("warm-0", resource_vector(cpu=1_000, memory=1_024), 0, 1000)]
        + [(f"prod-{i}", resource_vector(cpu=1_000, memory=1_024), 0, 1000)
           for i in range(PROD_PODS)]
        + [(f"be-{i}", resource_vector(batch_cpu=500, batch_memory=256),
            int(QoSClass.BE), 0)
           for i in range(BE_PODS)]
    )
    pushed_pods: set[str] = set()

    def push_pending_pods(client):
        for name, req, qos, prio in pods:
            if name in pushed_pods:
                continue
            try:
                client.call(FrameType.STATE_PUSH,
                            {"kind": "pod_add", "name": name,
                             "qos": qos, "priority": prio},
                            {"requests": np.asarray(req, np.int32)})
                pushed_pods.add(name)
            except (RpcError, RpcRemoteError, OSError):
                return                       # retry the rest next cycle

    def one_cycle():
        """One control-plane beat with every error swallowed the way the
        real binaries swallow them (count-and-retry-next-tick)."""
        for n in range(NODES):
            try:
                feeder.call(FrameType.STATE_PUSH,
                            {"kind": "node_usage", "name": f"n{n}",
                             "usage_time": time.time()},
                            node_usage_arrays())
            except (RpcError, RpcRemoteError, OSError):
                pass
        push_pending_pods(feeder)
        loop.tick()
        try:
            solver.call(FrameType.SOLVE_REQUEST, {}, deadline_ms=3_000)
        except (RpcError, RpcRemoteError, OSError):
            pass
        assert not oracle.violations, oracle.violations[:3]

    try:
        # ---- warmup (no faults): register nodes, compile the solve,
        # establish every steady-state connection BEFORE the baseline
        for n in range(NODES):
            feeder.call(FrameType.STATE_PUSH,
                        {"kind": "node_upsert", "name": f"n{n}"},
                        {"allocatable": np.asarray(
                            resource_vector(cpu=16_000, memory=16_384),
                            np.int32)})
        feeder.call(FrameType.STATE_PUSH,
                    {"kind": "pod_add", "name": "warm-0", "priority": 1000},
                    {"requests": np.asarray(
                        resource_vector(cpu=1_000, memory=1_024),
                        np.int32)})
        pushed_pods.add("warm-0")
        loop.tick()
        # generous deadline: the first solve pays jit compilation, and a
        # client-side timeout here would close the solver connection and
        # skew the thread/fd baseline
        solver.call(FrameType.SOLVE_REQUEST, {}, deadline_ms=120_000)
        with sched.lock:
            assert sched.bound, "warmup pod never scheduled"
        wait_until(lambda: sync.rv >= 0, 5, "manager bootstrap")
        base_threads, base_fds = _counts()

        # ---- chaos phase
        inj.enabled = True
        t_end = time.monotonic() + 8.0
        while time.monotonic() < t_end:
            one_cycle()
            time.sleep(0.01)
        assert sum(inj.injected.values()) > 0, (
            "the fault schedule never fired — the soak proved nothing")

        # ---- heal: the system must reconverge to the full fixpoint
        inj.heal()
        deadline = time.monotonic() + 30.0
        want = {name for name, *_ in pods}
        while time.monotonic() < deadline:
            one_cycle()
            with sched.lock:
                done = (set(sched.bound) == want and not sched.degraded)
            if done and sync.rv == service.rv:
                break
            time.sleep(0.02)
        with sched.lock:
            assert set(sched.bound) == want, (
                f"no-fault fixpoint not reached: "
                f"missing={sorted(want - set(sched.bound))} "
                f"pending={sorted(sched.pending)} "
                f"degraded={sched.degraded}")
            assert not sched.degraded
        assert sync.rv == service.rv, "manager watch never caught up"
        assert not oracle.violations, oracle.violations[:3]
        assert oracle.accepted >= len(pods)

        # ---- no thread/fd growth vs the warmed-up baseline
        def settled():
            t, f = _counts()
            return t <= base_threads and f <= base_fds + 2

        wait_until(settled, 10,
                   f"thread/fd settle (base={base_threads}t/{base_fds}fd, "
                   f"now={_counts()})")
    finally:
        feeder.close()
        mgr_client.close()
        solver.close()
        server.stop()
