"""Wire protocol + delta sync + RPC services (koordinator_tpu/transport/)
vs the reference's deployment seams: apiserver watch streams (LIST+WATCH,
410-Gone resync), the hook gRPC protocol (api.proto:148), and the sidecar
solve bridge (SURVEY.md §7 step 4)."""

import threading
import time

import numpy as np
import pytest

from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS, resource_vector
from koordinator_tpu.ops.assignment import ScoringConfig
from koordinator_tpu.scheduler import ClusterSnapshot, Scheduler
from koordinator_tpu.transport import (
    RpcClient,
    RpcError,
    RpcServer,
    StateSyncClient,
    StateSyncService,
)
from koordinator_tpu.transport.deltasync import DeltaLog, ResyncRequired, SchedulerBinding
from koordinator_tpu.transport.services import (
    HookService,
    SolveService,
    hook_remote,
    solve_remote,
)
from koordinator_tpu.transport.wire import (
    FrameType,
    decode_payload,
    encode_payload,
)

R = NUM_RESOURCE_DIMS


def test_payload_roundtrip_with_arrays():
    doc = {"kind": "x", "names": ["a", "b"]}
    arrays = {
        "alloc": np.arange(2 * R, dtype=np.int32).reshape(2, R),
        "mask": np.asarray([True, False]),
        "scalar": np.int64(7).reshape(()),
    }
    out_doc, out_arrays = decode_payload(encode_payload(doc, arrays))
    assert out_doc == doc
    assert np.array_equal(out_arrays["alloc"], arrays["alloc"])
    assert out_arrays["alloc"].dtype == np.int32
    assert np.array_equal(out_arrays["mask"], arrays["mask"])
    assert out_arrays["scalar"].reshape(()).item() == 7


def test_delta_log_window_and_resync():
    log = DeltaLog(retention=3)
    for rv in range(1, 6):
        log.append(rv, {"kind": "e", "n": rv}, {})
    assert [e["n"] for _, e, _ in log.since(3)] == [4, 5]
    assert log.since(5) == []
    with pytest.raises(ResyncRequired):
        log.since(0)   # window starts at rv 3


@pytest.fixture
def rpc(tmp_path):
    server = RpcServer(str(tmp_path / "koord.sock"))
    clients = []
    try:
        yield server, clients
    finally:
        for c in clients:
            c.close()
        server.stop()


def connect(server, clients, **kw):
    client = RpcClient(server.path, **kw)
    client.connect()
    clients.append(client)
    return client


def test_rpc_call_and_error(rpc):
    server, clients = rpc

    def echo(doc, arrays):
        if doc.get("boom"):
            raise ValueError("kaput")
        out = {"arr": arrays["arr"] * 2} if "arr" in arrays else None
        return {"echo": doc["msg"]}, out

    server.register(FrameType.SOLVE_REQUEST, echo)
    server.start()
    client = connect(server, clients)
    ftype, doc, arrays = client.call(
        FrameType.SOLVE_REQUEST, {"msg": "hi"},
        {"arr": np.asarray([1, 2], np.int32)})
    assert ftype is FrameType.SOLVE_RESPONSE
    assert doc == {"echo": "hi"}
    assert arrays["arr"].tolist() == [2, 4]
    with pytest.raises(RpcError, match="kaput"):
        client.call(FrameType.SOLVE_REQUEST, {"msg": "x", "boom": True})
    # the connection survives handler errors
    _, doc, _ = client.call(FrameType.SOLVE_REQUEST, {"msg": "still up"})
    assert doc == {"echo": "still up"}


def mk_scheduler():
    snap = ClusterSnapshot(capacity=16)
    cfg = ScoringConfig.default().replace(
        usage_thresholds=np.zeros(R, np.int32),
        estimator_defaults=np.zeros(R, np.int32))
    return Scheduler(snap, config=cfg)


def wait_until(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while not pred() and time.monotonic() < deadline:
        time.sleep(0.005)
    assert pred(), "condition not reached in time"


def test_sync_snapshot_deltas_and_solve_end_to_end(rpc):
    server, clients = rpc
    service = StateSyncService()
    service.attach(server)
    # pre-existing state before any solver connects
    service.upsert_node("n1", resource_vector(cpu=16_000, memory=65_536))
    service.add_pod("p1", resource_vector(cpu=1_000, memory=1_024))

    sched = mk_scheduler()
    SolveService(sched).attach(server)
    server.start()

    sync = StateSyncClient(SchedulerBinding(sched))
    client = connect(server, clients, on_push=sync.on_push)
    applied = sync.bootstrap(client)
    assert applied == 2 and sync.rv == service.rv

    result = solve_remote(client)
    assert result["assignments"] == {"p1": "n1"}

    # live watch: push a node and a pod, solver applies without polling
    service.upsert_node("n2", resource_vector(cpu=16_000, memory=65_536))
    service.add_pod("p2", resource_vector(cpu=1_000, memory=1_024),
                    node_selector={})
    wait_until(lambda: sync.rv == service.rv)
    result = solve_remote(client)
    assert "p2" in result["assignments"]

    # pod deletion flows too
    service.add_pod("p3", resource_vector(cpu=99_000, memory=1))
    wait_until(lambda: sync.rv == service.rv)
    result = solve_remote(client)
    assert "p3" in result["failures"]
    service.remove_pod("p3")
    wait_until(lambda: sync.rv == service.rv)
    assert "p3" not in sched.pending


def test_delta_burst_within_retention_survives_the_wire(rpc):
    """A push burst the delta log could replay WITHOUT a full resync
    must not poison the connection first: r5's deltasync bench caught a
    1,024-event NodeMetric burst overflowing the old 256-deep per-conn
    send queue at event 256 (the tight producer loop starves the sender
    thread of GIL slices), silently killing the watch.  SEND_QUEUE_DEPTH
    is now sized to the DeltaLog retention window."""
    server, clients = rpc
    service = StateSyncService()
    service.attach(server)
    server.start()
    service.upsert_node("n1", resource_vector(cpu=16_000, memory=65_536))

    sched = mk_scheduler()
    sync = StateSyncClient(SchedulerBinding(sched))
    client = connect(server, clients, on_push=sync.on_push)
    sync.bootstrap(client)

    n_burst = 1_024
    for i in range(n_burst):
        service.update_node_usage(
            "n1", resource_vector(cpu=100 + i, memory=1_024))
    wait_until(lambda: sync.rv == service.rv, timeout=30.0)
    assert client.connected, "burst poisoned the connection"
    assert sync.applied >= n_burst
    spec = sched.snapshot.node_specs["n1"]
    assert spec.usage[0] == 100 + n_burst - 1   # last update won


def test_sync_reconnect_resumes_from_rv(rpc):
    server, clients = rpc
    service = StateSyncService()
    service.attach(server)
    server.start()
    service.upsert_node("n1", resource_vector(cpu=16_000, memory=65_536))

    sched = mk_scheduler()
    sync = StateSyncClient(SchedulerBinding(sched))
    client = connect(server, clients, on_push=sync.on_push)
    sync.bootstrap(client)
    rv_before = sync.rv

    client.close()   # solver restarts its connection
    # events land while disconnected
    service.add_pod("p1", resource_vector(cpu=1_000, memory=1_024))
    service.upsert_node("n2", resource_vector(cpu=16_000, memory=65_536))

    client2 = connect(server, clients, on_push=sync.on_push)
    applied = sync.bootstrap(client2)
    assert applied == 2                    # only the missed deltas replayed
    assert sync.rv == service.rv > rv_before
    assert "p1" in sched.pending
    assert "n2" in sched.snapshot.node_index


def test_hello_detects_service_restart_despite_rv_collision(tmp_path):
    """A restarted service resets its rv counter; if the new counter
    happens to EQUAL the client's last_rv, an rv-only HELLO would return
    a bare ACK and the client would keep a permanently stale view.  The
    instance (boot-epoch) id in the handshake forces the full snapshot
    across incarnations regardless of rv."""
    sock = str(tmp_path / "epoch.sock")

    def boot(node_name):
        server = RpcServer(sock)
        service = StateSyncService()
        service.attach(server)
        server.start()
        service.upsert_node(node_name,
                            resource_vector(cpu=8_000, memory=8_192))
        return server, service

    server1, service1 = boot("n-old")
    sched = mk_scheduler()
    sync = StateSyncClient(SchedulerBinding(sched))
    client = RpcClient(sock, on_push=sync.on_push)
    client.connect()
    sync.bootstrap(client)
    assert sync.rv == service1.rv == 1
    assert sync.instance == service1.instance
    client.close()
    server1.stop()

    # fresh incarnation, DIFFERENT state, same rv counter value
    server2, service2 = boot("n-new")
    assert service2.rv == 1 and service2.instance != service1.instance
    client2 = RpcClient(sock, on_push=sync.on_push)
    client2.connect()
    applied = sync.bootstrap(client2)
    assert applied == 1, "rv collision returned ACK instead of snapshot"
    assert sync.instance == service2.instance
    assert sorted(sched.snapshot.node_index) == ["n-new"]
    # same incarnation, same rv: NOW the ACK shortcut is correct
    assert sync.bootstrap(client2) == 0
    client2.close()
    server2.stop()


def test_sync_falls_back_to_snapshot_beyond_retention(rpc):
    server, clients = rpc
    service = StateSyncService(retention=2)
    service.attach(server)
    server.start()
    service.upsert_node("n1", resource_vector(cpu=16_000, memory=65_536))

    sched = mk_scheduler()
    sync = StateSyncClient(SchedulerBinding(sched))
    client = connect(server, clients, on_push=sync.on_push)
    sync.bootstrap(client)
    client.close()

    for i in range(5):   # blow past the 2-event retention window
        service.upsert_node(f"m{i}", resource_vector(cpu=8_000, memory=8_192))
    service.remove_node("n1")

    client2 = connect(server, clients, on_push=sync.on_push)
    sync.bootstrap(client2)
    assert sync.rv == service.rv
    assert "n1" not in sched.snapshot.node_index     # full resync state
    assert all(f"m{i}" in sched.snapshot.node_index for i in range(5))


def test_sync_replay_overlap_is_idempotent(rpc):
    server, clients = rpc
    service = StateSyncService()
    service.attach(server)
    server.start()
    service.add_pod("p1", resource_vector(cpu=1_000, memory=1_024))

    sched = mk_scheduler()
    sync = StateSyncClient(SchedulerBinding(sched))
    client = connect(server, clients, on_push=sync.on_push)
    sync.bootstrap(client)
    rev = sched._pending_rev
    # a duplicated HELLO (e.g. overlap between push and replay) re-sends
    # everything; the rv guard must drop it without touching the queue
    from koordinator_tpu.transport.wire import PROTOCOL_VERSION

    ftype, doc, arrays = client.call(
        FrameType.HELLO, {"last_rv": 0, "proto": PROTOCOL_VERSION})
    assert ftype is FrameType.DELTA
    sync._apply(doc, arrays)
    assert sync.skipped >= 1
    assert sched._pending_rev == rev      # no spurious cache invalidation


def test_hook_rpc_roundtrip_and_fail_open(rpc):
    from koordinator_tpu.runtimeproxy import (
        Dispatcher, HookRequest, HookResponse, HookType)

    server, clients = rpc
    dispatcher = Dispatcher()

    class BvtServer:
        def handle(self, hook, request):
            return HookResponse(
                annotations={"koordinator.sh/bvt": "2"},
                envs={"SEEN": request.pod_meta.get("uid", "")})

    dispatcher.register(BvtServer(), [HookType.PRE_RUN_POD_SANDBOX])
    HookService(dispatcher).attach(server)
    server.start()
    client = connect(server, clients)

    out = hook_remote(client, HookType.PRE_RUN_POD_SANDBOX,
                      HookRequest(pod_meta={"uid": "u1"}))
    assert out["annotations"]["koordinator.sh/bvt"] == "2"
    assert out["envs"]["SEEN"] == "u1"

    client.close()
    assert hook_remote(client, HookType.PRE_RUN_POD_SANDBOX,
                       HookRequest()) is None      # fail-open
    with pytest.raises(RpcError):
        hook_remote(client, HookType.PRE_RUN_POD_SANDBOX,
                    HookRequest(), fail_open=False)


def test_service_restart_with_lower_rv_forces_snapshot(rpc):
    # the service restarts (rv counter resets); a client whose rv is AHEAD
    # must get a snapshot, not an empty delta that strands it skipping
    # every future event
    server, clients = rpc
    service = StateSyncService()
    service.attach(server)
    server.start()
    service.upsert_node("n1", resource_vector(cpu=16_000, memory=65_536))

    sched = mk_scheduler()
    sync = StateSyncClient(SchedulerBinding(sched))
    client = connect(server, clients, on_push=sync.on_push)
    sync.bootstrap(client)
    sync.rv = 100    # simulate: previous service instance had rv 100
    applied = sync.bootstrap(client)
    assert applied == 1                  # snapshot re-applied
    assert sync.rv == service.rv == 1    # rv dropped to the new authority
    # and future events apply instead of being skipped
    service.add_pod("p1", resource_vector(cpu=1_000, memory=1_024))
    wait_until(lambda: "p1" in sched.pending)


def test_concurrent_mutations_solves_and_pushes():
    # the race-stress version of the sidecar wiring: one thread mutates the
    # informer state while another runs solve RPCs; the scheduler lock and
    # rv ordering must keep every pod accounted exactly once
    import tempfile, os

    d = tempfile.mkdtemp()
    server = RpcServer(os.path.join(d, "s.sock"))
    service = StateSyncService()
    service.attach(server)
    sched = mk_scheduler()
    SolveService(sched).attach(server)
    server.start()
    sync = StateSyncClient(SchedulerBinding(sched))
    client = RpcClient(server.path, on_push=sync.on_push, timeout=60)
    client.connect()
    try:
        service.upsert_node("n1", resource_vector(cpu=100_000, memory=65_536))
        sync.bootstrap(client)

        N = 30
        def mutate():
            for i in range(N):
                service.add_pod(f"p{i}",
                                resource_vector(cpu=100, memory=16))

        th = threading.Thread(target=mutate)
        th.start()
        assigned = {}
        for _ in range(50):
            result = solve_remote(client)
            assigned.update(result["assignments"])
            if len(assigned) == N and not th.is_alive():
                break
            time.sleep(0.01)
        th.join()
        wait_until(lambda: sync.rv == service.rv)
        result = solve_remote(client)
        assigned.update(result["assignments"])
        assert len(assigned) == N        # every pod placed exactly once
        assert not sched.pending
    finally:
        client.close()
        server.stop()


def test_bound_pod_delete_releases_reservation_and_quota(rpc):
    from koordinator_tpu.quota.tree import QuotaTree, UNBOUNDED

    server, clients = rpc
    service = StateSyncService()
    service.attach(server)
    server.start()

    snap = ClusterSnapshot(capacity=16)
    tree = QuotaTree(
        total_resource=resource_vector(cpu=16_000, memory=65_536).astype("int64"))
    tree.add("team", min=resource_vector(cpu=1_000).astype("int64"),
             max=np.full(R, UNBOUNDED, "int64"))
    cfg = ScoringConfig.default().replace(
        usage_thresholds=np.zeros(R, np.int32),
        estimator_defaults=np.zeros(R, np.int32))
    sched = Scheduler(snap, config=cfg, quota_tree=tree)
    SolveService(sched).attach(server)

    sync = StateSyncClient(SchedulerBinding(sched))
    client = connect(server, clients, on_push=sync.on_push)
    sync.bootstrap(client)

    service.upsert_node("n1", resource_vector(cpu=16_000, memory=65_536))
    service.add_pod("p1", resource_vector(cpu=16_000, memory=1_024),
                    quota="team")
    wait_until(lambda: sync.rv == service.rv)
    result = solve_remote(client)
    assert result["assignments"] == {"p1": "n1"}
    assert tree.nodes["team"].used[0] == 16_000

    # p1 completes: the informer delete must free the node AND the quota
    service.remove_pod("p1")
    wait_until(lambda: tree.nodes["team"].used[0] == 0)
    assert "p1" not in sched.bound
    service.add_pod("p2", resource_vector(cpu=16_000, memory=1_024),
                    quota="team")
    wait_until(lambda: sync.rv == service.rv)
    result = solve_remote(client)
    assert result["assignments"] == {"p2": "n1"}   # capacity was released


def test_snapshot_resync_releases_bound_state(rpc):
    server, clients = rpc
    service = StateSyncService(retention=1)
    service.attach(server)
    server.start()

    sched = mk_scheduler()
    binds = []
    sched.bind_fn = lambda p, n: binds.append(p)
    SolveService(sched).attach(server)
    sync = StateSyncClient(SchedulerBinding(sched))
    client = connect(server, clients, on_push=sync.on_push)

    service.upsert_node("n1", resource_vector(cpu=16_000, memory=65_536))
    service.add_pod("p1", resource_vector(cpu=16_000, memory=1_024))
    sync.bootstrap(client)
    solve_remote(client)
    assert "p1" in sched.bound

    client.close()
    for i in range(4):   # push far past the 1-event retention window
        service.upsert_node(f"m{i}", resource_vector(cpu=8_000, memory=8_192))

    client2 = connect(server, clients, on_push=sync.on_push)
    sync.bootstrap(client2)     # snapshot resync: restart semantics
    assert not sched.bound      # bound state released with its reservation
    result = solve_remote(client2)
    assert result["assignments"] == {"p1": "n1"}   # re-placed cleanly


def test_reservation_sync_over_the_wire(rpc):
    """Reservation CRs ride the delta protocol: upsert places a reservation
    (hidden capacity), an owner pod draws from it, removal frees it."""
    server, clients = rpc
    service = StateSyncService()
    service.attach(server)
    server.start()

    sched = mk_scheduler()
    SolveService(sched).attach(server)
    sync = StateSyncClient(SchedulerBinding(sched))
    client = connect(server, clients, on_push=sync.on_push)
    sync.bootstrap(client)

    service.upsert_node("n1", resource_vector(cpu=10_000, memory=65_536))
    service.upsert_reservation(
        "rsv-a", resource_vector(cpu=8_000, memory=8_192).astype("int64"),
        owners=[{"labels": {"app": "web"}}])
    wait_until(lambda: sync.rv == service.rv)
    solve_remote(client)                   # round: reserve-pod places
    assert sched.reservations.get("rsv-a").node == "n1"

    # reserved capacity hidden from non-owners pushed over the wire
    service.add_pod("other", resource_vector(cpu=4_000, memory=1_024))
    wait_until(lambda: sync.rv == service.rv)
    result = solve_remote(client)
    assert "other" in result["failures"]

    # ...but an owner pod (labels ride POD_ADD) draws from it
    service.add_pod("web-1", resource_vector(cpu=6_000, memory=1_024),
                    labels={"app": "web"})
    wait_until(lambda: sync.rv == service.rv)
    result = solve_remote(client)
    assert result["assignments"].get("web-1") == "n1"
    assert sched.reservations.get("rsv-a").allocated[0] == 6_000
    service.remove_pod("web-1")
    wait_until(lambda: "web-1" not in sched.bound)

    # removal over the wire frees the capacity
    service.remove_reservation("rsv-a")
    wait_until(lambda: sync.rv == service.rv)
    result = solve_remote(client)
    assert result["assignments"].get("other") == "n1"


def test_reservation_in_snapshot_resync(rpc):
    # a fresh client bootstraps reservations from the snapshot too
    server, clients = rpc
    service = StateSyncService()
    service.attach(server)
    service.upsert_node("n1", resource_vector(cpu=10_000, memory=65_536))
    service.upsert_reservation(
        "rsv-a", resource_vector(cpu=6_000, memory=4_096).astype("int64"),
        owners=[{"labels": {"app": "web"}}])
    server.start()

    sched = mk_scheduler()
    SolveService(sched).attach(server)
    sync = StateSyncClient(SchedulerBinding(sched))
    client = connect(server, clients, on_push=sync.on_push)
    sync.bootstrap(client)
    solve_remote(client)
    assert sched.reservations.get("rsv-a").node == "n1"


def test_fine_grained_registries_ride_node_sync(rpc):
    """NRT annotations + Device inventory on NODE_UPSERT register the
    client scheduler's CPU/device managers, so wire-synced LSR and GPU
    pods get real fine-grained allocations (the deployment path)."""
    from koordinator_tpu.api.qos import QoSClass
    from koordinator_tpu.koordlet.nodetopo import NodeTopology, NUMAZone
    from koordinator_tpu.koordlet.system import procfs
    from koordinator_tpu.scheduler.cpu_manager import CPUManager
    from koordinator_tpu.scheduler.device_manager import DeviceManager

    server, clients = rpc
    service = StateSyncService()
    service.attach(server)
    server.start()

    snap = ClusterSnapshot(capacity=16)
    cfg = ScoringConfig.default().replace(
        usage_thresholds=np.zeros(R, np.int32),
        estimator_defaults=np.zeros(R, np.int32))
    sched = Scheduler(snap, config=cfg, cpu_manager=CPUManager(),
                      device_manager=DeviceManager())
    SolveService(sched).attach(server)
    sync = StateSyncClient(SchedulerBinding(sched))
    client = connect(server, clients, on_push=sync.on_push)
    sync.bootstrap(client)

    cpus = tuple(procfs.CPUInfo(cpu=i, core=i // 2, socket=0, node=i // 4)
                 for i in range(8))
    topo = NodeTopology(
        zones=(NUMAZone("node0", 4_000, 1 << 30, (0, 1, 2, 3)),
               NUMAZone("node1", 4_000, 1 << 30, (4, 5, 6, 7))),
        cpu_topology=cpus)
    service.upsert_node(
        "n1",
        resource_vector({"cpu": 16_000, "memory": 65_536,
                         "kubernetes.io/gpu": 400,
                         "kubernetes.io/gpu-memory": 81_920 * 4}),
        annotations=topo.to_annotations(),
        devices={"gpu": [{"core": 100, "memory": 81_920, "group": 0}
                         for _ in range(4)]})
    wait_until(lambda: sync.rv == service.rv)

    service.add_pod("lsr-1", resource_vector({"cpu": 2_000, "memory": 512}),
                    priority=9_000, qos=int(QoSClass.LSR))
    wait_until(lambda: sync.rv == service.rv)
    result = solve_remote(client)
    assert result["assignments"]["lsr-1"] == "n1"
    assert len(sched.resource_status["lsr-1"]["resource-status"]
               ["cpuset"].split(",")) == 2

    service.add_pod("gpu-1", resource_vector(
        {"cpu": 1_000, "memory": 512, "kubernetes.io/gpu": 100,
         "kubernetes.io/gpu-memory": 8_192}))
    wait_until(lambda: sync.rv == service.rv)
    result = solve_remote(client)
    assert result["assignments"]["gpu-1"] == "n1"
    assert sched.resource_status["gpu-1"]["device-allocated"]["gpu"]


def test_koordlet_device_report_feeds_scheduler_over_wire(rpc, tmp_path):
    """The full device loop: koordlet daemon reports the Device CR, the
    shell converts it to inventory on NODE_UPSERT, the wire-synced
    scheduler allocates real minors to a GPU pod."""
    import os

    from koordinator_tpu.features import KOORDLET_GATES
    from koordinator_tpu.koordlet.daemon import Daemon
    from koordinator_tpu.koordlet.devices import device_infos_to_inventory
    from koordinator_tpu.koordlet.system.config import (
        make_test_config,
    )
    from koordinator_tpu.scheduler.cpu_manager import CPUManager
    from koordinator_tpu.scheduler.device_manager import DeviceManager

    cfg = make_test_config(tmp_path)
    for i in range(2):
        root = os.path.join(cfg.sys_root, "class", "accel", f"accel{i}")
        os.makedirs(root, exist_ok=True)
        for fn, val in (("uuid", f"GPU-{i}"), ("minor", str(i)),
                        ("mem_total", "81920"), ("mem_used", "0"),
                        ("usage_pct", "0"), ("numa_node", "0"),
                        ("health", "1"), ("type", "gpu")):
            with open(os.path.join(root, fn), "w") as f:
                f.write(val)
    os.makedirs(cfg.proc_root, exist_ok=True)
    with open(cfg.proc_path("stat"), "w") as f:
        f.write("cpu  0 0 0 0 0 0 0 0 0 0\n")
    with open(cfg.proc_path("meminfo"), "w") as f:
        f.write("MemTotal: 1024 kB\nMemAvailable: 512 kB\nCached: 0\n")

    server, clients = rpc
    service = StateSyncService()
    service.attach(server)
    server.start()

    # the shell's device_report_fn: Device CR -> inventory -> NODE_UPSERT
    def on_device_report(device):
        service.upsert_node(
            "n0",
            resource_vector({"cpu": 16_000, "memory": 65_536,
                             "kubernetes.io/gpu": 200,
                             "kubernetes.io/gpu-memory": 81_920 * 2}),
            devices=device_infos_to_inventory(list(device.devices)))

    daemon = Daemon(cfg=cfg, clock=lambda: 1000.0,
                    device_report_fn=on_device_report)
    from koordinator_tpu.koordlet.statesinformer import NodeInfo

    daemon.states.set_node(NodeInfo(name="n0", allocatable={}))

    snap = ClusterSnapshot(capacity=16)
    scoring = ScoringConfig.default().replace(
        usage_thresholds=np.zeros(R, np.int32),
        estimator_defaults=np.zeros(R, np.int32))
    sched = Scheduler(snap, config=scoring, cpu_manager=CPUManager(),
                      device_manager=DeviceManager())
    SolveService(sched).attach(server)
    sync = StateSyncClient(SchedulerBinding(sched))
    client = connect(server, clients, on_push=sync.on_push)
    sync.bootstrap(client)

    KOORDLET_GATES.set("Accelerators", True)
    try:
        daemon.tick()          # reports the Device CR through the shell
    finally:
        KOORDLET_GATES.set("Accelerators", False)
    wait_until(lambda: sync.rv == service.rv)

    service.add_pod("gpu-1", resource_vector(
        {"cpu": 1_000, "memory": 512, "kubernetes.io/gpu": 200,
         "kubernetes.io/gpu-memory": 16_384}))
    wait_until(lambda: sync.rv == service.rv)
    result = solve_remote(client)
    assert result["assignments"]["gpu-1"] == "n0"
    minors = [g["minor"] for g in
              sched.resource_status["gpu-1"]["device-allocated"]["gpu"]]
    assert sorted(minors) == [0, 1]   # both probed GPUs allocated


class TestLocalBindings:
    """StateSyncService.attach_binding: the in-process sidecar feed."""

    def test_synchronous_apply_in_rv_order(self):
        applied = []

        class Recorder:
            def node_upsert(self, entry, arrs):
                applied.append(("node", entry["name"]))

            def pod_add(self, entry, arrs):
                applied.append(("pod", entry["name"]))

            def pod_remove(self, name):
                applied.append(("rm", name))

        service = StateSyncService()
        service.attach_binding(Recorder())
        service.upsert_node("n1", resource_vector(cpu=8_000, memory=8_192))
        service.add_pod("p1", resource_vector(cpu=500, memory=512))
        service.remove_pod("p1")
        # applied before each mutation returned, in commit order
        assert applied == [("node", "n1"), ("pod", "p1"), ("rm", "p1")]

    def test_service_stays_live_while_a_binding_apply_blocks(self):
        """The liveness contract: binding applies run OUTSIDE the service
        lock, so a push stuck behind a long solve (the binding blocks on
        scheduler.lock) cannot stall HELLO/snapshot for other peers."""
        gate = threading.Event()
        entered = threading.Event()

        class Stuck:
            def node_upsert(self, entry, arrs):
                entered.set()
                assert gate.wait(10), "test gate never opened"

        service = StateSyncService()
        service.attach_binding(Stuck())
        pusher = threading.Thread(
            target=lambda: service.upsert_node(
                "slow", resource_vector(cpu=1_000, memory=1_024)),
            daemon=True)
        pusher.start()
        assert entered.wait(5), "binding apply never started"
        # the pusher is parked inside the binding; the service must still
        # answer a fresh HELLO (snapshot) without waiting for it
        doc, _ = service._handle_hello({"last_rv": -1, "proto": 3}, {})
        assert doc["rv"] == 1 and len(doc["events"]) == 1
        gate.set()
        pusher.join(5)
        assert not pusher.is_alive()


def test_node_devices_push_registers_inventory(tmp_path):
    """node_devices frames (the device daemon's report loop in wire
    form): a pushed inventory lands in the scheduler's device manager
    through the binding, merges into the stored node doc for bootstrap
    replay, and an unknown node fails the call without touching the
    log."""
    from koordinator_tpu.cmd.binaries import main_koord_scheduler
    from koordinator_tpu.transport.wire import FrameType

    asm = main_koord_scheduler([
        "--node-capacity", "8",
        "--listen-socket", str(tmp_path / "dev.sock"),
        "--disable-leader-election",
    ])
    try:
        asm.state_sync.upsert_node(
            "n-dev", resource_vector(cpu=8_000, memory=8_192))
        client = RpcClient(asm.server.path)
        client.connect()
        try:
            inventory = {"gpu": [{"core": 100, "memory": 1 << 14,
                                  "group": 0}] * 2}
            _, doc, _ = client.call(
                FrameType.STATE_PUSH,
                {"kind": "node_devices", "name": "n-dev",
                 "devices": inventory})
            assert doc["rv"] == 2
            state = asm.component.device_manager.state("gpu")
            assert state is not None
            assert int(np.asarray(state.valid).sum()) == 2
            # the stored node doc carries the inventory for bootstrap
            stored = asm.state_sync.nodes["n-dev"]["doc"]["devices"]
            assert stored == inventory

            with pytest.raises(RpcError, match="unknown node"):
                client.call(FrameType.STATE_PUSH,
                            {"kind": "node_devices", "name": "ghost",
                             "devices": inventory})
            assert asm.state_sync.rv == 2
        finally:
            client.close()
    finally:
        asm.stop()


def test_node_devices_refresh_clears_disappeared_types(tmp_path):
    """A full-inventory refresh must clear types that vanished, or live
    state diverges from what bootstrap replay would build."""
    from koordinator_tpu.cmd.binaries import main_koord_scheduler
    from koordinator_tpu.transport.wire import FrameType

    asm = main_koord_scheduler([
        "--node-capacity", "8",
        "--listen-socket", str(tmp_path / "dev2.sock"),
        "--disable-leader-election",
    ])
    try:
        asm.state_sync.upsert_node(
            "n-dev", resource_vector(cpu=8_000, memory=8_192))
        client = RpcClient(asm.server.path)
        client.connect()
        try:
            client.call(FrameType.STATE_PUSH,
                        {"kind": "node_devices", "name": "n-dev",
                         "devices": {"gpu": [{"core": 100,
                                              "memory": 1 << 14}]}})
            manager = asm.component.device_manager
            assert int(np.asarray(manager.state("gpu").valid).sum()) == 1
            # gpu collector disappears; tpu appears
            client.call(FrameType.STATE_PUSH,
                        {"kind": "node_devices", "name": "n-dev",
                         "devices": {"xpu": [{"core": 100,
                                              "memory": 1 << 14}]}})
            assert int(np.asarray(manager.state("xpu").valid).sum()) == 1
            gpu_state = manager.state("gpu")
            assert gpu_state is None or int(
                np.asarray(gpu_state.valid).sum()) == 0
        finally:
            client.close()
    finally:
        asm.stop()


def test_node_upsert_clears_omitted_device_types(tmp_path):
    """upsert_node REPLACES the stored doc's devices wholesale, so the
    live registration must clear omitted types too — otherwise the
    in-process scheduler keeps allocating devices a bootstrap-replay
    client cannot see (live-vs-replay divergence on the upsert kind)."""
    from koordinator_tpu.cmd.binaries import main_koord_scheduler

    asm = main_koord_scheduler([
        "--node-capacity", "8",
        "--listen-socket", str(tmp_path / "dev3.sock"),
        "--disable-leader-election",
    ])
    try:
        inventory = {"gpu": [{"core": 100, "memory": 1 << 14, "group": 0}]}
        asm.state_sync.upsert_node(
            "n-up", resource_vector(cpu=8_000, memory=8_192),
            devices=inventory)
        manager = asm.component.device_manager
        assert int(np.asarray(manager.state("gpu").valid).sum()) == 1
        # a label-only re-upsert omits devices: stored doc now has {},
        # so live tensors must clear to match what replay would build
        asm.state_sync.upsert_node(
            "n-up", resource_vector(cpu=8_000, memory=8_192),
            labels={"zone": "b"})
        assert asm.state_sync.nodes["n-up"]["doc"]["devices"] == {}
        gpu_state = manager.state("gpu")
        assert gpu_state is None or int(
            np.asarray(gpu_state.valid).sum()) == 0
    finally:
        asm.stop()


def test_reset_clears_fine_grained_registries():
    """Snapshot resync = restart semantics: device tensors and CPU
    topologies must not survive reset(), or types absent from the
    replayed snapshot stay live and allocatable."""
    from koordinator_tpu.ops.numa import CPUTopology
    from koordinator_tpu.scheduler.cpu_manager import CPUManager
    from koordinator_tpu.scheduler.device_manager import DeviceManager
    from koordinator_tpu.scheduler.scheduler import Scheduler
    from koordinator_tpu.scheduler.snapshot import ClusterSnapshot, NodeSpec
    from koordinator_tpu.transport.deltasync import SchedulerBinding

    snap = ClusterSnapshot(capacity=8)
    sched = Scheduler(snap, config=ScoringConfig.default(),
                      cpu_manager=CPUManager(),
                      device_manager=DeviceManager())
    snap.upsert_node(NodeSpec(
        name="n0",
        allocatable=np.asarray(resource_vector(cpu=8_000, memory=8_192)),
        usage=np.zeros(R, np.int32)))
    sched.device_manager.register_node_devices(
        "gpu", "n0", [{"core": 100, "memory": 1 << 14}])
    sched.cpu_manager.register_node(
        "n0", CPUTopology.uniform(sockets=1, numa_per_socket=1,
                                  cores_per_numa=4))
    SchedulerBinding(sched).reset()
    assert sched.device_manager.state("gpu") is None
    assert sched.device_manager.registered_types_for("n0") == set()
    assert sched.cpu_manager.node("n0") is None


def test_direct_api_rejects_malformed_device_inventory():
    """upsert_node / update_node_devices validate inventory shape at the
    DIRECT API too (the wire push validator does not cover in-process
    callers): a non-list type value would commit to the log, skip
    registration on replay, yet count as 'present' for full-inventory
    clearing — silent live-vs-replay divergence."""
    from koordinator_tpu.transport.deltasync import StateSyncService
    from koordinator_tpu.transport.wire import WireSchemaError

    service = StateSyncService()
    with pytest.raises(WireSchemaError, match="must be a list"):
        service.upsert_node("n0", resource_vector(cpu=1_000, memory=1_024),
                            devices={"gpu": "bogus"})
    service.upsert_node("n0", resource_vector(cpu=1_000, memory=1_024))
    with pytest.raises(WireSchemaError, match="must be a list"):
        service.update_node_devices("n0", {"gpu": "bogus"})
    with pytest.raises(WireSchemaError, match="must be an integer"):
        service.update_node_devices(
            "n0", {"gpu": [{"core": "a-hundred"}]})
    # nothing malformed entered the log: rv is still just the upsert
    assert service.rv == 1


def test_node_upsert_clears_stale_cpu_topology(tmp_path):
    """The NRT twin of the device-clearing rule: a re-upsert whose
    annotations no longer carry a cpu-topology must clear the live
    topology — the stored doc was replaced wholesale, so a replayed
    client has no topology either."""
    import json as _json

    from koordinator_tpu.cmd.binaries import main_koord_scheduler

    asm = main_koord_scheduler([
        "--node-capacity", "8",
        "--listen-socket", str(tmp_path / "nrt.sock"),
        "--disable-leader-election",
    ])
    try:
        detail = [{"core": c // 2, "node": 0, "socket": 0, "id": c}
                  for c in range(4)]
        asm.state_sync.upsert_node(
            "n-nrt", resource_vector(cpu=4_000, memory=4_096),
            annotations={"node.koordinator.sh/cpu-topology":
                         _json.dumps({"detail": detail})})
        mgr = asm.component.cpu_manager
        assert mgr.node("n-nrt") is not None
        # label-only re-upsert: no NRT annotation -> topology clears
        asm.state_sync.upsert_node(
            "n-nrt", resource_vector(cpu=4_000, memory=4_096),
            labels={"zone": "b"})
        assert mgr.node("n-nrt") is None
    finally:
        asm.stop()


def test_unchanged_device_heartbeat_does_not_churn_the_log():
    """The koordlet sink re-pushes inventory every interval (heartbeat);
    an UNCHANGED push must not append to the bounded delta log or wake
    watchers — N nodes heartbeating would shrink retention to ~4096/N
    intervals and force slow watchers into full resyncs."""
    from koordinator_tpu.transport.deltasync import StateSyncService

    service = StateSyncService()
    service.upsert_node("n0", resource_vector(cpu=1_000, memory=1_024))
    inventory = {"gpu": [{"core": 100, "memory": 1 << 14, "group": 0}]}
    rv = service.update_node_devices("n0", inventory)
    assert rv == 2
    # identical heartbeat: same rv back, nothing committed
    assert service.update_node_devices("n0", dict(inventory)) == 2
    assert service.rv == 2
    # a real change commits again
    assert service.update_node_devices("n0", {}) == 3


def test_node_remove_clears_fine_grained_registries(tmp_path):
    """NODE_REMOVE takes the node's device tensors and CPU topology with
    it — a bootstrap-replay client has neither, so live state keeping
    them would re-create the divergence the upsert/refresh paths fix."""
    import json as _json

    from koordinator_tpu.cmd.binaries import main_koord_scheduler

    asm = main_koord_scheduler([
        "--node-capacity", "8",
        "--listen-socket", str(tmp_path / "rm.sock"),
        "--disable-leader-election",
    ])
    try:
        detail = [{"core": c, "node": 0, "socket": 0, "id": c}
                  for c in range(2)]
        asm.state_sync.upsert_node(
            "n-rm", resource_vector(cpu=2_000, memory=2_048),
            annotations={"node.koordinator.sh/cpu-topology":
                         _json.dumps({"detail": detail})},
            devices={"gpu": [{"core": 100, "memory": 1 << 14,
                              "group": 0}]})
        dm = asm.component.device_manager
        cm = asm.component.cpu_manager
        assert int(np.asarray(dm.state("gpu").valid).sum()) == 1
        assert cm.node("n-rm") is not None
        asm.state_sync.remove_node("n-rm")
        gpu_state = dm.state("gpu")
        assert gpu_state is None or int(
            np.asarray(gpu_state.valid).sum()) == 0
        assert dm.registered_types_for("n-rm") in (set(), {"gpu"})
        assert cm.node("n-rm") is None
    finally:
        asm.stop()


def test_node_allocatable_push_merges_without_clobbering(rpc):
    """The noderesource controller's wire form: a node_allocatable push
    replaces ONLY the allocatable vector — usage, labels, and the stored
    doc's devices survive — and the merged value rides a later bootstrap
    snapshot.  Unknown node fails the call without touching the log."""
    from koordinator_tpu.api import extension as ext
    from koordinator_tpu.transport.channel import RpcRemoteError
    from koordinator_tpu.transport.wire import FrameType

    server, clients = rpc
    service = StateSyncService()
    service.attach(server)
    server.start()
    service.upsert_node(
        "n1", resource_vector(cpu=16_000, memory=65_536),
        usage=resource_vector(cpu=4_000, memory=8_192),
        labels={"zone": "a"},
        devices={"gpu": [{"core": 100, "memory": 1 << 14, "group": 0}]})

    sched = mk_scheduler()
    sync = StateSyncClient(SchedulerBinding(sched))
    client = connect(server, clients, on_push=sync.on_push)
    sync.bootstrap(client)

    new_alloc = resource_vector({
        "cpu": 16_000, "memory": 65_536,
        ext.RESOURCE_BATCH_CPU: 9_000, ext.RESOURCE_BATCH_MEMORY: 30_000})
    _, doc, _ = client.call(
        FrameType.STATE_PUSH,
        {"kind": "node_allocatable", "name": "n1"},
        {"allocatable": np.asarray(new_alloc, np.int32)})
    assert doc["rv"] == service.rv
    wait_until(lambda: sync.rv == service.rv)

    spec = sched.snapshot.node_specs["n1"]
    from koordinator_tpu.api.resources import ResourceDim
    assert spec.allocatable[ResourceDim.BATCH_CPU] == 9_000
    assert spec.usage[ResourceDim.CPU] == 4_000       # usage untouched
    assert spec.labels == {"zone": "a"}
    stored = service.nodes["n1"]
    assert stored["doc"]["devices"]["gpu"]            # inventory survives
    assert int(stored["arrays"]["allocatable"][ResourceDim.BATCH_CPU]) \
        == 9_000

    # a fresh bootstrapper replays the MERGED allocatable
    sched2 = mk_scheduler()
    sync2 = StateSyncClient(SchedulerBinding(sched2))
    client2 = connect(server, clients, on_push=sync2.on_push)
    sync2.bootstrap(client2)
    assert sched2.snapshot.node_specs["n1"].allocatable[
        ResourceDim.BATCH_CPU] == 9_000

    with pytest.raises(RpcRemoteError, match="unknown node"):
        client.call(FrameType.STATE_PUSH,
                    {"kind": "node_allocatable", "name": "ghost"},
                    {"allocatable": np.asarray(new_alloc, np.int32)})


def test_conn_close_with_full_queue_does_not_leak_sender_thread():
    """_Conn.close vs a momentarily-full queue: the sender can drain the
    whole backlog between close()'s failed poison put and its direct
    socket shutdown, then block forever on queue.get() with no poison
    coming.  close() must retry the poison after the shutdown so the
    sender thread always exits."""
    import queue as _queue

    from koordinator_tpu.transport.channel import _Conn
    from koordinator_tpu.transport.wire import (
        Frame,
        FrameType,
        encode_payload,
    )

    drained = threading.Event()
    in_send = threading.Event()

    class FakeSock:
        """sendall blocks until released; shutdown (called from close's
        Full branch) WAITS for the sender to drain the backlog — the
        exact interleaving that leaked the thread."""

        def __init__(self):
            self.release = threading.Event()

        def sendall(self, data):
            in_send.set()
            self.release.wait(5)

        def shutdown(self, how):
            # simulate the race window: by the time the shutdown lands,
            # the sender has drained everything and is parked in get()
            self.release.set()
            assert drained.wait(5), "sender never drained the backlog"

    conn = _Conn.__new__(_Conn)
    conn.sock = FakeSock()
    conn.faults = None
    conn._held = None
    conn.queue = _queue.Queue(4)
    conn.alive = True
    conn.dropped = 0

    orig_get = conn.queue.get

    def tracking_get(*a, **kw):
        if conn.queue.empty():
            drained.set()
        return orig_get(*a, **kw)

    conn.queue.get = tracking_get
    frame = Frame(FrameType.DELTA, 0, encode_payload({"x": 1}))
    # sender holds one frame inside the blocked sendall...
    conn.queue.put_nowait(frame)
    sender = threading.Thread(target=conn._drain, daemon=True)
    conn._sender = sender
    sender.start()
    assert in_send.wait(5)
    # ...while the queue refills to capacity: close() sees Full
    for _ in range(4):
        conn.queue.put_nowait(frame)

    conn.close()          # Full -> shutdown (sender drains) -> poison retry
    sender.join(5)
    assert not sender.is_alive(), \
        "sender thread leaked: blocked on queue.get() with no poison"
