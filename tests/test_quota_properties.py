"""Randomized invariants of the quota tree's fair-share runtime.

The example tests in test_quota.py pin the reference formulas
(runtime_quota_calculator.go water-filling + Hamilton apportionment) at
hand-built shapes; this sweeps random hierarchical trees and asserts
the structural invariants that must hold for ANY input where mins are
not oversubscribed:

  (bound)     runtime <= max on bounded dims
  (floor)     runtime >= min(min, limited_request) — a quota never gets
              less than the smaller of its guaranteed min and what it
              asked for
  (conserve)  sum(children runtime) <= parent pool, per dim
  (work)      if any positive-weight child is still hungry
              (runtime < limited_request), the parent pool is fully
              distributed — water-filling never strands headroom while
              someone wants it
"""

import numpy as np
import pytest

from tests.conftest import prop_seeds

from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS
from koordinator_tpu.quota.tree import ROOT, UNBOUNDED, QuotaTree

R = NUM_RESOURCE_DIMS


def _random_tree(rng: np.random.Generator) -> QuotaTree:
    total = rng.integers(10_000, 1_000_000, R).astype(np.int64)
    tree = QuotaTree(total_resource=total)
    n_parents = int(rng.integers(1, 4))
    parents = []
    # keep sum(min) under the parent pool at every level: min
    # oversubscription is legal input (cluster shrank) but suspends the
    # conservation invariant by design (scale-min is the opt-in fix)
    parent_min_budget = total // (2 * max(n_parents, 1))
    for p in range(n_parents):
        name = f"team{p}"
        mn = (parent_min_budget * rng.random(R)).astype(np.int64)
        mx = np.where(rng.random(R) < 0.3, UNBOUNDED,
                      rng.integers(1, 2_000_000, R)).astype(np.int64)
        mx = np.where((mx != UNBOUNDED) & (mx < mn), mn, mx)
        tree.add(name, min=mn, max=mx)
        parents.append(name)
        n_kids = int(rng.integers(0, 4))
        kid_budget = mn // (2 * max(n_kids, 1) + 1)
        for k in range(n_kids):
            kmn = (kid_budget * rng.random(R)).astype(np.int64)
            kmx = np.where(rng.random(R) < 0.3, UNBOUNDED,
                           rng.integers(1, 2_000_000, R)).astype(np.int64)
            kmx = np.where((kmx != UNBOUNDED) & (kmx < kmn), kmn, kmx)
            tree.add(f"{name}-sub{k}", parent=name, min=kmn, max=kmx)
    # leaves get random requests (pods); internal nodes aggregate
    for name, node in tree.nodes.items():
        if name != ROOT and not tree.children.get(name):
            tree.set_request(
                name, rng.integers(0, 500_000, R).astype(np.int64))
    return tree


@pytest.mark.parametrize("seed", prop_seeds(16))
def test_runtime_invariants_hold_on_random_trees(seed):
    rng = np.random.default_rng(seed)
    tree = _random_tree(rng)
    tree.refresh_runtime()

    for parent, kids in tree.children.items():
        if not kids:
            continue
        pool = (tree.total_resource if parent == ROOT
                else tree.nodes[parent].runtime)
        kid_sum = np.zeros(R, np.int64)
        hungry_weight = np.zeros(R, np.int64)
        for kid in kids:
            node = tree.nodes[kid]
            rt = node.runtime
            assert (rt >= 0).all(), (seed, kid)
            bounded = node.max != UNBOUNDED
            assert (rt[bounded] <= node.max[bounded]).all(), (
                f"seed {seed}: {kid} runtime exceeds max")
            floor = np.minimum(node.min, node.limited_request)
            assert (rt >= floor).all(), (
                f"seed {seed}: {kid} runtime {rt} below floor {floor}")
            kid_sum += rt
            hungry = rt < node.limited_request
            hungry_weight += np.where(hungry, node.shared_weight, 0)
        assert (kid_sum <= pool).all(), (
            f"seed {seed}: children of {parent} oversubscribe the pool")
        # work conservation: headroom may remain only on dims where no
        # positive-weight child is still hungry
        headroom = pool - kid_sum
        strandable = (headroom > 0) & (hungry_weight > 0)
        assert not strandable.any(), (
            f"seed {seed}: {parent} stranded headroom {headroom} with "
            f"hungry children")
