"""batch_assign: data-parallel propose/accept solver tests.

Invariants checked against the exact sequential solver (greedy_assign) and
the integer oracle: capacity is never violated, priority wins conflicts,
quota headroom caps acceptance, and abundant capacity assigns everything.
"""

import jax
import jax.numpy as jnp
import numpy as np

from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS, ResourceDim
from koordinator_tpu.ops.assignment import ScoringConfig, greedy_assign
from koordinator_tpu.ops.batch_assign import batch_assign
from koordinator_tpu.quota.admission import QuotaDeviceState
from koordinator_tpu.quota.tree import UNBOUNDED, QuotaTree
from koordinator_tpu.state.cluster_state import ClusterState, PodBatch

R = NUM_RESOURCE_DIMS
CPU, MEM = ResourceDim.CPU, ResourceDim.MEMORY


def cfg():
    return ScoringConfig.default().replace(
        usage_thresholds=jnp.zeros(R, jnp.int32),
        estimator_defaults=jnp.zeros(R, jnp.int32),
    )


def mk_state(node_cpus, mem=65_536):
    alloc = np.zeros((len(node_cpus), R), np.int32)
    alloc[:, CPU] = node_cpus
    alloc[:, MEM] = mem
    return ClusterState.from_arrays(alloc)


def mk_pods(cpus, mem=1_024, priority=None, **kw):
    req = np.zeros((len(cpus), R), np.int32)
    req[:, CPU] = cpus
    req[:, MEM] = mem
    return PodBatch.build(
        req,
        priority=np.asarray(priority, np.int32) if priority is not None else None,
        node_capacity=kw.pop("node_capacity", 64),
        **kw,
    )


def assert_no_overcommit(state, pods, assignments):
    a = np.asarray(assignments)
    alloc = np.asarray(state.node_allocatable)
    used = np.zeros_like(alloc)
    for i, nd in enumerate(a):
        if nd >= 0:
            used[nd] += np.asarray(pods.requests)[i]
    assert (used <= alloc).all(), (used, alloc)


def test_abundant_capacity_assigns_all():
    state = mk_state([16_000] * 8)
    pods = mk_pods([1_000] * 20)
    a, new_state, _ = batch_assign(state, pods, cfg())
    a = np.asarray(a)
    assert (a[:20] >= 0).all()
    assert (a[20:] == -1).all()  # padding stays unassigned
    assert_no_overcommit(state, pods, a)


def test_rotation_spreads_identical_pods():
    # 8 identical nodes, 16 identical pods: without the rotated tie-break
    # they would all stampede one argmax node and take many rounds
    state = mk_state([4_000] * 8)
    pods = mk_pods([1_000] * 16)
    a, _, _ = batch_assign(state, pods, cfg())
    counts = np.bincount(np.asarray(a)[:16], minlength=8)
    assert (np.asarray(a)[:16] >= 0).all()
    assert counts.max() <= 4  # capacity bound per node


def test_priority_wins_contended_node():
    state = mk_state([1_000], mem=2_048)
    pods = mk_pods([1_000, 1_000], mem=1_024, priority=[10, 9_000])
    a, _, _ = batch_assign(state, pods, cfg())
    a = np.asarray(a)
    assert a[1] == 0   # high priority wins the only slot
    assert a[0] == -1


def test_capacity_respected_under_contention():
    state = mk_state([4_000, 4_000])
    pods = mk_pods([3_000] * 5, mem=512)
    a, _, _ = batch_assign(state, pods, cfg())
    a = np.asarray(a)
    assert (a[:5] >= 0).sum() == 2  # one 3k pod per 4k node
    assert_no_overcommit(state, pods, a)


def test_matches_greedy_on_assignment_count():
    rng = np.random.default_rng(0)
    state = mk_state(rng.integers(4_000, 16_000, size=16).tolist())
    cpus = rng.integers(500, 4_000, size=40).tolist()
    pris = rng.integers(0, 10_000, size=40).tolist()
    pods = mk_pods(cpus, mem=256, priority=pris)
    ab, _, _ = batch_assign(state, pods, cfg())
    ag, _, _ = greedy_assign(state, pods, cfg())
    nb = int((np.asarray(ab) >= 0).sum())
    ng = int((np.asarray(ag) >= 0).sum())
    assert_no_overcommit(state, pods, ab)
    # the parallel solver may differ in placement but must not lose
    # meaningfully many pods vs the exact sequential solve
    assert nb >= ng - 1, (nb, ng)


def test_compact_gathers_rows_and_pads_pow2():
    pods = mk_pods([100, 200, 300, 400, 500], priority=[1, 2, 3, 4, 5])
    keep = np.zeros(pods.capacity, bool)
    keep[[1, 3, 4]] = True
    small, idx = pods.compact(keep, min_capacity=4)
    assert list(idx) == [1, 3, 4]
    assert small.capacity == 4
    np.testing.assert_array_equal(
        np.asarray(small.requests)[:3, CPU], [200, 400, 500])
    np.testing.assert_array_equal(np.asarray(small.priority)[:3], [2, 4, 5])
    assert not bool(small.valid[3])          # pad row invalid
    # solving the compact batch matches solving the masked original
    state = mk_state([8_000] * 4)
    a_small, _, _ = batch_assign(state, small, cfg())
    a_full, _, _ = batch_assign(
        state, pods.replace(valid=pods.valid & jnp.asarray(keep)), cfg())
    np.testing.assert_array_equal(
        np.asarray(a_small)[:3], np.asarray(a_full)[idx])


def test_compact_empty_keep():
    pods = mk_pods([100, 200])
    small, idx = pods.compact(np.zeros(pods.capacity, bool))
    assert len(idx) == 0
    assert not np.asarray(small.valid).any()


def test_no_candidate_collapse_at_scale():
    # regression: with exact-score ranking every pod's top-k collapsed onto
    # the same few nodes and >75% of a fully schedulable queue stranded
    # (observed 3,178/50,000 at the north-star shape).  spread_bits must
    # keep the parallel solver at parity with the exact greedy scan.
    from __graft_entry__ import _build_problem

    state, pods, scoring = _build_problem(512, 2_500, seed=3)
    ab, _, _ = jax.jit(batch_assign)(state, pods, scoring)
    ag, _, _ = jax.jit(greedy_assign)(state, pods, scoring)
    nb = int((np.asarray(ab) >= 0).sum())
    ng = int((np.asarray(ag) >= 0).sum())
    assert_no_overcommit(state, pods, ab)
    assert nb >= ng * 0.99, (nb, ng)


def test_determinism():
    state = mk_state([8_000] * 4)
    pods = mk_pods([1_000] * 10)
    a1, _, _ = batch_assign(state, pods, cfg())
    a2, _, _ = batch_assign(state, pods, cfg())
    assert (np.asarray(a1) == np.asarray(a2)).all()


def test_jit_compiles():
    state = mk_state([8_000] * 4)
    pods = mk_pods([1_000] * 10)
    f = jax.jit(batch_assign, static_argnames=("k", "rounds"))
    a, _, _ = f(state, pods, cfg(), k=8, rounds=4)
    assert (np.asarray(a)[:10] >= 0).all()


def vec64(cpu):
    v = np.zeros(R, np.int64)
    v[CPU] = cpu
    return v


def test_quota_headroom_caps_round():
    # quota runtime fits ONE 2k pod; two same-round proposers of different
    # priority: the prefix check admits only the higher-priority one
    tree = QuotaTree(vec64(2_000))
    mx = np.full(R, UNBOUNDED, np.int64)
    mx[CPU] = 2_000
    tree.add("q", min=vec64(0), max=mx)
    tree.set_request("q", vec64(4_000))
    tree.refresh_runtime()
    quota, index = QuotaDeviceState.from_tree(tree)

    state = mk_state([16_000, 16_000])
    pods = mk_pods(
        [2_000, 2_000], mem=0, priority=[10, 9_000],
        quota_id=np.array([index["q"], index["q"]], np.int32),
    )
    a, _, new_quota = batch_assign(state, pods, cfg(), quota=quota)
    a = np.asarray(a)
    assert a[1] >= 0
    assert a[0] == -1
    # headroom fully consumed
    assert int(new_quota.headroom[index["q"], CPU]) == 0


def test_quota_chain_parent_capped():
    # hand-built device state (tree runtimes normally keep children within
    # the parent; the chain prefix is the defense when headrooms drift):
    # parent headroom 2k, children a/b 2k each — one same-round proposer per
    # child, only the higher-priority one may pass the shared parent level
    headroom = np.zeros((4, R), np.int32)
    headroom[0, CPU] = 2_000   # parent
    headroom[1, CPU] = 2_000   # a
    headroom[2, CPU] = 2_000   # b
    checked = np.zeros((4, R), bool)
    checked[:3, CPU] = True
    chain = np.full((4, 8), -1, np.int32)
    chain[0, 0] = 0
    chain[1, :2] = [1, 0]
    chain[2, :2] = [2, 0]
    valid = np.array([True, True, True, False])
    quota = QuotaDeviceState(
        headroom=jnp.asarray(headroom),
        min_headroom=jnp.asarray(np.zeros((4, R), np.int32)),
        checked=jnp.asarray(checked),
        chain=jnp.asarray(chain),
        valid=jnp.asarray(valid),
    )

    state = mk_state([16_000, 16_000])
    pods = mk_pods(
        [2_000, 2_000], mem=0, priority=[9_000, 10],
        quota_id=np.array([1, 2], np.int32),
    )
    a, _, _ = batch_assign(state, pods, cfg(), quota=quota)
    a = np.asarray(a)
    assert a[0] >= 0   # higher priority child pod wins the parent headroom
    assert a[1] == -1


class TestSolverProperties:
    """Property-based invariants over random shapes (hypothesis)."""

    def test_no_overcommit_valid_rows_deterministic(self):
        from hypothesis import given, settings, strategies as st

        @settings(max_examples=25, deadline=None)
        @given(st.integers(2, 12), st.integers(1, 40),
               st.integers(0, 2**32 - 1))
        def prop(n_nodes, n_pods, seed):
            rng = np.random.default_rng(seed)
            state = mk_state(rng.integers(1_000, 20_000, n_nodes).tolist(),
                             mem=int(rng.integers(1_024, 65_536)))
            pods = mk_pods(rng.integers(100, 8_000, n_pods).tolist(),
                           mem=int(rng.integers(64, 2_048)),
                           priority=rng.integers(0, 10_000,
                                                 n_pods).tolist())
            a1, s1, _ = batch_assign(state, pods, cfg())
            a1 = np.asarray(a1)
            # 1. capacity never overcommitted
            assert_no_overcommit(state, pods, a1)
            # 2. assignments land on real rows; padding stays unassigned
            assert ((a1 == -1) | ((a1 >= 0) & (a1 < n_nodes))).all()
            assert (a1[n_pods:] == -1).all()
            # 3. deterministic
            a2, _, _ = batch_assign(state, pods, cfg())
            np.testing.assert_array_equal(a1, np.asarray(a2))
            # 4. accounting consistent: per-node requested delta equals the
            # sum of its assigned pods' requests
            delta = (np.asarray(s1.node_requested)
                     - np.asarray(state.node_requested))
            expect = np.zeros_like(delta)
            req = np.asarray(pods.requests)
            for i, nd in enumerate(a1):
                if nd >= 0:
                    expect[nd] += req[i]
            np.testing.assert_array_equal(delta, expect)

        prop()


class TestPrefixAcceptFastPath:
    """The uncontended lax.cond fast path must be indistinguishable from
    the sorted segmented-prefix path (the single source of truth)."""

    def test_fast_path_matches_sorted_across_seeds(self):
        from koordinator_tpu.ops.batch_assign import (
            _prefix_accept,
            _prefix_accept_sorted,
        )

        for seed in range(12):
            rng = np.random.default_rng(seed)
            p, s, r = 64, 8, 3
            choice = rng.integers(0, s, p).astype(np.int32)
            requests = rng.integers(0, 50, (p, r)).astype(np.int32)
            # seeds alternate between roomy (uncontended) and tight
            # (contended) headroom so BOTH cond branches are exercised
            headroom = (rng.integers(500, 4000, (s, r)) if seed % 2 == 0
                        else rng.integers(0, 120, (s, r))).astype(np.int32)
            active = rng.random(p) < 0.8
            order = np.argsort(rng.random(p)).astype(np.int32)
            got = _prefix_accept(
                jnp.asarray(choice), jnp.asarray(requests),
                jnp.asarray(headroom), jnp.asarray(order),
                jnp.asarray(active))
            seg = jnp.where(jnp.asarray(active), jnp.asarray(choice), s)
            want = _prefix_accept_sorted(
                seg, jnp.asarray(requests), jnp.asarray(headroom),
                jnp.asarray(order), jnp.asarray(active))
            np.testing.assert_array_equal(
                np.asarray(got), np.asarray(want), err_msg=f"seed {seed}")

    def test_uncontended_round_accepts_all_proposers(self):
        from koordinator_tpu.ops.batch_assign import _prefix_accept

        p, s, r = 16, 4, 2
        choice = jnp.asarray(np.arange(p, dtype=np.int32) % s)
        requests = jnp.ones((p, r), jnp.int32)
        headroom = jnp.full((s, r), 100, jnp.int32)   # roomy everywhere
        active = jnp.asarray(np.array([True] * 12 + [False] * 4))
        order = jnp.asarray(np.arange(p, dtype=np.int32))
        got = np.asarray(_prefix_accept(choice, requests, headroom,
                                        order, active))
        np.testing.assert_array_equal(got, np.asarray(active))


def _batch_quality_tracks_greedy(method):
    """Randomized quality floor vs the exact sequential solver.  Across
    random shapes and tightness (measured over these seeds): a SINGLE
    parallel solve places 82-100% of greedy (propose/accept conflict
    loss), and THREE retry waves — the scheduler's round-loop semantics
    — recover greedy's count exactly on every seed.  The guard pins
    both: single call >= 0.8x, three waves >= 0.98x, capacity always
    holds.  Fixed padding buckets keep this to one compile per
    method."""
    solve = jax.jit(lambda s, p: batch_assign(
        s, p, cfg(), k=16, method=method)[:2])
    gsolve = jax.jit(lambda s, p: greedy_assign(s, p, cfg())[:2])
    for seed in range(8):
        rng = np.random.default_rng(seed)
        n_nodes = int(rng.integers(8, 64))
        n_pods = int(rng.integers(16, 256))
        alloc = np.zeros((64, R), np.int32)
        alloc[:n_nodes, CPU] = rng.integers(2_000, 16_000, n_nodes)
        alloc[:n_nodes, MEM] = rng.integers(4_096, 65_536, n_nodes)
        state = ClusterState.from_arrays(alloc[:n_nodes], capacity=64)
        req = np.zeros((n_pods, R), np.int32)
        req[:, CPU] = rng.integers(100, 3_000, n_pods)
        req[:, MEM] = rng.integers(128, 6_000, n_pods)
        pods = PodBatch.build(
            req, priority=rng.integers(3_000, 10_000, n_pods)
            .astype(np.int32), node_capacity=64, capacity=256)

        ag, _ = gsolve(state, pods)
        ng = int((np.asarray(ag) >= 0).sum())
        st, rem, total = state, pods, 0
        first = None
        for _ in range(3):
            ab, st = solve(st, rem)
            wave = (np.asarray(ab) >= 0) & np.asarray(rem.valid)
            total += int(wave.sum())
            if first is None:
                first = total
            stranded = np.asarray(rem.valid) & ~wave
            if not stranded.any():
                break
            rem = rem.replace(valid=jnp.asarray(stranded))
        assert (np.asarray(st.node_requested)
                <= np.asarray(st.node_allocatable)).all(), (seed, method)
        assert first >= 0.8 * ng, (
            f"seed {seed} {method}: single call placed {first} vs "
            f"greedy {ng}")
        assert total >= 0.98 * ng, (
            f"seed {seed} {method}: 3 waves placed {total} vs greedy {ng}")


def test_batch_quality_tracks_greedy_exact():
    _batch_quality_tracks_greedy("exact")


def test_batch_quality_tracks_greedy_approx():
    _batch_quality_tracks_greedy("approx")


def test_batch_quality_tracks_greedy_chunked():
    _batch_quality_tracks_greedy("chunked")


def test_node_capacity_ceiling_moved_past_the_packed_wall():
    """ISSUE 10: the 32,768 packing wall is GONE — a 40,960-node problem
    (the shape the old guard refused) selects and solves in the wide
    lexicographic key regime — and the loud guard moved to 2**30 (int32
    row-index / rotation-arithmetic width, not packing)."""
    import pytest

    from koordinator_tpu.ops.batch_assign import (
        MAX_NODE_CAPACITY,
        PACKED_NODE_CAPACITY,
        _packed_regime,
        check_node_capacity,
        select_candidates,
    )

    assert MAX_NODE_CAPACITY == 1 << 30
    check_node_capacity(PACKED_NODE_CAPACITY + 1)   # old wall: allowed
    check_node_capacity(MAX_NODE_CAPACITY)          # boundary allowed
    with pytest.raises(ValueError, match="ranking-key ceiling"):
        check_node_capacity(MAX_NODE_CAPACITY + 1)

    # explicit capacity: the default power-of-two bucket would balloon
    # this to 65,536 columns (that shape's full solve lives in
    # tests/test_sharded_solve.py) — the point HERE is only that the
    # old guard's exact failure shape now selects
    alloc = np.zeros((40_960, R), np.int32)
    alloc[:, CPU] = 16_000
    alloc[:, MEM] = 65_536
    state = ClusterState.from_arrays(alloc, capacity=40_960)
    assert not _packed_regime(state.capacity)
    pods = mk_pods([500] * 4, node_capacity=state.capacity, capacity=4)
    key, node = select_candidates(state, pods, cfg(), k=8,
                                  method="exact")
    assert (np.asarray(key)[:4] >= 0).all()
    assert int(np.asarray(node).max()) < 40_960


def test_node_capacity_at_boundary_solves():
    """Exactly 2**15 nodes — the PACKED key regime's boundary — still
    solves correctly (the regime switch is not off-by-one): a small pod
    batch assigns with no overcommit on the packed path."""
    from koordinator_tpu.ops.batch_assign import (
        PACKED_NODE_CAPACITY,
        _packed_regime,
    )

    state = mk_state([16_000] * PACKED_NODE_CAPACITY)
    assert _packed_regime(state.capacity)
    pods = mk_pods([500] * 8, node_capacity=state.capacity)
    asn, st, _ = batch_assign(state, pods, cfg(), k=8, method="exact")
    assert int((np.asarray(asn) >= 0).sum()) == 8
    assert_no_overcommit(state, pods, asn)
