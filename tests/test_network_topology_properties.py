"""Randomized invariants of the network-topology gang packer.

test_network_topology.py pins the PlacePods scenarios at hand-built
trees; this sweeps random spine/block/node trees, capacities, and gang
sizes (uniform member requests, so the prefix-fit slot rule has an
exact closed form) asserting:

  (members)  only gang members get planned nodes; a plan is all-or-
             nothing across members
  (capacity) per node, planned pods' cumulative request fits the free
             capacity (the plan never oversells a node)
  (gather)   with must_gather_layer set, every planned node lies in ONE
             subtree at that layer
  (complete) an empty plan only happens when no gather-layer subtree
             has enough slots — checked with an independent numpy
             slot count
"""

import numpy as np
import pytest

from tests.conftest import prop_seeds

from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS, ResourceDim
from koordinator_tpu.ops.network_topology import (
    TopologyRequirements,
    TopologyTree,
    plan_gang_placement,
)
from koordinator_tpu.state.cluster_state import ClusterState, PodBatch

R = NUM_RESOURCE_DIMS
CPU, MEM = ResourceDim.CPU, ResourceDim.MEM if hasattr(ResourceDim, "MEM") \
    else ResourceDim.MEMORY


def _random_problem(rng: np.random.Generator):
    spines = int(rng.integers(1, 3))
    blocks = int(rng.integers(1, 4))
    per_block = int(rng.integers(1, 4))
    tree = TopologyTree(["spine", "block", "node"])
    node_block = []
    idx = 0
    for s in range(spines):
        for b in range(blocks):
            for _ in range(per_block):
                tree.add_node([f"s{s}", f"b{s}.{b}", f"n{idx}"])
                node_block.append(s * blocks + b)
                idx += 1
    topo = tree.build()
    n = idx
    cpus = rng.integers(2_000, 12_000, n)
    alloc = np.zeros((n, R), np.int32)
    alloc[:, CPU] = cpus
    alloc[:, MEM] = 65_536
    state = ClusterState.from_arrays(alloc, capacity=n)

    members = int(rng.integers(1, 7))
    per_pod = int(rng.integers(500, 5_000))
    req = np.zeros((members, R), np.int32)
    req[:, CPU] = per_pod
    req[:, MEM] = 512
    pods = PodBatch.build(req, node_capacity=n)
    mask = np.zeros(pods.capacity, bool)
    mask[:members] = True
    return (state, pods, mask, topo, np.asarray(node_block),
            cpus, members, per_pod)


@pytest.mark.parametrize("seed", prop_seeds(20))
def test_plan_invariants(seed):
    rng = np.random.default_rng(seed)
    (state, pods, mask, topo, node_block, cpus, members,
     per_pod) = _random_problem(rng)

    # layer indexing includes the implicit cluster root at 0, so for
    # ["spine", "block", "node"] the block layer is 2
    plan = plan_gang_placement(
        state, pods, mask, topo,
        TopologyRequirements(desired_slots=members, must_gather_layer=2))
    plan = np.asarray(plan)

    # (members) plan only covers gang members, all-or-nothing
    assert (plan[~mask] == -1).all(), f"seed {seed}: non-member planned"
    planned = plan[mask]
    assert (planned >= 0).all() or (planned == -1).all(), (
        f"seed {seed}: partial plan {planned}")

    # independent slot oracle: uniform requests -> node slots =
    # floor(cpu / per_pod), block slots = sum over its nodes
    node_slots = cpus // per_pod
    block_slots = np.bincount(node_block, weights=node_slots).astype(int)

    if (planned == -1).all():
        # (complete) no block could host the gang
        assert (block_slots < members).all(), (
            f"seed {seed}: empty plan but a block has "
            f"{block_slots.max()} >= {members} slots")
        return

    # (capacity) per-node cumulative fit
    counts = np.bincount(planned, minlength=state.capacity)
    assert (counts * per_pod <= cpus[:len(counts)] if len(counts) <= len(cpus)
            else counts[:len(cpus)] * per_pod <= cpus).all(), (
        f"seed {seed}: plan oversells a node")

    # (gather) one block hosts everything
    blocks_used = set(node_block[p] for p in planned)
    assert len(blocks_used) == 1, (
        f"seed {seed}: gang spread over blocks {blocks_used}")
